"""Operator tests (reference tests/python/unittest/test_operator.py):
forward values against numpy closed forms, gradients against finite
differences via the test_utils harness."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_symbolic_forward)


def _bind_forward(s, args_np, is_train=False, aux=None, grad_req="null"):
    args = {k: mx.nd.array(v) for k, v in args_np.items()}
    ex = s.bind(mx.cpu(), args, grad_req=grad_req)
    if aux:
        for k, v in aux.items():
            ex.aux_dict[k][:] = v
    return ex, ex.forward(is_train=is_train)


def test_elementwise_sum():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = sym.Variable("c")
    s = sym.ElementWiseSum(a, b, c, num_args=3, name="esum")
    rng = np.random.RandomState(0)
    arrs = {k: rng.randn(3, 4).astype(np.float32) for k in "abc"}
    _, outs = _bind_forward(s, arrs)
    np.testing.assert_allclose(outs[0].asnumpy(),
                               arrs["a"] + arrs["b"] + arrs["c"], rtol=1e-5)


def test_fullyconnected_grad():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=4, name="fc")
    rng = np.random.RandomState(0)
    check_numeric_gradient(fc, {
        "data": rng.randn(3, 5).astype(np.float32),
        "fc_weight": rng.randn(4, 5).astype(np.float32),
        "fc_bias": rng.randn(4).astype(np.float32)})


def test_activation():
    x_np = np.array([[-1.0, 0.0, 2.0]], dtype=np.float32)
    for act, fn in [("relu", lambda x: np.maximum(x, 0)),
                    ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
                    ("tanh", np.tanh),
                    ("softrelu", lambda x: np.log1p(np.exp(x)))]:
        s = sym.Activation(data=sym.Variable("data"), act_type=act)
        _, outs = _bind_forward(s, {"data": x_np})
        np.testing.assert_allclose(outs[0].asnumpy(), fn(x_np), rtol=1e-5)


def test_leaky_relu():
    x_np = np.array([[-2.0, 3.0]], dtype=np.float32)
    s = sym.LeakyReLU(data=sym.Variable("data"), act_type="leaky", slope=0.1)
    _, outs = _bind_forward(s, {"data": x_np})
    np.testing.assert_allclose(outs[0].asnumpy(), [[-0.2, 3.0]], rtol=1e-5)


def test_softmax_output_semantics():
    """Backward must be softmax - onehot regardless of head grads
    (the reference's fused loss-layer contract)."""
    data = sym.Variable("data")
    s = sym.SoftmaxOutput(data=data, name="softmax")
    rng = np.random.RandomState(0)
    x = rng.randn(4, 3).astype(np.float32)
    label = np.array([0, 1, 2, 1], dtype=np.float32)
    args = {"data": mx.nd.array(x), "softmax_label": mx.nd.array(label)}
    grads = {"data": mx.nd.zeros((4, 3)),
             "softmax_label": mx.nd.zeros((4,))}
    ex = s.bind(mx.cpu(), args, args_grad=grads,
                grad_req={"data": "write", "softmax_label": "null"})
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    expected = np.exp(x) / np.exp(x).sum(axis=1, keepdims=True)
    np.testing.assert_allclose(out, expected, rtol=1e-5)
    ex.backward()
    onehot = np.eye(3)[label.astype(int)]
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               out - onehot, rtol=1e-4, atol=1e-6)


def test_softmax_ignore_label():
    data = sym.Variable("data")
    s = sym.SoftmaxOutput(data=data, name="softmax", use_ignore=True,
                          ignore_label=-1)
    x = np.random.randn(3, 4).astype(np.float32)
    label = np.array([1, -1, 2], dtype=np.float32)
    args = {"data": mx.nd.array(x), "softmax_label": mx.nd.array(label)}
    grads = {"data": mx.nd.zeros((3, 4))}
    ex = s.bind(mx.cpu(), args, args_grad=grads,
                grad_req={"data": "write", "softmax_label": "null"})
    ex.forward(is_train=True)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    np.testing.assert_allclose(g[1], np.zeros(4), atol=1e-7)
    assert np.abs(g[0]).sum() > 0


def test_convolution_forward():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(3, 3), num_filter=2, pad=(1, 1),
                           name="conv")
    rng = np.random.RandomState(0)
    x = rng.randn(1, 1, 5, 5).astype(np.float32)
    w = rng.randn(2, 1, 3, 3).astype(np.float32)
    b = np.zeros(2, dtype=np.float32)
    _, outs = _bind_forward(conv, {"data": x, "conv_weight": w, "conv_bias": b})
    out = outs[0].asnumpy()
    assert out.shape == (1, 2, 5, 5)
    # center value check vs direct correlation
    ref = sum(x[0, 0, 1 + di, 1 + dj] * w[0, 0, 1 + di, 1 + dj]
              for di in (-1, 0, 1) for dj in (-1, 0, 1))
    np.testing.assert_allclose(out[0, 0, 1, 1], ref, rtol=1e-4)


def test_convolution_grad():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(2, 2), num_filter=2,
                           name="conv", no_bias=True)
    rng = np.random.RandomState(0)
    check_numeric_gradient(conv, {
        "data": rng.randn(2, 2, 4, 4).astype(np.float32),
        "conv_weight": rng.randn(2, 2, 2, 2).astype(np.float32)},
        numeric_eps=1e-2, check_eps=0.05)


def test_pooling():
    data = sym.Variable("data")
    x = np.arange(16).reshape(1, 1, 4, 4).astype(np.float32)
    pmax = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2), pool_type="max")
    _, outs = _bind_forward(pmax, {"data": x})
    np.testing.assert_allclose(outs[0].asnumpy()[0, 0],
                               [[5, 7], [13, 15]])
    pavg = sym.Pooling(data=data, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    _, outs = _bind_forward(pavg, {"data": x})
    np.testing.assert_allclose(outs[0].asnumpy()[0, 0],
                               [[2.5, 4.5], [10.5, 12.5]])
    pglobal = sym.Pooling(data=data, kernel=(1, 1), global_pool=True,
                          pool_type="max")
    _, outs = _bind_forward(pglobal, {"data": x})
    assert outs[0].shape == (1, 1, 1, 1)
    assert outs[0].asnumpy().ravel()[0] == 15


def test_batchnorm_train_and_aux():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn", fix_gamma=False, momentum=0.9)
    rng = np.random.RandomState(0)
    x = (rng.randn(8, 3, 2, 2) * 2 + 1).astype(np.float32)
    args = {"data": mx.nd.array(x), "bn_gamma": mx.nd.ones((3,)),
            "bn_beta": mx.nd.zeros((3,))}
    grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()}
    ex = bn.bind(mx.cpu(), args, args_grad=grads, grad_req="write",
                 aux_states=[mx.nd.zeros((3,)), mx.nd.ones((3,))])
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    # normalized output: per-channel mean ~0 var ~1
    np.testing.assert_allclose(out.mean(axis=(0, 2, 3)), np.zeros(3),
                               atol=1e-5)
    np.testing.assert_allclose(out.var(axis=(0, 2, 3)), np.ones(3), atol=1e-2)
    ex.backward()
    # moving stats committed on backward
    mean = x.mean(axis=(0, 2, 3))
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               0.1 * mean, rtol=1e-4)
    # inference path uses moving stats
    ex.forward(is_train=False)
    out_inf = ex.outputs[0].asnumpy()
    assert not np.allclose(out, out_inf)


def test_dropout():
    data = sym.Variable("data")
    do = sym.Dropout(data=data, p=0.5)
    x = np.ones((100, 100), dtype=np.float32)
    ex, outs = _bind_forward(do, {"data": x}, is_train=True)
    out = ex.outputs[0].asnumpy()
    frac_zero = (out == 0).mean()
    assert 0.3 < frac_zero < 0.7
    kept = out[out != 0]
    np.testing.assert_allclose(kept, 2.0, rtol=1e-5)
    _, outs = _bind_forward(do, {"data": x}, is_train=False)
    np.testing.assert_allclose(outs[0].asnumpy(), x)


def test_concat_and_slice():
    a = sym.Variable("a")
    b = sym.Variable("b")
    cat = sym.Concat(a, b, num_args=2, dim=1)
    an = np.ones((2, 2), dtype=np.float32)
    bn = np.zeros((2, 3), dtype=np.float32)
    _, outs = _bind_forward(cat, {"a": an, "b": bn})
    assert outs[0].shape == (2, 5)
    np.testing.assert_allclose(outs[0].asnumpy(),
                               np.concatenate([an, bn], axis=1))


def test_reshape_flatten_transpose():
    data = sym.Variable("data")
    x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    r = sym.Reshape(data=data, shape=(2, 12))
    _, outs = _bind_forward(r, {"data": x})
    assert outs[0].shape == (2, 12)
    # old API: target_shape 0 means "infer this dim" (reference
    # reshape-inl.h, exercised as target_shape=(2,0) -> (2,75) in the
    # reference's test_reshape)
    r2 = sym.Reshape(data=data, target_shape=(2, 0))
    _, outs = _bind_forward(r2, {"data": x})
    assert outs[0].shape == (2, 12)
    f = sym.Flatten(data=data)
    _, outs = _bind_forward(f, {"data": x})
    assert outs[0].shape == (2, 12)
    t = sym.transpose(data=data, axes=(1, 0, 2))
    _, outs = _bind_forward(t, {"data": x})
    np.testing.assert_allclose(outs[0].asnumpy(), x.transpose(1, 0, 2))
    s = sym.SwapAxis(data=data, dim1=0, dim2=2)
    _, outs = _bind_forward(s, {"data": x})
    np.testing.assert_allclose(outs[0].asnumpy(), x.swapaxes(0, 2))


def test_embedding():
    data = sym.Variable("data")
    emb = sym.Embedding(data=data, input_dim=5, output_dim=3, name="emb")
    w = np.random.randn(5, 3).astype(np.float32)
    idx = np.array([0, 4, 2], dtype=np.float32)
    _, outs = _bind_forward(emb, {"data": idx, "emb_weight": w})
    np.testing.assert_allclose(outs[0].asnumpy(), w[[0, 4, 2]])


def test_block_grad():
    data = sym.Variable("data")
    blocked = sym.BlockGrad(data=data)
    out = blocked * 2
    x = np.ones((2, 2), dtype=np.float32)
    args = {"data": mx.nd.array(x)}
    grads = {"data": mx.nd.zeros((2, 2))}
    ex = out.bind(mx.cpu(), args, args_grad=grads, grad_req="write")
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               np.zeros((2, 2)))


def test_make_loss():
    data = sym.Variable("data")
    loss = sym.MakeLoss(data=data, grad_scale=0.5)
    x = np.random.rand(3, 3).astype(np.float32)
    args = {"data": mx.nd.array(x)}
    grads = {"data": mx.nd.zeros((3, 3))}
    ex = loss.bind(mx.cpu(), args, args_grad=grads, grad_req="write")
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               np.full((3, 3), 0.5))


def test_regression_outputs():
    data = sym.Variable("data")
    lro = sym.LinearRegressionOutput(data=data, name="lro")
    x = np.array([[1.0], [2.0]], dtype=np.float32)
    label = np.array([[1.5], [1.0]], dtype=np.float32)
    args = {"data": mx.nd.array(x), "lro_label": mx.nd.array(label)}
    grads = {"data": mx.nd.zeros((2, 1))}
    ex = lro.bind(mx.cpu(), args, args_grad=grads,
                  grad_req={"data": "write", "lro_label": "null"})
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), x - label,
                               rtol=1e-5)


def test_reductions():
    data = sym.Variable("data")
    x = np.random.rand(2, 3, 4).astype(np.float32)
    s = sym.sum(data=data, axis=(1,))
    _, outs = _bind_forward(s, {"data": x})
    np.testing.assert_allclose(outs[0].asnumpy(), x.sum(axis=1), rtol=1e-5)
    m = sym.max(data=data)
    _, outs = _bind_forward(m, {"data": x})
    np.testing.assert_allclose(outs[0].asnumpy(), [x.max()], rtol=1e-6)


def test_lrn():
    data = sym.Variable("data")
    lrn = sym.LRN(data=data, nsize=3, alpha=1e-4, beta=0.75, knorm=2.0)
    x = np.random.rand(1, 5, 2, 2).astype(np.float32)
    _, outs = _bind_forward(lrn, {"data": x})
    # manual reference for channel 2
    sq = x ** 2
    ssum = sq[:, 1:4].sum(axis=1)
    denom = (2.0 + (1e-4 / 3) * ssum) ** 0.75
    np.testing.assert_allclose(outs[0].asnumpy()[0, 2], (x[0, 2] / denom[0]),
                               rtol=1e-5)


def test_upsampling():
    data = sym.Variable("data")
    up = sym.UpSampling(data, scale=2, sample_type="nearest", num_args=1)
    x = np.arange(4).reshape(1, 1, 2, 2).astype(np.float32)
    _, outs = _bind_forward(up, {"data": x})
    expected = x.repeat(2, axis=2).repeat(2, axis=3)
    np.testing.assert_allclose(outs[0].asnumpy(), expected)


def test_numeric_gradient_various():
    rng = np.random.RandomState(0)
    data = sym.Variable("data")
    for s in [sym.Activation(data=data, act_type="tanh"),
              sym.L2Normalization(data=data),
              sym.Flatten(data=data) * 2.0]:
        check_numeric_gradient(s, {"data": rng.randn(3, 4).astype(np.float32)},
                               check_eps=0.05)


def test_smooth_l1():
    data = sym.Variable("data")
    s = sym.smooth_l1(data=data, scalar=1.0)
    x = np.array([-2.0, -0.5, 0.5, 2.0], dtype=np.float32)
    _, outs = _bind_forward(s, {"data": x})
    expected = np.where(np.abs(x) < 1, 0.5 * x ** 2, np.abs(x) - 0.5)
    np.testing.assert_allclose(outs[0].asnumpy(), expected, rtol=1e-5)
