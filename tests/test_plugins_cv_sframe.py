"""opencv + sframe plugin equivalents (reference plugin/opencv/,
plugin/sframe/): same surfaces over PIL/pandas backends."""
import os

import numpy as np
import pytest

pytest.importorskip("PIL")

import mxnet_tpu as mx
from mxnet_tpu.plugins import opencv as cv
from mxnet_tpu.plugins.sframe import MXSFrameDataIter, MXSFrameImageIter


def _png_bytes(arr):
    import io as bio

    from PIL import Image

    buf = bio.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def test_imdecode_bgr_and_grayscale():
    rgb = np.zeros((5, 7, 3), dtype=np.uint8)
    rgb[..., 0] = 200          # red image
    raw = _png_bytes(rgb)
    img = cv.imdecode(raw, cv.IMREAD_COLOR)
    assert img.shape == (5, 7, 3)
    out = img.asnumpy()
    assert out[0, 0, 2] == 200 and out[0, 0, 0] == 0   # BGR order
    gray = cv.imdecode(raw, cv.IMREAD_GRAYSCALE)
    assert gray.shape == (5, 7, 1)


def test_resize_border_crop_normalize():
    img = mx.nd.array(np.arange(48, dtype=np.uint8).reshape(4, 4, 3))
    big = cv.resize(img, (8, 6))
    assert big.shape == (6, 8, 3)
    padded = cv.copyMakeBorder(img, 1, 1, 2, 2, cv.BORDER_CONSTANT, 9)
    assert padded.shape == (6, 8, 3)
    assert padded.asnumpy()[0, 0, 0] == 9
    rep = cv.copyMakeBorder(img, 1, 0, 0, 0, cv.BORDER_REPLICATE)
    assert (rep.asnumpy()[0] == img.asnumpy()[0]).all()

    crop = cv.fixed_crop(big, 1, 2, 4, 3)
    assert crop.shape == (3, 4, 3)
    crop2, roi = cv.random_crop(big, (4, 4))
    assert crop2.shape == (4, 4, 3) and len(roi) == 4
    crop3, _ = cv.random_size_crop(big, (4, 4))
    assert crop3.shape == (4, 4, 3)

    norm = cv.color_normalize(img, mean=(1.0, 2.0, 3.0), std=(2, 2, 2))
    np.testing.assert_allclose(
        norm.asnumpy()[0, 0], (np.array([0, 1, 2]) - [1, 2, 3]) / 2.0)


def test_image_list_iter(tmp_path):
    from PIL import Image

    rng = np.random.RandomState(0)
    names = []
    for i in range(5):
        name = "img%d.png" % i
        Image.fromarray((rng.rand(10, 12, 3) * 255).astype(np.uint8)) \
            .save(os.path.join(tmp_path, name))
        names.append("%d\t%d\t%s" % (i, i % 2, name))
    flist = tmp_path / "list.txt"
    flist.write_text("\n".join(names) + "\n")

    it = cv.ImageListIter(str(tmp_path) + os.sep, str(flist),
                          batch_size=2, size=(8, 6))
    batches = list(iter(it))
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 6, 8, 3)
    assert batches[-1].pad == 1
    it.reset()
    assert next(iter(it)).label[0].asnumpy().tolist() == [0.0, 1.0]


def test_sframe_data_iter_roundtrip(tmp_path):
    import pandas as pd

    rng = np.random.RandomState(1)
    rows = [{"data": " ".join("%g" % v for v in rng.rand(6)),
             "label": i % 3} for i in range(10)]
    path = tmp_path / "table.csv"
    pd.DataFrame(rows).to_csv(path, index=False)

    it = MXSFrameDataIter(str(path), data_field="data",
                          label_field="label", data_shape=(2, 3),
                          label_shape=(1,), batch_size=4)
    b = next(iter(it))
    assert b.data[0].shape == (4, 2, 3)
    assert b.label[0].shape == (4,)
    # registry creation path (reference MXNET_REGISTER_IO_ITER)
    it2 = mx.io.MXDataIter("MXSFrameDataIter", path_sframe=str(path),
                           data_field="data", label_field="label",
                           data_shape=(6,), batch_size=5)
    assert next(iter(it2)).data[0].shape == (5, 6)


def test_sframe_image_iter(tmp_path):
    import pandas as pd

    rng = np.random.RandomState(2)
    paths = []
    from PIL import Image

    for i in range(6):
        p = str(tmp_path / ("im%d.png" % i))
        Image.fromarray((rng.rand(9, 9, 3) * 255).astype(np.uint8)).save(p)
        paths.append(p)
    df = pd.DataFrame({"image": paths, "label": [i % 2 for i in range(6)]})
    it = MXSFrameImageIter(df, data_field="image", label_field="label",
                           data_shape=(3, 8, 8), batch_size=3)
    b = next(iter(it))
    assert b.data[0].shape == (3, 3, 8, 8)


def test_sframe_field_error():
    import pandas as pd

    with pytest.raises(mx.base.MXNetError):
        MXSFrameDataIter(pd.DataFrame({"a": [1]}), data_field="nope")
