"""R frontend validation without an R runtime.

Three gates (R-package/README.md): (1) the C glue compiles against the
real c_api.h (stub R headers supply the SEXP surface), (2) every .Call
from R resolves to a registered native routine with matching arity,
(3) NAMESPACE exports exist in the R source. The ABI semantics under the
glue are covered by test_c_api_core.py / test_perl_frontend.py."""
import os
import re
import subprocess
import tempfile

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
RPKG = os.path.join(REPO, "R-package")

R_STUB = r"""
#ifndef R_INTERNALS_STUB
#define R_INTERNALS_STUB
#include <stddef.h>
typedef void *SEXP;
typedef ptrdiff_t R_xlen_t;
typedef void (*R_CFinalizer_t)(SEXP);
#define STRSXP 16
#define INTSXP 13
#define REALSXP 14
#define VECSXP 19
extern SEXP R_NilValue, R_NamesSymbol;
SEXP Rf_allocVector(int, R_xlen_t);
SEXP Rf_mkChar(const char*); SEXP Rf_mkString(const char*);
SEXP Rf_install(const char*);
void SET_STRING_ELT(SEXP, R_xlen_t, SEXP);
SEXP STRING_ELT(SEXP, R_xlen_t);
void SET_VECTOR_ELT(SEXP, R_xlen_t, SEXP);
SEXP VECTOR_ELT(SEXP, R_xlen_t);
const char *CHAR(SEXP);
int *INTEGER(SEXP); double *REAL(SEXP);
int Rf_length(SEXP); R_xlen_t Rf_xlength(SEXP);
int Rf_asInteger(SEXP);
double Rf_asReal(SEXP);
SEXP Rf_ScalarInteger(int);
SEXP Rf_setAttrib(SEXP, SEXP, SEXP); SEXP Rf_getAttrib(SEXP, SEXP);
SEXP PROTECT(SEXP); void UNPROTECT(int);
void Rf_error(const char*, ...);
char *R_alloc(size_t, int);
SEXP R_MakeExternalPtr(void*, SEXP, SEXP);
void *R_ExternalPtrAddr(SEXP);
void R_ClearExternalPtr(SEXP);
void R_RegisterCFinalizerEx(SEXP, R_CFinalizer_t, int);
typedef void *DL_FUNC;
typedef struct { const char *name; DL_FUNC fun; int numArgs; }
    R_CallMethodDef;
typedef struct _DllInfo DllInfo;
int R_registerRoutines(DllInfo*, const void*, const R_CallMethodDef*,
                       const void*, const void*);
int R_useDynamicSymbols(DllInfo*, int);
#ifndef TRUE
#define TRUE 1
#define FALSE 0
#endif
#endif
"""


def test_glue_compiles_against_real_c_api():
    import shutil
    if shutil.which("gcc") is None:
        pytest.skip("no gcc toolchain")
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "Rinternals.h"), "w") as f:
            f.write(R_STUB)
        with open(os.path.join(tmp, "R.h"), "w") as f:
            f.write('#include "Rinternals.h"\n')
        out = subprocess.run(
            ["gcc", "-fsyntax-only", "-Wall", "-Werror",
             "-Wno-unused-variable", "-I", tmp,
             "-I", os.path.join(REPO, "include"),
             os.path.join(RPKG, "src", "mxnet_glue.c")],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr


def _registered_routines():
    src = open(os.path.join(RPKG, "src", "mxnet_glue.c")).read()
    return dict(re.findall(
        r'\{"(mxr_\w+)",\s*\(DL_FUNC\)&\w+,\s*(\d+)\}', src))


def _r_calls():
    """Every .Call(symbol, args...) in R/ with its argument count."""
    calls = []
    for fname in os.listdir(os.path.join(RPKG, "R")):
        src = open(os.path.join(RPKG, "R", fname)).read()
        for m in re.finditer(r"\.Call\(", src):
            i = m.end()
            depth, args, cur = 1, [], []
            while depth > 0:
                ch = src[i]
                if ch in "([":
                    depth += 1
                elif ch in ")]":
                    depth -= 1
                    if depth == 0:
                        break
                elif ch == "," and depth == 1:
                    args.append("".join(cur))
                    cur = []
                    i += 1
                    continue
                cur.append(ch)
                i += 1
            args.append("".join(cur))
            calls.append((args[0].strip(), len(args) - 1, fname))
    return calls


def test_every_dotcall_resolves_with_matching_arity():
    routines = _registered_routines()
    calls = _r_calls()
    assert calls, "no .Call sites found — parser broken?"
    for symbol, nargs, fname in calls:
        assert symbol in routines, "%s: unregistered .Call %s" % (
            fname, symbol)
        assert int(routines[symbol]) == nargs, (
            "%s: .Call(%s) passes %d args, glue registers %s"
            % (fname, symbol, nargs, routines[symbol]))


def test_namespace_exports_defined():
    ns = open(os.path.join(RPKG, "NAMESPACE")).read()
    exports = re.findall(r"export\(([^)]+)\)", ns)
    rsrc = "".join(open(os.path.join(RPKG, "R", f)).read()
                   for f in os.listdir(os.path.join(RPKG, "R")))
    for name in exports:
        # value bindings count too (mx.metric.accuracy <- mx.metric.custom(...))
        pattern = re.escape(name) + r"\s*<-"
        assert re.search(pattern, rsrc), "export %s has no definition" % name


def test_c_registration_table_covers_all_functions():
    """Every SEXP-returning glue function is registered (a missing row
    means the R symbol silently resolves to NULL at runtime)."""
    src = open(os.path.join(RPKG, "src", "mxnet_glue.c")).read()
    defined = set(re.findall(r"^SEXP (mxr_\w+)\(", src, re.M))
    registered = set(_registered_routines())
    assert defined == registered, (defined - registered,
                                   registered - defined)


def test_r_glue_training_loop_executes(tmp_path):
    """Execution gate for the R frontend's native path: no R interpreter
    exists in this image, so tests/r_shim.c provides a REAL (minimal)
    implementation of the R C API and tests/r_glue_train.c performs the
    exact .Call sequence mx.model.FeedForward.create (R/model.R) drives
    — registry symbol construction, infer_shape with aux.shapes,
    simple_bind, per-batch set/forward/backward/get_grad, the
    optimizer.R SGD-momentum update — gating convergence to >= 0.9.
    What this cannot check is R-language semantics of the .R files;
    those are covered by the arity/NAMESPACE static gates above."""
    import shutil
    if shutil.which("gcc") is None or shutil.which("make") is None:
        pytest.skip("no gcc toolchain")
    r = subprocess.run(["make", "-C", REPO, "predict"],
                       capture_output=True, text=True)
    lib = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_predict.so")
    assert r.returncode == 0 and os.path.exists(lib), r.stderr[-800:]

    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "Rinternals.h"), "w") as f:
            f.write(R_STUB)
        with open(os.path.join(tmp, "R.h"), "w") as f:
            f.write('#include "Rinternals.h"\n')
        exe = os.path.join(tmp, "r_glue_train")
        r = subprocess.run(
            ["gcc", os.path.join(REPO, "tests", "r_shim.c"),
             os.path.join(REPO, "tests", "r_glue_train.c"),
             os.path.join(RPKG, "src", "mxnet_glue.c"),
             "-o", exe, "-I", tmp, "-I", os.path.join(REPO, "include"),
             "-L", os.path.dirname(lib), "-lmxtpu_predict",
             "-Wl,-rpath," + os.path.dirname(lib)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run([exe], capture_output=True, text=True, env=env,
                           timeout=600)
        assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
        acc = float(r.stdout.strip().split("final_acc=")[1])
        assert acc >= 0.9, r.stdout


def test_r_glue_rnn_training_and_inference_execute(tmp_path):
    """Execution gate for the R RNN tier's native path (round-4 item:
    reference R-package/R/{lstm,gru,rnn,rnn_model}.R): tests/
    r_glue_rnn_train.c performs the .Call sequence mx.lstm /
    mx.lstm.inference / mx.lstm.forward drive — Embedding/transpose/
    fused-RNN symbol construction, the new mxr_sym_get_output +
    mxr_sym_group glue for the state-carrying inference graph, training
    to convergence, then token-by-token stateful stepping — gating both
    accuracies >= 0.9."""
    import shutil
    if shutil.which("gcc") is None or shutil.which("make") is None:
        pytest.skip("no gcc toolchain")
    r = subprocess.run(["make", "-C", REPO, "predict"],
                       capture_output=True, text=True)
    lib = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_predict.so")
    assert r.returncode == 0 and os.path.exists(lib), r.stderr[-800:]

    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "Rinternals.h"), "w") as f:
            f.write(R_STUB)
        with open(os.path.join(tmp, "R.h"), "w") as f:
            f.write('#include "Rinternals.h"\n')
        exe = os.path.join(tmp, "r_glue_rnn_train")
        r = subprocess.run(
            ["gcc", os.path.join(REPO, "tests", "r_shim.c"),
             os.path.join(REPO, "tests", "r_glue_rnn_train.c"),
             os.path.join(RPKG, "src", "mxnet_glue.c"),
             "-o", exe, "-I", tmp, "-I", os.path.join(REPO, "include"),
             "-L", os.path.dirname(lib), "-lmxtpu_predict",
             "-Wl,-rpath," + os.path.dirname(lib)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run([exe], capture_output=True, text=True, env=env,
                           timeout=900)
        assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
        train_acc = float(r.stdout.split("train_acc=")[1].split()[0])
        infer_acc = float(r.stdout.split("infer_acc=")[1].split()[0])
        assert train_acc >= 0.9 and infer_acc >= 0.9, r.stdout
        # the Ops.MXNDArray arithmetic path (mxr_func_invoke) ran too
        assert "func_invoke_ok" in r.stdout, r.stdout


def test_rnn_R_defines_reference_surface():
    """The R RNN tier's public entry points exist with the reference's
    names (reference lstm.R:152-361, gru.R:150-355, rnn.R:136-342,
    viz.graph.R:24-158)."""
    rsrc = "".join(open(os.path.join(RPKG, "R", f)).read()
                   for f in os.listdir(os.path.join(RPKG, "R")))
    for fn in ["mx.lstm", "mx.lstm.inference", "mx.lstm.forward",
               "mx.gru", "mx.gru.inference", "mx.gru.forward",
               "mx.rnn", "mx.rnn.inference", "mx.rnn.forward",
               "mx.rnn.train", "mx.rnn.infer.model", "mx.rnn.step",
               "graph.viz", "mx.graph.viz",
               "mx.symbol.get.output", "mx.symbol.Group"]:
        assert re.search(re.escape(fn) + r"\s*(<-|<<-)", rsrc), \
            "missing %s" % fn


def test_model_R_defines_reference_training_surface():
    """mx.model.FeedForward.create and its reference companions exist in
    the R sources (reference R-package/R/model.R:94-562 scope)."""
    rsrc = "".join(open(os.path.join(RPKG, "R", f)).read()
                   for f in os.listdir(os.path.join(RPKG, "R")))
    for fn in ["mx.model.FeedForward.create", "mx.model.init.params",
               "mx.model.save", "mx.model.load", "mx.mlp",
               "mx.io.arrayiter", "mx.metric.accuracy", "mx.opt.sgd",
               "mx.init.Xavier", "mx.init.uniform",
               "mx.lr_scheduler.FactorScheduler",
               "mx.callback.log.train.metric"]:
        assert re.search(re.escape(fn) + r"\s*(<-|<<-)", rsrc), \
            "missing %s" % fn


def test_r_glue_io_iterators_train(tmp_path):
    """Execution gate for the R io-iterator bindings (round-4 verdict
    #3): tests/r_glue_io_train.c drives the exact .Call sequence
    mx.io.ImageRecordIter / CSVIter / MNISTIter (R/io.R) and the
    iterator form of mx.model.FeedForward.create perform — create from
    string kwargs, before_first/next/value, batches into a conv
    executor trained with the optimizer.R SGD math — gating >= 0.9
    accuracy from a recordio file, exact CSV read-back, and idx-format
    MNIST parsing. Reference surface: R-package/R/mxnet_generated.R:
    480-610."""
    import shutil
    if shutil.which("gcc") is None or shutil.which("make") is None:
        pytest.skip("no gcc toolchain")
    import sys as _sys

    import numpy as np

    _sys.path.insert(0, os.path.join(REPO, "tools"))
    from make_mnist_synth import write_idx_images, write_idx_labels

    from mxnet_tpu import recordio as rio

    # class-conditional 12x12 recordio (dark=0 / bright=1)
    rng = np.random.RandomState(0)
    rec = str(tmp_path / "t.rec")
    w = rio.MXRecordIO(rec, "w")
    for i in range(64):
        label = i % 2
        lo, hi = (0, 110) if label == 0 else (145, 255)
        w.write(rio.pack_img(
            rio.IRHeader(0, float(label), i, 0),
            rng.randint(lo, hi, (12, 12, 3)).astype(np.uint8),
            quality=95))
    w.close()

    csv = str(tmp_path / "t.csv")
    with open(csv, "w") as f:
        for r in range(4):
            f.write(",".join(str((r * 3 + c) * 0.5) for c in range(3))
                    + "\n")

    mimg = str(tmp_path / "imgs-idx3-ubyte")
    mlbl = str(tmp_path / "lbls-idx1-ubyte")
    write_idx_images(mimg, rng.randint(0, 255, (16, 28, 28))
                     .astype(np.uint8))
    write_idx_labels(mlbl, (np.arange(16) % 10).astype(np.uint8))

    r = subprocess.run(["make", "-C", REPO, "predict"],
                       capture_output=True, text=True)
    lib = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_predict.so")
    assert r.returncode == 0 and os.path.exists(lib), r.stderr[-800:]

    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "Rinternals.h"), "w") as f:
            f.write(R_STUB)
        with open(os.path.join(tmp, "R.h"), "w") as f:
            f.write('#include "Rinternals.h"\n')
        exe = os.path.join(tmp, "r_glue_io_train")
        r = subprocess.run(
            ["gcc", os.path.join(REPO, "tests", "r_shim.c"),
             os.path.join(REPO, "tests", "r_glue_io_train.c"),
             os.path.join(RPKG, "src", "mxnet_glue.c"),
             "-o", exe, "-I", tmp, "-I", os.path.join(REPO, "include"),
             "-L", os.path.dirname(lib), "-lmxtpu_predict",
             "-Wl,-rpath," + os.path.dirname(lib)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr[-2000:]

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        r = subprocess.run([exe, rec, csv, mimg, mlbl],
                           capture_output=True, text=True, env=env,
                           timeout=600)
        assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
        acc = float(r.stdout.strip().split("final_acc=")[1])
        assert acc >= 0.9, r.stdout
