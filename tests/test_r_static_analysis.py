"""Static call-resolution linter for the R sources.

No R interpreter exists in this image (round-3 verdict weak #4: an R
semantics bug would pass CI). This narrows the gap: every function
CALL in R-package/{R,demo}/*.R and examples/**/*.R must resolve to a
definition in the R sources, a base-R/stats/utils builtin, or a
load-time-generated operator name — so a typo'd call like
`mx.rnn.infer.create` (for `mx.rnn.infer.model`) fails CI instead of
waiting for a user with an R runtime.
"""
import os
import re

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
RPKG = os.path.join(REPO, "R-package")

# base R + recommended-package functions the sources may call freely
BASE_R = {
    # control / structure
    "function", "if", "for", "while", "return", "switch", "stop",
    "warning", "on.exit", "invisible", "missing", "match", "match.arg",
    "do.call", "Recall", "tryCatch", "sys.nframe", "requireNamespace",
    "require", "library", "structure", "class", "inherits", "unclass",
    "attr", "attributes", "new.env", "environment", "local", "get",
    "exists", "assign", "asNamespace", "namespaceExport", "ls",
    # vectors / lists
    "c", "list", "vector", "length", "names", "unlist", "lapply",
    "sapply", "vapply", "mapply", "seq", "seq_len", "seq_along", "rep",
    "rev", "which", "which.max", "which.min", "sort", "order", "unique",
    "max", "min", "sum", "prod", "mean", "abs", "sqrt", "exp", "log",
    "floor", "ceiling", "round", "pmin", "pmax", "cumsum", "range",
    "setdiff", "union", "intersect", "any", "all", "is.null",
    "is.numeric", "is.character", "is.function", "is.list", "is.array",
    "is.matrix", "is.na", "is.nan", "is.logical", "unname", "Filter",
    "Negate", "nchar", "paste", "paste0",
    "sprintf", "format", "substr", "strsplit", "sub", "gsub", "grepl",
    "regmatches", "gregexpr", "startsWith", "endsWith", "toupper",
    "tolower", "trimws", "as.numeric", "as.integer", "as.character",
    "as.logical", "as.array", "as.matrix", "as.vector", "as.list",
    "ifelse", "identical", "isTRUE", "isFALSE", "xor", "nrow", "ncol",
    "as.double", "nzchar",
    "dim", "t", "aperm", "array", "matrix", "max.col", "head", "tail",
    "numeric", "integer", "character", "logical", "double", "expm1",
    "tanh", "stopifnot",
    # io / files
    "file", "close", "readBin", "file.path", "file.exists", "dir.create",
    "tempfile", "basename", "dirname", "cat", "print", "message",
    "readRDS", "saveRDS", "read.csv", "write.csv", "data.frame",
    "commandArgs", "Sys.getenv", "Sys.time", "system", "setwd",
    "download.file", "unzip", "file.remove", "load", "save", "imshow",
    "imresize",
    # random / stats (stats::)
    "set.seed", "rnorm", "runif", "sample", "rbinom", "setNames",
    "cbind", "rbind", "rowSums", "colSums", "emptyenv", "quote",
    "eval", "conditionMessage", "packageStartupMessage",
    # testthat / knitr surfaces used in tests and vignettes
    "test_that", "context", "expect_equal", "expect_true",
    "expect_false", "expect_error", "test_check", "data.matrix",
    # Rcpp-free .Call interface
    ".Call",
}

# dynamic names created at package load (operator generation) or by R
# itself — validated by prefix instead of definition lookup
DYNAMIC_PREFIXES = ("mx.symbol.", "mxr_")

# per-file dot-methods R dispatches dynamically (S3 generics)
S3_GENERICS = {"predict", "dim", "as.array", "print", "Ops"}


def _strip_r(src):
    """Blank out strings and comments with a char scanner — regexes
    mis-nest when a comment contains an apostrophe (\"don't\") or a
    string contains '#'. POSITION-PRESERVING: the result has the same
    length as the input (string contents and comments become spaces),
    so offsets found in the stripped text index into the raw text."""
    out = []
    i, n = 0, len(src)
    while i < n:
        ch = src[i]
        if ch in "'\"":
            quote = ch
            out.append(quote)
            i += 1
            while i < n and src[i] != quote:
                if src[i] == "\\":
                    out.append(" ")
                    i += 1
                out.append(" ")
                i += 1
            out.append(quote)
            i += 1
        elif ch == "#":
            while i < n and src[i] != "\n":
                out.append(" ")
                i += 1
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _r_files():
    roots = [os.path.join(RPKG, "R"), os.path.join(RPKG, "demo"),
             os.path.join(RPKG, "tests"), os.path.join(REPO, "examples")]
    for root in roots:
        for dirpath, _, files in os.walk(root):
            for f in files:
                if f.endswith(".R"):
                    yield os.path.join(dirpath, f)


NAME = r"[A-Za-z._][A-Za-z0-9._]*"


def _definitions(sources):
    defined = set()
    for src in sources.values():
        for m in re.finditer(r"(?:^|[\n;{(])\s*[`]?(%s)[`]?\s*(?:<<?-|=)\s*function"
                             % NAME, src):
            defined.add(m.group(1))
        # alias bindings count too (mx.graph.viz <- graph.viz), but
        # ONLY when the RHS is a bare name — whitelisting every
        # assigned variable would let a typo'd call that collides with
        # any local (`model(x)`) slip through
        for m in re.finditer(r"(?:^|\n)\s*(%s)\s*<<?-\s*(%s)\s*(?:\n|$)"
                             % (NAME, NAME), src):
            defined.add(m.group(1))
    return defined


def _param_names(src):
    """Formal parameter names of every function(...) in the file —
    higher-order code calls them (feval(...), batch.end.callback(...))."""
    params = set()
    for m in re.finditer(r"function\s*\(", src):
        depth, i = 1, m.end()
        start = i
        while i < len(src) and depth:
            if src[i] == "(":
                depth += 1
            elif src[i] == ")":
                depth -= 1
            i += 1
        arglist = src[start:i - 1]
        for part in re.split(r",(?![^()\[\]]*[)\]])", arglist):
            name = part.split("=")[0].strip().strip("`")
            if re.fullmatch(NAME, name):
                params.add(name)
    return params


def test_every_r_call_resolves():
    sources = {p: _strip_r(open(p).read()) for p in _r_files()}
    assert sources, "no R sources found"
    defined = _definitions(sources)

    # a call site is any <name>( not preceded by name chars or '::'
    call_re = re.compile(r"(?<![A-Za-z0-9._:])(%s)\s*\(" % NAME)
    unresolved = []
    for path, src in sources.items():
        # SAME-FILE bindings of any RHS are callable (function-valued
        # locals like `updater <- mx.opt.create.updater(...)`): scoped
        # per file, so a typo'd API name can't resolve via a binding in
        # some other file
        local_ok = defined | _param_names(src) | {
            m.group(1) for m in re.finditer(
                r"(?:^|\n)\s*(%s)\s*<<?-\s*" % NAME, src)}
        for m in call_re.finditer(src):
            name = m.group(1)
            if name in BASE_R or name in local_ok:
                continue
            if any(name.startswith(p) for p in DYNAMIC_PREFIXES):
                continue
            if name.split(".")[0] in S3_GENERICS:
                continue
            unresolved.append((os.path.relpath(path, REPO), name))
    unresolved = sorted(set(unresolved))
    assert not unresolved, (
        "R calls that resolve to no definition (typo'd API name?):\n"
        + "\n".join("  %s: %s()" % u for u in unresolved))


def test_r_operator_usage_matches_registry():
    """Every mx.symbol.create(\"Op\", key = ...) call site in R names a
    REGISTERED operator and only passes declared parameter keys — a
    typo'd op name or param (`n_filter` for `num_filter`) fails here
    instead of at the first R runtime."""
    import sys

    sys.path.insert(0, REPO)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from mxnet_tpu.ops import registry

    known_ops = {}
    for key in registry.OP_REGISTRY.list_names():
        cls = registry.OP_REGISTRY.get(key)
        known_ops[getattr(cls, "op_name", key).lower()] = set(
            getattr(cls, "PARAMS", {}))

    # args every creator accepts regardless of op (symbol inputs by
    # role name, and the node name)
    generic = {"name", "data", "label", "weight", "bias", "rois",
               "lhs", "rhs"}

    bad = []
    for path in _r_files():
        raw = open(path).read()
        stripped = _strip_r(raw)   # same length: offsets carry over
        # single- or double-quoted op name; contents are blanked in
        # `stripped`, so recover the actual name from `raw` at the
        # same offsets
        for m in re.finditer(
                r"mx\.symbol\.create\(\s*([\"'])", stripped):
            quote = m.group(1)
            name_end = stripped.index(quote, m.end())
            op = raw[m.end():name_end]
            if op.lower() not in known_ops:
                bad.append((os.path.relpath(path, REPO),
                            "unknown op %r" % op))
                continue
            # param keys of THIS call: scan the STRIPPED text (strings
            # and comments blanked) to the matching close paren
            depth, i = 1, name_end + 1
            while i < len(stripped) and depth:
                if stripped[i] == "(":
                    depth += 1
                elif stripped[i] == ")":
                    depth -= 1
                i += 1
            call = stripped[name_end + 1:i - 1]
            # split the call body at DEPTH-0 commas so params of nested
            # mx.symbol.create(...) calls aren't attributed to this op
            args, depth, seg = [], 0, []
            for ch in call:
                if ch in "([":
                    depth += 1
                elif ch in ")]":
                    depth -= 1
                if ch == "," and depth == 0:
                    args.append("".join(seg))
                    seg = []
                else:
                    seg.append(ch)
            args.append("".join(seg))
            for arg in args:
                pk = re.match(r"\s*([A-Za-z_][A-Za-z0-9._]*)\s*=[^=]",
                              arg)
                if not pk:
                    continue
                key = pk.group(1).replace(".", "_")
                if key in generic or key in known_ops[op.lower()]:
                    continue
                bad.append((os.path.relpath(path, REPO),
                            "%s(%s=...) not a declared param"
                            % (op, pk.group(1))))
    bad = sorted(set(bad))
    assert not bad, ("R operator usage inconsistent with the registry:\n"
                     + "\n".join("  %s: %s" % b for b in bad))
