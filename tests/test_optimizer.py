"""Optimizer tests: update rules against numpy references
(reference tests validated via Test optimizer + training convergence)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _run_updates(optimizer, w0, grads):
    weight = mx.nd.array(w0.copy())
    state = optimizer.create_state(0, weight)
    for g in grads:
        optimizer.update(0, weight, mx.nd.array(g), state)
    return weight.asnumpy()


def test_sgd_no_momentum():
    w0 = np.ones(4, dtype=np.float32)
    g = np.full(4, 0.5, dtype=np.float32)
    sgd = opt.SGD(learning_rate=0.1, rescale_grad=1.0, wd=0.0)
    w = _run_updates(sgd, w0, [g, g])
    np.testing.assert_allclose(w, w0 - 0.1 * g * 2, rtol=1e-6)


def test_sgd_momentum_matches_numpy():
    rng = np.random.RandomState(0)
    w0 = rng.randn(5).astype(np.float32)
    grads = [rng.randn(5).astype(np.float32) for _ in range(4)]
    lr, mom, wd = 0.05, 0.9, 0.01
    sgd = opt.SGD(learning_rate=lr, momentum=mom, wd=wd, rescale_grad=1.0)
    w = _run_updates(sgd, w0, grads)
    # numpy reference
    wn = w0.copy().astype(np.float64)
    m = np.zeros(5)
    for g in grads:
        gg = g + wd * wn
        m = mom * m - lr * gg
        wn = wn + m
    np.testing.assert_allclose(w, wn, rtol=1e-5)


def test_adam_matches_numpy():
    rng = np.random.RandomState(1)
    w0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(5)]
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    adam = opt.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                    rescale_grad=1.0)
    w = _run_updates(adam, w0, grads)
    wn = w0.astype(np.float64).copy()
    m = np.zeros(6)
    v = np.zeros(6)
    for t, g in enumerate(grads, 1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        step = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        wn -= step * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(w, wn, rtol=1e-4)


def test_adagrad():
    w0 = np.ones(3, dtype=np.float32)
    g = np.full(3, 2.0, dtype=np.float32)
    ada = opt.AdaGrad(learning_rate=0.1, rescale_grad=1.0, eps=1e-7)
    w = _run_updates(ada, w0, [g])
    np.testing.assert_allclose(w, w0 - 0.1 * g / np.sqrt(g * g + 1e-7),
                               rtol=1e-5)


def test_rescale_and_clip():
    w0 = np.zeros(3, dtype=np.float32)
    g = np.array([10.0, -10.0, 1.0], dtype=np.float32)
    sgd = opt.SGD(learning_rate=1.0, rescale_grad=0.1, clip_gradient=0.5)
    w = _run_updates(sgd, w0, [g])
    np.testing.assert_allclose(w, [-0.5, 0.5, -0.1], rtol=1e-6)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler

    sched = FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(5) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25
    msched = MultiFactorScheduler(step=[5, 15], factor=0.1)
    msched.base_lr = 1.0
    assert msched(3) == 1.0
    assert abs(msched(7) - 0.1) < 1e-9
    assert abs(msched(20) - 0.01) < 1e-9


def test_lr_wd_mult_from_symbol():
    from mxnet_tpu import symbol as sym

    data = sym.Variable("data")
    w = sym.Variable("fc_weight", lr_mult=2.0)
    fc = sym.FullyConnected(data=data, weight=w, num_hidden=2, name="fc")
    sgd = opt.SGD(learning_rate=0.1, sym=fc,
                  param_idx2name={0: "fc_weight", 1: "fc_bias"})
    assert sgd.lr_mult.get("fc_weight") == 2.0
    assert sgd._get_lr(0) == pytest.approx(0.2)
    assert sgd._get_lr(1) == pytest.approx(0.1)


def test_updater_state():
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    updater = opt.get_updater(sgd)
    w = mx.nd.ones((3,))
    updater(0, mx.nd.ones((3,)), w)
    updater(0, mx.nd.ones((3,)), w)
    assert 0 in updater.states


def test_update_multi_matches_sequential():
    """Fused multi-param updates must be numerically identical to the
    per-param path for every planned optimizer kind, including per-param
    lr/wd multipliers and Adam's per-index step counts."""
    import numpy as np

    from mxnet_tpu import nd
    from mxnet_tpu import optimizer as opt_mod

    rng = np.random.RandomState(0)
    shapes = [(8, 4), (16,), (3, 3, 2)]

    def make(opt_cls, **kw):
        o = opt_cls(**kw)
        o.idx2name = {0: "a_weight", 1: "b_bias", 2: "c_weight"}
        o.set_lr_mult({"a_weight": 2.0})
        o.set_wd_mult({"b_bias": 0.0})
        return o

    for cls, kw in [(opt_mod.SGD, dict(learning_rate=0.1, momentum=0.9,
                                       wd=1e-3)),
                    (opt_mod.Adam, dict(learning_rate=0.01, wd=1e-4)),
                    (opt_mod.RMSProp, dict(learning_rate=0.01)),
                    (opt_mod.AdaGrad, dict(learning_rate=0.05)),
                    (opt_mod.AdaDelta, dict()),
                    (opt_mod.NAG, dict(learning_rate=0.1, momentum=0.8,
                                       clip_gradient=0.5))]:
        grads_per_step = [
            [rng.randn(*s).astype(np.float32) for s in shapes]
            for _ in range(3)]

        def run(multi):
            seq_opt = make(cls, **kw)
            upd = opt_mod.get_updater(seq_opt)
            ws = [nd.zeros(s) for s in shapes]
            for step_grads in grads_per_step:
                items = [(i, nd.array(g), w)
                         for i, (g, w) in enumerate(zip(step_grads, ws))]
                if multi:
                    upd.update_multi(items)
                else:
                    for i, g, w in items:
                        upd(i, g, w)
            return [w.asnumpy() for w in ws]

        for a, b in zip(run(multi=False), run(multi=True)):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7,
                                       err_msg=cls.__name__)


def test_update_multi_falls_back_for_custom_optimizer():
    """User optimizers that only override update() (the reference
    contract) must keep working through update_multi."""
    import numpy as np

    from mxnet_tpu import nd
    from mxnet_tpu import optimizer as opt_mod

    calls = []

    class Plain(opt_mod.Optimizer):
        def update(self, index, weight, grad, state):
            calls.append(index)
            weight -= grad * 0.5

    upd = opt_mod.get_updater(Plain())
    ws = [nd.ones((4,)), nd.ones((2, 2))]
    upd.update_multi([(0, nd.ones((4,)), ws[0]),
                      (1, nd.ones((2, 2)), ws[1])])
    assert calls == [0, 1]
    np.testing.assert_allclose(ws[0].asnumpy(), np.full(4, 0.5))


def test_update_multi_respects_subclass_update_override():
    """A subclass of a BUILT-IN optimizer that overrides update() (the
    reference extension contract) must take the sequential path: the
    inherited plan does not describe its custom math."""
    import numpy as np

    from mxnet_tpu import nd
    from mxnet_tpu import optimizer as opt_mod

    class HalvedSGD(opt_mod.SGD):
        def update(self, index, weight, grad, state):
            weight -= grad * 0.5      # NOT sgd math

    upd = opt_mod.get_updater(HalvedSGD(learning_rate=123.0))
    w = nd.ones((4,))
    upd.update_multi([(0, nd.ones((4,)), w)])
    np.testing.assert_allclose(w.asnumpy(), np.full(4, 0.5))

    # overriding _plan alone keeps the fused path (plan describes it)
    class PlannedSGD(opt_mod.SGD):
        def _plan(self, index, weight, grad, state):
            return super()._plan(index, weight, grad, state)

    assert PlannedSGD(learning_rate=0.1)._fusable()
    assert not HalvedSGD(learning_rate=0.1)._fusable()


def test_donation_disabled_by_engine_warns_once(monkeypatch, caplog):
    """An engine outside the inline allowlist silently doubles transient
    param HBM; _donation_ok must say so, once, not per step."""
    import logging

    from mxnet_tpu import engine as eng
    from mxnet_tpu import optimizer as optmod

    class FakeThreadedEngine:
        pass

    monkeypatch.setattr(optmod, "_DONATION_WARNED", False)
    monkeypatch.setattr(eng, "get_engine", lambda: FakeThreadedEngine())
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.optimizer"):
        assert optmod._donation_ok() is False
        assert optmod._donation_ok() is False
    warns = [r for r in caplog.records
             if "donation disabled" in r.getMessage()]
    assert len(warns) == 1
    assert "FakeThreadedEngine" in warns[0].getMessage()


def test_donation_env_off_does_not_warn(monkeypatch, caplog):
    """MXNET_TPU_DONATE=0 is an explicit user choice — no nagging."""
    import logging

    from mxnet_tpu import optimizer as optmod

    monkeypatch.setattr(optmod, "_DONATION_WARNED", False)
    monkeypatch.setenv("MXNET_TPU_DONATE", "0")
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.optimizer"):
        assert optmod._donation_ok() is False
    assert not [r for r in caplog.records
                if "donation disabled" in r.getMessage()]
