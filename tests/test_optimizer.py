"""Optimizer tests: update rules against numpy references
(reference tests validated via Test optimizer + training convergence)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _run_updates(optimizer, w0, grads):
    weight = mx.nd.array(w0.copy())
    state = optimizer.create_state(0, weight)
    for g in grads:
        optimizer.update(0, weight, mx.nd.array(g), state)
    return weight.asnumpy()


def test_sgd_no_momentum():
    w0 = np.ones(4, dtype=np.float32)
    g = np.full(4, 0.5, dtype=np.float32)
    sgd = opt.SGD(learning_rate=0.1, rescale_grad=1.0, wd=0.0)
    w = _run_updates(sgd, w0, [g, g])
    np.testing.assert_allclose(w, w0 - 0.1 * g * 2, rtol=1e-6)


def test_sgd_momentum_matches_numpy():
    rng = np.random.RandomState(0)
    w0 = rng.randn(5).astype(np.float32)
    grads = [rng.randn(5).astype(np.float32) for _ in range(4)]
    lr, mom, wd = 0.05, 0.9, 0.01
    sgd = opt.SGD(learning_rate=lr, momentum=mom, wd=wd, rescale_grad=1.0)
    w = _run_updates(sgd, w0, grads)
    # numpy reference
    wn = w0.copy().astype(np.float64)
    m = np.zeros(5)
    for g in grads:
        gg = g + wd * wn
        m = mom * m - lr * gg
        wn = wn + m
    np.testing.assert_allclose(w, wn, rtol=1e-5)


def test_adam_matches_numpy():
    rng = np.random.RandomState(1)
    w0 = rng.randn(6).astype(np.float32)
    grads = [rng.randn(6).astype(np.float32) for _ in range(5)]
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    adam = opt.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                    rescale_grad=1.0)
    w = _run_updates(adam, w0, grads)
    wn = w0.astype(np.float64).copy()
    m = np.zeros(6)
    v = np.zeros(6)
    for t, g in enumerate(grads, 1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        step = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        wn -= step * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(w, wn, rtol=1e-4)


def test_adagrad():
    w0 = np.ones(3, dtype=np.float32)
    g = np.full(3, 2.0, dtype=np.float32)
    ada = opt.AdaGrad(learning_rate=0.1, rescale_grad=1.0, eps=1e-7)
    w = _run_updates(ada, w0, [g])
    np.testing.assert_allclose(w, w0 - 0.1 * g / np.sqrt(g * g + 1e-7),
                               rtol=1e-5)


def test_rescale_and_clip():
    w0 = np.zeros(3, dtype=np.float32)
    g = np.array([10.0, -10.0, 1.0], dtype=np.float32)
    sgd = opt.SGD(learning_rate=1.0, rescale_grad=0.1, clip_gradient=0.5)
    w = _run_updates(sgd, w0, [g])
    np.testing.assert_allclose(w, [-0.5, 0.5, -0.1], rtol=1e-6)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler

    sched = FactorScheduler(step=10, factor=0.5)
    sched.base_lr = 1.0
    assert sched(5) == 1.0
    assert sched(11) == 0.5
    assert sched(21) == 0.25
    msched = MultiFactorScheduler(step=[5, 15], factor=0.1)
    msched.base_lr = 1.0
    assert msched(3) == 1.0
    assert abs(msched(7) - 0.1) < 1e-9
    assert abs(msched(20) - 0.01) < 1e-9


def test_lr_wd_mult_from_symbol():
    from mxnet_tpu import symbol as sym

    data = sym.Variable("data")
    w = sym.Variable("fc_weight", lr_mult=2.0)
    fc = sym.FullyConnected(data=data, weight=w, num_hidden=2, name="fc")
    sgd = opt.SGD(learning_rate=0.1, sym=fc,
                  param_idx2name={0: "fc_weight", 1: "fc_bias"})
    assert sgd.lr_mult.get("fc_weight") == 2.0
    assert sgd._get_lr(0) == pytest.approx(0.2)
    assert sgd._get_lr(1) == pytest.approx(0.1)


def test_updater_state():
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    updater = opt.get_updater(sgd)
    w = mx.nd.ones((3,))
    updater(0, mx.nd.ones((3,)), w)
    updater(0, mx.nd.ones((3,)), w)
    assert 0 in updater.states
