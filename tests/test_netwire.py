"""Zero-copy socket transport: frame codec properties (round-trip,
zero-length arrays, >cap refusal before allocation, truncation at every
cut point, version skew in BOTH directions), the pooled client against
a live loopback server (echo, reconnect, mid multiplexing), the four
net_* faults injected inside the framing layer, and the disaggregated
netfeed input plane (bit-identical batches across processes, seq
reassembly under net_reorder, FeedScheduler integration)."""
import struct
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import faults, netfeed, netwire, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.netwire import (WireClient, WireError, WirePeerLost,
                               WireServer, WireTimeout, decode_frame,
                               encode_frame)


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


@pytest.fixture
def no_faults():
    yield
    faults.configure(None)


def _wire_bytes(*args, **kwargs) -> bytes:
    return b"".join(bytes(b) for b in encode_frame(*args, **kwargs))


def _echo_server():
    """A server that doubles float arrays and echoes metadata."""
    def handler(frame, respond):
        if frame.op == "boom":
            raise RuntimeError("handler exploded")
        respond("ok", [np.asarray(a) * 2 for a in frame.arrays],
                {"echo": frame.meta})
    return WireServer(handler, name="echo-test")


# ---------------------------------------------------------------------------
# codec properties
# ---------------------------------------------------------------------------

def test_frame_round_trip_is_bit_identical():
    rng = np.random.RandomState(0)
    arrays = [rng.randn(4, 3).astype(np.float32),
              rng.randint(0, 255, (2, 2, 2)).astype(np.uint8),
              np.float64(3.5),                      # 0-d scalar
              np.zeros((0, 7), dtype=np.int64),     # zero-length
              np.array([], dtype=np.float16),
              rng.randn(5).astype(">f8")]           # big-endian dtype
    meta = {"k": [1, 2], "s": "x"}
    f = decode_frame(_wire_bytes("infer", "m-1", arrays, meta,
                                 trace_ctx={"trace": "t1"}))
    assert f.op == "infer" and f.mid == "m-1"
    assert f.meta == meta
    assert f.tctx == {"trace": "t1"}
    assert len(f.arrays) == len(arrays)
    for orig, got in zip(arrays, f.arrays):
        orig = np.asarray(orig)
        assert got.dtype == orig.dtype
        assert got.shape == orig.shape
        assert np.array_equal(got, orig)
        assert got.tobytes() == orig.tobytes()      # bit-identical


def test_empty_frame_round_trips():
    f = decode_frame(_wire_bytes("ping", "m-0"))
    assert f.op == "ping" and f.arrays == [] and f.meta == {}
    assert f.tctx is None


def test_non_contiguous_arrays_round_trip():
    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    views = [base[:, ::2], base.T, np.asfortranarray(base)]
    f = decode_frame(_wire_bytes("x", "m", views))
    for orig, got in zip(views, f.arrays):
        assert got.shape == orig.shape
        assert np.array_equal(got, orig)


def test_object_dtype_is_refused_no_pickle_on_the_wire():
    with pytest.raises(WireError, match="pickle"):
        encode_frame("x", "m", [np.array([object()])])


def test_oversize_length_field_refused_before_allocation(monkeypatch):
    """A corrupt/hostile prefix claiming a multi-GiB body must be
    refused from the 18-byte header alone — no allocation, and the
    error names the cap knob."""
    prefix = netwire._PREFIX
    cap = netwire._max_frame_bytes()
    assert cap == 4 << 30     # the default cap is 4 GiB
    for body_len in (cap + 1, 5 << 30, (1 << 64) - 1):
        head = prefix.pack(netwire._MAGIC, netwire.WIRE_VERSION, 0,
                           prefix.size, 0, body_len)
        with pytest.raises(WireError,
                           match="MXNET_TPU_WIRE_MAX_FRAME_MB"):
            decode_frame(head)
    # the metadata length field (u32) can only exceed a lowered cap
    monkeypatch.setenv("MXNET_TPU_WIRE_MAX_FRAME_MB", "1")
    head = prefix.pack(netwire._MAGIC, netwire.WIRE_VERSION, 0,
                       prefix.size, 2 << 20, 0)
    with pytest.raises(WireError, match="MXNET_TPU_WIRE_MAX_FRAME_MB"):
        decode_frame(head)


def test_oversize_payload_refused_at_encode(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_WIRE_MAX_FRAME_MB", "1")
    with pytest.raises(WireError, match="MXNET_TPU_WIRE_MAX_FRAME_MB"):
        encode_frame("x", "m", [np.zeros(2 << 20, dtype=np.uint8)])


def test_truncated_frames_raise_named_errors():
    whole = _wire_bytes("infer", "m-1", [np.arange(8, dtype=np.int32)],
                        {"a": 1})
    prefix = netwire._PREFIX
    # cut mid-header, mid-metadata, and mid-payload: every cut point
    # raises a WireError (an MXNetError) naming what was truncated
    for cut in (0, 3, prefix.size - 1, prefix.size + 2, len(whole) - 5):
        with pytest.raises(MXNetError, match="truncated"):
            decode_frame(whole[:cut])
    # and the named part tells you WHICH read starved
    with pytest.raises(WireError, match="header"):
        decode_frame(whole[:4])
    with pytest.raises(WireError, match="payload"):
        decode_frame(whole[:len(whole) - 1])


def test_bad_magic_rejected():
    bad = b"XX" + _wire_bytes("x", "m")[2:]
    with pytest.raises(WireError, match="magic"):
        decode_frame(bad)


def test_header_len_shorter_than_prefix_rejected():
    prefix = netwire._PREFIX
    head = prefix.pack(netwire._MAGIC, netwire.WIRE_VERSION, 0,
                       prefix.size - 4, 0, 0)
    with pytest.raises(WireError, match="header_len"):
        decode_frame(head)


def test_descriptor_body_mismatch_rejected():
    whole = bytearray(_wire_bytes("x", "m", [np.zeros(4, np.float64)]))
    # lie about the body length: descriptors now claim more than it holds
    prefix = netwire._PREFIX
    magic, ver, flags, hlen, mlen, blen = prefix.unpack(
        bytes(whole[:prefix.size]))
    whole[:prefix.size] = prefix.pack(magic, ver, flags, hlen, mlen,
                                      blen - 8)
    with pytest.raises(WireError, match="descriptors"):
        decode_frame(bytes(whole[:-8]))


# ---------------------------------------------------------------------------
# version skew: both directions, pinned
# ---------------------------------------------------------------------------

def test_skew_newer_sender_to_old_reader():
    """A future sender appends header bytes (header_len grows) and new
    metadata keys; THIS version's reader skips the tail via header_len
    and ignores the unknown keys — the PR 15 appended-field idiom on
    the wire."""
    arrays = [np.arange(6, dtype=np.float32).reshape(2, 3)]
    raw = _wire_bytes("infer", "m-9", arrays, {"known": 1},
                      _header_tail=b"\xde\xad\xbe\xef\x00\x01")
    # splice an unknown top-level metadata key in, like a new field
    prefix = netwire._PREFIX
    f = decode_frame(raw)
    assert f.meta == {"known": 1}
    assert np.array_equal(f.arrays[0], arrays[0])
    # longer tail than any plausible extension still decodes
    f2 = decode_frame(_wire_bytes("x", "m", arrays,
                                  _header_tail=b"\x00" * 512))
    assert np.array_equal(f2.arrays[0], arrays[0])
    assert prefix.unpack(raw[:prefix.size])[3] == prefix.size + 6


def test_skew_old_sender_to_new_reader():
    """An older sender omits fields newer readers know about (tctx,
    m): the reader fills safe defaults instead of crashing — JSON
    metadata makes absent keys indistinguishable from default."""
    import json
    prefix = netwire._PREFIX
    meta_bytes = json.dumps({"op": "infer", "mid": "m-old",
                             "arrays": []}).encode()
    raw = prefix.pack(netwire._MAGIC, netwire.WIRE_VERSION, 0,
                      prefix.size, len(meta_bytes), 0) + meta_bytes
    f = decode_frame(raw)
    assert f.op == "infer" and f.mid == "m-old"
    assert f.meta == {} and f.tctx is None and f.arrays == []


# ---------------------------------------------------------------------------
# live loopback: pooled client vs threaded server
# ---------------------------------------------------------------------------

def test_client_server_echo_and_stats(tel):
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=2)
    try:
        for i in range(10):
            x = np.full((4, 4), i, dtype=np.float32)
            f = client.call("infer", [x], {"i": i}, timeout_s=10.0)
            assert f.op == "ok"
            assert np.array_equal(f.arrays[0], x * 2)
            assert f.meta["echo"] == {"i": i}
        st = client.stats()
        assert st["peer"] == "echo" and st["pool"] == 2
        assert st["frames_tx"] == 10 and st["frames_rx"] == 10
        assert st["bytes_tx"] > 10 * 64 and st["bytes_rx"] > 10 * 64
        assert st["reconnects"] == 0 and st["pending"] == 0
        assert st["rtt_ms"]["count"] == 10
        assert st["rtt_ms"]["p99"] >= st["rtt_ms"]["p50"] >= 0.0
        assert tel.peek("wire.frames_tx") >= 10
    finally:
        client.close()
        srv.close()


def test_server_handler_exception_becomes_err_reply():
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=1)
    try:
        f = client.call("boom", timeout_s=10.0)
        assert f.op == "err"
        assert "exploded" in f.meta["error"]
        # the connection survives a handler error
        f2 = client.call("infer", [np.ones(2, np.float32)],
                         timeout_s=10.0)
        assert f2.op == "ok"
    finally:
        client.close()
        srv.close()


def test_concurrent_requests_multiplex_by_mid():
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=2)
    errs, lock = [], threading.Lock()

    def worker(i):
        try:
            x = np.full((8,), i, dtype=np.float64)
            f = client.call("infer", [x], {"i": i}, timeout_s=30.0)
            assert np.array_equal(f.arrays[0], x * 2), i
            assert f.meta["echo"]["i"] == i
        except Exception as e:   # noqa: BLE001 (collected+asserted)
            with lock:
                errs.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errs, errs[:3]
        assert client.pending_count() == 0
    finally:
        client.close()
        srv.close()


# ---------------------------------------------------------------------------
# the network fault plane, injected inside the framing layer
# ---------------------------------------------------------------------------

def test_net_partition_fails_fast_then_reconnects(tel, no_faults):
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=1)
    try:
        assert client.call("infer", timeout_s=10.0).op == "ok"
        faults.configure("net_partition")
        with pytest.raises(WirePeerLost):
            client.request("infer")
        faults.configure(None)
        # the pooled conn redials on the next request
        assert client.call("infer", timeout_s=10.0).op == "ok"
        assert client.stats()["reconnects"] >= 1
    finally:
        faults.configure(None)
        client.close()
        srv.close()


def test_net_drop_times_out_without_leaking_pending(no_faults):
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=1)
    try:
        faults.configure("net_drop")
        w = client.request("infer", [np.ones(4, np.float32)])
        with pytest.raises(WireTimeout):
            w.wait(0.3)
        w.cancel()   # the router's timeout path: forget the mid
        assert client.pending_count() == 0
        faults.configure(None)
        assert client.call("infer", timeout_s=10.0).op == "ok"
    finally:
        faults.configure(None)
        client.close()
        srv.close()


def test_net_reorder_swaps_frames_mids_still_match(no_faults):
    """With reorder armed the FIRST frame is held and rides behind the
    second — replies come back swapped, and mid multiplexing still
    resolves each waiter with its own answer."""
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=1)
    try:
        faults.configure("net_reorder", seed=1)
        a = np.full((4,), 1.0, dtype=np.float64)
        b = np.full((4,), 2.0, dtype=np.float64)
        wa = client.request("infer", [a], {"tag": "a"})
        wb = client.request("infer", [b], {"tag": "b"})
        fa, fb = wa.wait(10.0), wb.wait(10.0)
        assert np.array_equal(fa.arrays[0], a * 2)
        assert np.array_equal(fb.arrays[0], b * 2)
        assert fa.meta["echo"]["tag"] == "a"
        assert fb.meta["echo"]["tag"] == "b"
        plan = faults._PLAN
        assert plan.injected.get("net_reorder", 0) >= 1
    finally:
        faults.configure(None)
        client.close()
        srv.close()


def test_net_slow_injects_wire_latency(no_faults):
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=1)
    try:
        t0 = time.perf_counter()
        client.call("infer", timeout_s=10.0)
        base = time.perf_counter() - t0
        faults.configure("net_slow", slow_ms=60.0)
        t0 = time.perf_counter()
        client.call("infer", timeout_s=10.0)
        slowed = time.perf_counter() - t0
        assert slowed >= 0.05 and slowed > base
    finally:
        faults.configure(None)
        client.close()
        srv.close()


def test_server_close_is_idempotent_and_joins_threads():
    srv = _echo_server()
    client = WireClient(srv.host, srv.port, peer="echo", pool=1)
    client.call("infer", timeout_s=10.0)
    client.close()
    srv.close()
    srv.close()   # idempotent
    # pending requests against a closed server fail, not hang
    client2 = WireClient(srv.host, srv.port, peer="gone", pool=1)
    with pytest.raises(WireError):
        client2.call("infer", timeout_s=2.0)
    client2.close()


# ---------------------------------------------------------------------------
# netfeed: the disaggregated input plane
# ---------------------------------------------------------------------------

def _collect_epoch(it):
    out = []
    while True:
        try:
            out.append(it.next())
        except StopIteration:
            return out


def _assert_batches_bit_identical(ref, got):
    assert len(got) == len(ref)
    for r, g in zip(ref, got):
        for rd, gd in zip(r.data, g.data):
            rn, gn = rd.asnumpy(), gd.asnumpy()
            assert gn.dtype == rn.dtype
            assert rn.tobytes() == gn.tobytes()
        for rl, gl in zip(r.label, g.label):
            assert np.array_equal(rl.asnumpy(), gl.asnumpy())
        assert np.array_equal(r.index, g.index)
        assert r.pad == g.pad
        for k in ("tops", "lefts", "mirror"):
            assert np.array_equal(r.aug[k], g.aug[k]), k
        for k in ("mean", "scale", "layout", "crop"):
            assert r.aug[k] == g.aug[k], k
        assert isinstance(g.aug["crop"], tuple)


def test_netfeed_batches_cross_bit_identical_in_process():
    ref = _collect_epoch(netfeed.demo_feed_factory())
    srv = netfeed.NetFeedServer(netfeed.demo_feed_factory())
    it = netfeed.NetFeedIter(srv.host, srv.port)
    try:
        assert it.batch_size == 8
        d = it.provide_data[0]
        assert d.name == "data" and np.dtype(d.dtype) == np.uint8
        assert d.layout == "NHWC"
        _assert_batches_bit_identical(ref, _collect_epoch(it))
        # reset restarts the epoch deterministically
        it.reset()
        _assert_batches_bit_identical(ref, _collect_epoch(it))
    finally:
        it.close()
        srv.close()


def test_netfeed_seq_reassembly_survives_net_reorder(no_faults):
    """Depth-pipelined batch replies arrive out of order under an
    armed net_reorder; the client reassembles by sequence number, so
    the epoch order is exactly the in-process order."""
    ref = _collect_epoch(netfeed.demo_feed_factory())
    srv = netfeed.NetFeedServer(netfeed.demo_feed_factory())
    it = netfeed.NetFeedIter(srv.host, srv.port, depth=3)
    try:
        faults.configure("net_reorder:0.5", seed=5)
        got = _collect_epoch(it)
        faults.configure(None)
        _assert_batches_bit_identical(ref, got)
    finally:
        faults.configure(None)
        it.close()
        srv.close()


@pytest.mark.slow
def test_netfeed_two_process_epoch_bit_identical(tel):
    """The acceptance run: a real spawned decode host streams an epoch
    over loopback; batches match the in-process iterator byte for
    byte, and wrapped in FeedScheduler the feed-stall p99 stays near
    zero (the chip never starves)."""
    from mxnet_tpu.io_pipeline import FeedScheduler

    ref = _collect_epoch(netfeed.demo_feed_factory())
    proc, host, port = netfeed.serve_subprocess(
        "mxnet_tpu.netfeed:demo_feed_factory")
    it = netfeed.NetFeedIter(host, port)
    try:
        sched = FeedScheduler(it, depth=2)
        got = [sched.next()]    # warmup: first device_put compiles
        telemetry.reset()       # measure steady-state stalls only
        telemetry.enable()
        for batch in sched:
            got.append(batch)
            time.sleep(0.005)   # a "training step": read-ahead covers it
        _assert_batches_bit_identical(ref, got)
        sched.close()
        snap = telemetry.snapshot()
        stall = snap["io"]["feed_stall_ms"]
        assert stall["count"] >= len(got) - 2
        # the wire feed kept the queue full: stalls are queue-pop noise
        assert stall["p99"] < 250.0
    finally:
        it.close(stop_server=True)
        proc.join(10)
        if proc.is_alive():
            proc.kill()
            proc.join(5)
        assert not proc.is_alive()


def test_netfeed_timeout_names_the_decode_host(no_faults):
    """A wedged decode host fails the epoch with a named WireTimeout
    instead of hanging the training loop."""
    hang = threading.Event()

    class _WedgedIter(netfeed._DemoFeed):
        def next(self):
            hang.wait(30.0)
            raise StopIteration

    srv = netfeed.NetFeedServer(_WedgedIter())
    it = netfeed.NetFeedIter(srv.host, srv.port, timeout_s=0.5)
    try:
        with pytest.raises(WireTimeout, match="decode host"):
            it.next()
    finally:
        hang.set()
        it.close()
        srv.close()
