"""graftrace concurrency analysis + lock/deadlock sanitizers:
good/bad fixture pairs per rule family, suppression, registration into
the graftlint driver, the whole-tree tier-1 gate for the concurrency
families, and seeded runtime violations (an ABBA lock inversion caught
by the `locks` sanitizer; a stalled progress signal tripping the
deadlock watchdog into a FlightRecorder dump with all-thread stacks)."""
import json
import os
import threading
import time

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.analysis import graftlint, graftrace, sanitizers
from mxnet_tpu.analysis.sanitizers import (DeadlockWatchdog,
                                           InstrumentedLock,
                                           LockOrderRegistry,
                                           SanitizerError)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONC_RULES = frozenset(graftrace.RULES)


def _rules(findings):
    return sorted({f.rule for f in findings})


def _lint(src, path="pkg/worker.py", rules=CONC_RULES):
    cfg = graftlint.Config(declared_env={"MXNET_TPU_DECLARED"},
                           rules=rules)
    return graftlint.analyze_source(src, path, cfg)


# ---------------------------------------------------------------------------
# registration into the graftlint driver
# ---------------------------------------------------------------------------

def test_concurrency_rules_registered_as_default():
    assert set(graftrace.RULES) <= set(graftlint.RULES)
    assert set(graftrace.RULES) <= graftlint.Config().rules
    for rule, tag in graftrace.SUPPRESS_TAGS.items():
        assert graftlint.SUPPRESS_TAGS[rule] == tag


# ---------------------------------------------------------------------------
# lock-order rule
# ---------------------------------------------------------------------------

BAD_ABBA = """
import threading

class W:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()

    def f(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def g(self):
        with self.b_lock:
            with self.a_lock:
                pass
"""


def test_lock_order_flags_abba_cycle():
    bad = _lint(BAD_ABBA)
    assert _rules(bad) == ["lock-order"]
    # both directions of the cycle are reported
    assert len(bad) == 2
    assert "deadlock" in bad[0].message


def test_lock_order_consistent_nesting_is_clean():
    src = BAD_ABBA.replace(
        "with self.b_lock:\n            with self.a_lock:",
        "with self.a_lock:\n            with self.b_lock:")
    assert _lint(src) == []


def test_lock_order_cycle_through_method_call():
    # g holds B and calls h, which takes A; f takes A then B -> cycle
    src = """
import threading

class W:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()

    def f(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def h(self):
        with self.a_lock:
            pass

    def g(self):
        with self.b_lock:
            self.h()
"""
    assert "lock-order" in _rules(_lint(src))


def test_lock_order_suppression():
    src = BAD_ABBA.replace(
        "with self.b_lock:\n            with self.a_lock:",
        "with self.b_lock:  # graft: lock-order-ok\n"
        "            with self.a_lock:  # graft: lock-order-ok")
    # suppressing one direction still leaves the other edge's findings
    remaining = _lint(src)
    assert all(f.line < 14 for f in remaining)


# ---------------------------------------------------------------------------
# blocking-under-lock rule
# ---------------------------------------------------------------------------

def test_blocking_under_lock_flags_queue_get():
    src = """
class W:
    def take(self):
        with self._lock:
            return self._queue.get()
"""
    bad = _lint(src)
    assert _rules(bad) == ["blocking-under-lock"]
    assert "no timeout" in bad[0].message


def test_blocking_under_lock_timeout_or_unlocked_is_clean():
    src = """
class W:
    def take(self):
        with self._lock:
            return self._queue.get(timeout=0.5)

    def take2(self):
        return self._queue.get()
"""
    assert _lint(src) == []


def test_blocking_under_lock_flags_join_sleep_socket_jax():
    for call in ("t.join()", "time.sleep(1)", "sock.recv(1024)",
                 "x.block_until_ready()", "jnp.dot(a, b)"):
        src = ("class W:\n"
               "    def f(self):\n"
               "        with self._lock:\n"
               "            %s\n" % call)
        assert _rules(_lint(src)) == ["blocking-under-lock"], call


def test_blocking_under_lock_interprocedural():
    src = """
def slow():
    return sock.recv(4)

class W:
    def f(self):
        with self._lock:
            return slow()
"""
    bad = _lint(src)
    assert _rules(bad) == ["blocking-under-lock"]
    assert "slow" in bad[0].message


def test_cv_wait_needs_predicate_loop_or_timeout():
    bad = """
class W:
    def f(self):
        with self._cv:
            self._cv.wait()
"""
    assert _rules(_lint(bad)) == ["blocking-under-lock"]
    good_loop = """
class W:
    def f(self):
        with self._cv:
            while not self._ready:
                self._cv.wait()
"""
    assert _lint(good_loop) == []
    good_timeout = bad.replace("wait()", "wait(timeout=1.0)")
    assert _lint(good_timeout) == []


def test_blocking_under_lock_suppression():
    src = """
class W:
    def f(self):
        with self._lock:
            t.join()  # graft: blocking-ok
"""
    assert _lint(src) == []


# ---------------------------------------------------------------------------
# thread-lifecycle rule
# ---------------------------------------------------------------------------

def test_lifecycle_flags_nondaemon_thread_without_join():
    src = """
import threading

class W:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()
"""
    bad = _lint(src)
    assert _rules(bad) == ["thread-lifecycle"]
    assert "non-daemon" in bad[0].message


def test_lifecycle_daemon_or_joined_thread_is_clean():
    daemon = """
import threading

class W:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
"""
    assert _lint(daemon) == []
    joined = """
import threading

class W:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def stop(self):
        self._t.join(timeout=5.0)
"""
    assert _lint(joined) == []


def test_lifecycle_flags_unbounded_join_on_shutdown_path():
    src = """
class W:
    def close(self):
        self._t.join()
"""
    bad = _lint(src)
    assert _rules(bad) == ["thread-lifecycle"]
    assert "shutdown path" in bad[0].message
    assert _lint(src.replace("join()", "join(timeout=5.0)")) == []


def test_lifecycle_flags_start_in_init_without_teardown():
    src = """
import threading

class W:
    def __init__(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
"""
    bad = _lint(src)
    assert _rules(bad) == ["thread-lifecycle"]
    assert "no reachable" in bad[0].message
    with_close = src + """
    def close(self):
        self._t.join(timeout=1.0)
"""
    assert _lint(with_close) == []


def test_lifecycle_flags_stop_event_set_after_join():
    src = """
import threading

class W:
    def __init__(self):
        self._stop_event = threading.Event()

    def close(self):
        self._t.join(timeout=1.0)
        self._stop_event.set()
"""
    bad = _lint(src)
    assert any("after the join" in f.message for f in bad)
    ordered = """
import threading

class W:
    def __init__(self):
        self._stop_event = threading.Event()

    def close(self):
        self._stop_event.set()
        self._t.join(timeout=1.0)
"""
    assert _lint(ordered) == []


# ---------------------------------------------------------------------------
# fork-safety rule
# ---------------------------------------------------------------------------

def test_fork_safety_flags_bound_method_target_and_self_args():
    src = """
import multiprocessing

class W:
    def spawn(self):
        p = multiprocessing.Process(target=self._run)
        p.start()
        p.join(timeout=5.0)
"""
    bad = _lint(src)
    assert _rules(bad) == ["fork-safety"]
    assert "bound method" in bad[0].message
    src2 = """
import multiprocessing

def main(w):
    p = multiprocessing.Process(target=work, args=(w.engine_lock,))
    p.start()
    p.join(timeout=5.0)
"""
    assert _rules(_lint(src2)) == ["fork-safety"]


def test_fork_safety_module_level_target_is_clean():
    src = """
import multiprocessing

def work(q):
    pass

class W:
    def spawn(self):
        p = multiprocessing.Process(target=work, args=(self.depth,))
        p.start()
        p.join(timeout=5.0)
"""
    assert _lint(src) == []


def test_fork_safety_flags_fork_start_method():
    src = "import multiprocessing\n" \
          "ctx = multiprocessing.get_context('fork')\n"
    bad = _lint(src)
    assert _rules(bad) == ["fork-safety"]
    assert _lint(src.replace("'fork'", "'spawn'")) == []


# ---------------------------------------------------------------------------
# whole-tree gate (tier-1): concurrency families, empty baseline
# ---------------------------------------------------------------------------

def test_repo_tree_clean_under_concurrency_rules():
    cfg = graftlint.Config(rules=CONC_RULES)
    findings = graftlint.analyze_paths(
        [os.path.join(ROOT, "mxnet_tpu"), os.path.join(ROOT, "tools"),
         os.path.join(ROOT, "bench.py")], cfg, root=ROOT)
    assert findings == [], \
        "new concurrency findings (fix or annotate):\n%s" % "\n".join(
            repr(f) for f in findings)


# ---------------------------------------------------------------------------
# runtime: lock-order sanitizer
# ---------------------------------------------------------------------------

def test_instrumented_lock_raises_on_abba_inversion():
    """Seeded inversion: thread 1 exhibits A->B; the main thread then
    attempts B->A and gets a SanitizerError instead of a deadlock."""
    reg = LockOrderRegistry()
    a = InstrumentedLock(threading.Lock(), "A", registry=reg)
    b = InstrumentedLock(threading.Lock(), "B", registry=reg)

    def ab():
        with a:
            with b:
                pass

    t = threading.Thread(target=ab)
    t.start()
    t.join(timeout=10)
    telemetry.reset()
    telemetry.enable()
    try:
        with b:
            with pytest.raises(SanitizerError, match="lock-order"):
                with a:
                    pass
        assert telemetry.peek("sanitizer.trips.locks") == 1
    finally:
        telemetry.disable()
        telemetry.reset()


def test_instrumented_lock_consistent_order_and_reentry_ok():
    reg = LockOrderRegistry()
    a = InstrumentedLock(threading.RLock(), "A", registry=reg)
    b = InstrumentedLock(threading.Lock(), "B", registry=reg)
    for _ in range(2):
        with a:
            with a:      # re-entrant acquire records no self-edge
                with b:
                    pass
    # same order again from another thread: still fine
    t = threading.Thread(target=lambda: a.acquire() and None)
    with a:
        with b:
            pass


def test_instrumented_condition_keeps_cv_semantics():
    reg = LockOrderRegistry()
    cv = InstrumentedLock(threading.Condition(), "CV", registry=reg)
    hits = []

    def consumer():
        with cv:
            while not hits:
                cv.wait(timeout=5.0)
            hits.append("consumed")

    t = threading.Thread(target=consumer)
    t.start()
    time.sleep(0.05)
    with cv:
        hits.append("produced")
        cv.notify_all()
    t.join(timeout=10)
    assert not t.is_alive()
    assert hits == ["produced", "consumed"]


def test_lock_wait_telemetry_histogram():
    telemetry.reset()
    telemetry.enable()
    try:
        reg = LockOrderRegistry()
        lk = InstrumentedLock(threading.Lock(), "tst", registry=reg)
        with lk:
            pass
        assert telemetry.histogram("lock.wait_ms").count == 1
        assert telemetry.histogram("lock.wait_ms.tst").count == 1
    finally:
        telemetry.disable()
        telemetry.reset()


def test_maybe_instrument_gated_by_env(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "")
    raw = threading.Lock()
    assert sanitizers.maybe_instrument(raw, "x") is raw
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "locks")
    wrapped = sanitizers.maybe_instrument(raw, "x")
    assert isinstance(wrapped, InstrumentedLock)


def test_engine_locks_instrumented_when_armed(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "locks")
    from mxnet_tpu.engine import ThreadedEngine

    eng = ThreadedEngine(num_workers=2)
    try:
        assert isinstance(eng._heap_lock, InstrumentedLock)
        done = []
        eng.push(lambda: done.append(1))
        eng.wait_for_all()
        assert done == [1]
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# runtime: deadlock watchdog
# ---------------------------------------------------------------------------

def test_watchdog_dumps_stacks_on_stall(tmp_path, monkeypatch):
    """Seeded stall: a progress fn that never advances trips the
    watchdog, which counts the trip and writes a FlightRecorder dump
    whose stacks.txt contains every live thread's stack."""
    from mxnet_tpu import tracing

    monkeypatch.setenv("MXNET_TPU_CRASH_DIR", str(tmp_path))
    telemetry.reset()
    telemetry.enable()
    parked = threading.Event()
    release = threading.Event()

    def parked_thread():
        parked.set()
        release.wait(timeout=30)

    t = threading.Thread(target=parked_thread,
                         name="test-parked-worker", daemon=True)
    t.start()
    parked.wait(timeout=10)
    wd = DeadlockWatchdog(progress_fn=lambda: 0,
                          threshold_s=0.2, interval_s=0.05)
    wd.start()
    try:
        deadline = time.time() + 20
        while wd.trips == 0 and time.time() < deadline:
            time.sleep(0.05)
    finally:
        release.set()
        wd.stop()
        t.join(timeout=10)
    assert wd.trips == 1
    assert telemetry.peek("sanitizer.trips.deadlock") == 1
    assert wd.last_dump is not None
    stacks = open(os.path.join(wd.last_dump, "stacks.txt")).read()
    assert "test-parked-worker" in stacks
    assert "release.wait" in stacks
    with open(os.path.join(wd.last_dump, "meta.json")) as f:
        assert "deadlock-watchdog" in json.load(f)["reason"]
    telemetry.disable()
    telemetry.reset()


def test_watchdog_quiet_while_progressing():
    ticks = []

    def progress():
        ticks.append(1)
        return len(ticks)     # always advancing

    wd = DeadlockWatchdog(progress_fn=progress,
                          threshold_s=0.2, interval_s=0.02)
    wd.start()
    time.sleep(0.6)
    wd.stop()
    assert wd.trips == 0


def test_tracing_starts_and_stops_watchdog(monkeypatch):
    from mxnet_tpu import tracing

    monkeypatch.setenv("MXNET_TPU_SANITIZE", "deadlock")
    monkeypatch.setenv("MXNET_TPU_WATCHDOG_S", "3600")
    telemetry.enable()
    try:
        tracing.maybe_init()
        assert tracing._watchdog is not None
        names = {t.name for t in threading.enumerate()}
        assert "mxtpu-watchdog" in names
    finally:
        tracing.shutdown()
        telemetry.disable()
        telemetry.reset()
    assert tracing._watchdog is None
    assert "mxtpu-watchdog" not in {t.name for t in threading.enumerate()}


# ---------------------------------------------------------------------------
# satellites: trace_report lock view, MetricsServer.stop
# ---------------------------------------------------------------------------

def test_trace_report_lock_contention_view(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    snap = {
        "lock": {"wait_ms": {
            "_value": {"count": 7, "sum": 3.5, "mean": 0.5, "min": 0.1,
                       "max": 1.2, "p50": 0.4, "p90": 1.0, "p99": 1.2},
            "engine-heap": {"count": 5, "sum": 2.5, "mean": 0.5,
                            "min": 0.1, "max": 1.2, "p50": 0.4,
                            "p90": 1.0, "p99": 1.2},
        }},
        "sanitizer": {"trips": {"_value": 2, "locks": 1, "deadlock": 1}},
    }
    out = trace_report.render_locks(snap)
    assert "lock contention" in out
    assert "engine-heap" in out
    assert "(all)" in out
    assert "sanitizer trips: 2" in out
    assert "deadlock=1" in out
    # and the crash-dump report path picks it up end to end
    d = tmp_path / "flight-test-pid1-1"
    d.mkdir()
    (d / "telemetry.json").write_text(json.dumps(snap))
    report = trace_report.report_crash_dump(str(d))
    assert "lock contention" in report
    # a snapshot with no lock/sanitizer data renders nothing
    assert trace_report.render_locks({}) == ""


def test_metrics_server_stop_joins_thread():
    from mxnet_tpu import tracing

    srv = tracing.MetricsServer(0)
    assert any(t.name == "mxtpu-metrics" for t in threading.enumerate())
    srv.stop()
    assert not any(t.name == "mxtpu-metrics"
                   for t in threading.enumerate())
    srv.stop()     # idempotent; close is an alias
    srv.close()
