"""IO tests (reference tests/python/unittest/test_io.py + recordio tests)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu import recordio as rio


def test_ndarray_iter():
    data = np.arange(100).reshape(25, 4).astype(np.float32)
    label = np.arange(25).astype(np.float32)
    it = mio.NDArrayIter(data, label, batch_size=10, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (10, 4)
    assert batches[2].pad == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:10])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), label[:10])
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_discard():
    data = np.zeros((25, 4), dtype=np.float32)
    it = mio.NDArrayIter(data, np.zeros(25), batch_size=10,
                         last_batch_handle="discard")
    assert len(list(it)) == 2


def test_ndarray_iter_dict_input():
    it = mio.NDArrayIter({"a": np.zeros((10, 2)), "b": np.zeros((10, 3))},
                         np.zeros(10), batch_size=5)
    assert sorted(d.name for d in it.provide_data) == ["a", "b"]


def test_resize_iter():
    data = np.zeros((20, 2), dtype=np.float32)
    it = mio.NDArrayIter(data, np.zeros(20), batch_size=5)
    rit = mio.ResizeIter(it, size=7)
    assert len(list(rit)) == 7
    rit.reset()
    assert len(list(rit)) == 7


def test_prefetching_iter():
    data = np.random.rand(40, 3).astype(np.float32)
    label = np.arange(40).astype(np.float32)
    base = mio.NDArrayIter(data, label, batch_size=10)
    pre = mio.PrefetchingIter(base)
    batches = list(pre)
    assert len(batches) == 4
    got = np.concatenate([b.label[0].asnumpy() for b in batches])
    np.testing.assert_allclose(np.sort(got), label)
    pre.reset()
    assert len(list(pre)) == 4


def test_csv_iter(tmp_path):
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    data = np.random.rand(12, 3)
    label = np.arange(12)
    np.savetxt(data_path, data, delimiter=",")
    np.savetxt(label_path, label, delimiter=",")
    it = mio.CSVIter(data_csv=data_path, data_shape=(3,),
                     label_csv=label_path, batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4],
                               rtol=1e-5)


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = rio.MXRecordIO(path, "w")
    payloads = [b"hello", b"x" * 1000, b"", b"abc123"]
    for p in payloads:
        writer.write(p)
    writer.close()
    reader = rio.MXRecordIO(path, "r")
    got = []
    while True:
        rec = reader.read()
        if rec is None:
            break
        got.append(rec)
    reader.close()
    assert got == payloads


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = rio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(10):
        writer.write_idx(i, b"record%d" % i)
    writer.close()
    reader = rio.MXIndexedRecordIO(idx_path, path, "r")
    assert reader.read_idx(7) == b"record7"
    assert reader.read_idx(2) == b"record2"
    reader.close()


def test_pack_unpack():
    header = rio.IRHeader(0, 3.0, 42, 0)
    packed = rio.pack(header, b"payload")
    h, payload = rio.unpack(packed)
    assert h.label == 3.0
    assert h.id == 42
    assert payload == b"payload"
    # multi-label
    header = rio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    packed = rio.pack(header, b"xyz")
    h, payload = rio.unpack(packed)
    np.testing.assert_allclose(h.label, [1.0, 2.0, 3.0])
    assert payload == b"xyz"


def test_image_record_iter(tmp_path):
    pytest.importorskip("PIL")
    path = str(tmp_path / "img.rec")
    writer = rio.MXRecordIO(path, "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = (rng.rand(10, 12, 3) * 255).astype(np.uint8)
        writer.write(rio.pack_img(rio.IRHeader(0, float(i % 3), i, 0), img))
    writer.close()
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                             batch_size=4, rand_crop=True, rand_mirror=True)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 8, 8)
    assert batches[0].label[0].shape == (4,)
    # sharding
    it2 = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                              batch_size=2, num_parts=2, part_index=0)
    assert it2.num_data == 4


def test_mnist_iter_synthetic(tmp_path):
    """MNISTIter against synthetic idx files (no dataset download)."""
    import struct

    img_path = str(tmp_path / "images-idx3-ubyte")
    lbl_path = str(tmp_path / "labels-idx1-ubyte")
    n = 32
    rng = np.random.RandomState(0)
    images = (rng.rand(n, 28, 28) * 255).astype(np.uint8)
    labels = (rng.randint(0, 10, n)).astype(np.uint8)
    with open(img_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">III", n, 28, 28))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 1))
        f.write(struct.pack(">I", n))
        f.write(labels.tobytes())
    it = mio.MNISTIter(image=img_path, label=lbl_path, batch_size=8,
                       shuffle=False)
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (8, 1, 28, 28)
    flat_it = mio.MNISTIter(image=img_path, label=lbl_path, batch_size=8,
                            flat=True, shuffle=False)
    assert next(iter(flat_it)).data[0].shape == (8, 784)


def test_imagerecord_mean_img_caching(tmp_path):
    """mean_img file missing -> computed over the dataset and cached;
    second iterator loads it (reference iter_normalize.h behavior)."""
    from mxnet_tpu import recordio as rio

    rec_path = str(tmp_path / "imgs.rec")
    rng = np.random.RandomState(0)
    writer = rio.MXRecordIO(rec_path, "w")
    imgs = []
    for i in range(6):
        img = rng.randint(0, 255, (8, 8, 3), dtype=np.uint8)
        imgs.append(img.astype(np.float64))
        header = rio.IRHeader(0, float(i % 2), i, 0)
        writer.write(rio.pack_img(header, img, quality=100, img_fmt=".png"))
    writer.close()

    mean_path = str(tmp_path / "mean.nd")
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 8, 8),
                               batch_size=3, mean_img=mean_path, scale=2.0)
    assert os.path.exists(mean_path)
    saved = list(mx.nd.load(mean_path).values())[0].asnumpy()
    expected = np.mean([im.transpose(2, 0, 1) for im in imgs], axis=0)
    np.testing.assert_allclose(saved, expected, rtol=1e-5)

    # batch = (img - mean) * scale
    batch = next(iter(it)).data[0].asnumpy()
    raw0 = imgs[0].transpose(2, 0, 1)
    np.testing.assert_allclose(batch[0], (raw0 - expected) * 2.0, rtol=1e-4)

    # second iterator reuses the cached file (no recompute): corrupt-proof
    # by checking identical mean after modifying nothing
    it2 = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 8, 8),
                                batch_size=3, mean_img=mean_path)
    np.testing.assert_allclose(it2.mean, expected, rtol=1e-5)


def test_prefetching_iter_close_joins_thread():
    """close() stops and joins the background thread — no leak even if
    the consumer abandons the epoch midway."""
    import threading

    data = np.random.rand(40, 3).astype(np.float32)
    base = mio.NDArrayIter(data, np.arange(40, dtype=np.float32),
                           batch_size=10)
    pre = mio.PrefetchingIter(base)
    next(iter(pre))  # abandon mid-epoch with batches still queued
    worker = pre._thread
    assert worker is not None and worker.is_alive()
    pre.close()
    assert pre._thread is None and not worker.is_alive()
    assert not any(t is worker for t in threading.enumerate())
    # closed iterator reports exhaustion rather than hanging
    assert pre.iter_next() is False


def test_prefetching_iter_context_manager():
    data = np.zeros((20, 2), dtype=np.float32)
    with mio.PrefetchingIter(
            mio.NDArrayIter(data, np.zeros(20), batch_size=5)) as pre:
        assert len(list(pre)) == 4
        worker = pre._thread
    assert pre._thread is None
    assert worker is None or not worker.is_alive()


def test_prefetching_iter_reset_after_partial_epoch():
    """reset() mid-epoch drains safely and the next epoch is complete."""
    data = np.random.rand(40, 3).astype(np.float32)
    label = np.arange(40, dtype=np.float32)
    with mio.PrefetchingIter(
            mio.NDArrayIter(data, label, batch_size=10)) as pre:
        next(iter(pre))
        pre.reset()
        batches = list(pre)
        assert len(batches) == 4
        got = np.concatenate([b.label[0].asnumpy() for b in batches])
        np.testing.assert_allclose(np.sort(got), label)
