"""Fused train step (MXNET_TPU_FUSED_STEP=1): gating, numerical parity
with the classic loop, donation safety, dispatch/recompile telemetry,
engine sync semantics, and lazy metric accumulation."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine as eng_mod
from mxnet_tpu import symbol as sym
from mxnet_tpu import telemetry
from mxnet_tpu.fused_step import make_fused_step
from mxnet_tpu.module import Module

BATCH = 8
DIM = 6
CLASSES = 3


def _mlp_sym():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _synthetic(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, DIM).astype(np.float32)
    w = rng.randn(DIM, CLASSES)
    y = X.dot(w).argmax(axis=1).astype(np.float32)
    return X, y


def _seed_params(net, seed=3):
    """Deterministic initial params so two fits start bit-identical."""
    arg_shapes, _, _ = net.infer_shape(data=(BATCH, DIM),
                                       softmax_label=(BATCH,))
    rng = np.random.RandomState(seed)
    return {name: mx.nd.array((rng.randn(*shape) * 0.1).astype(np.float32))
            for name, shape in zip(net.list_arguments(), arg_shapes)
            if name not in ("data", "softmax_label")}


def _fit(nbatches, num_epoch=1, fused=False, monkeypatch=None,
         optimizer_params=None):
    if fused:
        monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    else:
        monkeypatch.delenv("MXNET_TPU_FUSED_STEP", raising=False)
    net = _mlp_sym()
    X, y = _synthetic(BATCH * nbatches)
    data = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = Module(net, context=mx.cpu())
    mod.fit(data, num_epoch=num_epoch, optimizer="sgd",
            arg_params=_seed_params(net), initializer=None,
            optimizer_params=optimizer_params
            or {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4})
    assert mod._fused_step_active == fused
    return mod


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


def test_fused_step_off_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_FUSED_STEP", raising=False)
    net = _mlp_sym()
    X, y = _synthetic(BATCH * 2)
    data = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = Module(net, context=mx.cpu())
    mod.bind(data.provide_data, data.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    metric = mx.metric.create("acc")
    assert make_fused_step(mod, metric) is None
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    assert make_fused_step(mod, metric) is not None


def test_fused_gate_rejects_custom_update_optimizer(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    net = _mlp_sym()
    X, y = _synthetic(BATCH * 2)
    data = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = Module(net, context=mx.cpu())
    mod.bind(data.provide_data, data.provide_label)
    mod.init_params()
    # "test" overrides update() with eager python math — no traced plan
    mod.init_optimizer(optimizer="test")
    assert make_fused_step(mod, mx.metric.create("acc")) is None


def test_fused_unfused_parity(monkeypatch):
    """Parameter trajectories must be bit-identical after >= 10 batches
    of momentum SGD (same init, same data, same lr schedule)."""
    mod_a = _fit(nbatches=5, num_epoch=2, fused=False,
                 monkeypatch=monkeypatch)
    mod_b = _fit(nbatches=5, num_epoch=2, fused=True,
                 monkeypatch=monkeypatch)
    args_a, _ = mod_a.get_params()
    args_b, _ = mod_b.get_params()
    assert set(args_a) == set(args_b)
    for name in args_a:
        a, b = args_a[name].asnumpy(), args_b[name].asnumpy()
        assert np.array_equal(a, b), \
            "param %s diverged: max |d|=%g" % (name, np.abs(a - b).max())


def test_fused_parity_with_clip_and_scheduler(monkeypatch):
    """Clipping and a per-step lr schedule must not recompile or change
    numerics vs the classic loop."""
    from mxnet_tpu.lr_scheduler import FactorScheduler

    def params():
        return {"learning_rate": 0.05, "momentum": 0.9,
                "clip_gradient": 0.5,
                "lr_scheduler": FactorScheduler(step=3, factor=0.5)}

    mod_a = _fit(nbatches=10, fused=False, monkeypatch=monkeypatch,
                 optimizer_params=params())
    mod_b = _fit(nbatches=10, fused=True, monkeypatch=monkeypatch,
                 optimizer_params=params())
    args_a, _ = mod_a.get_params()
    args_b, _ = mod_b.get_params()
    for name in args_a:
        assert np.array_equal(args_a[name].asnumpy(),
                              args_b[name].asnumpy()), name


def test_fused_one_dispatch_per_batch(tel, monkeypatch):
    """The acceptance criterion: with MXNET_TPU_FUSED_STEP=1 one batch
    issues exactly ONE XLA computation for fwd+bwd+update(+metric)."""
    nbatches = 4
    before = telemetry.peek("step.dispatches") or 0
    _fit(nbatches=nbatches, fused=True, monkeypatch=monkeypatch)
    fused_delta = (telemetry.peek("step.dispatches") or 0) - before
    assert fused_delta == nbatches

    before = telemetry.peek("step.dispatches") or 0
    _fit(nbatches=nbatches, fused=False, monkeypatch=monkeypatch)
    unfused_delta = (telemetry.peek("step.dispatches") or 0) - before
    # classic loop: fwd+bwd, one optimizer group kernel, one metric fold
    assert unfused_delta >= 3 * nbatches


def test_fused_no_retrace_on_same_shapes(tel, monkeypatch):
    """Second and later same-shape batches must reuse the compiled step:
    exactly one fresh trace signature for the whole epoch."""
    before = telemetry.peek("step.fused_recompiles") or 0
    _fit(nbatches=4, fused=True, monkeypatch=monkeypatch)
    assert (telemetry.peek("step.fused_recompiles") or 0) - before == 1


def test_fused_step_donation_safety(monkeypatch):
    """The batch's data/label buffers ride in the NON-donated arg pack:
    they must stay readable (and unchanged) after donating steps."""
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    net = _mlp_sym()
    X, y = _synthetic(BATCH)
    data = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = Module(net, context=mx.cpu())
    mod.bind(data.provide_data, data.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    metric = mx.metric.create("acc")
    fused = mod._fused_train_step(metric)
    assert fused is not None
    batch = next(iter(data))
    before = batch.data[0].asnumpy().copy()
    fused.step(batch, metric)
    fused.step(batch, metric)  # same buffers through a second donation
    np.testing.assert_array_equal(batch.data[0].asnumpy(), before)
    batch.label[0].asnumpy()  # label buffer alive too


def test_naive_engine_skips_block_for_fused_step(monkeypatch):
    class _Ret:
        calls = 0

        def block_until_ready(self):
            self.calls += 1

    monkeypatch.delenv("MXNET_TPU_ENGINE_SYNC", raising=False)
    e = eng_mod.NaiveEngine()
    r = _Ret()
    e.push(lambda: r, prop="fused_step")
    assert r.calls == 0  # donated outputs: no serializing block
    e.push(lambda: r)
    assert r.calls == 1  # default prop still blocks
    monkeypatch.setenv("MXNET_TPU_ENGINE_SYNC", "1")
    e.push(lambda: r, prop="fused_step")
    assert r.calls == 2  # debug switch restores blocking


def test_metric_lazy_device_accumulation():
    """Accuracy.update over NDArrays must not sync to host; get() is the
    only fetch point and matches the numpy computation."""
    rng = np.random.RandomState(11)
    lab_np = rng.randint(0, CLASSES, (BATCH,)).astype(np.float32)
    pred_np = rng.rand(BATCH, CLASSES).astype(np.float32)
    m = mx.metric.create("acc")
    m.update([mx.nd.array(lab_np)], [mx.nd.array(pred_np)])
    assert m.sum_metric == 0.0 and m.num_inst == 0  # host untouched
    assert m._device_acc is not None
    m.update([mx.nd.array(lab_np)], [mx.nd.array(pred_np)])
    _, val = m.get()
    expected = float((pred_np.argmax(axis=1) == lab_np).mean())
    assert val == pytest.approx(expected)
    m.reset()
    assert m._device_acc is None
    assert np.isnan(m.get()[1])


def test_metric_device_folds_match_numpy():
    """Every has_device_fold metric's fold must agree with its own
    eager numpy update path."""
    rng = np.random.RandomState(5)
    cls_lab = rng.randint(0, CLASSES, (BATCH,)).astype(np.float32)
    cls_pred = rng.rand(BATCH, CLASSES).astype(np.float32)
    cls_pred /= cls_pred.sum(axis=1, keepdims=True)
    reg_lab = rng.randn(BATCH).astype(np.float32)
    reg_pred = rng.randn(BATCH, 1).astype(np.float32)
    cases = [(mx.metric.Accuracy(), cls_lab, cls_pred),
             (mx.metric.CrossEntropy(), cls_lab, cls_pred),
             (mx.metric.TopKAccuracy(top_k=2), cls_lab, cls_pred),
             (mx.metric.MSE(), reg_lab, reg_pred),
             (mx.metric.MAE(), reg_lab, reg_pred),
             (mx.metric.RMSE(), reg_lab, reg_pred)]
    for lazy, lab_np, pred_np in cases:
        eager = type(lazy)(top_k=lazy.top_k) \
            if isinstance(lazy, mx.metric.TopKAccuracy) else type(lazy)()
        # instance attr shadows the class flag -> eager numpy path
        eager.has_device_fold = False
        lazy.update([mx.nd.array(lab_np)], [mx.nd.array(pred_np)])
        eager.update([mx.nd.array(lab_np)], [mx.nd.array(pred_np)])
        assert lazy._device_acc is not None
        assert eager._device_acc is None
        assert lazy.get()[1] == pytest.approx(eager.get()[1], rel=1e-5), \
            type(lazy).__name__


def test_fused_metric_matches_host_metric(monkeypatch):
    """The in-step metric fold must produce the same epoch accuracy as
    the classic host-side update."""
    mod_a = _fit(nbatches=6, fused=False, monkeypatch=monkeypatch)
    mod_b = _fit(nbatches=6, fused=True, monkeypatch=monkeypatch)
    X, y = _synthetic(BATCH * 6)
    data = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    sa = mod_a.score(data, "acc")[0][1]
    sb = mod_b.score(data, "acc")[0][1]
    assert sa == pytest.approx(sb)


def test_trace_report_shows_dispatch_columns():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from trace_report import render

    out = render([{"step": 1, "latency_ms": 10.0, "dominant": "compute",
                   "deltas": {"dispatches": 1, "fused_recompiles": 1}}])
    header = out.splitlines()[2]
    assert "dispatch" in header and "fused_rc" in header
