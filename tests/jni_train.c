/* Drives the Scala JNI glue (mxnet_tpu_jni.c) through the exact call
 * sequence the typed Scala API performs, using the real-implementation
 * JNI shim (tests/jni_shim.c):
 *
 *   local mode:  Module.bind -> initParams -> fit (SGD momentum) — the
 *                Module.scala loop, gating accuracy.
 *   dist mode:   MXNetTPUSpark.trainPartition — rank-sharded data,
 *                kvCreate("dist_sync"), per-step push/pull of every
 *                gradient through the collective, lr rescaled by
 *                1/(batch*numWorkers). Run under tools/launch.py with 2
 *                workers; prints a weight checksum so the pytest can
 *                assert ALL ranks end bit-identical (the reference
 *                Spark trainer's invariant).
 *
 * Prints "final_acc=<v>" and "weights_sum=<v>".
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "jni.h"

extern JNIEnv jni_shim_env;
void *jni_shim_make_ints(const jint *v, jsize n);
void *jni_shim_make_floats(const jfloat *v, jsize n);
void *jni_shim_make_longs(const jlong *v, jsize n);
void *jni_shim_make_strs(const char **v, jsize n);
jsize jni_shim_len(void *a);
jint *jni_shim_ints(void *a);
jfloat *jni_shim_floats(void *a);
jlong *jni_shim_longs(void *a);
void **jni_shim_objs(void *a);

/* glue entry points (jstring == const char* under the shim) */
jlong Java_ml_mxnet_1tpu_LibInfo_symCreateVariable(JNIEnv *, jobject,
                                                   jstring);
jlong Java_ml_mxnet_1tpu_LibInfo_symCreateAtomic(JNIEnv *, jobject,
                                                 jstring, jobjectArray,
                                                 jobjectArray);
void Java_ml_mxnet_1tpu_LibInfo_symCompose(JNIEnv *, jobject, jlong,
                                           jstring, jobjectArray,
                                           jlongArray);
jobjectArray Java_ml_mxnet_1tpu_LibInfo_symListArguments(JNIEnv *, jobject,
                                                         jlong);
jintArray Java_ml_mxnet_1tpu_LibInfo_symInferShapes(JNIEnv *, jobject,
                                                    jlong, jobjectArray,
                                                    jintArray, jintArray,
                                                    jint);
jlong Java_ml_mxnet_1tpu_LibInfo_execSimpleBind(JNIEnv *, jobject, jlong,
                                                jint, jint, jobjectArray,
                                                jintArray, jintArray,
                                                jint);
void Java_ml_mxnet_1tpu_LibInfo_execSetArg(JNIEnv *, jobject, jlong,
                                           jstring, jfloatArray);
void Java_ml_mxnet_1tpu_LibInfo_execForward(JNIEnv *, jobject, jlong,
                                            jint);
void Java_ml_mxnet_1tpu_LibInfo_execBackward(JNIEnv *, jobject, jlong);
jfloatArray Java_ml_mxnet_1tpu_LibInfo_execGetOutput(JNIEnv *, jobject,
                                                     jlong, jint, jint);
jfloatArray Java_ml_mxnet_1tpu_LibInfo_execGetGrad(JNIEnv *, jobject,
                                                   jlong, jstring, jint);
jlong Java_ml_mxnet_1tpu_LibInfo_ndCreate(JNIEnv *, jobject, jintArray,
                                          jint, jint);
void Java_ml_mxnet_1tpu_LibInfo_ndSet(JNIEnv *, jobject, jlong,
                                      jfloatArray);
jfloatArray Java_ml_mxnet_1tpu_LibInfo_ndGet(JNIEnv *, jobject, jlong);
void Java_ml_mxnet_1tpu_LibInfo_ndFree(JNIEnv *, jobject, jlong);
jlong Java_ml_mxnet_1tpu_LibInfo_kvCreate(JNIEnv *, jobject, jstring);
jint Java_ml_mxnet_1tpu_LibInfo_kvRank(JNIEnv *, jobject, jlong);
jint Java_ml_mxnet_1tpu_LibInfo_kvNumWorkers(JNIEnv *, jobject, jlong);
void Java_ml_mxnet_1tpu_LibInfo_kvInit(JNIEnv *, jobject, jlong, jint,
                                       jlong);
void Java_ml_mxnet_1tpu_LibInfo_kvPush(JNIEnv *, jobject, jlong, jint,
                                       jlong, jint);
void Java_ml_mxnet_1tpu_LibInfo_kvPull(JNIEnv *, jobject, jlong, jint,
                                       jlong, jint);
void Java_ml_mxnet_1tpu_LibInfo_kvBarrier(JNIEnv *, jobject, jlong);
void Java_ml_mxnet_1tpu_LibInfo_kvFree(JNIEnv *, jobject, jlong);
void Java_ml_mxnet_1tpu_LibInfo_randomSeed(JNIEnv *, jobject, jint);
void Java_ml_mxnet_1tpu_LibInfo_ndSave(JNIEnv *, jobject, jstring,
                                       jobjectArray, jlongArray);
jobjectArray Java_ml_mxnet_1tpu_LibInfo_ndLoad(JNIEnv *, jobject, jstring);
void Java_ml_mxnet_1tpu_LibInfo_funcInvoke(JNIEnv *, jobject, jstring,
                                           jlongArray, jfloatArray, jlong);
jobjectArray Java_ml_mxnet_1tpu_LibInfo_listFunctions(JNIEnv *, jobject);

#define ENV (&jni_shim_env)
#define BATCH 32
#define NFEAT 8
#define NCLASS 2
#define NSAMPLE 256
#define ROUNDS 10
#define MAXARGS 16

static double frand_state = 12345;
static float frand(void) {
  frand_state = fmod(frand_state * 48271.0, 2147483647.0);
  return (float)(frand_state / 2147483647.0);
}

/* SymbolOps.X(data=input, params...) */
static jlong apply_op(const char *op, jlong input, const char *name,
                      const char **pk, const char **pv, int np) {
  jlong h = Java_ml_mxnet_1tpu_LibInfo_symCreateAtomic(
      ENV, NULL, op, jni_shim_make_strs(pk, np),
      jni_shim_make_strs(pv, np));
  const char *inkeys[] = {"data"};
  jlong ins[] = {input};
  Java_ml_mxnet_1tpu_LibInfo_symCompose(ENV, NULL, h, name,
                                        jni_shim_make_strs(inkeys, 1),
                                        jni_shim_make_longs(ins, 1));
  return h;
}

/* NDArrayIO.save/load round-trip (Scala's loadCheckpoint path): the
 * loaded handles must be caller-owned — readable AND freeable after
 * the glue released the load record (ndLoad detaches each via
 * MXNDArrayDup; the earlier ListFree-only version double-freed here,
 * which an ASAN build of this driver catches deterministically). */
static int ndio_mode(const char *path) {
  jint shape[] = {4};
  void *jshape = jni_shim_make_ints(shape, 1);
  jlong a = Java_ml_mxnet_1tpu_LibInfo_ndCreate(ENV, NULL, jshape, 1, 0);
  jlong b = Java_ml_mxnet_1tpu_LibInfo_ndCreate(ENV, NULL, jshape, 1, 0);
  jfloat va[] = {1.f, 2.f, 3.f, 4.f}, vb[] = {9.f, 8.f, 7.f, 6.f};
  Java_ml_mxnet_1tpu_LibInfo_ndSet(ENV, NULL, a,
                                   jni_shim_make_floats(va, 4));
  Java_ml_mxnet_1tpu_LibInfo_ndSet(ENV, NULL, b,
                                   jni_shim_make_floats(vb, 4));
  const char *names[] = {"arg:w", "aux:mean"};
  jlong hs[] = {a, b};
  Java_ml_mxnet_1tpu_LibInfo_ndSave(ENV, NULL, path,
                                    jni_shim_make_strs(names, 2),
                                    jni_shim_make_longs(hs, 2));
  Java_ml_mxnet_1tpu_LibInfo_ndFree(ENV, NULL, a);
  Java_ml_mxnet_1tpu_LibInfo_ndFree(ENV, NULL, b);

  for (int round = 0; round < 2; ++round) {
    void *pair = Java_ml_mxnet_1tpu_LibInfo_ndLoad(ENV, NULL, path);
    void *jnames = jni_shim_objs(pair)[0];
    void *jhandles = jni_shim_objs(pair)[1];
    if (jni_shim_len(jnames) != 2 || jni_shim_len(jhandles) != 2) {
      fprintf(stderr, "ndLoad arity wrong\n");
      return 1;
    }
    const char **lnames = (const char **)jni_shim_objs(jnames);
    jlong *lhs = jni_shim_longs(jhandles);
    if (strcmp(lnames[0], "arg:w") || strcmp(lnames[1], "aux:mean")) {
      fprintf(stderr, "ndLoad names wrong: %s %s\n", lnames[0], lnames[1]);
      return 1;
    }
    for (int i = 0; i < 2; ++i) {
      void *got = Java_ml_mxnet_1tpu_LibInfo_ndGet(ENV, NULL, lhs[i]);
      jfloat *g = jni_shim_floats(got);
      const jfloat *want = i == 0 ? va : vb;
      for (int d = 0; d < 4; ++d) {
        if (g[d] != want[d]) {
          fprintf(stderr, "ndLoad data wrong [%d][%d]=%f\n", i, d, g[d]);
          return 1;
        }
      }
      Java_ml_mxnet_1tpu_LibInfo_ndFree(ENV, NULL, lhs[i]);
    }
  }
  /* imperative function surface (NDArrayOpsGen path): _plus then
   * _mul_scalar through funcInvoke; listFunctions must name both */
  void *fnames = Java_ml_mxnet_1tpu_LibInfo_listFunctions(ENV, NULL);
  int have_plus = 0, have_muls = 0;
  for (jsize i = 0; i < jni_shim_len(fnames); ++i) {
    const char *nm = (const char *)jni_shim_objs(fnames)[i];
    if (!strcmp(nm, "_plus")) have_plus = 1;
    if (!strcmp(nm, "_mul_scalar")) have_muls = 1;
  }
  if (!have_plus || !have_muls) {
    fprintf(stderr, "listFunctions missing _plus/_mul_scalar\n");
    return 1;
  }
  jlong fa = Java_ml_mxnet_1tpu_LibInfo_ndCreate(ENV, NULL, jshape, 1, 0);
  jlong fb = Java_ml_mxnet_1tpu_LibInfo_ndCreate(ENV, NULL, jshape, 1, 0);
  jlong fo = Java_ml_mxnet_1tpu_LibInfo_ndCreate(ENV, NULL, jshape, 1, 0);
  Java_ml_mxnet_1tpu_LibInfo_ndSet(ENV, NULL, fa,
                                   jni_shim_make_floats(va, 4));
  Java_ml_mxnet_1tpu_LibInfo_ndSet(ENV, NULL, fb,
                                   jni_shim_make_floats(vb, 4));
  jlong use2[] = {fa, fb};
  jfloat two[] = {2.f};
  Java_ml_mxnet_1tpu_LibInfo_funcInvoke(
      ENV, NULL, "_plus", jni_shim_make_longs(use2, 2),
      jni_shim_make_floats(two, 0), fo);
  jlong use1[] = {fo};
  Java_ml_mxnet_1tpu_LibInfo_funcInvoke(
      ENV, NULL, "_mul_scalar", jni_shim_make_longs(use1, 1),
      jni_shim_make_floats(two, 1), fo);
  void *fres = Java_ml_mxnet_1tpu_LibInfo_ndGet(ENV, NULL, fo);
  for (int d = 0; d < 4; ++d) {
    jfloat want = 2.f * (va[d] + vb[d]);
    if (jni_shim_floats(fres)[d] != want) {
      fprintf(stderr, "funcInvoke wrong [%d]=%f want %f\n", d,
              jni_shim_floats(fres)[d], want);
      return 1;
    }
  }
  Java_ml_mxnet_1tpu_LibInfo_ndFree(ENV, NULL, fa);
  Java_ml_mxnet_1tpu_LibInfo_ndFree(ENV, NULL, fb);
  Java_ml_mxnet_1tpu_LibInfo_ndFree(ENV, NULL, fo);
  printf("ndio_ok\n");
  return 0;
}

int main(int argc, char **argv) {
  int dist = argc > 1 && strcmp(argv[1], "dist") == 0;
  if (argc > 2 && strcmp(argv[1], "ndio") == 0)
    return ndio_mode(argv[2]);

  /* dist mode: the collective group must form BEFORE anything touches
   * the XLA backend (jax.distributed contract) — same ordering the
   * Spark trainPartition uses (KVStore.create first) */
  jlong kv = 0;
  int rank = 0, nworkers = 1;
  if (dist) {
    kv = Java_ml_mxnet_1tpu_LibInfo_kvCreate(ENV, NULL, "dist_sync");
    rank = Java_ml_mxnet_1tpu_LibInfo_kvRank(ENV, NULL, kv);
    nworkers = Java_ml_mxnet_1tpu_LibInfo_kvNumWorkers(ENV, NULL, kv);
  }
  Java_ml_mxnet_1tpu_LibInfo_randomSeed(ENV, NULL, 7);

  /* ---- Module symbol: data -> FC(16) -> relu -> FC(2) -> softmax --- */
  jlong data = Java_ml_mxnet_1tpu_LibInfo_symCreateVariable(ENV, NULL,
                                                            "data");
  const char *k_hid[] = {"num_hidden"};
  const char *v16[] = {"16"};
  const char *v2[] = {"2"};
  const char *k_act[] = {"act_type"};
  const char *v_relu[] = {"relu"};
  jlong fc1 = apply_op("FullyConnected", data, "fc1", k_hid, v16, 1);
  jlong act = apply_op("Activation", fc1, "act1", k_act, v_relu, 1);
  jlong fc2 = apply_op("FullyConnected", act, "fc2", k_hid, v2, 1);
  jlong net = apply_op("SoftmaxOutput", fc2, "softmax", NULL, NULL, 0);

  /* ---- Module.bind: inferShapes + simpleBind ---- */
  const char *skeys[] = {"data"};
  jint ind[] = {0, 2};
  jint sdata[] = {BATCH, NFEAT};
  void *jkeys = jni_shim_make_strs(skeys, 1);
  void *jind = jni_shim_make_ints(ind, 2);
  void *jsdata = jni_shim_make_ints(sdata, 2);
  void *flat = Java_ml_mxnet_1tpu_LibInfo_symInferShapes(
      ENV, NULL, net, jkeys, jind, jsdata, 0);
  void *argnames = Java_ml_mxnet_1tpu_LibInfo_symListArguments(ENV, NULL,
                                                               net);
  int nargs = jni_shim_len(argnames);
  const char **names = (const char **)jni_shim_objs(argnames);
  long psize[MAXARGS];
  {
    jint *f = jni_shim_ints(flat);
    int p = 1;
    for (int i = 0; i < nargs; ++i) {
      int ndim = f[p++];
      long n = 1;
      for (int d = 0; d < ndim; ++d) n *= f[p++];
      psize[i] = n;
    }
  }
  jlong exec = Java_ml_mxnet_1tpu_LibInfo_execSimpleBind(
      ENV, NULL, net, 1, 0, jkeys, jind, jsdata, 1);

  /* ---- Module.initParams (same seed every rank -> identical init) -- */
  float *params[MAXARGS];
  float *moms[MAXARGS];
  for (int i = 0; i < nargs; ++i) {
    params[i] = calloc(psize[i], sizeof(float));
    moms[i] = calloc(psize[i], sizeof(float));
    if (strstr(names[i], "weight"))
      for (long j = 0; j < psize[i]; ++j)
        params[i][j] = (frand() - 0.5f) * 0.5f;
    if (strcmp(names[i], "data") && strcmp(names[i], "softmax_label"))
      Java_ml_mxnet_1tpu_LibInfo_execSetArg(
          ENV, NULL, exec, names[i],
          jni_shim_make_floats(params[i], (jsize)psize[i]));
  }
  /* per-param kv keys + gradient staging buffers (Spark initParams) */
  jlong gnd[MAXARGS];
  if (dist) {
    for (int i = 0; i < nargs; ++i) {
      if (!strcmp(names[i], "data") ||
          !strcmp(names[i], "softmax_label")) continue;
      jint shp[] = {(jint)psize[i]};
      gnd[i] = Java_ml_mxnet_1tpu_LibInfo_ndCreate(
          ENV, NULL, jni_shim_make_ints(shp, 1), 1, 0);
      Java_ml_mxnet_1tpu_LibInfo_ndSet(
          ENV, NULL, gnd[i],
          jni_shim_make_floats(params[i], (jsize)psize[i]));
      Java_ml_mxnet_1tpu_LibInfo_kvInit(ENV, NULL, kv, i, gnd[i]);
    }
  }

  /* ---- dataset: two separable blobs, rank-sharded in dist mode ---- */
  static float X[NSAMPLE][NFEAT];
  static float y[NSAMPLE];
  int nlocal = 0;
  for (int i = 0; i < NSAMPLE; ++i) {
    int cls = i % 2;
    float row[NFEAT];
    for (int j = 0; j < NFEAT; ++j)
      row[j] = (frand() - 0.5f) + (cls ? 0.8f : -0.8f);
    /* every rank draws the full stream (keeps RNG identical), keeps
     * its shard — Spark's repartition equivalent */
    if (!dist || i % nworkers == rank) {
      memcpy(X[nlocal], row, sizeof(row));
      y[nlocal] = (float)cls;
      nlocal++;
    }
  }

  const float lr = 0.1f, momentum = 0.9f;
  const float rescale = dist ? 1.0f / nworkers : 1.0f;
  float acc = 0.0f;
  int cursor = 0;
  for (int round = 0; round < ROUNDS; ++round) {
    int correct = 0, seen = 0;
    int steps = nlocal / BATCH;          /* equal on all ranks */
    for (int s = 0; s < steps; ++s) {
      float batch[BATCH * NFEAT];
      float labels[BATCH];
      for (int b = 0; b < BATCH; ++b) {
        int idx = (cursor + b) % nlocal;
        memcpy(&batch[b * NFEAT], X[idx], NFEAT * sizeof(float));
        labels[b] = y[idx];
      }
      cursor = (cursor + BATCH) % nlocal;
      Java_ml_mxnet_1tpu_LibInfo_execSetArg(
          ENV, NULL, exec, "data",
          jni_shim_make_floats(batch, BATCH * NFEAT));
      Java_ml_mxnet_1tpu_LibInfo_execSetArg(
          ENV, NULL, exec, "softmax_label",
          jni_shim_make_floats(labels, BATCH));
      Java_ml_mxnet_1tpu_LibInfo_execForward(ENV, NULL, exec, 1);
      Java_ml_mxnet_1tpu_LibInfo_execBackward(ENV, NULL, exec);
      for (int i = 0; i < nargs; ++i) {
        if (!strcmp(names[i], "data") ||
            !strcmp(names[i], "softmax_label")) continue;
        void *g = Java_ml_mxnet_1tpu_LibInfo_execGetGrad(
            ENV, NULL, exec, names[i], (jint)psize[i]);
        float *gv = jni_shim_floats(g);
        if (dist) {
          /* trainPartition: push local grad, pull the cross-worker
           * sum back before updating */
          Java_ml_mxnet_1tpu_LibInfo_ndSet(
              ENV, NULL, gnd[i],
              jni_shim_make_floats(gv, (jsize)psize[i]));
          Java_ml_mxnet_1tpu_LibInfo_kvPush(ENV, NULL, kv, i, gnd[i], 0);
          Java_ml_mxnet_1tpu_LibInfo_kvPull(ENV, NULL, kv, i, gnd[i], 0);
          void *red = Java_ml_mxnet_1tpu_LibInfo_ndGet(ENV, NULL, gnd[i]);
          gv = jni_shim_floats(red);
        }
        for (long j = 0; j < psize[i]; ++j) {   /* SGD.update */
          moms[i][j] = momentum * moms[i][j] - lr * rescale * gv[j];
          params[i][j] += moms[i][j];
        }
        Java_ml_mxnet_1tpu_LibInfo_execSetArg(
            ENV, NULL, exec, names[i],
            jni_shim_make_floats(params[i], (jsize)psize[i]));
      }
      void *out = Java_ml_mxnet_1tpu_LibInfo_execGetOutput(
          ENV, NULL, exec, 0, BATCH * NCLASS);
      float *ov = jni_shim_floats(out);
      for (int b = 0; b < BATCH; ++b) {
        int guess = ov[b * NCLASS] > ov[b * NCLASS + 1] ? 0 : 1;
        correct += (guess == (int)labels[b]);
        seen += 1;
      }
    }
    acc = (float)correct / seen;
  }
  if (dist) Java_ml_mxnet_1tpu_LibInfo_kvBarrier(ENV, NULL, kv);

  double wsum = 0.0;
  for (int i = 0; i < nargs; ++i) {
    if (!strcmp(names[i], "data") ||
        !strcmp(names[i], "softmax_label")) continue;
    for (long j = 0; j < psize[i]; ++j) wsum += (double)params[i][j];
  }
  printf("final_acc=%f\n", acc);
  printf("weights_sum=%.9f\n", wsum);
  if (dist) Java_ml_mxnet_1tpu_LibInfo_kvFree(ENV, NULL, kv);
  return acc >= 0.9f ? 0 : 1;
}
