"""Core C ABI (training-capable subset): ctypes drive of NDArray /
Symbol / Executor functions.

Reference analogue: src/c_api/c_api.cc consumed by the R/Scala
bindings — create tensors, load symbols, bind, forward/backward, read
gradients, update weights host-side.
"""
import ctypes
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LIB = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_predict.so")


def _lib():
    if not shutil.which("make"):
        pytest.skip("no make toolchain")
    r = subprocess.run(["make", "-C", REPO, "predict"], capture_output=True,
                       text=True)
    if r.returncode != 0 or not os.path.exists(LIB):
        pytest.skip("c api build failed: %s" % r.stderr[-500:])
    lib = ctypes.CDLL(LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def _fptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def test_ndarray_roundtrip_and_saveload(tmp_path):
    lib = _lib()
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint32 * 2)(3, 4)
    assert lib.MXNDArrayCreate(shape, 2, 1, 0, ctypes.byref(h)) == 0, \
        lib.MXGetLastError()

    ndim = ctypes.c_uint32()
    pdata = ctypes.POINTER(ctypes.c_uint32)()
    assert lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    assert tuple(pdata[i] for i in range(ndim.value)) == (3, 4)

    x = np.arange(12, dtype=np.float32)
    assert lib.MXNDArraySyncCopyFromCPU(h, _fptr(x), 12) == 0, \
        lib.MXGetLastError()
    out = np.zeros(12, dtype=np.float32)
    assert lib.MXNDArraySyncCopyToCPU(h, _fptr(out), 12) == 0
    np.testing.assert_array_equal(out, x)
    assert lib.MXNDArrayWaitAll() == 0

    # save/load container roundtrip
    fname = str(tmp_path / "arrs.nd").encode()
    keys = (ctypes.c_char_p * 1)(b"w")
    handles = (ctypes.c_void_p * 1)(h)
    assert lib.MXNDArraySave(fname, 1, handles, keys) == 0, \
        lib.MXGetLastError()
    out_size = ctypes.c_uint32()
    out_arr = ctypes.POINTER(ctypes.c_void_p)()
    name_size = ctypes.c_uint32()
    out_names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXNDArrayLoad(fname, ctypes.byref(out_size),
                             ctypes.byref(out_arr),
                             ctypes.byref(name_size),
                             ctypes.byref(out_names)) == 0, \
        lib.MXGetLastError()
    assert out_size.value == 1 and out_names[0] == b"w"
    loaded = np.zeros(12, dtype=np.float32)
    assert lib.MXNDArraySyncCopyToCPU(ctypes.c_void_p(out_arr[0]),
                                      _fptr(loaded), 12) == 0
    np.testing.assert_array_equal(loaded, x)
    assert lib.MXNDArrayListFree(out_arr, 1, out_names) == 0
    assert lib.MXNDArrayFree(h) == 0

    # error path: size mismatch
    h2 = ctypes.c_void_p()
    lib.MXNDArrayCreate(shape, 2, 1, 0, ctypes.byref(h2))
    bad = np.zeros(5, dtype=np.float32)
    assert lib.MXNDArraySyncCopyFromCPU(h2, _fptr(bad), 5) == -1
    assert b"size" in lib.MXGetLastError()
    lib.MXNDArrayFree(h2)


def test_symbol_and_training_loop():
    lib = _lib()
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc1")
    net = mx.sym.Activation(data=net, act_type="tanh")
    net = mx.sym.FullyConnected(data=net, num_hidden=1, name="fc2")
    net = mx.sym.LinearRegressionOutput(data=net, name="lro")
    json = net.tojson().encode()

    sh = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(json, ctypes.byref(sh)) == 0, \
        lib.MXGetLastError()

    # round trip JSON
    out_json = ctypes.c_char_p()
    assert lib.MXSymbolSaveToJSON(sh, ctypes.byref(out_json)) == 0
    assert mx.sym.load_json(out_json.value.decode()).list_arguments() == \
        net.list_arguments()

    n_args = ctypes.c_uint32()
    names = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXSymbolListArguments(sh, ctypes.byref(n_args),
                                     ctypes.byref(names)) == 0
    arg_names = [names[i].decode() for i in range(n_args.value)]
    assert arg_names == net.list_arguments()

    # infer shapes from data shape
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    sdata = (ctypes.c_uint32 * 2)(8, 3)
    in_size = ctypes.c_uint32()
    in_ndim = ctypes.POINTER(ctypes.c_uint32)()
    in_data = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32))()
    out_size = ctypes.c_uint32()
    out_ndim = ctypes.POINTER(ctypes.c_uint32)()
    out_data = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32))()
    assert lib.MXSymbolInferShape(
        sh, 1, keys, indptr, sdata, ctypes.byref(in_size),
        ctypes.byref(in_ndim), ctypes.byref(in_data),
        ctypes.byref(out_size), ctypes.byref(out_ndim),
        ctypes.byref(out_data)) == 0, lib.MXGetLastError()
    arg_shapes = [tuple(in_data[i][d] for d in range(in_ndim[i]))
                  for i in range(in_size.value)]
    assert arg_shapes[arg_names.index("fc1_weight")] == (4, 3)
    assert tuple(out_data[0][d] for d in range(out_ndim[0])) == (8, 1)

    # bind for training
    eh = ctypes.c_void_p()
    assert lib.MXExecutorSimpleBind(sh, 1, 0, 1, keys, indptr, sdata, 1,
                                    ctypes.byref(eh)) == 0, \
        lib.MXGetLastError()

    rng = np.random.RandomState(0)
    X = rng.rand(8, 3).astype(np.float32)
    y = (X @ np.array([1.0, -2.0, 0.5], np.float32))[:, None]
    params = {n: (rng.randn(*s) * 0.3).astype(np.float32)
              for n, s in zip(arg_names, arg_shapes)
              if n not in ("data", "lro_label")}

    def set_arg(name, arr):
        a = np.ascontiguousarray(arr, dtype=np.float32)
        assert lib.MXExecutorSetArg(eh, name.encode(), _fptr(a),
                                    a.size) == 0, lib.MXGetLastError()

    losses = []
    lr = 0.05
    for step in range(60):
        set_arg("data", X)
        set_arg("lro_label", y)
        for n, v in params.items():
            set_arg(n, v)
        assert lib.MXExecutorForward(eh, 1) == 0, lib.MXGetLastError()
        assert lib.MXExecutorBackward(eh) == 0, lib.MXGetLastError()
        n_out = ctypes.c_uint32()
        assert lib.MXExecutorOutputs(eh, ctypes.byref(n_out)) == 0
        pred = np.zeros((8, 1), np.float32)
        assert lib.MXExecutorGetOutput(eh, 0, _fptr(pred), 8) == 0
        losses.append(float(((pred - y) ** 2).mean()))
        for n in params:
            g = np.zeros_like(params[n])
            assert lib.MXExecutorGetGrad(eh, n.encode(), _fptr(g),
                                         g.size) == 0, lib.MXGetLastError()
            params[n] = params[n] - lr * g
    assert losses[-1] < losses[0] * 0.2, losses[::10]

    # error: unknown grad name
    g = np.zeros(4, np.float32)
    assert lib.MXExecutorGetGrad(eh, b"nope", _fptr(g), 4) == -1
    assert lib.MXExecutorFree(eh) == 0
    assert lib.MXSymbolFree(sh) == 0


def test_executor_aux_states_roundtrip():
    """MXExecutorSetAux/GetAux: restore BatchNorm moving stats from C
    (what the R frontend's predict() does for checkpoints with aux:
    entries) and verify eval-mode forward consumes them."""
    lib = _lib()
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(data=data, fix_gamma=False, name="bn")
    sh = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(net.tojson().encode(),
                                      ctypes.byref(sh)) == 0

    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint32 * 2)(0, 2)
    sdata = (ctypes.c_uint32 * 2)(4, 3)
    eh = ctypes.c_void_p()
    assert lib.MXExecutorSimpleBind(sh, 1, 0, 1, keys, indptr, sdata, 0,
                                    ctypes.byref(eh)) == 0, \
        lib.MXGetLastError()

    rng = np.random.RandomState(0)
    X = rng.rand(4, 3).astype(np.float32) * 4 + 2
    mean = np.array([2.0, 3.0, 4.0], np.float32)
    var = np.array([4.0, 1.0, 0.25], np.float32)

    def set_arg(name, arr):
        a = np.ascontiguousarray(arr, dtype=np.float32)
        assert lib.MXExecutorSetArg(eh, name.encode(), _fptr(a),
                                    a.size) == 0, lib.MXGetLastError()

    set_arg("data", X)
    set_arg("bn_gamma", np.ones(3, np.float32))
    set_arg("bn_beta", np.zeros(3, np.float32))
    for name, val in [("bn_moving_mean", mean), ("bn_moving_var", var)]:
        a = np.ascontiguousarray(val)
        assert lib.MXExecutorSetAux(eh, name.encode(), _fptr(a),
                                    a.size) == 0, lib.MXGetLastError()

    # GetAux roundtrip
    back = np.zeros(3, np.float32)
    assert lib.MXExecutorGetAux(eh, b"bn_moving_mean", _fptr(back), 3) == 0
    np.testing.assert_allclose(back, mean, rtol=1e-6)

    # eval-mode forward normalizes with the restored stats
    assert lib.MXExecutorForward(eh, 0) == 0, lib.MXGetLastError()
    out = np.zeros((4, 3), np.float32)
    assert lib.MXExecutorGetOutput(eh, 0, _fptr(out), out.size) == 0
    expected = (X - mean) / np.sqrt(var + 1e-3)
    np.testing.assert_allclose(out, expected, rtol=1e-2, atol=1e-2)

    # unknown aux name errors cleanly
    assert lib.MXExecutorSetAux(eh, b"nope", _fptr(back), 3) != 0
    assert b"auxiliary" in lib.MXGetLastError()
