"""Multithreaded ImageRecordIter decode pool (reference
src/io/iter_image_recordio.cc:188-196: OMP pool sized by
preprocess_threads).

Key invariants: augmentation is keyed by (epoch, record index) so the
pool size can never change what a record looks like; read-ahead futures
overlap decode with consumer compute; throughput tooling works.
"""
import os

import numpy as np
import pytest

pytest.importorskip("PIL")

import mxnet_tpu.io as mio
import mxnet_tpu.recordio as rio


def _make_rec(tmp_path, n=24, size=16, name="p.rec"):
    path = str(tmp_path / name)
    rng = np.random.RandomState(0)
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % 5), i, 0), img,
                             quality=100, img_fmt=".png"))
    w.close()
    return path


AUG = dict(rand_crop=True, rand_mirror=True, max_rotate_angle=15,
           random_h=20, random_s=20, random_l=20, scale=1.0 / 255)


def _epoch(it):
    out = []
    for b in it:
        out.append((b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy()))
    return out


def test_threaded_decode_matches_serial(tmp_path):
    """Same seed, any pool size -> bit-identical batches: augmentation
    draws derive from (epoch, record idx), not decode order."""
    path = _make_rec(tmp_path)
    a = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                            batch_size=8, preprocess_threads=1, seed=5, **AUG)
    b = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                            batch_size=8, preprocess_threads=4, seed=5, **AUG)
    for (da, la), (db, lb) in zip(_epoch(a), _epoch(b)):
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(la, lb)


def test_epochs_reaugment_but_reproducibly(tmp_path):
    """reset() moves to a new augmentation epoch (reference parser RNG
    keeps drawing across epochs); two identically-seeded iterators agree
    epoch by epoch."""
    path = _make_rec(tmp_path)
    mk = lambda: mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 12, 12), batch_size=8,
        preprocess_threads=2, seed=9, **AUG)
    a, b = mk(), mk()
    e1a = _epoch(a)
    a.reset()
    e2a = _epoch(a)
    e1b = _epoch(b)
    b.reset()
    e2b = _epoch(b)
    assert any(not np.array_equal(x[0], y[0]) for x, y in zip(e1a, e2a)), \
        "epoch 2 should re-augment differently"
    for (x, _), (y, _) in zip(e2a, e2b):
        np.testing.assert_array_equal(x, y)


def test_read_ahead_submits_futures(tmp_path):
    path = _make_rec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                             batch_size=4, preprocess_threads=2,
                             prefetch_buffer=2)
    next(iter(it))
    # after serving batch 0 (cursor 0), batches at cursors 4 and 8 are
    # in flight on the pool
    assert set(it._inflight.keys()) == {4, 8}
    # and the prefetched result is the one served later
    d = next(it).data[0].asnumpy()
    assert d.shape == (4, 3, 12, 12)


def test_preprocess_threads_one_uses_no_pool(tmp_path):
    path = _make_rec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                             batch_size=4, preprocess_threads=1)
    next(iter(it))
    assert it._pool is None and not it._inflight


def test_pipeline_bench_tool(tmp_path):
    """The throughput tool runs end to end and reports a sane rate; on
    any host the decode pipeline must comfortably beat the reference
    CPU-era 100-200 img/s floor at small images."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pipeline_bench", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "pipeline_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    results = mod.main(["--image", "32", "--num", "64", "--batch", "16",
                        "--seconds", "1.0", "--threads", "1,2"])
    assert len(results) == 2
    assert all(r["value"] > 100 for r in results), results
