"""Multithreaded ImageRecordIter decode pool (reference
src/io/iter_image_recordio.cc:188-196: OMP pool sized by
preprocess_threads).

Key invariants: augmentation is keyed by (epoch, record index) so the
pool size can never change what a record looks like; read-ahead futures
overlap decode with consumer compute; throughput tooling works.
"""
import os

import numpy as np
import pytest

pytest.importorskip("PIL")

import mxnet_tpu.io as mio
import mxnet_tpu.recordio as rio


def _make_rec(tmp_path, n=24, size=16, name="p.rec"):
    path = str(tmp_path / name)
    rng = np.random.RandomState(0)
    w = rio.MXRecordIO(path, "w")
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % 5), i, 0), img,
                             quality=100, img_fmt=".png"))
    w.close()
    return path


AUG = dict(rand_crop=True, rand_mirror=True, max_rotate_angle=15,
           random_h=20, random_s=20, random_l=20, scale=1.0 / 255)


def _epoch(it):
    out = []
    for b in it:
        out.append((b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy()))
    return out


def test_threaded_decode_matches_serial(tmp_path):
    """Same seed, any pool size -> bit-identical batches: augmentation
    draws derive from (epoch, record idx), not decode order."""
    path = _make_rec(tmp_path)
    a = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                            batch_size=8, preprocess_threads=1, seed=5, **AUG)
    b = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                            batch_size=8, preprocess_threads=4, seed=5, **AUG)
    for (da, la), (db, lb) in zip(_epoch(a), _epoch(b)):
        np.testing.assert_array_equal(da, db)
        np.testing.assert_array_equal(la, lb)


def test_epochs_reaugment_but_reproducibly(tmp_path):
    """reset() moves to a new augmentation epoch (reference parser RNG
    keeps drawing across epochs); two identically-seeded iterators agree
    epoch by epoch."""
    path = _make_rec(tmp_path)
    mk = lambda: mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 12, 12), batch_size=8,
        preprocess_threads=2, seed=9, **AUG)
    a, b = mk(), mk()
    e1a = _epoch(a)
    a.reset()
    e2a = _epoch(a)
    e1b = _epoch(b)
    b.reset()
    e2b = _epoch(b)
    assert any(not np.array_equal(x[0], y[0]) for x, y in zip(e1a, e2a)), \
        "epoch 2 should re-augment differently"
    for (x, _), (y, _) in zip(e2a, e2b):
        np.testing.assert_array_equal(x, y)


def test_read_ahead_submits_futures(tmp_path):
    path = _make_rec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                             batch_size=4, preprocess_threads=2,
                             prefetch_buffer=2)
    next(iter(it))
    # after serving batch 0 (cursor 0), batches at cursors 4 and 8 are
    # in flight on the pool
    assert set(it._inflight.keys()) == {4, 8}
    # and the prefetched result is the one served later
    d = next(it).data[0].asnumpy()
    assert d.shape == (4, 3, 12, 12)


def test_preprocess_threads_one_uses_no_pool(tmp_path):
    path = _make_rec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                             batch_size=4, preprocess_threads=1)
    next(iter(it))
    assert it._pool is None and not it._inflight


def test_pipeline_bench_tool(tmp_path):
    """The throughput tool runs end to end and reports a sane rate; on
    any host the decode pipeline must comfortably beat the reference
    CPU-era 100-200 img/s floor at small images."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "pipeline_bench", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "pipeline_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    results = mod.main(["--image", "32", "--num", "64", "--batch", "16",
                        "--seconds", "1.0", "--threads", "1,2"])
    assert len(results) == 2
    assert all(r["value"] > 100 for r in results), results


# ---- multi-process decode + shared-memory batch ring -------------------

import threading

import mxnet_tpu.io_pipeline as iop
from mxnet_tpu import telemetry


def _with_timeout(fn, seconds=90):
    """Hand-rolled per-test timeout (pytest-timeout is not in the image):
    run fn on a daemon thread; a hang fails the test instead of wedging
    the whole tier-1 run."""
    result = {}

    def run():
        try:
            result["value"] = fn()
        except BaseException as e:  # re-raised on the pytest thread
            result["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    assert not t.is_alive(), "pipeline test timed out after %ss" % seconds
    if "error" in result:
        raise result["error"]
    return result.get("value")


def test_shm_record_store_roundtrip():
    recs = [b"alpha", b"", b"x" * 1000, b"tail"]
    store = iop.ShmRecordStore.create(recs)
    try:
        att = iop.ShmRecordStore.attach(store.name)
        assert len(att) == len(recs)
        for i, r in enumerate(recs):
            assert att.get(i) == r
        att.close()
    finally:
        store.close()


def test_shm_batch_ring_views():
    ring = iop.ShmBatchRing(num_slots=2, batch_size=3, data_shape=(3, 4, 4),
                            label_width=1)
    try:
        ring.img_view(0)[:] = 7.0
        ring.label_view(1)[:] = 2.0
        att = iop.ShmBatchRing.attach(ring.meta())
        np.testing.assert_array_equal(att.img_view(0),
                                      np.full((3, 3, 4, 4), 7.0, np.float32))
        np.testing.assert_array_equal(att.label_view(1),
                                      np.full((3, 1), 2.0, np.float32))
        att.close()
    finally:
        ring.close()


def test_process_decode_matches_thread(tmp_path):
    """preprocess_mode='process' (2 spawn workers, shm ring) is
    bit-identical to the serial thread path for the same seed, across
    two epochs, with no fallback."""
    path = _make_rec(tmp_path)

    def body():
        a = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                batch_size=8, preprocess_threads=1, seed=5,
                                **AUG)
        c = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                batch_size=8, preprocess_threads=2,
                                preprocess_mode="process", seed=5, **AUG)
        with a, c:
            for (da, la), (dc, lc) in zip(_epoch(a), _epoch(c)):
                np.testing.assert_array_equal(da, dc)
                np.testing.assert_array_equal(la, lc)
            a.reset()
            c.reset()
            for (da, _), (dc, _) in zip(_epoch(a), _epoch(c)):
                np.testing.assert_array_equal(da, dc)
            assert c.preprocess_mode == "process", \
                "fell back to thread decode: %s" % c.preprocess_mode

    _with_timeout(body)


def test_process_worker_crash_falls_back(tmp_path):
    """Killing every decode worker mid-epoch degrades to in-process
    decode with identical output — never a hang, never a wrong batch."""
    path = _make_rec(tmp_path, n=96)

    def body():
        a = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                batch_size=8, preprocess_threads=1, seed=5,
                                **AUG)
        c = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                batch_size=8, preprocess_threads=2,
                                preprocess_mode="process", seed=5, **AUG)
        with a, c:
            it_a, it_c = iter(a), iter(c)
            np.testing.assert_array_equal(next(it_a).data[0].asnumpy(),
                                          next(it_c).data[0].asnumpy())
            for p in c._proc_pipe._procs:
                p.terminate()
            for p in c._proc_pipe._procs:
                p.join()
            served = 1
            while True:
                try:
                    bc = next(it_c)
                except StopIteration:
                    break
                ba = next(it_a)
                np.testing.assert_array_equal(ba.data[0].asnumpy(),
                                              bc.data[0].asnumpy())
                served += 1
            assert served == 12, served
            assert c.preprocess_mode == "thread"

    _with_timeout(body)


@pytest.mark.slow
def test_process_decode_four_workers(tmp_path):
    """Heavier 4-worker sweep (slow tier): worker count still cannot
    change a single bit of the output."""
    path = _make_rec(tmp_path, n=64)

    def body():
        a = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                batch_size=8, preprocess_threads=1, seed=3,
                                **AUG)
        c = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                batch_size=8, preprocess_threads=4,
                                preprocess_mode="process", seed=3, **AUG)
        with a, c:
            for (da, la), (dc, lc) in zip(_epoch(a), _epoch(c)):
                np.testing.assert_array_equal(da, dc)
                np.testing.assert_array_equal(la, lc)
            assert c.preprocess_mode == "process"

    _with_timeout(body, seconds=180)


def test_decode_procs_env_opts_in(tmp_path, monkeypatch):
    """MXNET_TPU_DECODE_PROCS turns process mode on without a code
    change (and wins over preprocess_threads for worker count)."""
    path = _make_rec(tmp_path)
    monkeypatch.setenv("MXNET_TPU_DECODE_PROCS", "2")
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                             batch_size=8, preprocess_threads=1, seed=5)
    with it:
        assert it.preprocess_mode == "process"
        assert it._num_procs == 2
        next(iter(it))


def test_device_staging_iter(tmp_path):
    """DeviceStagingIter yields the same batches as the bare iterator
    (one batch staged ahead), supports reset, and is what
    MXNET_TPU_DEVICE_STAGING wraps in."""
    path = _make_rec(tmp_path)
    mk = lambda: mio.ImageRecordIter(
        path_imgrec=path, data_shape=(3, 12, 12), batch_size=8,
        preprocess_threads=1, seed=5, **AUG)
    plain = [d for d, _ in _epoch(mk())]
    staged = iop.DeviceStagingIter(mk())
    got = [b.data[0].asnumpy().copy() for b in staged]
    assert len(got) == len(plain)
    for x, y in zip(plain, got):
        np.testing.assert_array_equal(x, y)
    staged.reset()
    again = [b.data[0].asnumpy().copy() for b in staged]
    assert len(again) == len(plain)


def test_maybe_wrap_device_staging(tmp_path, monkeypatch):
    path = _make_rec(tmp_path)
    it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                             batch_size=8, preprocess_threads=1)
    assert iop.maybe_wrap_device_staging(it) is it
    monkeypatch.setenv("MXNET_TPU_DEVICE_STAGING", "1")
    wrapped = iop.maybe_wrap_device_staging(it)
    assert isinstance(wrapped, iop.DeviceStagingIter)
    # idempotent: wrapping a wrapper is a no-op
    assert iop.maybe_wrap_device_staging(wrapped) is wrapped


def test_pipeline_telemetry_counters(tmp_path):
    """The process pipeline reports decode latency, ring occupancy and
    H2D staging through the PR-1 telemetry registry."""
    path = _make_rec(tmp_path)
    telemetry.enable()
    telemetry.reset()
    try:
        it = mio.ImageRecordIter(path_imgrec=path, data_shape=(3, 12, 12),
                                 batch_size=8, preprocess_threads=2,
                                 preprocess_mode="process", seed=5, **AUG)
        with it:
            staged = iop.DeviceStagingIter(it)
            for _ in staged:
                pass
        snap = telemetry.snapshot()
        io_m = snap["io"]
        assert io_m["pipeline"]["decode_ms"]["count"] >= 3
        assert io_m["staging"]["batches"] == 3
        assert io_m["staging"]["h2d_ms"]["count"] == 3
        assert snap["ndarray"]["h2d_transfers"] >= 3
        assert snap["ndarray"]["h2d_bytes"] > 0
    finally:
        telemetry.disable()
        telemetry.reset()
