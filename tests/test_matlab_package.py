"""MATLAB frontend validation without a MATLAB runtime (see
matlab-package/README.md): calllib targets must exist in the predict
header, the loader paths must be real, and the m-files must be
structurally sound (balanced blocks, methods declared)."""
import os
import re

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
MPKG = os.path.join(REPO, "matlab-package")


def _m_sources():
    out = {}
    for root, _, files in os.walk(MPKG):
        for f in files:
            if f.endswith(".m"):
                path = os.path.join(root, f)
                out[os.path.relpath(path, MPKG)] = open(path).read()
    return out


def test_calllib_targets_exist_in_header():
    header = open(os.path.join(
        REPO, "include", "mxnet_tpu", "c_predict_api.h")).read()
    declared = set(re.findall(r"^(?:int|const char \*)\s*(MX\w+)\(",
                              header, re.M))
    srcs = _m_sources()
    called = set()
    for src in srcs.values():
        called |= set(re.findall(
            r"calllib\('libmxtpu_predict',\s*'(\w+)'", src))
    assert called, "no calllib sites found"
    missing = called - declared
    assert not missing, "calllib of undeclared functions: %s" % missing


def test_library_and_header_paths_referenced_correctly():
    src = _m_sources()["+mxnet/callmxtpu.m"]
    assert "libmxtpu_predict.so" in src
    assert "c_predict_api.h" in src
    # the referenced header really exists at the path the loader builds
    assert os.path.exists(os.path.join(
        REPO, "include", "mxnet_tpu", "c_predict_api.h"))


def test_m_files_structurally_balanced():
    """Every function/classdef/if/for/switch opens a block closed by
    `end`; counting both gives a cheap structural syntax gate."""
    openers = re.compile(
        r"^\s*(classdef|function|if|for|while|switch|methods|properties)\b")
    for name, src in _m_sources().items():
        opens = ends = 0
        for line in src.splitlines():
            stripped = line.split("%", 1)[0]
            if openers.match(stripped):
                opens += 1
            ends += len(re.findall(r"\bend\b", stripped))
        assert opens == ends, (
            "%s: %d block openers vs %d end keywords" % (name, opens, ends))


def test_model_class_covers_reference_surface():
    """The reference model.m exposes load/forward with predictor
    caching; ours must too."""
    src = _m_sources()["+mxnet/model.m"]
    for method in ("function load(", "function out = forward(",
                   "function free_predictor(", "MXPredCreate",
                   "MXPredSetInput", "MXPredForward", "MXPredGetOutput",
                   "MXPredFree"):
        assert method in src, "missing: %s" % method
