"""Sharded fused step (device_sync kvstore): in-jit GSPMD gradient
exchange. dp=8 vs dp=1 bit-identical parity, one-dispatch and
no-retrace regressions under NamedSharding, donation safety, fused
default-on under device_sync, and the xprof collective bucket."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu import telemetry, xprof
from mxnet_tpu.module import Module

# exact-arithmetic regime so dp=8 mean-psum reduction order cannot
# perturb bits: integer-valued data/labels, quarter-integer weights,
# power-of-two batch/lr/rescale — every product, partial sum, psum and
# update is an exactly-representable dyadic rational in float32
BATCH = 16          # global; 2 rows per shard at dp=8
DIM = 4
HID = 8


def _reg_sym():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=HID, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=1, name="fc2")
    return mx.sym.LinearRegressionOutput(net, name="lro")


def _synthetic(n, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.randint(-3, 4, (n, DIM)).astype(np.float32)
    y = rng.randint(-3, 4, (n, 1)).astype(np.float32)
    return X, y


def _seed_params(net, seed=9, batch=BATCH):
    arg_shapes, _, _ = net.infer_shape(data=(batch, DIM),
                                       lro_label=(batch, 1))
    rng = np.random.RandomState(seed)
    return {name: mx.nd.array(
        (rng.randint(-2, 3, shape) * 0.5).astype(np.float32))
        for name, shape in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "lro_label")}


# single-layer head for the bit-parity tests: backward through a hidden
# layer multiplies two current-weight quantities (mantissa doubles per
# step, float32 rounds by step 2), while the linear head's gradient
# x^T(pred-label) is linear in the weights — mantissa grows ~5 bits per
# step and K=4 steps stay exactly representable
LBATCH = 8          # 1 row per shard at dp=8; mean divides by 2^3


def _lin_sym():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=1, name="fc1")
    return mx.sym.LinearRegressionOutput(net, name="lro")


def _synthetic_lin(n, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.randint(0, 2, (n, DIM)).astype(np.float32)
    y = rng.randint(0, 4, (n, 1)).astype(np.float32)
    return X, y


def _fit_dp(dp, nbatches=6, num_epoch=2, monkeypatch=None, fused_env="1",
            linear=False, lr=0.5):
    if fused_env is None:
        monkeypatch.delenv("MXNET_TPU_FUSED_STEP", raising=False)
    else:
        monkeypatch.setenv("MXNET_TPU_FUSED_STEP", fused_env)
    batch = LBATCH if linear else BATCH
    net = _lin_sym() if linear else _reg_sym()
    X, y = (_synthetic_lin if linear else _synthetic)(batch * nbatches)
    data = mx.io.NDArrayIter(X, y, batch_size=batch, label_name="lro_label")
    mod = Module(net, context=[mx.cpu(i) for i in range(dp)],
                 label_names=("lro_label",))
    mod.fit(data, num_epoch=num_epoch, kvstore="device_sync",
            eval_metric="mse", optimizer="sgd",
            arg_params=_seed_params(net, batch=batch), initializer=None,
            optimizer_params={"learning_rate": lr})
    return mod


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


@pytest.mark.multichip
def test_sharded_fused_bit_identical_to_single_device(monkeypatch):
    """dp=8 GSPMD mean-psum == dp=1 fused step, bit for bit, after K
    steps inside the exact-arithmetic window: the in-jit gradient
    exchange is exactly a mean reduce, not approximately equivalent.

    A linear head keeps every quantity a dyadic rational (~5 mantissa
    bits added per step), so K=4 steps are exactly representable in
    float32 and reduction order (1-row shards + psum vs one 8-row
    reduce) cannot perturb bits. A wrong rescale or a sum-not-mean
    reduce would diverge at step 1 by far more than rounding."""
    mod1 = _fit_dp(1, nbatches=4, num_epoch=1, monkeypatch=monkeypatch,
                   linear=True)
    mod8 = _fit_dp(8, nbatches=4, num_epoch=1, monkeypatch=monkeypatch,
                   linear=True)
    assert mod1._fused_step_active and mod8._fused_step_active
    args1, _ = mod1.get_params()
    args8, _ = mod8.get_params()
    assert set(args1) == set(args8)
    for name in sorted(args1):
        a, b = args1[name].asnumpy(), args8[name].asnumpy()
        assert a.dtype == b.dtype
        assert np.array_equal(a, b), (
            "param %s diverged under sharding (max abs diff %g)"
            % (name, np.abs(a - b).max()))
    # and training actually moved the params
    init = _seed_params(_lin_sym(), batch=LBATCH)
    assert any(not np.array_equal(args8[n].asnumpy(), init[n].asnumpy())
               for n in init)


@pytest.mark.multichip
def test_sharded_fused_tracks_single_device_long_run(monkeypatch):
    """Past the exact window only float non-associativity separates the
    two reductions: after 12 steps the params still agree to rounding
    noise."""
    mod1 = _fit_dp(1, nbatches=6, num_epoch=2, monkeypatch=monkeypatch,
                   lr=0.0625)
    mod8 = _fit_dp(8, nbatches=6, num_epoch=2, monkeypatch=monkeypatch,
                   lr=0.0625)
    args1, _ = mod1.get_params()
    args8, _ = mod8.get_params()
    for name in sorted(args1):
        np.testing.assert_allclose(
            args1[name].asnumpy(), args8[name].asnumpy(),
            rtol=1e-4, atol=1e-5, err_msg=name)


@pytest.mark.multichip
def test_sharded_fused_one_dispatch_per_batch(tel, monkeypatch):
    """dispatches_per_step stays 1.0 under NamedSharding: the gradient
    exchange costs zero extra dispatches."""
    nbatches, epochs = 6, 2
    before = telemetry.peek("step.dispatches") or 0
    _fit_dp(8, nbatches=nbatches, num_epoch=epochs, monkeypatch=monkeypatch)
    delta = (telemetry.peek("step.dispatches") or 0) - before
    assert delta / float(nbatches * epochs) == 1.0


@pytest.mark.multichip
def test_sharded_fused_no_retrace_across_batches(tel, monkeypatch):
    """One trace serves every batch and epoch: sharded inputs arrive
    with a stable aval+sharding signature on the staged feed path."""
    before = telemetry.peek("step.fused_recompiles") or 0
    _fit_dp(8, nbatches=5, num_epoch=3, monkeypatch=monkeypatch)
    assert (telemetry.peek("step.fused_recompiles") or 0) - before == 1


@pytest.mark.multichip
def test_sharded_fused_donation_safety(monkeypatch):
    """Donated params/opt-state buffers stay safe under NamedSharding
    across many steps — a use-after-donate raises inside jax, and the
    surviving params must be finite and real."""
    mod = _fit_dp(8, nbatches=4, num_epoch=4, monkeypatch=monkeypatch,
                  lr=0.03125)
    args, _ = mod.get_params()
    for name, arr in args.items():
        assert np.isfinite(arr.asnumpy()).all(), name


@pytest.mark.multichip
def test_device_sync_defaults_fused_on(monkeypatch):
    """device_sync flips kvstore.fused_step_compatible: the fused path
    engages with MXNET_TPU_FUSED_STEP unset, and the
    MXNET_TPU_DEVICE_SYNC_FUSED=0 escape hatch restores the classic
    loop."""
    monkeypatch.delenv("MXNET_TPU_DEVICE_SYNC_FUSED", raising=False)
    mod = _fit_dp(8, nbatches=3, num_epoch=1,
                  monkeypatch=monkeypatch, fused_env=None)
    assert mod._fused_step_active
    monkeypatch.setenv("MXNET_TPU_DEVICE_SYNC_FUSED", "0")
    mod = _fit_dp(8, nbatches=3, num_epoch=1,
                  monkeypatch=monkeypatch, fused_env=None)
    assert not mod._fused_step_active


@pytest.mark.multichip
def test_sharded_step_has_collective_bucket(monkeypatch):
    """The xprof op-category breakdown of the sharded fused executable
    reports a nonzero collective bucket — the gradient all-reduce is
    visibly inside the one dispatch."""
    monkeypatch.setenv("MXNET_TPU_XPROF_OPS", "1")
    xprof.enable()
    xprof.reset()
    try:
        _fit_dp(8, nbatches=3, num_epoch=1, monkeypatch=monkeypatch)
        xp = xprof.summary()
        last = (xp["sites"].get("fused_step") or {}).get("last") or {}
        bd = last.get("op_breakdown") or {}
        coll = bd.get("collective")
        assert coll, "sharded fused step compiled without collective ops"
        assert coll.get("count", 0) > 0
        assert coll.get("bytes", 0) > 0
        assert last.get("num_devices") == 8
    finally:
        xprof.reset()
        xprof.disable()
