"""Shape inference tests (reference tests/python/unittest/test_infer_shape.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError


def test_mlp_infer_shape():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data=data, name="fc1", num_hidden=1000)
    out = sym.SoftmaxOutput(data=fc1, name="softmax")
    arg_shapes, out_shapes, _ = out.infer_shape(data=(100, 100))
    names = out.list_arguments()
    d = dict(zip(names, arg_shapes))
    assert d["fc1_weight"] == (1000, 100)
    assert d["fc1_bias"] == (1000,)
    assert d["softmax_label"] == (100,)
    assert out_shapes == [(100, 1000)]


def test_conv_chain_infer_shape():
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                           pad=(1, 1), name="conv")
    pool = sym.Pooling(data=conv, kernel=(2, 2), stride=(2, 2),
                       pool_type="max")
    flat = sym.Flatten(data=pool)
    fc = sym.FullyConnected(data=flat, num_hidden=10, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape(data=(2, 3, 28, 28))
    d = dict(zip(fc.list_arguments(), arg_shapes))
    assert d["conv_weight"] == (8, 3, 3, 3)
    assert d["fc_weight"] == (10, 8 * 14 * 14)
    assert out_shapes == [(2, 10)]


def test_incomplete_shape_raises():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=10)
    with pytest.raises(MXNetError):
        fc.infer_shape()


def test_infer_shape_partial():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=10, name="fc")
    arg_shapes, out_shapes, _ = fc.infer_shape_partial()
    assert arg_shapes[0] is None


def test_batchnorm_aux_shapes():
    data = sym.Variable("data")
    bn = sym.BatchNorm(data=data, name="bn")
    arg_shapes, out_shapes, aux_shapes = bn.infer_shape(data=(4, 7, 5, 5))
    assert aux_shapes == [(7,), (7,)]
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]


def test_deconv_infer_shape():
    data = sym.Variable("data")
    deconv = sym.Deconvolution(data=data, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=8, name="dc")
    arg_shapes, out_shapes, _ = deconv.infer_shape(data=(1, 3, 16, 16))
    assert out_shapes == [(1, 8, 32, 32)]
    d = dict(zip(deconv.list_arguments(), arg_shapes))
    assert d["dc_weight"] == (3, 8, 4, 4)
