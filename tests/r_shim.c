/* A real (minimal) implementation of the R C API subset that
 * R-package/src/mxnet_glue.c consumes, so the glue can be EXECUTED in
 * CI without an R interpreter (none exists in this image). SEXPs are
 * heap records; PROTECT is identity; memory is deliberately leaked
 * (driver-lifetime only). Together with tests/r_glue_train.c this
 * upgrades the R tier from "compiles" to "the exact .Call surface the
 * R training API drives runs a training loop end to end".
 */
#include <stdarg.h>
#include <stddef.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "Rinternals.h"

enum { NILSXP_ = 0, CHARSXP_ = 9, EXTPTRSXP_ = 22 };

typedef struct attrib {
  const char *key;
  void *value;
  struct attrib *next;
} attrib;

typedef struct sexp_rec {
  int type;
  R_xlen_t len;
  int *ints;
  double *reals;
  struct sexp_rec **vec;    /* STRSXP / VECSXP elements */
  char *str;                /* CHARSXP payload */
  void *ptr;                /* external pointer address */
  attrib *attribs;
} sexp_rec;

static sexp_rec nil_rec = {NILSXP_, 0, 0, 0, 0, 0, 0, 0};
SEXP R_NilValue = &nil_rec;
static sexp_rec names_sym = {CHARSXP_, 0, 0, 0, 0, (char *)"names", 0, 0};
SEXP R_NamesSymbol = &names_sym;

static sexp_rec *rec(int type, R_xlen_t n) {
  sexp_rec *r = calloc(1, sizeof(sexp_rec));
  r->type = type;
  r->len = n;
  if (type == INTSXP) r->ints = calloc(n ? n : 1, sizeof(int));
  else if (type == REALSXP) r->reals = calloc(n ? n : 1, sizeof(double));
  else if (type == STRSXP || type == VECSXP)
    r->vec = calloc(n ? n : 1, sizeof(sexp_rec *));
  return r;
}

SEXP Rf_allocVector(int type, R_xlen_t n) { return rec(type, n); }

SEXP Rf_mkChar(const char *s) {
  sexp_rec *r = rec(CHARSXP_, (R_xlen_t)strlen(s));
  r->str = strdup(s);
  return r;
}

SEXP Rf_mkString(const char *s) {
  sexp_rec *r = rec(STRSXP, 1);
  r->vec[0] = Rf_mkChar(s);
  return r;
}

SEXP Rf_install(const char *s) { return Rf_mkChar(s); }

void SET_STRING_ELT(SEXP v, R_xlen_t i, SEXP c) {
  ((sexp_rec *)v)->vec[i] = (sexp_rec *)c;
}
SEXP STRING_ELT(SEXP v, R_xlen_t i) {
  return ((sexp_rec *)v)->vec[i];
}
void SET_VECTOR_ELT(SEXP v, R_xlen_t i, SEXP x) {
  ((sexp_rec *)v)->vec[i] = (sexp_rec *)x;
}
SEXP VECTOR_ELT(SEXP v, R_xlen_t i) { return ((sexp_rec *)v)->vec[i]; }

const char *CHAR(SEXP c) { return ((sexp_rec *)c)->str; }
int *INTEGER(SEXP v) { return ((sexp_rec *)v)->ints; }
double *REAL(SEXP v) { return ((sexp_rec *)v)->reals; }

int Rf_length(SEXP v) { return (int)((sexp_rec *)v)->len; }
R_xlen_t Rf_xlength(SEXP v) { return ((sexp_rec *)v)->len; }

int Rf_asInteger(SEXP v) {
  sexp_rec *r = (sexp_rec *)v;
  if (r->type == INTSXP) return r->ints[0];
  if (r->type == REALSXP) return (int)r->reals[0];
  return 0;
}
double Rf_asReal(SEXP v) {
  sexp_rec *r = (sexp_rec *)v;
  if (r->type == REALSXP) return r->reals[0];
  if (r->type == INTSXP) return (double)r->ints[0];
  return 0;
}

SEXP Rf_ScalarInteger(int v) {
  sexp_rec *r = rec(INTSXP, 1);
  r->ints[0] = v;
  return r;
}

SEXP Rf_setAttrib(SEXP x, SEXP sym, SEXP val) {
  sexp_rec *r = (sexp_rec *)x;
  attrib *a = calloc(1, sizeof(attrib));
  a->key = CHAR(sym);
  a->value = val;
  a->next = r->attribs;
  r->attribs = a;
  return x;
}
SEXP Rf_getAttrib(SEXP x, SEXP sym) {
  for (attrib *a = ((sexp_rec *)x)->attribs; a; a = a->next)
    if (strcmp(a->key, CHAR(sym)) == 0) return a->value;
  return R_NilValue;
}

SEXP PROTECT(SEXP x) { return x; }
void UNPROTECT(int n) { (void)n; }

void Rf_error(const char *fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "Rf_error: ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
  exit(2);
}

char *R_alloc(size_t n, int size) { return calloc(n ? n : 1, size); }

SEXP R_MakeExternalPtr(void *p, SEXP tag, SEXP prot) {
  (void)tag; (void)prot;
  sexp_rec *r = rec(EXTPTRSXP_, 0);
  r->ptr = p;
  return r;
}
void *R_ExternalPtrAddr(SEXP x) { return ((sexp_rec *)x)->ptr; }
void R_ClearExternalPtr(SEXP x) { ((sexp_rec *)x)->ptr = NULL; }
void R_RegisterCFinalizerEx(SEXP x, R_CFinalizer_t fin, int onexit) {
  (void)x; (void)fin; (void)onexit;   /* driver-lifetime objects */
}

int R_registerRoutines(DllInfo *info, const void *c, const R_CallMethodDef *call,
                       const void *f, const void *e) {
  (void)info; (void)c; (void)call; (void)f; (void)e;
  return 0;
}
int R_useDynamicSymbols(DllInfo *info, int x) {
  (void)info; (void)x;
  return 0;
}
