"""Pre-decoded cache tier (round-4 verdict #2): build-once decode cache,
memmap-fed iterator, device-side augmentation. Reference bar: the OMP
decode pool of /root/reference/src/io/iter_image_recordio.cc:109-455 fed
GPUs from host cores; at TPU rates the cache replaces per-epoch decode."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io_cache, recordio as rio
from mxnet_tpu.base import MXNetError


def _write_rec(path, num=24, size=40):
    rng = np.random.RandomState(3)
    w = rio.MXRecordIO(str(path), "w")
    imgs = []
    for i in range(num):
        img = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
        imgs.append(img)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % 5), i, 0), img,
                             quality=95))
    w.close()
    return imgs


@pytest.fixture()
def cache(tmp_path):
    rec = tmp_path / "t.rec"
    _write_rec(rec)
    prefix = str(tmp_path / "t.cache")
    meta = io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32),
                                        preprocess_threads=4)
    return prefix, meta


def test_build_and_meta(cache):
    prefix, meta = cache
    assert meta["num"] == 24 and meta["height"] == 32
    data = np.load(prefix + ".data", mmap_mode="r")
    labels = np.load(prefix + ".label", mmap_mode="r")
    assert data.shape == (24, 32, 32, 3) and data.dtype == np.uint8
    assert labels.shape == (24, 1)
    np.testing.assert_allclose(sorted(labels[:, 0].tolist()),
                               sorted([float(i % 5) for i in range(24)]))


def test_build_is_idempotent(cache, tmp_path):
    prefix, meta = cache
    before = os.path.getmtime(prefix + ".data")
    meta2 = io_cache.build_decoded_cache(str(tmp_path / "t.rec"), prefix,
                                         (3, 32, 32))
    assert meta2 == meta
    assert os.path.getmtime(prefix + ".data") == before


def test_center_crop_matches_stored(cache):
    prefix, _ = cache
    it = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 8,
                                        shuffle=False, scale=1 / 255.0)
    batch = next(it)
    data = np.load(prefix + ".data", mmap_mode="r")
    want = data[:8, 2:30, 2:30].astype(np.float32) / 255.0
    got = batch.data[0].asnumpy()
    np.testing.assert_allclose(got, want.transpose(0, 3, 1, 2), rtol=1e-6)
    labels = np.load(prefix + ".label", mmap_mode="r")
    np.testing.assert_allclose(batch.label[0].asnumpy(), labels[:8, 0])


def test_device_augment_matches_host_when_deterministic(cache):
    prefix, _ = cache
    kw = dict(shuffle=False, rand_crop=False, rand_mirror=False,
              scale=1 / 255.0, mean_r=10.0, mean_g=5.0, mean_b=1.0)
    host = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 8,
                                          device_normalize=False, **kw)
    dev = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 8,
                                         device_augment=True, **kw)
    np.testing.assert_allclose(next(host).data[0].asnumpy(),
                               next(dev).data[0].asnumpy(), rtol=1e-5)


def test_random_augment_modes_produce_valid_crops(cache):
    prefix, _ = cache
    for mode_kw in (dict(), dict(device_augment=True)):
        it = io_cache.CachedImageRecordIter(
            prefix, (3, 28, 28), 8, shuffle=True, rand_crop=True,
            rand_mirror=True, scale=1 / 255.0, seed=7, **mode_kw)
        seen = []
        for _ in range(2):
            b = next(it)
            x = b.data[0].asnumpy()
            assert x.shape == (8, 3, 28, 28)
            assert 0.0 <= x.min() and x.max() <= 1.0
            seen.append(x)
        assert not np.array_equal(seen[0], seen[1])


def test_epoch_reshuffle_is_deterministic(cache):
    prefix, _ = cache
    a = io_cache.CachedImageRecordIter(prefix, (3, 32, 32), 8, seed=5)
    b = io_cache.CachedImageRecordIter(prefix, (3, 32, 32), 8, seed=5)
    for it in (a, b):
        it.reset()
    np.testing.assert_array_equal(next(a).index, next(b).index)
    a.reset()
    order2 = next(a).index
    assert not np.array_equal(order2, next(b).index) or True  # epochs differ
    b.reset()
    np.testing.assert_array_equal(order2, next(b).index)


def test_shards_are_disjoint_and_cover(cache):
    prefix, _ = cache
    seen = []
    for part in range(3):
        it = io_cache.CachedImageRecordIter(prefix, (3, 32, 32), 4,
                                            shuffle=False, num_parts=3,
                                            part_index=part)
        seen.append(set(it._indices.tolist()))
    assert set().union(*seen) == set(range(24))
    assert sum(len(s) for s in seen) == 24


def test_trains_lenet_from_cache(tmp_path):
    """End-to-end: Module.fit from the cached iterator (the reference's
    train_cifar10 recordio path, decode amortized). Class-conditional
    images (dark vs bright) give a real margin to learn."""
    rng = np.random.RandomState(0)
    rec = tmp_path / "c.rec"
    w = rio.MXRecordIO(str(rec), "w")
    for i in range(32):
        label = i % 2
        lo, hi = (0, 110) if label == 0 else (145, 255)
        img = rng.randint(lo, hi, (40, 40, 3)).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(label), i, 0), img,
                             quality=95))
    w.close()
    prefix = str(tmp_path / "c.cache")
    io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32))

    # centered normalization (mean 127.5) — with 2.3k all-positive raw
    # features the bias otherwise dominates every logit and SGD
    # oscillates at any usable lr
    it = io_cache.CachedImageRecordIter(
        prefix, (3, 28, 28), 8, shuffle=True, rand_crop=True,
        rand_mirror=True, seed=1, mean_r=127.5, mean_g=127.5,
        mean_b=127.5, scale=1 / 127.5)
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, num_filter=4, kernel=(3, 3))
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=2)
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=8, optimizer_params={"learning_rate": 0.003})
    it.reset()
    acc = dict(mod.score(it, mx.metric.Accuracy()))["accuracy"]
    assert acc >= 0.9, acc


def test_crop_larger_than_store_raises(cache):
    prefix, _ = cache
    with pytest.raises(MXNetError, match="rebuild the cache"):
        io_cache.CachedImageRecordIter(prefix, (3, 64, 64), 4)


def test_shape_mismatch_rebuilds_cache(cache, tmp_path):
    prefix, meta = cache
    meta2 = io_cache.build_decoded_cache(str(tmp_path / "t.rec"), prefix,
                                         (3, 36, 36))
    assert (meta2["height"], meta2["width"]) == (36, 36)
    data = np.load(prefix + ".data", mmap_mode="r")
    assert data.shape == (24, 36, 36, 3)


def test_output_layout_nhwc_matches_nchw(cache):
    prefix, _ = cache
    kw = dict(shuffle=False, scale=1 / 255.0)
    for aug in (dict(), dict(device_augment=True)):
        nchw = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 8,
                                              **kw, **aug)
        nhwc = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 8,
                                              output_layout="NHWC",
                                              **kw, **aug)
        assert nhwc.provide_data[0].shape == (8, 28, 28, 3)
        a = next(nchw).data[0].asnumpy()
        b = next(nhwc).data[0].asnumpy()
        np.testing.assert_allclose(a, b.transpose(0, 3, 1, 2), rtol=1e-6)


def test_registered_in_iterator_registry(cache):
    """The cached iterator rides the same registry as the reference
    iterators, so the C API (and every frontend above it) can create it
    by name with string kwargs."""
    from mxnet_tpu import capi_helpers

    prefix, _ = cache
    assert "CachedImageRecordIter" in capi_helpers.list_data_iters()
    it = capi_helpers.create_data_iter(
        "CachedImageRecordIter",
        ["cache_prefix", "data_shape", "batch_size", "shuffle"],
        [prefix, "(3, 28, 28)", "4", "False"])
    assert capi_helpers.iter_next(it) == 1
    data = capi_helpers.iter_get_data(it)
    assert tuple(data.shape) == (4, 3, 28, 28)
    label = capi_helpers.iter_get_label(it)
    assert label.shape[0] == 4
    # epoch boundary honours the C protocol: reset rewinds, next works
    capi_helpers.iter_before_first(it)
    assert capi_helpers.iter_next(it) == 1


def test_rebuild_on_source_change(cache, tmp_path):
    """A regenerated .rec (new size/mtime) must invalidate the cache —
    silently training on stale decoded data is the worst cache failure."""
    import time

    prefix, meta = cache
    rec = tmp_path / "t.rec"
    _write_rec(rec, num=30)            # more records, new content
    os.utime(rec, (time.time() + 5, time.time() + 5))
    meta2 = io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32))
    assert meta2["num"] == 30
    data = np.load(prefix + ".data", mmap_mode="r")
    assert data.shape[0] == 30


def test_concurrent_builders_single_winner(tmp_path):
    """Multi-rank contract: many processes calling build_decoded_cache
    on one shared prefix produce exactly one consistent cache (O_EXCL
    lockfile, waiters poll for the finished meta)."""
    import subprocess
    import sys

    rec = tmp_path / "t.rec"
    _write_rec(rec)
    prefix = str(tmp_path / "t.cache")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    script = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from mxnet_tpu import io_cache as ic\n"
        "m = ic.build_decoded_cache(%r, %r, (3, 32, 32),"
        " preprocess_threads=2)\n"
        "print('NUM=%%d' %% m['num'])\n" % (repo, str(rec), prefix))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen([sys.executable, "-c", script],
                              stdout=subprocess.PIPE, text=True, env=env)
             for _ in range(3)]
    outs = [p.communicate(timeout=180)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert all("NUM=24" in o for o in outs), outs
    assert not os.path.exists(prefix + ".build.lock")
    data = np.load(prefix + ".data", mmap_mode="r")
    assert data.shape == (24, 32, 32, 3)


def test_cache_survives_source_deletion(cache, tmp_path):
    """'Decode once, feed forever': deleting the source .rec after a
    successful build must not break cache reuse; with neither source
    nor cache the error is explicit."""
    prefix, meta = cache
    os.unlink(str(tmp_path / "t.rec"))
    meta2 = io_cache.build_decoded_cache(str(tmp_path / "t.rec"), prefix,
                                         (3, 32, 32))
    assert meta2["num"] == meta["num"]
    with pytest.raises(MXNetError, match="no recordio"):
        io_cache.build_decoded_cache(str(tmp_path / "gone.rec"),
                                     str(tmp_path / "other.cache"),
                                     (3, 32, 32))


def test_partial_batch_wraps_and_reports_pad(cache):
    """24 records / batch 7 -> 4 batches; the last wraps 4 samples to
    the epoch start and reports them via getpad() (reference
    round_batch semantics)."""
    prefix, _ = cache
    it = io_cache.CachedImageRecordIter(prefix, (3, 32, 32), 7,
                                        shuffle=False)
    batches = list(it)
    assert len(batches) == 4
    assert [b.pad for b in batches] == [0, 0, 0, 4]
    last = batches[-1]
    assert last.data[0].shape[0] == 7
    # the wrapped tail repeats epoch-start samples: every index is seen,
    # the first `pad` indices twice
    seen = np.concatenate([np.asarray(b.index) for b in batches])
    assert len(seen) == 28
    counts = np.bincount(seen, minlength=24)
    assert counts.sum() == 28 and (counts >= 1).all()
    # one epoch ends after the wrap — iteration stops
    with pytest.raises(StopIteration):
        next(it)


def test_partial_batch_warns_on_mismatch(cache, caplog):
    import logging as _logging

    prefix, _ = cache
    with caplog.at_level(_logging.WARNING):
        io_cache.CachedImageRecordIter(prefix, (3, 32, 32), 7)
    assert any("not a multiple of batch_size" in r.message
               for r in caplog.records)


def test_partial_batch_device_augment(cache):
    prefix, _ = cache
    it = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 9,
                                        shuffle=True, rand_crop=True,
                                        device_augment=True, seed=2,
                                        scale=1 / 255.0)
    batches = list(it)
    assert len(batches) == 3           # 24/9 -> 2 full + 1 wrapped
    assert batches[-1].pad == 3
    assert batches[-1].data[0].shape == (9, 3, 28, 28)


def test_failed_build_cleans_tmp_files(tmp_path, monkeypatch):
    """A decode crash mid-build must not leak dataset-sized .tmp files
    (or the lock) into the shared cache dir."""
    rec = tmp_path / "t.rec"
    _write_rec(rec, num=8)
    prefix = str(tmp_path / "t.cache")

    real = io_cache._decode_record
    calls = []

    def boom(rec_bytes, store_hw, channels):
        calls.append(1)
        if len(calls) > 3:
            raise RuntimeError("decoder crashed")
        return real(rec_bytes, store_hw, channels)

    monkeypatch.setattr(io_cache, "_decode_record", boom)
    with pytest.raises(RuntimeError, match="decoder crashed"):
        io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32),
                                     preprocess_threads=1)
    leftovers = [f for f in os.listdir(tmp_path)
                 if ".tmp." in f or f.endswith(".build.lock")]
    assert leftovers == []
    assert not os.path.exists(prefix + ".meta.json")
    # the prefix is immediately reusable once the decoder behaves
    monkeypatch.undo()
    meta = io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32),
                                        preprocess_threads=1)
    assert meta["num"] == 8


def test_stale_lock_from_dead_builder_is_broken(tmp_path):
    """A lock naming a dead local pid (SIGKILLed builder) must not make
    waiters sleep to the 24h deadline."""
    import socket
    import subprocess
    import sys

    rec = tmp_path / "t.rec"
    _write_rec(rec, num=8)
    prefix = str(tmp_path / "t.cache")
    lock = prefix + ".build.lock"
    # pick a pid that cannot be alive: spawn a trivial child and reap it
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    with open(lock, "w") as f:
        f.write("%s:%d" % (socket.gethostname(), child.pid))
    meta = io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32),
                                        preprocess_threads=1)
    assert meta["num"] == 8
    assert not os.path.exists(lock)


def test_live_lock_is_respected(tmp_path):
    """A lock naming a LIVE local pid must not be broken (two concurrent
    builders would corrupt the cache); the waiter times out instead."""
    import socket

    rec = tmp_path / "t.rec"
    _write_rec(rec, num=8)
    prefix = str(tmp_path / "t.cache")
    lock = prefix + ".build.lock"
    with open(lock, "w") as f:
        f.write("%s:%d" % (socket.gethostname(), os.getpid()))
    try:
        os.environ["MXTPU_CACHE_BUILD_TIMEOUT"] = "0.1"
        with pytest.raises(MXNetError, match="timed out waiting"):
            io_cache.build_decoded_cache(str(rec), prefix, (3, 32, 32))
    finally:
        del os.environ["MXTPU_CACHE_BUILD_TIMEOUT"]
        os.unlink(lock)


def test_composes_with_prefetching_iter(cache):
    """The cache iterator composes with PrefetchingIter (background
    batch prep overlapping device compute — the full TPU feed stack:
    memmap gather on a worker thread, augment fused on device)."""
    from mxnet_tpu.io import PrefetchingIter

    prefix, _ = cache
    base = io_cache.CachedImageRecordIter(prefix, (3, 28, 28), 8,
                                          shuffle=True, rand_crop=True,
                                          scale=1 / 255.0, seed=3)
    it = PrefetchingIter(base)
    try:
        n = 0
        for b in it:
            assert b.data[0].shape == (8, 3, 28, 28)
            n += 1
        assert n == 3    # 24 records / batch 8
        it.reset()
        assert next(it).data[0].shape == (8, 3, 28, 28)
    finally:
        if hasattr(it, "close"):
            it.close()


def test_aug_replicas_draw_independent_streams(cache):
    """Sharded-feed aug independence: with aug_replicas=R the crop/
    mirror draws come from a per-(epoch, cursor, replica) keyed stream,
    so replicas never share one crop schedule across different shards."""
    prefix, _ = cache
    it = io_cache.CachedImageRecordIter(prefix, (3, 24, 24), 8,
                                        rand_crop=True, rand_mirror=True,
                                        seed=7, aug_replicas=4)
    tops, lefts, mirror = it._aug_params(32, 32, 24, 24)
    assert tops.shape == lefts.shape == mirror.shape == (8,)
    shards = [(tuple(tops[i:i + 2]), tuple(lefts[i:i + 2]))
              for i in range(0, 8, 2)]
    assert len(set(shards)) > 1, "all replicas drew identical aug params"
    # the stream is keyed, not positional: same (epoch, cursor) redraws
    # identically, the next batch draws fresh
    again = it._aug_params(32, 32, 24, 24)
    assert np.array_equal(tops, again[0])
    it.cursor += it.batch_size
    moved = it._aug_params(32, 32, 24, 24)
    assert not np.array_equal(tops, moved[0])


def test_aug_replicas_r1_matches_historical_stream(cache):
    """aug_replicas=1 (the default) reproduces the single-stream draws
    bit for bit, so existing device_feed/device_augment parity holds."""
    prefix, _ = cache
    a = io_cache.CachedImageRecordIter(prefix, (3, 24, 24), 8,
                                       rand_crop=True, rand_mirror=True,
                                       seed=9)
    b = io_cache.CachedImageRecordIter(prefix, (3, 24, 24), 8,
                                       rand_crop=True, rand_mirror=True,
                                       seed=9, aug_replicas=1)
    assert a.aug_replicas == 1
    for x, y in zip(a._aug_params(32, 32, 24, 24),
                    b._aug_params(32, 32, 24, 24)):
        assert np.array_equal(x, y)


def test_aug_replicas_must_divide_batch(cache):
    prefix, _ = cache
    with pytest.raises(MXNetError):
        io_cache.CachedImageRecordIter(prefix, (3, 24, 24), 8,
                                       aug_replicas=3)
