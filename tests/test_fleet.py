"""Fault-tolerant serving fleet: retry/backoff/deadline math under a
fake clock (the budget is never exceeded), the circuit-breaker FSM,
consistent-hash session affinity, hedging, the typed fault registry,
and the chaos proofs — kill a replica mid-load with zero client-visible
errors, recover lost responses under an injected drop_response fault,
and a rolling refresh_params swap under load that serves zero
mixed-version responses even with a torn_swap fault armed."""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, fleet, serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import (AttemptTimeout, CircuitBreaker,
                             DeadlineExceeded, FleetError, FleetRouter,
                             ReplicaCrash, backoff_delay_s)
from mxnet_tpu.module import Module

DIM = 8
CLASSES = 4
HID = 16


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


@pytest.fixture
def no_faults():
    yield
    faults.configure(None)


def _rows(n, seed=11):
    rng = np.random.RandomState(seed)
    return rng.randint(-3, 4, (n, DIM)).astype(np.float32)


# ---------------------------------------------------------------------------
# fakes: router logic with no jax, no sleeping
# ---------------------------------------------------------------------------

class FakeClock:
    """Monotonic fake time; sleep() just advances it."""

    def __init__(self):
        self.t = 0.0
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self.t

    def sleep(self, s):
        assert s >= 0.0
        with self._lock:
            self.t += s


class _OkWaiter:
    def __init__(self, arrays):
        self._arrays = arrays

    def wait(self, timeout_s):
        return [np.asarray(a) * 2.0 for a in self._arrays]

    def cancel(self):
        pass


class _HangWaiter:
    """Never answers: consumes the full wait (fake or real time)."""

    def __init__(self, clock_sleep):
        self._sleep = clock_sleep

    def wait(self, timeout_s):
        self._sleep(timeout_s)
        raise AttemptTimeout("fake replica never answered")

    def cancel(self):
        pass


class _SlowWaiter:
    """Answers after delay_s of real time."""

    def __init__(self, arrays, delay_s):
        self._arrays = arrays
        self._t_due = time.monotonic() + delay_s

    def wait(self, timeout_s):
        rem = self._t_due - time.monotonic()
        if rem > 0:
            if timeout_s < rem:
                time.sleep(timeout_s)
                raise AttemptTimeout("still slow")
            time.sleep(rem)
        return [np.asarray(a) * 2.0 for a in self._arrays]

    def cancel(self):
        pass


class FakeReplica(fleet.Replica):
    """behavior: ok | hang | crash | slow; health_status is mutable so
    autoscale tests can flip a replica degraded."""

    def __init__(self, rid, behavior="ok", clock_sleep=time.sleep,
                 slow_s=0.2):
        self.rid = rid
        self.behavior = behavior
        self.health_status = "ok"
        self.submits = 0
        self.envelopes = []   # (request_id, deadline_ms, priority)
        self._alive = True
        self._clock_sleep = clock_sleep
        self._slow_s = slow_s

    def submit(self, arrays, request_id=None, deadline_ms=None,
               priority=None):
        self.submits += 1
        self.envelopes.append((request_id, deadline_ms, priority))
        if not self._alive:
            raise ReplicaCrash("replica %s is down" % self.rid)
        if self.behavior == "crash":
            self._alive = False
            raise ReplicaCrash("replica %s crashed" % self.rid)
        if self.behavior == "hang":
            return _HangWaiter(self._clock_sleep)
        if self.behavior == "slow":
            return _SlowWaiter(arrays, self._slow_s)
        return _OkWaiter(arrays)

    def alive(self):
        return self._alive

    def health(self):
        if not self._alive:
            raise ReplicaCrash("down")
        return {"status": self.health_status, "in_flight": 0}

    def in_flight(self):
        return 0

    def refresh_params(self, apply_fn=None):
        pass

    def restart(self):
        self._alive = True
        self.behavior = "ok"

    def kill(self):
        self._alive = False

    def close(self):
        self._alive = False


def _fake_router(behaviors, clock=None, **kw):
    """Router over FakeReplicas; behaviors assigned per slot in order."""
    made = {}
    queue = list(behaviors)
    sleep = clock.sleep if clock is not None else time.sleep

    def factory(rid):
        behavior = queue.pop(0) if queue else "ok"
        made[rid] = FakeReplica(rid, behavior, clock_sleep=sleep)
        return made[rid]

    kw.setdefault("health_interval_s", 60.0)   # monitor stays out of
    kw.setdefault("auto_respawn", False)       # the fake-clock math
    if clock is not None:
        kw.setdefault("clock", clock)
        kw.setdefault("sleep", clock.sleep)
    r = FleetRouter(factory, len(behaviors), **kw)
    return r, made


# ---------------------------------------------------------------------------
# retry math: jitter bounds, deadline budget, attempt cap
# ---------------------------------------------------------------------------

def test_backoff_jitter_bounds():
    rng = __import__("random").Random(7)
    base = 0.01
    for attempt in range(8):
        e = min(1.0, base * 2 ** attempt)
        for _ in range(50):
            d = backoff_delay_s(attempt, base, rng, cap_s=1.0)
            assert e / 2 <= d < e, (attempt, d, e)


def test_deadline_budget_never_exceeded_across_retries():
    """Every attempt timeout and backoff sleep is clamped to the
    remaining budget: with replicas that never answer, the caller's
    total (fake) wait is <= the deadline, bit-for-bit."""
    clock = FakeClock()
    router, _ = _fake_router(["hang", "hang"], clock=clock,
                             deadline_ms=1000.0, attempt_timeout_ms=300.0,
                             retries=1000, backoff_ms=10.0, hedge=False)
    try:
        t0 = clock()
        with pytest.raises(DeadlineExceeded) as ei:
            router._serve([_rows(1)], None, "req-dl", 1.0)
        elapsed = clock() - t0
        assert elapsed <= 1.0 + 1e-9, elapsed
        # the budget was genuinely used (several attempts ran)
        assert elapsed >= 0.9
        assert "deadline" in str(ei.value)
    finally:
        router.close()


def test_retry_cap_raises_before_deadline():
    clock = FakeClock()
    router, _ = _fake_router(["hang"], clock=clock, deadline_ms=60000.0,
                             attempt_timeout_ms=10.0, retries=3,
                             backoff_ms=1.0, hedge=False)
    try:
        with pytest.raises(FleetError, match="after 3 attempts"):
            router._serve([_rows(1)], None, "req-cap", 60.0)
        assert clock() < 60.0
    finally:
        router.close()


def test_failover_retry_succeeds_on_peer(tel):
    router, made = _fake_router(["crash", "ok"], deadline_ms=5000.0,
                                attempt_timeout_ms=500.0, retries=4,
                                backoff_ms=1.0)
    try:
        x = _rows(1, seed=5)
        (out,) = router.infer([x], request_id="req-fo")
        assert np.array_equal(out, x * 2.0)
        st = router.stats()
        assert st["counters"]["retries"] >= 1
        assert st["counters"]["served"] == 1
        assert st["counters"]["recovered_requests"] == 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# circuit breaker FSM under a fake clock
# ---------------------------------------------------------------------------

def test_breaker_state_machine():
    t = [0.0]
    b = CircuitBreaker(fail_threshold=2, cooldown_s=1.0,
                       clock=lambda: t[0])
    assert b.state == CircuitBreaker.CLOSED
    assert b.allow()
    assert b.record_failure() is False        # 1 of 2
    assert b.state == CircuitBreaker.CLOSED
    assert b.record_failure() is True         # trip
    assert b.state == CircuitBreaker.OPEN
    assert b.trips == 1
    assert not b.allow()                      # shedding
    t[0] = 0.99
    assert not b.allow()                      # still cooling down
    t[0] = 1.01
    assert b.state == CircuitBreaker.HALF_OPEN
    assert b.allow()                          # the one probe
    assert not b.allow()                      # only one probe at a time
    assert b.record_failure() is True         # probe failed: re-open
    assert b.state == CircuitBreaker.OPEN
    assert b.trips == 2
    t[0] = 2.5
    assert b.allow()                          # second probe
    b.record_success()
    assert b.state == CircuitBreaker.CLOSED
    assert b.allow() and b.allow()            # fully closed again
    # success resets the consecutive-failure count
    assert b.record_failure() is False
    b.record_success()
    assert b.record_failure() is False


def test_breaker_sheds_load_to_healthy_peer():
    """After the breaker trips, the broken replica stops being picked
    at all until its cooldown expires."""
    clock = FakeClock()
    router, made = _fake_router(["crash", "ok"], clock=clock,
                                deadline_ms=10000.0,
                                attempt_timeout_ms=100.0, retries=8,
                                backoff_ms=1.0, breaker_fails=1,
                                breaker_cooldown_ms=1e7)
    try:
        crashed = next(r for r in made.values() if not r.behavior == "ok")
        (out,) = router.infer([_rows(1)], request_id="r1")
        assert out is not None
        n = crashed.submits
        for i in range(5):
            router.infer([_rows(1)], request_id="r-%d" % i)
        assert crashed.submits == n          # breaker open: never picked
        st = router.stats()
        assert st["counters"]["breaker_trips"] == 1
        assert st["replicas"][crashed.rid]["breaker"]["state"] == "open"
        assert any(e["type"] == "breaker_open" for e in st["events"])
    finally:
        router.close()


# ---------------------------------------------------------------------------
# consistent-hash session affinity
# ---------------------------------------------------------------------------

def test_session_affinity_stable_and_fails_over():
    router, made = _fake_router(["ok", "ok", "ok"], deadline_ms=5000.0)
    try:
        home = {s: router._pick("sess-%d" % s)[0] for s in range(64)}
        # stable: the same session maps to the same replica every time
        for s, rid in home.items():
            for _ in range(3):
                assert router._pick("sess-%d" % s)[0] == rid
        # all three replicas own some sessions (md5 spreads)
        assert len(set(home.values())) == 3
        # kill one: only ITS sessions move; everyone else stays home
        dead_rid = home[0]
        made[dead_rid].kill()
        for s, rid in home.items():
            got = router._pick("sess-%d" % s)[0]
            if rid == dead_rid:
                assert got != dead_rid
            else:
                assert got == rid
    finally:
        router.close()


# ---------------------------------------------------------------------------
# hedging
# ---------------------------------------------------------------------------

def test_hedge_second_send_wins(tel):
    router, made = _fake_router(["slow", "ok"], deadline_ms=10000.0,
                                attempt_timeout_ms=2000.0, retries=3,
                                backoff_ms=1.0, hedge=True)
    try:
        # prime the latency window so p95 ~ 5ms (hedge trigger)
        with router._rlock:
            router._lat.extend([0.005] * 30)
        # least-inflight tie breaks by rid: r1 (slow) is primary
        slow = made["r1"]
        assert slow.behavior == "slow"
        x = _rows(1, seed=9)
        (out,) = router.infer([x], request_id="req-hedge")
        assert np.array_equal(out, x * 2.0)
        st = router.stats()
        assert st["counters"]["hedges"] >= 1
        assert st["counters"]["hedge_wins"] >= 1
        assert made["r2"].submits >= 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# lifecycle: crash detection + respawn, drain-then-stop, autoscale
# ---------------------------------------------------------------------------

def test_monitor_detects_crash_and_respawns():
    router, made = _fake_router(["ok", "ok"], health_interval_s=0.01,
                                auto_respawn=True, deadline_ms=5000.0)
    try:
        rid = router.replica_ids()[0]
        router.kill_replica(rid)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            st = router.stats()
            if st["counters"].get("respawns", 0) >= 1:
                break
            time.sleep(0.01)
        st = router.stats()
        assert st["counters"]["replica_crashes"] >= 1
        assert st["counters"]["respawns"] >= 1
        types = [e["type"] for e in st["events"]]
        assert "replica_killed" in types
        assert "replica_dead" in types
        assert "replica_respawned" in types
        assert st["replicas"][rid]["state"] == "up"
        # and it serves again
        (out,) = router.infer([_rows(1)], session=None)
        assert out is not None
    finally:
        router.close()


def test_remove_replica_drains_then_stops():
    router, made = _fake_router(["ok", "ok"], deadline_ms=5000.0)
    try:
        rid = router.replica_ids()[0]
        router.remove_replica(rid, drain_timeout_s=5.0)
        assert rid not in router.replica_ids()
        assert not made[rid].alive()
        (out,) = router.infer([_rows(1)])   # the peer still serves
        assert out is not None
    finally:
        router.close()


def test_autoscale_up_on_degraded_down_when_healthy():
    armed = {"degraded": True}
    made = {}

    def factory(rid):
        r = FakeReplica(rid, "ok")
        r.health_status = "degraded" if armed["degraded"] else "ok"
        made[rid] = r
        return r

    router = FleetRouter(factory, 1, autoscale=True, min_replicas=1,
                         max_replicas=3, scale_down_ticks=3,
                         health_interval_s=0.01, auto_respawn=True,
                         deadline_ms=5000.0)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if len(router.replica_ids()) >= 3:
                break
            time.sleep(0.01)
        assert len(router.replica_ids()) == 3
        assert router.stats()["counters"]["scale_ups"] >= 2
        # flip everyone healthy: the fleet drains back down to min
        armed["degraded"] = False
        for r in made.values():
            r.health_status = "ok"
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(router.replica_ids()) == 1:
                break
            time.sleep(0.02)
        assert len(router.replica_ids()) == 1
        assert router.stats()["counters"]["scale_downs"] >= 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# typed fault registry
# ---------------------------------------------------------------------------

def test_fault_registry_is_typed(no_faults):
    with pytest.raises(MXNetError, match="unknown fault"):
        faults.FaultPlan("replica_crash,not_a_fault")
    with pytest.raises(MXNetError, match="outside"):
        faults.FaultPlan("slow_replica:1.5")
    with pytest.raises(MXNetError, match="not a float"):
        faults.FaultPlan("slow_replica:often")
    plan = faults.FaultPlan("replica_crash:0.25,torn_swap")
    assert plan.rates == {"replica_crash": 0.25, "torn_swap": 1.0}


def test_fault_registry_includes_network_faults(no_faults):
    """The net_* faults are first-class registry members, and an
    unknown name fails fast with the FULL valid-name list in the
    error — a typo'd chaos spec can never silently inject nothing."""
    plan = faults.FaultPlan(
        "net_drop:0.1,net_partition:0.05,net_reorder,net_slow:0.2")
    assert plan.rates == {"net_drop": 0.1, "net_partition": 0.05,
                          "net_reorder": 1.0, "net_slow": 0.2}
    with pytest.raises(MXNetError) as ei:
        faults.FaultPlan("net_dorp")
    msg = str(ei.value)
    assert "net_dorp" in msg
    for name in faults.FAULTS:          # every valid name is listed
        assert name in msg
    # same fail-fast contract through the env-driven configure path
    with pytest.raises(MXNetError, match="net_everything"):
        faults.configure("net_everything")


def test_fault_plan_seeded_and_counted(no_faults):
    a = faults.FaultPlan("drop_response:0.5", seed=42)
    b = faults.FaultPlan("drop_response:0.5", seed=42)
    seq_a = [a.fires("drop_response") for _ in range(64)]
    seq_b = [b.fires("drop_response") for _ in range(64)]
    assert seq_a == seq_b                      # reproducible chaos
    assert 0 < sum(seq_a) < 64
    assert a.injected["drop_response"] == sum(seq_a)
    # unarmed faults never fire, even on an armed plan
    assert not a.fires("torn_swap")


def test_faults_disabled_is_inert(no_faults):
    faults.configure(None)
    assert not faults.active()
    assert not faults.fires("replica_crash")
    assert faults.slow_ms() == 0.0
    faults.configure("slow_replica", slow_ms=7.5)
    assert faults.active()
    assert faults.fires("slow_replica")
    assert faults.slow_ms() == 7.5


def test_drop_response_fault_times_out_caller(tel, no_faults):
    def fake(placed):
        return [placed[0] * 2.0], ()

    faults.configure("drop_response")
    sched = serving.BatchScheduler(fake, [(4, DIM)], max_batch=4,
                                   max_wait_ms=0.5, slo_ms=0.0)
    try:
        r = sched.submit([_rows(1)])
        with pytest.raises(MXNetError, match="timed out"):
            r.get(0.3)
        # dropped requests do not leak the in-flight gauge
        assert sched.in_flight() == 0
        assert tel.peek("serve.dropped_responses") >= 1
    finally:
        faults.configure(None)
        sched.close()


# ---------------------------------------------------------------------------
# chaos proofs on real InferenceServer replicas (in-process)
# ---------------------------------------------------------------------------

def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _seed_params(net, batch, seed=3):
    arg_shapes, _, _ = net.infer_shape(data=(batch, DIM),
                                       softmax_label=(batch,))
    rng = np.random.RandomState(seed)
    return {name: mx.nd.array(
        (rng.randint(-2, 3, shape) * 0.5).astype(np.float32))
        for name, shape in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}


def _server_factory():
    net = _mlp()
    batch = 8
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (batch, DIM))],
             label_shapes=[("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(initializer=None,
                    arg_params=_seed_params(net, batch), aux_params={})
    return serving.InferenceServer(mod, top_k=0, max_batch=batch,
                                   max_wait_ms=0.5, buckets=[batch],
                                   slo_ms=0.0, port=None)


def test_chaos_kill_replica_mid_load_zero_failures(tel):
    """THE chaos acceptance: kill a replica mid-load; every request
    still gets a correct answer (zero client-visible errors) and p99
    stays bounded — inflated by retries, but nowhere near the deadline."""
    router = FleetRouter(fleet.in_process(_server_factory), 2,
                         deadline_ms=30000.0, attempt_timeout_ms=5000.0,
                         retries=10, backoff_ms=2.0,
                         health_interval_s=0.02)
    lat_lock = threading.Lock()
    baseline, chaos = [], []
    try:
        x = _rows(1, seed=77)
        (expect,) = router.infer([x])

        def run_phase(n, sink, kill_at=None):
            futs = []
            for i in range(n):
                t0 = time.perf_counter()

                def cb(f, t0=t0):
                    with lat_lock:
                        sink.append(time.perf_counter() - t0)

                f = router.submit([x], request_id=None)
                f.add_done_callback(cb)
                futs.append(f)
                if kill_at is not None and i == kill_at:
                    router.kill_replica(router.replica_ids()[0])
                time.sleep(0.002)
            return futs

        futs = run_phase(40, baseline)
        for f in futs:
            (out,) = f.result(60)            # raises on any failure
            assert np.array_equal(out, expect)
        futs = run_phase(60, chaos, kill_at=20)
        for f in futs:
            (out,) = f.result(60)            # zero client-visible errors
            assert np.array_equal(out, expect)
        st = router.stats()
        assert st["counters"]["replica_crashes"] >= 1
        assert st["counters"]["respawns"] >= 1
        assert st["counters"].get("client_errors", 0) == 0
        p99_base = sorted(baseline)[int(0.99 * (len(baseline) - 1))]
        p99_chaos = sorted(chaos)[int(0.99 * (len(chaos) - 1))]
        # bounded inflation: retries cost something, but the recovery
        # is orders of magnitude inside the 30s deadline
        assert p99_chaos < max(20 * p99_base, 5.0), (p99_base, p99_chaos)
    finally:
        router.close()


def test_router_recovers_injected_drop_response(tel, no_faults):
    """Lost responses (served but never delivered) are recovered by
    deadline-budgeted retries: every caller still gets its answer."""
    faults.configure("drop_response:0.4", seed=1234)
    router = FleetRouter(fleet.in_process(_server_factory), 2,
                         deadline_ms=30000.0, attempt_timeout_ms=400.0,
                         retries=20, backoff_ms=2.0,
                         health_interval_s=60.0)
    try:
        x = _rows(1, seed=31)
        futs = [router.submit([x], request_id="drop-%d" % i)
                for i in range(24)]
        outs = [f.result(60)[0] for f in futs]  # all succeed
        ref = outs[0]
        for out in outs:
            assert np.array_equal(out, ref)
        st = router.stats()
        assert st["counters"]["retries"] >= 1   # drops really happened
        plan = faults._PLAN
        assert plan is not None
        assert plan.injected.get("drop_response", 0) >= 1
    finally:
        router.close()
        faults.configure(None)


def _double_params(srv):
    """apply_fn for the rolling swap: double every packed param of the
    served executor (the new 'trained' weights)."""
    fused = srv._fused
    ex = fused._ex
    for i in fused._p_idx:
        arr = ex.arg_arrays[i]
        arr._data = arr._data * 2.0


def test_rolling_swap_under_load_zero_mixed_versions(tel, no_faults):
    """Glitch-free serve-while-training swap, with the torn_swap fault
    ARMED: every response served during the rolling refresh is exactly
    pure-old or pure-new — the drain masks the torn window entirely —
    and zero requests fail."""
    faults.configure("torn_swap", slow_ms=30.0)
    router = FleetRouter(fleet.in_process(_server_factory), 2,
                         deadline_ms=30000.0, attempt_timeout_ms=5000.0,
                         retries=10, backoff_ms=2.0,
                         health_interval_s=60.0)
    try:
        x = _rows(1, seed=55)
        (old,) = router.infer([x])

        # reference NEW output: a third, private server swapped while
        # idle tells us what pure-new bits look like
        ref = fleet.InProcReplica("ref", _server_factory)
        try:
            _double_params(ref._srv)
            ref._srv.refresh_params()
            (new,) = ref.submit([x]).wait(30)
        finally:
            ref.close()
        assert not np.array_equal(old, new)

        stop = threading.Event()
        outs, errs = [], []

        def load():
            i = 0
            while not stop.is_set():
                try:
                    (out,) = router.infer([x], request_id="swap-%d" % i)
                    outs.append(out)
                except Exception as e:   # noqa: BLE001 (collected+pinned)
                    errs.append(e)
                i += 1

        threads = [threading.Thread(target=load, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        router.refresh_params(apply_fn=_double_params,
                              drain_timeout_s=30.0)
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(30)

        assert not errs, errs[:3]                 # zero failed responses
        n_old = sum(np.array_equal(o, old) for o in outs)
        n_new = sum(np.array_equal(o, new) for o in outs)
        assert n_old + n_new == len(outs), \
            "mixed-version responses served: %d of %d" \
            % (len(outs) - n_old - n_new, len(outs))
        assert n_old > 0 and n_new > 0            # load straddled the swap
        plan = faults._PLAN
        assert plan is not None
        assert plan.injected.get("torn_swap", 0) >= 2   # window existed
        st = router.stats()
        assert st["counters"]["param_swaps"] == 2
    finally:
        router.close()
        faults.configure(None)


# ---------------------------------------------------------------------------
# subprocess replicas: real processes, real SIGKILL
# ---------------------------------------------------------------------------

def test_subprocess_replica_serves_and_survives_sigkill(tel):
    router = FleetRouter(
        fleet.in_subprocess("mxnet_tpu.fleet:demo_server_factory"), 1,
        deadline_ms=120000.0, attempt_timeout_ms=60000.0, retries=20,
        backoff_ms=50.0, health_interval_s=0.05)
    try:
        x = _rows(1, seed=3)
        (out,) = router.infer([x], timeout=120.0)
        assert out.shape == (1, CLASSES)
        h = router._entries[router.replica_ids()[0]].replica.health()
        assert h["status"] == "ok"
        assert h["pid"] != __import__("os").getpid()   # really remote
        assert "in_flight" in h and "uptime_s" in h
        # SIGKILL the child mid-fleet; the monitor respawns it and the
        # next request succeeds with zero client-visible errors
        router.kill_replica(router.replica_ids()[0])
        (out2,) = router.infer([x], timeout=120.0)
        assert np.array_equal(out2, out)
        st = router.stats()
        assert st["counters"]["replica_crashes"] >= 1
        assert st["counters"]["respawns"] >= 1
    finally:
        router.close()


# ---------------------------------------------------------------------------
# socket replicas: the same fleet discipline over TCP frames
# ---------------------------------------------------------------------------

def test_socket_replica_serves_and_survives_sigkill(tel):
    """The third Replica backend: same factory, same router policies,
    but requests cross a real TCP socket as zero-copy frames. Parity
    with the in-process answer is bit-exact, health crosses the wire,
    and a SIGKILLed child respawns on a fresh port with zero
    client-visible errors."""
    srv = fleet.demo_server_factory()
    x = _rows(1, seed=3)
    expect = srv.submit([x]).get(30.0)[0]
    srv.close()

    router = FleetRouter(
        fleet.in_socket("mxnet_tpu.fleet:demo_server_factory"), 1,
        deadline_ms=120000.0, attempt_timeout_ms=60000.0, retries=20,
        backoff_ms=50.0, health_interval_s=0.05)
    try:
        (out,) = router.infer([x], timeout=120.0)
        assert np.array_equal(out, expect)        # bit-exact over TCP
        rep = router._entries[router.replica_ids()[0]].replica
        h = rep.health()
        assert h["status"] == "ok"
        assert h["pid"] != __import__("os").getpid()   # really remote
        st = rep.wire_stats()
        assert st["frames_tx"] >= 2 and st["frames_rx"] >= 2
        assert st["rtt_ms"]["count"] >= 1
        # SIGKILL mid-fleet: monitor respawns (new port, new client)
        router.kill_replica(router.replica_ids()[0])
        (out2,) = router.infer([x], timeout=120.0)
        assert np.array_equal(out2, expect)
        stats = router.stats()
        assert stats["counters"]["replica_crashes"] >= 1
        assert stats["counters"]["respawns"] >= 1
    finally:
        router.close()


def test_socket_fleet_serves_through_net_chaos(tel, no_faults):
    """net_drop + net_reorder armed inside the framing layer: the
    router's per-attempt deadlines and retries absorb every injected
    loss — zero client-visible errors, every answer bit-exact."""
    router = FleetRouter(
        fleet.in_socket("mxnet_tpu.fleet:demo_server_factory"), 1,
        deadline_ms=120000.0, attempt_timeout_ms=2000.0, retries=40,
        backoff_ms=10.0, health_interval_s=60.0, hedge=False)
    try:
        x = _rows(2, seed=9)
        (expect,) = router.infer([x], timeout=120.0)   # pre-chaos truth
        faults.configure("net_drop:0.15,net_reorder:0.2", seed=11)
        outs = []
        for i in range(12):
            (out,) = router.infer([x], request_id="chaos-%d" % i,
                                  timeout=120.0)
            outs.append(out)
        plan = faults._PLAN
        faults.configure(None)
        assert all(np.array_equal(o, expect) for o in outs)
        assert sum(plan.injected.values()) >= 1    # chaos actually fired
    finally:
        faults.configure(None)
        router.close()


def test_socket_replica_refresh_remote_mode_and_in_flight():
    rep = fleet.SocketReplica("s0",
                              "mxnet_tpu.fleet:demo_server_factory")
    try:
        x = _rows(1, seed=3)
        w = rep.submit([x], request_id="r1", deadline_ms=60000.0,
                       priority="interactive")
        (out,) = w.wait(60.0)
        assert out.shape == (1, CLASSES)
        assert rep.in_flight() == 0
        rep.refresh_params()                       # round-trips "ok"
        assert rep.alive()
        # remote mode: an explicit port attaches to the SAME child with
        # no lifecycle ownership — kill/restart refuse, close only
        # drops connections
        remote = fleet.SocketReplica("far", host="127.0.0.1",
                                     port=rep._port)
        try:
            assert remote.health()["status"] == "ok"
            with pytest.raises(MXNetError, match="remote"):
                remote.kill()
            with pytest.raises(MXNetError, match="remote"):
                remote.restart()
        finally:
            remote.close()
        assert rep.alive()                         # owner unaffected
    finally:
        rep.close()
    assert not rep.alive()


# ---------------------------------------------------------------------------
# reader-death accounting: unexpected != EOF
# ---------------------------------------------------------------------------

def _bare_subprocess_replica():
    """A SubprocessReplica shell with no child process — just enough
    state (rid, lock, pending table) to drive _read_loop/_send
    directly."""
    r = fleet.SubprocessReplica.__new__(fleet.SubprocessReplica)
    r.rid = "r-test"
    r._lock = threading.Lock()
    r._pending = {}
    r._dead = False
    r._closed = False
    return r


def test_unexpected_reader_death_is_counted_not_masked(tel):
    """A reader thread killed by a malformed reply (not EOF) counts
    ``fleet.reader_errors`` — it pages as a bug instead of
    masquerading as an ordinary replica crash — and still fails the
    pending waiters so no caller hangs."""
    class _MalformedConn:
        def recv(self):
            return 7   # not a (kind, mid, payload) tuple

    r = _bare_subprocess_replica()
    w = fleet._PendingWaiter()
    r._pending["m1"] = w
    r._read_loop(_MalformedConn())
    assert tel.peek("fleet.reader_errors") == 1
    with pytest.raises(ReplicaCrash):
        w.wait(0.1)
    assert r._dead


def test_clean_reader_eof_is_not_a_reader_error(tel):
    class _EOFConn:
        def recv(self):
            raise EOFError

    r = _bare_subprocess_replica()
    r._read_loop(_EOFConn())
    assert not tel.peek("fleet.reader_errors")
    assert r._dead


def test_send_valueerror_surfaces_as_bug_not_crash(tel):
    """An unpicklable/oversized payload raising ValueError in send()
    must reach the caller as ValueError — NOT be masked as a dead pipe
    that sends the router respawning a healthy replica."""
    class _BadSendConn:
        def send(self, msg):
            raise ValueError("payload too large to pickle")

    class _AliveProc:
        def is_alive(self):
            return True

    r = _bare_subprocess_replica()
    r._proc = _AliveProc()
    r._conn = _BadSendConn()
    with pytest.raises(ValueError, match="too large"):
        r._send("infer", (None,))
    assert not r._dead                  # still healthy
    assert r._pending == {}             # no leaked pending entry

    class _DeadPipeConn:
        def send(self, msg):
            raise BrokenPipeError

    r2 = _bare_subprocess_replica()
    r2._proc = _AliveProc()
    r2._conn = _DeadPipeConn()
    with pytest.raises(ReplicaCrash):
        r2._send("infer", (None,))
    assert r2._dead


# ---------------------------------------------------------------------------
# deadline-budget envelope: the scheduling envelope every attempt ships
# ---------------------------------------------------------------------------

def test_retry_envelope_carries_remaining_budget_not_fresh():
    """A retried attempt submits with the REMAINING deadline budget in
    its envelope, not the original one — a request can't double-spend
    its slack across replicas."""
    clock = FakeClock()
    router, made = _fake_router(["hang", "hang"], clock=clock,
                                deadline_ms=500.0,
                                attempt_timeout_ms=300.0, retries=10,
                                backoff_ms=10.0, hedge=False)
    try:
        with pytest.raises(DeadlineExceeded):
            router._serve([_rows(1)], None, "req-env", 0.5,
                          priority="batch")
        envs = [e for r in made.values() for e in r.envelopes]
        assert len(envs) >= 2
        for rid, dl, prio in envs:
            assert rid == "req-env"
            assert prio == "batch"
            assert dl <= 500.0 + 1e-9
        deadlines = sorted((dl for _, dl, _ in envs), reverse=True)
        # first attempt gets the full attempt timeout; the retry only
        # what the first one left behind (300ms attempt + backoff gone)
        assert deadlines[0] == pytest.approx(300.0)
        assert deadlines[1] < 200.0
    finally:
        router.close()


class _RecordingServer:
    """Duck-typed InferenceServer: records each submit envelope."""

    def __init__(self):
        self.calls = []
        self.closed = False

    def submit(self, arrays, request_id=None, deadline_ms=None,
               priority=None):
        self.calls.append((request_id, deadline_ms, priority))
        outs = [np.asarray(a) * 2.0 for a in arrays]

        class _Done:
            def get(self, timeout=None):
                return outs

            def done(self):
                return True

        return _Done()

    def close(self):
        self.closed = True


def test_inproc_replica_passes_envelope_through():
    srv = _RecordingServer()
    rep = fleet.InProcReplica("r0", lambda: srv)
    x = _rows(1, seed=9)
    w = rep.submit([x], request_id="rid-1", deadline_ms=42.0,
                   priority="batch")
    (out,) = w.wait(1.0)
    assert np.array_equal(out, x * 2.0)
    assert srv.calls == [("rid-1", 42.0, "batch")]
    rep.close()


def test_subprocess_wire_envelope_layout():
    """The parent-side wire message carries (op, mid, request_id,
    arrays, deadline_ms, priority) — the layout the child handler (and
    any older child that ignores the tail fields) decodes."""
    sent = []

    class _FakeConn:
        def send(self, msg):
            sent.append(msg)

    rep = fleet.SubprocessReplica.__new__(fleet.SubprocessReplica)
    rep.rid = "r0"
    rep._lock = threading.Lock()
    rep._dead = False
    rep._closed = False
    rep._pending = {}
    rep._conn = _FakeConn()
    rep._proc = type("P", (), {"is_alive": staticmethod(lambda: True)})()
    x = _rows(1, seed=4)
    rep.submit([x], request_id="rid-2", deadline_ms=77.0,
               priority="interactive")
    assert len(sent) == 1
    op, mid, request_id, arrays, deadline_ms, priority = sent[0]
    assert op == "infer"
    assert request_id == "rid-2"
    assert np.array_equal(arrays[0], x)
    assert deadline_ms == 77.0
    assert priority == "interactive"
