"""Pallas kernel + RTC tests (interpret mode on CPU; the same code paths
compile natively on TPU)."""
import os

import jax.numpy as jnp

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.ops.pallas_kernels import fused_linear, pallas_available

pytestmark = pytest.mark.skipif(not pallas_available(),
                                reason="pallas unavailable")


def test_fused_linear_matches_xla():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(128, 256).astype(np.float32))
    w = jnp.asarray(rng.randn(128, 256).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))
    out = fused_linear(x, w, b)
    assert out is not None
    expected = np.asarray(x) @ np.asarray(w).T + np.asarray(b)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4,
                               atol=1e-4)
    # fused relu epilogue
    out_relu = fused_linear(x, w, b, act="relu")
    np.testing.assert_allclose(np.asarray(out_relu),
                               np.maximum(expected, 0), rtol=1e-4, atol=1e-4)


def test_fused_linear_gradients():
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(128, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(128, 128).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))

    def loss_pallas(x, w, b):
        return jnp.sum(fused_linear(x, w, b, act="relu") ** 2)

    def loss_ref(x, w, b):
        return jnp.sum(jnp.maximum(x @ w.T + b, 0) ** 2)

    g1 = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, w, b)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-3,
                                   atol=1e-3)


def test_fused_linear_misaligned_falls_back():
    import jax.numpy as jnp

    x = jnp.zeros((5, 7), jnp.float32)
    w = jnp.zeros((3, 7), jnp.float32)
    assert fused_linear(x, w) is None


def test_fused_linear_matches_fc():
    """fused_linear stays correct even though the FC hot path is XLA
    (the MXNET_TPU_PALLAS gate was retired on measured data —
    docs/pallas.md)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    x = rng.randn(128, 256).astype(np.float32)
    w = rng.randn(128, 256).astype(np.float32)
    b = rng.randn(128).astype(np.float32)
    out = fused_linear(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    assert out is not None
    np.testing.assert_allclose(np.asarray(out), x @ w.T + b, rtol=1e-4,
                               atol=1e-3)


def test_rtc_kernel():
    from mxnet_tpu.rtc import Rtc

    x = mx.nd.array(np.arange(64, dtype=np.float32).reshape(8, 8))
    y = mx.nd.ones((8, 8))
    out = mx.nd.zeros((8, 8))
    rtc = Rtc("axpy", [("x", x), ("y", y)], [("out", out)],
              "out_ref[:] = 2.0 * x_ref[:] + y_ref[:]")
    rtc.push([x, y], [out])
    np.testing.assert_allclose(out.asnumpy(),
                               2 * x.asnumpy() + 1, rtol=1e-6)


def test_rtc_multiline_kernel():
    from mxnet_tpu.rtc import Rtc

    x = mx.nd.array(np.random.randn(16, 16).astype(np.float32))
    out = mx.nd.zeros((16, 16))
    rtc = Rtc("gelu_ish",
              [("x", x)], [("out", out)],
              "v = x_ref[:]\n"
              "out_ref[:] = v * jax.nn.sigmoid(1.702 * v)")
    rtc.push([x], [out])
    v = x.asnumpy()
    np.testing.assert_allclose(out.asnumpy(),
                               v / (1 + np.exp(-1.702 * v)), rtol=1e-4)


def test_rtc_bad_source():
    from mxnet_tpu.rtc import Rtc

    x = mx.nd.ones((4, 4))
    out = mx.nd.zeros((4, 4))
    with pytest.raises(Exception):
        Rtc("bad", [("x", x)], [("out", out)], "this is not python !!!")


def test_flash_attention_matches_reference():
    from mxnet_tpu.ops import pallas_kernels as pk
    from mxnet_tpu.parallel.ring_attention import reference_attention

    rng = np.random.RandomState(0)
    B, T, H, D = 2, 256, 2, 64
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    for causal in (False, True):
        out = pk.flash_attention(q, k, v, causal=causal)
        assert out is not None
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


def test_flash_attention_grads():
    import jax
    from mxnet_tpu.ops import pallas_kernels as pk
    from mxnet_tpu.parallel.ring_attention import reference_attention

    rng = np.random.RandomState(1)
    B, T, H, D = 1, 128, 2, 32
    q = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    g = jax.grad(lambda q, k, v: (pk.flash_attention(q, k, v, causal=True)
                                  ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: (reference_attention(q, k, v, causal=True)
                                   ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_attention_fallback():
    from mxnet_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(2)
    # T not a multiple of the block -> caller must fall back
    q = jnp.asarray(rng.randn(1, 100, 2, 32).astype(np.float32))
    assert pk.flash_attention(q, q, q) is None
