"""Device observability plane (mxnet_tpu/xprof.py): compile registry
records with real cost/memory analysis on CPU, retrace-cause diffs that
name the changed argument, op-category FLOP attribution, HBM watermark,
pre-flight OOM check, and the zero-overhead guarantee for the fused
step (instrumentation must not add dispatches)."""
import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu import telemetry, xprof
from mxnet_tpu.base import MXNetError
from mxnet_tpu.module import Module

BATCH = 8
DIM = 6
CLASSES = 3


@pytest.fixture
def xp():
    prev = xprof._override
    xprof.enable()
    xprof.reset()
    telemetry.reset()
    telemetry.enable()
    yield xprof
    xprof.reset()
    xprof._override = prev
    telemetry.reset()
    telemetry.disable()


# ---------------------------------------------------------------------------
# compile registry
# ---------------------------------------------------------------------------

def test_compile_record_nonzero_flops_on_cpu(xp):
    f = xprof.jit(lambda a, b: jnp.dot(a, b) + 1.0, site="t.matmul",
                  arg_names=("a", "b"))
    a = np.ones((8, 6), np.float32)
    b = np.ones((6, 4), np.float32)
    np.testing.assert_allclose(np.asarray(f(a, b)), a.dot(b) + 1.0)
    recs = [r for r in xprof.records() if r.site == "t.matmul"]
    assert len(recs) == 1
    r = recs[0]
    assert r.compile_time_s > 0
    assert r.flops and r.flops > 0          # cost_analysis on CPU
    assert r.peak_bytes and r.peak_bytes > 0  # memory_analysis on CPU
    assert r.retrace_cause is None  # first compile: nothing to diff
    assert telemetry.peek("compile.count") == 1
    assert (telemetry.peek("compile.time_ms", kind="hist_sum") or 0) > 0


def test_same_shapes_reuse_executable(xp):
    f = xprof.jit(lambda a: a * 2.0, site="t.reuse", arg_names=("a",))
    x = np.ones((4, 4), np.float32)
    f(x)
    f(np.zeros((4, 4), np.float32))  # same avals: no second compile
    assert len([r for r in xprof.records() if r.site == "t.reuse"]) == 1


def test_retrace_cause_names_changed_aval(xp):
    f = xprof.jit(lambda a: jnp.sum(a * a), site="t.retrace",
                  arg_names=("batch.data",))
    f(np.ones((8, 6), np.float32))
    f(np.ones((4, 6), np.float32))
    recs = [r for r in xprof.records() if r.site == "t.retrace"]
    assert len(recs) == 2
    cause = recs[1].retrace_cause
    assert "batch.data" in cause
    assert "(8,6)" in cause and "(4,6)" in cause
    assert "batch.data" in (xprof.last_retrace_cause() or "")


def test_recompile_detector_event_carries_cause(xp):
    from mxnet_tpu import tracing

    f = xprof.jit(lambda a: a + 1.0, site="t.cause", arg_names=("x",))
    f(np.ones((8,), np.float32))
    f(np.ones((4,), np.float32))  # seeds _last_cause with "on x"
    det = tracing.RecompileDetector(warmup=0)
    ev = det.check({"step": 5, "latency_ms": 80.0,
                    "deltas": {"compiles": 1}})
    assert ev is not None and ev["compiles"] == 1
    assert "on x" in ev.get("cause", "")


def test_tracing_marks_compile_dominant(xp):
    from mxnet_tpu import tracing

    fields = [f for f, _m, _k in tracing.DELTA_SOURCES]
    assert "compiles" in fields and "compile_ms" in fields
    assert tracing.StepTrace._dominant({"compiles": 1}, 50.0) == "compile"


# ---------------------------------------------------------------------------
# op-category attribution
# ---------------------------------------------------------------------------

_HLO = """\
HloModule m

ENTRY %main (a: f32[8,6], b: f32[6,4], i: f32[1,3,8,8], k: f32[4,3,3,3]) -> (f32[8,4], f32[1,4,6,6]) {
  %a = f32[8,6]{1,0} parameter(0)
  %b = f32[6,4]{1,0} parameter(1)
  %i = f32[1,3,8,8]{3,2,1,0} parameter(2)
  %k = f32[4,3,3,3]{3,2,1,0} parameter(3)
  %dot = f32[8,4]{1,0} dot(f32[8,6]{1,0} %a, f32[6,4]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %conv = f32[1,4,6,6]{3,2,1,0} convolution(f32[1,3,8,8]{3,2,1,0} %i, f32[4,3,3,3]{3,2,1,0} %k), window={size=3x3}, dim_labels=bf01_oi01->bf01, feature_group_count=1
  ROOT %out = (f32[8,4], f32[1,4,6,6]) tuple(%dot, %conv)
}
"""


def test_op_breakdown_analytic_model_and_sum():
    bd = xprof.hlo_op_breakdown(_HLO)
    # dot (8,6)x(6,4): 2*8*4*6; conv out (1,4,6,6), 3x3 kernel, Cin=3
    assert bd["dot"]["flops"] == 2 * 8 * 4 * 6
    assert bd["conv"]["flops"] == 2 * (4 * 6 * 6) * 9 * 3
    total = sum(v["flops"] for v in bd.values())
    assert total == bd["dot"]["flops"] + bd["conv"]["flops"]
    for cat in bd:
        assert cat in xprof.CATEGORIES


def test_real_executable_breakdown_sums_to_total(xp):
    f = xprof.jit(lambda a, b: jnp.tanh(jnp.dot(a, b)), site="t.ops",
                  arg_names=("a", "b"))
    f(np.ones((8, 6), np.float32), np.ones((6, 4), np.float32))
    r = [r for r in xprof.records() if r.site == "t.ops"][0]
    assert r.op_breakdown, "MXNET_TPU_XPROF_OPS default-on"
    total = sum(v["flops"] for v in r.op_breakdown.values())
    assert total > 0
    assert r.op_breakdown.get("dot", {}).get("flops", 0) > 0
    assert set(r.op_breakdown) <= set(xprof.CATEGORIES)


def test_analyze_roofline_classification():
    # v5e ridge = 197e12 / 819e9 ≈ 240 FLOP/B
    hi = xprof.analyze(1e12, 1e9, step_time_s=0.01, device_kind="v5e")
    assert hi["bound"] == "compute"
    assert hi["analytic_mfu_pct"] > 0
    lo = xprof.analyze(1e9, 1e9, device_kind="v5e")
    assert lo["bound"] == "bandwidth"
    cpu = xprof.analyze(1e9, 1e9, step_time_s=0.1)  # unknown chip
    assert cpu["analytic_mfu_pct"] == 0.0
    assert cpu["bound"] == "unknown"


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

def test_hbm_watermark_monotone_within_step(xp):
    wm = xprof.HbmWatermark()
    wm.sample()
    peaks = [wm.peak]
    keep = []
    for i in range(3):
        keep.append(jnp.ones((64, 64), jnp.float32) * i)
        wm.sample()
        peaks.append(wm.peak)
    assert all(b >= a for a, b in zip(peaks, peaks[1:]))
    assert peaks[-1] > 0
    stats = xprof.hbm_stats()
    assert stats["source"] in ("memory_stats", "live_arrays")
    del keep


def test_preflight_refuses_impossible_config(xp):
    with pytest.raises(MXNetError, match="pre-flight OOM"):
        xprof.preflight_check(10 << 30, limit_bytes=1 << 30,
                              what="test step")
    # fits: returns the headroom
    assert xprof.preflight_check(1 << 20, limit_bytes=1 << 30) > 0
    # no limit known (CPU): advisory no-op
    assert xprof.preflight_check(10 << 30, limit_bytes=None) is None


# ---------------------------------------------------------------------------
# fused-step regression: observability must be free
# ---------------------------------------------------------------------------

def _mlp_sym():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_fused_step_instrumented_still_one_dispatch(xp, monkeypatch):
    """The AOT wrapper dispatches the cached executable directly — with
    xprof ON, dispatches-per-step must stay exactly 1.0 and the compile
    registry must hold the fused_step record with real FLOPs."""
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    nbatches = 4
    rng = np.random.RandomState(0)
    X = rng.randn(BATCH * nbatches, DIM).astype(np.float32)
    y = rng.randint(0, CLASSES, (BATCH * nbatches,)).astype(np.float32)
    data = mx.io.NDArrayIter(X, y, batch_size=BATCH)
    mod = Module(_mlp_sym(), context=mx.cpu())
    before = telemetry.peek("step.dispatches") or 0
    mod.fit(data, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    assert mod._fused_step_active
    delta = (telemetry.peek("step.dispatches") or 0) - before
    assert delta / float(nbatches) == 1.0
    recs = [r for r in xprof.records() if r.site == "fused_step"]
    assert len(recs) == 1
    assert recs[0].flops and recs[0].flops > 0
    # the fused retrace diff speaks executor language: batch.* / params.*
    sig_names = [n for n, _a in recs[0].signature]
    assert any(n.startswith("batch.") for n in sig_names)
    assert any(n.startswith("params.") for n in sig_names)


def test_disabled_xprof_records_nothing():
    prev = xprof._override
    try:
        xprof.disable()
        xprof.reset()
        f = xprof.jit(lambda a: a + 1, site="t.off")
        f(np.ones((2,), np.float32))
        assert xprof.records() == []
    finally:
        xprof._override = prev
        xprof.reset()
