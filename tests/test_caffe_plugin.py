"""Caffe plugin bridge (reference plugin/caffe): CaffeOp/CaffeLoss with
the reference's prototxt-driven parameterization, emulated layer zoo
validated against numpy closed forms and trained end-to-end."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.plugins.caffe_op import parse_prototxt


def test_parse_prototxt():
    cfg = parse_prototxt(
        'layer{type:"InnerProduct" inner_product_param{num_output: 128} }')
    assert cfg["type"] == "InnerProduct"
    assert cfg["inner_product_param"]["num_output"] == 128
    cfg = parse_prototxt('layer{type:"Pooling" pooling_param{pool: MAX '
                         'kernel_size: 2 stride: 2}}')
    assert cfg["pooling_param"]["pool"] == "MAX"
    cfg = parse_prototxt('layer{type:"Dropout" '
                         'dropout_param{dropout_ratio: 0.25}}')
    assert cfg["dropout_param"]["dropout_ratio"] == 0.25


def test_caffe_innerproduct_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)
    w = rng.randn(3, 6).astype(np.float32)   # caffe layout (out, in)
    b = rng.randn(3).astype(np.float32)
    s = sym.CaffeOp(data_0=sym.Variable("data_0"), num_weight=2, name="ip",
                    prototxt='layer{type:"InnerProduct" '
                             'inner_product_param{num_output: 3}}')
    arg_shapes, out_shapes, _ = s.infer_shape(data_0=(4, 6))
    assert out_shapes[0] == (4, 3)
    assert arg_shapes[1] == (3, 6) and arg_shapes[2] == (3,)
    args = {"data_0": mx.nd.array(x), "ip_0_weight": mx.nd.array(w),
            "ip_1_bias": mx.nd.array(b)}
    ex = s.bind(mx.cpu(), args, grad_req="null")
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x @ w.T + b,
                               rtol=1e-5)


def test_caffe_activations_and_softmax():
    rng = np.random.RandomState(1)
    x = rng.randn(3, 5).astype(np.float32)
    for ltype, fn in [("TanH", np.tanh),
                      ("ReLU", lambda v: np.maximum(v, 0)),
                      ("Sigmoid", lambda v: 1 / (1 + np.exp(-v))),
                      ("AbsVal", np.abs)]:
        s = sym.CaffeOp(data_0=sym.Variable("data_0"),
                        prototxt='layer{type:"%s"}' % ltype)
        ex = s.bind(mx.cpu(), {"data_0": mx.nd.array(x)}, grad_req="null")
        ex.forward(is_train=False)
        np.testing.assert_allclose(ex.outputs[0].asnumpy(), fn(x),
                                   rtol=1e-5, err_msg=ltype)
    s = sym.CaffeOp(data_0=sym.Variable("data_0"),
                    prototxt='layer{type:"Softmax"}')
    ex = s.bind(mx.cpu(), {"data_0": mx.nd.array(x)}, grad_req="null")
    ex.forward(is_train=False)
    e = np.exp(x - x.max(axis=1, keepdims=True))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               e / e.sum(axis=1, keepdims=True), rtol=1e-5)


def test_caffe_pooling_and_convolution():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    s = sym.CaffeOp(data_0=sym.Variable("data_0"),
                    prototxt='layer{type:"Pooling" pooling_param{'
                             'pool: MAX kernel_size: 2 stride: 2}}')
    _, out_shapes, _ = s.infer_shape(data_0=(1, 2, 6, 6))
    assert out_shapes[0] == (1, 2, 3, 3)
    ex = s.bind(mx.cpu(), {"data_0": mx.nd.array(x)}, grad_req="null")
    ex.forward(is_train=False)
    expected = x.reshape(1, 2, 3, 2, 3, 2).max(axis=(3, 5))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), expected, rtol=1e-6)

    w = rng.randn(4, 2, 3, 3).astype(np.float32)
    s = sym.CaffeOp(data_0=sym.Variable("data_0"), num_weight=2, name="cv",
                    prototxt='layer{type:"Convolution" convolution_param{'
                             'num_output: 4 kernel_size: 3 pad: 1}}')
    arg_shapes, out_shapes, _ = s.infer_shape(data_0=(1, 2, 6, 6))
    assert arg_shapes[1] == (4, 2, 3, 3)
    assert out_shapes[0] == (1, 4, 6, 6)
    # cross-check against the native Convolution op
    ref = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3),
                          num_filter=4, pad=(1, 1), no_bias=True,
                          name="ref")
    ex_ref = ref.bind(mx.cpu(), {"data": mx.nd.array(x),
                                 "ref_weight": mx.nd.array(w)},
                      grad_req="null")
    ex_ref.forward(is_train=False)
    b = np.zeros(4, np.float32)
    ex = s.bind(mx.cpu(), {"data_0": mx.nd.array(x),
                           "cv_0_weight": mx.nd.array(w),
                           "cv_1_bias": mx.nd.array(b)}, grad_req="null")
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               ex_ref.outputs[0].asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_caffe_loss_gradient():
    """CaffeLoss(SoftmaxWithLoss): loss value and grad_scale-seeded
    gradient (reference caffe_loss-inl.h:153)."""
    rng = np.random.RandomState(3)
    x = rng.randn(6, 4).astype(np.float32)
    label = rng.randint(0, 4, 6).astype(np.float32)
    s = sym.CaffeLoss(data=sym.Variable("data"), label=sym.Variable("label"),
                      grad_scale=2.0,
                      prototxt='layer{type:"SoftmaxWithLoss"}')
    args = {"data": mx.nd.array(x), "label": mx.nd.array(label)}
    grads = {"data": mx.nd.zeros((6, 4))}
    ex = s.bind(mx.cpu(), args, args_grad=grads,
                grad_req={"data": "write", "label": "null"})
    ex.forward(is_train=True)
    e = np.exp(x - x.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    expected_loss = -np.log(p[np.arange(6), label.astype(int)]).mean()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [expected_loss],
                               rtol=1e-5)
    ex.backward()
    onehot = np.eye(4)[label.astype(int)]
    np.testing.assert_allclose(grads["data"].asnumpy(),
                               2.0 * (p - onehot) / 6, rtol=1e-4,
                               atol=1e-6)


def test_caffe_mlp_trains():
    """The README's caffe_net.py MLP: CaffeOp InnerProduct + TanH stack
    with SoftmaxOutput learns a separable task."""
    rng = np.random.RandomState(4)
    n = 200
    y = rng.randint(0, 2, n).astype(np.float32)
    X = (rng.randn(n, 8).astype(np.float32) * 0.5 + y[:, None])

    data = sym.Variable("data")
    fc1 = sym.CaffeOp(data_0=data, num_weight=2, name="fc1",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 16}}')
    act1 = sym.CaffeOp(data_0=fc1, prototxt='layer{type:"TanH"}')
    fc2 = sym.CaffeOp(data_0=act1, num_weight=2, name="fc2",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 2}}')
    net = sym.SoftmaxOutput(data=fc2, name="softmax")

    mod = mx.mod.Module(net, label_names=["softmax_label"])
    it = mx.io.NDArrayIter(X, y, batch_size=20, shuffle=False,
                           label_name="softmax_label")
    mod.fit(it, num_epoch=10, optimizer_params={"learning_rate": 0.2})
    score = dict(mod.score(mx.io.NDArrayIter(X, y, batch_size=20,
                                             label_name="softmax_label"),
                           "acc"))
    assert score["accuracy"] > 0.95, score


def test_caffe_pooling_pad_clip():
    """caffe's pad-clip rule: (pooled-1)*stride >= dim+pad drops the
    window that would start entirely inside padding."""
    rng = np.random.RandomState(5)
    x = rng.randn(1, 1, 5, 5).astype(np.float32)
    s = sym.CaffeOp(data_0=sym.Variable("data_0"),
                    prototxt='layer{type:"Pooling" pooling_param{'
                             'pool: MAX kernel_size: 2 stride: 2 pad: 1}}')
    _, out_shapes, _ = s.infer_shape(data_0=(1, 1, 5, 5))
    assert out_shapes[0] == (1, 1, 3, 3)        # caffe clips 4 -> 3
    ex = s.bind(mx.cpu(), {"data_0": mx.nd.array(x)}, grad_req="null")
    ex.forward(is_train=False)
    out = ex.outputs[0].asnumpy()
    assert np.isfinite(out).all()               # no -inf rows
    # AVE divides edge windows by the caffe (padded-extent) area
    s = sym.CaffeOp(data_0=sym.Variable("data_0"),
                    prototxt='layer{type:"Pooling" pooling_param{'
                             'pool: AVE kernel_size: 3 stride: 2}}')
    ex = s.bind(mx.cpu(), {"data_0": mx.nd.array(x)}, grad_req="null")
    ex.forward(is_train=False)
    out = ex.outputs[0].asnumpy()
    # output 2x2? h=5,k=3,s=2,pad=0 -> ceil((5-3)/2)+1 = 2 ... exact grid
    np.testing.assert_allclose(out[0, 0, 0, 0], x[0, 0, :3, :3].mean(),
                               rtol=1e-5)


def test_caffe_prototxt_comments_and_floats():
    from mxnet_tpu.plugins.caffe_op import parse_prototxt
    cfg = parse_prototxt('layer{type:"Dropout" # from caffenet\n'
                         'dropout_param{dropout_ratio: .5}}')
    assert cfg["dropout_param"]["dropout_ratio"] == 0.5


def test_caffe_multi_layer_prototxt_rejected():
    with pytest.raises(mx.base.MXNetError, match="ONE layer"):
        sym.CaffeOp(data_0=sym.Variable("d"),
                    prototxt='layer{type:"TanH"} layer{type:"ReLU"}')


def test_caffe_unknown_layer_errors():
    s = sym.CaffeOp(data_0=sym.Variable("d"),
                    prototxt='layer{type:"FancyNewLayer"}')
    with pytest.raises(mx.base.MXNetError, match="no emulation"):
        s.infer_shape(d=(2, 3))
