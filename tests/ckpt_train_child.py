"""Child process for the checkpoint/preemption subprocess tests (not a
test module). Trains the exact-arithmetic linear model with the
checkpoint manager armed via MXNET_TPU_CKPT_* env, appends each step's
(epoch, nbatch, mse-as-hexfloat) to ``$T_DIR/stream.txt``, and — when
``DIE_AT_STEP`` is set — delivers ``DIE_SIG`` (SIGTERM default, or
SIGKILL for the hard-crash tests) to itself after that global step's
batch_end callback. A run that reaches fit() completion writes
``$T_DIR/completed``."""
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import symbol as sym  # noqa: E402
from mxnet_tpu.module import Module  # noqa: E402

TMP = os.environ["T_DIR"]
DIE_AT_STEP = int(os.environ.get("DIE_AT_STEP", "-1"))
DIE_SIG = getattr(signal, os.environ.get("DIE_SIG", "SIGTERM"))
BATCH, DIM, NBATCHES, NUM_EPOCH = 8, 4, 6, 2

net = sym.Variable("data")
net = sym.FullyConnected(net, num_hidden=1, name="fc1")
net = mx.sym.LinearRegressionOutput(net, name="lro")

rng = np.random.RandomState(5)
X = rng.randint(0, 2, (BATCH * NBATCHES, DIM)).astype(np.float32)
y = rng.randint(0, 4, (BATCH * NBATCHES, 1)).astype(np.float32)
data = mx.io.NDArrayIter(X, y, batch_size=BATCH, label_name="lro_label")

arg_shapes, _, _ = net.infer_shape(data=(BATCH, DIM),
                                   lro_label=(BATCH, 1))
prng = np.random.RandomState(9)
arg_params = {name: mx.nd.array(
    (prng.randint(-2, 3, shape) * 0.5).astype(np.float32))
    for name, shape in zip(net.list_arguments(), arg_shapes)
    if name not in ("data", "lro_label")}

mod = Module(net, label_names=("lro_label",))
step = [0]


def cb(param):
    step[0] += 1
    mse = float(dict(param.eval_metric.get_name_value())["mse"])
    with open(os.path.join(TMP, "stream.txt"), "a") as f:
        f.write("%d %d %s\n" % (param.epoch, param.nbatch, mse.hex()))
    if DIE_AT_STEP >= 0 and step[0] == DIE_AT_STEP:
        os.kill(os.getpid(), DIE_SIG)


mod.fit(data, num_epoch=NUM_EPOCH, eval_metric="mse", optimizer="sgd",
        arg_params=arg_params, initializer=None,
        optimizer_params={"learning_rate": 0.5, "momentum": 0.5},
        batch_end_callback=cb)

args_out, _ = mod.get_params()
np.save(os.path.join(TMP, "final_w.npy"),
        args_out["fc1_weight"].asnumpy())
with open(os.path.join(TMP, "completed"), "w") as f:
    f.write("ok")
