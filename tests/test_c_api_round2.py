"""Round-2 C ABI breadth: the reference C API functions added on top of
the round-1 subset — NDArray extras (At/GetData/raw bytes/waits), symbol
file IO / name / print / grad / partial shape inference, the full
executor bind family + monitor callback, the optimizer C surface, Rtc,
KVStore role predicates / RunServer, RecordIO seek/tell, FuncInvokeEx,
and MXCustomOpRegister driven end-to-end through sym.Custom.

Reference analogue: include/mxnet/c_api.h (~110 functions) /
src/c_api/c_api.cc:116-1338.
"""
import ctypes
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LIB = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_predict.so")


def _lib():
    if not shutil.which("make"):
        pytest.skip("no make toolchain")
    r = subprocess.run(["make", "-C", REPO, "predict"], capture_output=True,
                       text=True)
    if r.returncode != 0 or not os.path.exists(LIB):
        pytest.skip("c api build failed: %s" % r.stderr[-500:])
    lib = ctypes.CDLL(LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def _fptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _make_array(lib, np_arr):
    np_arr = np.ascontiguousarray(np_arr, dtype=np.float32)
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint32 * np_arr.ndim)(*np_arr.shape)
    assert lib.MXNDArrayCreate(shape, np_arr.ndim, 1, 0,
                               ctypes.byref(h)) == 0, lib.MXGetLastError()
    flat = np_arr.ravel()
    assert lib.MXNDArraySyncCopyFromCPU(h, _fptr(flat), flat.size) == 0
    return h


def _read_array(lib, h, shape):
    if isinstance(h, int):   # c_void_p-array indexing yields raw ints,
        h = ctypes.c_void_p(h)   # which ctypes would truncate to C int
    out = np.zeros(int(np.prod(shape)), dtype=np.float32)
    assert lib.MXNDArraySyncCopyToCPU(h, _fptr(out), out.size) == 0, \
        lib.MXGetLastError()
    return out.reshape(shape)


def test_ndarray_extras(tmp_path):
    lib = _lib()
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = _make_array(lib, x)

    assert lib.MXNDArrayWaitToRead(h) == 0
    assert lib.MXNDArrayWaitToWrite(h) == 0

    # At: row indexing drops the leading axis
    row = ctypes.c_void_p()
    assert lib.MXNDArrayAt(h, 1, ctypes.byref(row)) == 0, lib.MXGetLastError()
    np.testing.assert_array_equal(_read_array(lib, row, (4,)), x[1])
    assert lib.MXNDArrayFree(row) == 0

    # GetData: host view of the floats
    pdata = ctypes.POINTER(ctypes.c_float)()
    assert lib.MXNDArrayGetData(h, ctypes.byref(pdata)) == 0
    np.testing.assert_array_equal(
        np.array([pdata[i] for i in range(12)], np.float32).reshape(3, 4), x)

    # raw byte round-trip
    size = ctypes.c_size_t()
    buf = ctypes.POINTER(ctypes.c_char)()
    assert lib.MXNDArraySaveRawBytes(h, ctypes.byref(size),
                                     ctypes.byref(buf)) == 0
    blob = ctypes.string_at(buf, size.value)
    h2 = ctypes.c_void_p()
    assert lib.MXNDArrayLoadFromRawBytes(blob, len(blob),
                                         ctypes.byref(h2)) == 0, \
        lib.MXGetLastError()
    np.testing.assert_array_equal(_read_array(lib, h2, (3, 4)), x)
    assert lib.MXNDArrayFree(h2) == 0

    # CreateNone: empty handle is completed by an allocating invoke and
    # rejected (not crashed on) by functions needing an allocated array
    none_h = ctypes.c_void_p()
    assert lib.MXNDArrayCreateNone(ctypes.byref(none_h)) == 0
    assert lib.MXNDArrayWaitToRead(none_h) == -1  # clean error, no crash
    fh = ctypes.c_void_p()
    assert lib.MXGetFunction(b"_mul_scalar", ctypes.byref(fh)) == 0
    use = (ctypes.c_void_p * 1)(h)
    mut = (ctypes.c_void_p * 1)(none_h)
    scal = (ctypes.c_float * 1)(3.0)
    assert lib.MXFuncInvoke(fh, use, scal, mut) == 0, lib.MXGetLastError()
    np.testing.assert_allclose(_read_array(lib, none_h, (3, 4)), x * 3.0)
    assert lib.MXNDArrayFree(none_h) == 0

    assert lib.MXRandomSeed(7) == 0
    assert lib.MXNotifyShutdown() == 0
    assert lib.MXNDArrayFree(h) == 0


def _mlp_json():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="relu")
    return act.tojson(), act


def test_symbol_file_name_print_attr(tmp_path):
    lib = _lib()
    json_str, _ = _mlp_json()
    h = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(json_str.encode(),
                                      ctypes.byref(h)) == 0

    fname = str(tmp_path / "net.json").encode()
    assert lib.MXSymbolSaveToFile(h, fname) == 0
    h2 = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromFile(fname, ctypes.byref(h2)) == 0, \
        lib.MXGetLastError()

    name = ctypes.c_char_p()
    success = ctypes.c_int()
    assert lib.MXSymbolGetName(h2, ctypes.byref(name),
                               ctypes.byref(success)) == 0
    assert success.value == 1 and name.value == b"relu"

    out_str = ctypes.c_char_p()
    assert lib.MXSymbolPrint(h2, ctypes.byref(out_str)) == 0
    dump = out_str.value.decode()
    assert "Variable:data" in dump and "relu" in dump

    assert lib.MXSymbolSetAttr(h2, b"ctx_group", b"dev1") == 0
    n = ctypes.c_uint32()
    flat = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXSymbolListAttrShallow(h2, ctypes.byref(n),
                                       ctypes.byref(flat)) == 0
    pairs = {flat[2 * i]: flat[2 * i + 1] for i in range(n.value)}
    assert pairs.get(b"ctx_group") == b"dev1"
    lib.MXSymbolFree(h)
    lib.MXSymbolFree(h2)


def test_symbol_infer_shape_partial():
    lib = _lib()
    json_str, _ = _mlp_json()
    h = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(json_str.encode(),
                                      ctypes.byref(h)) == 0

    def run(keys_shapes):
        keys = (ctypes.c_char_p * len(keys_shapes))(
            *[k.encode() for k, _ in keys_shapes])
        ind = [0]
        flat = []
        for _, s in keys_shapes:
            flat.extend(s)
            ind.append(len(flat))
        ind_arr = (ctypes.c_uint32 * len(ind))(*ind)
        data_arr = (ctypes.c_uint32 * max(len(flat), 1))(*flat or [0])
        sizes = [ctypes.c_uint32() for _ in range(3)]
        ndims = [ctypes.POINTER(ctypes.c_uint32)() for _ in range(3)]
        datas = [ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32))()
                 for _ in range(3)]
        complete = ctypes.c_int()
        assert lib.MXSymbolInferShapePartial(
            h, len(keys_shapes), keys, ind_arr, data_arr,
            ctypes.byref(sizes[0]), ctypes.byref(ndims[0]),
            ctypes.byref(datas[0]),
            ctypes.byref(sizes[1]), ctypes.byref(ndims[1]),
            ctypes.byref(datas[1]),
            ctypes.byref(sizes[2]), ctypes.byref(ndims[2]),
            ctypes.byref(datas[2]), ctypes.byref(complete)) == 0, \
            lib.MXGetLastError()
        args = [tuple(datas[0][i][d] for d in range(ndims[0][i]))
                for i in range(sizes[0].value)]
        outs = [tuple(datas[1][i][d] for d in range(ndims[1][i]))
                for i in range(sizes[1].value)]
        return args, outs, complete.value

    # nothing known: weight/bias stay unknown, incomplete
    args, outs, complete = run([])
    assert complete == 0
    # data known: everything resolves
    args, outs, complete = run([("data", (2, 5))])
    assert complete == 1
    assert (2, 3) in outs and (3, 5) in args


def test_symbol_grad_matches_python():
    lib = _lib()
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=2, no_bias=True, name="fc")
    h = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(fc.tojson().encode(),
                                      ctypes.byref(h)) == 0
    wrt = (ctypes.c_char_p * 1)(b"fc_weight")
    gh = ctypes.c_void_p()
    assert lib.MXSymbolGrad(h, 1, wrt, ctypes.byref(gh)) == 0, \
        lib.MXGetLastError()
    # bind the grad symbol through MXExecutorSimpleBind and check values
    keys = (ctypes.c_char_p * 1)(b"data")
    ind = (ctypes.c_uint32 * 2)(0, 2)
    shp = (ctypes.c_uint32 * 2)(4, 3)
    eh = ctypes.c_void_p()
    assert lib.MXExecutorSimpleBind(gh, 1, 0, 1, keys, ind, shp, 0,
                                    ctypes.byref(eh)) == 0, \
        lib.MXGetLastError()
    x = np.random.RandomState(0).rand(4, 3).astype(np.float32)
    w = np.random.RandomState(1).rand(2, 3).astype(np.float32)
    assert lib.MXExecutorSetArg(eh, b"data", _fptr(x), x.size) == 0
    assert lib.MXExecutorSetArg(eh, b"fc_weight", _fptr(w), w.size) == 0
    assert lib.MXExecutorForward(eh, 0) == 0, lib.MXGetLastError()
    out = np.zeros(6, dtype=np.float32)
    assert lib.MXExecutorGetOutput(eh, 0, _fptr(out), 6) == 0
    # d(sum(x @ w.T))/dw = ones(4,2).T @ x
    np.testing.assert_allclose(out.reshape(2, 3), np.ones((4, 2)).T @ x,
                               rtol=2e-2)
    lib.MXExecutorFree(eh)
    lib.MXSymbolFree(h)
    lib.MXSymbolFree(gh)


def test_executor_bind_family_and_monitor():
    lib = _lib()
    json_str, sym = _mlp_json()
    h = ctypes.c_void_p()
    assert lib.MXSymbolCreateFromJSON(json_str.encode(),
                                      ctypes.byref(h)) == 0

    rs = np.random.RandomState(0)
    x = rs.rand(2, 5).astype(np.float32)
    w = rs.rand(3, 5).astype(np.float32)
    b = np.zeros(3, np.float32)
    arrs = [x, w, b]
    handles = (ctypes.c_void_p * 3)(*[_make_array(lib, a) for a in arrs])
    grads = (ctypes.c_void_p * 3)(
        *[_make_array(lib, np.zeros_like(a)) for a in arrs])
    reqs = (ctypes.c_uint32 * 3)(1, 1, 1)

    eh = ctypes.c_void_p()
    assert lib.MXExecutorBind(h, 1, 0, 3, handles, grads, reqs, 0, None,
                              ctypes.byref(eh)) == 0, lib.MXGetLastError()

    # monitor callback fires per internal output on forward
    seen = []
    cb_t = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                            ctypes.c_void_p)

    @cb_t
    def monitor(name, arr_handle, user):
        seen.append(name.decode())
        # ownership of the handle transfers to the callback (reference
        # convention) — the callee must free it
        assert lib.MXNDArrayFree(ctypes.c_void_p(arr_handle)) == 0

    assert lib.MXExecutorSetMonitorCallback(eh, monitor, None) == 0, \
        lib.MXGetLastError()

    assert lib.MXExecutorForward(eh, 1) == 0, lib.MXGetLastError()
    out = np.zeros(6, dtype=np.float32)
    assert lib.MXExecutorGetOutput(eh, 0, _fptr(out), 6) == 0
    expected = np.maximum(x @ w.T + b, 0)
    np.testing.assert_allclose(out.reshape(2, 3), expected, rtol=2e-2)
    assert any("fc" in s for s in seen) and any("relu" in s for s in seen)

    assert lib.MXExecutorBackward(eh) == 0, lib.MXGetLastError()
    gw = _read_array(lib, grads[1], (3, 5))
    mask = (x @ w.T + b > 0).astype(np.float32)
    np.testing.assert_allclose(gw, mask.T @ x, rtol=2e-2, atol=1e-4)

    # the gradients also flow back into the arrays passed at bind time
    out_str = ctypes.c_char_p()
    assert lib.MXExecutorPrint(eh, ctypes.byref(out_str)) == 0
    assert b"fc" in out_str.value
    lib.MXExecutorFree(eh)

    # BindX/BindEX accept group2ctx maps (single-device here)
    map_keys = (ctypes.c_char_p * 1)(b"dev1")
    map_types = (ctypes.c_int * 1)(1)
    map_ids = (ctypes.c_int * 1)(0)
    eh2 = ctypes.c_void_p()
    assert lib.MXExecutorBindX(h, 1, 0, 1, map_keys, map_types, map_ids,
                               3, handles, grads, reqs, 0, None,
                               ctypes.byref(eh2)) == 0, lib.MXGetLastError()
    eh3 = ctypes.c_void_p()
    assert lib.MXExecutorBindEX(h, 1, 0, 1, map_keys, map_types, map_ids,
                                3, handles, grads, reqs, 0, None, eh2,
                                ctypes.byref(eh3)) == 0, lib.MXGetLastError()
    lib.MXExecutorFree(eh3)
    lib.MXExecutorFree(eh2)
    lib.MXSymbolFree(h)


def test_optimizer_c_surface():
    lib = _lib()
    creator = ctypes.c_void_p()
    assert lib.MXOptimizerFindCreator(b"sgd", ctypes.byref(creator)) == 0, \
        lib.MXGetLastError()
    keys = (ctypes.c_char_p * 1)(b"momentum")
    vals = (ctypes.c_char_p * 1)(b"0.9")
    oh = ctypes.c_void_p()
    assert lib.MXOptimizerCreateOptimizer(creator, 1, keys, vals,
                                          ctypes.byref(oh)) == 0, \
        lib.MXGetLastError()

    w = np.ones(4, np.float32)
    g = np.full(4, 0.5, np.float32)
    wh = _make_array(lib, w)
    gh = _make_array(lib, g)
    lr, wd = 0.1, 0.0
    assert lib.MXOptimizerUpdate(oh, 0, wh, gh, ctypes.c_float(lr),
                                 ctypes.c_float(wd)) == 0, \
        lib.MXGetLastError()
    got1 = _read_array(lib, wh, (4,))
    # first step: mom = -lr*g
    np.testing.assert_allclose(got1, w - lr * g, rtol=1e-5)
    assert lib.MXOptimizerUpdate(oh, 0, wh, gh, ctypes.c_float(lr),
                                 ctypes.c_float(wd)) == 0
    got2 = _read_array(lib, wh, (4,))
    mom = -lr * g
    mom = 0.9 * mom - lr * g
    np.testing.assert_allclose(got2, got1 + mom, rtol=1e-5)
    assert lib.MXOptimizerFree(oh) == 0
    lib.MXNDArrayFree(wh)
    lib.MXNDArrayFree(gh)

    bad = ctypes.c_void_p()
    assert lib.MXOptimizerFindCreator(b"nonexistent-opt",
                                      ctypes.byref(bad)) == -1


def test_rtc_c_surface():
    lib = _lib()
    a = _make_array(lib, np.arange(8, dtype=np.float32))
    out = _make_array(lib, np.zeros(8, dtype=np.float32))
    in_names = (ctypes.c_char_p * 1)(b"x")
    out_names = (ctypes.c_char_p * 1)(b"y")
    ins = (ctypes.c_void_p * 1)(a)
    outs = (ctypes.c_void_p * 1)(out)
    kernel = b"y_ref[...] = x_ref[...] * 2.0 + 1.0"
    rh = ctypes.c_void_p()
    assert lib.MXRtcCreate(b"double_plus", 1, 1, in_names, out_names,
                           ins, outs, kernel, ctypes.byref(rh)) == 0, \
        lib.MXGetLastError()
    assert lib.MXRtcPush(rh, 1, 1, ins, outs, 1, 1, 1, 1, 1, 1) == 0, \
        lib.MXGetLastError()
    np.testing.assert_allclose(_read_array(lib, out, (8,)),
                               np.arange(8) * 2.0 + 1.0)
    assert lib.MXRtcFree(rh) == 0
    lib.MXNDArrayFree(a)
    lib.MXNDArrayFree(out)


def test_kvstore_roles_and_run_server():
    lib = _lib()
    keys = (ctypes.c_char_p * 1)(b"MXTPU_TEST_PS_VAR")
    vals = (ctypes.c_char_p * 1)(b"42")
    assert lib.MXInitPSEnv(1, keys, vals) == 0
    assert os.environ.get("MXTPU_TEST_PS_VAR") == "42"

    ret = ctypes.c_int()
    assert lib.MXKVStoreIsWorkerNode(ctypes.byref(ret)) == 0
    assert ret.value == 1  # default role
    assert lib.MXKVStoreIsServerNode(ctypes.byref(ret)) == 0
    assert ret.value == 0
    assert lib.MXKVStoreIsSchedulerNode(ctypes.byref(ret)) == 0
    assert ret.value == 0

    kh = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kh)) == 0
    got = []
    ctrl_t = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_char_p,
                              ctypes.c_void_p)

    @ctrl_t
    def controller(head, body, user):
        got.append((head, body.decode()))

    assert lib.MXKVStoreRunServer(kh, controller, None) == 0, \
        lib.MXGetLastError()
    assert lib.MXKVStoreSendCommmandToServers(kh, 3, b"lr=0.01") == 0
    assert got == [(3, "lr=0.01")]
    lib.MXKVStoreFree(kh)


def test_recordio_tell_seek(tmp_path):
    lib = _lib()
    uri = str(tmp_path / "r.rec").encode()
    wh = ctypes.c_void_p()
    assert lib.MXRecordIOWriterCreate(uri, ctypes.byref(wh)) == 0
    positions = []
    for payload in (b"first", b"second", b"third"):
        pos = ctypes.c_size_t()
        assert lib.MXRecordIOWriterTell(ctypes.byref(wh),
                                        ctypes.byref(pos)) == 0
        positions.append(pos.value)
        assert lib.MXRecordIOWriterWriteRecord(wh, payload, len(payload)) == 0
    assert lib.MXRecordIOWriterFree(wh) == 0

    rh = ctypes.c_void_p()
    assert lib.MXRecordIOReaderCreate(uri, ctypes.byref(rh)) == 0
    assert lib.MXRecordIOReaderSeek(ctypes.byref(rh), positions[1]) == 0
    buf = ctypes.POINTER(ctypes.c_char)()
    size = ctypes.c_size_t()
    assert lib.MXRecordIOReaderReadRecord(rh, ctypes.byref(buf),
                                          ctypes.byref(size)) == 0
    assert ctypes.string_at(buf, size.value) == b"second"
    assert lib.MXRecordIOReaderFree(rh) == 0


def test_func_invoke_ex():
    lib = _lib()
    fh = ctypes.c_void_p()
    assert lib.MXGetFunction(b"_plus_scalar", ctypes.byref(fh)) == 0
    a = _make_array(lib, np.arange(4, dtype=np.float32))
    out = _make_array(lib, np.zeros(4, dtype=np.float32))
    use = (ctypes.c_void_p * 1)(a)
    mut = (ctypes.c_void_p * 1)(out)
    scal = (ctypes.c_float * 1)(2.0)
    assert lib.MXFuncInvokeEx(fh, use, scal, mut, 0, None, None) == 0, \
        lib.MXGetLastError()
    np.testing.assert_allclose(_read_array(lib, out, (4,)),
                               np.arange(4) + 2.0)
    # unknown kwargs are rejected like the reference param parser
    keys = (ctypes.c_char_p * 1)(b"bogus")
    vals = (ctypes.c_char_p * 1)(b"1")
    assert lib.MXFuncInvokeEx(fh, use, scal, mut, 1, keys, vals) == -1
    lib.MXNDArrayFree(a)
    lib.MXNDArrayFree(out)


def test_custom_op_register_end_to_end():
    """A C-ABI custom op (creator + forward/backward callbacks handed over
    as function pointers) registered via MXCustomOpRegister and executed
    through sym.Custom, gradients included."""
    lib = _lib()

    fwd_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                             ctypes.POINTER(ctypes.c_void_p),
                             ctypes.POINTER(ctypes.c_int),
                             ctypes.POINTER(ctypes.c_int), ctypes.c_int,
                             ctypes.c_void_p)
    del_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)
    strlist_t = ctypes.CFUNCTYPE(ctypes.c_int,
                                 ctypes.POINTER(ctypes.POINTER(
                                     ctypes.c_char_p)), ctypes.c_void_p)
    shape_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int,
                               ctypes.POINTER(ctypes.c_int),
                               ctypes.POINTER(ctypes.POINTER(ctypes.c_uint)),
                               ctypes.c_void_p)

    class OpInfo(ctypes.Structure):
        _fields_ = [("forward", fwd_t), ("backward", fwd_t), ("del_", del_t),
                    ("p_forward", ctypes.c_void_p),
                    ("p_backward", ctypes.c_void_p),
                    ("p_del", ctypes.c_void_p)]

    create_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint)),
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(OpInfo), ctypes.c_void_p)

    class PropInfo(ctypes.Structure):
        _fields_ = [("list_arguments", strlist_t),
                    ("list_outputs", strlist_t),
                    ("infer_shape", shape_t),
                    ("create_operator", create_t),
                    ("list_auxiliary_states", strlist_t),
                    ("del_", del_t),
                    ("p_list_arguments", ctypes.c_void_p),
                    ("p_list_outputs", ctypes.c_void_p),
                    ("p_infer_shape", ctypes.c_void_p),
                    ("p_create_operator", ctypes.c_void_p),
                    ("p_list_auxiliary_states", ctypes.c_void_p),
                    ("p_del", ctypes.c_void_p)]

    creator_t = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(PropInfo))

    keep = []  # keep every callback/buffer alive for the op's lifetime

    arg_names = (ctypes.c_char_p * 2)(b"data", None)
    out_names = (ctypes.c_char_p * 2)(b"output", None)
    aux_names = (ctypes.c_char_p * 1)(None)

    @strlist_t
    def list_args(out, state):
        ctypes.cast(out, ctypes.POINTER(ctypes.c_void_p))[0] = \
            ctypes.cast(arg_names, ctypes.c_void_p)
        return 1

    @strlist_t
    def list_outs(out, state):
        ctypes.cast(out, ctypes.POINTER(ctypes.c_void_p))[0] = \
            ctypes.cast(out_names, ctypes.c_void_p)
        return 1

    @strlist_t
    def list_aux(out, state):
        ctypes.cast(out, ctypes.POINTER(ctypes.c_void_p))[0] = \
            ctypes.cast(aux_names, ctypes.c_void_p)
        return 1

    @shape_t
    def infer_shape(num, ndims, shapes, state):
        # output shape = input shape (already in slot 0); copy to slot 1
        ndims[1] = ndims[0]
        shapes[1] = shapes[0]
        return 1

    def _copy_to_host(handle):
        ndim = ctypes.c_uint32()
        pshape = ctypes.POINTER(ctypes.c_uint32)()
        lib.MXNDArrayGetShape(handle, ctypes.byref(ndim),
                              ctypes.byref(pshape))
        shape = tuple(pshape[i] for i in range(ndim.value))
        return _read_array(lib, handle, shape)

    def _copy_from_host(handle, arr):
        flat = np.ascontiguousarray(arr, np.float32).ravel()
        assert lib.MXNDArraySyncCopyFromCPU(handle, _fptr(flat),
                                            flat.size) == 0

    @fwd_t
    def forward(size, ptrs, tags, reqs, is_train, state):
        by_tag = {}
        for i in range(size):
            by_tag.setdefault(tags[i], []).append(
                ctypes.c_void_p(ptrs[i]))
        x = _copy_to_host(by_tag[0][0])
        _copy_from_host(by_tag[1][0], x * 2.0)  # y = 2x
        return 1

    @fwd_t
    def backward(size, ptrs, tags, reqs, is_train, state):
        by_tag = {}
        for i in range(size):
            by_tag.setdefault(tags[i], []).append(
                ctypes.c_void_p(ptrs[i]))
        dy = _copy_to_host(by_tag[4][0])
        _copy_from_host(by_tag[3][0], dy * 2.0)  # dx = 2*dy
        return 1

    @del_t
    def deleter(state):
        return 1

    @create_t
    def create_operator(ctx, num_inputs, shapes, ndims, dtypes, ret, state):
        ret[0].forward = forward
        ret[0].backward = backward
        ret[0].del_ = deleter
        return 1

    @creator_t
    def creator(op_type, num_kwargs, keys, vals, ret):
        ret[0].list_arguments = list_args
        ret[0].list_outputs = list_outs
        ret[0].list_auxiliary_states = list_aux
        ret[0].infer_shape = infer_shape
        ret[0].create_operator = create_operator
        ret[0].del_ = deleter
        return 1

    keep.extend([list_args, list_outs, list_aux, infer_shape, forward,
                 backward, deleter, create_operator, creator, arg_names,
                 out_names, aux_names])

    assert lib.MXCustomOpRegister(b"cdouble", creator) == 0, \
        lib.MXGetLastError()

    # drive it through the Python frontend like any registered custom op
    data = mx.sym.Variable("data")
    net = mx.sym.Custom(data, op_type="cdouble", name="cd")
    exe = net.simple_bind(mx.cpu(), data=(2, 3))
    x = np.random.RandomState(0).rand(2, 3).astype(np.float32)
    exe.arg_dict["data"][:] = x
    exe.forward(is_train=True)
    out = exe.outputs[0].asnumpy()
    np.testing.assert_allclose(out, x * 2.0, rtol=1e-5)
    exe.backward([mx.nd.array(np.ones((2, 3), np.float32))])
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               np.full((2, 3), 2.0), rtol=1e-5)
    keep.clear()
