"""Regression tests for review findings."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def test_deconvolution_forward_shape_and_value():
    data = sym.Variable("data")
    deconv = sym.Deconvolution(data=data, kernel=(3, 3), stride=(2, 2),
                               num_filter=1, name="dc", no_bias=True)
    _, out_shapes, _ = deconv.infer_shape(data=(1, 1, 4, 4))
    assert out_shapes == [(1, 1, 9, 9)]
    x = np.zeros((1, 1, 4, 4), dtype=np.float32)
    x[0, 0, 0, 0] = 1.0
    w = np.arange(9).reshape(1, 1, 3, 3).astype(np.float32)
    ex = deconv.bind(mx.cpu(), {"data": mx.nd.array(x),
                                "dc_weight": mx.nd.array(w)}, grad_req="null")
    out = ex.forward()[0].asnumpy()
    assert out.shape == (1, 1, 9, 9)
    # single impulse at (0,0): output top-left 3x3 == kernel
    np.testing.assert_allclose(out[0, 0, :3, :3], w[0, 0])


def test_deconvolution_is_conv_transpose():
    """Deconv must be the transpose of conv: forward deconv == grad of conv
    wrt its input (the defining property)."""
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)  # deconv layout (Cin,Cout,k,k)

    data = sym.Variable("data")
    deconv = sym.Deconvolution(data=data, kernel=(3, 3), stride=(1, 1),
                               pad=(1, 1), num_filter=2, name="dc",
                               no_bias=True)
    ex = deconv.bind(mx.cpu(), {"data": mx.nd.array(x),
                                "dc_weight": mx.nd.array(w)}, grad_req="null")
    out = ex.forward()[0].asnumpy()

    # conv with weight (Cin=2 out-chan view) computing grad wrt input:
    import jax
    import jax.numpy as jnp

    def conv(inp):
        return jax.lax.conv_general_dilated(
            inp, jnp.asarray(w).transpose(0, 1, 2, 3),
            window_strides=(1, 1), padding=[(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    # conv maps (N,2,5,5)->(N,3,5,5) with weight (3,2,3,3) OIHW;
    # its vjp applied to x gives deconv of x
    primal = jnp.zeros((2, 2, 5, 5), dtype=jnp.float32)
    _, vjp = jax.vjp(conv, primal)
    expected = np.asarray(vjp(jnp.asarray(x))[0])
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_deconvolution_grad():
    from mxnet_tpu.test_utils import check_numeric_gradient

    rng = np.random.RandomState(0)
    data = sym.Variable("data")
    deconv = sym.Deconvolution(data=data, kernel=(2, 2), stride=(2, 2),
                               num_filter=2, name="dc", no_bias=True)
    check_numeric_gradient(deconv, {
        "data": rng.randn(1, 2, 3, 3).astype(np.float32),
        "dc_weight": rng.randn(2, 2, 2, 2).astype(np.float32)},
        numeric_eps=1e-2, check_eps=0.06)


def test_expand_dims_negative_axis():
    data = sym.Variable("data")
    s = sym.expand_dims(data=data, axis=-1)
    arg_shapes, out_shapes, _ = s.infer_shape(data=(2, 3))
    assert out_shapes == [(2, 3, 1)]
    ex = s.bind(mx.cpu(), {"data": mx.nd.ones((2, 3))}, grad_req="null")
    assert ex.forward()[0].shape == (2, 3, 1)


def test_optimizer_states_pickle_roundtrip(tmp_path):
    from mxnet_tpu import optimizer as opt

    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    updater = opt.get_updater(sgd)
    w = mx.nd.ones((3, 3))
    updater(0, mx.nd.ones((3, 3)), w)
    blob = updater.get_states()
    updater2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    updater2.set_states(blob)
    np.testing.assert_allclose(updater2.states[0].asnumpy(),
                               updater.states[0].asnumpy())


def test_module_checkpoint_with_optimizer_states(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(40, 5).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.io.NDArrayIter(X, y, batch_size=10)
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(data, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    import os

    assert os.path.exists(prefix + "-0001.states")
    mod.load_optimizer_states(prefix + "-0001.states")


def test_init_params_allow_missing_enforced():
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind([("data", (4, 5))], [("softmax_label", (4,))])
    with pytest.raises(Exception, match="missing arg_param"):
        mod.init_params(arg_params={"fc_weight": mx.nd.ones((2, 5))},
                        allow_missing=False)
    mod.init_params(arg_params={"fc_weight": mx.nd.ones((2, 5))},
                    allow_missing=True)
    arg, _ = mod.get_params()
    np.testing.assert_allclose(arg["fc_weight"].asnumpy(), np.ones((2, 5)))


def test_train_forward_is_lazy():
    """forward(is_train=True) must not dispatch the forward computation —
    the fused fwd+bwd in backward() does it once."""
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 5))
    ret = ex.forward(is_train=True)
    assert ret is None
    assert ex._outputs is None
    ex.backward()
    assert ex._outputs is not None


def test_imperative_op_on_async_pending_input():
    """Registry-generated imperative ops must go through the dependency
    engine: an input whose compute is still queued (ThreadedEngine) has
    _data=None and must not crash."""
    import mxnet_tpu.engine as eng

    old = eng.get_engine()
    eng.set_engine(eng.ThreadedEngine())
    try:
        x = mx.nd.array(np.random.rand(4, 3, 5, 5).astype(np.float32))
        y = x + 1
        z = mx.nd.Flatten(y)
        w = mx.nd.Concat(z, z, num_args=2, dim=1)
        assert w.shape == (4, 150)
        np.testing.assert_allclose(
            w.asnumpy()[:, :75], (x.asnumpy() + 1).reshape(4, 75),
            rtol=1e-6)
    finally:
        eng.set_engine(old)


def test_predict_with_labelless_iterator():
    """FeedForward.predict must not treat the label argument as a missing
    parameter when the iterator provides no labels."""
    X = np.random.rand(32, 5).astype(np.float32)
    y = np.random.randint(0, 2, 32).astype(np.float32)
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    model = mx.model.FeedForward(net, ctx=mx.cpu(), num_epoch=1,
                                 learning_rate=0.1)
    model.fit(X=mx.io.NDArrayIter(X, y, batch_size=8))
    preds = model.predict(mx.io.NDArrayIter(X, None, batch_size=8))
    assert preds.shape == (32, 2)


def test_backward_grad_for_integer_argument_is_zero():
    """Integer-dtype args (e.g. int labels) produce float0 jax tangents;
    backward must map them to zeros, not crash."""
    data = sym.Variable("data")
    emb = sym.Embedding(data, input_dim=6, output_dim=3, name="emb")
    net = sym.MakeLoss(sym.sum(emb * emb))
    ex = net.simple_bind(mx.cpu(), data=(4,),
                         type_dict={"data": np.int32},
                         grad_req={"data": "write", "emb_weight": "write"})
    ex.arg_dict["data"][:] = np.array([0, 1, 2, 3])
    ex.arg_dict["emb_weight"][:] = np.random.rand(6, 3).astype(np.float32)
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               np.zeros(4), atol=0)
    assert np.abs(ex.grad_dict["emb_weight"].asnumpy()).sum() > 0
