"""Multi-host launch story (reference tools/launch.py:32-79 ->
dmlc_tracker ssh launcher): the ssh mode builds per-rank remote
commands with coordinator/rank env propagation, round-robins the
hostfile, and reuses the local launcher's failure detection.

No sshd runs in this image, so a loopback shim stands in for ssh: it
logs the (host, remote-command) pair and executes the command locally
through `sh -c` — exactly what sshd would do — so the whole launcher
path (env propagation, quoting, cd, rendezvous, collectives) executes
for real across 2 processes.
"""
import os
import signal
import stat
import subprocess
import sys

import pytest

from dist_util import REPO

WORKER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
assert rank == int(os.environ["MXTPU_WORKER_RANK"]), "rank env mismatch"
assert nw == 2, nw
# exact push/pull arithmetic across the group
v = mx.nd.array(np.full((4,), float(rank + 1), dtype=np.float32))
kv.init(9, mx.nd.zeros((4,)))
kv.push(9, v)
out = mx.nd.zeros((4,))
kv.pull(9, out)
np.testing.assert_allclose(out.asnumpy(), np.full((4,), 3.0))
print("SSH_WORKER_OK rank=" + str(rank) + " cwd=" + os.getcwd())
"""


def test_ssh_launcher_loopback(tmp_path):
    shim = tmp_path / "fake_ssh"
    log = tmp_path / "ssh_log.txt"
    shim.write_text(
        "#!/bin/sh\n"
        "# drop '-tt' and '-o opt' args, record host + command, run locally\n"
        "while [ \"$1\" = \"-o\" ] || [ \"$1\" = \"-tt\" ]; do\n"
        "  if [ \"$1\" = \"-o\" ]; then shift 2; else shift; fi\n"
        "done\n"
        "host=\"$1\"; shift\n"
        "printf '%s\\t%s\\n' \"$host\" \"$*\" >> " + str(log) + "\n"
        "exec /bin/sh -c \"$*\"\n")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)

    hostfile = tmp_path / "hosts"
    hostfile.write_text("host-a  # first pod host\nhost-b\n")

    workdir = tmp_path / "job"
    workdir.mkdir()
    script = workdir / "worker.py"
    script.write_text(WORKER.replace("%(repo)r", repr(REPO)))

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO
    env["MXTPU_PS_SECRET"] = "hunter2-cluster-token"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "ssh", "-H", str(hostfile),
         "--ssh-cmd", str(shim), "--coordinator", "127.0.0.1:23474",
         "--sync-dir", str(workdir),
         sys.executable, "worker.py"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path), start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        stdout, stderr = proc.communicate()
        raise
    if proc.returncode != 0 and "SSH_WORKER_OK" not in stdout \
            and "distributed" in (stderr or "").lower():
        pytest.skip("jax.distributed unavailable: %s" % stderr[-200:])
    assert proc.returncode == 0, (stdout[-1000:], stderr[-2000:])
    assert stdout.count("SSH_WORKER_OK") == 2, stdout

    lines = log.read_text().strip().splitlines()
    hosts = [l.split("\t")[0] for l in lines]
    assert sorted(hosts) == ["host-a", "host-b"], hosts  # round-robin
    for l in lines:
        cmd = l.split("\t")[1]
        assert "MXTPU_COORDINATOR=127.0.0.1:23474" in cmd
        assert "MXTPU_NUM_WORKERS=2" in cmd
        assert "PYTHONPATH=" in cmd          # forwarded env
        assert "cd %s" % workdir in cmd      # shared-dir assumption
    ranks = sorted(int(l.split("MXTPU_WORKER_RANK=")[1].split()[0])
                   for l in lines)
    assert ranks == [0, 1]

    # the PS shared secret must never ride the (world-readable) ssh
    # argv: it is staged as a 0600 file in the job dir and only its
    # PATH is forwarded (launch.py round-4 hardening)
    for l in lines:
        assert "hunter2-cluster-token" not in l, "secret leaked to argv"
        assert "MXTPU_PS_SECRET_FILE=" in l.split("\t")[1]
    # filename is unique per job (pid.time suffix) so overlapping jobs
    # in one shared dir cannot clobber each other's secret
    secrets = list(workdir.glob(".mxtpu_ps_secret.*"))
    assert len(secrets) == 1, secrets
    assert secrets[0].read_text() == "hunter2-cluster-token"
    assert (secrets[0].stat().st_mode & 0o777) == 0o600
