"""dist_async parameter-server tier (reference
kvstore_dist_server.h:199-207): per-push server-side updates with NO
cross-worker aggregation — workers run at their own pace on
possibly-stale weights. Round-2 left this tier synchronous (documented
divergence); round 3 implements the reference architecture for real
over a host-side TCP server (mxnet_tpu/parallel/ps.py).

Launched through tools/launch.py like every dist tier; needs no
jax.distributed (the async control plane is sockets), so it runs
anywhere.
"""
import pytest

from dist_util import REPO, fill, launch

ASYNC_SCRIPT = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_async")
rank, nw = kv.rank, kv.num_workers
assert nw == 2, nw
assert kv.type == "dist_async"

# ---- semantics: no-optimizer push ASSIGNS (reference DataHandle
# without updater); last writer wins, both writes are valid outcomes
kv.init(0, mx.nd.zeros((3,)))
kv.push(0, mx.nd.array(np.full((3,), float(rank + 1), np.float32)))
kv.barrier()
out = mx.nd.zeros((3,))
kv.pull(0, out)
v = out.asnumpy()[0]
assert v in (1.0, 2.0), v

# ---- server-side optimizer: per-push SGD update, pulls see progress
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
kv.barrier()
kv.init(1, mx.nd.zeros((2,)))
for step in range(5):
    kv.push(1, mx.nd.array(np.ones((2,), np.float32)))
w = mx.nd.zeros((2,))
kv.barrier()
kv.pull(1, w)
# 10 pushes total (5 per worker) of grad=1 with lr 0.5: w = -0.5 * 10
np.testing.assert_allclose(w.asnumpy(), np.full((2,), -5.0), atol=1e-5)

# ---- end-to-end: Module trains with update_on_kvstore through the
# async server (push grad -> server SGD -> pull weights)
rng = np.random.RandomState(0)
n = 256
y = rng.randint(0, 2, n).astype(np.float32)
X = (rng.randn(n, 8).astype(np.float32) * 0.5 + y[:, None])
Xs, ys = X[rank::nw], y[rank::nw]

data = mx.sym.Variable("data")
net = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
net = mx.sym.Activation(data=net, act_type="relu")
net = mx.sym.FullyConnected(data=net, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(data=net, name="softmax")

it = mx.io.NDArrayIter(Xs, ys, batch_size=16, shuffle=False)
mod = mx.mod.Module(net, context=mx.cpu())
# async staleness slows the early epochs (workers descend on
# possibly-stale weights — the reference async mode's known trade);
# 30 epochs converges fully where sync needs ~8
mod.fit(it, num_epoch=30, kvstore=kv,
        optimizer="sgd", optimizer_params={"learning_rate": 0.1})
it.reset()
acc = next(iter(dict(mod.score(it, "acc")).values()))
print("ASYNC rank=%d acc=%.3f" % (rank, acc))
assert acc > 0.9, acc
kv.barrier()
if rank == 0:
    kv.close()
print("ASYNC_OK rank=%d" % rank)
"""


def test_dist_async_two_workers(tmp_path):
    # run the whole tier AUTHENTICATED: the secret propagates through
    # launch.py's local env path and every PS frame carries an HMAC
    # tag (round-4 hardening exercised end to end, not just in-process)
    out = launch(tmp_path, fill(ASYNC_SCRIPT, tmp_path), port=23475,
                 timeout=420,
                 extra_env={"MXTPU_PS_SECRET": "gate-token"})
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-2500:])
    assert out.stdout.count("ASYNC_OK") == 2, out.stdout[-1500:]


def test_set_optimizer_repeat_keeps_state(tmp_path):
    """A late worker's set_optimizer must NOT wipe server-side momentum
    accumulated by earlier pushes (advisor r3 medium finding; the
    reference only sends the command from rank 0). First writer wins."""
    import pickle

    import numpy as np

    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.parallel import ps

    server = ps.ParameterServer("127.0.0.1", 23711, num_workers=1)
    try:
        c = ps.PSClient("127.0.0.1", 23711)
        blob = pickle.dumps(opt_mod.SGD(learning_rate=0.1, momentum=0.9))
        c.call("set_optimizer", blob)
        c.call("init", 0, 0, np.zeros(2, np.float32))
        c.call("push", 0, np.ones(2, np.float32))
        # repeat from a "late worker": must be a no-op on server state
        c.call("set_optimizer", blob)
        c.call("push", 0, np.ones(2, np.float32))
        got = c.call("pull", 0)
        # momentum SGD, mom=0.9 lr=0.1 grad=1: u1=-0.1, u2=0.9*u1-0.1
        want = np.full(2, -0.1 + (0.9 * -0.1 - 0.1), np.float32)
        np.testing.assert_allclose(got, want, atol=1e-6)
        c.close()
    finally:
        server.close()


def test_ps_hmac_framing(monkeypatch):
    """MXTPU_PS_SECRET adds an HMAC tag per frame; a peer with the
    wrong secret cannot get a frame past the unpickler."""
    import numpy as np

    from mxnet_tpu.parallel import ps

    monkeypatch.setenv("MXTPU_PS_SECRET", "cluster-token")
    # the secret resolves once per process; reset the cache so this
    # test's env takes effect (and is restored for later tests)
    monkeypatch.setattr(ps, "_SECRET_CACHE", False)
    server = ps.ParameterServer("127.0.0.1", 23712, num_workers=1)
    try:
        c = ps.PSClient("127.0.0.1", 23712)
        c.call("init", 0, 0, np.arange(3, dtype=np.float32))
        np.testing.assert_allclose(c.call("pull", 0), [0.0, 1.0, 2.0])
        c.close()

        # wrong secret: hand-craft a frame tagged with the wrong key
        # (raw socket — the in-process server reads the env too, so a
        # monkeypatched client would just agree with it). The server
        # must close the connection at the HMAC check, before
        # pickle.loads, never sending an "ok".
        import hashlib
        import hmac as hmac_mod
        import pickle as pkl
        import socket
        import struct

        payload = pkl.dumps(("pull", 0))
        bad_tag = hmac_mod.new(b"wrong-token", payload,
                               hashlib.sha256).digest()
        raw = socket.create_connection(("127.0.0.1", 23712), timeout=10)
        raw.sendall(struct.pack("!Q", len(payload)) + bad_tag + payload)
        assert raw.recv(1) == b"", "server answered a mistagged frame"
        raw.close()

        # server is still healthy for authenticated peers
        c2 = ps.PSClient("127.0.0.1", 23712)
        np.testing.assert_allclose(c2.call("pull", 0), [0.0, 1.0, 2.0])
        c2.close()
    finally:
        server.close()


def test_async_dead_node_detection():
    """Failure-detection parity for the async tier (reference
    KVStore::get_num_dead_node, kvstore_dist.h:149-158): a rank that
    joined the group and then lost its connection is reported dead."""
    import os
    import subprocess
    import sys

    script = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import mxnet_tpu as mx
from mxnet_tpu.parallel import ps

os.environ["MXTPU_COORDINATOR"] = "127.0.0.1:23476"
os.environ["MXTPU_NUM_WORKERS"] = "2"
os.environ["MXTPU_WORKER_RANK"] = "0"
kv = mx.kv.create("dist_async")            # rank 0: hosts server + hello
assert kv.num_dead_node() == 0

host, port = ps.ps_address()
peer = ps.PSClient(host, port)             # rank 1 joins...
peer.call("hello", 1)
assert kv.num_dead_node() == 0
peer.close()                               # ...and dies
import time
deadline = time.time() + 10
while kv.num_dead_node() != 1 and time.time() < deadline:
    time.sleep(0.1)
assert kv.num_dead_node() == 1, kv.num_dead_node()

# graceful leave is NOT a death: a polite rank 2 joins and says bye
peer2 = ps.PSClient(host, port)
peer2.call("hello", 2)
peer2.call("bye", 2)
peer2.close()
time.sleep(0.3)
assert kv.num_dead_node() == 1, kv.num_dead_node()
kv.close()
print("DEAD_NODE_OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        [sys.executable, "-c", fill(script, "")],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr[-1500:])
    assert "DEAD_NODE_OK" in r.stdout


def test_server_refuses_unauthenticated_start(monkeypatch):
    """Default-on frame auth (round-4 verdict #7): with no secret staged
    the server must refuse to start (unauthenticated pickle frames are
    RCE for anyone who can reach the port); MXTPU_PS_INSECURE=1 is the
    explicit opt-out."""
    import pytest

    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.parallel import ps

    monkeypatch.delenv("MXTPU_PS_SECRET", raising=False)
    monkeypatch.delenv("MXTPU_PS_SECRET_FILE", raising=False)
    monkeypatch.delenv("MXTPU_PS_INSECURE", raising=False)
    monkeypatch.setattr(ps, "_SECRET_CACHE", False)
    with pytest.raises(MXNetError, match="refuses to start"):
        ps.ParameterServer("127.0.0.1", 23713, num_workers=1)

    monkeypatch.setenv("MXTPU_PS_INSECURE", "1")
    monkeypatch.setattr(ps, "_SECRET_CACHE", False)
    server = ps.ParameterServer("127.0.0.1", 23713, num_workers=1)
    server.close()


def test_launch_generates_job_secret(monkeypatch):
    """tools/launch.py stages a generated secret when the operator set
    none, so every launched job runs authenticated by default."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "launch_mod", _os.path.join(_os.path.dirname(__file__), "..",
                                    "tools", "launch.py"))
    launch_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(launch_mod)

    monkeypatch.delenv("MXTPU_PS_SECRET", raising=False)
    monkeypatch.delenv("MXTPU_PS_INSECURE", raising=False)
    s = launch_mod.job_secret()
    assert s and len(s) >= 32
    # operator-provided secret wins
    monkeypatch.setenv("MXTPU_PS_SECRET", "operator-token")
    assert launch_mod.job_secret() == "operator-token"
    # explicit opt-out: no generated secret
    monkeypatch.setenv("MXTPU_PS_INSECURE", "1")
    monkeypatch.delenv("MXTPU_PS_SECRET", raising=False)
    assert launch_mod.job_secret() is None
