"""Torch plugin bridge + imperative op unification + monitor/viz/remat
coverage."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def test_torch_module_forward_backward():
    torch = pytest.importorskip("torch")
    from mxnet_tpu.plugins.torch_bridge import torch_module

    lin = torch.nn.Linear(4, 4, bias=False)
    with torch.no_grad():
        lin.weight.copy_(torch.eye(4) * 2.0)

    data = sym.Variable("data")
    out = torch_module(lambda: lin, data, name="t0") * 1.0
    x = np.random.randn(3, 4).astype(np.float32)
    g = mx.nd.zeros((3, 4))
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(x)}, args_grad={"data": g})
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), 2 * x, rtol=1e-5)
    ex.backward()
    np.testing.assert_allclose(g.asnumpy(), np.full((3, 4), 2.0), rtol=1e-5)


def test_torch_criterion():
    torch = pytest.importorskip("torch")
    from mxnet_tpu.plugins.torch_bridge import torch_criterion

    data = sym.Variable("data")
    label = sym.Variable("label")
    loss = torch_criterion(lambda: torch.nn.MSELoss(), data, label,
                           name="mse")
    x = np.array([[1.0, 2.0]], dtype=np.float32)
    y = np.array([[0.0, 0.0]], dtype=np.float32)
    gx = mx.nd.zeros((1, 2))
    ex = loss.bind(mx.cpu(), {"data": mx.nd.array(x), "label": mx.nd.array(y)},
                   args_grad={"data": gx},
                   grad_req={"data": "write", "label": "null"})
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), [2.5], rtol=1e-5)
    ex.backward()
    np.testing.assert_allclose(gx.asnumpy(), x, rtol=1e-5)  # d(mse)/dx = x


def test_imperative_ops_unified():
    """SimpleOp parity: registered symbolic ops callable from mx.nd."""
    x = mx.nd.array(np.random.randn(2, 6).astype(np.float32))
    out = mx.nd.SliceChannel(x, num_outputs=3, axis=1)
    assert isinstance(out, list) and len(out) == 3
    np.testing.assert_allclose(out[0].asnumpy(), x.asnumpy()[:, :2])

    f = mx.nd.Flatten(mx.nd.array(np.ones((2, 3, 4), np.float32)))
    assert f.shape == (2, 12)

    a = mx.nd.array(np.random.rand(4, 4).astype(np.float32))
    sm = mx.nd.SoftmaxActivation(a)
    np.testing.assert_allclose(sm.asnumpy().sum(axis=1), np.ones(4),
                               rtol=1e-5)

    with pytest.raises(Exception, match="auxiliary"):
        mx.nd.BatchNorm(a, mx.nd.ones((4,)), mx.nd.zeros((4,)))


def test_monitor():
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=3, name="fc"), name="sm")
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.arg_dict["data"][:] = np.random.randn(2, 4)
    ex.arg_dict["fc_weight"][:] = np.random.randn(3, 4)
    mon = mx.Monitor(interval=1, pattern=".*fc.*")
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    ex.backward()
    rows = mon.toc()
    names = [k for _, k, _ in rows]
    assert any("fc_output" in n for n in names)
    assert any(n == "fc_weight" for n in names)
    assert any(n == "fc_weight_grad" for n in names)


def test_print_summary(capsys):
    from mxnet_tpu import models

    net = models.get_mlp(10)
    mx.viz.print_summary(net, shape={"data": (1, 784)})
    out = capsys.readouterr().out
    assert "fc1" in out
    assert "Total params" in out
    # 784*128+128 + 128*64+64 + 64*10+10
    assert str(784 * 128 + 128 + 128 * 64 + 64 + 64 * 10 + 10) in out


def test_backward_do_mirror_equivalence():
    """Remat (the mirroring flag) must not change results."""
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=4, name="fc"), name="sm")
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)
    w = rng.randn(4, 6).astype(np.float32)

    def run():
        ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
        ex.arg_dict["data"][:] = x
        ex.arg_dict["fc_weight"][:] = w
        ex.arg_dict["sm_label"][:] = np.array([0, 1, 2, 3], np.float32)
        ex.forward(is_train=True)
        ex.backward()
        return ex.grad_dict["fc_weight"].asnumpy()

    g1 = run()
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1"
    try:
        g2 = run()
    finally:
        del os.environ["MXNET_BACKWARD_DO_MIRROR"]
    np.testing.assert_allclose(g1, g2, rtol=1e-6)


def test_ccsgd_alias():
    from mxnet_tpu import optimizer as opt

    o = opt.create("ccsgd", learning_rate=0.1)
    assert isinstance(o, opt.SGD)


def test_do_checkpoint_callback(tmp_path):
    from mxnet_tpu import models

    prefix = str(tmp_path / "cp")
    rng = np.random.RandomState(0)
    X = rng.randn(40, 5).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.io.NDArrayIter(X, y, batch_size=10)
    net = sym.SoftmaxOutput(sym.FullyConnected(
        sym.Variable("data"), num_hidden=2, name="fc"), name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(data, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            epoch_end_callback=mx.callback.do_checkpoint(prefix))
    assert os.path.exists(prefix + "-0001.params")
    assert os.path.exists(prefix + "-0002.params")
    loaded_sym, arg, aux = mx.model.load_checkpoint(prefix, 2)
    assert "fc_weight" in arg


def test_torch_bridge_int_label_criterion():
    """Integer labels: no requires_grad on int tensors, int32→Long cast,
    float0 label grad mapped to zeros."""
    torch = pytest.importorskip("torch")
    from mxnet_tpu.plugins.torch_bridge import torch_criterion

    data = sym.Variable("data")
    label = sym.Variable("label")
    loss = torch_criterion(lambda: torch.nn.CrossEntropyLoss(), data,
                           label, name="ce_int")
    ex = loss.simple_bind(mx.cpu(), data=(4, 3), label=(4,),
                          type_dict={"label": np.int32})
    ex.arg_dict["data"][:] = np.random.rand(4, 3).astype(np.float32)
    ex.arg_dict["label"][:] = np.array([0, 1, 2, 0])
    ex.forward(is_train=True)
    ex.backward()
    assert np.abs(ex.grad_dict["data"].asnumpy()).sum() > 0
    np.testing.assert_allclose(ex.grad_dict["label"].asnumpy(),
                               np.zeros(4))


def test_torch_bridge_stateful_module_consistency():
    """Dropout masks must match between forward and the backward re-run,
    eval mode must disable dropout, and BatchNorm running stats must not
    be double-updated by backward."""
    torch = pytest.importorskip("torch")
    from mxnet_tpu.plugins.torch_bridge import torch_module

    data = sym.Variable("data")
    net = torch_module(lambda: torch.nn.Dropout(0.5), data,
                       name="torchdrop")
    ex = net.simple_bind(mx.cpu(), data=(64, 8),
                         grad_req={"data": "write"})
    x = np.random.rand(64, 8).astype(np.float32) + 1.0
    ex.arg_dict["data"][:] = x
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones((64, 8)))
    out = ex.outputs[0].asnumpy()
    grad = ex.grad_dict["data"].asnumpy()
    # same mask: grad is 2 exactly where output survived, 0 where dropped
    np.testing.assert_allclose((out != 0).astype(np.float32) * 2.0, grad)
    assert (out == 0).any()  # dropout actually active in train mode

    # eval mode: dropout off → identity
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x, rtol=1e-6)

    # BatchNorm: backward's re-run must not advance running stats again
    bn_holder = {}

    def make_bn():
        bn_holder["m"] = torch.nn.BatchNorm1d(8)
        return bn_holder["m"]

    net2 = torch_module(make_bn, sym.Variable("data"), name="torchbn")
    ex2 = net2.simple_bind(mx.cpu(), data=(16, 8),
                           grad_req={"data": "write"})
    ex2.arg_dict["data"][:] = np.random.rand(16, 8).astype(np.float32)
    ex2.forward(is_train=True)
    _ = ex2.outputs[0].asnumpy()
    mean_after_fwd = bn_holder["m"].running_mean.clone().numpy()
    ex2.forward(is_train=True)
    ex2.backward(mx.nd.ones((16, 8)))
    _ = ex2.grad_dict["data"].asnumpy()
    mean_after_bwd = bn_holder["m"].running_mean.clone().numpy()
    # exactly one more update from the second forward, none from backward
    expect = mean_after_fwd + 0.1 * (
        np.asarray(ex2.arg_dict["data"].asnumpy()).mean(0)
        - mean_after_fwd)
    np.testing.assert_allclose(mean_after_bwd, expect, rtol=1e-5)
