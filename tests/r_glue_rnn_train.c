/* Executes the exact .Call sequence the R RNN tier drives
 * (R-package/R/rnn_model.R mx.rnn.create / mx.rnn.infer.model /
 * mx.rnn.step, behind mx.lstm / mx.lstm.inference / mx.lstm.forward —
 * reference R-package/R/lstm.R:152-361), through the real mxnet_glue.c
 * compiled against tests/r_shim.c. No R interpreter exists in this
 * image, so this is the execution gate for the R RNN tier's native
 * path.
 *
 * Two phases:
 *   train      mx.rnn.train.symbol graph (Embedding -> transpose ->
 *              fused RNN(lstm) -> Reshape -> FC -> SoftmaxOutput with
 *              transposed flat label), trained to next-token accuracy
 *              >= 0.9 on a deterministic cyclic-sequence task with the
 *              optimizer.R SGD-momentum update.
 *   inference  mx.rnn.inference.symbol graph (state_outputs=TRUE, the
 *              new mxr_sym_get_output / mxr_sym_group glue), seq.len=1
 *              executor fed the TRAINED weights, stepped token-by-token
 *              carrying h/c state exactly like mx.rnn.step — gating the
 *              same accuracy.
 *
 * Prints "train_acc=<v> infer_acc=<v>"; the pytest wrapper gates both.
 */
#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "Rinternals.h"

SEXP mxr_sym_variable(SEXP name);
SEXP mxr_sym_create_atomic(SEXP opname, SEXP keys, SEXP vals);
SEXP mxr_sym_compose(SEXP ptr, SEXP name, SEXP keys, SEXP args);
SEXP mxr_sym_infer_shape(SEXP ptr, SEXP keys, SEXP ind, SEXP data);
SEXP mxr_sym_list_arguments(SEXP ptr);
SEXP mxr_sym_list_outputs(SEXP ptr);
SEXP mxr_sym_get_output(SEXP ptr, SEXP index);
SEXP mxr_sym_group(SEXP handles);
SEXP mxr_exec_simple_bind(SEXP sym, SEXP dev_type, SEXP dev_id, SEXP keys,
                          SEXP ind, SEXP data, SEXP for_training);
SEXP mxr_exec_set_arg(SEXP ptr, SEXP name, SEXP values);
SEXP mxr_exec_forward(SEXP ptr, SEXP is_train);
SEXP mxr_exec_backward(SEXP ptr);
SEXP mxr_exec_get_output(SEXP ptr, SEXP index, SEXP size);
SEXP mxr_exec_get_grad(SEXP ptr, SEXP name, SEXP size);
SEXP mxr_random_seed(SEXP seed);
SEXP mxr_nd_create(SEXP shape, SEXP dev_type, SEXP dev_id);
SEXP mxr_nd_set(SEXP ptr, SEXP values);
SEXP mxr_nd_get(SEXP ptr);
SEXP mxr_func_invoke(SEXP name, SEXP use, SEXP scalars, SEXP out);

#define SEQLEN 8
#define BATCH 16
#define VOCAB 8
#define NEMBED 8
#define NHID 16
#define NLAYER 1
#define NSAMPLE 64
#define ROUNDS 60
#define MAXARGS 16

static SEXP ints(int n, const int *v) {
  SEXP s = Rf_allocVector(INTSXP, n);
  for (int i = 0; i < n; ++i) INTEGER(s)[i] = v[i];
  return s;
}
static SEXP int1(int v) { return ints(1, &v); }

static SEXP reals(R_xlen_t n, const double *v) {
  SEXP s = Rf_allocVector(REALSXP, n);
  for (R_xlen_t i = 0; i < n; ++i) REAL(s)[i] = v[i];
  return s;
}

static SEXP strs(int n, const char **v) {
  SEXP s = Rf_allocVector(STRSXP, n);
  for (int i = 0; i < n; ++i) SET_STRING_ELT(s, i, Rf_mkChar(v[i]));
  return s;
}

/* mx.symbol.create(op, <positional data>, params..., name=) */
static SEXP op1(const char *op, SEXP input, const char *name,
                const char **pk, const char **pv, int np) {
  SEXP h = mxr_sym_create_atomic(Rf_mkString(op), strs(np, pk),
                                 strs(np, pv));
  const char *inkeys[] = {"data"};
  SEXP args = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(args, 0, input);
  mxr_sym_compose(h, Rf_mkString(name), strs(1, inkeys), args);
  return h;
}

/* mx.symbol.create("SoftmaxOutput", data=, label=, name=) */
static SEXP softmax_with_label(SEXP data, SEXP label, const char *name) {
  SEXP h = mxr_sym_create_atomic(Rf_mkString("SoftmaxOutput"),
                                 strs(0, NULL), strs(0, NULL));
  const char *inkeys[] = {"data", "label"};
  SEXP args = Rf_allocVector(VECSXP, 2);
  SET_VECTOR_ELT(args, 0, data);
  SET_VECTOR_ELT(args, 1, label);
  mxr_sym_compose(h, Rf_mkString(name), strs(2, inkeys), args);
  return h;
}

static double frand(unsigned *seed) {
  *seed ^= *seed << 13;
  *seed ^= *seed >> 17;
  *seed ^= *seed << 5;
  return (double)(*seed % 1000003) / 1000003.0;
}

/* Embedding -> time-major transpose -> fused RNN (rnn_model.R
 * mx.rnn.train.symbol / mx.rnn.inference.symbol share this trunk) */
static SEXP rnn_trunk(SEXP data, int state_outputs) {
  const char *k_emb[] = {"input_dim", "output_dim"};
  char vocab_s[8], embed_s[8];
  snprintf(vocab_s, sizeof vocab_s, "%d", VOCAB);
  snprintf(embed_s, sizeof embed_s, "%d", NEMBED);
  const char *v_emb[] = {vocab_s, embed_s};
  SEXP embed = op1("Embedding", data, "embed", k_emb, v_emb, 2);
  const char *k_axes[] = {"axes"};
  const char *v_axes[] = {"(1, 0, 2)"};
  SEXP tm = op1("transpose", embed, "tm", k_axes, v_axes, 1);
  const char *k_rnn[] = {"state_size", "num_layers", "mode",
                         "state_outputs"};
  char hid_s[8], lay_s[8];
  snprintf(hid_s, sizeof hid_s, "%d", NHID);
  snprintf(lay_s, sizeof lay_s, "%d", NLAYER);
  const char *v_rnn[] = {hid_s, lay_s, "lstm",
                         state_outputs ? "True" : "False"};
  return op1("RNN", tm, "rnn", k_rnn, v_rnn, 4);
}

static SEXP head_over(SEXP hidden_flat_input, const char *reshape_name) {
  const char *k_shape[] = {"shape"};
  char shp[24];
  snprintf(shp, sizeof shp, "(-1, %d)", NHID);
  const char *v_shape[] = {shp};
  SEXP flat = op1("Reshape", hidden_flat_input, reshape_name,
                  k_shape, v_shape, 1);
  const char *k_hid[] = {"num_hidden"};
  char vocab_s[8];
  snprintf(vocab_s, sizeof vocab_s, "%d", VOCAB);
  const char *v_hid[] = {vocab_s};
  return op1("FullyConnected", flat, "cls", k_hid, v_hid, 1);
}

int main(void) {
  mxr_random_seed(int1(11));

  /* ---- training symbol (mx.rnn.train.symbol) ---- */
  SEXP data = mxr_sym_variable(Rf_mkString("data"));
  SEXP label = mxr_sym_variable(Rf_mkString("label"));
  SEXP rnn = rnn_trunk(data, 0);
  SEXP fc = head_over(rnn, "flat");
  const char *k_axes2[] = {"axes"};
  const char *v_axes2[] = {"(1, 0)"};
  SEXP lab_t = op1("transpose", label, "lab_t", k_axes2, v_axes2, 1);
  const char *k_shape1[] = {"shape"};
  const char *v_shape1[] = {"-1"};
  SEXP lab = op1("Reshape", lab_t, "lab", k_shape1, v_shape1, 1);
  SEXP net = softmax_with_label(fc, lab, "sm");

  /* ---- infer shapes (C-order; the R side revs before this call) --- */
  const char *skeys[] = {"data", "label", "rnn_state", "rnn_state_cell"};
  int ind[] = {0, 2, 4, 7, 10};
  int sdata[] = {BATCH, SEQLEN, BATCH, SEQLEN,
                 NLAYER, BATCH, NHID, NLAYER, BATCH, NHID};
  SEXP shapes = mxr_sym_infer_shape(net, strs(4, skeys), ints(5, ind),
                                    ints(10, sdata));
  SEXP arg_shapes = VECTOR_ELT(shapes, 0);
  SEXP arg_names = mxr_sym_list_arguments(net);
  int nargs = Rf_length(arg_names);
  if (nargs > MAXARGS) { fprintf(stderr, "too many args\n"); return 1; }

  SEXP exec = mxr_exec_simple_bind(net, int1(1), int1(0), strs(4, skeys),
                                   ints(5, ind), ints(10, sdata),
                                   int1(1));

  /* ---- init: uniform weights, zero states/bias (mx.init.uniform) -- */
  unsigned seed = 99;
  double *params[MAXARGS];
  double *moms[MAXARGS];
  long psize[MAXARGS];
  for (int i = 0; i < nargs; ++i) {
    const char *nm = CHAR(STRING_ELT(arg_names, i));
    SEXP shp = VECTOR_ELT(arg_shapes, i);
    long n = 1;
    for (int j = 0; j < Rf_length(shp); ++j) n *= INTEGER(shp)[j];
    psize[i] = n;
    params[i] = calloc(n, sizeof(double));
    moms[i] = calloc(n, sizeof(double));
    int is_param = strstr(nm, "weight") || strstr(nm, "bias") ||
                   strstr(nm, "parameters");
    if (is_param && !strstr(nm, "bias"))
      for (long j = 0; j < n; ++j) params[i][j] = 0.4 * (frand(&seed) - 0.5);
    if (strcmp(nm, "data") && strcmp(nm, "label"))
      mxr_exec_set_arg(exec, Rf_mkString(nm), reals(n, params[i]));
  }

  /* ---- deterministic cyclic sequences: next = (tok + step) % V ---- */
  static double X[NSAMPLE][SEQLEN];   /* C-order (batch, seq) per batch */
  static double Y[NSAMPLE][SEQLEN];
  for (int s = 0; s < NSAMPLE; ++s) {
    int start = s % VOCAB;
    int step = 1 + (s / VOCAB) % 2;   /* two interleaved rules */
    for (int t = 0; t < SEQLEN; ++t) {
      X[s][t] = (start + t * step) % VOCAB;
      Y[s][t] = (start + (t + 1) * step) % VOCAB;
    }
  }

  const double lr = 0.25, momentum = 0.9;
  double train_acc = 0.0;
  for (int round = 0; round < ROUNDS; ++round) {
    int correct = 0, seen = 0;
    for (int lo = 0; lo + BATCH <= NSAMPLE; lo += BATCH) {
      mxr_exec_set_arg(exec, Rf_mkString("data"),
                       reals(BATCH * SEQLEN, &X[lo][0]));
      mxr_exec_set_arg(exec, Rf_mkString("label"),
                       reals(BATCH * SEQLEN, &Y[lo][0]));
      mxr_exec_forward(exec, int1(1));
      mxr_exec_backward(exec);
      for (int i = 0; i < nargs; ++i) {
        const char *nm = CHAR(STRING_ELT(arg_names, i));
        if (!(strstr(nm, "weight") || strstr(nm, "bias") ||
              strstr(nm, "parameters")))
          continue;                      /* mx.rnn.is.param.name */
        SEXP g = mxr_exec_get_grad(exec, Rf_mkString(nm),
                                   int1((int)psize[i]));
        for (long j = 0; j < psize[i]; ++j) {
          moms[i][j] = momentum * moms[i][j]
                       - (lr / BATCH) * REAL(g)[j];
          params[i][j] += moms[i][j];
        }
        mxr_exec_set_arg(exec, Rf_mkString(nm),
                         reals(psize[i], params[i]));
      }
      /* output rows are seq-major: row r = t*BATCH + b */
      SEXP out = mxr_exec_get_output(exec, int1(0),
                                     int1(SEQLEN * BATCH * VOCAB));
      for (int t = 0; t < SEQLEN; ++t)
        for (int b = 0; b < BATCH; ++b) {
          const double *row = REAL(out) + (t * BATCH + b) * VOCAB;
          int guess = 0;
          for (int c = 1; c < VOCAB; ++c)
            if (row[c] > row[guess]) guess = c;
          correct += (guess == (int)Y[lo + b][t]);
          seen += 1;
        }
    }
    train_acc = (double)correct / seen;
  }

  /* ---- inference symbol (mx.rnn.inference.symbol): state_outputs,
   * output selection + group through the NEW glue ---- */
  SEXP data_i = mxr_sym_variable(Rf_mkString("data"));
  SEXP rnn_i = rnn_trunk(data_i, 1);
  int nouts = Rf_length(mxr_sym_list_outputs(rnn_i));
  if (nouts != 3) { fprintf(stderr, "state_outputs=3 expected\n"); return 1; }
  SEXP fc_i = head_over(mxr_sym_get_output(rnn_i, int1(0)), "flat");
  SEXP sm_i = op1("SoftmaxOutput", fc_i, "sm", NULL, NULL, 0);
  SEXP group_members = Rf_allocVector(VECSXP, 3);
  SET_VECTOR_ELT(group_members, 0, sm_i);
  SET_VECTOR_ELT(group_members, 1,
                 op1("BlockGrad", mxr_sym_get_output(rnn_i, int1(1)),
                     "bg_h", NULL, NULL, 0));
  SET_VECTOR_ELT(group_members, 2,
                 op1("BlockGrad", mxr_sym_get_output(rnn_i, int1(2)),
                     "bg_c", NULL, NULL, 0));
  SEXP inet = mxr_sym_group(group_members);

  const char *ikeys[] = {"data", "rnn_state", "rnn_state_cell"};
  int iind[] = {0, 2, 5, 8};
  int isdata[] = {1, 1, NLAYER, 1, NHID, NLAYER, 1, NHID};
  SEXP iexec = mxr_exec_simple_bind(inet, int1(1), int1(0),
                                    strs(3, ikeys), ints(4, iind),
                                    ints(8, isdata), int1(0));

  /* trained weights carry over by NAME (mx.rnn.infer.model) */
  for (int i = 0; i < nargs; ++i) {
    const char *nm = CHAR(STRING_ELT(arg_names, i));
    if (strstr(nm, "weight") || strstr(nm, "bias") ||
        strstr(nm, "parameters"))
      mxr_exec_set_arg(iexec, Rf_mkString(nm),
                       reals(psize[i], params[i]));
  }

  int state_n = NLAYER * 1 * NHID;
  double *h_state = calloc(state_n, sizeof(double));
  double *c_state = calloc(state_n, sizeof(double));
  int icorrect = 0, iseen = 0;
  for (int s = 0; s < VOCAB * 2; ++s) {   /* one walk per rule/start */
    int start = s % VOCAB, step = 1 + (s / VOCAB) % 2;
    memset(h_state, 0, state_n * sizeof(double));   /* new.seq=TRUE */
    memset(c_state, 0, state_n * sizeof(double));
    for (int t = 0; t < SEQLEN; ++t) {
      double tok = (start + t * step) % VOCAB;
      int want = (start + (t + 1) * step) % VOCAB;
      mxr_exec_set_arg(iexec, Rf_mkString("data"), reals(1, &tok));
      mxr_exec_set_arg(iexec, Rf_mkString("rnn_state"),
                       reals(state_n, h_state));
      mxr_exec_set_arg(iexec, Rf_mkString("rnn_state_cell"),
                       reals(state_n, c_state));
      mxr_exec_forward(iexec, int1(0));
      SEXP prob = mxr_exec_get_output(iexec, int1(0), int1(VOCAB));
      SEXP h_out = mxr_exec_get_output(iexec, int1(1), int1(state_n));
      SEXP c_out = mxr_exec_get_output(iexec, int1(2), int1(state_n));
      memcpy(h_state, REAL(h_out), state_n * sizeof(double));
      memcpy(c_state, REAL(c_out), state_n * sizeof(double));
      if (t >= 1) {             /* first step has no rule context yet */
        int guess = 0;
        for (int c = 1; c < VOCAB; ++c)
          if (REAL(prob)[c] > REAL(prob)[guess]) guess = c;
        icorrect += (guess == want);
        iseen += 1;
      }
    }
  }
  double infer_acc = (double)icorrect / iseen;

  /* ---- Ops.MXNDArray path: ((v + w) * 2 - 1) / 4 via the exact
   * mxr_func_invoke sequence the R group generic drives ---- */
  int nd_shape[] = {3};
  SEXP va_nd = mxr_nd_create(ints(1, nd_shape), int1(1), int1(0));
  SEXP vb_nd = mxr_nd_create(ints(1, nd_shape), int1(1), int1(0));
  SEXP vo_nd = mxr_nd_create(ints(1, nd_shape), int1(1), int1(0));
  double va[] = {1, 2, 3}, vb[] = {10, 20, 30};
  mxr_nd_set(va_nd, reals(3, va));
  mxr_nd_set(vb_nd, reals(3, vb));
  SEXP use2 = Rf_allocVector(VECSXP, 2);
  SET_VECTOR_ELT(use2, 0, va_nd);
  SET_VECTOR_ELT(use2, 1, vb_nd);
  mxr_func_invoke(Rf_mkString("_plus"), use2,
                  Rf_allocVector(REALSXP, 0), vo_nd);
  SEXP use1 = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(use1, 0, vo_nd);
  double two = 2.0, one = 1.0, four = 4.0;
  mxr_func_invoke(Rf_mkString("_mul_scalar"), use1, reals(1, &two),
                  vo_nd);
  mxr_func_invoke(Rf_mkString("_minus_scalar"), use1, reals(1, &one),
                  vo_nd);
  mxr_func_invoke(Rf_mkString("_div_scalar"), use1, reals(1, &four),
                  vo_nd);
  SEXP got = mxr_nd_get(vo_nd);
  for (int d = 0; d < 3; ++d) {
    double want = ((va[d] + vb[d]) * 2.0 - 1.0) / 4.0;
    if (fabs(REAL(got)[d] - want) > 1e-5) {
      fprintf(stderr, "func_invoke wrong [%d]=%f want %f\n", d,
              REAL(got)[d], want);
      return 1;
    }
  }
  printf("func_invoke_ok\n");

  printf("train_acc=%f infer_acc=%f\n", train_acc, infer_acc);
  return (train_acc >= 0.9 && infer_acc >= 0.9) ? 0 : 1;
}
