"""Device-feed fast path (round-6 tentpole): CachedImageRecordIter ships
raw uint8 frames + deferred augmentation params, and the fused train
step runs cast/crop/mirror/normalize/layout INSIDE its one donated XLA
dispatch. Gates: bit-identical params vs the eager device-augment path,
exactly one dispatch per batch, uint8 H2D <= 1/3 of the float32 bytes,
and feed-stall telemetry for StepTrace's dominant-cause labeling."""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io_cache, recordio as rio, telemetry
from mxnet_tpu.io import DataBatch, DataIter, DataDesc
from mxnet_tpu.io_pipeline import FeedScheduler, maybe_wrap_feed_scheduler

BATCH = 8
# geometry mirrors the 256-store/224-crop ImageNet ratio: uint8 stored
# frames must move <= 1/3 the bytes of float32 crops, i.e.
# store^2 * 1B <= (1/3) * crop^2 * 4B -> 36^2/(4*32^2) ~= 0.316
STORE = 36
CROP = 32


def _write_rec(path, num=24, size=48):
    rng = np.random.RandomState(11)
    w = rio.MXRecordIO(str(path), "w")
    for i in range(num):
        img = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
        w.write(rio.pack_img(rio.IRHeader(0, float(i % 5), i, 0), img,
                             quality=95))
    w.close()


@pytest.fixture(scope="module")
def cache(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("feed")
    rec = tmp / "t.rec"
    _write_rec(rec)
    prefix = str(tmp / "t.cache")
    io_cache.build_decoded_cache(str(rec), prefix, (3, STORE, STORE),
                                 preprocess_threads=2)
    return prefix


@pytest.fixture()
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


def _net():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _seed_params(net, data_shape, seed=3):
    arg_shapes, _, _ = net.infer_shape(data=data_shape,
                                       softmax_label=(BATCH,))
    rng = np.random.RandomState(seed)
    return {name: mx.nd.array((rng.randn(*shape) * 0.1).astype(np.float32))
            for name, shape in zip(net.list_arguments(), arg_shapes)
            if name not in ("data", "softmax_label")}


def _iter(prefix, **mode):
    return io_cache.CachedImageRecordIter(
        prefix, (3, CROP, CROP), BATCH, shuffle=True, seed=7,
        rand_crop=True, rand_mirror=True, scale=1.0 / 255.0, **mode)


def _fit(prefix, monkeypatch, num_epoch=2, fused=True, **mode):
    if fused:
        monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    else:
        monkeypatch.delenv("MXNET_TPU_FUSED_STEP", raising=False)
    it = _iter(prefix, **mode)
    net = _net()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=num_epoch, optimizer="sgd",
            arg_params=_seed_params(net, (BATCH, 3, CROP, CROP)),
            initializer=None,
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    assert mod._fused_step_active == fused
    arg, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in arg.items()}


# ---------------------------------------------------------------------------
# iterator-level device-feed mode
# ---------------------------------------------------------------------------

def test_device_feed_batch_shape_and_aug(cache, tel):
    it = _iter(cache, device_feed=True)
    b = next(it)
    # raw stored frames, uint8, NHWC — NOT the crop shape
    assert b.data[0].shape == (BATCH, STORE, STORE, 3)
    assert b.data[0].dtype == np.uint8
    aug = b.aug
    assert aug["crop"] == (CROP, CROP)
    assert aug["tops"].shape == (BATCH,) and aug["lefts"].shape == (BATCH,)
    assert aug["mirror"].shape == (BATCH,)
    assert tel.peek("io.feed_batches") >= 1
    # provide_data still advertises the CROP shape the graph will see
    assert it.provide_data[0].shape == (BATCH, 3, CROP, CROP)


def test_materialize_matches_device_augment(cache):
    b_eager = next(_iter(cache, device_augment=True))
    b_feed = next(_iter(cache, device_feed=True))
    assert np.array_equal(b_eager.label[0].asnumpy(),
                          b_feed.label[0].asnumpy())
    m = io_cache.materialize_device_feed(b_feed)
    assert getattr(m, "aug", None) is None
    assert np.array_equal(b_eager.data[0].asnumpy(), m.data[0].asnumpy())


def test_device_feed_env_gate(cache, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_DEVICE_FEED", "1")
    it = io_cache.CachedImageRecordIter(
        cache, (3, CROP, CROP), BATCH, scale=1.0 / 255.0)
    assert it.device_feed
    b = next(it)
    assert b.data[0].dtype == np.uint8 and b.aug is not None


# ---------------------------------------------------------------------------
# fused-step integration: parity + dispatch count
# ---------------------------------------------------------------------------

def test_fused_feed_bit_identical_to_eager_cached(cache, tel, monkeypatch):
    p_eager = _fit(cache, monkeypatch, device_augment=True)
    p_feed = _fit(cache, monkeypatch, device_feed=True)
    assert set(p_eager) == set(p_feed)
    for k in p_eager:
        assert np.array_equal(p_eager[k], p_feed[k]), \
            "param %s diverged between eager and device-feed paths" % k


def test_fused_feed_one_dispatch_per_batch(cache, tel, monkeypatch):
    before = tel.peek("step.dispatches") or 0
    _fit(cache, monkeypatch, num_epoch=2, device_feed=True)
    dispatches = (tel.peek("step.dispatches") or 0) - before
    nbatches = 2 * (24 // BATCH)
    assert dispatches == nbatches
    assert tel.peek("step.fused_feed_batches") == nbatches


def test_classic_loop_materializes_feed_batches(cache, tel, monkeypatch):
    # non-fused consumers must still train (and agree with the eager
    # iterator bit-for-bit): load_data_batch materializes batch.aug
    p_eager = _fit(cache, monkeypatch, fused=False, device_augment=True)
    p_feed = _fit(cache, monkeypatch, fused=False, device_feed=True)
    for k in p_eager:
        assert np.array_equal(p_eager[k], p_feed[k])


def test_fused_vs_classic_feed_parity(cache, tel, monkeypatch):
    p_classic = _fit(cache, monkeypatch, fused=False, device_feed=True)
    p_fused = _fit(cache, monkeypatch, fused=True, device_feed=True)
    for k in p_classic:
        assert np.array_equal(p_classic[k], p_fused[k])


# ---------------------------------------------------------------------------
# H2D byte accounting
# ---------------------------------------------------------------------------

def test_uint8_feed_h2d_bytes_at_most_one_third_of_f32(cache, tel):
    telemetry.reset()
    telemetry.enable()
    for _ in _iter(cache, device_feed=True):
        pass
    u8_bytes = telemetry.peek("ndarray.h2d_bytes")
    telemetry.reset()
    telemetry.enable()
    for _ in _iter(cache, device_normalize=False):
        pass
    f32_bytes = telemetry.peek("ndarray.h2d_bytes")
    assert u8_bytes and f32_bytes
    assert u8_bytes / f32_bytes <= 1.0 / 3.0, \
        "uint8 feed moved %d bytes vs %d f32 (ratio %.3f > 1/3)" % (
            u8_bytes, f32_bytes, u8_bytes / f32_bytes)


# ---------------------------------------------------------------------------
# feed scheduler
# ---------------------------------------------------------------------------

class _SlowIter(DataIter):
    """Tiny deterministic iterator with a controllable per-batch delay."""

    def __init__(self, nbatches=4, delay=0.0):
        super().__init__()
        self.nbatches = nbatches
        self.delay = delay
        self.cursor = 0
        self.batch_size = 2

    @property
    def provide_data(self):
        return [DataDesc("data", (2, 3))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (2,))]

    def reset(self):
        self.cursor = 0

    def next(self):
        if self.cursor >= self.nbatches:
            raise StopIteration
        if self.delay:
            time.sleep(self.delay)
        i = self.cursor
        self.cursor += 1
        return DataBatch([mx.nd.array(np.full((2, 3), i, np.float32))],
                         [mx.nd.array(np.zeros(2, np.float32))], 0, None)


def test_feed_scheduler_order_and_reset(tel):
    sched = FeedScheduler(_SlowIter(nbatches=4), depth=2)
    seen = [int(b.data[0].asnumpy()[0, 0]) for b in sched]
    assert seen == [0, 1, 2, 3]
    sched.reset()
    seen2 = [int(b.data[0].asnumpy()[0, 0]) for b in sched]
    assert seen2 == [0, 1, 2, 3]
    sched.close()
    assert telemetry.peek("io.feed.batches") == 8


def test_feed_scheduler_stall_telemetry(tel):
    sched = FeedScheduler(_SlowIter(nbatches=3, delay=0.05), depth=1)
    for _ in sched:
        pass
    sched.close()
    # the consumer is instant, the producer sleeps 50 ms/batch: the
    # stall histogram must see (most of) that wait
    assert telemetry.peek("io.feed_stall_ms", "hist_sum") > 50.0
    assert telemetry.peek("io.feed.batches") == 3


def test_feed_scheduler_propagates_worker_error():
    class _Boom(_SlowIter):
        def next(self):
            if self.cursor == 1:
                raise RuntimeError("decode exploded")
            return super().next()

    sched = FeedScheduler(_Boom(nbatches=3), depth=2)
    next(sched)
    with pytest.raises(RuntimeError, match="decode exploded"):
        while True:
            next(sched)
    sched.close()


def test_feed_scheduler_preserves_aug(cache):
    sched = FeedScheduler(_iter(cache, device_feed=True), depth=2)
    b = next(sched)
    assert b.aug is not None and b.data[0].dtype == np.uint8
    sched.close()


def test_feed_scheduler_env_gate(monkeypatch):
    it = _SlowIter()
    monkeypatch.delenv("MXNET_TPU_FEED_DEPTH", raising=False)
    assert maybe_wrap_feed_scheduler(it) is it
    monkeypatch.setenv("MXNET_TPU_FEED_DEPTH", "3")
    w = maybe_wrap_feed_scheduler(it)
    assert isinstance(w, FeedScheduler) and w.depth == 3
    # idempotent
    assert maybe_wrap_feed_scheduler(w) is w
    w.close()


def test_feed_scheduler_fit_integration(cache, tel, monkeypatch):
    # end to end through module.fit: scheduler + device feed + fused
    # step, still bit-identical to the plain eager path
    p_eager = _fit(cache, monkeypatch, device_augment=True)
    monkeypatch.setenv("MXNET_TPU_FEED_DEPTH", "2")
    p_feed = _fit(cache, monkeypatch, device_feed=True)
    monkeypatch.delenv("MXNET_TPU_FEED_DEPTH")
    for k in p_eager:
        assert np.array_equal(p_eager[k], p_feed[k])
    assert telemetry.peek("io.feed.batches") == 2 * (24 // BATCH)
    assert telemetry.peek("io.feed_stall_ms", "hist_sum") is not None
