"""Executable-documentation tier (reference
``tests/python/doctest/run.py``: the reference ran its operator doc
examples as doctests in CI so documentation could never drift from
behavior). Runs every example in ``mxnet_tpu/symbol_doc.py``."""
import doctest

import mxnet_tpu  # noqa: F401  (imported for the doctest globals)
from mxnet_tpu import symbol_doc


def test_symbol_doc_examples():
    results = doctest.testmod(symbol_doc, verbose=False)
    assert results.attempted > 15, \
        "doctest collection shrank: %d examples" % results.attempted
    assert results.failed == 0, "%d doctest failures" % results.failed
