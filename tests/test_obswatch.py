"""Fleet-wide metric federation: prometheus round trip back into
payload shape, the HTTP and in-process scrape targets, bucket-merged
rollups headlining the router-view latency, the durable JSONL ring
store (rollover, retention, torn trailing lines, dotted-path queries),
multi-window SLO burn-rate alerting under a fake clock, the ObsWatch
loop end to end against a fake fleet, and the fleet-health report
view."""
import json
import os
import sys

import pytest

import mxnet_tpu as mx  # noqa: F401 (package init wires telemetry hooks)
from mxnet_tpu import fleet, obswatch, telemetry, tracing
from mxnet_tpu.base import MXNetError

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.disable()


def _hist(values, include_sample=True):
    h = telemetry.Histogram("t.ms")
    for v in values:
        h.observe(v)
    return h.export(include_sample=include_sample)


# -- federation ----------------------------------------------------------

def _payload(rid, served, breaches, in_flight, lats, up=True):
    return {"rid": rid, "up": up,
            "health": {"status": "ok" if up else "down"},
            "metrics": {"serve.requests_served": served,
                        "serve.slo_breaches": breaches,
                        "serve.in_flight": float(in_flight),
                        "serve.request_ms": _hist(lats)}}


def test_federate_counters_sum_gauges_fan_out():
    """Counters merge by sum into the fleet row; gauges stay labeled
    per replica so a hot replica is visible, not averaged away."""
    p0 = _payload("r0", 10, 1, 2, [1.0] * 20)
    p1 = _payload("r1", 30, 0, 5, [2.0] * 20)
    stats = {"replicas": {
        "r0": {"state": "up", "breaker": {"state": "closed"}},
        "r1": {"state": "up", "breaker": {"state": "open"}}}}
    r = obswatch.federate([p0, p1], router_stats=stats, ts=100.0)
    assert r["ts"] == 100.0 and r["kind"] == "rollup"
    f = r["fleet"]
    assert f["replicas"] == 2 and f["up"] == 2
    assert f["served"] == 40 and f["slo_breaches"] == 1
    assert f["in_flight"] == 7.0
    assert f["breakers_open"] == 1
    rows = r["replica_rows"]
    assert rows["r0"]["served"] == 10 and rows["r1"]["served"] == 30
    assert rows["r0"]["in_flight"] == 2.0 and rows["r1"]["in_flight"] == 5.0
    assert rows["r1"]["breaker"] == "open"
    # per-replica percentiles come from each replica's own histogram
    assert rows["r0"]["p50_ms"] == pytest.approx(1.0)
    assert rows["r1"]["p50_ms"] == pytest.approx(2.0)
    # fleet latency merges bucket-wise across replicas (no router view
    # here, so the scheduler-side merge is the headline)
    assert 1.0 <= f["p50_ms"] <= 2.0
    assert "sample" not in f["request_ms"]  # store stays slim


def test_federate_headlines_router_view():
    """With a router histogram in the merge, fleet percentiles come
    from the client-experienced series, not the scheduler view."""
    p = _payload("r0", 100, 0, 0, [1.0] * 50)
    rm = {"router.request_ms": _hist([10.0] * 50)}
    r = obswatch.federate([p], router_metrics=rm, ts=1.0)
    assert r["fleet"]["p50_ms"] == pytest.approx(10.0)
    # the per-replica row still shows the scheduler view
    assert r["replica_rows"]["r0"]["p50_ms"] == pytest.approx(1.0)


def test_federate_down_replica_rows():
    p0 = _payload("r0", 10, 0, 0, [1.0])
    p1 = {"rid": "r1", "up": False,
          "health": {"status": "down", "error": "boom"}, "metrics": {}}
    r = obswatch.federate([p0, p1], ts=1.0)
    assert r["fleet"]["up"] == 1 and r["fleet"]["replicas"] == 2
    assert r["replica_rows"]["r1"]["status"] == "down"


def test_goodput_from_served_delta():
    r0 = {"ts": 10.0, "fleet": {"served": 100}}
    r1 = {"ts": 12.0, "fleet": {"served": 200}}
    assert obswatch.goodput(r0, r1) == pytest.approx(50.0)
    assert obswatch.goodput(r0, r0) is None  # zero dt is not a rate


# -- prometheus round trip -----------------------------------------------

def test_prometheus_round_trip():
    """tracing.prometheus_text -> obswatch.parse_prometheus_text
    reconstructs the flat payload: counters as ints, gauges as floats,
    histograms reassembled from _bucket/_sum/_count."""
    telemetry.inc("engine.push", 7)
    telemetry.set_gauge("io.ring_occupancy", 3.0)
    for v in (1.0, 2.0, 3.0, 4.0):
        telemetry.observe("profiler.step_ms", v)
    parsed = obswatch.parse_prometheus_text(tracing.prometheus_text())
    assert parsed["engine.push"] == 7
    assert parsed["io.ring_occupancy"] == 3.0
    h = parsed["profiler.step_ms"]
    assert h["count"] == 4 and h["sum"] == pytest.approx(10.0)
    assert h["mean"] == pytest.approx(2.5)
    # cumulative finite-bound counts survive the trip
    b = dict(zip(h["buckets"]["bounds"], h["buckets"]["counts"]))
    assert b[1.0] == 1 and b[2.5] == 2 and b[5.0] == 4
    # and the reassembled export merges with a native one
    native = _hist([1.0, 2.0, 3.0, 4.0], include_sample=False)
    merged = telemetry.merge_snapshots(
        [{"profiler.step_ms": h}, {"profiler.step_ms": native}])
    assert merged["profiler.step_ms"]["count"] == 8


def test_http_target_scrapes_metrics_server():
    telemetry.inc("engine.push", 5)
    server = tracing.MetricsServer(0)
    try:
        out = obswatch.HttpTarget("r9", "127.0.0.1", server.port).scrape()
    finally:
        server.close()
    assert out["rid"] == "r9" and out["up"]
    assert out["metrics"]["engine.push"] == 5
    assert out["health"].get("status")


def test_http_target_down_on_refused_connection():
    out = obswatch.HttpTarget("r9", "127.0.0.1", 1, timeout_s=0.2).scrape()
    assert not out["up"] and out["health"]["status"] == "down"


# -- durable time-series store -------------------------------------------

def test_store_rollover_and_retention(tmp_path):
    store = obswatch.TimeSeriesStore(str(tmp_path), seg_records=5,
                                     seg_keep=2)
    for i in range(23):
        store.append({"ts": float(i), "fleet": {"served": 2 * i}})
    # 23 records over 5-record segments -> segments 0..4; rollover
    # prunes the closed ring down to seg_keep before opening the next
    # segment, so at most seg_keep+1 segments ever exist on disk
    assert store.segments() == [2, 3, 4]
    recs = store.records()
    assert len(recs) == 13 and recs[0]["ts"] == 10.0
    with open(os.path.join(str(tmp_path), store.MANIFEST)) as f:
        manifest = json.load(f)
    assert manifest["current"] == 4 and manifest["seg_keep"] == 2


def test_store_query_dotted_path_and_window(tmp_path):
    store = obswatch.TimeSeriesStore(str(tmp_path), seg_records=100,
                                     seg_keep=2)
    for i in range(10):
        store.append({"ts": float(i), "fleet": {"served": i,
                                                "p99_ms": 1.5 * i}})
    pts = store.query("fleet.p99_ms", t_min=3.0, t_max=6.0)
    assert [t for t, _ in pts] == [3.0, 4.0, 5.0, 6.0]
    assert pts[-1][1] == pytest.approx(9.0)
    assert store.query("fleet.nope") == []


def test_store_skips_torn_trailing_line(tmp_path):
    store = obswatch.TimeSeriesStore(str(tmp_path), seg_records=100,
                                     seg_keep=2)
    for i in range(3):
        store.append({"ts": float(i), "v": i})
    seg = os.path.join(str(tmp_path), "segment-0.jsonl")
    with open(seg, "a") as f:
        f.write('{"ts": 99, "v"')  # crash mid-append: no newline, torn
    assert len(store.records()) == 3
    # a fresh store over the same dir keeps appending past the tear
    store2 = obswatch.TimeSeriesStore(str(tmp_path), seg_records=100,
                                      seg_keep=2)
    store2.append({"ts": 100.0, "v": 100})
    assert store2.query("v")[-1] == (100.0, 100)


# -- burn-rate monitor (fake clock) --------------------------------------

def _roll(ts, served, bad):
    return {"ts": ts, "fleet": {"served": served, "slo_breaches": bad}}


def test_burn_alert_fires_before_budget_spent():
    mon = obswatch.BurnRateMonitor(slo_target=0.9, fast_s=10.0,
                                   slow_s=60.0, threshold=2.0,
                                   min_events=5)
    mon.update(_roll(0.0, 0, 0))
    v = mon.update(_roll(5.0, 100, 50))  # 50% bad / 10% budget = 5x burn
    assert v["alert"]
    assert v["fast_burn"] == pytest.approx(5.0)
    assert v["slow_burn"] == pytest.approx(5.0)
    # the page fires while budget remains: 5x burn for 5s of a 60s
    # window spends ~42% of the budget
    assert 0 < v["budget_spent"] < 1.0


def test_burn_blip_does_not_page():
    """A short spike lights the fast window only; the slow window
    filters it, so no alert."""
    mon = obswatch.BurnRateMonitor(slo_target=0.9, fast_s=10.0,
                                   slow_s=100.0, threshold=2.0,
                                   min_events=5)
    for t in range(0, 91, 5):
        mon.update(_roll(float(t), 20 * t, 0))  # long clean history
    v = mon.update(_roll(95.0, 1900, 50))       # 5s spike
    assert v["fast_burn"] > 2.0 and v["slow_burn"] < 2.0
    assert not v["alert"]


def test_burn_min_events_guard():
    mon = obswatch.BurnRateMonitor(slo_target=0.9, fast_s=10.0,
                                   slow_s=60.0, threshold=2.0,
                                   min_events=50)
    mon.update(_roll(0.0, 0, 0))
    v = mon.update(_roll(5.0, 10, 10))  # hot, but only 10 events
    assert not v["alert"]


def test_burn_clears_when_traffic_recovers():
    mon = obswatch.BurnRateMonitor(slo_target=0.9, fast_s=5.0,
                                   slow_s=20.0, threshold=2.0,
                                   min_events=5)
    mon.update(_roll(0.0, 0, 0))
    assert mon.update(_roll(2.0, 100, 60))["alert"]
    # breaches stop; the fast window drains first
    assert not mon.update(_roll(10.0, 1000, 60))["alert"]


def test_burn_requires_error_budget():
    with pytest.raises(MXNetError):
        obswatch.BurnRateMonitor(slo_target=1.0)


# -- ObsWatch end to end over a fake fleet -------------------------------

class _FakeReplica:
    def __init__(self):
        self.served = 0
        self.bad = 0
        self.alive = True

    def health(self):
        if not self.alive:
            raise RuntimeError("dead")
        return {"status": "ok"}

    def metrics(self):
        return {"serve.requests_served": self.served,
                "serve.slo_breaches": self.bad,
                "serve.in_flight": 0.0,
                "serve.request_ms": _hist([1.0] * max(1, self.served))}


class _FakeRouter:
    def __init__(self, n=2):
        self._reps = [_FakeReplica() for _ in range(n)]

    def replicas(self):
        return [("r%d" % i, r) for i, r in enumerate(self._reps)]

    def stats(self):
        return {"replicas": {}}

    def metrics_payload(self):
        return {"router.served": sum(r.served for r in self._reps)}


def test_obswatch_tick_persists_and_alerts(tmp_path):
    clk = [0.0]
    router = _FakeRouter()
    store = obswatch.TimeSeriesStore(str(tmp_path), seg_records=100,
                                     seg_keep=2)
    mon = obswatch.BurnRateMonitor(slo_target=0.9, fast_s=10.0,
                                   slow_s=60.0, threshold=2.0,
                                   min_events=5)
    watch = obswatch.ObsWatch(router, store=store, monitor=mon,
                              interval_ms=3600e3, clock=lambda: clk[0])
    try:
        watch.tick()
        for rep in router._reps:
            rep.served, rep.bad = 50, 25
        clk[0] = 5.0
        r = watch.tick()
        assert r["burn"]["alert"] and watch.alerts == 1
        # the rising edge landed a slo_burn_alert step record, which is
        # what FleetHealthDetector keys on
        recs = tracing.step_trace().records()
        assert any(rec.get("slo_burn_alert") for rec in recs)
        ev = tracing.FleetHealthDetector().check(
            [rec for rec in recs if rec.get("slo_burn_alert")][-1])
        assert ev and ev.get("slo_burn_alert")
        # the registered health probe reports the burn while alerting
        probe = watch._probe()
        assert probe and probe["budget_spent"] == \
            r["burn"]["budget_spent"]
        # and every tick landed durably
        assert len(store.records()) == 2
        assert store.query("burn.fast_burn")[-1][1] > 2.0
        # a second hot tick is NOT a second alert (edge, not level)
        clk[0] = 6.0
        watch.tick()
        assert watch.alerts == 1
    finally:
        watch.close()


def test_obswatch_survives_dead_replica(tmp_path):
    router = _FakeRouter()
    router._reps[1].alive = False
    store = obswatch.TimeSeriesStore(str(tmp_path), seg_records=100,
                                     seg_keep=2)
    mon = obswatch.BurnRateMonitor(slo_target=0.9, fast_s=10.0,
                                   slow_s=60.0, threshold=2.0)
    with obswatch.ObsWatch(router, store=store, monitor=mon,
                           interval_ms=3600e3, clock=lambda: 1.0) as w:
        r = w.tick()
    assert r["fleet"]["up"] == 1
    assert r["replica_rows"]["r1"]["status"] == "down"


def test_obswatch_over_real_inproc_fleet(tmp_path):
    """The scraper against a real router + InProc replicas: served
    counters federate and the router-view latency headline exists."""
    router = fleet.FleetRouter(fleet.in_process(fleet.demo_server_factory),
                               2, health_interval_s=0.02)
    try:
        import numpy as np
        x = np.zeros((1, 8), dtype=np.float32)
        futs = [router.submit([x]) for _ in range(8)]
        for f in futs:
            f.result(timeout=30)
        store = obswatch.TimeSeriesStore(str(tmp_path), seg_records=100,
                                         seg_keep=2)
        mon = obswatch.BurnRateMonitor(slo_target=0.5, fast_s=10.0,
                                       slow_s=60.0, threshold=1e9)
        with obswatch.ObsWatch(router, store=store, monitor=mon,
                               interval_ms=3600e3) as w:
            r = w.tick()
    finally:
        router.close()
    assert r["fleet"]["served"] == 8 and r["fleet"]["up"] == 2
    assert r["fleet"]["p50_ms"] > 0  # router-view histogram populated
    assert sum(row["served"] for row in r["replica_rows"].values()) == 8


# -- fleet-health view ---------------------------------------------------

def test_fleet_health_view_renders():
    rec = {
        "federation": {"fed_goodput_rps": 100.0,
                       "client_goodput_rps": 101.0,
                       "goodput_rel_err": 0.01, "fed_p99_ms": 5.0,
                       "client_p99_ms": 5.1, "p99_rel_err": 0.02},
        "final_rollup": {
            "ts": 10.0, "fleet": {"replicas": 2, "up": 2, "served": 500,
                                  "slo_breaches": 3, "in_flight": 1,
                                  "breakers_open": 0, "p50_ms": 2.0,
                                  "p99_ms": 5.0},
            "replica_rows": {"r0": {"status": "ok", "state": "up",
                                    "breaker": "closed", "served": 250,
                                    "slo_breaches": 1, "in_flight": 1,
                                    "p50_ms": 2.0, "p99_ms": 5.0}}},
        "burn": {"alert_fired": True, "alert_at_s": 0.4,
                 "budget_spent_at_alert": 0.2, "fast_burn": 1.6,
                 "slow_burn": 1.6},
        "series": {"burn.budget_spent": [[0.0, 0.0], [1.0, 0.5]]},
    }
    out = trace_report.render_fleet_health(rec)
    assert "r0" in out and "FLEET" in out
    assert "federation agreement" in out
    assert "SLO burn: ALERT" in out and "20% of error budget" in out
    assert "budget burn-down" in out


def test_fleet_health_view_incomplete_safe():
    out = trace_report.render_fleet_health(
        {"incomplete": "fleet obswatch phase did not run"})
    assert "INCOMPLETE" in out
