/* A real (minimal) JNI environment for executing
 * scala-package/native/src/main/native/mxnet_tpu_jni.c without a JVM
 * (none exists in this image): arrays are {len, data} records, strings
 * are C strings, ThrowNew prints and exits. Compiled against the same
 * stub jni.h as the glue (tests/test_scala_package.py JNI_STUB), so the
 * struct layout agrees. tests/jni_train.c drives the glue through the
 * exact sequence the Scala Module / Spark trainPartition performs.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "jni.h"

typedef struct {
  jsize len;
  void *data;          /* ints, floats, longs, or void* elements */
} arr_t;

static jclass shim_FindClass(JNIEnv *env, const char *name) {
  (void)env;
  return (jclass)name;
}

static jint shim_ThrowNew(JNIEnv *env, jclass cls, const char *msg) {
  (void)env;
  fprintf(stderr, "JNI throw %s: %s\n", (const char *)cls, msg);
  exit(2);
}

static jsize shim_GetArrayLength(JNIEnv *env, jarray a) {
  (void)env;
  return ((arr_t *)a)->len;
}

static jint *shim_GetIntArrayElements(JNIEnv *env, jintArray a, void *c) {
  (void)env; (void)c;
  return (jint *)((arr_t *)a)->data;
}
static void shim_ReleaseIntArrayElements(JNIEnv *env, jintArray a,
                                         jint *p, jint mode) {
  (void)env; (void)a; (void)p; (void)mode;
}

static jfloat *shim_GetFloatArrayElements(JNIEnv *env, jfloatArray a,
                                          void *c) {
  (void)env; (void)c;
  return (jfloat *)((arr_t *)a)->data;
}
static void shim_ReleaseFloatArrayElements(JNIEnv *env, jfloatArray a,
                                           jfloat *p, jint mode) {
  (void)env; (void)a; (void)p; (void)mode;
}

static jlong *shim_GetLongArrayElements(JNIEnv *env, jlongArray a,
                                        void *c) {
  (void)env; (void)c;
  return (jlong *)((arr_t *)a)->data;
}
static void shim_ReleaseLongArrayElements(JNIEnv *env, jlongArray a,
                                          jlong *p, jint mode) {
  (void)env; (void)a; (void)p; (void)mode;
}

static arr_t *new_arr(jsize n, size_t elem) {
  arr_t *a = calloc(1, sizeof(arr_t));
  a->len = n;
  a->data = calloc(n ? n : 1, elem);
  return a;
}

static jfloatArray shim_NewFloatArray(JNIEnv *env, jsize n) {
  (void)env;
  return (jfloatArray)new_arr(n, sizeof(jfloat));
}
static void shim_SetFloatArrayRegion(JNIEnv *env, jfloatArray a, jsize off,
                                     jsize n, const jfloat *src) {
  (void)env;
  memcpy((jfloat *)((arr_t *)a)->data + off, src, n * sizeof(jfloat));
}

static jintArray shim_NewIntArray(JNIEnv *env, jsize n) {
  (void)env;
  return (jintArray)new_arr(n, sizeof(jint));
}
static void shim_SetIntArrayRegion(JNIEnv *env, jintArray a, jsize off,
                                   jsize n, const jint *src) {
  (void)env;
  memcpy((jint *)((arr_t *)a)->data + off, src, n * sizeof(jint));
}

static jlongArray shim_NewLongArray(JNIEnv *env, jsize n) {
  (void)env;
  return (jlongArray)new_arr(n, sizeof(jlong));
}
static void shim_SetLongArrayRegion(JNIEnv *env, jlongArray a, jsize off,
                                    jsize n, const jlong *src) {
  (void)env;
  memcpy((jlong *)((arr_t *)a)->data + off, src, n * sizeof(jlong));
}

static const char *shim_GetStringUTFChars(JNIEnv *env, jstring s,
                                          void *c) {
  (void)env; (void)c;
  return (const char *)s;
}
static void shim_ReleaseStringUTFChars(JNIEnv *env, jstring s,
                                       const char *p) {
  (void)env; (void)s; (void)p;
}
static jstring shim_NewStringUTF(JNIEnv *env, const char *s) {
  (void)env;
  return (jstring)strdup(s);
}

static jobjectArray shim_NewObjectArray(JNIEnv *env, jsize n, jclass cls,
                                        jobject init) {
  (void)env; (void)cls; (void)init;
  return (jobjectArray)new_arr(n, sizeof(void *));
}
static void shim_SetObjectArrayElement(JNIEnv *env, jobjectArray a,
                                       jsize i, jobject v) {
  (void)env;
  ((void **)((arr_t *)a)->data)[i] = v;
}
static jobject shim_GetObjectArrayElement(JNIEnv *env, jobjectArray a,
                                          jsize i) {
  (void)env;
  return ((void **)((arr_t *)a)->data)[i];
}

static struct JNINativeInterface_ iface = {
  .FindClass = shim_FindClass,
  .ThrowNew = shim_ThrowNew,
  .GetArrayLength = shim_GetArrayLength,
  .GetIntArrayElements = shim_GetIntArrayElements,
  .ReleaseIntArrayElements = shim_ReleaseIntArrayElements,
  .GetFloatArrayElements = shim_GetFloatArrayElements,
  .ReleaseFloatArrayElements = shim_ReleaseFloatArrayElements,
  .GetLongArrayElements = shim_GetLongArrayElements,
  .ReleaseLongArrayElements = shim_ReleaseLongArrayElements,
  .NewLongArray = shim_NewLongArray,
  .SetLongArrayRegion = shim_SetLongArrayRegion,
  .NewFloatArray = shim_NewFloatArray,
  .SetFloatArrayRegion = shim_SetFloatArrayRegion,
  .NewIntArray = shim_NewIntArray,
  .SetIntArrayRegion = shim_SetIntArrayRegion,
  .GetStringUTFChars = shim_GetStringUTFChars,
  .ReleaseStringUTFChars = shim_ReleaseStringUTFChars,
  .NewStringUTF = shim_NewStringUTF,
  .NewObjectArray = shim_NewObjectArray,
  .SetObjectArrayElement = shim_SetObjectArrayElement,
  .GetObjectArrayElement = shim_GetObjectArrayElement,
};

/* exported for the driver */
JNIEnv jni_shim_env = &iface;

/* helpers the driver uses to build/read shim arrays */
void *jni_shim_make_ints(const jint *v, jsize n) {
  arr_t *a = new_arr(n, sizeof(jint));
  memcpy(a->data, v, n * sizeof(jint));
  return a;
}
void *jni_shim_make_floats(const jfloat *v, jsize n) {
  arr_t *a = new_arr(n, sizeof(jfloat));
  memcpy(a->data, v, n * sizeof(jfloat));
  return a;
}
void *jni_shim_make_longs(const jlong *v, jsize n) {
  arr_t *a = new_arr(n, sizeof(jlong));
  memcpy(a->data, v, n * sizeof(jlong));
  return a;
}
void *jni_shim_make_strs(const char **v, jsize n) {
  arr_t *a = new_arr(n, sizeof(void *));
  for (jsize i = 0; i < n; ++i) ((void **)a->data)[i] = (void *)v[i];
  return a;
}
jsize jni_shim_len(void *a) { return ((arr_t *)a)->len; }
jlong *jni_shim_longs(void *a) { return (jlong *)((arr_t *)a)->data; }
jint *jni_shim_ints(void *a) { return (jint *)((arr_t *)a)->data; }
jfloat *jni_shim_floats(void *a) { return (jfloat *)((arr_t *)a)->data; }
void **jni_shim_objs(void *a) { return (void **)((arr_t *)a)->data; }
