"""Model-parallel tests (reference
tests/python/unittest/test_model_parallel.py:14-50: same net bound on 1 vs
2 contexts via ctx_group/group2ctx must produce identical results)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _net():
    with mx.AttrScope(ctx_group="dev1"):
        data = sym.Variable("data")
        fc1 = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
        act1 = sym.Activation(fc1, act_type="relu")
    with mx.AttrScope(ctx_group="dev2"):
        fc2 = sym.FullyConnected(act1, num_hidden=4, name="fc2")
        out = sym.SoftmaxOutput(fc2, name="softmax")
    return out


def _run(group2ctx):
    net = _net()
    rng = np.random.RandomState(0)
    shapes = {"data": (6, 10), "softmax_label": (6,)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    args = {}
    grads = {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        args[name] = mx.nd.array(rng.randn(*shape).astype(np.float32) * 0.3)
        grads[name] = mx.nd.zeros(shape)
    args["softmax_label"][:] = np.array([0, 1, 2, 3, 0, 1], dtype=np.float32)
    ex = net.bind(mx.cpu(), args, args_grad=grads,
                  grad_req={n: ("null" if n == "softmax_label" else "write")
                            for n in args},
                  group2ctx=group2ctx)
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    ex.backward()
    g = {n: a.asnumpy() for n, a in ex.grad_dict.items()}
    return out, g


def test_model_parallel_matches_single_device():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    out1, g1 = _run(None)
    out2, g2 = _run({"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    np.testing.assert_allclose(out1, out2, rtol=1e-5)
    for name in g1:
        np.testing.assert_allclose(g1[name], g2[name], rtol=1e-4, atol=1e-6,
                                   err_msg=name)


def test_model_parallel_lstm_style_placement():
    """Layer-per-device placement as in example/model-parallel-lstm."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    from mxnet_tpu import models

    group2ctx = {"layer0": mx.cpu(0), "layer1": mx.cpu(1)}
    data = sym.Variable("data")
    with mx.AttrScope(ctx_group="layer0"):
        fc0 = sym.FullyConnected(data, num_hidden=16, name="l0")
        a0 = sym.Activation(fc0, act_type="tanh")
    with mx.AttrScope(ctx_group="layer1"):
        fc1 = sym.FullyConnected(a0, num_hidden=16, name="l1")
        out = sym.LinearRegressionOutput(fc1, name="lro")
    shapes = {"data": (4, 8), "lro_label": (4, 16)}
    ex = out.simple_bind(ctx=mx.cpu(), grad_req="write",
                         **{k: v for k, v in shapes.items()})
    # rebind with group2ctx through bind()
    ex2 = out.bind(mx.cpu(), ex.arg_arrays,
                   args_grad={n: mx.nd.zeros(a.shape)
                              for n, a in ex.arg_dict.items()},
                   group2ctx=group2ctx)
    rng = np.random.RandomState(0)
    for name, arr in ex2.arg_dict.items():
        arr[:] = rng.randn(*arr.shape).astype(np.float32) * 0.2
    ex2.forward(is_train=True)
    ex2.backward()
    assert ex2.outputs[0].shape == (4, 16)
    assert np.abs(ex2.grad_dict["l0_weight"].asnumpy()).sum() > 0
