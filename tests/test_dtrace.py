"""Distributed request tracing: the tail sampler's keep/drop decisions
under a fake clock, span-tree construction and the exact five-way
decomposition, cross-process clock alignment via shipped epochs, wire
compatibility in BOTH rolling-upgrade directions, hedged traces with
winning and abandoned attempts, the Perfetto export (lanes, metadata,
flow events), the waterfall rendering, and the disabled-cost contract
(one module-global None check, no spans, no counters)."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import dtrace, fleet, serving, telemetry
from mxnet_tpu.fleet import FleetRouter
from mxnet_tpu.serving import BatchScheduler
from mxnet_tpu.tracing import SlowRequestDetector

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import trace_report  # noqa: E402

DIM = 8


@pytest.fixture
def trc():
    """An armed tracer, disarmed (and telemetry reset) afterwards."""
    telemetry.reset()
    telemetry.enable()
    t = dtrace.enable(sample=0)
    yield t
    dtrace.disable()
    telemetry.reset()
    telemetry.disable()


@pytest.fixture
def no_dtrace():
    dtrace.disable()
    yield
    dtrace.disable()


def _rows(n, seed=11):
    rng = np.random.RandomState(seed)
    return rng.randint(-3, 4, (n, DIM)).astype(np.float32)


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# tail sampling: keep/drop pinned by a fake clock, no real waiting
# ---------------------------------------------------------------------------

def test_tail_sampler_keeps_interesting_drops_the_rest():
    clk = _Clock()
    t = dtrace.Tracer(sample=0, buffer=64, keep=64, clock=clk,
                      epoch=0.0)

    def run_trace(error=None, child_tags=None, hedged=False):
        root = t.start_trace("fleet.request", request_id="r")
        clk.t += 0.010
        if child_tags is not None:
            t.emit("serve.request", root, clk.t - 0.005, clk.t,
                   tags=child_tags)
        if hedged:
            root.tag(hedged=True)
        t.finish_root(root, error=error)
        return root.trace_id

    # boring success: dropped at root-finish
    run_trace()
    assert t.kept == 0 and t.dropped == 1
    # errored: kept, reason "error"
    tid = run_trace(error=RuntimeError("boom"))
    assert t._kept[tid]["kept"] == "error"
    # shed: the typed RequestShed error maps to its own reason
    tid = run_trace(error=serving.RequestShed("req r shed"))
    assert t._kept[tid]["kept"] == "shed"
    # a shed child span also keeps (child-side shed, ok root path)
    tid = run_trace(child_tags={"shed": True})
    assert t._kept[tid]["kept"] == "shed"
    # SLO breach tagged by the scheduler's decomposition spans
    tid = run_trace(child_tags={"slo_breach": True})
    assert t._kept[tid]["kept"] == "slo"
    # hedged: kept even when it succeeded fast
    tid = run_trace(hedged=True)
    assert t._kept[tid]["kept"] == "hedge"
    assert t.kept == 5 and t.dropped == 1
    # in-flight buffer is drained either way
    assert t.stats()["in_flight"] == 0


def test_head_sample_floor_and_boring_drop_rate():
    """With 1-in-N head sampling armed, EVERY interesting trace is
    still kept and boring traces are kept at exactly the head rate."""
    clk = _Clock()
    t = dtrace.Tracer(sample=4, buffer=64, keep=64, clock=clk,
                      epoch=0.0)
    kept_boring = 0
    for i in range(20):
        root = t.start_trace("fleet.request")
        clk.t += 0.001
        t.finish_root(root)
        if root.trace_id in t._kept:
            kept_boring += 1
            assert t._kept[root.trace_id]["kept"] == "head"
    assert kept_boring == 5           # 20 / 4
    # interesting traces are NEVER subject to the head rate
    for _ in range(8):
        root = t.start_trace("fleet.request")
        root.tag(hedged=True)
        t.finish_root(root)
        assert t._kept[root.trace_id]["kept"] in ("hedge", "head")
    assert t.kept == 5 + 8


def test_inflight_buffer_bounded_and_keep_cap_evicts():
    clk = _Clock()
    t = dtrace.Tracer(sample=0, buffer=2, keep=2, clock=clk, epoch=0.0)
    r1 = t.start_trace("a")
    r2 = t.start_trace("b")
    # buffer full: the third request simply goes untraced
    assert t.start_trace("c") is None
    assert t.overflow == 1
    kept_ids = []
    for root in (r1, r2):
        root.tag(hedged=True)
        t.finish_root(root)
        kept_ids.append(root.trace_id)
    r3 = t.start_trace("d")
    r3.tag(hedged=True)
    t.finish_root(r3)
    kept_ids.append(r3.trace_id)
    # keep cap: oldest kept tree evicted first
    assert len(t._kept) == 2
    assert kept_ids[0] not in t._kept
    assert kept_ids[1] in t._kept and kept_ids[2] in t._kept


# ---------------------------------------------------------------------------
# span trees, ids, clock alignment across processes
# ---------------------------------------------------------------------------

def test_trace_and_span_id_widths(trc):
    root = trc.start_trace("fleet.request")
    child = trc.start_span("fleet.attempt", root)
    assert len(root.trace_id) == 32      # 128-bit trace id
    assert len(root.span_id) == 16       # 64-bit span id
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert root.parent_id == ""
    # the wire context is the minimal {trace, span} pair
    assert child.ctx() == {"t": child.trace_id, "s": child.span_id}
    child.finish()
    trc.finish_root(root, error=RuntimeError("keep me"))


def test_finish_is_idempotent_first_writer_wins(trc):
    root = trc.start_trace("fleet.request")
    a = trc.start_span("fleet.attempt", root)
    assert a.finish(won=True) is True
    assert a.finish(won=False, abandoned=True) is False
    root.tag(hedged=True)
    trc.finish_root(root)
    (rec,) = [s for s in trc._kept[root.trace_id]["spans"]
              if s["span"] == a.span_id]
    assert rec["tags"] == {"won": True}


def test_absorb_aligns_child_clock_via_shipped_epoch():
    """The child records on ITS monotonic clock; the router absorbs
    with the child's shipped epoch, landing the spans on the shared
    wall axis next to its own."""
    router_clk, child_clk = _Clock(), _Clock()
    child_clk.t = 5.0                      # wildly skewed perf_counter
    router = dtrace.Tracer(sample=0, buffer=8, keep=8,
                           clock=router_clk, epoch=1000.0)
    child = dtrace.Tracer(sample=0, buffer=8, keep=8,
                          clock=child_clk, epoch=1095.0)
    root = router.start_trace("fleet.request")
    ctx = {"t": root.trace_id, "s": root.span_id}
    # child-side span: wall time 1095 + 5 = 1100
    child.emit("serve.request", ctx, child_clk.t, child_clk.t + 0.010)
    payload = child.harvest(ctx)
    assert payload["epoch"] == 1095.0
    assert router.absorb(payload) == 1
    router_clk.t += 0.020                  # root: wall 1100 .. 1100.02
    root.tag(hedged=True)
    router.finish_root(root)
    spans = {s["name"]: s for s in router._kept[root.trace_id]["spans"]}
    assert spans["serve.request"]["ts"] == pytest.approx(1100.0)
    assert spans["fleet.request"]["ts"] == pytest.approx(1100.0)
    # the child interval nests inside the root interval on the shared
    # axis even though the two monotonic clocks never agreed
    r, c = spans["fleet.request"], spans["serve.request"]
    assert r["ts"] <= c["ts"]
    assert c["ts"] + c["dur"] <= r["ts"] + r["dur"] + 1e-9
    # harvest drained the child buffer; a second harvest ships nothing
    assert child.harvest(ctx) is None


def test_late_arrival_lands_in_already_kept_tree():
    """A hedge loser's reply arrives after the root finished: the
    spans are absorbed into the kept tree, not dropped."""
    clk = _Clock()
    t = dtrace.Tracer(sample=0, buffer=8, keep=8, clock=clk, epoch=0.0)
    root = t.start_trace("fleet.request")
    root.tag(hedged=True)
    t.finish_root(root)
    assert root.trace_id in t._kept
    before = len(t._kept[root.trace_id]["spans"])
    t.absorb({"epoch": 50.0, "spans": [
        {"trace": root.trace_id, "span": "feedfeedfeedfeed",
         "parent": root.span_id, "name": "serve.request", "pid": 4242,
         "tid": 1, "t0": 1.0, "dur": 0.002, "tags": {}}]})
    spans = t._kept[root.trace_id]["spans"]
    assert len(spans) == before + 1
    late = spans[-1]
    assert late["ts"] == pytest.approx(51.0)   # child epoch applied


# ---------------------------------------------------------------------------
# the wire: rolling-upgrade compatibility in BOTH directions
# ---------------------------------------------------------------------------

def _fake_parent_replica():
    sent = []

    class _FakeConn:
        def send(self, msg):
            sent.append(msg)

    rep = fleet.SubprocessReplica.__new__(fleet.SubprocessReplica)
    rep.rid = "r0"
    rep._lock = threading.Lock()
    rep._dead = False
    rep._closed = False
    rep._pending = {}
    rep._conn = _FakeConn()
    rep._proc = type("P", (), {"is_alive": staticmethod(lambda: True)})()
    return rep, sent


def test_untraced_envelope_stays_six_tuple():
    """No trace_ctx -> the wire message is EXACTLY the pre-trace
    layout; an old child's strict unpack keeps working."""
    rep, sent = _fake_parent_replica()
    rep.submit([_rows(1)], request_id="rid", deadline_ms=5.0,
               priority="batch")
    assert len(sent[0]) == 6


def test_traced_envelope_appends_ctx_old_child_ignores_tail():
    rep, sent = _fake_parent_replica()
    ctx = {"t": "ab" * 16, "s": "cd" * 8}
    rep.submit([_rows(1)], request_id="rid", deadline_ms=5.0,
               priority="batch", trace_ctx=ctx)
    msg = sent[0]
    assert len(msg) == 7 and msg[6] == ctx
    # an old child decodes the head conditionally and never looks past
    # what it knows — the appended ctx is invisible to it
    op, mid, request_id, arrays = msg[0], msg[1], msg[2], msg[3]
    deadline = msg[4] if len(msg) > 4 else None
    priority = msg[5] if len(msg) > 5 else None
    assert (op, request_id, deadline, priority) == \
        ("infer", "rid", 5.0, "batch")


class _PipeEnd:
    """One end of an in-memory duplex pipe driving the child main loop
    in a thread (no spawn, no jax)."""

    def __init__(self):
        import queue

        self._in = queue.Queue()
        self.sent = []

    def recv(self):
        msg = self._in.get()
        if msg is None:
            raise EOFError
        return msg

    def send(self, msg):
        self.sent.append(msg)

    def feed(self, msg):
        self._in.put(msg)

    def close(self):
        pass


class _TracingFakeServer:
    """Duck-typed InferenceServer for the child main loop: doubles the
    input; when the envelope carried a trace ctx it emits one span the
    harvest must ship back."""

    def __init__(self):
        self.closed = False

    def submit(self, arrays, request_id=None, deadline_ms=None,
               priority=None, trace_ctx=None):
        if trace_ctx is not None:
            t = dtrace.tracer()
            t.emit("serve.request", trace_ctx, 1.0, 1.002,
                   tags={"request_id": request_id})
        outs = [np.asarray(a) * 2.0 for a in arrays]

        class _Done:
            def get(self, timeout=None):
                return outs

        return _Done()

    def close(self):
        self.closed = True


def test_child_main_loop_reply_shapes_both_directions(
        monkeypatch, no_dtrace):
    """Old router (no trace_ctx) -> strict 3-tuple reply, tracer never
    armed. New router (trace_ctx) -> 4-tuple reply carrying the span
    payload with the child's epoch."""
    monkeypatch.setattr(fleet, "_resolve_factory",
                        lambda ref: _TracingFakeServer)
    conn = _PipeEnd()
    worker = threading.Thread(
        target=fleet._subprocess_replica_main, args=(conn, "x:y"),
        daemon=True)
    worker.start()
    x = _rows(1, seed=3)
    # old-style envelope: untraced, reply must stay a strict 3-tuple
    conn.feed(("infer", "m1", "rid-1", [x], 50.0, None))
    # traced envelope: reply grows the harvested-span payload
    ctx = {"t": "ee" * 16, "s": "ff" * 8}
    conn.feed(("infer", "m2", "rid-2", [x], 50.0, None, ctx))
    conn.feed(("stop", "m3"))
    worker.join(10.0)
    assert not worker.is_alive()
    replies = {m[1]: m for m in conn.sent}
    assert len(replies["m1"]) == 3
    kind, _, payload, spans_payload = replies["m2"]
    assert kind == "ok"
    assert np.array_equal(payload[0], x * 2.0)
    assert isinstance(spans_payload, dict)
    assert "epoch" in spans_payload
    (rec,) = spans_payload["spans"]
    assert rec["trace"] == ctx["t"] and rec["parent"] == ctx["s"]
    # a traced envelope armed the child's tracer lazily
    assert dtrace.enabled()


def test_old_router_missing_ctx_means_untraced(monkeypatch, no_dtrace):
    """An old router never sends trace_ctx: the new child must not arm
    its tracer and must not grow the reply."""
    monkeypatch.setattr(fleet, "_resolve_factory",
                        lambda ref: _TracingFakeServer)
    conn = _PipeEnd()
    worker = threading.Thread(
        target=fleet._subprocess_replica_main, args=(conn, "x:y"),
        daemon=True)
    worker.start()
    conn.feed(("infer", "m1", "rid-1", [_rows(1)], 50.0, None))
    conn.feed(("stop", "m2"))
    worker.join(10.0)
    assert len([m for m in conn.sent if m[1] == "m1"][0]) == 3
    assert not dtrace.enabled()


# ---------------------------------------------------------------------------
# router spans: root, attempts, hedging (fake replicas, no jax)
# ---------------------------------------------------------------------------

class _TraceFakeReplica(fleet.Replica):
    """ok | slow fake accepting the traced submit signature."""

    def __init__(self, rid, behavior="ok", slow_s=0.1):
        self.rid = rid
        self.behavior = behavior
        self.ctxs = []
        self._slow_s = slow_s

    def submit(self, arrays, request_id=None, deadline_ms=None,
               priority=None, trace_ctx=None):
        self.ctxs.append(trace_ctx)
        outs = [np.asarray(a) * 2.0 for a in arrays]
        if self.behavior == "slow":
            t_due = time.monotonic() + self._slow_s

            class _Slow:
                def wait(self, timeout_s):
                    rem = t_due - time.monotonic()
                    if rem > 0:
                        time.sleep(min(timeout_s, rem))
                        if timeout_s < rem:
                            raise fleet.AttemptTimeout("still slow")
                    return outs

                def cancel(self):
                    pass

            return _Slow()
        if self.behavior == "crash":
            raise fleet.ReplicaCrash("replica %s crashed" % self.rid)

        class _Ok:
            def wait(self, timeout_s):
                return outs

            def cancel(self):
                pass

        return _Ok()

    def alive(self):
        return True

    def health(self):
        return {"status": "ok", "in_flight": 0}

    def in_flight(self):
        return 0

    def refresh_params(self, apply_fn=None):
        pass

    def restart(self):
        pass

    def kill(self):
        pass

    def close(self):
        pass


def _trace_router(behaviors, **kw):
    made = {}
    queue = list(behaviors)

    def factory(rid):
        made[rid] = _TraceFakeReplica(rid, queue.pop(0) if queue
                                      else "ok")
        return made[rid]

    kw.setdefault("health_interval_s", 60.0)
    kw.setdefault("auto_respawn", False)
    kw.setdefault("deadline_ms", 5000.0)
    kw.setdefault("attempt_timeout_ms", 2000.0)
    kw.setdefault("retries", 4)
    kw.setdefault("backoff_ms", 1.0)
    return FleetRouter(factory, len(behaviors), **kw), made


def test_boring_request_traced_then_dropped(trc):
    router, made = _trace_router(["ok"], hedge=False)
    try:
        (out,) = router.infer([_rows(1)], timeout=10.0)
    finally:
        router.close()
    assert trc.kept == 0 and trc.dropped == 1
    # the attempt DID ride the wire with a ctx while in flight
    (ctx,) = made["r1"].ctxs
    assert set(ctx) == {"t", "s"}


def test_failed_request_keeps_trace_with_attempt_errors(trc):
    router, made = _trace_router(["crash", "crash"], hedge=False,
                                 retries=2, deadline_ms=500.0)
    try:
        with pytest.raises(fleet.FleetError):
            router.infer([_rows(1)], request_id="doomed", timeout=10.0)
    finally:
        router.close()
    (ent,) = trc.kept_traces()
    assert ent["kept"] == "error"
    assert ent["request_id"] == "doomed"
    by_name = {}
    for s in ent["spans"]:
        by_name.setdefault(s["name"], []).append(s)
    (root,) = by_name["fleet.request"]
    assert "FleetError" in root["tags"]["error"]
    attempts = by_name["fleet.attempt"]
    assert len(attempts) == 2
    for a in attempts:
        assert a["parent"] == root["span"]
        assert a["tags"]["won"] is False
        assert "ReplicaCrash" in a["tags"]["error"]
        assert a["tags"]["breaker"] == "closed"
    assert {a["tags"]["attempt"] for a in attempts} == {0, 1}
    assert {a["tags"]["replica"] for a in attempts} == {"r1", "r2"}


def test_hedged_trace_has_winning_and_abandoned_attempts(trc):
    router, made = _trace_router(["slow", "ok"], hedge=True)
    try:
        with router._rlock:
            router._lat.extend([0.004] * 30)   # pin hedge_after ~4ms
        (out,) = router.infer([_rows(1, seed=5)], timeout=10.0)
        assert np.array_equal(out, _rows(1, seed=5) * 2.0)
    finally:
        router.close()
    assert router.stats()["counters"].get("hedge_wins", 0) == 1
    (ent,) = trc.kept_traces()
    assert ent["kept"] == "hedge"
    attempts = [s for s in ent["spans"] if s["name"] == "fleet.attempt"]
    assert len(attempts) == 2
    by_replica = {a["tags"]["replica"]: a for a in attempts}
    assert by_replica["r2"]["tags"]["won"] is True
    assert by_replica["r2"]["tags"]["hedge"] is True
    assert by_replica["r1"]["tags"]["won"] is False
    assert by_replica["r1"]["tags"]["abandoned"] is True
    # both attempts carried their own ctx on the wire
    assert made["r1"].ctxs[0]["s"] == by_replica["r1"]["span"]
    assert made["r2"].ctxs[0]["s"] == by_replica["r2"]["span"]


# ---------------------------------------------------------------------------
# scheduler decomposition spans (real BatchScheduler, fake infer)
# ---------------------------------------------------------------------------

def _fake_infer(placed):
    return [placed[0] * 2.0], ()


def test_scheduler_emits_five_components_summing_to_request(trc):
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0)
    try:
        root = trc.start_trace("fleet.request")
        ctx = root.ctx()
        req = sched.submit([_rows(1)], request_id="q1", trace_ctx=ctx)
        req.get(timeout=30)
        root.tag(hedged=True)          # force the keep
        trc.finish_root(root)
    finally:
        sched.close()
    (ent,) = trc.kept_traces()
    spans = {s["name"]: s for s in ent["spans"]}
    request = spans["serve.request"]
    assert request["parent"] == root.span_id
    assert request["tags"]["request_id"] == "q1"
    comp_names = ("serve.queue", "serve.sched_idle", "serve.h2d",
                  "serve.dispatch", "serve.d2h")
    total = 0.0
    for name in comp_names:
        s = spans[name]
        assert s["parent"] == request["span"]
        total += s["dur"]
    # the EXACT decomposition: five children partition the parent
    assert total == pytest.approx(request["dur"], rel=1e-6, abs=1e-9)
    assert total * 1e3 == pytest.approx(req.latency_ms, rel=1e-6)
    batch = spans["serve.batch_dispatch"]
    assert batch["tags"]["bucket"] >= 1
    assert batch["tags"]["compile"] is True     # first dispatch
    assert spans["serve.dispatch"]["tags"]["batch"] == batch["span"]
    assert spans["serve.h2d"]["tags"]["fastpath"] in (True, False)
    assert spans["serve.h2d"]["tags"]["h2d_bytes"] > 0


def test_slo_breach_keeps_trace_and_probe_names_it(trc):
    def slow_infer(placed):
        time.sleep(0.01)
        return [placed[0] * 2.0], ()

    sched = BatchScheduler(slow_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=0.5, slo_ms=0.001)
    try:
        root = trc.start_trace("fleet.request")
        sched.submit([_rows(1)], trace_ctx=root.ctx()).get(timeout=30)
        trc.finish_root(root)          # NOT hedged: slo tag must keep
        probe = sched.slo_probe()
    finally:
        sched.close()
    (ent,) = trc.kept_traces()
    assert ent["kept"] == "slo"
    req = [s for s in ent["spans"] if s["name"] == "serve.request"]
    assert req and req[0]["tags"]["slo_breach"] is True
    assert probe is not None
    assert probe["worst_trace_id"] == root.trace_id


def test_slow_request_detector_event_carries_worst_trace_id():
    det = SlowRequestDetector()
    ev = det.check({"request_ms": 9.0, "slo_ms": 1.0,
                    "worst_trace_id": "aa" * 16, "queue_depth": 3})
    assert ev["type"] == "slow_request"
    assert ev["worst_trace_id"] == "aa" * 16
    assert ev["queue_depth"] == 3
    # records without a sampled trace simply omit the key
    ev2 = det.check({"request_ms": 9.0, "slo_ms": 1.0})
    assert "worst_trace_id" not in ev2


def test_shed_request_keeps_trace_with_shed_span(trc):
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0,
                           autostart=False, clock=time.perf_counter)
    try:
        root = trc.start_trace("fleet.request")
        req = sched.submit([_rows(1)], request_id="victim",
                           deadline_ms=0.001, trace_ctx=root.ctx())
        # enough backlog that the shed threshold trips
        for i in range(12):
            sched.submit([_rows(1, seed=i)])
        time.sleep(0.002)
        sched._admit_intake()
        sched._maybe_shed(sched._clock())
        assert req.done()
        with pytest.raises(serving.RequestShed):
            req.get(timeout=0)
        trc.finish_root(root, error=req.error)
    finally:
        sched.close()
    (ent,) = trc.kept_traces()
    assert ent["kept"] == "shed"
    shed = [s for s in ent["spans"] if s["name"] == "serve.shed"]
    assert shed and shed[0]["tags"]["shed"] is True
    assert shed[0]["tags"]["request_id"] == "victim"


# ---------------------------------------------------------------------------
# disabled cost: no tracer, no spans, no counters, untouched wire
# ---------------------------------------------------------------------------

def test_disabled_is_inert_everywhere(no_dtrace):
    assert dtrace.tracer() is None
    assert dtrace.stats() == {}
    assert dtrace.kept_traces() == []
    assert dtrace.to_chrome_events() == []
    assert dtrace.harvest({"t": "x", "s": "y"}) is None
    assert dtrace.absorb({"epoch": 0, "spans": []}) == 0
    dtrace.finish_root(None)           # no-op, no error
    router, made = _trace_router(["ok"], hedge=False)
    try:
        router.infer([_rows(1)], timeout=10.0)
    finally:
        router.close()
    assert made["r1"].ctxs == [None]   # nothing rode the wire
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0)
    try:
        sched.submit([_rows(1)]).get(timeout=30)
    finally:
        sched.close()
    assert dtrace.stats() == {}        # never lazily armed


def test_env_reload_arms_and_disarms(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_DTRACE", "1")
    monkeypatch.setenv("MXNET_TPU_DTRACE_SAMPLE", "7")
    assert dtrace.reload() is not None
    assert dtrace.tracer()._sample == 7
    monkeypatch.delenv("MXNET_TPU_DTRACE")
    assert dtrace.reload() is None
    assert not dtrace.enabled()


# ---------------------------------------------------------------------------
# export: chrome events, lanes, flow stitching, waterfall text
# ---------------------------------------------------------------------------

def _kept_cross_pid_tracer():
    clk = _Clock()
    t = dtrace.Tracer(sample=0, buffer=8, keep=8, clock=clk, epoch=0.0)
    root = t.start_trace("fleet.request", request_id="rq")
    att = t.start_span("fleet.attempt", root,
                       tags={"attempt": 0, "replica": "r1"})
    ctx = att.ctx()
    clk.t += 0.002
    att.finish(won=True)
    # replica-side spans arrive via the wire from another pid
    base = 7.0
    spans = [{"trace": root.trace_id, "span": "a" * 16,
              "parent": ctx["s"], "name": "serve.request", "pid": 4242,
              "tid": 9, "t0": base, "dur": 0.0015, "tags": {}}]
    for i, name in enumerate(("serve.queue", "serve.sched_idle",
                              "serve.h2d", "serve.dispatch",
                              "serve.d2h")):
        spans.append({"trace": root.trace_id, "span": "b%015x" % i,
                      "parent": "a" * 16, "name": name, "pid": 4242,
                      "tid": 9, "t0": base + 0.0003 * i, "dur": 0.0003,
                      "tags": {}})
    assert t.absorb({"epoch": 100.0 - base + 0.0002,
                     "spans": spans}) == 6
    root.tag(hedged=True)
    t.finish_root(root)
    return t, root


def test_chrome_events_lanes_and_flow(trc):
    t, root = _kept_cross_pid_tracer()
    events = t.to_chrome_events()
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {os.getpid(), 4242}
    # one lane-name metadata event per pid, role-labelled
    metas = {e["pid"]: e["args"]["name"] for e in events
             if e["ph"] == "M"}
    assert "router" in metas[os.getpid()]
    assert "replica" in metas[4242]
    # the cross-pid parent edge is stitched with a flow pair
    starts = [e for e in events if e["ph"] == "s"]
    finishes = [e for e in events if e["ph"] == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["id"] == finishes[0]["id"]
    assert starts[0]["pid"] == os.getpid()
    assert finishes[0]["pid"] == 4242
    assert finishes[0]["bp"] == "e"
    # flow binds inside the parent attempt's interval
    att = next(e for e in xs if e["name"] == "fleet.attempt")
    assert att["ts"] <= starts[0]["ts"] <= att["ts"] + att["dur"]


def test_write_chrome_trace_merges_and_loads(trc, tmp_path, monkeypatch):
    t, root = _kept_cross_pid_tracer()
    monkeypatch.setattr(dtrace, "_TRACER", t)
    with telemetry.span("host_work"):
        pass
    path = str(tmp_path / "FLEET_trace.json")
    n = dtrace.write_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert n == len(evs)
    cats = {e.get("cat") for e in evs}
    assert "dtrace" in cats and "host" in cats   # merged, one file
    trees = trace_report.dtrace_trees(evs)
    assert list(trees) == [root.trace_id]
    assert len(trees[root.trace_id]) == 8        # root+attempt+request+5


def test_waterfall_renders_tree_and_decomposition(tmp_path):
    t, root = _kept_cross_pid_tracer()
    events = t.to_chrome_events()
    trees = trace_report.dtrace_trees(events)
    out = trace_report.render_waterfall(root.trace_id,
                                        trees[root.trace_id])
    assert root.trace_id in out
    assert "kept=hedge" in out
    for name in ("fleet.request", "fleet.attempt", "serve.request",
                 "serve.queue", "serve.sched_idle", "serve.h2d",
                 "serve.dispatch", "serve.d2h"):
        assert name in out
    assert "2 processes" in out
    # the five-way decomposition line, parts summing to the request
    assert "decomposition of serve.request" in out
    assert "= 1.50ms (request span 1.50ms)" in out
    # summary view ranks kept traces and names the dominant span
    summary = trace_report.render_trace_summary(trees)
    assert root.trace_id[:16] in summary
    assert "dominant" in summary and "waterfall" in summary


def test_waterfall_cli_resolves_id_prefix(tmp_path, monkeypatch, trc):
    t, root = _kept_cross_pid_tracer()
    monkeypatch.setattr(dtrace, "_TRACER", t)
    path = str(tmp_path / "FLEET_trace.json")
    dtrace.write_chrome_trace(path)
    monkeypatch.setattr(trace_report, "_repo_root", lambda: str(tmp_path))
    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = trace_report.main(["--view", "waterfall",
                                root.trace_id[:8]])
    assert rc == 0
    assert "serve.dispatch" in buf.getvalue()
    buf2 = io.StringIO()
    with contextlib.redirect_stdout(buf2):
        rc2 = trace_report.main(["--view", "waterfall", path])
    assert rc2 == 0 and root.trace_id in buf2.getvalue()


# ---------------------------------------------------------------------------
# the real wire: a spawned replica's spans, clock-aligned and nested
# ---------------------------------------------------------------------------

def test_subprocess_end_to_end_traced_and_clock_aligned():
    dtrace.enable(sample=1)            # head-keep every trace
    router = FleetRouter(
        fleet.in_subprocess("mxnet_tpu.fleet:demo_server_factory"), 1,
        deadline_ms=120000.0, attempt_timeout_ms=60000.0, retries=5,
        backoff_ms=50.0, health_interval_s=60.0, hedge=False)
    try:
        x = _rows(1, seed=3)
        (out,) = router.infer([x], request_id="e2e", timeout=120.0)
        assert out.shape[0] == 1
    finally:
        router.close()
        kept = dtrace.kept_traces()
        dtrace.disable()
    ent = next(e for e in kept if e["request_id"] == "e2e")
    spans = {s["name"]: s for s in ent["spans"]}
    root = spans["fleet.request"]
    att = spans["fleet.attempt"]
    request = spans["serve.request"]
    assert root["pid"] == os.getpid()
    assert request["pid"] != os.getpid()          # really remote
    assert request["parent"] == att["span"]       # stitched across
    assert att["parent"] == root["span"]          # the wire
    assert att["tags"]["won"] is True
    # clock alignment: the remote spans land INSIDE the root's wall
    # interval (same host, per-process epochs measured independently)
    eps = 0.025
    for name in ("serve.request", "serve.queue", "serve.sched_idle",
                 "serve.h2d", "serve.dispatch", "serve.d2h",
                 "serve.batch_dispatch"):
        s = spans[name]
        assert s["ts"] >= root["ts"] - eps
        assert s["ts"] + s["dur"] <= root["ts"] + root["dur"] + eps
    total = sum(spans[n]["dur"] for n in
                ("serve.queue", "serve.sched_idle", "serve.h2d",
                 "serve.dispatch", "serve.d2h"))
    assert total == pytest.approx(spans["serve.request"]["dur"],
                                  rel=1e-6, abs=1e-9)


def test_socket_end_to_end_traced_across_the_wire():
    """The socket hop carries the trace context inside the frame
    metadata ("tctx" out, harvested spans back in the reply): the
    remote serve.* spans stitch under the local attempt span exactly
    like the pipe path, so one request is one tree whichever transport
    served it."""
    dtrace.enable(sample=1)            # head-keep every trace
    router = FleetRouter(
        fleet.in_socket("mxnet_tpu.fleet:demo_server_factory"), 1,
        deadline_ms=120000.0, attempt_timeout_ms=60000.0, retries=5,
        backoff_ms=50.0, health_interval_s=60.0, hedge=False)
    try:
        x = _rows(1, seed=3)
        (out,) = router.infer([x], request_id="wire-e2e", timeout=120.0)
        assert out.shape[0] == 1
    finally:
        router.close()
        kept = dtrace.kept_traces()
        dtrace.disable()
    ent = next(e for e in kept if e["request_id"] == "wire-e2e")
    spans = {s["name"]: s for s in ent["spans"]}
    root = spans["fleet.request"]
    att = spans["fleet.attempt"]
    request = spans["serve.request"]
    assert root["pid"] == os.getpid()
    assert request["pid"] != os.getpid()          # served over TCP
    assert request["parent"] == att["span"]       # stitched across
    assert att["parent"] == root["span"]          # the socket hop
    # clock alignment holds across the wire exactly like the pipe
    eps = 0.025
    assert request["ts"] >= root["ts"] - eps
    assert (request["ts"] + request["dur"]
            <= root["ts"] + root["dur"] + eps)
