"""Native C++ runtime tests: dependency engine (vs serial oracle, like the
reference's tests/cpp/threaded_engine_test.cc) and recordio codec
cross-compatibility with the Python implementation."""
import os
import threading

import numpy as np
import pytest

from mxnet_tpu import engine as eng
from mxnet_tpu._native_lib import get_lib

pytestmark = pytest.mark.skipif(get_lib() is None,
                                reason="native library unavailable")


def test_native_engine_vs_serial_oracle():
    from tests.test_engine import _random_workload, _run_workload

    ops = _random_workload(seed=7, num_ops=300)
    oracle_state, oracle_logs = _run_workload(eng.NaiveEngine(), ops, 10)
    native = eng.NativeThreadedEngine(num_workers=4)
    state, logs = _run_workload(native, ops, 10)
    assert state == oracle_state
    assert logs == oracle_logs


def test_native_engine_write_serialization():
    engine = eng.NativeThreadedEngine(num_workers=8)
    v = engine.new_variable()
    counter = {"x": 0, "max_in_flight": 0}
    lock = threading.Lock()

    def writer():
        with lock:
            counter["x"] += 1
            counter["max_in_flight"] = max(counter["max_in_flight"],
                                           counter["x"])
        with lock:
            counter["x"] -= 1

    for _ in range(200):
        engine.push(writer, mutable_vars=[v])
    engine.wait_for_all()
    assert counter["max_in_flight"] == 1


def test_native_engine_error_propagation():
    engine = eng.NativeThreadedEngine(num_workers=2)

    def boom():
        raise ValueError("boom")
    engine.push(boom)
    with pytest.raises(ValueError, match="boom"):
        engine.wait_for_all()
    # engine still usable after the error
    out = []
    engine.push(lambda: out.append(1))
    engine.wait_for_all()
    assert out == [1]


def test_native_recordio_python_interop(tmp_path):
    """Files written natively must read back through pure Python and vice
    versa (same on-disk format)."""
    from mxnet_tpu import recordio as rio

    payloads = [b"alpha", b"", b"x" * 1001, b"tail"]

    native_path = str(tmp_path / "native.rec")
    w = rio.MXRecordIO(native_path, "w")
    assert w._h is not None, "native path not active"
    offs = [w.write(p) for p in payloads]
    w.close()
    assert offs[0] == 0 and offs[1] > offs[0]

    # read with pure python
    os.environ["MXNET_TPU_NO_NATIVE"] = "1"
    try:
        import mxnet_tpu._native_lib as nl

        saved = (nl._lib, nl._tried)
        nl._lib, nl._tried = None, True
        r = rio.MXRecordIO(native_path, "r")
        assert r._h is None
        got = []
        while True:
            rec = r.read()
            if rec is None:
                break
            got.append(rec)
        r.close()
        assert got == payloads

        # write with pure python, read natively
        py_path = str(tmp_path / "py.rec")
        w2 = rio.MXRecordIO(py_path, "w")
        for p in payloads:
            w2.write(p)
        w2.close()
    finally:
        nl._lib, nl._tried = saved
        del os.environ["MXNET_TPU_NO_NATIVE"]

    r2 = rio.MXRecordIO(py_path, "r")
    assert r2._h is not None
    got2 = []
    while True:
        rec = r2.read()
        if rec is None:
            break
        got2.append(rec)
    r2.close()
    assert got2 == payloads


def test_native_indexed_recordio(tmp_path):
    from mxnet_tpu import recordio as rio

    path = str(tmp_path / "x.rec")
    idx_path = str(tmp_path / "x.idx")
    w = rio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(20):
        w.write_idx(i, ("payload-%d" % i).encode())
    w.close()
    r = rio.MXIndexedRecordIO(idx_path, path, "r")
    assert r.read_idx(13) == b"payload-13"
    assert r.read_idx(0) == b"payload-0"
    assert r.read_idx(19) == b"payload-19"
    r.close()
