"""Extended operator tests, porting the remaining coverage of the
reference's tests/python/unittest/test_operator.py (41 cases) that
tests/test_operator.py does not already hold: scalar/symbol arithmetic,
the unary functor zoo, broadcast binaries, matrix ops (dot/batch_dot,
swapaxes, crop/slice_axis/flip, reshape 0/-1/reverse), conv variants
(grouping, dilated impulse response, deconvolution), vision ops
(ROIPooling, SpatialTransformer, Correlation, nearest upsampling), and
SVM outputs. Oracles are numpy closed forms or finite differences — same
strategy as the reference, fresh implementations."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.test_utils import (check_numeric_gradient, reldiff)


def _run(s, args_np, out_grads=None, grad_req="write"):
    """bind, forward(train), optionally backward; returns (outputs, grads)."""
    args = {k: mx.nd.array(v) for k, v in args_np.items()}
    grads = {k: mx.nd.zeros(np.asarray(v).shape) for k, v in args_np.items()}
    req = grad_req if isinstance(grad_req, dict) \
        else {k: grad_req for k in args_np}
    ex = s.bind(mx.cpu(), args, args_grad=grads, grad_req=req)
    ex.forward(is_train=True)
    if out_grads is not None:
        ex.backward([mx.nd.array(g) for g in out_grads])
    return ([o.asnumpy() for o in ex.outputs],
            {k: v.asnumpy() for k, v in grads.items()})


def test_swapaxes():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4).astype(np.float32)
    s = sym.SwapAxis(data=sym.Variable("data"), dim1=0, dim2=2)
    outs, grads = _run(s, {"data": x},
                       out_grads=[np.ones((4, 3, 2), np.float32)])
    np.testing.assert_allclose(outs[0], np.swapaxes(x, 0, 2), rtol=1e-6)
    np.testing.assert_allclose(grads["data"], np.ones_like(x))


def test_scalar_op_composition():
    """(4x + 2) / 2 - 2.5 etc. through operator overloading."""
    rng = np.random.RandomState(1)
    x = rng.rand(2, 3).astype(np.float32) + 1.0
    data = sym.Variable("data")
    s = ((data * 4 + 2) / 2 - 0.5) * 2
    outs, grads = _run(s, {"data": x},
                       out_grads=[np.ones_like(x)])
    np.testing.assert_allclose(outs[0], ((x * 4 + 2) / 2 - 0.5) * 2,
                               rtol=1e-5)
    np.testing.assert_allclose(grads["data"], np.full_like(x, 4.0),
                               rtol=1e-5)


def test_scalar_pow():
    rng = np.random.RandomState(2)
    x = rng.rand(3, 4).astype(np.float32) + 0.5
    data = sym.Variable("data")
    g = rng.rand(3, 4).astype(np.float32)
    outs, grads = _run(data ** 2, {"data": x}, out_grads=[g])
    np.testing.assert_allclose(outs[0], x ** 2, rtol=1e-5)
    np.testing.assert_allclose(grads["data"], 2 * x * g, rtol=1e-4)


def test_symbol_pow():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 3).astype(np.float32) + 0.5
    y = rng.rand(2, 3).astype(np.float32) + 0.5
    g = rng.rand(2, 3).astype(np.float32)
    s = sym.Variable("x") ** sym.Variable("y")
    outs, grads = _run(s, {"x": x, "y": y}, out_grads=[g])
    np.testing.assert_allclose(outs[0], x ** y, rtol=1e-5)
    np.testing.assert_allclose(grads["x"], g * y * x ** (y - 1), rtol=1e-4)
    np.testing.assert_allclose(grads["y"], g * x ** y * np.log(x), rtol=1e-4)


def test_pow_fn():
    """scalar ** symbol (reference test_pow_fn: 2**x)."""
    rng = np.random.RandomState(4)
    x = rng.rand(1, 4).astype(np.float32)
    g = rng.rand(1, 4).astype(np.float32)
    s = 2 ** sym.Variable("x")
    outs, grads = _run(s, {"x": x}, out_grads=[g])
    np.testing.assert_allclose(outs[0], 2 ** x, rtol=1e-5)
    np.testing.assert_allclose(grads["x"], g * np.log(2) * 2 ** x,
                               rtol=1e-4)


def test_binary_op_duplicate_input():
    """The same variable feeding both sides accumulates both grads
    (reference test_binary_op_duplicate_input)."""
    rng = np.random.RandomState(5)
    x = rng.rand(3, 4).astype(np.float32) + 0.5
    g = rng.rand(3, 4).astype(np.float32)
    data = sym.Variable("data")
    outs, grads = _run(data * data, {"data": x}, out_grads=[g])
    np.testing.assert_allclose(outs[0], x * x, rtol=1e-5)
    np.testing.assert_allclose(grads["data"], 2 * x * g, rtol=1e-4)
    outs, grads = _run(data + data, {"data": x}, out_grads=[g])
    np.testing.assert_allclose(grads["data"], 2 * g, rtol=1e-5)


def test_sign_round_ceil_floor():
    rng = np.random.RandomState(6)
    x = (rng.randn(3, 4) * 3).astype(np.float32)
    g = rng.rand(3, 4).astype(np.float32)
    for name, fn in [("sign", np.sign), ("round", np.round),
                     ("ceil", np.ceil), ("floor", np.floor)]:
        s = getattr(sym, name)(sym.Variable("data"))
        outs, grads = _run(s, {"data": x}, out_grads=[g])
        np.testing.assert_allclose(outs[0], fn(x), rtol=1e-6,
                                   err_msg=name)
        # piecewise-constant: zero gradient everywhere (reference functors)
        np.testing.assert_allclose(grads["data"], np.zeros_like(x),
                                   atol=1e-7, err_msg=name)


def test_abs_grad():
    rng = np.random.RandomState(7)
    x = (rng.randn(3, 4) * 2 + 0.1).astype(np.float32)
    g = rng.rand(3, 4).astype(np.float32)
    outs, grads = _run(sym.abs(sym.Variable("data")), {"data": x},
                       out_grads=[g])
    np.testing.assert_allclose(outs[0], np.abs(x), rtol=1e-6)
    np.testing.assert_allclose(grads["data"], np.sign(x) * g, rtol=1e-5)


def test_rsqrt_cos_sin():
    rng = np.random.RandomState(8)
    x = (rng.rand(3, 4) + 0.5).astype(np.float32)
    g = rng.rand(3, 4).astype(np.float32)
    cases = [
        ("rsqrt", lambda v: 1 / np.sqrt(v), lambda v: -0.5 * v ** -1.5),
        ("cos", np.cos, lambda v: -np.sin(v)),
        ("sin", np.sin, np.cos),
    ]
    for name, fn, dfn in cases:
        s = getattr(sym, name)(sym.Variable("data"))
        outs, grads = _run(s, {"data": x}, out_grads=[g])
        np.testing.assert_allclose(outs[0], fn(x), rtol=1e-5, err_msg=name)
        np.testing.assert_allclose(grads["data"], dfn(x) * g, rtol=1e-4,
                                   err_msg=name)


def test_maximum_minimum():
    rng = np.random.RandomState(9)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3, 4).astype(np.float32)
    g = rng.rand(3, 4).astype(np.float32)
    va, vb = sym.Variable("a"), sym.Variable("b")
    s = sym.maximum(va, vb) + sym.minimum(va, vb)
    outs, grads = _run(s, {"a": a, "b": b}, out_grads=[g])
    np.testing.assert_allclose(outs[0], np.maximum(a, b) + np.minimum(a, b),
                               rtol=1e-5)
    # each element contributes exactly once to each input
    np.testing.assert_allclose(grads["a"], g, rtol=1e-5)
    np.testing.assert_allclose(grads["b"], g, rtol=1e-5)


def test_maximum_minimum_scalar():
    rng = np.random.RandomState(10)
    a = (rng.rand(3, 4) * 2).astype(np.float32)
    g = rng.rand(3, 4).astype(np.float32)
    s = sym.maximum(sym.Variable("a"), 1.0)
    outs, grads = _run(s, {"a": a}, out_grads=[g])
    np.testing.assert_allclose(outs[0], np.maximum(a, 1.0), rtol=1e-6)
    np.testing.assert_allclose(grads["a"], g * (a > 1.0), rtol=1e-5)
    s = sym.minimum(sym.Variable("a"), 1.0)
    outs, grads = _run(s, {"a": a}, out_grads=[g])
    np.testing.assert_allclose(outs[0], np.minimum(a, 1.0), rtol=1e-6)
    np.testing.assert_allclose(grads["a"], g * (a < 1.0), rtol=1e-5)


def test_broadcast_binary_ops():
    rng = np.random.RandomState(11)
    a = (rng.rand(2, 1, 4) + 0.5).astype(np.float32)
    b = (rng.rand(2, 3, 1) + 0.5).astype(np.float32)
    g = rng.rand(2, 3, 4).astype(np.float32)
    cases = [
        ("broadcast_plus", lambda x, y: x + y,
         lambda x, y: (g, g)),
        ("broadcast_minus", lambda x, y: x - y,
         lambda x, y: (g, -g)),
        ("broadcast_mul", lambda x, y: x * y,
         lambda x, y: (g * y, g * x)),
        ("broadcast_div", lambda x, y: x / y,
         lambda x, y: (g / y, -g * x / (y * y))),
        ("broadcast_power", lambda x, y: x ** y,
         lambda x, y: (g * y * x ** (y - 1), g * x ** y * np.log(x))),
    ]
    for name, fn, dfn in cases:
        s = getattr(sym, name)(sym.Variable("a"), sym.Variable("b"))
        _, out_shapes, _ = s.infer_shape(a=a.shape, b=b.shape)
        assert out_shapes[0] == (2, 3, 4), name
        outs, grads = _run(s, {"a": a, "b": b}, out_grads=[g])
        np.testing.assert_allclose(outs[0], fn(a, b), rtol=1e-5,
                                   err_msg=name)
        da, db = dfn(a, b)
        np.testing.assert_allclose(
            grads["a"], da.sum(axis=1, keepdims=True), rtol=1e-4,
            err_msg=name)
        np.testing.assert_allclose(
            grads["b"], db.sum(axis=2, keepdims=True), rtol=1e-4,
            err_msg=name)


def test_convolution_grouping():
    """num_group=2 equals two independent half-convs concatenated
    (reference test_convolution_grouping, built from our own ops)."""
    rng = np.random.RandomState(12)
    num_filter, num_group, c, h, w = 4, 2, 6, 7, 7
    x = rng.randn(2, c, h, w).astype(np.float32)
    wgt = rng.randn(num_filter, c // num_group, 3, 3).astype(np.float32)
    bias = rng.randn(num_filter).astype(np.float32)

    s = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3),
                        num_filter=num_filter, num_group=num_group,
                        name="conv")
    outs, _ = _run(s, {"data": x, "conv_weight": wgt, "conv_bias": bias},
                   grad_req="null")

    halves = []
    for gi in range(num_group):
        ci = slice(gi * c // num_group, (gi + 1) * c // num_group)
        fi = slice(gi * num_filter // num_group,
                   (gi + 1) * num_filter // num_group)
        sg = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3),
                             num_filter=num_filter // num_group, name="g")
        o, _ = _run(sg, {"data": x[:, ci], "g_weight": wgt[fi],
                         "g_bias": bias[fi]}, grad_req="null")
        halves.append(o[0])
    np.testing.assert_allclose(outs[0], np.concatenate(halves, axis=1),
                               rtol=1e-4, atol=1e-5)


def test_convolution_dilated_impulse_response():
    """A centered impulse through a dilated conv of ones lights up exactly
    the dilated kernel footprint (reference dilated impulse test)."""
    for dil in [(1, 1), (2, 2), (3, 3)]:
        x = np.zeros((1, 1, 18, 18), dtype=np.float32)
        x[0, 0, 9, 9] = 1.0
        k = np.ones((1, 1, 3, 3), dtype=np.float32)
        s = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3),
                            num_filter=1, dilate=dil, no_bias=True,
                            pad=(dil[0], dil[1]), name="conv")
        outs, _ = _run(s, {"data": x, "conv_weight": k}, grad_req="null")
        out = outs[0][0, 0]
        assert out.shape == (18, 18)
        nz = np.transpose(np.nonzero(out))
        expected = {(9 + dy * dil[0], 9 + dx * dil[1])
                    for dy in (-1, 0, 1) for dx in (-1, 0, 1)}
        assert {tuple(p) for p in nz} == expected, dil


def test_deconvolution_gradient():
    rng = np.random.RandomState(13)
    x = rng.randn(2, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 4, 3, 3).astype(np.float32) * 0.3
    s = sym.Deconvolution(data=sym.Variable("data"), kernel=(3, 3),
                          num_filter=4, no_bias=True, name="deconv")
    _, out_shapes, _ = s.infer_shape(data=x.shape)
    assert out_shapes[0] == (2, 4, 7, 7)
    check_numeric_gradient(s, {"data": x, "deconv_weight": w},
                           numeric_eps=1e-2, check_eps=0.05)


def test_deconvolution_inverts_convolution_shape():
    """conv(deconv(x)) and deconv(conv(x)) restore spatial dims for
    matching stride/kernel/pad (reference test_deconvolution checks the
    same shape algebra)."""
    for kernel, stride, pad in [((3, 3), (2, 2), (1, 1)),
                                ((5, 5), (1, 1), (2, 2))]:
        data = sym.Variable("data")
        conv = sym.Convolution(data=data, kernel=kernel, stride=stride,
                               pad=pad, num_filter=4, name="conv")
        deconv = sym.Deconvolution(data=conv, kernel=kernel, stride=stride,
                                   pad=pad, num_filter=3, name="dc")
        _, out_shapes, _ = deconv.infer_shape(data=(2, 3, 9, 9))
        assert out_shapes[0] == (2, 3, 9, 9), (kernel, stride, pad)


def test_nearest_upsampling():
    rng = np.random.RandomState(14)
    for scale in (2, 3):
        x = rng.randn(1, 2, 3, 3).astype(np.float32)
        s = sym.UpSampling(sym.Variable("data"), scale=scale,
                           sample_type="nearest", num_args=1)
        g = rng.rand(1, 2, 3 * scale, 3 * scale).astype(np.float32)
        outs, grads = _run(s, {"data": x}, out_grads=[g])
        expected = x.repeat(scale, axis=2).repeat(scale, axis=3)
        np.testing.assert_allclose(outs[0], expected, rtol=1e-6)
        # backward of nearest upsampling = sum-pool the head grad
        gsum = g.reshape(1, 2, 3, scale, 3, scale).sum(axis=(3, 5))
        np.testing.assert_allclose(grads["data"], gsum, rtol=1e-5)


def test_reshape_cases():
    """0 (keep) / -1 (infer) / reverse semantics, all reference cases."""
    cases = [[(2, 3, 5, 5), (0, -1), False, (2, 75)],
             [(2, 3, 5, 5), (0, 0, -1), False, (2, 3, 25)],
             [(5, 3, 4, 5), (0, -1, 0), False, (5, 15, 4)],
             [(2, 3, 5, 4), (-1, 0, 0), False, (8, 3, 5)],
             [(2, 3, 5, 5), (0, 0, 0, 0), False, (2, 3, 5, 5)],
             [(2, 4, 5, 3), (-1, 2, 2, 1), False, (30, 2, 2, 1)],
             [(2, 3, 5, 5), (0, -1), True, (5, 30)],
             [(2, 3, 5, 5), (0, 0, -1), True, (3, 5, 10)],
             [(5, 3, 4, 5), (0, -1, 0), True, (3, 20, 5)],
             [(2, 3, 5, 4), (-1, 0, 0), True, (6, 5, 4)],
             [(2, 3, 4, 5), (3, -1, 0), True, (3, 8, 5)],
             [(2, 3, 5, 5), (5, 3, 0, -1), True, (5, 3, 5, 2)],
             [(2, 3, 5, 5), (0, 0, 0, 0), True, (2, 3, 5, 5)]]
    rng = np.random.RandomState(15)
    for src, shape_args, reverse, dst in cases:
        net = sym.Reshape(sym.Variable("data"), shape=shape_args,
                          reverse=reverse)
        net = sym.load_json(net.tojson())       # serialization roundtrip
        _, out_shapes, _ = net.infer_shape(data=src)
        assert out_shapes[0] == dst, (src, shape_args, reverse)
        x = rng.rand(*src).astype(np.float32)
        g = rng.rand(*dst).astype(np.float32)
        outs, grads = _run(net, {"data": x}, out_grads=[g])
        np.testing.assert_allclose(outs[0], x.reshape(dst), rtol=1e-6)
        np.testing.assert_allclose(grads["data"], g.reshape(src), rtol=1e-6)
    # old api: target_shape
    net = sym.Reshape(sym.Variable("data"), target_shape=(2, 0))
    net = sym.load_json(net.tojson())
    _, out_shapes, _ = net.infer_shape(data=(2, 3, 5, 5))
    assert out_shapes[0] == (2, 75)


def test_reduce_random_sweep():
    """Random shapes/axes/keepdims for sum (reference test_reduce, fewer
    samples — XLA compile per shape is the cost here)."""
    rng = np.random.RandomState(16)
    for _ in range(20):
        ndim = rng.randint(1, 6)
        shape = tuple(rng.randint(1, 6, size=ndim))
        axes = tuple(a for a in range(ndim) if rng.rand() < 0.5) or None
        keepdims = bool(rng.randint(0, 2))
        kwargs = {"keepdims": keepdims}
        if axes is not None:
            kwargs["axis"] = axes
        s = sym.sum(sym.Variable("a"), **kwargs)
        x = rng.rand(*shape).astype(np.float32)
        expected = np.sum(x, axis=axes, keepdims=keepdims)
        if expected.shape == ():
            expected = expected.reshape(1)
        g = rng.rand(*expected.shape).astype(np.float32)
        outs, grads = _run(s, {"a": x}, out_grads=[g])
        np.testing.assert_allclose(outs[0], expected, rtol=1e-5)
        if keepdims or axes is None:
            gb = np.broadcast_to(g.reshape(
                [1] * ndim if axes is None and not keepdims
                else g.shape if keepdims
                else [1] * ndim), shape)
        else:
            expand = list(shape)
            for a in axes:
                expand[a] = 1
            gb = np.broadcast_to(g.reshape(expand), shape)
        np.testing.assert_allclose(grads["a"], gb, rtol=1e-5)


def test_broadcast_axis_sweep():
    rng = np.random.RandomState(17)
    for _ in range(10):
        ndim = rng.randint(1, 5)
        shape = list(rng.randint(2, 6, size=ndim))
        n_axes = rng.randint(1, ndim + 1)
        axes = tuple(sorted(rng.choice(ndim, n_axes, replace=False)))
        sizes = tuple(int(rng.randint(2, 5)) for _ in axes)
        src = list(shape)
        for a in axes:
            src[a] = 1
        s = sym.broadcast_axis(sym.Variable("a"), axis=axes, size=sizes)
        x = rng.rand(*src).astype(np.float32)
        dst = list(src)
        for a, n in zip(axes, sizes):
            dst[a] = n
        expected = np.broadcast_to(x, dst)
        g = rng.rand(*dst).astype(np.float32)
        outs, grads = _run(s, {"a": x}, out_grads=[g])
        np.testing.assert_allclose(outs[0], expected, rtol=1e-6)
        np.testing.assert_allclose(
            grads["a"], g.sum(axis=axes, keepdims=True), rtol=1e-5)


def test_crop_begin_end():
    """matrix crop with begin/end over 1-4D (reference test_crop)."""
    rng = np.random.RandomState(18)
    for ndim in range(1, 5):
        dims, begin, end, idx = [], [], [], []
        for _ in range(ndim):
            d = rng.randint(2, 8)
            b = rng.randint(0, d - 1)
            e = rng.randint(b + 1, d + 1)
            dims.append(d); begin.append(b); end.append(e)
            idx.append(slice(b, e))
        x = rng.randn(*dims).astype(np.float32)
        y = mx.nd.crop(mx.nd.array(x), begin=tuple(begin), end=tuple(end))
        np.testing.assert_allclose(y.asnumpy(), x[tuple(idx)], rtol=1e-6)


def test_slice_axis():
    rng = np.random.RandomState(19)
    for ndim in range(1, 5):
        shape = tuple(rng.randint(2, 8, size=ndim))
        for t in range(ndim):
            d = shape[t]
            b = rng.randint(0, d - 1)
            e = rng.randint(b + 1, d + 1)
            s = sym.slice_axis(sym.Variable("X"), axis=t, begin=b, end=e)
            x = rng.randn(*shape).astype(np.float32)
            idx = [slice(None)] * ndim
            idx[t] = slice(b, e)
            expected = x[tuple(idx)]
            outs, grads = _run(s, {"X": x}, out_grads=[expected])
            np.testing.assert_allclose(outs[0], expected, rtol=1e-6)
            scattered = np.zeros_like(x)
            scattered[tuple(idx)] = expected
            np.testing.assert_allclose(grads["X"], scattered, rtol=1e-6)


def test_flip():
    rng = np.random.RandomState(20)
    for ndim in range(1, 5):
        dims = tuple(rng.randint(2, 8, size=ndim))
        axis = rng.randint(0, ndim)
        x = rng.randn(*dims).astype(np.float32)
        y = mx.nd.flip(mx.nd.array(x), axis=int(axis))
        idx = tuple(slice(None, None, -1) if i == axis else slice(None)
                    for i in range(ndim))
        np.testing.assert_allclose(y.asnumpy(), x[idx], rtol=1e-6)


def test_dot():
    rng = np.random.RandomState(21)
    for m, k, n in [(1, 1, 1), (2, 3, 4), (4, 2, 3), (3, 4, 2)]:
        a = rng.randn(m, k).astype(np.float32)
        b = rng.randn(k, n).astype(np.float32)
        g = rng.randn(m, n).astype(np.float32)
        s = sym.dot(sym.Variable("a"), sym.Variable("b"))
        outs, grads = _run(s, {"a": a, "b": b}, out_grads=[g])
        assert reldiff(outs[0], a @ b) < 1e-4
        assert reldiff(grads["a"], g @ b.T) < 1e-4
        assert reldiff(grads["b"], a.T @ g) < 1e-4


def test_batch_dot():
    rng = np.random.RandomState(22)
    bs, m, k, n = 3, 2, 4, 3
    a = rng.randn(bs, m, k).astype(np.float32)
    b = rng.randn(bs, k, n).astype(np.float32)
    g = rng.randn(bs, m, n).astype(np.float32)
    s = sym.batch_dot(sym.Variable("a"), sym.Variable("b"))
    outs, grads = _run(s, {"a": a, "b": b}, out_grads=[g])
    assert reldiff(outs[0], np.einsum("bmk,bkn->bmn", a, b)) < 1e-4
    assert reldiff(grads["a"], np.einsum("bmn,bkn->bmk", g, b)) < 1e-4
    assert reldiff(grads["b"], np.einsum("bmk,bmn->bkn", a, g)) < 1e-4


def test_svm_l1():
    """L1 SVM: grad = -mask * 1[1 - mask*x > 0] (reference l1 svm test)."""
    rng = np.random.RandomState(23)
    shape = (8, 5)
    x = rng.rand(*shape).astype(np.float32)
    label = rng.randint(0, shape[1], shape[0]).astype(np.float32)
    s = sym.SVMOutput(data=sym.Variable("X"), label=sym.Variable("L"),
                      use_linear=True)
    outs, grads = _run(s, {"X": x, "L": label},
                       grad_req={"X": "write", "L": "null"},
                       out_grads=[np.ones(shape, np.float32)])
    np.testing.assert_allclose(outs[0], x, rtol=1e-6)
    mask = (label[:, None] == np.arange(shape[1])).astype(np.float32) * 2 - 1
    expected = -mask * (1 - mask * x > 0)
    np.testing.assert_allclose(grads["X"], expected, rtol=1e-5, atol=1e-6)


def test_svm_l2():
    """L2 SVM: grad = -2 * mask * max(1 - mask*x, 0)."""
    rng = np.random.RandomState(24)
    shape = (8, 5)
    x = rng.rand(*shape).astype(np.float32)
    label = rng.randint(0, shape[1], shape[0]).astype(np.float32)
    s = sym.SVMOutput(data=sym.Variable("X"), label=sym.Variable("L"))
    outs, grads = _run(s, {"X": x, "L": label},
                       grad_req={"X": "write", "L": "null"},
                       out_grads=[np.ones(shape, np.float32)])
    np.testing.assert_allclose(outs[0], x, rtol=1e-6)
    mask = (label[:, None] == np.arange(shape[1])).astype(np.float32) * 2 - 1
    expected = -2 * mask * np.maximum(1 - mask * x, 0)
    np.testing.assert_allclose(grads["X"], expected, rtol=1e-5, atol=1e-6)


def test_roipooling_forward_and_grad():
    rng = np.random.RandomState(25)
    x = rng.rand(2, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 1, 1, 6, 6], [1, 2, 2, 7, 7]], dtype=np.float32)
    s = sym.ROIPooling(data=sym.Variable("data"), rois=sym.Variable("rois"),
                       pooled_size=(3, 3), spatial_scale=1.0)
    outs, grads = _run(s, {"data": x, "rois": rois},
                       grad_req={"data": "write", "rois": "null"},
                       out_grads=[np.ones((2, 2, 3, 3), np.float32)])
    assert outs[0].shape == (2, 2, 3, 3)
    # every pooled cell is the max of its bin: value must exist in the roi
    for r in range(2):
        batch = int(rois[r, 0])
        roi = x[batch][:, int(rois[r, 2]):int(rois[r, 4]) + 1,
                       int(rois[r, 1]):int(rois[r, 3]) + 1]
        for c in range(2):
            for val in outs[0][r, c].ravel():
                assert np.isclose(roi[c], val, atol=1e-6).any()
    # gradient flows back only into argmax cells, total mass preserved
    assert abs(grads["data"].sum() - 2 * 2 * 3 * 3) < 1e-3


def test_stn_identity_transform():
    """Zero loc-net + identity-scaled bias crops the center at half
    resolution (reference test_stn, simplified loc net)."""
    rng = np.random.RandomState(26)
    n, c, h, w = 2, 2, 9, 9
    target = ((h + 1) // 2, (w + 1) // 2)
    data = sym.Variable("data")
    loc = sym.FullyConnected(data=sym.Flatten(data=data), num_hidden=6,
                             name="loc")
    stn = sym.SpatialTransformer(data=data, loc=loc, target_shape=target,
                                 transform_type="affine",
                                 sampler_type="bilinear")
    _, out_shapes, _ = stn.infer_shape(data=(n, c, h, w))
    assert out_shapes[0] == (n, c) + target
    x = rng.randn(n, c, h, w).astype(np.float32)
    args = {"data": x,
            "loc_weight": np.zeros((6, c * h * w), np.float32),
            "loc_bias": np.array([0.5, 0, 0, 0, 0.5, 0], np.float32)}
    outs, grads = _run(stn, args,
                       grad_req={"data": "write", "loc_weight": "null",
                                 "loc_bias": "null"},
                       out_grads=[np.ones((n, c) + target, np.float32)])
    # scale-0.5 affine == center crop at stride 2... sampling grid hits
    # exact input pixels for odd h,w: compare against strided center slice
    center = x[:, :, h // 4:h - h // 4, w // 4:w - w // 4]
    assert reldiff(outs[0], center[:, :, ::1, ::1][:, :, :target[0],
                                                   :target[1]]) < 0.35
    assert grads["data"].sum() > 0


def test_correlation_self_match():
    """Correlating an image with itself at zero displacement gives the
    (normalized) self-dot-product channel (reference test_correlation
    checks against a numpy forward; this is the analytic special case)."""
    rng = np.random.RandomState(27)
    x = rng.randn(1, 3, 6, 6).astype(np.float32)
    s = sym.Correlation(data1=sym.Variable("a"), data2=sym.Variable("b"),
                        kernel_size=1, max_displacement=0, stride1=1,
                        stride2=1, pad_size=0, is_multiply=True)
    outs, _ = _run(s, {"a": x, "b": x}, grad_req="null")
    out = outs[0]
    assert out.shape[:2] == (1, 1)
    expected = (x * x).sum(axis=1, keepdims=True) / x.shape[1]
    np.testing.assert_allclose(out, expected, rtol=1e-4)


def test_embedding_grad_accumulates():
    rng = np.random.RandomState(28)
    vocab, dim = 6, 4
    idx = np.array([1, 3, 1, 5], dtype=np.float32)
    w = rng.randn(vocab, dim).astype(np.float32)
    s = sym.Embedding(data=sym.Variable("data"), weight=sym.Variable("w"),
                      input_dim=vocab, output_dim=dim)
    g = rng.rand(4, dim).astype(np.float32)
    outs, grads = _run(s, {"data": idx, "w": w},
                       grad_req={"data": "null", "w": "write"},
                       out_grads=[g])
    np.testing.assert_allclose(outs[0], w[idx.astype(int)], rtol=1e-6)
    expected = np.zeros_like(w)
    for i, t in enumerate(idx.astype(int)):
        expected[t] += g[i]
    np.testing.assert_allclose(grads["w"], expected, rtol=1e-5)


def test_transpose_axes_sweep():
    rng = np.random.RandomState(29)
    for axes in [(1, 0), (2, 0, 1), (0, 2, 1, 3)]:
        shape = tuple(rng.randint(2, 5, size=len(axes)))
        x = rng.randn(*shape).astype(np.float32)
        s = sym.transpose(sym.Variable("a"), axes=axes)
        g = rng.rand(*np.transpose(x, axes).shape).astype(np.float32)
        outs, grads = _run(s, {"a": x}, out_grads=[g])
        np.testing.assert_allclose(outs[0], np.transpose(x, axes), rtol=1e-6)
        np.testing.assert_allclose(grads["a"],
                                   np.transpose(g, np.argsort(axes)),
                                   rtol=1e-6)


def test_duplicate_argument_name_rejected():
    """Two distinct Variables with one name must fail at bind, not
    silently drop gradients (reference 'Find duplicate argument name')."""
    x = np.ones((2, 2), np.float32)
    s = sym.maximum(sym.Variable("a"), sym.Variable("a"))
    with pytest.raises(mx.base.MXNetError, match="duplicate argument"):
        s.bind(mx.cpu(), {"a": mx.nd.array(x)})


def test_expand_dims():
    rng = np.random.RandomState(30)
    x = rng.randn(3, 4).astype(np.float32)
    for axis in (0, 1, 2):
        s = sym.expand_dims(sym.Variable("a"), axis=axis)
        outs, _ = _run(s, {"a": x}, grad_req="null")
        np.testing.assert_allclose(outs[0], np.expand_dims(x, axis),
                                   rtol=1e-6)


def test_clip_symbol():
    """reference SimpleOp clip as a symbol (round-2 registry gap)."""
    d = mx.sym.Variable("data")
    c = mx.sym.clip(d, a_min=-1.0, a_max=1.0)
    ex = c.simple_bind(mx.cpu(), data=(2, 3), grad_req="write")
    x = np.array([[-2, 0, 2], [0.5, -0.5, 3]], np.float32)
    ex.arg_dict["data"][:] = x
    np.testing.assert_allclose(ex.forward()[0].asnumpy(),
                               np.clip(x, -1, 1))
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               [[0, 1, 0], [1, 1, 0]])


def test_argmax_channel_symbol():
    d = mx.sym.Variable("data")
    a = mx.sym.argmax_channel(d)
    ex = a.simple_bind(mx.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = np.array([[1, 5, 2], [9, 0, 1]], np.float32)
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [1, 0])
    # spatial variant: argmax over channel axis keeps trailing dims
    s = mx.sym.argmax_channel(mx.sym.Variable("x"))
    ex2 = s.simple_bind(mx.cpu(), x=(2, 4, 3))
    v = np.random.RandomState(0).rand(2, 4, 3).astype(np.float32)
    ex2.arg_dict["x"][:] = v
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(),
                               v.argmax(axis=1))
