"""Registry-diff gate: every operator the reference registers resolves here.

The reference registers its operator surface through two macro families:
``MXNET_REGISTER_SIMPLE_OP`` (src/operator/*-inl.h, imperative+symbolic
SimpleOps) and ``MXNET_REGISTER_OP_PROPERTY`` (src/operator/*.cc, symbolic
layer ops). The name lists below are a snapshot of
``grep -rhoE 'MXNET_REGISTER_(SIMPLE_OP|OP_PROPERTY)\\(\\w+' src/operator/``
over the reference tree — asserting each name resolves in this framework's
symbolic registry or imperative NDArray function registry, so a silently
missing reference op fails CI (round-4 verdict: element_mask was the one
uncovered name).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import Registry
from mxnet_tpu.ops.registry import get_operator_class
from mxnet_tpu.test_utils import check_numeric_gradient

# reference src/operator/ MXNET_REGISTER_SIMPLE_OP registrations
REFERENCE_SIMPLE_OPS = [
    "_crop_assign", "_crop_assign_scalar", "_div", "_div_scalar",
    "_maximum", "_maximum_scalar", "_minimum", "_minimum_scalar",
    "_minus", "_minus_scalar", "_mul", "_mul_scalar", "_plus",
    "_plus_scalar", "_power", "_power_scalar", "_rdiv_scalar",
    "_rminus_scalar", "_rpower_scalar", "_sample_normal",
    "_sample_uniform", "abs", "argmax_channel", "batch_dot",
    "broadcast_axis", "broadcast_div", "broadcast_minus",
    "broadcast_mul", "broadcast_plus", "broadcast_power",
    "broadcast_to", "ceil", "cos", "crop", "dot", "element_mask",
    "exp", "expand_dims", "flip", "floor", "log", "max", "max_axis",
    "min", "min_axis", "norm", "round", "rsqrt", "sign", "sin",
    "slice_axis", "smooth_l1", "softmax_cross_entropy", "sqrt",
    "square", "sum", "sum_axis", "transpose",
]

# reference src/operator/ MXNET_REGISTER_OP_PROPERTY registrations.
# _NDArray / _Native are the legacy frontend-callback op properties
# (ndarray_op.cc / native_op.cc); their role — user ops written in the
# frontend, called back from the graph — is filled by the Custom
# machinery (operator.py NDArrayOp/NumpyOp/PythonOp over CustomOpProp),
# so they map to "Custom" rather than to same-named graph ops.
REFERENCE_OP_PROPERTIES = [
    "Activation", "BatchNorm", "BlockGrad", "Cast", "Concat",
    "Convolution", "Correlation", "Crop", "CuDNNBatchNorm", "Custom",
    "Deconvolution", "Dropout", "ElementWiseSum", "Embedding", "Flatten",
    "FullyConnected", "IdentityAttachKLSparseReg", "L2Normalization",
    "LRN", "LeakyReLU", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput", "MakeLoss",
    "Pooling", "RNN", "ROIPooling", "Reshape", "SVMOutput",
    "SequenceLast", "SequenceMask", "SequenceReverse", "SliceChannel",
    "Softmax", "SoftmaxActivation", "SoftmaxOutput",
    "SpatialTransformer", "SwapAxis", "UpSampling", "_CrossDeviceCopy",
]
FRONTEND_CALLBACK_PROPERTIES = {"_NDArray": "Custom", "_Native": "Custom"}


def _resolves(name: str) -> bool:
    if get_operator_class(name) is not None:
        return True
    reg = Registry.get_registry("ndarray_function")
    return reg.find(name) is not None


def test_reference_registry_complete():
    missing = [n for n in REFERENCE_SIMPLE_OPS + REFERENCE_OP_PROPERTIES
               if not _resolves(n)]
    assert not missing, "reference ops with no equivalent: %s" % missing
    for name, target in FRONTEND_CALLBACK_PROPERTIES.items():
        assert _resolves(target), \
            "%s maps to %s which is not registered" % (name, target)


def test_element_mask_forward_and_grad():
    """out[i,...] = lhs[i,...]*rhs[i]; grad flows to lhs only (reference
    broadcast_mask_op-inl.h backward assigns no rhs grad)."""
    rng = np.random.RandomState(0)
    lhs_np = rng.randn(4, 3, 2).astype(np.float32)
    mask_np = np.array([1, 0, 1, 0], dtype=np.float32)
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    out = sym.element_mask(lhs, rhs, name="em")

    args = {"lhs": mx.nd.array(lhs_np), "rhs": mx.nd.array(mask_np)}
    grads = {"lhs": mx.nd.zeros(lhs_np.shape), "rhs": mx.nd.zeros((4,))}
    ex = out.bind(mx.cpu(), args, args_grad=grads, grad_req="write")
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               lhs_np * mask_np[:, None, None], rtol=1e-6)
    np.testing.assert_allclose(ex.grad_dict["lhs"].asnumpy(),
                               np.broadcast_to(mask_np[:, None, None],
                                               lhs_np.shape))
    # mask is a constant for autodiff
    np.testing.assert_allclose(ex.grad_dict["rhs"].asnumpy(), np.zeros(4))


def test_element_mask_shape_checks():
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    out = sym.element_mask(lhs, rhs)
    with pytest.raises(mx.MXNetError):
        out.infer_shape(lhs=(4,), rhs=(4,))       # lhs must be >=2D
    with pytest.raises(mx.MXNetError):
        out.infer_shape(lhs=(4, 3), rhs=(3,))     # first dims must match
    _, outs, _ = out.infer_shape(lhs=(4, 3))      # rhs inferred as (4,)
    assert outs[0] == (4, 3)


def test_element_mask_imperative():
    a = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    m = mx.nd.array(np.array([0, 1, 0, 2], dtype=np.float32))
    out = mx.nd.element_mask(a, m)
    np.testing.assert_allclose(
        out.asnumpy(), a.asnumpy() * m.asnumpy()[:, None])


def test_crop_assign_symbolic():
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    out = sym._crop_assign(lhs, rhs, begin=(1, 0), end=(3, 2), name="ca")
    lhs_np = np.zeros((4, 3), dtype=np.float32)
    rhs_np = np.ones((2, 2), dtype=np.float32) * 7
    args = {"lhs": mx.nd.array(lhs_np), "rhs": mx.nd.array(rhs_np)}
    ex = out.bind(mx.cpu(), args)
    ex.forward()
    want = lhs_np.copy()
    want[1:3, 0:2] = 7
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), want)
    # region/shape validation
    with pytest.raises(mx.MXNetError):
        sym._crop_assign(lhs, rhs, begin=(1, 0), end=(5, 2)) \
            .infer_shape(lhs=(4, 3))
    with pytest.raises(mx.MXNetError):
        sym._crop_assign(lhs, rhs, begin=(1, 0), end=(3, 2)) \
            .infer_shape(lhs=(4, 3), rhs=(3, 3))


def test_crop_assign_scalar_symbolic_and_imperative():
    data = sym.Variable("data")
    out = sym._crop_assign_scalar(data, scalar=5.0, begin=(0, 1),
                                  end=(2, 3), name="cas")
    x = np.zeros((3, 4), dtype=np.float32)
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(x)})
    ex.forward()
    want = x.copy()
    want[0:2, 1:3] = 5.0
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), want)

    nd_out = mx.nd.crop_assign_scalar(mx.nd.array(x), 5.0, (0, 1), (2, 3))
    np.testing.assert_allclose(nd_out.asnumpy(), want)
    nd_out2 = mx.nd.crop_assign(mx.nd.array(x),
                                mx.nd.ones((2, 2)) * 5.0, (0, 1), (2, 3))
    np.testing.assert_allclose(nd_out2.asnumpy(), want)


def test_crop_assign_gradients():
    """Autodiff through the functional crop-assign: lhs grad is zeroed in
    the written region, rhs grad gathers from it."""
    lhs = sym.Variable("lhs")
    rhs = sym.Variable("rhs")
    out = sym._crop_assign(lhs, rhs, begin=(1,), end=(3,))
    check_numeric_gradient(out, {"lhs": np.random.rand(4).astype(np.float32),
                                 "rhs": np.random.rand(2).astype(np.float32)})


def test_scalar_op_snake_case_aliases():
    """The reference registers its scalar SimpleOps under snake_case
    (_plus_scalar et al.); both spellings must resolve to the same class."""
    for snake, camel in [("_plus_scalar", "_PlusScalar"),
                         ("_rdiv_scalar", "_RDivScalar"),
                         ("_rpower_scalar", "_RPowerScalar")]:
        assert get_operator_class(snake) is get_operator_class(camel)


def test_cudnn_batchnorm_alias():
    assert get_operator_class("CuDNNBatchNorm") \
        is get_operator_class("BatchNorm")


def test_cross_device_copy_identity():
    data = sym.Variable("data")
    out = sym._CrossDeviceCopy(data)
    x = np.random.rand(2, 3).astype(np.float32)
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(x)})
    ex.forward()
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x)


def test_imperative_crop_assign_validation():
    """The imperative twins enforce the same region/shape checks as the
    symbolic ops (review finding: jax slice-clamping would otherwise
    silently fill the whole array)."""
    a = mx.nd.zeros((3, 4))
    with pytest.raises(mx.MXNetError):
        mx.nd.crop_assign_scalar(a, 9.0, (0, 0), (5, 9))  # out of range
    with pytest.raises(mx.MXNetError):
        mx.nd.crop_assign(a, mx.nd.ones((1, 1)), (0, 0), (2, 2))  # shape
    with pytest.raises(mx.MXNetError):
        mx.nd.element_mask(mx.nd.ones((3,)), mx.nd.ones((3,)))  # 1-D lhs


def test_zeros_dtype_none_defaults_to_float32():
    assert mx.nd.zeros((2,), dtype=None).dtype == np.float32
