"""Step-trace flight recorder, anomaly detection, and live metrics
exposition (mxnet_tpu.tracing) plus its satellite fixes (Speedometer
tail/zero-elapsed, StepTimer percentiles, crash-safe dump_jsonl)."""
import json
import logging
import os
import signal
import sys
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry, tracing

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import trace_report  # noqa: E402


@pytest.fixture(autouse=True)
def _isolated_tracing():
    """Clean registry + tracing globals per test; leave the process the
    way the rest of the suite expects (telemetry disabled, no server)."""
    tracing.shutdown()
    telemetry.reset()
    telemetry.enable()
    tracing.set_worker_rank(0)
    yield
    tracing.shutdown()
    telemetry.reset()
    telemetry.disable()
    tracing.set_worker_rank(0)


# -- step deltas ---------------------------------------------------------

def test_step_deltas_against_hand_advanced_counters():
    st = tracing.StepTrace(capacity=8, detectors=[])
    telemetry.inc("ndarray.h2d_bytes", 4096)
    telemetry.inc("kvstore.push_bytes", 100)
    rec1 = st.record(5.0)
    assert rec1["step"] == 1
    assert rec1["deltas"]["h2d_bytes"] == 4096
    assert rec1["deltas"]["kv_push_bytes"] == 100
    assert rec1["deltas"]["recompiles"] == 0

    telemetry.inc("ndarray.h2d_bytes", 1024)
    telemetry.inc("executor.jit_build")
    telemetry.observe("io.pipeline.stall_ms", 7.5)
    rec2 = st.record(6.0)
    # deltas are per-step, not cumulative
    assert rec2["deltas"]["h2d_bytes"] == 1024
    assert rec2["deltas"]["kv_push_bytes"] == 0
    assert rec2["deltas"]["recompiles"] == 1
    assert rec2["deltas"]["io_stall_ms"] == pytest.approx(7.5)

    rec3 = st.record(4.0)
    assert all(v == 0 for v in rec3["deltas"].values())
    assert [r["step"] for r in st.records()] == [1, 2, 3]


def test_ring_is_bounded():
    st = tracing.StepTrace(capacity=4, detectors=[])
    for _ in range(10):
        st.record(1.0)
    recs = st.records()
    assert len(recs) == 4
    assert [r["step"] for r in recs] == [7, 8, 9, 10]
    assert st.step == 10


def test_dominant_delta_classification():
    st = tracing.StepTrace(capacity=8, detectors=[])
    assert st.record(10.0)["dominant"] == "compute"
    # stall claiming >25% of the step wall time wins
    telemetry.observe("io.pipeline.stall_ms", 80.0)
    assert st.record(100.0)["dominant"] == "io_stall_ms"
    # a recompile trumps everything
    telemetry.observe("io.pipeline.stall_ms", 80.0)
    telemetry.inc("executor.jit_build")
    assert st.record(100.0)["dominant"] == "recompile"
    telemetry.observe("io.prefetch_stall_ms", 50.0)
    assert st.record(100.0)["dominant"] == "prefetch_stall_ms"


# -- anomaly detectors ---------------------------------------------------

def test_slow_step_detector_triggers_with_correct_record():
    st = tracing.StepTrace(
        capacity=64, event_cooldown=1,
        detectors=[tracing.SlowStepDetector(k=2.0, warmup=4)])
    for _ in range(8):
        st.record(10.0)
    assert not st.events
    telemetry.observe("io.pipeline.stall_ms", 90.0)  # the evidence
    st.record(100.0)
    assert len(st.events) == 1
    ev = st.events[0]
    assert ev["type"] == "slow_step"
    assert ev["step"] == 9
    assert ev["latency_ms"] == pytest.approx(100.0)
    assert ev["median_ms"] == pytest.approx(10.0)
    # the event carries the step's dominant delta: it was input-stalled
    assert ev["dominant"] == "io_stall_ms"
    assert telemetry.counter("tracing.anomalies").value == 1


def test_slow_step_warmup_suppresses_compile_steps():
    st = tracing.StepTrace(
        capacity=64, event_cooldown=1,
        detectors=[tracing.SlowStepDetector(k=2.0, warmup=4)])
    st.record(1.0)
    st.record(500.0)  # step 2 <= warmup: the compile step, not an anomaly
    assert not st.events


def test_event_cooldown_rate_limits_repeats():
    st = tracing.StepTrace(
        capacity=64, event_cooldown=10,
        detectors=[tracing.SlowStepDetector(k=2.0, warmup=2)])
    for _ in range(4):
        st.record(10.0)
    st.record(100.0)
    st.record(100.0)  # within cooldown: counted into the ring, no event
    assert len(st.events) == 1


def test_recompile_detector():
    st = tracing.StepTrace(
        capacity=64, event_cooldown=1,
        detectors=[tracing.RecompileDetector(warmup=2)])
    telemetry.inc("executor.jit_build")  # warmup compile: expected
    st.record(50.0)
    st.record(5.0)
    assert not st.events
    telemetry.inc("executor.jit_build")  # steady state: anomaly
    st.record(60.0)
    assert [e["type"] for e in st.events] == ["recompile"]
    assert st.events[0]["recompiles"] == 1


def test_input_stall_detector():
    st = tracing.StepTrace(
        capacity=64, event_cooldown=1,
        detectors=[tracing.InputStallDetector(frac=0.5)])
    telemetry.observe("io.pipeline.stall_ms", 2.0)
    st.record(10.0)  # 20% stalled: fine
    assert not st.events
    telemetry.observe("io.pipeline.stall_ms", 8.0)
    telemetry.observe("io.prefetch_stall_ms", 1.0)
    st.record(10.0)  # 90% stalled
    assert [e["type"] for e in st.events] == ["input_stall"]
    assert st.events[0]["stall_frac"] == pytest.approx(0.9)


def test_anomaly_profiler_window_and_rate_limit(tmp_path):
    starts, stops = [], []
    prof = tracing.AnomalyProfiler(
        trace_dir=str(tmp_path), window_steps=2, cooldown_s=3600.0,
        start_fn=starts.append, stop_fn=lambda: stops.append(True))
    st = tracing.StepTrace(
        capacity=64, event_cooldown=1, profiler=prof,
        detectors=[tracing.SlowStepDetector(k=2.0, warmup=2)])
    for _ in range(4):
        st.record(10.0)
    st.record(100.0)                     # step 5: trigger -> trace starts
    assert len(starts) == 1
    assert "step5_slow_step" in starts[0]
    assert st.events[0]["trace_started"] is True
    assert not stops
    st.record(10.0)
    st.record(10.0)                      # step 7 = 5+window: trace stops
    assert stops == [True]
    st.record(100.0)                     # within cooldown: suppressed
    assert len(starts) == 1
    assert prof.suppressed == 1
    assert telemetry.counter("tracing.auto_traces").value == 1
    assert telemetry.counter("tracing.auto_trace_suppressed").value == 1


# -- flight recorder -----------------------------------------------------

def _read_dump(dump_dir):
    with open(os.path.join(dump_dir, "meta.json")) as f:
        meta = json.load(f)
    with open(os.path.join(dump_dir, "telemetry.json")) as f:
        snap = json.load(f)
    with open(os.path.join(dump_dir, "stacks.txt")) as f:
        stacks = f.read()
    steps = []
    with open(os.path.join(dump_dir, "steps.jsonl")) as f:
        for line in f:
            steps.append(json.loads(line))
    return meta, snap, stacks, steps


def test_flight_recorder_dump_contents(tmp_path):
    st = tracing.StepTrace(capacity=8, detectors=[])
    telemetry.inc("engine.push", 3)
    st.record(5.0)
    st.record(7.0)
    fr = tracing.FlightRecorder(str(tmp_path), trace=st)
    d = fr.dump("unit-test")
    assert d is not None and os.path.isdir(d)
    meta, snap, stacks, steps = _read_dump(d)
    assert meta["reason"] == "unit-test"
    assert meta["pid"] == os.getpid()
    assert meta["steps_recorded"] == 2
    assert snap["engine"]["push"] == 3
    assert "test_flight_recorder_dump_contents" in stacks  # our own frame
    assert [r["step"] for r in steps] == [1, 2]
    assert steps[1]["latency_ms"] == pytest.approx(7.0)


def test_flight_recorder_excepthook_chains_and_dumps(tmp_path):
    st = tracing.StepTrace(capacity=8, detectors=[])
    st.record(1.0)
    seen = []
    prev_hook = sys.excepthook
    sys.excepthook = lambda *a: seen.append(a)
    fr = tracing.FlightRecorder(str(tmp_path), trace=st).install()
    try:
        try:
            raise ValueError("simulated training crash")
        except ValueError:
            sys.excepthook(*sys.exc_info())
    finally:
        fr.uninstall()
        sys.excepthook = prev_hook
    # the prior hook still ran (chained), and one dump was written
    assert len(seen) == 1 and seen[0][0] is ValueError
    dumps = [p for p in os.listdir(str(tmp_path)) if p.startswith("flight-")]
    assert len(dumps) == 1
    meta, _, _, _ = _read_dump(os.path.join(str(tmp_path), dumps[0]))
    assert meta["reason"] == "exception:ValueError"
    assert "simulated training crash" in meta["exception"]


def test_flight_recorder_sigusr1_mid_run(tmp_path):
    """SIGUSR1 writes a complete dump and the process keeps running."""
    st = tracing.StepTrace(capacity=8, detectors=[])
    st.record(3.0)
    fr = tracing.FlightRecorder(str(tmp_path), trace=st).install()
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.time() + 5.0
        dumps = []
        while not dumps and time.time() < deadline:
            dumps = [p for p in os.listdir(str(tmp_path))
                     if p.startswith("flight-")]
            time.sleep(0.01)
    finally:
        fr.uninstall()
    assert len(dumps) == 1
    meta, snap, stacks, steps = _read_dump(
        os.path.join(str(tmp_path), dumps[0]))
    assert meta["reason"] == "signal:SIGUSR1"
    assert len(steps) == 1 and "Thread" in stacks
    # uninstall restored the previous disposition
    assert signal.getsignal(signal.SIGUSR1) != fr._on_signal


# -- live metrics exposition ---------------------------------------------

def _scrape(port, path="/metrics"):
    with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


def _parse_prom(text):
    """Exposition-format round-trip: {name: {labels: value}} + types."""
    samples, types = {}, {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line.startswith("# TYPE"):
                _, _, name, mtype = line.split()
                types[name] = mtype
            continue
        name_labels, value = line.rsplit(" ", 1)
        if "{" in name_labels:
            name, labels = name_labels.split("{", 1)
            labels = "{" + labels
        else:
            name, labels = name_labels, ""
        samples.setdefault(name, {})[labels] = float(value)
    return samples, types


def test_metrics_exposition_round_trip():
    telemetry.inc("engine.push", 7)
    telemetry.set_gauge("io.pipeline.ring_occupancy", 3.0)
    for v in (1.0, 2.0, 3.0, 4.0):
        telemetry.observe("profiler.step_ms", v)
    server = tracing.MetricsServer(0)
    try:
        status, ctype, text = _scrape(server.port)
    finally:
        server.close()
    assert status == 200 and ctype.startswith("text/plain")
    samples, types = _parse_prom(text)
    assert types["mxnet_tpu_engine_push"] == "counter"
    assert samples["mxnet_tpu_engine_push"]['{rank="0"}'] == 7
    assert types["mxnet_tpu_io_pipeline_ring_occupancy"] == "gauge"
    assert samples["mxnet_tpu_io_pipeline_ring_occupancy"]['{rank="0"}'] == 3.0
    assert types["mxnet_tpu_profiler_step_ms"] == "histogram"
    assert samples["mxnet_tpu_profiler_step_ms_count"]['{rank="0"}'] == 4
    assert samples["mxnet_tpu_profiler_step_ms_sum"]['{rank="0"}'] == 10.0
    # real histogram series: cumulative le buckets closing with +Inf.
    # samples 1,2,3,4 against the default ladder: le="1" holds 1,
    # le="2.5" holds 2, le="5" holds all 4
    b = samples["mxnet_tpu_profiler_step_ms_bucket"]
    assert b['{rank="0",le="1"}'] == 1
    assert b['{rank="0",le="2.5"}'] == 2
    assert b['{rank="0",le="5"}'] == 4
    assert b['{rank="0",le="+Inf"}'] == 4
    # cumulative counts are monotone in ladder order
    ladder = [v for k, v in sorted(
        b.items(), key=lambda kv: float("inf") if "+Inf" in kv[0]
        else float(kv[0].split('le="')[1].rstrip('"}')))]
    assert ladder == sorted(ladder)


def test_metrics_rank_label_tags_dist_workers():
    telemetry.inc("kvstore.push", 2)
    tracing.set_worker_rank(3)
    server = tracing.MetricsServer(0)
    try:
        _, _, text = _scrape(server.port)
    finally:
        server.close()
    samples, _ = _parse_prom(text)
    assert samples["mxnet_tpu_kvstore_push"]['{rank="3"}'] == 2


def test_healthz_and_maybe_init_env(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_METRICS_PORT", "0")
    server = tracing.maybe_init()
    assert server is not None
    assert tracing.maybe_init() is server  # idempotent
    tracing.record_step(5.0)
    status, ctype, body = _scrape(server.port, "/healthz")
    assert status == 200 and ctype == "application/json"
    health = json.loads(body)
    assert health["status"] == "ok"
    assert health["pid"] == os.getpid()
    assert health["steps"] == 1
    status, _, _ = _scrape(server.port, "/metrics")
    assert status == 200


# -- disabled-path contract ----------------------------------------------

def test_disabled_hooks_are_noops():
    telemetry.disable()
    assert tracing.record_step(5.0) is None
    assert tracing.maybe_init() is None
    # nothing was created: no recorder, no server, no flight recorder
    assert tracing._recorder is None
    assert tracing.metrics_server() is None
    assert tracing.flight_recorder() is None


def test_disabled_record_step_under_a_microsecond():
    """The overhead contract, enforced: the disabled path (one flag
    check, immediate return) must stay ~1 us/call. Best-of-5 timing
    rides out CI noise; the 2 us bar is 10-20x the expected cost."""
    telemetry.disable()
    n = 100_000
    best = float("inf")
    rs = tracing.record_step
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            rs(1.0)
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 2e-6, "disabled record_step took %.0f ns/call" % (best * 1e9)


# -- fit-loop integration ------------------------------------------------

def test_fit_populates_step_trace_ring():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    x = np.random.RandomState(0).rand(20, 4).astype(np.float32)
    y = (np.arange(20) % 8).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    recs = tracing.step_trace().records()
    assert len(recs) == 5  # 20 samples / batch 4
    assert [r["nbatch"] for r in recs] == list(range(5))
    assert all(r["epoch"] == 0 for r in recs)
    assert all(r["latency_ms"] > 0 for r in recs)
    # the compile lands in step 1's window: it must dominate
    assert recs[0]["latency_ms"] == max(r["latency_ms"] for r in recs)
    # every step carries the delta fields
    for field, _m, _k in tracing.DELTA_SOURCES:
        assert field in recs[0]["deltas"]


# -- trace_report CLI ----------------------------------------------------

def test_trace_report_renders_top_slowest(tmp_path):
    st = tracing.StepTrace(capacity=16, detectors=[])
    st.record(5.0)
    telemetry.observe("io.pipeline.stall_ms", 90.0)
    st.record(120.0)
    st.record(6.0)
    path = str(tmp_path / "steps.jsonl")
    assert st.dump_jsonl(path) == 3
    recs = trace_report.load_records(path)
    assert len(recs) == 3
    out = trace_report.render(recs, top=2)
    lines = out.splitlines()
    assert "3 steps" in lines[0]
    # table body: header, dashes, then the top-2 slowest, slowest first
    body = lines[-2:]
    assert "120.00" in body[0] and "io_stall_ms" in body[0]  # step 2
    assert body[1].lstrip().startswith("3")                  # step 3, 6ms


def test_trace_report_reads_crash_dump(tmp_path):
    st = tracing.StepTrace(capacity=8, detectors=[])
    st.record(2.0)
    d = tracing.FlightRecorder(str(tmp_path), trace=st).dump("report-test")
    out = trace_report.report_crash_dump(d)
    assert "report-test" in out
    assert "1 steps" in out


def test_trace_report_accepts_telemetry_jsonl(tmp_path):
    path = str(tmp_path / "run.jsonl")
    telemetry.dump_jsonl(path, extra={"step_ms": 12.5})
    recs = trace_report.load_records(path)
    assert len(recs) == 1 and recs[0]["latency_ms"] == 12.5


# -- satellites ----------------------------------------------------------

def test_speedometer_zero_elapsed_no_crash(monkeypatch):
    monkeypatch.setattr(time, "time", lambda: 100.0)  # frozen clock

    class _Param:
        epoch, nbatch, eval_metric = 0, 0, None

    sp = mx.callback.Speedometer(batch_size=4, frequent=2)
    p = _Param()
    sp(p)
    p.nbatch = 2
    sp(p)  # elapsed is exactly 0.0: must not ZeroDivisionError
    assert telemetry.gauge("train.samples_per_sec").value > 0
    assert telemetry.counter("train.batches").value == 2


def test_speedometer_epoch_end_reports_tail(caplog):
    class _Param:
        epoch, nbatch, eval_metric = 0, 0, None

    sp = mx.callback.Speedometer(batch_size=4, frequent=10)
    p = _Param()
    for n in range(4):          # epoch ends at nbatch 3, boundary never hit
        p.nbatch = n
        sp(p)
    with caplog.at_level(logging.INFO):
        sp.epoch_end(p)
    assert telemetry.counter("train.batches").value == 3  # batches 1..3
    assert any("tail(3)" in r.getMessage() for r in caplog.records)
    # idempotent: a second call has nothing left to report
    caplog.clear()
    with caplog.at_level(logging.INFO):
        sp.epoch_end(p)
    assert not caplog.records


def test_step_timer_summary_nearest_rank_and_p99():
    timer = mx.profiler.StepTimer()
    timer._times = [i / 1000.0 for i in range(1, 11)]  # 1..10 ms
    s = timer.summary(skip_first=0)
    assert s["steps"] == 10
    # nearest-rank: p50 = 5th smallest, p90 = 9th, p99 = 10th
    assert s["p50_ms"] == pytest.approx(5.0)
    assert s["p90_ms"] == pytest.approx(9.0)
    assert s["p99_ms"] == pytest.approx(10.0)
    assert s["max_ms"] == pytest.approx(10.0)
    # single sample: every percentile is that sample, no index error
    timer._times = [0.002]
    s1 = timer.summary(skip_first=0)
    assert s1["p50_ms"] == s1["p99_ms"] == pytest.approx(2.0)


def test_step_timer_summary_safe_when_skip_exceeds_len():
    timer = mx.profiler.StepTimer()
    timer._times = [0.001, 0.002]
    assert timer.summary(skip_first=2) == {"steps": 0}
    assert timer.summary(skip_first=99) == {"steps": 0}
    assert timer.summary(skip_first=-3)["steps"] == 2  # clamped, not wrapped


def test_dump_jsonl_append_only_and_fsync_opt_in(tmp_path, monkeypatch):
    path = str(tmp_path / "run.jsonl")
    telemetry.inc("a.c", 1)
    telemetry.dump_jsonl(path)
    monkeypatch.setenv("MXNET_TPU_TELEMETRY_FSYNC", "1")
    telemetry.dump_jsonl(path, extra={"note": "fsynced"})
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[1]["note"] == "fsynced"


def test_feed_stall_is_a_tracked_stall_field():
    """io.feed_stall_ms (FeedScheduler queue waits) must flow into step
    deltas, dominant-cause labeling, and the input-stall detector."""
    st = tracing.StepTrace(capacity=8, detectors=[])
    telemetry.observe("io.feed_stall_ms", 60.0)
    rec = st.record(100.0)
    assert rec["deltas"]["feed_stall_ms"] == pytest.approx(60.0)
    assert rec["dominant"] == "feed_stall_ms"

    st2 = tracing.StepTrace(
        capacity=8, event_cooldown=1,
        detectors=[tracing.InputStallDetector(frac=0.5)])
    telemetry.observe("io.feed_stall_ms", 9.0)
    st2.record(10.0)
    assert [e["type"] for e in st2.events] == ["input_stall"]
    assert st2.events[0]["stall_frac"] == pytest.approx(0.9)
