"""Executor tests (reference tests/python/unittest/test_executor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _net():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=3, name="fc")
    return sym.SoftmaxOutput(data=fc, name="sm")


def test_simple_bind_and_forward():
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
    assert set(ex.arg_dict) == {"data", "fc_weight", "fc_bias", "sm_label"}
    ex.arg_dict["data"][:] = np.random.randn(4, 6)
    ex.arg_dict["fc_weight"][:] = np.random.randn(3, 6)
    outs = ex.forward(is_train=False)
    assert outs[0].shape == (4, 3)
    np.testing.assert_allclose(outs[0].asnumpy().sum(axis=1), np.ones(4),
                               rtol=1e-5)


def test_grad_req_add():
    a = sym.Variable("a")
    out = a * 3.0
    arr = mx.nd.array(np.ones((2, 2), dtype=np.float32))
    grad = mx.nd.zeros((2, 2))
    ex = out.bind(mx.cpu(), {"a": arr}, args_grad={"a": grad}, grad_req="add")
    for i in range(3):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(grad.asnumpy(), np.full((2, 2), 9.0))


def test_grad_req_null():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = a * b
    ones = np.ones((2, 2), dtype=np.float32)
    ga = mx.nd.zeros((2, 2))
    ex = out.bind(mx.cpu(), {"a": mx.nd.array(ones), "b": mx.nd.array(2 * ones)},
                  args_grad={"a": ga},
                  grad_req={"a": "write", "b": "null"})
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ga.asnumpy(), 2 * ones)
    assert ex.grad_dict.get("b") is None


def test_backward_head_grads():
    a = sym.Variable("a")
    out = a * a
    x = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    ga = mx.nd.zeros((3,))
    ex = out.bind(mx.cpu(), {"a": mx.nd.array(x)}, args_grad={"a": ga})
    ex.forward(is_train=True)
    head = mx.nd.array(np.array([1.0, 0.5, 2.0], dtype=np.float32))
    ex.backward([head])
    np.testing.assert_allclose(ga.asnumpy(), 2 * x * head.asnumpy(),
                               rtol=1e-6)


def test_executor_reshape():
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
    w = np.random.randn(3, 6).astype(np.float32)
    ex.arg_dict["fc_weight"][:] = w
    ex2 = ex.reshape(data=(8, 6))
    assert ex2.arg_dict["data"].shape == (8, 6)
    # params shared
    np.testing.assert_allclose(ex2.arg_dict["fc_weight"].asnumpy(), w)
    assert ex2.arg_dict["fc_weight"] is ex.arg_dict["fc_weight"]
    ex2.arg_dict["data"][:] = np.random.randn(8, 6)
    outs = ex2.forward(is_train=False)
    assert outs[0].shape == (8, 3)


def test_monitor_callback():
    """Monitor emission happens when the computation actually runs: the
    train forward is lazy, so internals arrive with backward() (fused —
    one forward per monitored batch) or with the lazy .outputs fetch."""
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.arg_dict["data"][:] = np.random.randn(2, 4)
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward(is_train=True)
    ex.backward()
    assert any("fc_output" in n for n in seen)
    assert any("sm_output" in n for n in seen)
    # gradients still computed alongside the monitored internals
    assert ex.grad_dict["fc_weight"].asnumpy().shape == (3, 4)

    # forward-only train step: internals arrive with the outputs fetch
    seen.clear()
    ex.forward(is_train=True)
    assert not seen
    _ = ex.outputs
    assert any("fc_output" in n for n in seen)


def test_monitor_with_integer_internals():
    """Integer-dtype internals (Cast) need float0 cotangents in the
    monitored fused fwd+bwd — a plain zeros_like would make jax.vjp
    reject the graph."""
    data = mx.sym.Variable("data")
    casted = mx.sym.Cast(data, dtype="int32", name="c")
    back = mx.sym.Cast(casted, dtype="float32", name="b")
    fc = mx.sym.FullyConnected(back, num_hidden=2, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    ex = out.simple_bind(mx.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = np.random.rand(2, 3) * 5
    seen = []
    ex.set_monitor_callback(lambda n, a: seen.append(n))
    ex.forward(is_train=True)
    ex.backward()
    assert any("c_output" in n for n in seen)


def test_copy_params_from():
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    w = np.random.randn(3, 4).astype(np.float32)
    ex.copy_params_from({"fc_weight": mx.nd.array(w)},
                        allow_extra_params=True)
    np.testing.assert_allclose(ex.arg_dict["fc_weight"].asnumpy(), w)


def test_outputs_lazy_train():
    """Train-mode forward defers compute to backward (one fused XLA call)."""
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.arg_dict["data"][:] = np.random.randn(2, 4)
    ex.forward(is_train=True)
    ex.backward()
    out = ex.outputs[0].asnumpy()
    assert out.shape == (2, 3)


def test_segmented_remat_matches_plain():
    """MXNET_BACKWARD_DO_MIRROR routes through segmented remat
    (make_graph_eval(remat=True)): outputs, aux updates and gradients
    must match the plain path exactly; the emitted backward must carry
    optimization barriers and recompute (more matmuls)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.executor import make_graph_eval

    net = mx.sym.Variable("data")
    for i in range(9):
        net = mx.sym.FullyConnected(net, num_hidden=16, name="rfc%d" % i)
        net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.BatchNorm(net, name="rbn")   # aux crosses segments
    net = mx.sym.FullyConnected(net, num_hidden=2, name="rcls")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    plain, n_aux = make_graph_eval(net)
    remat, n_aux2 = make_graph_eval(net, remat=True)
    assert n_aux == n_aux2

    arg_shapes, _, aux_shapes = net.infer_shape(data=(4, 16))
    rng = np.random.RandomState(0)
    args = [rng.randn(*s).astype(np.float32) * 0.3 for s in arg_shapes]
    lbl = net.list_arguments().index("softmax_label")
    args[lbl] = rng.randint(0, 2, (4,)).astype(np.float32)
    aux = [np.ones(s, np.float32) if "var" in n else np.zeros(s, np.float32)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]
    key = jax.random.PRNGKey(0)

    o1, a1 = plain(args, aux, key, True)
    o2, a2 = remat(args, aux, key, True)
    for x, y in zip(o1 + a1, o2 + a2):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)

    def loss(fn):
        def f(a):
            outs, aux_o = fn(a, aux, key, True)
            return (sum(jnp.sum(o) for o in outs)
                    + sum(jnp.sum(x) for x in aux_o))
        return f

    g1 = jax.grad(loss(plain))(args)
    g2 = jax.grad(loss(remat))(args)
    for x, y in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-6)

    txt = jax.jit(jax.grad(loss(remat))).lower(args).as_text()
    assert txt.count("optimization_barrier") > 0
    plain_txt = jax.jit(jax.grad(loss(plain))).lower(args).as_text()
    assert txt.count("stablehlo.dot") > plain_txt.count("stablehlo.dot")


def test_monitor_installed_between_forward_and_backward():
    """Per-batch monitor semantics: whether to monitor is decided at
    emission time (backward / lazy outputs), so a callback installed
    after forward(is_train=True) still observes that batch."""
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.arg_dict["data"][:] = np.random.randn(2, 4)
    ex.forward(is_train=True)
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.backward()
    assert any("fc_output" in n for n in seen)


def test_symbol_grad_with_integer_head():
    """Symbol.grad over a base symbol whose outputs include a
    non-differentiable (integer) head: float0 cotangents keep jax.vjp
    happy (ADVICE r2); the float head still produces real gradients."""
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    fc = mx.sym.FullyConnected(data=data, weight=w, no_bias=True,
                               num_hidden=3, name="fc")
    ints = mx.sym.Cast(fc, dtype="int32", name="ci")
    grp = mx.sym.Group([fc, ints])
    gsym = grp.grad(["w"])
    ex = gsym.simple_bind(mx.cpu(), data=(2, 4), w=(3, 4),
                          grad_req="null")
    x = np.random.rand(2, 4).astype(np.float32)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["w"][:] = np.random.rand(3, 4).astype(np.float32)
    out = ex.forward()[0].asnumpy()
    # d(sum(fc))/dw = column sums of x broadcast over hidden rows;
    # the integer head contributes nothing
    expect = np.tile(x.sum(axis=0), (3, 1))
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_monitor_fires_once_when_outputs_read_before_backward():
    """Reading .outputs between forward(is_train=True) and backward()
    must not double-emit the batch's monitor callbacks (once-per-batch
    contract of set_monitor_callback)."""
    net = _net()
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.arg_dict["data"][:] = np.random.randn(2, 4)
    seen = []
    ex.set_monitor_callback(lambda name, arr: seen.append(name))
    ex.forward(is_train=True)
    _ = ex.outputs            # lazy fetch emits this batch's internals
    n_after_outputs = len(seen)
    assert n_after_outputs > 0
    ex.backward()
    assert len(seen) == n_after_outputs, "backward re-emitted the batch"
