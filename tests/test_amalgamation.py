"""Amalgamation (reference amalgamation/mxnet_predict0.cc): the
generated single-file loader runs an exported bundle in a process with
NO mxnet_tpu on the path — only jax + numpy — and matches the in-
framework predictor's output."""
import os
import subprocess
import sys
import textwrap

import numpy as np

import mxnet_tpu as mx
from tools.amalgamation import amalgamate

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_amalgamated_loader_standalone(tmp_path):
    # build + export a small model
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    args = {"fc_weight": mx.nd.array(rng.randn(4, 6).astype(np.float32)),
            "fc_bias": mx.nd.array(np.zeros(4, np.float32))}
    blob = mx.export.export_model(net, args, {}, {"data": (2, 6)})
    bundle = tmp_path / "model.mxtpu"
    bundle.write_bytes(blob)

    x = rng.rand(2, 6).astype(np.float32)
    ref_pred = mx.export.ExportedPredictor(blob)
    ref_pred.set_input("data", x)
    ref_pred.forward()
    expected = ref_pred.get_output(0)

    # generate the single-file module and run it in a clean interpreter
    # whose sys.path does NOT contain the repo (so `import mxnet_tpu`
    # would fail — proving self-containedness)
    module_path = tmp_path / "mxnet_tpu_predict.py"
    module_path.write_text(amalgamate())
    np.save(tmp_path / "x.npy", x)
    driver = tmp_path / "driver.py"
    driver.write_text(textwrap.dedent("""
        import sys
        sys.path = [p for p in sys.path if p not in (%r, '')]
        try:
            import mxnet_tpu
            raise SystemExit("repo leaked into path")
        except ImportError:
            pass
        import numpy as np
        from mxnet_tpu_predict import ExportedPredictor
        p = ExportedPredictor(%r)
        p.set_input("data", np.load(%r))
        p.forward()
        np.save(%r, p.get_output(0))
        print("STANDALONE OK")
    """ % (REPO, str(bundle), str(tmp_path / "x.npy"),
           str(tmp_path / "y.npy"))))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(tmp_path))
    r = subprocess.run([sys.executable, str(driver)], cwd=str(tmp_path),
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "STANDALONE OK" in r.stdout
    got = np.load(tmp_path / "y.npy")
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
