"""Stateful-optimizer distributed gates (round-4 verdict #5): Adam /
momentum state must accumulate correctly across ≥2 ranks, survive a
late-joiner's set_optimizer, and survive worker restarts — the bug class
the reference guards with rank-0-only command handling
(/root/reference/src/kvstore/kvstore_dist_server.h:166-207)."""
import pickle

import numpy as np
import pytest

from dist_util import REPO, fill, launch, maybe_skip_unavailable


def _serial_adam_trajectory(n_steps, lr=0.1, shape=(2,)):
    """The expected weight after n_steps server-side Adam updates of
    grad=1 — computed through the SAME optimizer implementation the
    server unpickles, driven locally."""
    import mxnet_tpu as mx

    opt = mx.optimizer.Adam(learning_rate=lr)
    w = mx.nd.zeros(shape)
    state = opt.create_state(0, w)
    g = mx.nd.ones(shape)
    for _ in range(n_steps):
        opt.update(0, w, g, state)
    return w.asnumpy()


ADAM_ASYNC_SCRIPT = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx

kv = mx.kv.create("dist_async")
rank, nw = kv.rank, kv.num_workers
assert nw == 2, nw

# ---- exactness: server-side Adam accumulates first/second moments
# across BOTH workers' pushes. Constant grads make the trajectory
# order-independent, so the interleaving doesn't matter — only that the
# server kept ONE evolving (mean, var, t) across 2*K pushes.
K = 4
kv.set_optimizer(mx.optimizer.Adam(learning_rate=0.1))
kv.barrier()
kv.init(3, mx.nd.zeros((2,)))
for _ in range(K):
    kv.push(3, mx.nd.ones((2,), dtype="float32"))
kv.barrier()                       # all 2K pushes landed
w = mx.nd.zeros((2,))
kv.pull(3, w)

opt = mx.optimizer.Adam(learning_rate=0.1)
ref = mx.nd.zeros((2,))
state = opt.create_state(0, ref)
for _ in range(2 * K):
    opt.update(0, ref, mx.nd.ones((2,)), state)
np.testing.assert_allclose(w.asnumpy(), ref.asnumpy(), atol=1e-5)

# ---- convergence: Module trains through server-side Adam
rng = np.random.RandomState(0)
n = 256
y = rng.randint(0, 2, n).astype(np.float32)
X = (rng.randn(n, 8).astype(np.float32) * 0.5 + y[:, None])
Xs, ys = X[rank::nw], y[rank::nw]
data = mx.sym.Variable("data")
net = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
net = mx.sym.Activation(data=net, act_type="relu")
net = mx.sym.FullyConnected(data=net, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(data=net, name="softmax")
it = mx.io.NDArrayIter(Xs, ys, batch_size=16, shuffle=False)
mod = mx.mod.Module(net, context=mx.cpu())
mod.fit(it, num_epoch=20, kvstore=kv,
        optimizer="adam", optimizer_params={"learning_rate": 0.005})
it.reset()
acc = next(iter(dict(mod.score(it, "acc")).values()))
assert acc > 0.9, acc
kv.barrier()
if rank == 0:
    kv.close()
print("ADAM_ASYNC_OK rank=%d acc=%.3f" % (rank, acc))
"""


def test_dist_async_adam_two_workers(tmp_path):
    out = launch(tmp_path, fill(ADAM_ASYNC_SCRIPT, tmp_path), port=23480,
                 timeout=420)
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-2500:])
    assert out.stdout.count("ADAM_ASYNC_OK") == 2, out.stdout[-1500:]


SYNC_MOMENTUM_SCRIPT = r"""
import os, sys
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx

TMP = %(tmp)r
kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers

rng = np.random.RandomState(0)
n = 256
y = rng.randint(0, 2, n).astype(np.float32)
X = (rng.randn(n, 8).astype(np.float32) * 0.5 + y[:, None])
Xs, ys = X[rank::nw], y[rank::nw]
data = mx.sym.Variable("data")
net = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
net = mx.sym.Activation(data=net, act_type="relu")
net = mx.sym.FullyConnected(data=net, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(data=net, name="softmax")
it = mx.io.NDArrayIter(Xs, ys, batch_size=16, shuffle=False)
mod = mx.mod.Module(net, context=mx.cpu())
mod.fit(it, num_epoch=8, kvstore=kv, optimizer="sgd",
        optimizer_params={"learning_rate": 0.2, "momentum": 0.9})
it.reset()
acc = next(iter(dict(mod.score(it, "acc")).values()))
assert acc > 0.9, acc

# sync + stateful updater must stay bit-identical across ranks: every
# rank applies the same aggregated gradients to the same momentum
arg, _ = mod.get_params()
np.save(os.path.join(TMP, "w_%d.npy" % rank),
        arg["fc1_weight"].asnumpy())
kv.barrier()
if rank == 1:
    a = np.load(os.path.join(TMP, "w_0.npy"))
    b = np.load(os.path.join(TMP, "w_1.npy"))
    np.testing.assert_array_equal(a, b)
print("SYNC_MOM_OK rank=%d acc=%.3f" % (rank, acc))
"""


def test_dist_sync_momentum_identical_across_ranks(tmp_path):
    out = launch(tmp_path, fill(SYNC_MOMENTUM_SCRIPT, tmp_path),
                 port=23481, timeout=420)
    maybe_skip_unavailable(out, "SYNC_MOM_OK" in out.stdout)
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-2500:])
    assert out.stdout.count("SYNC_MOM_OK") == 2, out.stdout[-1500:]


def test_worker_restart_preserves_server_adam_state():
    """A worker dying and reconnecting (new TCP session, same rank) must
    keep descending the SAME Adam trajectory: the state lives on the
    server, not in any client."""
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import ps

    server = ps.ParameterServer("127.0.0.1", 23718, num_workers=1)
    try:
        c = ps.PSClient("127.0.0.1", 23718)
        c.call("hello", 0)
        c.call("set_optimizer",
               pickle.dumps(mx.optimizer.Adam(learning_rate=0.1)))
        c.call("init", 0, 0, np.zeros(2, np.float32))
        for _ in range(3):
            c.call("push", 0, np.ones(2, np.float32))
        c.close()                       # worker "crash"

        c2 = ps.PSClient("127.0.0.1", 23718)   # restarted worker
        c2.call("hello", 0)
        for _ in range(3):
            c2.call("push", 0, np.ones(2, np.float32))
        got = c2.call("pull", 0)
        c2.close()
        np.testing.assert_allclose(got, _serial_adam_trajectory(6),
                                   atol=1e-5)
    finally:
        server.close()


def test_late_joiner_set_optimizer_keeps_adam_state():
    """A late worker's set_optimizer must not wipe the server's Adam
    moments (first-writer-wins, reference rank-0-only command path)."""
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import ps

    server = ps.ParameterServer("127.0.0.1", 23719, num_workers=2)
    try:
        blob = pickle.dumps(mx.optimizer.Adam(learning_rate=0.1))
        c0 = ps.PSClient("127.0.0.1", 23719)
        c0.call("hello", 0)
        c0.call("set_optimizer", blob)
        c0.call("init", 0, 0, np.zeros(2, np.float32))
        for _ in range(3):
            c0.call("push", 0, np.ones(2, np.float32))

        c1 = ps.PSClient("127.0.0.1", 23719)   # late joiner
        c1.call("hello", 1)
        c1.call("set_optimizer", blob)          # must be a no-op
        for _ in range(3):
            c1.call("push", 0, np.ones(2, np.float32))
        got = c1.call("pull", 0)
        np.testing.assert_allclose(got, _serial_adam_trajectory(6),
                                   atol=1e-5)
        c0.close()
        c1.close()
    finally:
        server.close()


def test_updater_adam_state_checkpoint_roundtrip():
    """Worker restart via checkpoint: serializing updater states
    (get_states/set_states, the Module.save_checkpoint path) and
    restoring into a FRESH updater must continue the exact trajectory of
    an uninterrupted run — momentum/variance survive the restart."""
    import mxnet_tpu as mx
    from mxnet_tpu.optimizer import get_updater

    rng = np.random.RandomState(0)
    grads = [rng.randn(4, 3).astype(np.float32) for _ in range(10)]

    def run(split=None):
        w = mx.nd.zeros((4, 3))
        upd = get_updater(mx.optimizer.Adam(learning_rate=0.05))
        for i, g in enumerate(grads):
            if split is not None and i == split:
                blob = upd.get_states()
                w_np = w.asnumpy()
                # "restart": brand-new updater + weight from checkpoint
                upd = get_updater(mx.optimizer.Adam(learning_rate=0.05))
                upd.set_states(blob)
                # num_update lives in the optimizer; restore it the way
                # Module.load does via begin_num_update
                upd.optimizer.begin_num_update = i
                upd.optimizer.num_update = i
                w = mx.nd.array(w_np)
            upd(0, mx.nd.array(g), w)
        return w.asnumpy()

    np.testing.assert_allclose(run(split=5), run(), atol=1e-6)
