"""Pipeline parallelism tests: GPipe schedule vs sequential oracle.

Mirrors the reference's model-parallel validation style
(tests/python/unittest/test_model_parallel.py: same net on 1 vs N
devices must match) for the pipelined trunk.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from mxnet_tpu.parallel._compat import shard_map

from mxnet_tpu.parallel import (make_mesh, pipeline_forward,
                                build_pipeline_train_step,
                                stack_stage_params, sequential_reference)

HID = 8


def stage_fn(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def make_stage_params(rng, n_stages):
    return [{"w": rng.randn(HID, HID).astype(np.float32) * 0.5,
             "b": rng.randn(HID).astype(np.float32) * 0.1}
            for _ in range(n_stages)]


@pytest.mark.parametrize("n_stages,n_mb", [(4, 4), (4, 8), (2, 3), (8, 5)])
def test_pipeline_forward_matches_sequential(n_stages, n_mb):
    rng = np.random.RandomState(0)
    per_stage = make_stage_params(rng, n_stages)
    stacked = stack_stage_params(per_stage)
    mesh = make_mesh({"pp": n_stages})

    mb = rng.randn(n_mb, 2, HID).astype(np.float32)

    fwd = shard_map(
        lambda p, x: pipeline_forward(stage_fn, p, x, "pp"),
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked),
                  P(None)),
        out_specs=P(None))
    out = jax.jit(fwd)(stacked, mb)

    expect = np.stack([np.asarray(
        sequential_reference(stage_fn, per_stage, m)) for m in mb])
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5,
                               atol=1e-5)


def test_pipeline_grads_match_sequential():
    n_stages, n_mb = 4, 4
    rng = np.random.RandomState(1)
    per_stage = make_stage_params(rng, n_stages)
    stacked = stack_stage_params(per_stage)
    mesh = make_mesh({"pp": n_stages})
    mb = rng.randn(n_mb, 2, HID).astype(np.float32)

    def pipe_loss(stacked, mb):
        fwd = shard_map(
            lambda p, x: pipeline_forward(stage_fn, p, x, "pp"),
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked),
                      P(None)),
            out_specs=P(None))
        return jnp.sum(fwd(stacked, mb) ** 2)

    def seq_loss(stacked, mb):
        outs = []
        for i in range(n_mb):
            x = mb[i]
            for s in range(n_stages):
                x = stage_fn(jax.tree_util.tree_map(lambda l: l[s],
                                                    stacked), x)
            outs.append(x)
        return jnp.sum(jnp.stack(outs) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked, mb)
    g_seq = jax.grad(seq_loss)(stacked, mb)
    for k in ("w", "b"):
        np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("n_stages", [2, 4])
def test_train_step_grads_match_sequential(n_stages):
    """The train-step path (loss + grad INSIDE shard_map) must take the
    same SGD step as the sequential oracle — catches pp-size gradient
    scaling."""
    n_mb, mbsz, lr = 4, 2, 0.5
    rng = np.random.RandomState(3)
    per_stage = make_stage_params(rng, n_stages)
    stacked = stack_stage_params(per_stage)
    mesh = make_mesh({"pp": n_stages})
    mb = rng.randn(n_mb, mbsz, HID).astype(np.float32)
    labels = rng.randn(n_mb, mbsz, HID).astype(np.float32)

    def loss_fn(out, lab):
        return jnp.mean((out - lab) ** 2)

    step = build_pipeline_train_step(stage_fn, loss_fn, mesh,
                                     num_microbatches=n_mb,
                                     pp_axis="pp", lr=lr)
    loss, new_params = jax.jit(step)(stacked, mb, labels)

    def seq_loss(stacked):
        per_mb = []
        for i in range(n_mb):
            x = mb[i]
            for s in range(n_stages):
                x = stage_fn(jax.tree_util.tree_map(lambda l: l[s],
                                                    stacked), x)
            per_mb.append(loss_fn(x, labels[i]))
        return jnp.mean(jnp.stack(per_mb))

    g_seq = jax.grad(seq_loss)(stacked)
    for k in ("w", "b"):
        expect = np.asarray(stacked[k]) - lr * np.asarray(g_seq[k])
        np.testing.assert_allclose(np.asarray(new_params[k]), expect,
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(loss), float(seq_loss(stacked)),
                               rtol=1e-5)


def test_pipeline_train_step_dp_pp():
    """pp=4 x dp=2 mesh: loss decreases and grads stay in sync across dp."""
    n_stages, n_mb, mbsz = 4, 4, 4
    rng = np.random.RandomState(2)
    per_stage = make_stage_params(rng, n_stages)
    stacked = stack_stage_params(per_stage)
    mesh = make_mesh({"pp": n_stages, "dp": 2})

    mb = rng.randn(n_mb, mbsz, HID).astype(np.float32)
    labels = rng.randn(n_mb, mbsz, HID).astype(np.float32)

    def loss_fn(out, lab):
        return jnp.mean((out - lab) ** 2)

    step = build_pipeline_train_step(stage_fn, loss_fn, mesh,
                                     num_microbatches=n_mb,
                                     pp_axis="pp", dp_axis="dp", lr=0.05)
    jstep = jax.jit(step)
    stacked = jax.device_put(
        stacked, jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P("pp")), stacked))
    mbd = jax.device_put(mb, NamedSharding(mesh, P(None, "dp")))
    labd = jax.device_put(labels, NamedSharding(mesh, P(None, "dp")))

    losses = []
    params = stacked
    for _ in range(5):
        loss, params = jstep(params, mbd, labd)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # params on each pp rank updated (stage grads flowed to every stage)
    w_new = np.asarray(params["w"])
    w_old = np.asarray(stack_stage_params(per_stage)["w"])
    for s in range(n_stages):
        assert not np.allclose(w_new[s], w_old[s]), "stage %d frozen" % s
