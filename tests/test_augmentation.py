"""Augmentation parity tests: rotate / shear / pad / HSL color jitter
(reference src/io/image_aug_default.cc:40-300)."""
import colorsys

import numpy as np
import pytest

pytest.importorskip("PIL")

import mxnet_tpu.io as mio
import mxnet_tpu.recordio as rio


def _make_rec(tmp_path, imgs, fmt=".png"):
    path = str(tmp_path / "aug.rec")
    writer = rio.MXRecordIO(path, "w")
    for i, img in enumerate(imgs):
        writer.write(rio.pack_img(rio.IRHeader(0, float(i), i, 0), img,
                                  quality=100, img_fmt=fmt))
    writer.close()
    return path


def _iter(path, **kw):
    kw.setdefault("data_shape", (3, 8, 8))
    kw.setdefault("batch_size", 1)
    return mio.ImageRecordIter(path_imgrec=path, **kw)


def test_rotate_90_exact(tmp_path):
    """Deterministic rotate=90 on a square image == np.rot90 in the
    reference's convention (M = [[cos, sin], [-sin, cos]])."""
    rng = np.random.RandomState(0)
    img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
    path = _make_rec(tmp_path, [img])
    it = _iter(path, rotate=90)
    out = next(iter(it)).data[0].asnumpy()[0]          # (3, 8, 8)
    base = img.astype(np.float32).transpose(2, 0, 1)
    # reference forward matrix [[a, b], [-b, a]] at 90 degrees maps
    # (x, y) -> (y, -x): a counter-clockwise quarter turn (rot90 k=1);
    # atol 1 for uint8 bilinear rounding
    expected = np.rot90(base, k=1, axes=(1, 2))
    assert np.abs(out - expected).max() <= 1.0


def test_max_rotate_angle_changes_pixels(tmp_path):
    rng = np.random.RandomState(1)
    img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
    path = _make_rec(tmp_path, [img])
    plain = next(iter(_iter(path))).data[0].asnumpy()
    rot = next(iter(_iter(path, max_rotate_angle=30, seed=3))).data[0].asnumpy()
    assert np.abs(plain - rot).max() > 1.0


def test_rotate_fill_value(tmp_path):
    """Corners exposed by rotation are filled with fill_value."""
    img = np.full((8, 8, 3), 200, dtype=np.uint8)
    path = _make_rec(tmp_path, [img])
    out = next(iter(_iter(path, rotate=45, fill_value=0))).data[0].asnumpy()[0]
    assert out.min() < 1.0          # filled corners
    assert out.max() > 150.0        # original content survives


def test_shear_changes_pixels(tmp_path):
    rng = np.random.RandomState(2)
    img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
    path = _make_rec(tmp_path, [img])
    plain = next(iter(_iter(path))).data[0].asnumpy()
    sheared = next(iter(_iter(path, max_shear_ratio=0.3, seed=7))).data[0] \
        .asnumpy()
    assert np.abs(plain - sheared).max() > 1.0


def test_pad_then_crop(tmp_path):
    """pad=2 then center-crop: border shows fill_value."""
    img = np.full((8, 8, 3), 100, dtype=np.uint8)
    path = _make_rec(tmp_path, [img])
    out = next(iter(_iter(path, data_shape=(3, 12, 12), pad=2,
                          fill_value=255))).data[0].asnumpy()[0]
    assert abs(out[0, 0, 0] - 255.0) < 1e-4      # padded corner
    assert abs(out[0, 6, 6] - 100.0) < 1e-4      # original center


def test_hsl_lightness_direction(tmp_path):
    """random_l with a forced positive draw brightens the image; the
    magnitude matches the OpenCV unit convention (L in [0,255])."""
    rng = np.random.RandomState(3)
    img = (rng.rand(8, 8, 3) * 100 + 50).astype(np.uint8)
    path = _make_rec(tmp_path, [img])
    it = _iter(path, random_l=50)
    stub = type("R", (), {
        "rand": staticmethod(lambda *a: np.float64(1.0)),   # dl = +50
        "randint": staticmethod(lambda *a, **k: 0),
        "shuffle": staticmethod(lambda x: None)})()
    it._derive_rng = lambda epoch, idx: stub
    out = next(iter(it)).data[0].asnumpy()[0]
    base = img.astype(np.float32).transpose(2, 0, 1)
    assert out.mean() > base.mean() + 20.0


def test_hsl_zero_jitter_is_identity(tmp_path):
    rng = np.random.RandomState(4)
    img = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
    path = _make_rec(tmp_path, [img])
    out = next(iter(_iter(path, random_h=0, random_s=0,
                          random_l=0))).data[0].asnumpy()[0]
    np.testing.assert_allclose(out, img.astype(np.float32).transpose(2, 0, 1),
                               atol=1e-4)


def test_hsl_roundtrip_matches_colorsys(tmp_path):
    """The vectorized RGB<->HLS pair agrees with colorsys on random pixels
    (jitter forced to zero offsets but conversion path exercised)."""
    it = mio.RecordDecoder.__new__(mio.RecordDecoder)
    it.random_h, it.random_s, it.random_l = 180, 0, 0
    rng_half = type("R", (), {
        "rand": staticmethod(lambda *a: np.float64(0.5))})()  # dh = 0
    rng = np.random.RandomState(5)
    img = (rng.rand(6, 6, 3) * 255).astype(np.float32)
    out = it._hsl_augment(img, rng_half)
    np.testing.assert_allclose(out, img, atol=1.0)

    # and a real hue shift agrees with colorsys applied pixelwise
    it.random_h = 90
    rng_one = type("R", (), {
        "rand": staticmethod(lambda *a: np.float64(1.0))})()  # dh = +90
    out = it._hsl_augment(img, rng_one)
    i, j = 2, 3
    r, g, b = (img[i, j] / 255.0).tolist()
    h, l, s = colorsys.rgb_to_hls(r, g, b)
    h = min(h * 180.0 + 90.0, 180.0) / 180.0   # reference clamps H to 180
    exp = np.array(colorsys.hls_to_rgb(h, l, s)) * 255.0
    np.testing.assert_allclose(out[i, j], exp, atol=1.5)


def test_mean_image_ignores_augmentation(tmp_path):
    """The cached mean image must come from an unaugmented pass."""
    rng = np.random.RandomState(6)
    imgs = [(rng.rand(8, 8, 3) * 255).astype(np.uint8) for _ in range(4)]
    path = _make_rec(tmp_path, imgs)
    mean_path = str(tmp_path / "mean.bin")
    it = _iter(path, mean_img=mean_path, max_rotate_angle=45,
               random_l=50, max_shear_ratio=0.3)
    expected = np.mean([im.astype(np.float32).transpose(2, 0, 1)
                        for im in imgs], axis=0)
    np.testing.assert_allclose(it.mean, expected, atol=1e-3)
    # augmentation params restored after the mean pass
    assert it.max_rotate_angle == 45 and it.random_l == 50
