"""Continuous-batching serving tier: batcher parity (bit-identical
batched-padded vs one-by-one), the bucket-ladder compile pin (at most
len(buckets) executables ever, exactly one dispatch per served batch),
dp=8 vs dp=1 parity on the forced mesh, the SLO health probe flipping
/healthz, and the graceful-shutdown drain (leak-gate clean)."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.module import Module
from mxnet_tpu.serving import BatchScheduler, bucket_ladder

DIM = 8
CLASSES = 4
HID = 16


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _seed_params(net, batch, seed=3):
    """Exact-arithmetic regime (integer data x half-integer weights,
    power-of-two sizes): every logit is a dyadic rational, so batching,
    padding, row offset and dp-sharding cannot perturb bits."""
    arg_shapes, _, _ = net.infer_shape(data=(batch, DIM),
                                       softmax_label=(batch,))
    rng = np.random.RandomState(seed)
    return {name: mx.nd.array(
        (rng.randint(-2, 3, shape) * 0.5).astype(np.float32))
        for name, shape in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}


def _rows(n, seed=11):
    rng = np.random.RandomState(seed)
    return rng.randint(-3, 4, (n, DIM)).astype(np.float32)


def _bound_module(dp=1, batch=8):
    net = _mlp()
    ctx = [mx.cpu(i) for i in range(dp)] if dp > 1 else mx.cpu()
    mod = Module(net, context=ctx)
    mod.bind(data_shapes=[("data", (batch, DIM))],
             label_shapes=[("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(initializer=None, arg_params=_seed_params(net, batch),
                    aux_params={})
    return mod


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------

def test_bucket_ladder_powers_of_two():
    assert bucket_ladder(64) == (1, 2, 4, 8, 16, 32, 64)
    assert bucket_ladder(6) == (1, 2, 4, 6)


def test_bucket_ladder_dp_rounds_every_rung():
    # every rung a multiple of dp so the batch axis always shards evenly
    assert bucket_ladder(16, dp=8) == (8, 16)
    assert bucket_ladder(64, dp=8) == (8, 16, 32, 64)
    for r in bucket_ladder(24, dp=4):
        assert r % 4 == 0


def test_bucket_ladder_explicit_spec():
    assert bucket_ladder(64, spec="3,17,64") == (3, 17, 64)
    # a spec that tops out below max_batch still gets a covering rung
    assert bucket_ladder(64, dp=4, spec="3,17")[-1] == 64


# ---------------------------------------------------------------------------
# BatchScheduler on a fake infer: padding/slicing parity, FIFO carry,
# graceful shutdown — no jax in the loop, fully deterministic
# ---------------------------------------------------------------------------

def _fake_infer(placed):
    # identity "model": result row i is input row i doubled, so the
    # per-request slices prove the stager padded and the scheduler
    # sliced at the right offsets
    return [placed[0] * 2.0], ()


def test_scheduler_pads_and_slices_per_request():
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0)
    try:
        payloads = [_rows(1, seed=s) for s in (1, 2, 3)]
        payloads.append(_rows(3, seed=4))    # multi-row request
        reqs = [sched.submit([p]) for p in payloads]
        for p, r in zip(payloads, reqs):
            (out,) = r.get(timeout=30)
            assert out.shape == p.shape
            assert np.array_equal(out, p * 2.0)
    finally:
        sched.close()


def test_scheduler_rejects_bad_requests():
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0)
    try:
        with pytest.raises(MXNetError, match="row shape"):
            sched.submit([np.zeros((1, DIM + 1), np.float32)])
        with pytest.raises(MXNetError, match="max_batch"):
            sched.submit([np.zeros((5, DIM), np.float32)])
        with pytest.raises(MXNetError, match="input arrays"):
            sched.submit([np.zeros((1, DIM), np.float32)] * 2)
    finally:
        sched.close()


def test_graceful_shutdown_drains_queue(tel):
    done = threading.Event()

    def slow_infer(placed):
        time.sleep(0.002)
        return [placed[0] * 2.0], ()

    sched = BatchScheduler(slow_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=0.5, slo_ms=0.0)
    reqs = [sched.submit([_rows(1, seed=s)]) for s in range(32)]
    sched.close()
    # every request submitted before close() was SERVED, not dropped
    for r in reqs:
        assert r.done()
        (out,) = r.get(timeout=0)
        assert out.shape == (1, DIM)
    assert not sched._worker.is_alive()
    sched.close()                      # idempotent
    with pytest.raises(MXNetError, match="closed"):
        sched.submit([_rows(1)])
    assert not done.is_set()           # no stray callbacks
    assert tel.peek("serve.errors") in (None, 0)


# ---------------------------------------------------------------------------
# real model through InferenceServer
# ---------------------------------------------------------------------------

def test_batcher_parity_bit_identical(tel):
    """Coalesced-padded-sliced results == one-by-one results, bit for
    bit: whatever grouping the continuous batcher picks, padding rows
    and batch offsets must never leak into a request's answer."""
    mod = _bound_module(dp=1, batch=8)
    rows = [_rows(1, seed=100 + i) for i in range(12)]
    with serving.InferenceServer(mod, top_k=0, max_batch=8,
                                 max_wait_ms=1.0, buckets=[8],
                                 slo_ms=0.0, port=None) as srv:
        one_by_one = [srv.infer([r])[0] for r in rows]
        # burst: submit everything before collecting, so the batcher
        # coalesces multiple requests into shared padded dispatches
        reqs = [srv.submit([r]) for r in rows]
        batched = [req.get(timeout=30)[0] for req in reqs]
    for a, b in zip(one_by_one, batched):
        assert a.dtype == b.dtype
        assert np.array_equal(a, b), \
            "batched result diverged (max abs diff %g)" % np.abs(a - b).max()


def test_bucket_ladder_compile_pin(tel):
    """At most len(buckets) compiles EVER; zero once every rung is
    warm; exactly 1.0 dispatches per served batch (the forward and the
    on-device argmax ride one executable)."""
    mod = _bound_module(dp=1, batch=8)
    with serving.InferenceServer(mod, top_k=1, max_batch=8,
                                 max_wait_ms=0.5, slo_ms=0.0,
                                 port=None) as srv:
        assert srv.buckets == (1, 2, 4, 8)
        for n in (1, 2, 3, 5, 8, 1, 4, 7):
            srv.infer([_rows(n)])
        assert srv.compiles <= len(srv.buckets)
        warm = srv.compiles
        d0 = tel.peek("infer.dispatches") or 0
        b0 = tel.peek("serve.batches") or 0
        for n in (1, 2, 3, 5, 8, 6, 2, 1):
            srv.infer([_rows(n)])
        # steady state: every rung warm -> ZERO further compiles
        assert srv.compiles == warm
        assert tel.peek("infer.recompiles") == warm
        d1 = tel.peek("infer.dispatches") or 0
        b1 = tel.peek("serve.batches") or 0
        assert b1 - b0 == 8
        assert (d1 - d0) / float(b1 - b0) == 1.0
        stats = srv.stats()
        assert stats["requests_served"] >= 16
        assert stats["batches"] == b1
    assert (tel.peek("serve.pad_rows") or 0) > 0


@pytest.mark.multichip
def test_dp8_parity_with_single_device(tel):
    """Replicated params + dp-sharded request batches give the same
    bits as one device: GSPMD partitioning of the serving forward is
    a layout change, not a numeric one (exact-arithmetic regime)."""
    rows = [_rows(1, seed=200 + i) for i in range(10)]
    outs = {}
    for dp in (1, 8):
        mod = _bound_module(dp=dp, batch=16)
        # same bucket for both servers so XLA sees identical shapes
        with serving.InferenceServer(mod, top_k=0, max_batch=16,
                                     buckets=[16], max_wait_ms=0.5,
                                     slo_ms=0.0, port=None) as srv:
            if dp == 8:
                assert srv.dp == 8
            outs[dp] = [srv.infer([r])[0] for r in rows]
    for a, b in zip(outs[1], outs[8]):
        assert a.dtype == b.dtype
        assert np.array_equal(a, b), \
            "dp=8 serving diverged (max abs diff %g)" % np.abs(a - b).max()


# ---------------------------------------------------------------------------
# SLO -> /healthz
# ---------------------------------------------------------------------------

def _healthz(port):
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/healthz" % port, timeout=10) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_slo_breach_flips_healthz(tel):
    mod = _bound_module(dp=1, batch=8)
    # an SLO of 1 microsecond: every real dispatch breaches it
    with serving.InferenceServer(mod, top_k=1, max_batch=8,
                                 max_wait_ms=0.5, slo_ms=0.001,
                                 port=0) as srv:
        assert srv.port is not None
        for _ in range(4):
            srv.infer([_rows(1)])
        probe = srv.scheduler.slo_probe()
        assert probe is not None and probe["p99_ms"] > probe["slo_ms"]
        status, health = _healthz(srv.port)
        assert status == 503
        assert health["status"] == "degraded"
        assert any(k.startswith("serve_slo:") for k in health["probes"])


def test_healthz_ok_within_slo(tel):
    mod = _bound_module(dp=1, batch=8)
    with serving.InferenceServer(mod, top_k=1, max_batch=8,
                                 max_wait_ms=0.5, slo_ms=60000.0,
                                 port=0) as srv:
        for _ in range(3):
            srv.infer([_rows(1)])
        assert srv.scheduler.slo_probe() is None
        status, health = _healthz(srv.port)
        assert status == 200
        assert health["status"] == "ok"
        assert "probes" not in health


# ---------------------------------------------------------------------------
# base_module pad-and-slice: the final partial batch must reuse the one
# compiled forward, not trace a one-off shape
# ---------------------------------------------------------------------------

class _RaggedIter:
    """Yields a genuinely SMALLER final batch (11 rows at batch 4 ->
    4, 4, 3), the shape pattern that used to retrace the forward."""

    def __init__(self, X, y, batch_size):
        self._X, self._y, self._bs = X, y, batch_size
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size,) + X.shape[1:])]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (batch_size,))]

    def reset(self):
        pass

    def __iter__(self):
        for lo in range(0, len(self._X), self._bs):
            yield mx.io.DataBatch(
                [mx.nd.array(self._X[lo:lo + self._bs])],
                [mx.nd.array(self._y[lo:lo + self._bs])], pad=0)


def test_module_predict_partial_batch_no_retrace(tel):
    X = _rows(11, seed=5)
    y = np.array([i % CLASSES for i in range(11)], np.float32)
    it = _RaggedIter(X, y, batch_size=4)
    mod = _bound_module(dp=1, batch=4)
    out = mod.predict(it)
    assert out.shape == (11, CLASSES)
    # the pin: ONE traced forward served both the full and the padded
    # partial batches
    assert mod._exec_group.executor._fwd_infer._cache_size() == 1
    assert tel.peek("module.pad_batches") == 1
    # pad rows sliced off: the partial tail matches an unpadded forward
    full = mod.predict(_RaggedIter(X[8:], y[8:], batch_size=4))
    assert np.array_equal(out.asnumpy()[8:], full.asnumpy()[:3])


def test_module_score_partial_batch_exact_metric(tel):
    X = _rows(11, seed=6)
    y = np.array([i % CLASSES for i in range(11)], np.float32)
    it = _RaggedIter(X, y, batch_size=4)
    mod = _bound_module(dp=1, batch=4)
    (_, acc), = mod.score(it, "acc")
    pred = mod.predict(_RaggedIter(X, y, batch_size=4)).asnumpy()
    expect = float((pred.argmax(axis=1) == y).sum()) / 11.0
    assert acc == expect
    assert mod._exec_group.executor._fwd_infer._cache_size() == 1


# ---------------------------------------------------------------------------
# retry safety: request-id dedup, double-start guard, close idempotence,
# /healthz replica identity
# ---------------------------------------------------------------------------

def _idempotent_fake(placed):
    return [placed[0] * 2.0], ()


_idempotent_fake.idempotent = True


def test_scheduler_dedups_request_ids(tel):
    sched = BatchScheduler(_idempotent_fake, [(4, DIM)], max_batch=4,
                           max_wait_ms=200.0, slo_ms=0.0)
    try:
        x = _rows(1, seed=21)
        r1 = sched.submit([x], request_id="req-A")
        # a retry of an in-flight id joins the SAME request object:
        # one dispatch, one answer, both handles resolve together
        r2 = sched.submit([x], request_id="req-A")
        assert r2 is r1
        (out,) = r1.get(timeout=30)
        assert np.array_equal(out, x * 2.0)
        # a retry AFTER completion reuses the served result (the infer
        # fn is tagged idempotent, so replay is safe and free)
        r3 = sched.submit([x], request_id="req-A")
        assert r3 is r1
        assert np.array_equal(r3.get(timeout=1)[0], x * 2.0)
        assert tel.peek("serve.duplicate_requests") == 2
        assert tel.peek("serve.requests") == 1
    finally:
        sched.close()


def test_scheduler_no_completed_dedup_without_idempotent_tag(tel):
    # _fake_infer carries no .idempotent tag: completed results must
    # NOT be replayed (only the always-safe in-flight join applies)
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0)
    try:
        x = _rows(1, seed=22)
        r1 = sched.submit([x], request_id="req-B")
        r1.get(timeout=30)
        r2 = sched.submit([x], request_id="req-B")
        assert r2 is not r1
        r2.get(timeout=30)
        assert (tel.peek("serve.duplicate_requests") or 0) == 0
    finally:
        sched.close()


def test_scheduler_double_start_and_close_idempotence():
    sched = BatchScheduler(_fake_infer, [(4, DIM)], max_batch=4,
                           max_wait_ms=1.0, slo_ms=0.0)
    with pytest.raises(MXNetError, match="double start"):
        sched.start()
    sched.close()
    sched.close()                       # idempotent: second is a no-op
    assert not sched._worker.is_alive()
    with pytest.raises(MXNetError, match="closed"):
        sched.start()                   # closed schedulers stay closed
    with pytest.raises(MXNetError, match="closed"):
        sched.submit([_rows(1)])


def test_server_close_idempotent_and_healthz_identity(tel):
    mod = _bound_module(dp=1, batch=8)
    srv = serving.InferenceServer(mod, top_k=0, max_batch=8,
                                  max_wait_ms=0.5, buckets=[8],
                                  slo_ms=0.0, port=0)
    try:
        srv.infer([_rows(2)])
        status, health = _healthz(srv.port)
        assert status == 200
        # replica identity for the fleet router: who am I, how busy
        assert health["pid"] == __import__("os").getpid()
        assert "rank" in health and "uptime_s" in health
        assert health["in_flight"] == 0
        assert health["requests_served"] >= 1
        assert srv.stats()["in_flight"] == 0
    finally:
        srv.close()
        srv.close()                     # idempotent
    assert srv.closed
    with pytest.raises(MXNetError, match="closed"):
        srv.submit([_rows(1)])
