"""`import mxnet` compatibility alias: reference-style scripts run
against this framework unchanged (module names, `from mxnet.x import y`
forms, the FeedForward workflow, checkpointing by prefix)."""
import numpy as np

import mxnet as mx
from mxnet.symbol import Variable
from mxnet import io as mio
from mxnet import ndarray as mnd


def _build():
    net = Variable("data")
    net = mx.symbol.FullyConnected(data=net, name="fc1", num_hidden=16)
    net = mx.symbol.Activation(data=net, name="relu1", act_type="relu")
    net = mx.symbol.FullyConnected(data=net, name="fc2", num_hidden=2)
    return mx.symbol.SoftmaxOutput(data=net, name="softmax")


def test_alias_modules_are_mxnet_tpu():
    import mxnet_tpu

    assert mx.nd is mxnet_tpu.ndarray
    assert mx.sym is mxnet_tpu.symbol
    assert mx.mod is mxnet_tpu.module
    assert mnd is mxnet_tpu.ndarray
    assert mx.kv.create("local").type == "local"
    # reference gpu contexts resolve to the accelerator context
    assert mx.gpu(0) == mx.tpu(0)


def test_every_reference_module_name_imports():
    """Every python/mxnet/*.py module name from the reference resolves
    under the alias package (round 4 closed misc/kvstore_server/libinfo/
    _ndarray_internal/_symbol_internal/symbol_doc/torch)."""
    import importlib

    reference_modules = [
        "attribute", "base", "callback", "context", "executor",
        "executor_manager", "initializer", "io", "kvstore",
        "kvstore_server", "libinfo", "lr_scheduler", "metric", "misc",
        "model", "module", "monitor", "name", "ndarray", "operator",
        "optimizer", "random", "recordio", "rtc", "symbol",
        "symbol_doc", "test_utils", "torch", "visualization",
        "_ndarray_internal", "_symbol_internal",
    ]
    for name in reference_modules:
        mod = importlib.import_module("mxnet." + name)
        assert mod is getattr(mx, name), name
    # the misc module is the schedulers' historical home
    assert mx.misc.FactorScheduler is mx.lr_scheduler.FactorScheduler
    # libinfo finds the built native libraries (both ship in-tree, so
    # an empty list means discovery broke, not "nothing built")
    paths = mx.libinfo.find_lib_path()
    assert paths and all(p.endswith(".so") for p in paths), paths


def test_kvstore_server_role_hosts_ps(tmp_path):
    """A DMLC_ROLE=server process must host a live parameter server
    (the reference launch contract: trackers spawn server processes
    that sit in KVStoreServer.run())."""
    import os
    import subprocess
    import sys

    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    from mxnet_tpu.parallel import ps

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["DMLC_ROLE"] = "server"
    env["MXTPU_COORDINATOR"] = "127.0.0.1:23721"
    env["MXTPU_NUM_WORKERS"] = "1"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # log to files, not pipes: an undrained pipe can deadlock the child
    # and would swallow startup diagnostics on failure
    out_path = tmp_path / "server.log"
    with open(out_path, "w") as log:
        server = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu');"
             "import mxnet.kvstore_server"],  # module import runs the role
            env=env, stdout=log, stderr=subprocess.STDOUT)
    try:
        client = ps.PSClient("127.0.0.1", 23722, timeout_s=60)
        import numpy as np

        client.call("init", 0, 7, np.arange(3, dtype=np.float32))
        got = client.call("pull", 7)
        np.testing.assert_allclose(got, [0.0, 1.0, 2.0])
        client.close()

        # a WORKER kvstore must coexist with the external server: rank
        # 0 detects the bound address, runs as a pure client against
        # the SAME store, and its close() stops the external server
        # (the full reference tracker contract, not just raw sockets)
        import mxnet_tpu as mxt

        os.environ["MXTPU_COORDINATOR"] = "127.0.0.1:23721"
        os.environ["MXTPU_NUM_WORKERS"] = "1"
        os.environ["MXTPU_WORKER_RANK"] = "0"
        try:
            kv = mxt.kv.create("dist_async")
            assert kv._server is None         # deferred to external
            pulled = mxt.nd.zeros((3,))
            kv.pull(7, pulled)
            np.testing.assert_allclose(pulled.asnumpy(), [0.0, 1.0, 2.0])
            kv.close()                        # must stop the external PS
        finally:
            for k in ("MXTPU_COORDINATOR", "MXTPU_NUM_WORKERS",
                      "MXTPU_WORKER_RANK"):
                os.environ.pop(k, None)
        assert server.wait(timeout=30) == 0, out_path.read_text()[-1500:]
    finally:
        if server.poll() is None:
            server.kill()
            server.wait()


def test_reference_style_training_script(tmp_path):
    """The reference's python-howto flavor: build with mx.symbol.*,
    group outputs, train with FeedForward, checkpoint, reload."""
    out = _build()
    fc1 = out.get_internals()["fc1_output"]
    group = mx.symbol.Group([fc1, out])
    assert group.list_outputs() == ["fc1_output", "softmax_output"]

    rng = np.random.RandomState(0)
    y = rng.randint(0, 2, 128).astype(np.float32)
    X = (rng.randn(128, 6) + y[:, None] * 1.5).astype(np.float32)
    model = mx.model.FeedForward.create(
        out, X=mio.NDArrayIter(X, y, batch_size=32, shuffle=True),
        num_epoch=20, learning_rate=0.3)
    acc = (model.predict(mio.NDArrayIter(X, batch_size=32))
           .argmax(axis=1) == y).mean()
    assert acc > 0.9, acc

    prefix = str(tmp_path / "compat")
    model.save(prefix, 20)
    again = mx.model.FeedForward.load(prefix, 20)
    np.testing.assert_array_equal(
        again.predict(mio.NDArrayIter(X, batch_size=32)),
        model.predict(mio.NDArrayIter(X, batch_size=32)))
