"""`import mxnet` compatibility alias: reference-style scripts run
against this framework unchanged (module names, `from mxnet.x import y`
forms, the FeedForward workflow, checkpointing by prefix)."""
import numpy as np

import mxnet as mx
from mxnet.symbol import Variable
from mxnet import io as mio
from mxnet import ndarray as mnd


def _build():
    net = Variable("data")
    net = mx.symbol.FullyConnected(data=net, name="fc1", num_hidden=16)
    net = mx.symbol.Activation(data=net, name="relu1", act_type="relu")
    net = mx.symbol.FullyConnected(data=net, name="fc2", num_hidden=2)
    return mx.symbol.SoftmaxOutput(data=net, name="softmax")


def test_alias_modules_are_mxnet_tpu():
    import mxnet_tpu

    assert mx.nd is mxnet_tpu.ndarray
    assert mx.sym is mxnet_tpu.symbol
    assert mx.mod is mxnet_tpu.module
    assert mnd is mxnet_tpu.ndarray
    assert mx.kv.create("local").type == "local"
    # reference gpu contexts resolve to the accelerator context
    assert mx.gpu(0) == mx.tpu(0)


def test_reference_style_training_script(tmp_path):
    """The reference's python-howto flavor: build with mx.symbol.*,
    group outputs, train with FeedForward, checkpoint, reload."""
    out = _build()
    fc1 = out.get_internals()["fc1_output"]
    group = mx.symbol.Group([fc1, out])
    assert group.list_outputs() == ["fc1_output", "softmax_output"]

    rng = np.random.RandomState(0)
    y = rng.randint(0, 2, 128).astype(np.float32)
    X = (rng.randn(128, 6) + y[:, None] * 1.5).astype(np.float32)
    model = mx.model.FeedForward.create(
        out, X=mio.NDArrayIter(X, y, batch_size=32, shuffle=True),
        num_epoch=20, learning_rate=0.3)
    acc = (model.predict(mio.NDArrayIter(X, batch_size=32))
           .argmax(axis=1) == y).mean()
    assert acc > 0.9, acc

    prefix = str(tmp_path / "compat")
    model.save(prefix, 20)
    again = mx.model.FeedForward.load(prefix, 20)
    np.testing.assert_array_equal(
        again.predict(mio.NDArrayIter(X, batch_size=32)),
        model.predict(mio.NDArrayIter(X, batch_size=32)))
