"""Tensor-sharded inference on the ``(dp, tp)`` serving mesh: params
NamedSharding-split along each param's largest divisible dim,
activations resharded in-graph by GSPMD inside the ONE non-donated
dispatch. Covers the spec helpers and the mesh-extent bucket ladder,
tp=2 vs tp=1 bit-identical parity in the exact-arithmetic regime, the
one-dispatch/zero-retrace pin with tp armed, the per-device byte
ratio, the placement-aware executable caches (xprof leaf signature +
``FusedInfer.stale_for``), the delta-aware weight stream (equivalence
to a full re-pack, digest skip accounting, the env bypass hatch, the
snapshot round-trip) and rolling-swap purity under ``torn_swap`` with
checkpoint-streamed weights."""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import faults, fleet, serving, telemetry, xprof
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import SnapshotStore, param_digest
from mxnet_tpu.fleet import FleetRouter
from mxnet_tpu.module import Module
from mxnet_tpu.parallel.sharding import (batch_shard_extent, make_mesh,
                                         tp_param_spec)
from mxnet_tpu.serving import InferenceServer, bucket_ladder

# exact-arithmetic regime (see test_serving.py): integer data x
# half-integer weights, power-of-two sizes — every logit is a dyadic
# rational, so dp-replicated vs tp-sharded parity is ``==``, not
# ``allclose``. HID=16 so fc1 (weight (16, 8), bias (16,)) actually
# SHARDS at tp=2.
DIM = 8
CLASSES = 4
HID = 16
BATCH = 8


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


@pytest.fixture
def no_faults():
    yield
    faults.configure(None)


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _seed_params(net, batch, seed=3):
    arg_shapes, _, _ = net.infer_shape(data=(batch, DIM),
                                       softmax_label=(batch,))
    rng = np.random.RandomState(seed)
    return {name: mx.nd.array(
        (rng.randint(-2, 3, shape) * 0.5).astype(np.float32))
        for name, shape in zip(net.list_arguments(), arg_shapes)
        if name not in ("data", "softmax_label")}


def _rows(n, seed=11):
    rng = np.random.RandomState(seed)
    return rng.randint(-3, 4, (n, DIM)).astype(np.float32)


def _bound_module(n_dev=8, batch=BATCH):
    net = _mlp()
    ctx = [mx.cpu(i) for i in range(n_dev)] if n_dev > 1 else mx.cpu()
    mod = Module(net, context=ctx)
    mod.bind(data_shapes=[("data", (batch, DIM))],
             label_shapes=[("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(initializer=None,
                    arg_params=_seed_params(net, batch), aux_params={})
    return mod


def _server(tp=0, n_dev=8, batch=BATCH, **kw):
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("slo_ms", 0.0)
    return InferenceServer(_bound_module(n_dev, batch),
                           max_batch=batch, tp=tp, **kw)


def _dev0_bytes(fused):
    dev0 = total = 0
    for v in fused._param_vals:
        total += int(v.nbytes)
        for s in v.addressable_shards:
            if s.device.id == 0:
                dev0 += int(np.prod(s.data.shape)
                            * s.data.dtype.itemsize)
    return dev0, total


# ---------------------------------------------------------------------------
# spec helpers
# ---------------------------------------------------------------------------

@pytest.mark.multichip
def test_tp_param_spec_picks_largest_divisible_dim():
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"dp": 4, "tp": 2}, devices=jax.devices()[:8])
    assert tp_param_spec((16, 8), mesh) == P("tp", None)
    assert tp_param_spec((8, 16), mesh) == P(None, "tp")
    # a tie keeps the first largest dim
    assert tp_param_spec((8, 8), mesh) == P("tp", None)
    assert tp_param_spec((16,), mesh) == P("tp")
    # nothing divides -> replicate rather than fail the bind
    assert tp_param_spec((7, 3), mesh) == P()
    assert tp_param_spec((), mesh) == P()
    # no tp axis on the mesh -> not a tp placement at all
    dp_mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
    assert tp_param_spec((16, 8), dp_mesh) is None


@pytest.mark.multichip
def test_batch_shard_extent_counts_data_axes_only():
    import jax

    devs = jax.devices()[:8]
    assert batch_shard_extent(None) == 1
    assert batch_shard_extent(make_mesh({"dp": 8}, devices=devs)) == 8
    # fsdp is a DATA axis: batch shards over dp x fsdp
    assert batch_shard_extent(
        make_mesh({"dp": 2, "fsdp": 4}, devices=devs)) == 8
    # tp is a MODEL axis: it splits params, never rows
    assert batch_shard_extent(
        make_mesh({"dp": 4, "tp": 2}, devices=devs)) == 4


@pytest.mark.multichip
def test_bucket_ladder_rounds_to_mesh_extent():
    import jax

    devs = jax.devices()[:8]
    tp_mesh = make_mesh({"dp": 4, "tp": 2}, devices=devs)
    # rungs round to dp=4 (the batch-sharding extent), NOT the
    # 8-device group size — a tp mesh must not inflate the min rung
    assert bucket_ladder(16, mesh=tp_mesh) == bucket_ladder(16, dp=4)
    for r in bucket_ladder(16, mesh=tp_mesh):
        assert r % 4 == 0
    fsdp_mesh = make_mesh({"dp": 2, "fsdp": 4}, devices=devs)
    assert bucket_ladder(16, mesh=fsdp_mesh) == bucket_ladder(16, dp=8)


# ---------------------------------------------------------------------------
# the (dp, tp) server
# ---------------------------------------------------------------------------

@pytest.mark.multichip
def test_tp_server_refuses_indivisible_group():
    mod = _bound_module()
    with pytest.raises(MXNetError, match="does not divide"):
        InferenceServer(mod, tp=3, max_wait_ms=1.0, slo_ms=0.0)


@pytest.mark.multichip
def test_tp_halves_per_device_param_bytes():
    srv = _server(tp=2)
    try:
        # every param's largest dim divides by 2 (HID=16, CLASSES=4,
        # BATCH=8), so device 0 holds exactly half the pack
        dev0, total = _dev0_bytes(srv._fused)
        assert total > 0
        assert dev0 * 2 == total
    finally:
        srv.close()
    rep = _server(tp=1)
    try:
        dev0, total = _dev0_bytes(rep._fused)
        assert dev0 == total   # replicated baseline: the whole pack
    finally:
        rep.close()


@pytest.mark.multichip
def test_tp_parity_bit_identical():
    """tp=2 must serve the same bits as the dp-replicated server: in
    the exact-arithmetic regime the in-graph all-reduce adds exactly
    representable partial sums, so ``==`` holds, not ``allclose``."""
    X = _rows(6)
    srv1 = _server(tp=1)
    try:
        ref = [np.asarray(srv1.infer([X[i:i + 1]])[0]) for i in
               range(len(X))]
    finally:
        srv1.close()
    srv2 = _server(tp=2)
    try:
        assert srv2.tp == 2 and srv2.dp == 4
        for i in range(len(X)):
            (out,) = srv2.infer([X[i:i + 1]])
            assert np.array_equal(np.asarray(out), ref[i]), \
                "tp=2 diverged from tp=1 on row %d" % i
    finally:
        srv2.close()


@pytest.mark.multichip
def test_tp_env_knob_one_dispatch_zero_retrace(tel, monkeypatch):
    """With MXNET_TPU_SERVE_TP=2 armed via the env knob: every rung
    warmed once, then steady state never recompiles and every served
    batch is exactly one XLA dispatch (the collectives ride inside)."""
    monkeypatch.setenv("MXNET_TPU_SERVE_TP", "2")
    srv = _server(tp=None)
    try:
        assert srv.tp == 2
        for b in srv.buckets:
            srv._fused([np.zeros((b, DIM), np.float32)])
        compiles = srv.compiles
        assert compiles <= len(srv.buckets)
        rc0 = tel.peek("infer.recompiles") or 0
        di0 = tel.peek("infer.dispatches") or 0
        ba0 = tel.peek("serve.batches") or 0
        X = _rows(10)
        for i in range(len(X)):
            srv.infer([X[i:i + 1]])
        rc1 = tel.peek("infer.recompiles") or 0
        di1 = tel.peek("infer.dispatches") or 0
        ba1 = tel.peek("serve.batches") or 0
        assert rc1 == rc0, "steady state retraced under tp"
        assert srv.compiles == compiles
        batches = ba1 - ba0
        assert batches > 0
        assert di1 - di0 == batches   # exactly 1.0 dispatches/batch
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# placement-aware executable caches
# ---------------------------------------------------------------------------

@pytest.mark.multichip
def test_leaf_signature_keys_on_sharding():
    """Two arrays with identical shape/dtype but different placements
    must produce different xprof leaf signatures — the AOT cache would
    otherwise serve an executable compiled for the wrong layout."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()[:8]
    mesh = make_mesh({"dp": 4, "tp": 2}, devices=devs)
    host = np.zeros((16, 8), np.float32)
    a = jax.device_put(host, NamedSharding(mesh, P("tp", None)))
    b = jax.device_put(host, NamedSharding(mesh, P()))
    sig_a = xprof.leaf_signature([a])
    sig_b = xprof.leaf_signature([b])
    assert sig_a != sig_b
    # same placement -> same signature (no retrace churn)
    a2 = jax.device_put(host, NamedSharding(mesh, P("tp", None)))
    assert xprof.leaf_signature([a2]) == sig_a


@pytest.mark.multichip
def test_fused_infer_stale_for_mesh_factoring():
    import jax

    from mxnet_tpu.fused_step import make_fused_infer

    devs = jax.devices()[:8]
    mod = _bound_module()
    ex = mod._exec_group.executor
    dp_mesh = make_mesh({"dp": 8}, devices=devs)
    tp_mesh = make_mesh({"dp": 4, "tp": 2}, devices=devs)
    fused = make_fused_infer(ex, ["data"], mesh=dp_mesh)
    assert not fused.stale_for(ex, dp_mesh)
    # a re-bind across mesh factorings must MISS the cache
    assert fused.stale_for(ex, tp_mesh)
    assert fused.stale_for(ex, None)
    # a different executor always misses, same mesh or not
    mod2 = _bound_module()
    assert fused.stale_for(mod2._exec_group.executor, dp_mesh)


@pytest.mark.multichip
def test_server_rebuilds_executable_on_rebind(tel):
    """A module re-bound after the server was built serves through a
    REBUILT executable, not the stale one compiled for the old
    executor (serve.executable_rebuilds counts it)."""
    srv = _server(tp=2)
    try:
        old_fused = srv._fused
        # re-bind the module: new executor, same shapes
        srv._module.bind(data_shapes=[("data", (BATCH, DIM))],
                         label_shapes=[("softmax_label", (BATCH,))],
                         for_training=False, force_rebind=True)
        srv._module.init_params(
            initializer=None,
            arg_params=_seed_params(_mlp(), BATCH), aux_params={},
            force_init=True)
        rb0 = tel.peek("serve.executable_rebuilds") or 0
        srv.refresh_params()
        assert srv._fused is not old_fused
        assert (tel.peek("serve.executable_rebuilds") or 0) == rb0 + 1
        # the scheduler was re-pointed: serving still works
        (out,) = srv.infer([_rows(1)])
        assert np.asarray(out).shape == (1, CLASSES)
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# delta-aware weight streaming
# ---------------------------------------------------------------------------

def _host_pack(mod, scale_name=None, scale=2.0):
    args, _ = mod.get_params()
    host = {n: np.asarray(a.asnumpy()) for n, a in args.items()}
    if scale_name is not None:
        host[scale_name] = (host[scale_name] * scale).astype(np.float32)
    return host


@pytest.mark.multichip
def test_delta_refresh_equivalent_to_full_repack(tel):
    """Streaming one changed param through the delta path must serve
    the same bits as a full set_params + re-pack — and move only that
    param's bytes."""
    X = _rows(4)
    # full-repack arm: new weights via set_params + refresh_params()
    srv_full = _server(tp=2)
    try:
        host2 = _host_pack(srv_full._module, scale_name="fc1_weight")
        srv_full._module.set_params(
            {n: mx.nd.array(v) for n, v in host2.items()}, {},
            force_init=True)
        srv_full.refresh_params()
        ref = [np.asarray(srv_full.infer([X[i:i + 1]])[0])
               for i in range(len(X))]
    finally:
        srv_full.close()
    # delta arm: seed the resident digests, then stream the one change
    srv = _server(tp=2)
    try:
        fused = srv._fused
        host = _host_pack(srv._module)
        digests = {n: param_digest(v) for n, v in host.items()}
        srv.refresh_params(host_params=host, digests=digests)
        assert fused.last_refresh_changed == len(host)   # seeding pass
        host2 = dict(host)
        host2["fc1_weight"] = (host["fc1_weight"] * 2.0).astype(
            np.float32)
        digests2 = dict(digests)
        digests2["fc1_weight"] = param_digest(host2["fc1_weight"])
        by0 = tel.peek("infer.refresh_bytes") or 0
        srv.refresh_params(host_params=host2, digests=digests2)
        assert fused.last_refresh_changed == 1
        assert fused.last_refresh_skipped == len(host) - 1
        assert fused.last_refresh_bytes == host2["fc1_weight"].nbytes
        assert (tel.peek("infer.refresh_bytes") or 0) - by0 \
            == host2["fc1_weight"].nbytes
        for i in range(len(X)):
            (out,) = srv.infer([X[i:i + 1]])
            assert np.array_equal(np.asarray(out), ref[i]), \
                "delta-refreshed server diverged on row %d" % i
        # an identical re-send moves nothing at all
        srv.refresh_params(host_params=host2, digests=digests2)
        assert fused.last_refresh_changed == 0
        assert fused.last_refresh_bytes == 0
    finally:
        srv.close()


@pytest.mark.multichip
def test_delta_refresh_env_bypass(tel, monkeypatch):
    """MXNET_TPU_REFRESH_DELTA=0: the diff is bypassed and every
    refresh moves the full pack (the escape hatch when digests are
    suspect)."""
    monkeypatch.setenv("MXNET_TPU_REFRESH_DELTA", "0")
    srv = _server(tp=2)
    try:
        fused = srv._fused
        host = _host_pack(srv._module)
        digests = {n: param_digest(v) for n, v in host.items()}
        srv.refresh_params(host_params=host, digests=digests)
        srv.refresh_params(host_params=host, digests=digests)
        assert fused.last_refresh_skipped == 0
        assert fused.last_refresh_changed == len(host)
    finally:
        srv.close()


@pytest.mark.multichip
def test_refresh_from_snapshot_roundtrip(tmp_path):
    """The serve-while-training rollout path end to end: a snapshot
    payload (with the manifest's param_digests) saved to a
    SnapshotStore streams into a live tp server via the fleet helper,
    and the served bits match the snapshot's weights."""
    srv = _server(tp=2)
    try:
        host2 = _host_pack(srv._module, scale_name="fc2_weight")
        payload = {"format": 1, "params": host2,
                   "param_digests": {n: param_digest(v)
                                     for n, v in host2.items()},
                   "step": 7}
        store = SnapshotStore(str(tmp_path))
        store.save(payload, reason="test")
        entry = store._read_manifest()["snapshots"][-1]
        assert entry["param_digests"] == payload["param_digests"]

        X = _rows(3)
        before = [np.asarray(srv.infer([X[i:i + 1]])[0])
                  for i in range(len(X))]
        fleet._refresh_from_store(srv, str(tmp_path))
        assert srv._fused.last_refresh_changed > 0
        after = [np.asarray(srv.infer([X[i:i + 1]])[0])
                 for i in range(len(X))]
        assert not all(np.array_equal(a, b)
                       for a, b in zip(before, after))
        # equivalence: a server built directly on the new weights
        ref_srv = _server(tp=2)
        try:
            ref_srv._module.set_params(
                {n: mx.nd.array(v) for n, v in host2.items()}, {},
                force_init=True)
            ref_srv.refresh_params()
            for i in range(len(X)):
                (out,) = ref_srv.infer([X[i:i + 1]])
                assert np.array_equal(np.asarray(out), after[i])
        finally:
            ref_srv.close()
    finally:
        srv.close()


def _tp_server_factory():
    return _server(tp=0, n_dev=1)


@pytest.mark.multichip
def test_rolling_snapshot_swap_pure_under_torn_swap(tel, no_faults,
                                                    tmp_path):
    """The delta-streamed rolling swap keeps the fleet's purity
    contract with torn_swap ARMED: weights ship via the snapshot
    store (the only path subprocess/socket replicas accept), each
    replica drains, delta-refreshes and rejoins — every response is
    pure-old or pure-new, zero failed."""
    faults.configure("torn_swap", slow_ms=30.0)
    router = FleetRouter(fleet.in_process(_tp_server_factory), 2,
                         deadline_ms=30000.0, attempt_timeout_ms=5000.0,
                         retries=10, backoff_ms=2.0,
                         health_interval_s=60.0)
    try:
        x = _rows(1, seed=55)
        (old,) = router.infer([x])

        # the shipped snapshot: doubled fc1 weights + manifest digests
        ref = fleet.InProcReplica("ref", _tp_server_factory)
        try:
            host2 = _host_pack(ref._srv._module, scale_name="fc1_weight")
            payload = {"format": 1, "params": host2,
                       "param_digests": {n: param_digest(v)
                                         for n, v in host2.items()}}
            SnapshotStore(str(tmp_path)).save(payload, reason="test")
            ref._srv.refresh_from_snapshot(payload)
            (new,) = ref.submit([x]).wait(30)
        finally:
            ref.close()
        assert not np.array_equal(old, new)

        stop = threading.Event()
        outs, errs = [], []

        def load():
            i = 0
            while not stop.is_set():
                try:
                    (out,) = router.infer(
                        [x], request_id="tpswap-%d" % i)
                    outs.append(out)
                except Exception as e:   # noqa: BLE001 (collected)
                    errs.append(e)
                i += 1

        threads = [threading.Thread(target=load, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        router.refresh_params(snapshot_dir=str(tmp_path),
                              drain_timeout_s=30.0)
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(30)

        assert not errs, errs[:3]               # zero failed responses
        n_old = sum(np.array_equal(o, old) for o in outs)
        n_new = sum(np.array_equal(o, new) for o in outs)
        assert n_old + n_new == len(outs), \
            "mixed-version responses served: %d of %d" \
            % (len(outs) - n_old - n_new, len(outs))
        assert n_old > 0 and n_new > 0          # load straddled the swap
        plan = faults._PLAN
        assert plan is not None
        assert plan.injected.get("torn_swap", 0) >= 2   # window existed
        st = router.stats()
        assert st["counters"]["param_swaps"] == 2
    finally:
        router.close()
        faults.configure(None)
