"""KVStore tests (reference tests/python/unittest/test_kvstore.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore

SHAPE = (4, 4)
KEYS = [5, 7, 11]


def _check(kv_type):
    kv = kvstore.create(kv_type)
    kv.init(3, mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(SHAPE))

    # push single
    kv.push(3, mx.nd.ones(SHAPE) * 8)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 8.0))

    # aggregation across "devices" (reference: 4 GPUs -> sum)
    num_devs = 4
    vals = [mx.nd.ones(SHAPE, ctx=mx.cpu(i % 4)) for i in range(num_devs)]
    kv.push(3, vals)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 4.0))


@pytest.mark.parametrize("kv_type", ["local", "device", "tpu_sync"])
def test_kvstore_single_key(kv_type):
    _check(kv_type)


def test_kvstore_list_keys():
    kv = kvstore.create("local")
    kv.init(KEYS, [mx.nd.ones(SHAPE)] * len(KEYS))
    vals = [[mx.nd.ones(SHAPE) * 2] * 3] * len(KEYS)
    kv.push(KEYS, vals)
    outs = [mx.nd.zeros(SHAPE) for _ in KEYS]
    kv.pull(KEYS, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), np.full(SHAPE, 6.0))


def test_kvstore_updater():
    kv = kvstore.create("local")
    kv.init(3, mx.nd.ones(SHAPE))

    def updater(key, recv, stored):
        stored += recv * 2
    kv.set_updater(updater)
    kv.push(3, [mx.nd.ones(SHAPE)] * 4)
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 9.0))


def test_kvstore_optimizer():
    from mxnet_tpu import optimizer as opt

    kv = kvstore.create("local")
    kv.init(0, mx.nd.ones(SHAPE))
    kv.set_optimizer(opt.SGD(learning_rate=0.1, rescale_grad=1.0))
    kv.push(0, mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(SHAPE, 0.9), rtol=1e-6)


def test_kvstore_rank():
    kv = kvstore.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1


def test_kvstore_aggregation_exact():
    """Exact arithmetic of push/pull (reference
    tests/nightly/dist_sync_kvstore.py:14-40 single-process analogue)."""
    kv = kvstore.create("tpu_sync")
    kv.init(9, mx.nd.zeros((2, 3)))
    for i in range(1, 5):
        kv.push(9, [mx.nd.ones((2, 3)) * i])
    out = mx.nd.zeros((2, 3))
    kv.pull(9, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 4.0))


def test_reduce_tree_sum_matches_pairwise():
    """The jitted balanced tree reduce must agree with a host sum for
    any fan-in (odd counts exercise the carry leg)."""
    rng = np.random.RandomState(5)
    kv = kvstore.create("local")
    for n in (2, 3, 5, 8):
        arrs = [rng.randn(4, 3).astype(np.float32) for _ in range(n)]
        merged = kv._reduce([mx.nd.array(a) for a in arrs])
        np.testing.assert_allclose(merged.asnumpy(), sum(arrs), rtol=1e-6)


def test_reduce_single_dispatch(monkeypatch):
    """Fan-in N must cost ONE fused-reduce call, not N-1 eager adds."""
    from mxnet_tpu import telemetry

    telemetry.reset()
    telemetry.enable()
    kv = kvstore.create("local")
    kv._reduce([mx.nd.ones((2, 2)) for _ in range(6)])
    assert telemetry.peek("kvstore.fused_reduce") == 1
    telemetry.reset()
    telemetry.disable()
