"""Evidence hygiene for MFU experiment recording (round-6 satellite):
physically impossible measurements (mfu > 100%, step time below the
analytic FLOP floor) must be refused at record time and retro-tagged in
existing artifacts — a broken synchronization fence must never read as
a performance result."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools"))

from mfu_experiments import (RESNET50_TRAIN_GFLOPS_PER_IMG, retag,
                             validate)


def _row(**over):
    row = {"experiment": "baseline", "imgs_per_sec": 1000.0,
           "step_time_ms": 256.0, "batch": 256, "image": 224,
           "compute_dtype": "bfloat16", "chip": "TPU v5 lite",
           "xla_flags": "", "mfu_pct": 50.0}
    row.update(over)
    return row


def test_validate_accepts_plausible_row():
    assert validate(_row()) is None


def test_validate_rejects_impossible_mfu():
    reason = validate(_row(mfu_pct=1095.3))
    assert reason and "mfu_pct" in reason


def test_validate_rejects_step_below_analytic_floor():
    # batch 256 at ~394 peak TFLOPS: floor ~= 256*12.267/394 ~= 8 ms;
    # 1.46 ms (the real 2026-07-31 garbage) is impossible even without
    # an mfu_pct field on the row
    reason = validate(_row(step_time_ms=1.46, mfu_pct=None))
    assert reason and "floor" in reason


def test_validate_skips_floor_for_unknown_chip():
    # no peak known -> the floor cannot be computed; only the mfu bound
    # applies
    assert validate(_row(chip="mystery accelerator",
                         step_time_ms=0.01, mfu_pct=None)) is None


def test_validate_skips_floor_for_small_images():
    # the analytic constant is the 224x224 ResNet-50 cost; CPU smoke
    # runs at 32x32 are not comparable
    assert validate(_row(image=32, step_time_ms=0.01,
                         mfu_pct=None)) is None


def test_retag_tags_only_invalid_untagged_rows(tmp_path):
    path = tmp_path / "mfu.jsonl"
    rows = [
        _row(),                                     # plausible: untouched
        _row(mfu_pct=411.5),                        # garbage: tag
        dict(_row(mfu_pct=999.0), valid=False,
             invalid_reason="already tagged"),      # tagged: untouched
        _row(step_time_ms=1.46, mfu_pct=None),      # floor garbage: tag
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    assert retag(str(path)) == 2
    out = [json.loads(l) for l in open(path)]
    assert "valid" not in out[0]
    assert out[1]["valid"] is False and "mfu_pct" in out[1]["invalid_reason"]
    assert out[2]["invalid_reason"] == "already tagged"
    assert out[3]["valid"] is False and "floor" in out[3]["invalid_reason"]
    # idempotent
    assert retag(str(path)) == 0


def test_repo_artifact_has_no_untagged_impossible_rows():
    """The acceptance bar itself: MFU_EXPERIMENTS.jsonl contains no
    untagged mfu_pct > 100 rows."""
    path = os.path.join(REPO, "MFU_EXPERIMENTS.jsonl")
    if not os.path.exists(path):
        pytest.skip("no MFU_EXPERIMENTS.jsonl")
    for line in open(path):
        if not line.strip():
            continue
        row = json.loads(line)
        if row.get("mfu_pct", 0) and row["mfu_pct"] > 100:
            assert row.get("valid") is False, \
                "untagged impossible row: %s" % line


def test_main_refuses_to_print_invalid_rows(monkeypatch, capsys):
    """stdout is the .jsonl destination (chip_watch appends it): an
    invalid measurement must go to stderr only."""
    import mfu_experiments as mfu

    def fake_measure(variant, batch, image, num_classes, steps, dtype):
        r = _row(experiment=variant, mfu_pct=500.0)
        r["valid"] = False
        r["invalid_reason"] = "mfu_pct 500.0 exceeds 100% of chip peak"
        return r

    monkeypatch.setattr(mfu, "measure", fake_measure)
    mfu.main(["--variant", "baseline"])
    cap = capsys.readouterr()
    assert cap.out.strip() == ""
    assert "REFUSING" in cap.err


def test_chip_watch_scrubs_jsonl_stdout():
    import chip_watch

    good = json.dumps(_row())
    bad = json.dumps(_row(mfu_pct=700.0))
    tagged = json.dumps(dict(_row(mfu_pct=700.0), valid=False,
                             invalid_reason="x"))
    text = "\n".join([good, bad, tagged]) + "\n"
    out = chip_watch._scrub_jsonl(text)
    lines = [l for l in out.splitlines() if l.strip()]
    assert good in lines
    assert bad not in lines
    assert tagged in lines
