"""FeedForward multi-context behavior (VERDICT weak #4): the legacy
estimator API over several devices must match single-device training —
the reference's multi_lenet.py near-identical-weights contract — and the
executor_manager compat layer must drive training."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.executor_manager import (DataParallelExecutorManager,
                                        _split_input_slice)


def _task(n=192, d=6, seed=0):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 2, n).astype(np.float32)
    X = (rng.randn(n, d).astype(np.float32) * 0.5 + y[:, None])
    return X, y


def _net():
    data = sym.Variable("data")
    net = sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = sym.Activation(data=net, act_type="relu")
    net = sym.FullyConnected(data=net, num_hidden=2, name="fc2")
    return sym.SoftmaxOutput(data=net, name="softmax")


def _train(ctx, X, y, epochs=5):
    mx.random.seed(0)   # deterministic init for cross-run equivalence
    np.random.seed(0)   # NDArrayIter shuffles via the global numpy RNG
    model = mx.model.FeedForward.create(
        _net(), X=X, y=y, ctx=ctx, num_epoch=epochs, learning_rate=0.2,
        numpy_batch_size=32, initializer=mx.init.Uniform(0.07))
    return model


def test_feedforward_multi_context_trains():
    X, y = _task()
    model = _train([mx.cpu(0), mx.cpu(1)], X, y)
    pred = model.predict(X)
    acc = (pred.argmax(axis=1) == y).mean()
    assert acc > 0.95, acc


def test_feedforward_multi_vs_single_context_equivalence():
    """Synchronous DP over 2 devices must produce the same weights as
    one device seeing the full batch (grads are summed either way)."""
    X, y = _task()
    m1 = _train(mx.cpu(), X, y, epochs=3)
    m2 = _train([mx.cpu(0), mx.cpu(1)], X, y, epochs=3)
    a1, _ = m1.arg_params, m1.aux_params
    a2, _ = m2.arg_params, m2.aux_params
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_feedforward_four_contexts_predict_consistency():
    X, y = _task()
    model = _train([mx.cpu(i) for i in range(4)], X, y)
    p4 = model.predict(X)
    # prediction through a single-device rebind matches
    model2 = mx.model.FeedForward(_net(), ctx=mx.cpu(),
                                  arg_params=model.arg_params,
                                  aux_params=model.aux_params)
    p1 = model2.predict(X)
    np.testing.assert_allclose(p4, p1, rtol=1e-5, atol=1e-6)


def test_split_input_slice():
    slices = _split_input_slice(10, [1.0, 1.0])
    assert slices == [slice(0, 5), slice(5, 10)]
    slices = _split_input_slice(9, [2.0, 1.0])
    assert slices[0] == slice(0, 6) and slices[1] == slice(6, 9)
    total = sum(s.stop - s.start for s in _split_input_slice(7, [1, 1, 1]))
    assert total == 7


def test_executor_manager_training_loop():
    """The reference-era training loop over DataParallelExecutorManager:
    install params, forward/backward, update via grad arrays."""
    X, y = _task(n=64)
    it = mx.io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    net = _net()
    arg_names = net.list_arguments()
    param_names = [n for n in arg_names
                   if n not in ("data", "softmax_label")]
    mgr = DataParallelExecutorManager(net, [mx.cpu(0), mx.cpu(1)], it,
                                      arg_names=arg_names,
                                      param_names=param_names,
                                      aux_names=net.list_auxiliary_states())
    rng = np.random.RandomState(1)
    arg_params = {}
    arg_shapes, _, _ = net.infer_shape(data=(16, 6))
    for n_, s_ in zip(arg_names, arg_shapes):
        if n_ in param_names:
            arg_params[n_] = mx.nd.array(
                (rng.randn(*s_) * 0.1).astype(np.float32))
    mgr.set_params(arg_params, {})

    for epoch in range(4):
        it.reset()
        for batch in it:
            mgr.load_data_batch(batch)
            mgr.forward(is_train=True)
            mgr.backward()
            for name, block, grads in zip(mgr.param_names, mgr.param_arrays,
                                          mgr.grad_arrays):
                for w, g in zip(block, grads):
                    w[:] = w.asnumpy() - 0.05 * g.asnumpy()
    assert mgr.curr_execgrp is mgr.execgrp
    it.reset()
    correct = total = 0
    for batch in it:
        mgr.load_data_batch(batch)
        mgr.forward(is_train=False)
        outs = mgr.get_outputs()
        pred = outs[0].asnumpy()
        correct += (pred.argmax(axis=1) ==
                    batch.label[0].asnumpy()).sum()
        total += pred.shape[0]
    assert correct / total > 0.9, correct / total
