"""Perl frontend over the C ABI (perl-package/): proves the binding
surface is sufficient for a non-Python frontend — the reference's
R-package story (R code over .Call stubs into c_api.cc). The test
trains + checkpoints a model in Python, then a Perl script loads the
checkpoint, runs inference, and performs one SGD step; outputs and the
post-step loss drop are validated against Python."""
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _build():
    if not shutil.which("perl") or not shutil.which("xsubpp"):
        pytest.skip("no perl/xsubpp toolchain")
    r = subprocess.run(["make", "-C", REPO, "perl"], capture_output=True,
                       text=True)
    if r.returncode != 0:
        pytest.skip("perl extension build failed: %s" % r.stderr[-500:])


def test_perl_loads_checkpoint_infers_and_trains(tmp_path):
    _build()

    # train a small net in Python and checkpoint it
    rng = np.random.RandomState(3)
    X = rng.randn(32, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    model = mx.model.FeedForward(net, num_epoch=3, learning_rate=0.1,
                                 numpy_batch_size=32)
    model.fit(it)
    prefix = str(tmp_path / "m")
    model.save(prefix, 3)

    np.savetxt(tmp_path / "d.csv", X, delimiter=",")
    np.savetxt(tmp_path / "l.csv", y, delimiter=",")

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        ["perl", os.path.join(REPO, "perl-package", "examples",
                              "train_step.pl"),
         prefix + "-symbol.json", "%s-%04d.params" % (prefix, 3),
         str(tmp_path / "d.csv"), str(tmp_path / "l.csv"), "0.001"],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = dict(line.split("=", 1) for line in r.stdout.strip().splitlines())

    # inference agrees with Python
    probs_perl = np.array([float(v) for v in out["probs"].split(",")])
    pred = model.predict(mx.io.NDArrayIter(X, batch_size=32))
    np.testing.assert_allclose(probs_perl, pred.ravel()[:6], rtol=1e-4,
                               atol=1e-5)

    # the Perl-side SGD step reduced the loss
    assert float(out["loss_after"]) < float(out["loss_before"])


def test_perl_error_path(tmp_path):
    _build()
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        ["perl", "-I", os.path.join(REPO, "perl-package", "lib"),
         "-I", os.path.join(REPO, "perl-package", "blib"),
         "-MMXNetTPU",
         "-e", 'MXNetTPU::Symbol->load_json("{bad"); print "no\\n"'],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode != 0
    assert "MXSymbolCreateFromJSON failed" in r.stderr
