"""Perl frontend over the C ABI (perl-package/): proves the binding
surface is sufficient for a non-Python frontend — the reference's
R-package story (R code over .Call stubs into c_api.cc). The test
trains + checkpoints a model in Python, then a Perl script loads the
checkpoint, runs inference, and performs one SGD step; outputs and the
post-step loss drop are validated against Python."""
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _build():
    if not shutil.which("perl") or not shutil.which("xsubpp"):
        pytest.skip("no perl/xsubpp toolchain")
    r = subprocess.run(["make", "-C", REPO, "perl"], capture_output=True,
                       text=True)
    if r.returncode != 0:
        pytest.skip("perl extension build failed: %s" % r.stderr[-500:])


def test_perl_loads_checkpoint_infers_and_trains(tmp_path):
    _build()

    # train a small net in Python and checkpoint it
    rng = np.random.RandomState(3)
    X = rng.randn(32, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    model = mx.model.FeedForward(net, num_epoch=3, learning_rate=0.1,
                                 numpy_batch_size=32)
    model.fit(it)
    prefix = str(tmp_path / "m")
    model.save(prefix, 3)

    np.savetxt(tmp_path / "d.csv", X, delimiter=",")
    np.savetxt(tmp_path / "l.csv", y, delimiter=",")

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        ["perl", os.path.join(REPO, "perl-package", "examples",
                              "train_step.pl"),
         prefix + "-symbol.json", "%s-%04d.params" % (prefix, 3),
         str(tmp_path / "d.csv"), str(tmp_path / "l.csv"), "0.001"],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = dict(line.split("=", 1) for line in r.stdout.strip().splitlines())

    # inference agrees with Python
    probs_perl = np.array([float(v) for v in out["probs"].split(",")])
    pred = model.predict(mx.io.NDArrayIter(X, batch_size=32))
    np.testing.assert_allclose(probs_perl, pred.ravel()[:6], rtol=1e-4,
                               atol=1e-5)

    # the Perl-side SGD step reduced the loss
    assert float(out["loss_after"]) < float(out["loss_before"])


def test_perl_error_path(tmp_path):
    _build()
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(
        ["perl", "-I", os.path.join(REPO, "perl-package", "lib"),
         "-I", os.path.join(REPO, "perl-package", "blib"),
         "-MMXNetTPU",
         "-e", 'MXNetTPU::Symbol->load_json("{bad"); print "no\\n"'],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode != 0
    assert "MXSymbolCreateFromJSON failed" in r.stderr


def test_perl_round2_surface(tmp_path):
    """The round-2 XS functions: symbol save/load-from-file, grad,
    optimizer create/update (momentum math checked numerically),
    random_seed, and the odd-kv-count croak."""
    _build()
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=2, no_bias=True, name="fc")
    json_path = tmp_path / "net.json"
    script = tmp_path / "round2.pl"
    script.write_text(r"""
use strict; use warnings;
use lib "%(lib)s", "%(blib)s"; use MXNetTPU;
MXNetTPU::random_seed(11);

my $sym = MXNetTPU::Symbol->load_json(do {
    local $/; open my $fh, '<', $ARGV[0] or die; <$fh> });
$sym->save("%(tmp)s/resaved.json");
my $back = MXNetTPU::Symbol->load("%(tmp)s/resaved.json");
print "args=", join(",", $back->list_arguments), "\n";

my $g = $sym->grad("fc_weight");
print "gargs=", join(",", $g->list_arguments), "\n";

# optimizer: sgd with momentum on a 4-element weight, grad all 0.5
my $w = MXNetTPU::NDArray->from_list([1, 1, 1, 1]);
my $grad = MXNetTPU::NDArray->from_list([0.5, 0.5, 0.5, 0.5]);
my $opt = MXNetTPU::Optimizer->create("sgd", momentum => "0.9");
$opt->update(0, $w->{handle}, $grad->{handle}, 0.1, 0.0);
$opt->update(0, $w->{handle}, $grad->{handle}, 0.1, 0.0);
print "w=", join(",", $w->values), "\n";

my $died = eval { MXNetTPU::optimizer_create("sgd", "momentum"); 1 } ? 0 : 1;
print "odd_kv_croaks=$died\n";
""" % {"lib": os.path.join(REPO, "perl-package", "lib"),
       "blib": os.path.join(REPO, "perl-package", "blib"),
       "tmp": str(tmp_path)})
    json_path.write_text(net.tojson())

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run(["perl", str(script), str(json_path)],
                       capture_output=True, text=True, env=env,
                       timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    out = dict(line.split("=", 1)
               for line in r.stdout.strip().splitlines())
    assert out["args"] == "data,fc_weight"
    assert out["gargs"] == "data,fc_weight"
    # two momentum-SGD steps: w1 = 1 - .05; mom2 = .9*(-.05) - .05
    np.testing.assert_allclose(
        [float(v) for v in out["w"].split(",")],
        np.full(4, 1.0 - 0.05 + (0.9 * -0.05 - 0.05)), rtol=1e-5)
    assert out["odd_kv_croaks"] == "1"
