"""Type inference (reference StaticGraph::InferNodeTypes,
src/symbol/static_graph.cc:160-213): dtype seeds propagate through per-op
infer_type rules to every argument/output/aux at fixpoint."""
import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_default_float32():
    net = _mlp()
    arg_types, out_types, aux_types = net.infer_type()
    assert all(t == np.float32 for t in arg_types)
    assert all(t == np.float32 for t in out_types)


def test_fp16_seed_propagates_to_weights():
    net = _mlp()
    arg_types, out_types, _ = net.infer_type(data=np.float16)
    types = dict(zip(net.list_arguments(), arg_types))
    assert types["fc1_weight"] == np.float16
    assert types["fc1_bias"] == np.float16
    assert types["fc2_weight"] == np.float16
    assert types["softmax_label"] == np.float16
    assert out_types[0] == np.float16


def test_fp64_positional():
    net = _mlp()
    arg_types, _, _ = net.infer_type(np.float64)
    assert arg_types[0] == np.float64
    assert all(t == np.float64 for t in arg_types)


def test_cast_boundary():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    h = mx.sym.Cast(h, dtype="float16")
    h = mx.sym.FullyConnected(h, num_hidden=4, name="fc2")
    arg_types, out_types, _ = h.infer_type(data=np.float32)
    types = dict(zip(h.list_arguments(), arg_types))
    # weights before the cast are f32, after are f16
    assert types["fc1_weight"] == np.float32
    assert types["fc2_weight"] == np.float16
    assert out_types[0] == np.float16


def test_batchnorm_aux_stays_f32():
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(data=data, name="bn")
    arg_types, _, aux_types = net.infer_type(data=np.float16)
    types = dict(zip(net.list_arguments(), arg_types))
    assert types["bn_gamma"] == np.float16
    # moving stats accumulate in f32 regardless of data dtype
    assert all(t == np.float32 for t in aux_types)


def test_unknown_argument_errors():
    net = _mlp()
    try:
        net.infer_type(bogus=np.float32)
    except mx.base.MXNetError as e:
        assert "bogus" in str(e)
    else:
        raise AssertionError("expected MXNetError")


def test_fp64_single_op():
    # regression: None-vs-dtype comparison must not treat an unknown slot
    # as float64 (np.dtype(None) is float64)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4)
    arg_types, out_types, _ = net.infer_type(data=np.float64)
    assert all(t == np.float64 for t in arg_types)
    assert out_types[0] == np.float64


def test_seeded_dtype_conflict_raises():
    # regression: an explicitly-given dtype must never be silently
    # overwritten by propagation (reference InferNodeTypes errors too)
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = mx.sym.Variable("c")
    net = (a * b) + (a * c)
    try:
        net.infer_type(b=np.float16, c=np.float64)
    except mx.base.MXNetError:
        pass
    else:
        raise AssertionError("expected dtype-conflict MXNetError")


def test_late_seed_propagates():
    # regression: speculative float32 defaults must not pre-empt a seed on
    # a variable that appears late in topo order
    xs = [mx.sym.Variable("x%d" % i) for i in range(5)]
    net = xs[0]
    for x in xs[1:]:
        net = net * x
    arg_types, out_types, _ = net.infer_type(x4=np.float16)
    assert all(t == np.float16 for t in arg_types)
    assert out_types[0] == np.float16


def test_embedding_weight_follows_downstream():
    # regression: Embedding must not speculatively pin weight to f32 —
    # a downstream fp16 seed types the weight through backward propagation
    data = mx.sym.Variable("data")
    w2 = mx.sym.Variable("w2")
    net = mx.sym.Embedding(data, input_dim=10, output_dim=4,
                           name="emb") * w2
    arg_types, _, _ = net.infer_type(w2=np.float16, data=np.int32)
    types = dict(zip(net.list_arguments(), arg_types))
    assert types["emb_weight"] == np.float16
    assert types["data"] == np.int32


def test_none_kwarg_means_unknown():
    # regression: None dtype kwarg must not become np.dtype(None)==float64
    net = _mlp()
    arg_types, _, _ = net.infer_type(data=None)
    assert all(t == np.float32 for t in arg_types)


def test_producer_conflict_raises():
    # two producers disagreeing is an error, not a flap (reference
    # InferNodeTypes raises on mismatch)
    x = mx.sym.Variable("x")
    y = mx.sym.Variable("y")
    net = mx.sym.Cast(x, dtype="float16") + mx.sym.Cast(y, dtype="float32")
    try:
        net.infer_type()
    except mx.base.MXNetError:
        pass
    else:
        raise AssertionError("expected dtype-conflict MXNetError")


def test_simple_bind_allocates_inferred_dtypes():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), type_dict={"data": np.float16},
                         data=(4, 10))
    assert ex.arg_dict["data"].dtype == np.float16
    assert ex.arg_dict["fc1_weight"].dtype == np.float16
    assert ex.grad_dict["fc1_weight"].dtype == np.float16
