"""caffe_converter tool: prototxt text parsing and symbol conversion.

Reference analogue: tools/caffe_converter/convert_symbol.py (prototxt
NetParameter → mx.symbol script). Here conversion is direct to Symbol.
"""
import os
import sys

import numpy as np

import mxnet_tpu as mx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import caffe_converter  # noqa: E402

LENET = """
name: "LeNet"
input: "data"
input_dim: 2
input_dim: 1
input_dim: 28
input_dim: 28
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 1 }
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "relu1"
  type: "ReLU"
  bottom: "pool1"
  top: "pool1"
}
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool1"
  top: "ip1"
  inner_product_param { num_output: 10 }
}
layer {
  name: "loss"
  type: "SoftmaxWithLoss"
  bottom: "ip1"
  bottom: "label"
}
"""


def test_parse_prototxt_basic():
    msg = caffe_converter.parse_prototxt(LENET)
    assert msg["name"] == "LeNet"
    assert msg["input"] == "data"
    assert msg["input_dim"] == [2, 1, 28, 28]
    layers = msg["layer"]
    assert len(layers) == 5
    assert layers[0]["convolution_param"]["num_output"] == 8
    assert layers[1]["pooling_param"]["pool"] == "MAX"


def test_convert_lenet_forward():
    sym, input_shape = caffe_converter.convert_symbol(LENET)
    assert input_shape == (2, 1, 28, 28)
    arg_shapes, out_shapes, _ = sym.infer_shape(data=input_shape)
    assert out_shapes[0] == (2, 10)
    # executes end to end
    exe = sym.simple_bind(ctx=mx.cpu(), data=input_shape)
    exe.forward(is_train=False,
                data=np.random.rand(*input_shape).astype(np.float32))
    out = exe.outputs[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(2), rtol=1e-5)


def test_convert_v1_and_eltwise(tmp_path):
    proto = """
    input: "data"
    input_dim: 1 input_dim: 4 input_dim: 8 input_dim: 8
    layers { name: "c1" type: CONVOLUTION bottom: "data" top: "c1"
             convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
    layers { name: "sum" type: ELTWISE bottom: "data" bottom: "c1"
             top: "sum" eltwise_param { operation: SUM } }
    layers { name: "bn" type: BATCHNORM bottom: "sum" top: "bn" }
    layers { name: "sc" type: SCALE bottom: "bn" top: "bn" }
    layers { name: "sm" type: SOFTMAX_LOSS bottom: "bn" }
    """
    sym, shape = caffe_converter.convert_symbol(proto)
    assert shape == (1, 4, 8, 8)
    arg_shapes, out_shapes, aux = sym.infer_shape(data=shape)
    assert out_shapes[0] == shape  # softmax over channel of same shape
    # CLI writes loadable symbol json
    pp = tmp_path / "net.prototxt"
    pp.write_text(proto)
    out = caffe_converter.main([str(pp), str(tmp_path / "net")])
    loaded = mx.sym.load(out)
    assert loaded.list_arguments() == sym.list_arguments()


def test_pair_field_forms():
    # caffe's three geometry spellings: scalar, repeated, kernel_h/kernel_w
    assert caffe_converter._pair({"kernel_size": 3}, "kernel_size", 1) == \
        (3, 3)
    assert caffe_converter._pair({"kernel_size": [3, 5]}, "kernel_size",
                                 1) == (3, 5)
    assert caffe_converter._pair({"kernel_h": 4, "kernel_w": 2},
                                 "kernel_size", 1) == (4, 2)
    assert caffe_converter._pair({"stride_h": 2, "stride_w": 1},
                                 "stride", 1) == (2, 1)


def test_pooling_kernel_h_w_and_eltwise_coeff():
    proto = """
    input: "data"
    input_dim: 1 input_dim: 1 input_dim: 9 input_dim: 8
    layer { name: "p" type: "Pooling" bottom: "data" top: "p"
            pooling_param { pool: MAX kernel_h: 4 kernel_w: 2
                            stride_h: 1 stride_w: 2 } }
    """
    sym, shape = caffe_converter.convert_symbol(proto)
    _, out_shapes, _ = sym.infer_shape(data=shape)
    assert out_shapes[0] == (1, 1, 6, 4)

    # Eltwise with coeff 1,-1 = subtraction
    proto2 = """
    input: "data"
    input_dim: 1 input_dim: 2 input_dim: 4 input_dim: 4
    layer { name: "d" type: "Eltwise" bottom: "data" bottom: "data"
            top: "d" eltwise_param { operation: SUM coeff: 1 coeff: -1 } }
    """
    sym2, shape2 = caffe_converter.convert_symbol(proto2)
    exe = sym2.simple_bind(ctx=mx.cpu(), data=shape2)
    exe.forward(is_train=False,
                data=np.random.rand(*shape2).astype(np.float32))
    np.testing.assert_allclose(exe.outputs[0].asnumpy(),
                               np.zeros(shape2), atol=1e-6)


def test_parser_and_pool_errors():
    import pytest
    with pytest.raises(ValueError, match="truncated"):
        caffe_converter.parse_prototxt("name")
    with pytest.raises(ValueError, match="truncated"):
        caffe_converter.parse_prototxt("name:")
    proto = """
    input: "data"
    input_dim: 1 input_dim: 1 input_dim: 4 input_dim: 4
    layer { name: "p" type: "Pooling" bottom: "data" top: "p"
            pooling_param { pool: STOCHASTIC kernel_size: 2 } }
    """
    with pytest.raises(ValueError, match="pool type"):
        caffe_converter.convert_symbol(proto)
