"""Predictor (c_predict_api equivalent) + tools tests."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models
from mxnet_tpu.predictor import Predictor

REPO = os.path.join(os.path.dirname(__file__), "..")


def test_predictor_roundtrip(tmp_path):
    net = models.get_mlp(num_classes=5)
    prefix = str(tmp_path / "m")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind([("data", (4, 20))], [("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.save_checkpoint(prefix, 0)

    with open(prefix + "-symbol.json") as f:
        sym_json = f.read()
    pred = Predictor(sym_json, prefix + "-0000.params",
                     {"data": (4, 20), "softmax_label": (4,)})
    x = np.random.randn(4, 20).astype(np.float32)
    pred.forward(data=x)
    out = pred.get_output(0)
    assert out.shape == (4, 5)

    # must match the module's own prediction
    batch = mx.io.DataBatch([mx.nd.array(x)], [mx.nd.zeros((4,))])
    mod.forward(batch, is_train=False)
    np.testing.assert_allclose(out, mod.get_outputs()[0].asnumpy(),
                               rtol=1e-5)


def test_parse_log(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO:root:Epoch[0] Train-accuracy=0.5\n"
        "INFO:root:Epoch[0] Time cost=1.25\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.6\n"
        "INFO:root:Epoch[1] Train-accuracy=0.9\n"
        "INFO:root:Epoch[1] Validation-accuracy=0.92\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(log), "--metric", "val-accuracy"],
        capture_output=True, text=True)
    assert out.returncode == 0
    lines = out.stdout.strip().splitlines()
    assert lines == ["0\t0.6", "1\t0.92"]


def test_im2rec_and_iter(tmp_path):
    pytest.importorskip("PIL")
    from PIL import Image

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    rng = np.random.RandomState(0)
    lst = []
    for i in range(6):
        arr = (rng.rand(20, 24, 3) * 255).astype(np.uint8)
        Image.fromarray(arr).save(str(img_dir / ("%d.jpg" % i)))
        lst.append("%d\t%d\t%d.jpg" % (i, i % 3, i))
    lst_file = tmp_path / "imgs.lst"
    lst_file.write_text("\n".join(lst) + "\n")
    prefix = str(tmp_path / "packed")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "im2rec.py"),
         prefix, str(img_dir), "--list", str(lst_file), "--resize", "16"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert os.path.exists(prefix + ".rec")
    assert os.path.exists(prefix + ".idx")
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 14, 14), batch_size=3)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (3, 3, 14, 14)


def test_launch_local(tmp_path):
    # workers write per-rank files (stdout interleaves across processes)
    script = tmp_path / "worker.py"
    script.write_text(
        "import os\n"
        "rank = os.environ['MXTPU_WORKER_RANK']\n"
        "n = os.environ['MXTPU_NUM_WORKERS']\n"
        "open(os.path.join(%r, 'out_' + rank), 'w').write(rank + '/' + n)\n"
        % str(tmp_path))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "3", sys.executable, str(script)],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    for r in range(3):
        assert (tmp_path / ("out_%d" % r)).read_text() == "%d/3" % r


def test_bandwidth_tool():
    # in-process: conftest already forced the 8-device CPU platform
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bandwidth
    finally:
        sys.path.pop(0)
    res = bandwidth.main(["--num-mb", "0.5", "--iters", "2", "--test",
                          "both"])
    assert len(res) == 2
    assert res[0]["devices"] == 8
    assert res[0]["bus_gb_s"] > 0
    assert res[1]["bus_gb_s"] > 0
