"""Test configuration: force an 8-device CPU platform so multi-device
sharding paths run without TPU hardware (the reference's analogue: CPU-only
multi-device tests like tests/python/unittest/test_multi_device_exec.py)."""
import os

# force CPU: the session may default to a TPU platform (axon), but tests run
# on the virtual 8-device CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# full-precision matmuls/convs so finite-difference gradient checks are tight
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

# parameter-server frame auth is default-on (the server refuses to start
# without a secret); the suite runs authenticated end to end, like every
# launch.py job. Worker subprocesses inherit this env.
os.environ.setdefault("MXTPU_PS_SECRET", "test-suite-token")

# the axon TPU site hook overrides JAX_PLATFORMS at import; force cpu via
# config too
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# modules exercising the fused one-dispatch step run with the transfer
# sanitizer armed: jax.transfer_guard("disallow") around every fit's
# step loop, so an implicit host<->device transfer regression in the
# fused path fails these suites at the batch that caused it (see
# docs/static_analysis.md)
_TRANSFER_SANITIZED = {"test_fused_step", "test_fused_feed"}


@pytest.fixture(autouse=True)
def _arm_transfer_sanitizer(request, monkeypatch):
    if request.module.__name__.rpartition(".")[2] in _TRANSFER_SANITIZED \
            and "MXNET_TPU_SANITIZE" not in os.environ:
        monkeypatch.setenv("MXNET_TPU_SANITIZE", "transfer")
    yield
