"""Test configuration: force an 8-device CPU platform so multi-device
sharding paths run without TPU hardware (the reference's analogue: CPU-only
multi-device tests like tests/python/unittest/test_multi_device_exec.py)."""
import multiprocessing
import os
import time

# force CPU: the session may default to a TPU platform (axon), but tests run
# on the virtual 8-device CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# full-precision matmuls/convs so finite-difference gradient checks are tight
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

# parameter-server frame auth is default-on (the server refuses to start
# without a secret); the suite runs authenticated end to end, like every
# launch.py job. Worker subprocesses inherit this env.
os.environ.setdefault("MXTPU_PS_SECRET", "test-suite-token")

# the axon TPU site hook overrides JAX_PLATFORMS at import; force cpu via
# config too
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# modules exercising the fused one-dispatch step run with the transfer
# sanitizer armed: jax.transfer_guard("disallow") around every fit's
# step loop, so an implicit host<->device transfer regression in the
# fused path fails these suites at the batch that caused it (see
# docs/static_analysis.md)
_TRANSFER_SANITIZED = {"test_fused_step", "test_fused_feed",
                       "test_sharded_fused", "test_checkpoint",
                       "test_numwatch", "test_fsdp"}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "multichip: needs the forced 8-device cpu mesh (skipped when the "
        "backend refused --xla_force_host_platform_device_count)")


def pytest_collection_modifyitems(config, items):
    if len(jax.devices()) >= 8:
        return
    skip = pytest.mark.skip(
        reason="backend refused the forced 8-device cpu platform")
    for item in items:
        if "multichip" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _arm_transfer_sanitizer(request, monkeypatch):
    if request.module.__name__.rpartition(".")[2] in _TRANSFER_SANITIZED \
            and "MXNET_TPU_SANITIZE" not in os.environ:
        monkeypatch.setenv("MXNET_TPU_SANITIZE", "transfer")
    yield


@pytest.fixture(autouse=True)
def _no_thread_or_process_leaks(request):
    """Every test must clean up after itself on the concurrency plane:
    no new non-daemon threads and no live child processes may survive a
    test (graftrace's runtime counterpart — a leaked thread here is
    exactly the lifecycle hazard the static rules flag). Daemon threads
    (engine/feed workers live process-long by design) are exempt; brief
    stragglers get a join grace before we call them a leak."""
    import threading

    before = {t.ident for t in threading.enumerate()}
    yield
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t for t in threading.enumerate()
                  if t.ident not in before and t.is_alive()
                  and not t.daemon]
        if not leaked:
            break
        for t in leaked:
            t.join(timeout=0.2)
    else:
        pytest.fail("test leaked non-daemon thread(s): %s"
                    % ", ".join(t.name for t in leaked))
    procs = [p for p in multiprocessing.active_children() if p.is_alive()]
    for p in procs:
        p.join(timeout=5.0)
    procs = [p for p in procs if p.is_alive()]
    assert not procs, ("test leaked child process(es): %s"
                       % ", ".join("%s(pid=%s)" % (p.name, p.pid)
                                   for p in procs))
    # profiler sessions are process-global singletons in jax: one left
    # open poisons every later capture attempt with "already active"
    import sys as _sys

    prof = _sys.modules.get("mxnet_tpu.profiler")
    if prof is not None and prof.is_running():
        try:
            prof.stop()
        except Exception:
            pass
        pytest.fail("test left a profiler trace session open "
                    "(call profiler.stop() or use the context manager)")
