"""Registry/iterator/kvstore/recordio tiers of the C ABI (reference
src/c_api/c_api.cc:366-445 function registry, :447-937 symbol registry,
:1110-1197 data iterators, :1199-1338 kvstore) driven through ctypes,
plus the headline check: a standalone C program that builds a symbol
from the registry and trains with a kvstore whose updater is C code —
no Python-side graph construction."""
import ctypes
import os
import shutil
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LIB = os.path.join(REPO, "mxnet_tpu", "_native", "libmxtpu_predict.so")


def _lib():
    if not shutil.which("make"):
        pytest.skip("no make toolchain")
    r = subprocess.run(["make", "-C", REPO, "predict"], capture_output=True,
                       text=True)
    if r.returncode != 0 or not os.path.exists(LIB):
        pytest.skip("c api build failed: %s" % r.stderr[-500:])
    lib = ctypes.CDLL(LIB)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def _fptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def test_atomic_symbol_registry_enumeration():
    lib = _lib()
    n = ctypes.c_uint32()
    creators = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXSymbolListAtomicSymbolCreators(
        ctypes.byref(n), ctypes.byref(creators)) == 0, lib.MXGetLastError()
    assert n.value > 40  # the op zoo

    names = set()
    for i in range(n.value):
        cname = ctypes.c_char_p()
        assert lib.MXSymbolGetAtomicSymbolName(
            creators[i], ctypes.byref(cname)) == 0
        names.add(cname.value.decode())
    for want in ("Convolution", "FullyConnected", "BatchNorm", "RNN",
                 "SoftmaxOutput", "Pooling"):
        assert want in names, want

    # docstring plumbing for Convolution params
    for i in range(n.value):
        cname = ctypes.c_char_p()
        lib.MXSymbolGetAtomicSymbolName(creators[i], ctypes.byref(cname))
        if cname.value == b"Convolution":
            name = ctypes.c_char_p()
            desc = ctypes.c_char_p()
            nargs = ctypes.c_uint32()
            anames = ctypes.POINTER(ctypes.c_char_p)()
            atypes = ctypes.POINTER(ctypes.c_char_p)()
            adescs = ctypes.POINTER(ctypes.c_char_p)()
            kv = ctypes.c_char_p()
            assert lib.MXSymbolGetAtomicSymbolInfo(
                creators[i], ctypes.byref(name), ctypes.byref(desc),
                ctypes.byref(nargs), ctypes.byref(anames),
                ctypes.byref(atypes), ctypes.byref(adescs),
                ctypes.byref(kv)) == 0
            params = [anames[j].decode() for j in range(nargs.value)]
            assert "kernel" in params and "num_filter" in params
            types = [atypes[j].decode() for j in range(nargs.value)]
            assert any("required" in t for t in types)
            break


def test_compose_and_infer_type_from_c():
    lib = _lib()
    n = ctypes.c_uint32()
    creators = ctypes.POINTER(ctypes.c_void_p)()
    lib.MXSymbolListAtomicSymbolCreators(ctypes.byref(n),
                                         ctypes.byref(creators))
    fc = None
    for i in range(n.value):
        cname = ctypes.c_char_p()
        lib.MXSymbolGetAtomicSymbolName(creators[i], ctypes.byref(cname))
        if cname.value == b"FullyConnected":
            fc = creators[i]
            break

    data = ctypes.c_void_p()
    assert lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    sym = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"num_hidden")
    vals = (ctypes.c_char_p * 1)(b"8")
    assert lib.MXSymbolCreateAtomicSymbol(ctypes.c_void_p(fc), 1, keys, vals,
                                          ctypes.byref(sym)) == 0
    args = (ctypes.c_void_p * 1)(data)
    assert lib.MXSymbolCompose(sym, b"fc1", 1, None, args) == 0, \
        lib.MXGetLastError()

    nargs = ctypes.c_uint32()
    anames = ctypes.POINTER(ctypes.c_char_p)()
    assert lib.MXSymbolListArguments(sym, ctypes.byref(nargs),
                                     ctypes.byref(anames)) == 0
    got = [anames[i].decode() for i in range(nargs.value)]
    assert got == ["data", "fc1_weight", "fc1_bias"]

    # infer fp16 through the C dtype-id surface (2 == float16)
    tkeys = (ctypes.c_char_p * 1)(b"data")
    tvals = (ctypes.c_int * 1)(2)
    in_n = ctypes.c_uint32()
    out_n = ctypes.c_uint32()
    aux_n = ctypes.c_uint32()
    in_t = ctypes.POINTER(ctypes.c_int)()
    out_t = ctypes.POINTER(ctypes.c_int)()
    aux_t = ctypes.POINTER(ctypes.c_int)()
    assert lib.MXSymbolInferType(
        sym, 1, tkeys, tvals, ctypes.byref(in_n), ctypes.byref(in_t),
        ctypes.byref(out_n), ctypes.byref(out_t), ctypes.byref(aux_n),
        ctypes.byref(aux_t)) == 0, lib.MXGetLastError()
    assert [in_t[i] for i in range(in_n.value)] == [2, 2, 2]
    assert out_t[0] == 2

    # attributes
    assert lib.MXSymbolSetAttr(sym, b"ctx_group", b"dev1") == 0
    out = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert lib.MXSymbolGetAttr(sym, b"ctx_group", ctypes.byref(out),
                               ctypes.byref(ok)) == 0
    assert ok.value == 1 and out.value == b"dev1"

    lib.MXSymbolFree(sym)
    lib.MXSymbolFree(data)


def test_func_registry_invoke():
    lib = _lib()
    n = ctypes.c_uint32()
    funcs = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXListFunctions(ctypes.byref(n), ctypes.byref(funcs)) == 0
    assert n.value >= 10

    h = ctypes.c_void_p()
    assert lib.MXGetFunction(b"_plus", ctypes.byref(h)) == 0
    nu = ctypes.c_uint32()
    ns = ctypes.c_uint32()
    nm = ctypes.c_uint32()
    mask = ctypes.c_int()
    assert lib.MXFuncDescribe(h, ctypes.byref(nu), ctypes.byref(ns),
                              ctypes.byref(nm), ctypes.byref(mask)) == 0
    assert (nu.value, ns.value, nm.value) == (2, 0, 1)

    def make(vals):
        a = ctypes.c_void_p()
        shape = (ctypes.c_uint32 * 1)(4)
        assert lib.MXNDArrayCreate(shape, 1, 1, 0, ctypes.byref(a)) == 0
        arr = np.asarray(vals, dtype=np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(a, _fptr(arr), 4) == 0
        return a

    a = make([1, 2, 3, 4])
    b = make([10, 20, 30, 40])
    out = make([0, 0, 0, 0])
    use = (ctypes.c_void_p * 2)(a, b)
    mut = (ctypes.c_void_p * 1)(out)
    assert lib.MXFuncInvoke(h, use, None, mut) == 0, lib.MXGetLastError()
    res = np.zeros(4, dtype=np.float32)
    assert lib.MXNDArraySyncCopyToCPU(out, _fptr(res), 4) == 0
    np.testing.assert_array_equal(res, [11, 22, 33, 44])

    # scalar function
    assert lib.MXGetFunction(b"_mul_scalar", ctypes.byref(h)) == 0
    scal = (ctypes.c_float * 1)(2.5)
    use1 = (ctypes.c_void_p * 1)(a)
    assert lib.MXFuncInvoke(h, use1, scal, mut) == 0
    assert lib.MXNDArraySyncCopyToCPU(out, _fptr(res), 4) == 0
    np.testing.assert_array_equal(res, [2.5, 5, 7.5, 10])

    for x in (a, b, out):
        lib.MXNDArrayFree(x)


def test_data_iter_from_c(tmp_path):
    lib = _lib()
    n = ctypes.c_uint32()
    iters = ctypes.POINTER(ctypes.c_void_p)()
    assert lib.MXListDataIters(ctypes.byref(n), ctypes.byref(iters)) == 0
    names = {}
    for i in range(n.value):
        cname = ctypes.c_char_p()
        desc = ctypes.c_char_p()
        assert lib.MXDataIterGetIterInfo(iters[i], ctypes.byref(cname),
                                         ctypes.byref(desc)) == 0
        names[cname.value.decode()] = iters[i]
    assert {"CSVIter", "MNISTIter", "NDArrayIter",
            "ImageRecordIter"} <= set(names)

    data = np.arange(24, dtype=np.float32).reshape(8, 3)
    label = np.arange(8, dtype=np.float32)
    dcsv = tmp_path / "d.csv"
    lcsv = tmp_path / "l.csv"
    np.savetxt(dcsv, data, delimiter=",")
    np.savetxt(lcsv, label, delimiter=",")

    keys = (ctypes.c_char_p * 4)(b"data_csv", b"data_shape", b"label_csv",
                                 b"batch_size")
    vals = (ctypes.c_char_p * 4)(str(dcsv).encode(), b"(3,)",
                                 str(lcsv).encode(), b"4")
    it = ctypes.c_void_p()
    assert lib.MXDataIterCreateIter(ctypes.c_void_p(names["CSVIter"]), 4,
                                    keys, vals, ctypes.byref(it)) == 0, \
        lib.MXGetLastError()

    seen = []
    more = ctypes.c_int()
    assert lib.MXDataIterBeforeFirst(it) == 0
    assert lib.MXDataIterNext(it, ctypes.byref(more)) == 0
    while more.value:
        xa = ctypes.c_void_p()
        assert lib.MXDataIterGetData(it, ctypes.byref(xa)) == 0
        buf = np.zeros(12, dtype=np.float32)
        assert lib.MXNDArraySyncCopyToCPU(xa, _fptr(buf), 12) == 0
        seen.append(buf.copy())
        pad = ctypes.c_int()
        assert lib.MXDataIterGetPadNum(it, ctypes.byref(pad)) == 0
        assert pad.value == 0
        assert lib.MXDataIterNext(it, ctypes.byref(more)) == 0
    assert len(seen) == 2
    np.testing.assert_array_equal(np.concatenate(seen).reshape(8, 3), data)
    assert lib.MXDataIterFree(it) == 0


def test_kvstore_from_c_with_c_updater():
    lib = _lib()
    kv = ctypes.c_void_p()
    assert lib.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0

    t = ctypes.c_char_p()
    assert lib.MXKVStoreGetType(kv, ctypes.byref(t)) == 0
    assert t.value == b"local"
    rank = ctypes.c_int()
    size = ctypes.c_int()
    assert lib.MXKVStoreGetRank(kv, ctypes.byref(rank)) == 0
    assert lib.MXKVStoreGetGroupSize(kv, ctypes.byref(size)) == 0
    assert (rank.value, size.value) == (0, 1)
    dead = ctypes.c_int()
    assert lib.MXKVStoreGetNumDeadNode(kv, 0, ctypes.byref(dead)) == 0
    assert dead.value == 0
    assert lib.MXKVStoreBarrier(kv) == 0

    # C updater: local -= 0.5 * recv (via the ctypes callback bridge,
    # the same path a real C function pointer takes)
    UPDATER = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_void_p, ctypes.c_void_p)
    calls = []

    @UPDATER
    def upd(key, recv, local, handle):
        calls.append(key)
        buf = np.zeros(4, dtype=np.float32)
        lib.MXNDArraySyncCopyToCPU(ctypes.c_void_p(local), _fptr(buf), 4)
        g = np.zeros(4, dtype=np.float32)
        lib.MXNDArraySyncCopyToCPU(ctypes.c_void_p(recv), _fptr(g), 4)
        buf -= 0.5 * g
        lib.MXNDArraySyncCopyFromCPU(ctypes.c_void_p(local), _fptr(buf), 4)

    assert lib.MXKVStoreSetUpdater(
        kv, ctypes.cast(upd, ctypes.c_void_p), None) == 0, \
        lib.MXGetLastError()

    def make(vals):
        a = ctypes.c_void_p()
        shape = (ctypes.c_uint32 * 1)(4)
        assert lib.MXNDArrayCreate(shape, 1, 1, 0, ctypes.byref(a)) == 0
        arr = np.asarray(vals, dtype=np.float32)
        assert lib.MXNDArraySyncCopyFromCPU(a, _fptr(arr), 4) == 0
        return a

    w = make([1, 1, 1, 1])
    g = make([2, 2, 2, 2])
    key = (ctypes.c_int * 1)(3)
    vals = (ctypes.c_void_p * 1)(w)
    assert lib.MXKVStoreInit(kv, 1, key, vals) == 0, lib.MXGetLastError()
    gvals = (ctypes.c_void_p * 1)(g)
    assert lib.MXKVStorePush(kv, 1, key, gvals, 0) == 0, lib.MXGetLastError()
    out = make([0, 0, 0, 0])
    ovals = (ctypes.c_void_p * 1)(out)
    assert lib.MXKVStorePull(kv, 1, key, ovals, 0) == 0
    res = np.zeros(4, dtype=np.float32)
    assert lib.MXNDArraySyncCopyToCPU(out, _fptr(res), 4) == 0
    np.testing.assert_allclose(res, np.zeros(4))  # 1 - 0.5*2
    assert calls == [3]

    for x in (w, g, out):
        lib.MXNDArrayFree(x)
    assert lib.MXKVStoreFree(kv) == 0


def test_recordio_from_c(tmp_path):
    lib = _lib()
    path = str(tmp_path / "x.rec").encode()
    wr = ctypes.c_void_p()
    assert lib.MXRecordIOWriterCreate(path, ctypes.byref(wr)) == 0
    recs = [b"hello", b"world" * 100, b""]
    for r in recs:
        assert lib.MXRecordIOWriterWriteRecord(wr, r, len(r)) == 0
    assert lib.MXRecordIOWriterFree(wr) == 0

    rd = ctypes.c_void_p()
    assert lib.MXRecordIOReaderCreate(path, ctypes.byref(rd)) == 0
    got = []
    while True:
        buf = ctypes.c_char_p()
        size = ctypes.c_size_t()
        assert lib.MXRecordIOReaderReadRecord(rd, ctypes.byref(buf),
                                              ctypes.byref(size)) == 0
        if size.value == 0:
            break
        got.append(ctypes.string_at(buf, size.value))
    assert lib.MXRecordIOReaderFree(rd) == 0
    assert got == [r for r in recs if r]


def test_ndarray_extras():
    lib = _lib()
    # dtype-aware create (7 == bfloat16, 2 == float16)
    h = ctypes.c_void_p()
    shape = (ctypes.c_uint32 * 2)(4, 6)
    assert lib.MXNDArrayCreateEx(shape, 2, 1, 0, 2, ctypes.byref(h)) == 0
    dt = ctypes.c_int()
    assert lib.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0
    assert dt.value == 2
    devt = ctypes.c_int()
    devi = ctypes.c_int()
    assert lib.MXNDArrayGetContext(h, ctypes.byref(devt),
                                   ctypes.byref(devi)) == 0
    assert devt.value == 1

    out = ctypes.c_void_p()
    assert lib.MXNDArraySlice(h, 1, 3, ctypes.byref(out)) == 0
    ndim = ctypes.c_uint32()
    pdata = ctypes.POINTER(ctypes.c_uint32)()
    assert lib.MXNDArrayGetShape(out, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    assert tuple(pdata[i] for i in range(ndim.value)) == (2, 6)
    lib.MXNDArrayFree(out)

    dims = (ctypes.c_int * 2)(6, 4)
    assert lib.MXNDArrayReshape(h, 2, dims, ctypes.byref(out)) == 0
    assert lib.MXNDArrayGetShape(out, ctypes.byref(ndim),
                                 ctypes.byref(pdata)) == 0
    assert tuple(pdata[i] for i in range(ndim.value)) == (6, 4)
    lib.MXNDArrayFree(out)
    lib.MXNDArrayFree(h)


def test_standalone_c_training_program(tmp_path):
    """The VERDICT criterion: a C program builds a symbol from the
    registry, iterates a registered CSVIter, and trains via kvstore with
    a C SGD updater — no Python graph construction anywhere."""
    _lib()
    if not shutil.which("gcc"):
        pytest.skip("no gcc")

    rng = np.random.RandomState(0)
    X = rng.randn(256, 5).astype(np.float32)
    w_true = rng.randn(5)
    y = (X @ w_true > 0).astype(np.float32)
    dcsv = tmp_path / "data.csv"
    lcsv = tmp_path / "label.csv"
    np.savetxt(dcsv, X, delimiter=",")
    np.savetxt(lcsv, y, delimiter=",")

    src = os.path.join(os.path.dirname(__file__), "c_train_host.c")
    exe = tmp_path / "c_train_host"
    r = subprocess.run(
        ["gcc", src, "-o", str(exe), "-I", os.path.join(REPO, "include"),
         "-L", os.path.dirname(LIB), "-lmxtpu_predict",
         "-Wl,-rpath," + os.path.dirname(LIB)],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # pure-CPU child (see
    # test_c_predict_api.py: a dead accelerator tunnel must not hang it)
    r = subprocess.run([str(exe), str(dcsv), str(lcsv)],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    acc = float(r.stdout.strip().split("final_acc=")[1])
    assert acc >= 0.9, r.stdout
