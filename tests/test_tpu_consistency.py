"""Accelerator-vs-CPU consistency tier (reference
tests/python/gpu/test_operator_gpu.py): runs tools/tpu_consistency.py in
a subprocess on the default (accelerator) platform; skips when only CPU
is available OR when the accelerator tunnel is wedged (a half-alive
tunnel blocks on first dispatch — same guard as bench.py). The conftest
forces this pytest process itself onto the virtual CPU mesh, so the
sweep must run out-of-process."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _accelerator_alive(env, timeout_s=60):
    """Probe: EXECUTE a computation (device enumeration alone can succeed
    on a wedged tunnel)."""
    probe = ("import jax, jax.numpy as jnp; "
             "v=float(jax.jit(lambda x:(x*2).sum())(jnp.ones(8))); "
             "print('PLATFORM', jax.devices()[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", probe],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PLATFORM cpu" not in r.stdout


def test_tpu_vs_cpu_operator_consistency():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the accelerator platform load
    if not _accelerator_alive(env):
        pytest.skip("no live accelerator platform (absent or wedged)")
    try:
        r = subprocess.run(
            [sys.executable, "-u",
             os.path.join(REPO, "tools", "tpu_consistency.py")],
            capture_output=True, text=True, timeout=1500, env=env)
    except subprocess.TimeoutExpired:
        pytest.skip("accelerator wedged mid-sweep")
    out = r.stdout + r.stderr
    if r.returncode == 2 or "skipped: no accelerator" in out:
        pytest.skip("no accelerator platform reachable")
    assert r.returncode == 0, out[-3000:]
    assert "fail=0" in out, out[-3000:]
