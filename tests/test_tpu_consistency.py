"""Accelerator-vs-CPU consistency tier (reference
tests/python/gpu/test_operator_gpu.py): runs tools/tpu_consistency.py in
a subprocess on the default (accelerator) platform; skips when only CPU
is available OR when the accelerator tunnel is wedged (a half-alive
tunnel blocks on first dispatch — same guard as bench.py). The conftest
forces this pytest process itself onto the virtual CPU mesh, so the
sweep must run out-of-process."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _accelerator_alive(env, timeout_s=60):
    """Probe via bench._accelerator_reachable: it EXECUTEs a computation
    (device enumeration alone can succeed on a wedged tunnel) and
    memoizes the verdict, so when an earlier accelerator-gated test in
    this pytest run already paid the dead-tunnel timeout we skip
    instantly instead of burning it again."""
    sys.path.insert(0, REPO)
    from bench import _accelerator_reachable

    return _accelerator_reachable(timeout_s=timeout_s)


def test_tpu_vs_cpu_operator_consistency():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the accelerator platform load
    if not _accelerator_alive(env):
        pytest.skip("no live accelerator platform (absent or wedged)")
    try:
        r = subprocess.run(
            [sys.executable, "-u",
             os.path.join(REPO, "tools", "tpu_consistency.py")],
            capture_output=True, text=True, timeout=1500, env=env)
    except subprocess.TimeoutExpired:
        pytest.skip("accelerator wedged mid-sweep")
    out = r.stdout + r.stderr
    if r.returncode == 2 or "skipped: no accelerator" in out:
        pytest.skip("no accelerator platform reachable")
    assert r.returncode == 0, out[-3000:]
    assert "fail=0" in out, out[-3000:]
