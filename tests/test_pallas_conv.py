"""Interpreter-mode parity for the Pallas conv-backward pair and the
fused norm+activation kernel vs the lax reference: forward AND vjp, f32
and bf16, stride-1 and stride-2 geometries, with the misaligned-shape
fallback and the BatchNorm wiring (gradient chain through the traced
batch statistics) pinned too."""
import numpy as np
import pytest

from mxnet_tpu.ops import pallas_kernels as pk

pytestmark = pytest.mark.skipif(not pk.pallas_available(),
                                reason="pallas unavailable")


def _jx():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


def _ref_conv(x, w, stride, pad):
    jax, jnp = _jx(), _jnp()
    return jax.lax.conv_general_dilated(
        x, w, window_strides=stride, padding=[(p, p) for p in pad],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.float32
        if x.dtype == jnp.float32 else None)


# 128-aligned geometries: N*H*W, C, O, KH*KW*O, KH*KW*C, N*HO*WO all
# tile (the conv_backward_applicable conditions)
GEOMS = [
    ((2, 128, 8, 8), (128, 128, 3, 3), (1, 1), (1, 1)),
    ((2, 128, 16, 16), (128, 128, 2, 2), (2, 2), (0, 0)),
]


@pytest.mark.parametrize("shape,wshape,stride,pad", GEOMS)
@pytest.mark.parametrize("dt", ["float32", "bfloat16"])
def test_conv2d_forward_and_vjp_parity(shape, wshape, stride, pad, dt):
    jax, jnp = _jx(), _jnp()
    dt = jnp.dtype(dt)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape), dt)
    w = jnp.asarray(rng.randn(*wshape) * 0.1, dt)
    out = pk.conv2d(x, w, stride=stride, pad=pad)
    assert out is not None, "kernel must apply to this geometry"
    ref = _ref_conv(x, w, stride, pad)
    f_rtol, f_atol = (2e-2, 1e-2) if dt == jnp.bfloat16 else (1e-5, 1e-4)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=f_rtol, atol=f_atol)

    g = jnp.asarray(rng.randn(*ref.shape), dt)

    def loss_p(x, w):
        return (pk.conv2d(x, w, stride=stride, pad=pad) * g).sum()

    def loss_r(x, w):
        return (_ref_conv(x, w, stride, pad) * g).sum()

    dxp, dwp = jax.grad(loss_p, (0, 1))(x, w)
    dxr, dwr = jax.grad(loss_r, (0, 1))(x, w)
    rtol, atol = (3e-2, 3e-1) if dt == jnp.bfloat16 else (1e-4, 1e-3)
    np.testing.assert_allclose(np.asarray(dxp, np.float32),
                               np.asarray(dxr, np.float32),
                               rtol=rtol, atol=atol)
    np.testing.assert_allclose(np.asarray(dwp, np.float32),
                               np.asarray(dwr, np.float32),
                               rtol=rtol, atol=atol)


def test_conv2d_bf16_compute_dtype_backward():
    """The bf16-operand / f32-accumulate path: casting the backward
    matmul operands must stay within bf16 tolerance of the f32 vjp."""
    jax, jnp = _jx(), _jnp()
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 128, 8, 8), jnp.float32)
    w = jnp.asarray(rng.randn(128, 128, 3, 3) * 0.1, jnp.float32)
    g = jnp.asarray(rng.randn(2, 128, 8, 8), jnp.float32)

    def loss(x, w):
        out = pk.conv2d(x, w, stride=(1, 1), pad=(1, 1),
                        compute_dtype=jnp.bfloat16)
        return (out * g).sum()

    def loss_r(x, w):
        return (_ref_conv(x, w, (1, 1), (1, 1)) * g).sum()

    dxp, dwp = jax.grad(loss, (0, 1))(x, w)
    dxr, dwr = jax.grad(loss_r, (0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(dxp), np.asarray(dxr),
                               rtol=3e-2, atol=3e-1)
    np.testing.assert_allclose(np.asarray(dwp), np.asarray(dwr),
                               rtol=3e-2, atol=3e-1)


def test_conv2d_fallback_on_misaligned_and_grouped():
    jnp = _jnp()
    # channel count 7: no tile covers it
    assert pk.conv2d(jnp.zeros((2, 7, 8, 8)), jnp.zeros((7, 7, 3, 3)),
                     stride=(1, 1), pad=(1, 1)) is None
    # grouped conv is out of scope by design
    assert pk.conv2d(jnp.zeros((2, 128, 8, 8)),
                     jnp.zeros((128, 64, 3, 3)),
                     stride=(1, 1), pad=(1, 1), num_group=2) is None
    # pad > k-1 breaks the dgrad pad inversion
    assert not pk.conv_backward_applicable(
        (2, 8, 8, 128), (128, 128, 3, 3), (1, 1), (3, 3), (1, 1), 1)
    # inexact stride: (8 + 0 - 3) % 2 != 0
    assert not pk.conv_backward_applicable(
        (2, 8, 8, 128), (128, 128, 3, 3), (2, 2), (0, 0), (1, 1), 1)


@pytest.mark.parametrize("dt", ["float32", "bfloat16"])
@pytest.mark.parametrize("act", ["none", "relu"])
def test_fused_norm_act_parity(dt, act):
    jax, jnp = _jx(), _jnp()
    dt = jnp.dtype(dt)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(256, 128), dt)
    sc = jnp.asarray(rng.randn(128) * 0.5 + 1.0, jnp.float32)
    sh = jnp.asarray(rng.randn(128) * 0.1, jnp.float32)

    def ref(x, sc, sh):
        y = x.astype(jnp.float32) * sc + sh
        if act == "relu":
            y = jnp.maximum(y, 0.0)
        return y.astype(x.dtype)

    out = pk.fused_norm_act(x, sc, sh, act=act)
    assert out is not None
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref(x, sc, sh), np.float32),
                               rtol=2e-2, atol=1e-2)

    g = jnp.asarray(rng.randn(256, 128), dt)

    def lp(x, sc, sh):
        return (pk.fused_norm_act(x, sc, sh, act=act) * g).sum()

    def lr(x, sc, sh):
        return (ref(x, sc, sh) * g).sum()

    gp = jax.grad(lp, (0, 1, 2))(x, sc, sh)
    gr = jax.grad(lr, (0, 1, 2))(x, sc, sh)
    atol = 3e-1 if dt == jnp.bfloat16 else 1e-3
    for a, b in zip(gp, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=3e-2, atol=atol)


def test_fused_norm_act_block_rows_is_semantics_free():
    """block_rows is the autotune knob: every legal value must produce
    bit-identical output, or the tuner would be changing numerics."""
    jnp = _jnp()
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(512, 128), jnp.float32)
    sc = jnp.asarray(rng.rand(128) + 0.5, jnp.float32)
    sh = jnp.asarray(rng.randn(128), jnp.float32)
    o1 = pk.fused_norm_act(x, sc, sh, act="relu", block_rows=128)
    o2 = pk.fused_norm_act(x, sc, sh, act="relu", block_rows=256)
    o3 = pk.fused_norm_act(x, sc, sh, act="relu", block_rows=512)
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o3))


def test_fused_norm_act_fallback():
    jnp = _jnp()
    # 100 rows don't tile 128
    assert pk.fused_norm_act(jnp.zeros((100, 128)), jnp.ones((128,)),
                             jnp.zeros((128,))) is None
    # unsupported activation
    assert pk.fused_norm_act(jnp.zeros((256, 128)), jnp.ones((128,)),
                             jnp.zeros((128,)), act="tanh") is None


def test_batchnorm_fused_path_parity(monkeypatch, tmp_path):
    """The ops/nn.py wiring: a channels-last BatchNorm with an autotune
    cache hit must produce the same forward and the same data/gamma/beta
    gradients (the scale/shift cotangents chain through the traced batch
    statistics) as the XLA elementwise path."""
    import mxnet_tpu as mx
    from mxnet_tpu import autotune
    from mxnet_tpu import symbol as sym

    rng = np.random.RandomState(0)
    x = rng.randn(2, 8, 8, 128).astype(np.float32)
    gamma = (rng.rand(128) + 0.5).astype(np.float32)
    beta = rng.randn(128).astype(np.float32)

    def run():
        s = sym.BatchNorm(sym.Variable("data"), axis=-1,
                          fix_gamma=False, name="bn")
        args = {"data": mx.nd.array(x), "bn_gamma": mx.nd.array(gamma),
                "bn_beta": mx.nd.array(beta)}
        grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()}
        aux = {"bn_moving_mean": mx.nd.zeros((128,)),
               "bn_moving_var": mx.nd.ones((128,))}
        ex = s.bind(mx.cpu(), args, args_grad=grads, grad_req="write",
                    aux_states=aux)
        ex.forward(is_train=True)
        ex.backward([mx.nd.ones(x.shape)])
        return (ex.outputs[0].asnumpy(),
                {k: g.asnumpy() for k, g in grads.items()})

    out_ref, g_ref = run()

    cachep = str(tmp_path / "cache.json")
    autotune.save_best("norm_act", {"block_rows": 128},
                       chip=autotune._chip_kind(), path=cachep)
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE", "1")
    monkeypatch.setattr(autotune, "CACHE_FILE", cachep)
    monkeypatch.setattr(autotune, "_cache_memo", None)
    assert autotune.norm_block_rows() == 128
    out_f, g_f = run()
    np.testing.assert_allclose(out_f, out_ref, rtol=1e-5, atol=1e-5)
    for k in g_ref:
        np.testing.assert_allclose(g_f[k], g_ref[k],
                                   rtol=1e-4, atol=1e-4, err_msg=k)
