"""Mixed precision (bf16 compute, f32 master weights) train step.

Reference analogue: fp16 training validated via check_consistency
(test_utils.py:588-640, gpu/cpu x fp16/32/64 tolerances). Here the TPU
idiom is bfloat16 activations/matmuls with float32 master weights,
BatchNorm statistics pinned to f32 (ops/nn.py BatchNorm).
"""
import numpy as np

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.parallel import build_sgd_train_step


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="c1")
    net = mx.sym.BatchNorm(data=net, name="bn1")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(data=net)
    net = mx.sym.FullyConnected(data=net, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def _setup(batch=16):
    import jax

    net = _net()
    shapes = {"data": (batch, 1, 8, 8)}
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    rng = np.random.RandomState(0)
    params = {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        if name.endswith("gamma"):
            params[name] = jnp.ones(shape, jnp.float32)
        else:
            params[name] = jnp.asarray(rng.randn(*shape) * 0.1, jnp.float32)
    aux = [jnp.ones(s, jnp.float32) if "var" in n
           else jnp.zeros(s, jnp.float32)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]
    y = rng.randint(0, 2, batch).astype(np.float32)
    x = (rng.randn(batch, 1, 8, 8) * 0.5
         + y[:, None, None, None]).astype(np.float32)
    data = {"data": jnp.asarray(x), "softmax_label": jnp.asarray(y)}
    key = jax.random.PRNGKey(0)
    return net, params, aux, data, y, key


def test_bf16_step_converges_and_keeps_f32_state():
    import jax

    net, params, aux, data, y, key = _setup()
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.1, compute_dtype=jnp.bfloat16)
    jstep = jax.jit(step)
    for i in range(30):
        outputs, params, aux = jstep(params, data, aux,
                                     jax.random.fold_in(key, i))
    # master weights and BN stats stayed f32
    assert all(p.dtype == jnp.float32 for p in params.values())
    assert all(a.dtype == jnp.float32 for a in aux)
    probs = np.asarray(outputs[0], dtype=np.float32)
    acc = (probs.argmax(axis=1) == y).mean()
    assert acc > 0.9, acc


def test_bf16_matches_f32_first_step():
    import jax

    net, params, aux, data, y, key = _setup()
    s32, _ = build_sgd_train_step(net, ["data"], ["softmax_label"], lr=0.1)
    s16, _ = build_sgd_train_step(net, ["data"], ["softmax_label"], lr=0.1,
                                  compute_dtype=jnp.bfloat16)
    o32, p32, _ = jax.jit(s32)(params, data, aux, key)
    o16, p16, _ = jax.jit(s16)(params, data, aux, key)
    # bf16 has ~3 decimal digits; outputs/updates agree loosely
    np.testing.assert_allclose(np.asarray(o16[0], np.float32),
                               np.asarray(o32[0]), atol=0.06)
    for n in p32:
        np.testing.assert_allclose(np.asarray(p16[n], np.float32),
                                   np.asarray(p32[n]), atol=0.12)


def test_executor_amp_env_var(monkeypatch):
    """MXNET_COMPUTE_DTYPE=bfloat16 turns on mixed precision for the
    whole Module/FeedForward path: bf16 compute, f32 params/grads/
    outputs, labels untouched."""
    monkeypatch.setenv("MXNET_COMPUTE_DTYPE", "bfloat16")
    rng = np.random.RandomState(0)
    n = 128
    y = rng.randint(0, 2, n).astype(np.float32)
    X = (rng.randn(n, 1, 8, 8) * 0.5
         + y[:, None, None, None]).astype(np.float32)
    net = _net()
    it = mx.io.NDArrayIter(X, y, batch_size=32, label_name="softmax_label")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=4, optimizer_params={"learning_rate": 0.1})
    score = dict(mod.score(mx.io.NDArrayIter(X, y, batch_size=32,
                                             label_name="softmax_label"),
                           "acc"))
    assert score["accuracy"] > 0.9, score
    args, _ = mod.get_params()
    assert all(a.asnumpy().dtype == np.float32 for a in args.values())


def test_executor_amp_kwarg_matches_f32_loosely():
    net = _net()
    shapes = {"data": (8, 1, 8, 8)}
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    rng = np.random.RandomState(1)
    args = {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        if name == "data":
            args[name] = mx.nd.array(rng.rand(*shape).astype(np.float32))
        elif name == "softmax_label":
            args[name] = mx.nd.array(
                rng.randint(0, 2, shape).astype(np.float32))
        elif name.endswith("gamma"):
            args[name] = mx.nd.ones(shape)
        else:
            args[name] = mx.nd.array(
                (rng.randn(*shape) * 0.1).astype(np.float32))
    aux = [mx.nd.ones(s) if "var" in n else mx.nd.zeros(s)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]
    from mxnet_tpu.executor import Executor

    e32 = Executor(net, mx.cpu(), dict(args), aux_states=list(aux),
                   grad_req="null")
    e16 = Executor(net, mx.cpu(), dict(args), aux_states=list(aux),
                   grad_req="null", compute_dtype="bfloat16")
    o32 = e32.forward(is_train=False)[0].asnumpy()
    o16 = e16.forward(is_train=False)[0].asnumpy()
    assert o16.dtype == np.float32          # outputs cast back
    np.testing.assert_allclose(o16, o32, atol=0.05)
    assert not np.array_equal(o16, o32)     # genuinely lower precision


def test_amp_explicit_label_names_and_off_switch(monkeypatch):
    import jax.numpy as jnp
    import pytest
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.executor import Executor

    # a label variable with a non-conventional name, 1000 classes
    data = mx.sym.Variable("data")
    tgt = mx.sym.Variable("target")
    net = mx.sym.FullyConnected(data=data, num_hidden=1000, name="fc")
    net = mx.sym.SoftmaxOutput(data=net, label=tgt, name="softmax")
    args = {
        "data": mx.nd.array(np.random.rand(4, 8).astype(np.float32)),
        "fc_weight": mx.nd.array(
            np.random.randn(1000, 8).astype(np.float32) * 0.01),
        "fc_bias": mx.nd.zeros((1000,)),
        "target": mx.nd.array(np.array([257, 513, 999, 0], np.float32)),
    }
    exe = Executor(net, mx.cpu(), args, grad_req="null",
                   compute_dtype="bfloat16", label_names=["target"])
    out = exe.forward(is_train=False)[0].asnumpy()
    assert out.shape == (4, 1000)

    # env var set, but explicit None forces full precision
    monkeypatch.setenv("MXNET_COMPUTE_DTYPE", "bfloat16")
    e_off = Executor(net, mx.cpu(), args, grad_req="null",
                     compute_dtype=None)
    e_on = Executor(net, mx.cpu(), args, grad_req="null")
    o_off = e_off.forward(is_train=False)[0].asnumpy()
    o_on = e_on.forward(is_train=False)[0].asnumpy()
    assert not np.array_equal(o_off, o_on)

    # invalid dtype name -> clear error naming the setting
    monkeypatch.setenv("MXNET_COMPUTE_DTYPE", "bf16")
    with pytest.raises(MXNetError, match="MXNET_COMPUTE_DTYPE"):
        Executor(net, mx.cpu(), args, grad_req="null")
