"""Mixed precision (bf16 compute, f32 master weights) train step.

Reference analogue: fp16 training validated via check_consistency
(test_utils.py:588-640, gpu/cpu x fp16/32/64 tolerances). Here the TPU
idiom is bfloat16 activations/matmuls with float32 master weights,
BatchNorm statistics pinned to f32 (ops/nn.py BatchNorm).
"""
import numpy as np

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.parallel import build_sgd_train_step


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="c1")
    net = mx.sym.BatchNorm(data=net, name="bn1")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(data=net)
    net = mx.sym.FullyConnected(data=net, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(data=net, name="softmax")


def _setup(batch=16):
    import jax

    net = _net()
    shapes = {"data": (batch, 1, 8, 8)}
    arg_shapes, _, aux_shapes = net.infer_shape(**shapes)
    rng = np.random.RandomState(0)
    params = {}
    for name, shape in zip(net.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        if name.endswith("gamma"):
            params[name] = jnp.ones(shape, jnp.float32)
        else:
            params[name] = jnp.asarray(rng.randn(*shape) * 0.1, jnp.float32)
    aux = [jnp.ones(s, jnp.float32) if "var" in n
           else jnp.zeros(s, jnp.float32)
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)]
    y = rng.randint(0, 2, batch).astype(np.float32)
    x = (rng.randn(batch, 1, 8, 8) * 0.5
         + y[:, None, None, None]).astype(np.float32)
    data = {"data": jnp.asarray(x), "softmax_label": jnp.asarray(y)}
    key = jax.random.PRNGKey(0)
    return net, params, aux, data, y, key


def test_bf16_step_converges_and_keeps_f32_state():
    import jax

    net, params, aux, data, y, key = _setup()
    step, _ = build_sgd_train_step(net, ["data"], ["softmax_label"],
                                   lr=0.1, compute_dtype=jnp.bfloat16)
    jstep = jax.jit(step)
    for i in range(30):
        outputs, params, aux = jstep(params, data, aux,
                                     jax.random.fold_in(key, i))
    # master weights and BN stats stayed f32
    assert all(p.dtype == jnp.float32 for p in params.values())
    assert all(a.dtype == jnp.float32 for a in aux)
    probs = np.asarray(outputs[0], dtype=np.float32)
    acc = (probs.argmax(axis=1) == y).mean()
    assert acc > 0.9, acc


def test_bf16_matches_f32_first_step():
    import jax

    net, params, aux, data, y, key = _setup()
    s32, _ = build_sgd_train_step(net, ["data"], ["softmax_label"], lr=0.1)
    s16, _ = build_sgd_train_step(net, ["data"], ["softmax_label"], lr=0.1,
                                  compute_dtype=jnp.bfloat16)
    o32, p32, _ = jax.jit(s32)(params, data, aux, key)
    o16, p16, _ = jax.jit(s16)(params, data, aux, key)
    # bf16 has ~3 decimal digits; outputs/updates agree loosely
    np.testing.assert_allclose(np.asarray(o16[0], np.float32),
                               np.asarray(o32[0]), atol=0.06)
    for n in p32:
        np.testing.assert_allclose(np.asarray(p16[n], np.float32),
                                   np.asarray(p32[n]), atol=0.12)
