"""Example scripts run end-to-end (reference example/ tree): each is a
subprocess on the CPU platform with its own converge/behavior assertion
(FGSM accuracy drop, autoencoder mse drop, GAN mode distance, sorted
digits, trigram detection, SVM accuracy, NCE retrieval, module
walkthrough, embedded torch block). A failing assertion inside the
script fails the test."""
import os
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

EXAMPLES = [
    ("adversary/fgsm.py", "FGSM OK"),
    ("autoencoder/autoencoder.py", "autoencoder OK"),
    ("gan/gan_toy.py", "GAN OK"),
    ("bi_lstm_sort/bi_lstm_sort.py", "bi-LSTM sort OK"),
    ("cnn_text_classification/text_cnn.py", "text CNN OK"),
    ("svm_mnist/svm_toy.py", "SVM outputs OK"),
    ("nce_loss/toy_nce.py", "NCE OK"),
    ("module_api/module_howto.py", "module howto OK"),
    ("torch_plugin/torch_module_example.py", "torch plugin OK"),
    ("fcn_xs/fcn_toy.py", "FCN OK"),
    ("dqn/dqn_gridworld.py", "DQN OK"),
    ("stochastic_depth/sd_toy.py", "stochastic depth OK"),
    ("finetune/finetune_toy.py", "finetune OK"),
    ("long_context/ring_attention_demo.py", "ring attention OK"),
    ("bayesian_methods/sgld_toy.py", "SGLD OK"),
    ("dec/dec_toy.py", "DEC OK"),
    ("memcost/memcost.py", "memcost OK"),
    ("nmt/seq2seq_attention.py", "NMT OK"),
    ("neural_style/neural_style.py", "neural style OK"),
    ("rnn_time_major/rnn_time_major.py", "rnn time major OK"),
    ("speech_demo/speech_lstm.py", "speech demo OK"),
    ("kaggle_ndsb1/ndsb1.py", "kaggle ndsb1 OK"),
    ("kaggle_ndsb2/ndsb2.py", "kaggle ndsb2 OK"),
    ("python_howto/howto.py", "python howto OK"),
    ("notebooks/simple_bind.py", "simple bind OK"),
    ("notebooks/composite_symbol.py", "composite symbol OK"),
    ("notebooks/predict_pretrained.py", "predict pretrained OK"),
    ("notebooks/cifar_recipe.py", "cifar recipe OK"),
    ("rcnn/rcnn_demo.py",
     "Faster R-CNN pipeline (Proposal CustomOp + ROIPooling) OK"),
    ("rcnn/train_end2end.py", "rcnn end2end OK"),
]


@pytest.mark.parametrize("script,expect",
                         EXAMPLES, ids=[s for s, _ in EXAMPLES])
def test_example(script, expect):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert expect in r.stdout, r.stdout[-2000:]
