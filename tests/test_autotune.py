"""The closed-loop autotuner: search determinism and pruning off a
fake compile registry, the validate() fence on JSONL writes, the
best-config cache with its lookup fallback order, the trace_report tune
view, and the one-dispatch regression pin for tuned kernels inside the
fused step."""
import json
import os
import sys

import numpy as np
import pytest

from mxnet_tpu import autotune
from mxnet_tpu.base import MXNetError

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools"))


# ---------------------------------------------------------------------------
# search core off a fake registry
# ---------------------------------------------------------------------------

def _fake_site():
    """Three candidates with known registry facts and known run times:
    default (2 ms), a winner (1 ms), and an OOM candidate."""
    cands = [
        {"name": "default", "config": {"tile": 128}},
        {"name": "fast", "config": {"tile": 256}},
        {"name": "huge", "config": {"tile": 1024}},
    ]
    facts = {
        "default": {"flops": 1e9, "peak_bytes": 100, "compile_time_s": 0.1},
        "fast": {"flops": 1e9, "peak_bytes": 200, "compile_time_s": 0.1},
        "huge": {"flops": 1e9, "peak_bytes": 10_000, "compile_time_s": 0.1},
    }
    times = {"default": 2e-3, "fast": 1e-3, "huge": 0.5e-3}
    return (cands,
            lambda c: dict(facts[c["name"]]),
            lambda c: times[c["name"]])


def test_search_picks_winner_and_prunes_preflight():
    cands, compile_fn, run_fn = _fake_site()
    result, rows = autotune.search("fake", cands, compile_fn, run_fn,
                                   limit_bytes=1000)
    assert result["best"]["candidate"] == "fast"
    assert result["non_default"] is True
    assert result["pruned_preflight"] == 1
    assert result["measured"] == 2
    assert result["speedup_vs_default"] == pytest.approx(2.0)
    huge = next(r for r in rows if r["candidate"] == "huge")
    assert "pre-flight OOM" in huge["pruned"]
    assert "step_time_ms" not in huge
    # the winner row is flagged on every row list
    assert [r.get("best") for r in rows
            if "step_time_ms" in r] == [False, True]


def test_search_is_deterministic():
    cands, compile_fn, run_fn = _fake_site()
    a = autotune.search("fake", cands, compile_fn, run_fn,
                        limit_bytes=1000)
    b = autotune.search("fake", cands, compile_fn, run_fn,
                        limit_bytes=1000)
    assert a == b


def test_search_roofline_prune():
    """A candidate whose FLOP floor at chip peak already exceeds the
    best measured time must be pruned without being run."""
    cands = [
        {"name": "default", "config": {}},
        {"name": "bloated", "config": {}},
    ]
    facts = {"default": {"flops": 1e9},
             # 1e12 FLOPs at 100 TFLOPS -> 10 ms floor > 2 ms best
             "bloated": {"flops": 1e12}}
    ran = []

    def run_fn(c):
        ran.append(c["name"])
        return 2e-3

    result, rows = autotune.search(
        "fake", cands, lambda c: dict(facts[c["name"]]), run_fn,
        peak_tflops=100.0)
    assert result["pruned_roofline"] == 1
    assert "bloated" not in ran
    bl = next(r for r in rows if r["candidate"] == "bloated")
    assert "roofline-hopeless" in bl["pruned"]


def test_search_budget_prune_with_fake_clock():
    cands, compile_fn, run_fn = _fake_site()
    t = [0.0]

    def clock():
        t[0] += 10.0
        return t[0]

    result, rows = autotune.search("fake", cands, compile_fn, run_fn,
                                   budget_s=5.0, clock=clock)
    # the default always runs; everything after blows the budget
    assert result["measured"] == 1
    assert result["pruned_budget"] == 2
    assert all("budget exhausted" in r["pruned"] for r in rows[1:])


def test_search_inapplicable_candidate():
    def compile_fn(c):
        if c["name"] == "bad":
            raise MXNetError("candidate 'bad' not applicable")
        return {"flops": 1.0}

    result, rows = autotune.search(
        "fake",
        [{"name": "default", "config": {}}, {"name": "bad", "config": {}}],
        compile_fn, lambda c: 1e-3)
    assert result["pruned_inapplicable"] == 1
    assert result["best"]["candidate"] == "default"


# ---------------------------------------------------------------------------
# the validate() fence on JSONL writes
# ---------------------------------------------------------------------------

def test_record_refuses_physically_impossible_rows(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    rows = [
        {"experiment": "autotune:fake:a", "site": "fake",
         "candidate": "a", "config": {}, "step_time_ms": 2.0},
        # mfu over 100% of chip peak: the fence must refuse it
        {"experiment": "autotune:fake:b", "site": "fake",
         "candidate": "b", "config": {}, "step_time_ms": 1.0,
         "mfu_pct": 1095.0},
    ]
    rec = autotune.record(rows, path)
    assert rec["written"] == 1 and rec["refused"] == 1
    assert "exceeds 100%" in rec["refused_rows"][0]["refused"]
    on_disk = [json.loads(l) for l in open(path)]
    assert len(on_disk) == 1
    assert all(r["valid"] is True for r in on_disk)


# ---------------------------------------------------------------------------
# best-config cache
# ---------------------------------------------------------------------------

def test_cache_roundtrip_and_lookup_fallback(tmp_path):
    path = str(tmp_path / "cache.json")
    autotune.save_best("conv_backward", {"kernel": "pallas"},
                       sig="(2,8,8,128)float32", chip="v5e", path=path)
    autotune.save_best("conv_backward", {"kernel": "xla"},
                       chip="*", path=path)
    # exact hit wins over wildcards
    assert autotune.best_config("conv_backward", "(2,8,8,128)float32",
                                "v5e", path=path) == {"kernel": "pallas"}
    # unknown sig/chip falls back to the site-wide entry
    assert autotune.best_config("conv_backward", "(9,9)f32", "v6e",
                                path=path) == {"kernel": "xla"}
    assert autotune.best_config("norm_act", path=path) is None
    # atomic write left valid JSON behind
    cache = json.load(open(path))
    assert set(cache["entries"]) == {
        "conv_backward|(2,8,8,128)float32|v5e", "conv_backward|*|*"}


def test_consumers_default_off(monkeypatch, tmp_path):
    """With the knobs off nothing consults the cache: defaults apply,
    zero behavior change."""
    monkeypatch.delenv("MXNET_TPU_AUTOTUNE", raising=False)
    monkeypatch.delenv("MXNET_TPU_PALLAS_CONV", raising=False)
    assert autotune.conv_kernel_enabled() is False
    assert autotune.norm_block_rows() is None


def test_conv_kernel_enabled_via_cache(monkeypatch, tmp_path):
    path = str(tmp_path / "cache.json")
    autotune.save_best("conv_backward", {"kernel": "pallas"},
                       chip=autotune._chip_kind(), path=path)
    monkeypatch.setattr(autotune, "CACHE_FILE", path)
    monkeypatch.setattr(autotune, "_cache_memo", None)
    monkeypatch.delenv("MXNET_TPU_PALLAS_CONV", raising=False)
    monkeypatch.setenv("MXNET_TPU_AUTOTUNE", "1")
    assert autotune.conv_kernel_enabled() is True
    # the pin overrides even an empty cache
    monkeypatch.setattr(autotune, "_cache_memo", None)
    monkeypatch.setattr(autotune, "CACHE_FILE",
                        str(tmp_path / "missing.json"))
    monkeypatch.delenv("MXNET_TPU_AUTOTUNE", raising=False)
    assert autotune.conv_kernel_enabled() is False
    monkeypatch.setenv("MXNET_TPU_PALLAS_CONV", "1")
    assert autotune.conv_kernel_enabled() is True


# ---------------------------------------------------------------------------
# the smoke search end to end (the bench.py autotune child's body)
# ---------------------------------------------------------------------------

def test_run_smoke_non_default_winner(tmp_path):
    """The acceptance criterion: on the cpu interpreter the autotuner
    must demonstrably pick a non-default winning config, write only
    valid rows, and persist the winners."""
    jsonl = str(tmp_path / "rows.jsonl")
    cache = str(tmp_path / "cache.json")
    s = autotune.run_smoke(budget=120.0, jsonl_path=jsonl,
                           cache_path=cache)
    assert s["non_default_winner"] is True
    assert s["rows_refused"] == 0
    na = s["sites"]["norm_act"]
    assert na["best"]["config"]["block_rows"] != 128
    assert na["speedup_vs_default"] > 1.0
    rows = [json.loads(l) for l in open(jsonl)]
    assert rows and all(r["valid"] is True for r in rows)
    assert autotune.best_config("norm_act", chip=s["chip"],
                                path=cache) == na["best"]["config"]
    # losers are recorded too, with prune reasons where applicable
    pruned = [r for r in rows if r.get("pruned")]
    assert pruned, "pruned candidates must land in the jsonl as losers"


# ---------------------------------------------------------------------------
# trace_report --view tune
# ---------------------------------------------------------------------------

def test_tune_view_strikes_invalid_rows(tmp_path):
    import trace_report

    path = str(tmp_path / "rows.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(
            {"experiment": "autotune:fake:good", "site": "fake",
             "candidate": "good", "config": {"tile": 128},
             "step_time_ms": 2.0, "best": True, "valid": True}) + "\n")
        f.write(json.dumps(
            {"experiment": "autotune:fake:liar", "site": "fake",
             "candidate": "liar", "config": {"tile": 256},
             "step_time_ms": 1.0, "mfu_pct": 1095.0,
             "valid": False, "invalid_reason": "impossible"}) + "\n")
        f.write("not json\n")
    rows = trace_report.load_tune_rows(path)
    assert len(rows) == 2
    out = trace_report.render_tune(rows)
    assert "BEST" in out
    # the invalid row is struck through (combining stroke), not dropped
    assert "INVALID" in out
    assert "l̶i̶a̶r̶" in out
    assert "good" in out


def test_tune_view_empty():
    import trace_report

    assert "no autotune rows" in trace_report.render_tune([])


# ---------------------------------------------------------------------------
# one-dispatch regression pin: tuned kernels inside the fused step
# ---------------------------------------------------------------------------

def test_fused_step_one_dispatch_with_pallas_conv(monkeypatch):
    """dispatches_per_step must stay exactly 1.0 with the tuned conv
    backward in the trace — the whole point of trace-time config
    consultation. The pallas path is asserted really taken (not a
    silent per-layer fallback) by spying on conv2d."""
    import mxnet_tpu as mx
    from mxnet_tpu import symbol as sym
    from mxnet_tpu import telemetry
    from mxnet_tpu.module import Module
    from mxnet_tpu.ops import pallas_kernels as pk

    if not pk.pallas_available():
        pytest.skip("pallas unavailable")
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    monkeypatch.setenv("MXNET_TPU_PALLAS_CONV", "1")

    taken = []
    orig = pk.conv2d

    def spy(*a, **kw):
        out = orig(*a, **kw)
        taken.append(out is not None)
        return out

    monkeypatch.setattr(pk, "conv2d", spy)

    batch, c, h, nb = 2, 128, 8, 4
    net = sym.Variable("data")
    net = sym.Convolution(net, kernel=(3, 3), num_filter=c, pad=(1, 1),
                          no_bias=True, name="conv1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=3, name="fc")
    net = sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(0)
    X = rng.randn(batch * nb, c, h, h).astype(np.float32)
    y = rng.randint(0, 3, batch * nb).astype(np.float32)
    data = mx.io.NDArrayIter(X, y, batch_size=batch)

    telemetry.reset()
    telemetry.enable()
    try:
        before = telemetry.peek("step.dispatches") or 0
        mod = Module(net, context=mx.cpu())
        mod.fit(data, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.01})
        delta = (telemetry.peek("step.dispatches") or 0) - before
    finally:
        telemetry.reset()
        telemetry.disable()
    assert mod._fused_step_active
    assert delta / nb == 1.0
    assert taken and all(taken), \
        "the pallas conv backward must actually be in the fused trace"
