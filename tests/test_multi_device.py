"""Multi-device data parallelism tests on the 8-device CPU platform
(reference tests/python/unittest/test_multi_device_exec.py +
multi_lenet.py: multi-device training must match single-device)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.module import Module


def _mlp_sym():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=3, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _synthetic(n=400, dim=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    w = rng.randn(dim, classes)
    y = X.dot(w).argmax(axis=1).astype(np.float32)
    return X, y


def test_multi_device_fit():
    import jax

    n_dev = min(4, len(jax.devices()))
    if n_dev < 2:
        pytest.skip("needs >=2 devices")
    X, y = _synthetic()
    data = mx.io.NDArrayIter(X, y, batch_size=40, shuffle=True)
    ctxs = [mx.cpu(i) for i in range(n_dev)]
    mod = Module(_mlp_sym(), context=ctxs)
    mod.fit(data, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9})
    score = mod.score(data, "acc")
    assert score[0][1] > 0.9, score


def test_multi_vs_single_device_identical():
    """Same seed, same data => multi-device run must match single device
    closely (reference multi_lenet.py check)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    X, y = _synthetic(n=160)

    def run(ctxs, seed=7):
        mx.random.seed(seed)
        data = mx.io.NDArrayIter(X, y, batch_size=40, shuffle=False)
        mod = Module(_mlp_sym(), context=ctxs)
        mod.fit(data, num_epoch=3, optimizer="sgd",
                initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.2})
        arg, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in arg.items()}

    single = run([mx.cpu(0)])
    multi = run([mx.cpu(0), mx.cpu(1)])
    for name in single:
        np.testing.assert_allclose(single[name], multi[name], rtol=1e-3,
                                   atol=1e-4)


def test_batch_not_divisible_raises():
    import jax

    if len(jax.devices()) < 3:
        pytest.skip("needs >=3 devices")
    mod = Module(_mlp_sym(), context=[mx.cpu(i) for i in range(3)])
    with pytest.raises(Exception):
        mod.bind([("data", (10, 6))], [("softmax_label", (10,))])


def test_sharded_batch_placement():
    """The executor group shards the batch over the mesh dp axis."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")
    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup
    from mxnet_tpu.io import DataDesc

    group = DataParallelExecutorGroup(
        _mlp_sym(), [mx.cpu(0), mx.cpu(1)], None,
        [DataDesc("data", (8, 6))], [DataDesc("softmax_label", (8,))],
        ["fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"],
        for_training=True, inputs_need_grad=False)
    data_arr = group.executor.arg_dict["data"]
    assert len(data_arr._data.sharding.device_set) == 2
    # params replicated
    w_arr = group.executor.arg_dict["fc1_weight"]
    assert w_arr._data.sharding.is_fully_replicated
