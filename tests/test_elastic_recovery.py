"""Elastic recovery: kill a worker mid-training, restart the job from the
last checkpoint, converge (the reference's recovery story: ps-lite dead-node
tracking kvstore_dist.h:35,73 + checkpoint/resume; here the launcher's
failure detection kills the wedged survivors and a supervisor relaunches).

Step-granularity tier (mxnet_tpu/checkpoint.py): SIGKILL at an
arbitrary STEP, auto-resume from the full-state snapshot, and the
post-resume loss stream is bit-identical to the uninterrupted run —
epoch-granularity param files can't make that promise (optimizer
counters, metric sums, RNG and the data cursor all reset)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dist_util import TRAIN_PREAMBLE, fill, launch, maybe_skip_unavailable
# helpers (underscore names: not collected) + the telemetry fixture
from test_checkpoint import _fit, _keep_only_step, tel  # noqa: F401

WORKER = TRAIN_PREAMBLE + r"""
DIE_AT_EPOCH = int(os.environ.get("DIE_AT_EPOCH", "-1"))
LOAD_EPOCH = int(os.environ.get("LOAD_EPOCH", "-1"))
NUM_EPOCH = 6
prefix = os.path.join(TMP, "ck")

arg_params = aux_params = None
begin_epoch = 0
if LOAD_EPOCH >= 0:
    _, arg_params, aux_params = mx.model.load_checkpoint(prefix, LOAD_EPOCH)
    begin_epoch = LOAD_EPOCH

ckpt = mx.callback.do_checkpoint(prefix) if rank == 0 else None

def epoch_cb(epoch, symbol, arg, aux):
    if ckpt is not None:
        ckpt(epoch, symbol, arg, aux)
    if DIE_AT_EPOCH >= 0 and epoch + 1 == DIE_AT_EPOCH and rank == 1:
        # simulate a hard node failure: no cleanup, no exit barrier
        os.kill(os.getpid(), signal.SIGKILL)

mod = mx.mod.Module(net)
mod.fit(it, num_epoch=NUM_EPOCH, kvstore=kv, begin_epoch=begin_epoch,
        arg_params=arg_params, aux_params=aux_params,
        allow_missing=arg_params is not None,
        optimizer_params={"learning_rate": 0.2},
        epoch_end_callback=epoch_cb)

score = dict(mod.score(mx.io.NDArrayIter(Xs, ys, batch_size=16,
                                         label_name="softmax_label"),
                       "acc"))
assert score["accuracy"] > 0.9, score
args_out, _ = mod.get_params()
np.save(os.path.join(TMP, "w_%d.npy" % rank),
        args_out["fc1_weight"].asnumpy())
kv.barrier()
open(os.path.join(TMP, "done_%d" % rank), "w").write("pass")
"""


@pytest.mark.nightly
def test_worker_death_then_checkpoint_restart(tmp_path):
    # phase 1: rank 1 dies (SIGKILL) after epoch 2's checkpoint; the
    # launcher's failure detection must kill the survivor and fail the job
    out = launch(tmp_path, fill(WORKER, tmp_path), 13351,
                 {"DIE_AT_EPOCH": "2"})
    progressed = (tmp_path / "ck-0001.params").exists()
    maybe_skip_unavailable(out, progressed)
    assert out.returncode != 0, "job must fail when a worker dies"
    assert "terminating" in out.stderr, out.stderr[-500:]
    assert not (tmp_path / "done_0").exists()
    # checkpoints for completed epochs survive the crash
    assert (tmp_path / "ck-0002.params").exists(), os.listdir(tmp_path)
    assert (tmp_path / "ck-symbol.json").exists()

    # phase 2: supervisor restarts the job from the last checkpoint
    out = launch(tmp_path, fill(WORKER, tmp_path), 13352,
                 {"LOAD_EPOCH": "2"})
    assert out.returncode == 0, (out.stdout[-800:], out.stderr[-800:])
    for r in range(2):
        assert (tmp_path / ("done_%d" % r)).read_text() == "pass"
    # both workers end with identical converged weights
    w0 = np.load(tmp_path / "w_0.npy")
    w1 = np.load(tmp_path / "w_1.npy")
    np.testing.assert_allclose(w0, w1, rtol=1e-5, atol=1e-6)
    # and the resumed run kept training from the checkpoint, not scratch:
    # final epoch checkpoints exist beyond the crash point
    assert (tmp_path / "ck-0006.params").exists()


@pytest.mark.slow
def test_sigkill_at_step_resumes_bit_identical(tmp_path):
    """Hard crash (SIGKILL, no grace, no cleanup) at an arbitrary step;
    the relaunched process auto-resumes from the last periodic snapshot
    and its loss stream — written as exact hexfloats — continues the
    uninterrupted run bit for bit."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "ckpt_train_child.py")

    def run(tdir, extra):
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "MXNET_TPU_FUSED_STEP": "1",
                    "T_DIR": str(tdir)})
        env.pop("MXNET_TPU_SANITIZE", None)
        env.update(extra)
        return subprocess.run([sys.executable, script], env=env,
                              timeout=240, capture_output=True,
                              text=True)

    # uninterrupted reference stream (12 steps: 6 batches x 2 epochs)
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    r = run(ref_dir, {})
    assert r.returncode == 0, r.stderr[-2000:]
    ref = (ref_dir / "stream.txt").read_text().splitlines()
    assert len(ref) == 12

    # crash run: periodic snapshot every 4 steps, SIGKILL at step 7 —
    # after the step-4 snapshot, before the step-8 one
    crash_dir = tmp_path / "crash"
    crash_dir.mkdir()
    snaps = str(crash_dir / "snaps")
    ck_env = {"MXNET_TPU_CKPT_DIR": snaps,
              "MXNET_TPU_CKPT_EVERY_N_STEPS": "4"}
    r = run(crash_dir, dict(ck_env, DIE_AT_STEP="7", DIE_SIG="SIGKILL"))
    assert r.returncode != 0
    assert not (crash_dir / "completed").exists()
    with open(os.path.join(snaps, "MANIFEST.json")) as f:
        assert json.load(f)["snapshots"][-1]["step"] == 4

    # relaunch: auto-resume from step 4 (epoch 0, nbatch 3)
    r = run(crash_dir, ck_env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert (crash_dir / "completed").read_text() == "ok"
    got = (crash_dir / "stream.txt").read_text().splitlines()
    # pre-crash steps 1..7, then the resumed tail replays steps 5..12:
    # every post-resume line must equal the reference line bit for bit
    assert got[:7] == ref[:7]
    assert got[7:] == ref[4:], "post-resume stream diverged"
    np.testing.assert_array_equal(
        np.load(crash_dir / "final_w.npy"), np.load(ref_dir / "final_w.npy"))


@pytest.mark.multichip
def test_elastic_shrink_dp8_snapshot_resumes_at_dp1(tmp_path, tel,
                                                    monkeypatch):
    """Elastic rejoin, shrink direction: a snapshot saved at dp=8
    restores onto a single device (re-shard of replicated state) and
    the post-resume stream matches the uninterrupted dp=1 run exactly
    (the exact-arithmetic regime makes the dp=8 and dp=1 trajectories
    themselves identical — see test_sharded_fused)."""
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    ref1 = []
    _fit(dp=1, stream=ref1)

    d = str(tmp_path / "snaps")
    monkeypatch.setenv("MXNET_TPU_CKPT_DIR", d)
    monkeypatch.setenv("MXNET_TPU_CKPT_EVERY_N_STEPS", "3")
    monkeypatch.setenv("MXNET_TPU_CKPT_RESUME", "0")
    _fit(dp=8)                                        # saved at dp=8
    _keep_only_step(d, 3)
    with open(os.path.join(d, "MANIFEST.json")) as f:
        assert json.load(f)["snapshots"][0]["dp"] == 8

    monkeypatch.setenv("MXNET_TPU_CKPT_RESUME", "1")
    monkeypatch.setenv("MXNET_TPU_CKPT_EVERY_N_STEPS", "0")
    s = []
    _fit(dp=1, stream=s)                              # rejoin at dp=1
    assert s == [r for r in ref1 if (r[0], r[1]) > (0, 2)]


@pytest.mark.multichip
def test_elastic_reshard_dp8_to_fsdp4_and_back(tmp_path, tel,
                                               monkeypatch):
    """Elastic re-shard matrix across mesh FACTORINGS of the same 8
    devices: a dp=8 (replicated) snapshot resumes onto the
    dp=2 x fsdp=4 mesh — params and momentum re-enter sharded — and an
    fsdp=4 snapshot resumes back onto dp=8. Both directions continue
    the uninterrupted stream bit for bit (the exact-arithmetic regime
    of test_fsdp makes all three trajectories identical), and each
    resume costs exactly ONE fused compile: restore re-places state
    with the shardings fresh init uses, so the step never retraces."""
    from test_fsdp import _fit_mesh

    ref = []
    _fit_mesh(monkeypatch, stream=ref)            # uninterrupted dp=8
    assert len(ref) == 8
    tail = [r for r in ref if (r[0], r[1]) > (0, 2)]

    d = str(tmp_path / "snaps")
    monkeypatch.setenv("MXNET_TPU_CKPT_DIR", d)
    monkeypatch.setenv("MXNET_TPU_CKPT_EVERY_N_STEPS", "3")
    monkeypatch.setenv("MXNET_TPU_CKPT_RESUME", "0")
    _fit_mesh(monkeypatch)                        # saved at dp=8
    _keep_only_step(d, 3)
    with open(os.path.join(d, "MANIFEST.json")) as f:
        entry = json.load(f)["snapshots"][0]
    assert entry["dp"] == 8
    assert entry["mesh"] == {"dp": 8}

    # dp=8 snapshot -> dp=2 x fsdp=4 resume
    monkeypatch.setenv("MXNET_TPU_CKPT_RESUME", "1")
    monkeypatch.setenv("MXNET_TPU_CKPT_EVERY_N_STEPS", "0")
    before = tel.peek("step.fused_recompiles") or 0
    s = []
    mod = _fit_mesh(monkeypatch, fsdp=4, stream=s)
    assert s == tail, "dp->fsdp resume stream diverged"
    assert (tel.peek("step.fused_recompiles") or 0) - before == 1
    w = mod._exec_group.executor.arg_dict["fc1_weight"]._data
    assert tuple(w.sharding.spec)[0] == "fsdp"    # restored SHARDED

    # fsdp=4 snapshot -> dp=8 resume (the back direction)
    d2 = str(tmp_path / "snaps2")
    monkeypatch.setenv("MXNET_TPU_CKPT_DIR", d2)
    monkeypatch.setenv("MXNET_TPU_CKPT_EVERY_N_STEPS", "3")
    monkeypatch.setenv("MXNET_TPU_CKPT_RESUME", "0")
    _fit_mesh(monkeypatch, fsdp=4)                # saved sharded
    _keep_only_step(d2, 3)
    with open(os.path.join(d2, "MANIFEST.json")) as f:
        assert json.load(f)["snapshots"][0]["mesh"] == \
            {"dp": 2, "fsdp": 4}
    monkeypatch.setenv("MXNET_TPU_CKPT_RESUME", "1")
    monkeypatch.setenv("MXNET_TPU_CKPT_EVERY_N_STEPS", "0")
    before = tel.peek("step.fused_recompiles") or 0
    s2 = []
    _fit_mesh(monkeypatch, stream=s2)             # rejoin replicated
    assert s2 == tail, "fsdp->dp resume stream diverged"
    assert (tel.peek("step.fused_recompiles") or 0) - before == 1
