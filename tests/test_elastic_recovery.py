"""Elastic recovery: kill a worker mid-training, restart the job from the
last checkpoint, converge (the reference's recovery story: ps-lite dead-node
tracking kvstore_dist.h:35,73 + checkpoint/resume; here the launcher's
failure detection kills the wedged survivors and a supervisor relaunches)."""
import os

import numpy as np
import pytest

from dist_util import TRAIN_PREAMBLE, fill, launch, maybe_skip_unavailable

WORKER = TRAIN_PREAMBLE + r"""
DIE_AT_EPOCH = int(os.environ.get("DIE_AT_EPOCH", "-1"))
LOAD_EPOCH = int(os.environ.get("LOAD_EPOCH", "-1"))
NUM_EPOCH = 6
prefix = os.path.join(TMP, "ck")

arg_params = aux_params = None
begin_epoch = 0
if LOAD_EPOCH >= 0:
    _, arg_params, aux_params = mx.model.load_checkpoint(prefix, LOAD_EPOCH)
    begin_epoch = LOAD_EPOCH

ckpt = mx.callback.do_checkpoint(prefix) if rank == 0 else None

def epoch_cb(epoch, symbol, arg, aux):
    if ckpt is not None:
        ckpt(epoch, symbol, arg, aux)
    if DIE_AT_EPOCH >= 0 and epoch + 1 == DIE_AT_EPOCH and rank == 1:
        # simulate a hard node failure: no cleanup, no exit barrier
        os.kill(os.getpid(), signal.SIGKILL)

mod = mx.mod.Module(net)
mod.fit(it, num_epoch=NUM_EPOCH, kvstore=kv, begin_epoch=begin_epoch,
        arg_params=arg_params, aux_params=aux_params,
        allow_missing=arg_params is not None,
        optimizer_params={"learning_rate": 0.2},
        epoch_end_callback=epoch_cb)

score = dict(mod.score(mx.io.NDArrayIter(Xs, ys, batch_size=16,
                                         label_name="softmax_label"),
                       "acc"))
assert score["accuracy"] > 0.9, score
args_out, _ = mod.get_params()
np.save(os.path.join(TMP, "w_%d.npy" % rank),
        args_out["fc1_weight"].asnumpy())
kv.barrier()
open(os.path.join(TMP, "done_%d" % rank), "w").write("pass")
"""


@pytest.mark.nightly
def test_worker_death_then_checkpoint_restart(tmp_path):
    # phase 1: rank 1 dies (SIGKILL) after epoch 2's checkpoint; the
    # launcher's failure detection must kill the survivor and fail the job
    out = launch(tmp_path, fill(WORKER, tmp_path), 13351,
                 {"DIE_AT_EPOCH": "2"})
    progressed = (tmp_path / "ck-0001.params").exists()
    maybe_skip_unavailable(out, progressed)
    assert out.returncode != 0, "job must fail when a worker dies"
    assert "terminating" in out.stderr, out.stderr[-500:]
    assert not (tmp_path / "done_0").exists()
    # checkpoints for completed epochs survive the crash
    assert (tmp_path / "ck-0002.params").exists(), os.listdir(tmp_path)
    assert (tmp_path / "ck-symbol.json").exists()

    # phase 2: supervisor restarts the job from the last checkpoint
    out = launch(tmp_path, fill(WORKER, tmp_path), 13352,
                 {"LOAD_EPOCH": "2"})
    assert out.returncode == 0, (out.stdout[-800:], out.stderr[-800:])
    for r in range(2):
        assert (tmp_path / ("done_%d" % r)).read_text() == "pass"
    # both workers end with identical converged weights
    w0 = np.load(tmp_path / "w_0.npy")
    w1 = np.load(tmp_path / "w_1.npy")
    np.testing.assert_allclose(w0, w1, rtol=1e-5, atol=1e-6)
    # and the resumed run kept training from the checkpoint, not scratch:
    # final epoch checkpoints exist beyond the crash point
    assert (tmp_path / "ck-0006.params").exists()
