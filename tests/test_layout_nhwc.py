"""NHWC layout tier: channels-last Convolution/Pooling/BatchNorm must
compute exactly what NCHW computes (weights are OIHW in both layouts,
so parity is a transpose of data only). This is the correctness gate
behind tools/mfu_experiments.py's layout experiment."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import models


def _run(sym_net, feeds, train=False):
    shapes = {k: v.shape for k, v in feeds.items()}
    ex = sym_net.simple_bind(mx.cpu(), **shapes)
    for k, v in feeds.items():
        ex.arg_dict[k][:] = v
    if train:
        ex.forward(is_train=True)
        ex.backward()
        return ex.outputs[0].asnumpy(), ex
    return ex.forward()[0].asnumpy(), ex


def test_conv_pool_bn_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 12, 12).astype(np.float32)
    w = rng.randn(5, 3, 3, 3).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    gamma = rng.rand(5).astype(np.float32) + 0.5
    beta = rng.randn(5).astype(np.float32)

    def tower(layout):
        data = mx.sym.Variable("data")
        net = mx.sym.Convolution(data=data, num_filter=5, kernel=(3, 3),
                                 stride=(2, 2), pad=(1, 1), layout=layout,
                                 name="c")
        net = mx.sym.BatchNorm(net, fix_gamma=False,
                               axis=-1 if layout == "NHWC" else 1,
                               name="bn")
        net = mx.sym.Activation(net, act_type="relu")
        return mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                              pool_type="max", layout=layout)

    o1, _ = _run(tower("NCHW"),
                 {"data": x, "c_weight": w, "c_bias": b,
                  "bn_gamma": gamma, "bn_beta": beta}, train=True)
    o2, _ = _run(tower("NHWC"),
                 {"data": np.ascontiguousarray(x.transpose(0, 2, 3, 1)),
                  "c_weight": w, "c_bias": b,
                  "bn_gamma": gamma, "bn_beta": beta}, train=True)
    np.testing.assert_allclose(o1, o2.transpose(0, 3, 1, 2),
                               rtol=1e-4, atol=1e-5)


def test_global_pool_nhwc():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, 6, 4).astype(np.float32)
    net = mx.sym.Pooling(mx.sym.Variable("data"), kernel=(1, 1),
                         global_pool=True, pool_type="avg",
                         layout="NHWC")
    out, _ = _run(net, {"data": x})
    np.testing.assert_allclose(out, x.mean(axis=(1, 2), keepdims=True),
                               rtol=1e-5)


def test_resnet50_nhwc_matches_nchw_forward():
    """Whole-tower equivalence on the flagship model (small input)."""
    rng = np.random.RandomState(2)
    nchw = models.get_resnet50(num_classes=8, small_input=True)
    nhwc = models.get_resnet50(num_classes=8, small_input=True,
                               layout="NHWC")

    x = rng.rand(2, 3, 16, 16).astype(np.float32)
    arg_shapes, _, aux_shapes = nchw.infer_shape(data=(2, 3, 16, 16))
    feeds = {}
    for name, shape in zip(nchw.list_arguments(), arg_shapes):
        if name == "data":
            continue
        if name.endswith("gamma"):
            feeds[name] = np.ones(shape, np.float32)
        elif name == "softmax_label":
            feeds[name] = np.zeros(shape, np.float32)
        else:
            feeds[name] = (rng.randn(*shape) * 0.05).astype(np.float32)

    o1, _ = _run(nchw, dict(feeds, data=x))
    o2, _ = _run(nhwc, dict(
        feeds, data=np.ascontiguousarray(x.transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(o1, o2, rtol=1e-4, atol=1e-5)


def test_mfu_experiments_harness_runs():
    """The measurement harness executes every variant end to end (CPU
    smoke scale); on-chip numbers come from running it on the TPU."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "mfu_experiments", _os.path.join(
            _os.path.dirname(__file__), "..", "tools",
            "mfu_experiments.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    results = mod.main(["--variant", "nhwc", "--batch", "2", "--image",
                        "16", "--steps", "1"])
    assert results and results[0]["experiment"] == "nhwc"
    assert results[0]["imgs_per_sec"] > 0
    # the combined channels-last + space-to-depth variant (round 4)
    results = mod.main(["--variant", "nhwc_s2d", "--batch", "2",
                        "--image", "16", "--steps", "1"])
    assert results and results[0]["experiment"] == "nhwc_s2d"
    assert results[0]["imgs_per_sec"] > 0


def test_deconvolution_nhwc_matches_nchw():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, 6, 6).astype(np.float32)
    w = rng.randn(4, 3, 2, 2).astype(np.float32)

    def net(layout):
        return mx.sym.Deconvolution(
            mx.sym.Variable("data"), num_filter=3, kernel=(2, 2),
            stride=(2, 2), no_bias=True, layout=layout, name="d")

    o1, _ = _run(net(None), {"data": x, "d_weight": w})
    o2, _ = _run(net("NHWC"),
                 {"data": np.ascontiguousarray(x.transpose(0, 2, 3, 1)),
                  "d_weight": w})
    np.testing.assert_allclose(o1, o2.transpose(0, 3, 1, 2),
                               rtol=1e-4, atol=1e-5)


def test_invalid_layout_rejected():
    with pytest.raises(mx.base.MXNetError):
        net = mx.sym.Convolution(mx.sym.Variable("data"), num_filter=2,
                                 kernel=(3, 3), layout="NHCW", name="c")
        net.infer_shape(data=(1, 3, 8, 8))
