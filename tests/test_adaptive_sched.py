"""Deadline-aware adaptive batch scheduler, driven by a fake clock:
EDF dispatch order, slack-triggered early dispatch, rung-fill and idle
dispatch reasons, AIMD controller monotonicity and clamps, overload
shedding (batch-lane-first, interactive survives), the arrival-rate
estimator's decay, the exact latency-decomposition pin, and the
no-off-ladder-shape / one-dispatch-per-batch pin with adaptive on.

No jax dispatch in the manual-mode tests: the scheduler runs with
``autostart=False`` and an injected clock, so every decision is
deterministic and instantaneous."""
import numpy as np
import pytest

from mxnet_tpu import serving, telemetry
from mxnet_tpu.io_pipeline import RequestStager
from mxnet_tpu.serving import (AdaptiveWaitController,
                               ArrivalRateEstimator, BatchScheduler,
                               RequestShed, ServiceTimeEstimator)

DIM = 8


@pytest.fixture
def tel():
    telemetry.reset()
    telemetry.enable()
    yield telemetry
    telemetry.reset()
    telemetry.disable()


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, s: float):
        self.t += s


def _fake_infer(placed):
    return [placed[0] * 2.0], ()


def _row(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(-3, 4, (1, DIM)).astype(np.float32)


def _sched(clk, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("slo_ms", 100.0)
    kw.setdefault("adaptive", True)
    return BatchScheduler(_fake_infer, [(kw["max_batch"], DIM)],
                          clock=clk, autostart=False, **kw)


# ---------------------------------------------------------------------------
# dispatch decision plane
# ---------------------------------------------------------------------------

def test_edf_packing_serves_earliest_deadlines_first():
    clk = FakeClock()
    sched = _sched(clk, max_batch=4)
    try:
        deadlines = [500.0, 50.0, 400.0, 60.0, 300.0, 70.0]
        reqs = [sched.submit([_row(i)], deadline_ms=d)
                for i, d in enumerate(deadlines)]
        # 6 pending rows >= max_batch=4: dispatch fires "full" and the
        # EDF pack takes the four tightest deadlines (50/60/70/300)
        assert sched.step() == "full"
        assert [r.done() for r in reqs] == [False, True, False,
                                           True, True, True]
        # the two loose-deadline stragglers ride the next dispatch
        assert sched.step() == "wait"
        clk.advance(0.006)               # past the coalescing window
        assert sched.step() == "rung_fill"
        assert all(r.done() for r in reqs)
    finally:
        sched.close()


def test_slack_runs_out_triggers_deadline_dispatch():
    clk = FakeClock()
    sched = _sched(clk)                  # buckets 1,2,4,8
    try:
        # three quick arrivals pump the EWMA arrival rate high enough
        # that neither "idle" nor a cheap rung fill short-circuits
        for i in range(3):
            sched.submit([_row(i)], deadline_ms=10.0)
            clk.advance(0.0002)
        # slack = deadline - (2 x svc_est + margin): with the 2 ms
        # default estimate that is 10 - 6 = 4 ms after the first submit
        assert sched.step() == "wait"
        clk.advance(0.0035)              # now past the slack point
        assert sched.step() == "deadline"
    finally:
        sched.close()


def test_idle_dispatch_when_nothing_more_is_coming():
    clk = FakeClock()
    sched = _sched(clk)
    try:
        # one 3-row request (not on a rung), arrival rate ~0: holding
        # the 4-bucket open for phantom arrivals buys nothing
        sched.submit([np.concatenate([_row(i) for i in range(3)])])
        assert sched.step() == "idle"
    finally:
        sched.close()


def test_rung_fill_ships_full_bucket_when_next_is_out_of_reach():
    clk = FakeClock()
    sched = _sched(clk, max_batch=4)
    try:
        sched.submit([_row(0)], deadline_ms=1000.0)
        sched.submit([_row(1)], deadline_ms=1000.0)
        clk.advance(0.1)                 # idle-decayed rate: 10 req/s
        # 2 rows sit exactly on the 2-rung with slack to spare;
        # filling the 4-rung at this rate needs ~200 ms, far past the
        # window and its bounded stretch: ship a perfectly full bucket
        assert sched.step() == "rung_fill"
    finally:
        sched.close()


def test_lane_ride_along_no_starvation():
    clk = FakeClock()
    sched = _sched(clk)
    try:
        reqs = [sched.submit([_row(i)], priority="interactive")
                for i in range(4)]
        reqs += [sched.submit([_row(4 + i)], priority="batch")
                 for i in range(4)]
        # the urgent lane fills 4 of 8 rows; the batch lane rides along
        # in the same dispatch instead of waiting out its 4x deadline
        assert sched.step() == "full"
        assert all(r.done() for r in reqs)
        lanes = sched.lane_stats()
        assert lanes["interactive"]["served"] == 4
        assert lanes["batch"]["served"] == 4
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# overload shedding
# ---------------------------------------------------------------------------

def test_shed_expired_batch_lane_first_interactive_survives(tel):
    clk = FakeClock()
    sched = _sched(clk, max_batch=4)     # shed threshold: 8 rows
    try:
        live = [sched.submit([_row(i)], deadline_ms=500.0)
                for i in range(6)]
        doomed = [sched.submit([_row(10 + i)], deadline_ms=5.0,
                               priority="batch") for i in range(6)]
        clk.advance(0.05)                # batch-lane deadlines expired
        assert sched.step() == "full"    # shed happens, then dispatch
        for r in doomed:
            with pytest.raises(RequestShed, match="shed under overload"):
                r.get(timeout=0)
        while not all(r.done() for r in live):
            clk.advance(0.01)
            assert sched.step() != "shed"
        for r in live:
            (out,) = r.get(timeout=0)
            assert out.shape == (1, DIM)
        lanes = sched.lane_stats()
        assert lanes["batch"]["shed"] == 6
        assert lanes["interactive"]["shed"] == 0
        assert lanes["interactive"]["served"] == 6
        assert tel.peek("serve.shed_requests") == 6
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# control plane units
# ---------------------------------------------------------------------------

def test_controller_widens_on_headroom_collapses_near_breach():
    ctl = AdaptiveWaitController(slo_ms=100.0, start_ms=2.0)
    assert ctl.update(None) == pytest.approx(3.0)        # full headroom
    assert ctl.update(50.0) == pytest.approx(4.5)        # headroom 0.5
    assert ctl.update(70.0) == pytest.approx(4.5)        # deadband
    assert ctl.update(90.0) == pytest.approx(2.25)       # headroom 0.1
    for _ in range(40):
        ctl.update(10.0)
    assert ctl.wait_ms == pytest.approx(ctl.ceil_ms) == pytest.approx(50.0)
    for _ in range(40):
        ctl.update(99.0)
    assert ctl.wait_ms == pytest.approx(ctl.floor_ms)


def test_controller_monotone_in_p99():
    # for identical controller state, a worse p99 never yields a longer
    # wait — the law the scheduler's stability argument rests on
    waits = []
    for p99 in (None, 10.0, 40.0, 70.0, 90.0, 130.0):
        ctl = AdaptiveWaitController(slo_ms=100.0, start_ms=8.0)
        waits.append(ctl.update(p99))
    assert waits == sorted(waits, reverse=True)


def test_arrival_rate_ewma_and_idle_decay():
    clk = FakeClock()
    est = ArrivalRateEstimator(clock=clk)
    assert est.rate() == 0.0
    for _ in range(20):
        est.observe()
        clk.advance(0.01)                # 100 req/s
    assert 50.0 < est.rate() <= 100.0 + 1e-6
    clk.advance(1.0)                     # silence: rate <= 1/idle
    assert est.rate() <= 1.0


def test_service_time_estimator_borrows_worst_for_unseen_rungs():
    svc = ServiceTimeEstimator(default_ms=2.0)
    assert svc.estimate_ms(8) == 2.0     # nothing observed yet
    svc.observe(8, 10.0)
    assert svc.estimate_ms(8) == 10.0
    assert svc.estimate_ms(4) == 10.0    # unseen rung: conservative
    svc.observe(8, 20.0)
    assert svc.estimate_ms(8) == pytest.approx(12.5)     # EWMA 0.25


def test_controller_feedback_skips_first_compile_dispatch():
    clk = FakeClock()
    sched = _sched(clk)
    try:
        sched.submit([_row(0)])
        assert sched.step() == "rung_fill"
        # the 1-bucket's first (compile-carrying) dispatch must not
        # steer the controller: the recent window stays empty
        assert sched.recent_quantile(0.99) is None
        clk.advance(0.01)
        sched.submit([_row(1)])
        assert sched.step() == "rung_fill"
        # the warm repeat on the same rung does feed the controller
        assert sched.recent_quantile(0.99) is not None
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# decomposition + dispatch-count pins
# ---------------------------------------------------------------------------

def test_decomposition_sums_exactly_to_latency_fake_clock():
    clk = FakeClock()
    sched = _sched(clk)
    try:
        reqs = []
        for i in range(5):
            reqs.append(sched.submit([_row(i)], deadline_ms=50.0))
            clk.advance(0.003)
        while not all(r.done() for r in reqs):
            clk.advance(0.003)
            sched.step()
        for r in reqs:
            assert r.components is not None
            assert set(r.components) == {"queue_ms", "sched_idle_ms",
                                         "h2d_ms", "dispatch_ms",
                                         "d2h_ms"}
            assert sum(r.components.values()) == pytest.approx(
                r.latency_ms, abs=1e-9)
    finally:
        sched.close()


def test_decomposition_sums_to_latency_real_clock_threaded():
    sched = BatchScheduler(_fake_infer, [(8, DIM)], max_batch=8,
                           max_wait_ms=1.0, slo_ms=100.0, adaptive=True)
    try:
        reqs = [sched.submit([_row(i)]) for i in range(24)]
        for r in reqs:
            r.get(timeout=30)
        for r in reqs:
            assert sum(r.components.values()) == pytest.approx(
                r.latency_ms, rel=1e-6, abs=1e-6)
    finally:
        sched.close()


def test_adaptive_on_keeps_ladder_shapes_and_one_dispatch_per_batch():
    calls = []

    def counting_infer(placed):
        calls.append(int(placed[0].shape[0]))
        return [placed[0] * 2.0], ()

    sched = BatchScheduler(counting_infer, [(8, DIM)], max_batch=8,
                           max_wait_ms=1.0, slo_ms=100.0, adaptive=True)
    try:
        reqs = [sched.submit([_row(i)]) for i in range(40)]
        for r in reqs:
            r.get(timeout=30)
    finally:
        sched.close()
    # adaptive coalescing never invents an off-ladder shape (the
    # zero-retrace property) and costs exactly one dispatch per batch
    assert set(calls) <= set(sched.buckets)
    assert len(calls) == sched.stats()["batches"]
    assert sched.stats()["requests_served"] == 40


def test_stats_and_controller_state_surface_adaptive_fields():
    clk = FakeClock()
    sched = _sched(clk)
    try:
        sched.submit([_row(0)])
        sched.step()
        st = sched.stats()
        assert st["adaptive"] is True
        for key in ("adaptive_wait_ms", "arrival_rate_rps",
                    "queue_depth", "mean_occupancy", "lanes"):
            assert key in st
        traj = sched.wait_trajectory()
        assert traj and {"t_s", "wait_ms", "queue_depth", "occupancy",
                         "reason"} <= set(traj[0])
    finally:
        sched.close()


def test_submit_rejects_unknown_lane():
    clk = FakeClock()
    sched = _sched(clk)
    try:
        with pytest.raises(serving.MXNetError, match="priority lane"):
            sched.submit([_row(0)], priority="bulk")
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# stager fast path
# ---------------------------------------------------------------------------

def test_stager_fast_path_single_full_payload(tel):
    stager = RequestStager(place=None)
    full = np.arange(4 * DIM, dtype=np.float32).reshape(4, DIM)
    placed, pad = stager.stage([[full]], 4)
    assert pad == 0
    assert np.array_equal(placed[0], full)
    assert tel.peek("serve.stage_fastpath") == 1
    # two payloads (or any pad) take the concat path, not the fast one
    placed, pad = stager.stage([[_row(0)], [_row(1)]], 4)
    assert pad == 2
    assert placed[0].shape == (4, DIM)
    assert tel.peek("serve.stage_fastpath") == 1
