"""CTC loss: warp-ctc plugin parity (reference plugin/warpctc/warpctc-inl.h).

Ground truth: torch.nn.CTCLoss (CPU) — same algorithm warp-ctc implements —
for both the loss value and the gradient w.r.t. the pre-softmax activations.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _torch_ctc(x, labels, blank=0):
    """x: (T, B, A) logits; labels: (B, L) 0-padded.
    Returns (loss (B,), grad wrt x)."""
    torch = pytest.importorskip("torch")
    xt = torch.tensor(x, dtype=torch.float32, requires_grad=True)
    lp = torch.log_softmax(xt, dim=-1)
    T, B, A = x.shape
    label_lens = (labels != blank).sum(axis=1)
    targets = torch.tensor(
        np.concatenate([labels[b, :label_lens[b]] for b in range(B)]),
        dtype=torch.long)
    loss = torch.nn.functional.ctc_loss(
        lp, targets,
        input_lengths=torch.full((B,), T, dtype=torch.long),
        target_lengths=torch.tensor(label_lens, dtype=torch.long),
        blank=blank, reduction="none", zero_infinity=False)
    loss.sum().backward()
    return loss.detach().numpy(), xt.grad.numpy()


def test_ctc_nll_matches_torch():
    from mxnet_tpu.ops.ctc import ctc_neg_log_likelihood
    import jax
    rng = np.random.RandomState(0)
    T, B, A, L = 12, 4, 6, 4
    x = rng.randn(T, B, A).astype(np.float32)
    labels = np.zeros((B, L), dtype=np.int32)
    # variable lengths, labels in 1..A-1 (0 = blank = pad)
    for b, n in enumerate([4, 3, 2, 1]):
        labels[b, :n] = rng.randint(1, A, n)
    ref_loss, _ = _torch_ctc(x, labels)
    lp = jax.nn.log_softmax(x, axis=-1)
    ours = np.asarray(ctc_neg_log_likelihood(lp, labels))
    np.testing.assert_allclose(ours, ref_loss, rtol=1e-4, atol=1e-5)


def test_ctc_repeated_labels():
    """Repeated labels exercise the skip-transition mask."""
    from mxnet_tpu.ops.ctc import ctc_neg_log_likelihood
    import jax
    rng = np.random.RandomState(1)
    T, B, A = 10, 2, 5
    x = rng.randn(T, B, A).astype(np.float32)
    labels = np.array([[2, 2, 3, 0], [1, 1, 1, 1]], dtype=np.int32)
    ref_loss, _ = _torch_ctc(x, labels)
    lp = jax.nn.log_softmax(x, axis=-1)
    ours = np.asarray(ctc_neg_log_likelihood(lp, labels))
    np.testing.assert_allclose(ours, ref_loss, rtol=1e-4, atol=1e-5)


def test_ctc_mid_row_blanks():
    """Blanks embedded mid-row are compacted out, like the reference's
    removeBlank (warpctc-inl.h:100-109)."""
    from mxnet_tpu.ops.ctc import ctc_neg_log_likelihood
    import jax
    rng = np.random.RandomState(7)
    T, B, A = 10, 2, 5
    x = rng.randn(T, B, A).astype(np.float32)
    messy = np.array([[1, 0, 2, 0], [0, 3, 0, 4]], dtype=np.int32)
    clean = np.array([[1, 2, 0, 0], [3, 4, 0, 0]], dtype=np.int32)
    lp = jax.nn.log_softmax(x, axis=-1)
    np.testing.assert_allclose(
        np.asarray(ctc_neg_log_likelihood(lp, messy)),
        np.asarray(ctc_neg_log_likelihood(lp, clean)), rtol=1e-6)
    ref_loss, _ = _torch_ctc(x, clean)
    np.testing.assert_allclose(np.asarray(ctc_neg_log_likelihood(lp, messy)),
                               ref_loss, rtol=1e-4, atol=1e-5)


def test_warpctc_flat_label_shape():
    """Reference InferShape assigns a flat (label_length*minibatch,) label
    (warpctc-inl.h:237-239)."""
    T, B, A, L = 4, 3, 5, 2
    s = sym.WarpCTC(data=sym.Variable("data"), label=sym.Variable("label"),
                    input_length=T, label_length=L)
    arg_shapes, out_shapes, _ = s.infer_shape(data=(T * B, A))
    assert arg_shapes[1] == (B * L,)
    assert out_shapes[0] == (T * B, A)
    # a user-supplied 2D (B, L) label is also accepted
    arg_shapes, _, _ = s.infer_shape(data=(T * B, A), label=(B, L))
    assert arg_shapes[1] == (B, L)


def test_warpctc_forward_backward():
    """Reference contract: output is softmax(data); backward writes the CTC
    gradient and ignores head grads (warpctc-inl.h:67-199)."""
    rng = np.random.RandomState(2)
    T, B, A, L = 8, 3, 5, 3
    x = rng.randn(T * B, A).astype(np.float32)
    labels = np.zeros((B, L), dtype=np.float32)
    labels[0, :2] = [1, 2]
    labels[1, :3] = [3, 3, 4]
    labels[2, :1] = [2]

    s = sym.WarpCTC(data=sym.Variable("data"), label=sym.Variable("label"),
                    input_length=T, label_length=L)
    args = {"data": mx.nd.array(x), "label": mx.nd.array(labels)}
    grads = {"data": mx.nd.zeros((T * B, A))}
    ex = s.bind(mx.cpu(), args, args_grad=grads,
                grad_req={"data": "write", "label": "null"})
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    e = np.exp(x - x.max(axis=1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(axis=1, keepdims=True),
                               rtol=1e-5)
    ex.backward()
    _, ref_grad = _torch_ctc(x.reshape(T, B, A), labels.astype(np.int32))
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               ref_grad.reshape(T * B, A),
                               rtol=1e-3, atol=1e-5)


def test_ctcloss_op_values_and_infer():
    rng = np.random.RandomState(3)
    T, B, A, L = 9, 2, 4, 3
    x = rng.randn(T, B, A).astype(np.float32)
    labels = np.array([[1, 3, 0], [2, 0, 0]], dtype=np.float32)
    s = sym.CTCLoss(data=sym.Variable("data"), label=sym.Variable("label"))
    arg_shapes, out_shapes, _ = s.infer_shape(data=(T, B, A), label=(B, L))
    assert out_shapes[0] == (B,)
    ex = s.bind(mx.cpu(), {"data": mx.nd.array(x),
                           "label": mx.nd.array(labels)})
    ex.forward(is_train=False)
    ref_loss, _ = _torch_ctc(x, labels.astype(np.int32))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), ref_loss,
                               rtol=1e-4, atol=1e-5)


def test_warpctc_training_decreases_loss():
    """A linear model + WarpCTC trains: loss (measured via CTCLoss) drops."""
    rng = np.random.RandomState(4)
    T, B, A, L, D = 6, 4, 5, 2, 8
    x = rng.randn(T * B, D).astype(np.float32)
    labels = rng.randint(1, A, (B, L)).astype(np.float32)

    data = sym.Variable("data")
    fc = sym.FullyConnected(data=data, num_hidden=A, name="fc")
    net = sym.WarpCTC(data=fc, label=sym.Variable("label"),
                      input_length=T, label_length=L)

    w = (rng.randn(A, D) * 0.1).astype(np.float32)
    b = np.zeros(A, dtype=np.float32)
    args = {"data": mx.nd.array(x), "fc_weight": mx.nd.array(w),
            "fc_bias": mx.nd.array(b), "label": mx.nd.array(labels)}
    grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()
             if k in ("fc_weight", "fc_bias")}
    ex = net.bind(mx.cpu(), args, args_grad=grads,
                  grad_req={"fc_weight": "write", "fc_bias": "write",
                            "data": "null", "label": "null"})

    def loss_now():
        import jax
        from mxnet_tpu.ops.ctc import ctc_neg_log_likelihood
        logits = (x @ np.asarray(args["fc_weight"].asnumpy()).T
                  + args["fc_bias"].asnumpy())
        lp = jax.nn.log_softmax(logits.reshape(T, B, A), axis=-1)
        return float(np.sum(np.asarray(
            ctc_neg_log_likelihood(lp, labels.astype(np.int32)))))

    before = loss_now()
    for _ in range(30):
        ex.forward(is_train=True)
        ex.backward()
        for k in ("fc_weight", "fc_bias"):
            args[k][:] = args[k].asnumpy() - 0.05 * grads[k].asnumpy()
    after = loss_now()
    assert after < before * 0.8, (before, after)
