"""Engine tests (reference tests/cpp/threaded_engine_test.cc: randomized
read/write workloads on all engine types verified against serial oracle)."""
import random
import threading

import numpy as np
import pytest

from mxnet_tpu import engine as eng


def _random_workload(num_vars=10, num_ops=200, seed=0):
    """Generate ops: each reads/writes random var subsets, oracle = serial."""
    rng = random.Random(seed)
    ops = []
    for i in range(num_ops):
        reads = rng.sample(range(num_vars), rng.randint(0, 3))
        writes = rng.sample([v for v in range(num_vars) if v not in reads],
                            rng.randint(1, 2))
        ops.append((reads, writes))
    return ops


def _run_workload(engine, ops, num_vars):
    """Each op appends (op_id) to a log per written var; dependency
    correctness => per-var log order must match serial execution order of
    ops touching that var."""
    vars_ = [engine.new_variable() for _ in range(num_vars)]
    state = {v: 0.0 for v in range(num_vars)}
    lock = threading.Lock()
    logs = {v: [] for v in range(num_vars)}

    for op_id, (reads, writes) in enumerate(ops):
        def fn(op_id=op_id, reads=reads, writes=writes):
            with lock:
                s = sum(state[r] for r in reads)
                for w in writes:
                    state[w] += s + 1
                    logs[w].append(op_id)
        engine.push(fn, const_vars=[vars_[r] for r in reads],
                    mutable_vars=[vars_[w] for w in writes])
    engine.wait_for_all()
    return state, logs


@pytest.mark.parametrize("engine_factory", [
    eng.NaiveEngine, eng.XLAEngine,
    lambda: eng.ThreadedEngine(num_workers=4)])
def test_engine_vs_serial_oracle(engine_factory):
    ops = _random_workload(seed=42)
    # oracle: NaiveEngine is serial by construction
    oracle_state, oracle_logs = _run_workload(eng.NaiveEngine(), ops, 10)
    engine = engine_factory() if callable(engine_factory) else engine_factory
    state, logs = _run_workload(engine, ops, 10)
    assert state == oracle_state
    assert logs == oracle_logs


def test_threaded_engine_parallel_reads():
    """Reads on the same var may run concurrently; writes serialize."""
    engine = eng.ThreadedEngine(num_workers=4)
    v = engine.new_variable()
    results = []
    lock = threading.Lock()
    barrier = threading.Barrier(3, timeout=5)

    def reader():
        barrier.wait()  # deadlocks unless >=3 readers run concurrently
        with lock:
            results.append("r")

    for _ in range(3):
        engine.push(reader, const_vars=[v])
    engine.wait_for_all()
    assert results == ["r"] * 3


def test_threaded_engine_write_serialization():
    engine = eng.ThreadedEngine(num_workers=8)
    v = engine.new_variable()
    counter = {"x": 0, "max_in_flight": 0}
    lock = threading.Lock()

    def writer():
        with lock:
            counter["x"] += 1
            counter["max_in_flight"] = max(counter["max_in_flight"],
                                           counter["x"])
        # no sleep needed: overlap would be caught by in_flight > 1
        with lock:
            counter["x"] -= 1

    for _ in range(100):
        engine.push(writer, mutable_vars=[v])
    engine.wait_for_all()
    assert counter["max_in_flight"] == 1


def test_engine_wait_for_var():
    engine = eng.ThreadedEngine(num_workers=2)
    v = engine.new_variable()
    out = []
    engine.push(lambda: out.append(1), mutable_vars=[v])
    engine.wait_for_var(v)
    assert out == [1]


def test_duplicate_var_rejected():
    engine = eng.NaiveEngine()
    v = engine.new_variable()
    with pytest.raises(Exception):
        engine.push(lambda: None, const_vars=[v], mutable_vars=[v])


def test_engine_priority():
    """Higher priority ops dispatch first when queued together."""
    engine = eng.ThreadedEngine(num_workers=1)
    gate = engine.new_variable()
    order = []
    import time

    def blocker():
        time.sleep(0.05)

    engine.push(blocker, mutable_vars=[gate])
    engine.push(lambda: order.append("low"), priority=0)
    engine.push(lambda: order.append("high"), priority=10)
    engine.wait_for_all()
    # with 1 worker busy on blocker, both queued; high must pop first
    assert order == ["high", "low"]


def test_pooled_engine_io_routing():
    """ThreadedEnginePooled: io/copy ops run on the dedicated I/O pool,
    dependency ordering still holds across pools (reference
    threaded_engine_pooled.cc)."""
    import threading

    from mxnet_tpu.engine import ThreadedEnginePooled

    eng = ThreadedEnginePooled(num_workers=2, num_io_workers=1)
    v = eng.new_variable()
    order = []
    lock = threading.Lock()
    thread_names = {}

    def record(tag):
        def fn():
            with lock:
                order.append(tag)
                thread_names[tag] = threading.current_thread().name
        return fn

    eng.push(record("w1"), mutable_vars=[v])
    eng.push(record("io"), mutable_vars=[v], prop="io")
    eng.push(record("w2"), mutable_vars=[v])
    eng.wait_for_all()
    assert order == ["w1", "io", "w2"]
    assert thread_names["io"].startswith("mxtpu-engine-io")
    assert not thread_names["w1"].startswith("mxtpu-engine-io")
    eng.stop()


def test_pooled_engine_stress_vs_serial():
    """Randomized read/write workload on the pooled engine matches serial
    execution (reference tests/cpp/threaded_engine_test.cc)."""
    import random

    from mxnet_tpu.engine import ThreadedEnginePooled

    rng = random.Random(7)
    eng = ThreadedEnginePooled(num_workers=3, num_io_workers=2)
    n_vars = 6
    eng_vars = [eng.new_variable() for _ in range(n_vars)]
    state = [0] * n_vars
    serial = [0] * n_vars
    ops = []
    for i in range(120):
        reads = rng.sample(range(n_vars), rng.randint(0, 2))
        writes = rng.sample([j for j in range(n_vars) if j not in reads],
                            rng.randint(1, 2))
        prop = rng.choice(["normal", "normal", "io"])
        ops.append((reads, writes, prop))

    def make_fn(reads, writes):
        def fn():
            acc = sum(state[r] for r in reads)
            for w in writes:
                state[w] = state[w] * 2 + acc + 1
        return fn

    for reads, writes, prop in ops:
        eng.push(make_fn(reads, writes),
                 const_vars=[eng_vars[r] for r in reads],
                 mutable_vars=[eng_vars[w] for w in writes], prop=prop)
    eng.wait_for_all()
    for reads, writes, _ in ops:  # serial oracle
        acc = sum(serial[r] for r in reads)
        for w in writes:
            serial[w] = serial[w] * 2 + acc + 1
    assert state == serial
    eng.stop()


def test_pooled_engine_zero_io_workers_falls_through():
    from mxnet_tpu.engine import ThreadedEnginePooled

    eng = ThreadedEnginePooled(num_workers=2, num_io_workers=0)
    v = eng.new_variable()
    ran = []
    eng.push(lambda: ran.append("io"), mutable_vars=[v], prop="io")
    eng.wait_for_all()   # must not deadlock
    assert ran == ["io"]
    eng.stop()


def test_engine_info_logging(caplog):
    """MXNET_ENGINE_INFO=1 logs one line per pushed op (reference
    threaded_engine.h engine-op logging)."""
    import logging

    from mxnet_tpu import engine as eng

    old = eng._ENGINE_INFO
    eng._ENGINE_INFO = True
    try:
        e = eng.NaiveEngine()
        v = e.new_variable()
        with caplog.at_level(logging.INFO, logger="mxnet_tpu.engine"):
            e.push(lambda: None, mutable_vars=[v])
        assert any("NaiveEngine push" in r.getMessage()
                   for r in caplog.records if r.name == "mxnet_tpu.engine")
    finally:
        eng._ENGINE_INFO = old
