"""graftlint static analysis + runtime sanitizers: rule-family
fixtures (good/bad pairs), annotation + baseline suppression, the
whole-tree tier-1 gate, env-registry/docs drift, and seeded runtime
violations proving each sanitizer fires."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import env, telemetry
from mxnet_tpu.analysis import graftlint, sanitizers
from mxnet_tpu.analysis.sanitizers import (DonationSanitizer,
                                           RetraceSanitizer,
                                           SanitizerError)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a config with a known env universe so fixture tests don't depend on
# the real registry's contents
CFG = graftlint.Config(declared_env={"MXNET_TPU_DECLARED"})


def _rules(findings):
    return sorted({f.rule for f in findings})


def _lint(src, path="pkg/engine.py", rules=None):
    cfg = graftlint.Config(declared_env={"MXNET_TPU_DECLARED"},
                           rules=rules)
    return graftlint.analyze_source(src, path, cfg)


# ---------------------------------------------------------------------------
# host-sync rule
# ---------------------------------------------------------------------------

def test_host_sync_flags_numpy_conversion_in_step_loop_file():
    src = "def step(x):\n    return np.asarray(x)\n"
    bad = _lint(src, "pkg/engine.py")
    assert _rules(bad) == ["host-sync"]
    # same code outside the step-loop module set is fine
    assert _lint(src, "pkg/visualization.py") == []


def test_host_sync_flags_sync_methods_and_device_get():
    for call in ("x.item()", "x.tolist()", "x.asnumpy()",
                 "x.block_until_ready()", "jax.device_get(x)"):
        src = "def step(x):\n    return %s\n" % call
        assert _rules(_lint(src)) == ["host-sync"], call


def test_host_sync_flags_float_and_truthiness_on_device_value():
    src = ("def step(a):\n"
           "    loss = jnp.mean(a)\n"
           "    return float(loss)\n")
    assert _rules(_lint(src)) == ["host-sync"]
    src = ("def step(a):\n"
           "    ok = jnp.all(a)\n"
           "    if ok:\n"
           "        return 1\n")
    assert _rules(_lint(src)) == ["host-sync"]


def test_host_sync_ignores_host_only_values():
    src = ("def step(n):\n"
           "    m = n + 1\n"
           "    if m:\n"
           "        return float(m)\n")
    assert _lint(src) == []
    # metadata comparisons on device values don't sync
    src = ("def step(a):\n"
           "    v = jnp.mean(a)\n"
           "    if v is None:\n"
           "        return 0\n"
           "    return v\n")
    assert _lint(src) == []


def test_host_sync_annotation_suppresses():
    src = ("def step(x):\n"
           "    return np.asarray(x)  # graft: host-sync\n")
    assert _lint(src) == []
    src = ("def step(x):\n"
           "    # graft: host-sync\n"
           "    return np.asarray(x)\n")
    assert _lint(src) == []


# ---------------------------------------------------------------------------
# donation rule
# ---------------------------------------------------------------------------

def test_donation_flags_read_after_donating_call():
    src = ("fn = jax.jit(step, donate_argnums=(0,))\n"
           "out = fn(params, batch)\n"
           "print(params)\n")
    found = _lint(src, "pkg/train.py")
    assert _rules(found) == ["donation"]
    assert "donated" in found[0].message


def test_donation_reassignment_kills_the_hazard():
    # the canonical donated-step loop: the name is rebound to the NEW
    # buffer by the same statement that donates the old one
    src = ("fn = jax.jit(step, donate_argnums=(0,))\n"
           "_, params = fn(params, batch)\n"
           "_, params = fn(params, batch)\n"
           "print(params)\n")
    assert _lint(src, "pkg/train.py") == []


def test_donation_decorated_def_and_annotation():
    src = ("@functools.partial(jax.jit, donate_argnums=(1,))\n"
           "def fn(a, b):\n"
           "    return a + b\n"
           "out = fn(x, y)\n"
           "print(y)\n")
    assert _rules(_lint(src, "pkg/train.py")) == ["donation"]
    src = src.replace("print(y)", "print(y)  # graft: donated-ok")
    assert _lint(src, "pkg/train.py") == []


# ---------------------------------------------------------------------------
# tracer rule
# ---------------------------------------------------------------------------

def test_tracer_flags_impure_call_in_jitted_fn():
    src = ("@jax.jit\n"
           "def fn(a):\n"
           "    t = time.time()\n"
           "    return a * t\n")
    found = _lint(src, "pkg/anything.py")
    assert _rules(found) == ["tracer"]


def test_tracer_flags_python_branch_on_traced_param():
    src = ("@jax.jit\n"
           "def fn(a):\n"
           "    if a:\n"
           "        return a + 1\n"
           "    return a\n")
    assert _rules(_lint(src, "pkg/x.py")) == ["tracer"]


def test_tracer_callsite_wrap_and_suppressions():
    src = ("def fn(a):\n"
           "    return a * np.random.rand()\n"
           "fn = jax.jit(fn)\n")
    assert _rules(_lint(src, "pkg/x.py")) == ["tracer"]
    src = ("def fn(a):\n"
           "    return a * np.random.rand()  # graft: traced-ok\n"
           "fn = jax.jit(fn)\n")
    assert _lint(src, "pkg/x.py") == []
    # un-jitted functions may branch and be impure
    src = ("def fn(a):\n"
           "    if a:\n"
           "        return time.time()\n")
    assert _lint(src, "pkg/x.py") == []


def test_tracer_static_args_may_branch():
    src = ("@functools.partial(jax.jit, static_argnums=(1,))\n"
           "def fn(a, flag):\n"
           "    if flag:\n"
           "        return a + 1\n"
           "    return a\n")
    assert _lint(src, "pkg/x.py") == []


# ---------------------------------------------------------------------------
# env-registry rule
# ---------------------------------------------------------------------------

def test_env_registry_flags_raw_reads():
    for read in ('os.environ.get("MXNET_TPU_FOO")',
                 'os.getenv("MXNET_TPU_FOO")',
                 'getenv("MXNET_TPU_FOO", 3)',
                 'os.environ["MXNET_TPU_FOO"]'):
        src = "x = %s\n" % read
        assert _rules(_lint(src, "pkg/x.py")) == ["env-registry"], read


def test_env_registry_ignores_non_prefix_and_writes():
    src = ('a = os.environ.get("HOME")\n'
           'os.environ["MXNET_TPU_FOO"] = "1"\n')
    assert _lint(src, "pkg/x.py") == []


def test_env_registry_checks_declared_names():
    assert _lint('v = env.get("MXNET_TPU_DECLARED")\n', "pkg/x.py") == []
    found = _lint('v = env.get("MXNET_TPU_MISSING")\n', "pkg/x.py")
    assert _rules(found) == ["env-registry"]
    src = ('# graft: env-ok\n'
           'v = os.environ.get("MXNET_TPU_FOO")\n')
    assert _lint(src, "pkg/x.py") == []


def test_declared_env_names_parses_real_registry():
    names = graftlint.declared_env_names(
        os.path.join(ROOT, "mxnet_tpu", "env.py"))
    assert names == set(env.declared())
    assert "MXNET_TPU_FUSED_STEP" in names


# ---------------------------------------------------------------------------
# baseline + fingerprints
# ---------------------------------------------------------------------------

def test_fingerprints_stable_under_line_drift():
    src = "def step(x):\n    return np.asarray(x)\n"
    f1 = _lint(src)[0]
    f2 = _lint("import os\n\n\n" + src)[0]
    assert f1.line != f2.line
    assert f1.fingerprint == f2.fingerprint
    # ...but distinct duplicate occurrences stay distinct
    dup = ("def step(x):\n"
           "    a = np.asarray(x)\n"
           "    b = np.asarray(x)\n")
    fps = [f.fingerprint for f in _lint(dup)]
    assert len(fps) == 2 and len(set(fps)) == 2


def test_baseline_roundtrip_and_partition(tmp_path):
    src = "def step(x):\n    return np.asarray(x)\n"
    findings = _lint(src)
    bl = tmp_path / "baseline.json"
    graftlint.save_baseline(str(bl), findings)
    accepted = graftlint.load_baseline(str(bl))
    new, old = graftlint.partition(findings, accepted)
    assert new == [] and len(old) == 1
    # an unrelated finding is NOT covered
    other = _lint("def step(y):\n    return y.item()\n")
    new, _ = graftlint.partition(other, accepted)
    assert len(new) == 1
    data = json.loads(bl.read_text())
    assert data["version"] == 1


def test_parse_error_is_reported_not_raised():
    found = graftlint.analyze_source("def broken(:\n", "pkg/x.py", CFG)
    assert len(found) == 1 and found[0].rule == "parse-error"


# ---------------------------------------------------------------------------
# the tier-1 gate: the shipped tree is clean against the shipped baseline
# ---------------------------------------------------------------------------

def test_repo_tree_has_no_unbaselined_findings():
    findings = graftlint.analyze_paths(
        [os.path.join(ROOT, "mxnet_tpu"), os.path.join(ROOT, "tools"),
         os.path.join(ROOT, "bench.py")], root=ROOT)
    baseline = graftlint.load_baseline(
        os.path.join(ROOT, "tools", "graftlint_baseline.json"))
    new, _ = graftlint.partition(findings, baseline)
    assert new == [], "new graftlint findings:\n%s" % "\n".join(
        repr(f) for f in new)


def test_env_docs_in_sync_with_registry():
    assert env.sync_docs(os.path.join(ROOT, "docs", "env_vars.md"),
                         check=True), (
        "docs/env_vars.md is out of sync with mxnet_tpu/env.py — run "
        "`python tools/graftlint.py --write-env-docs`")


# ---------------------------------------------------------------------------
# env registry semantics
# ---------------------------------------------------------------------------

def test_env_get_reads_declared_default_and_coerces(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_FEED_DEPTH", raising=False)
    assert env.get("MXNET_TPU_FEED_DEPTH") == 0
    monkeypatch.setenv("MXNET_TPU_FEED_DEPTH", "3")
    assert env.get("MXNET_TPU_FEED_DEPTH") == 3
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "true")
    assert env.get("MXNET_TPU_FUSED_STEP") is True
    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "0")
    assert env.get("MXNET_TPU_FUSED_STEP") is False


def test_env_get_dynamic_default_override(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_BENCH_THREADS", raising=False)
    assert env.get("MXNET_TPU_BENCH_THREADS", default=7) == 7
    monkeypatch.setenv("MXNET_TPU_BENCH_THREADS", "2")
    assert env.get("MXNET_TPU_BENCH_THREADS", default=7) == 2


def test_env_undeclared_read_raises():
    with pytest.raises(KeyError, match="not declared"):
        env.get("MXNET_TPU_NOT_A_THING")
    with pytest.raises(ValueError, match="declared twice"):
        env.declare("MXNET_TPU_FUSED_STEP", bool, False, "dup")


# ---------------------------------------------------------------------------
# runtime sanitizers
# ---------------------------------------------------------------------------

def test_sanitize_parsing(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_SANITIZE", raising=False)
    assert sanitizers.enabled_kinds() == frozenset()
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "transfer, donation")
    assert sanitizers.enabled_kinds() == {"transfer", "donation"}
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "all")
    assert sanitizers.enabled_kinds() == set(sanitizers.KINDS)
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "typo")
    with pytest.raises(SanitizerError, match="unknown sanitizer"):
        sanitizers.enabled_kinds()


def test_transfer_sanitizer_catches_implicit_transfer(monkeypatch):
    """Seeded violation: a numpy array leaking into a jitted dispatch
    under the armed guard raises; the explicit device_put path and an
    intentional_transfer window stay allowed."""
    import jax
    import jax.numpy as jnp

    monkeypatch.setenv("MXNET_TPU_SANITIZE", "transfer")
    fn = jax.jit(lambda a: a * 2)
    host = np.ones((4,), np.float32)
    with sanitizers.step_guard():
        with pytest.raises(Exception) as ei:
            fn(host).block_until_ready()  # graft: host-sync
        assert sanitizers.is_transfer_guard_error(ei.value)
        # explicit transfers are the sanctioned API and stay legal
        dev = jax.device_put(host)
        fn(dev).block_until_ready()  # graft: host-sync
        # ...and a reviewed window re-allows implicit ones
        with sanitizers.intentional_transfer():
            fn(host).block_until_ready()  # graft: host-sync
        # the guard is restored after the window closes
        with pytest.raises(Exception):
            fn(host)
    # disarmed: no guard at all
    monkeypatch.delenv("MXNET_TPU_SANITIZE", raising=False)
    with sanitizers.step_guard():
        fn(host).block_until_ready()  # graft: host-sync


def test_retrace_sanitizer_fires_after_warmup(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "retrace")
    san = RetraceSanitizer(warmup=2)
    san.check(1)   # warmup step 1 (first trace)
    san.check(2)   # warmup step 2 (shape-bucket retrace: allowed)
    san.check(2)   # steady state, no growth
    telemetry.reset()
    telemetry.enable()
    try:
        with pytest.raises(SanitizerError, match="retrace sanitizer"):
            san.check(3)
        assert telemetry.peek("sanitizer.trips") == 1
        assert telemetry.peek("sanitizer.trips.retrace") == 1
    finally:
        telemetry.reset()
        telemetry.disable()


def test_retrace_sanitizer_warmup_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_SANITIZE_WARMUP", "5")
    assert RetraceSanitizer().warmup == 5


def test_donation_sanitizer_passes_on_real_donation():
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda a: a + 1, donate_argnums=(0,))
    x = jnp.ones((8,), jnp.float32)
    y = fn(x)
    y.block_until_ready()  # graft: host-sync
    # CPU jax honors donation: the input buffer is consumed
    DonationSanitizer.check("test dispatch", [x])


def test_donation_sanitizer_raises_on_alive_buffer():
    """Seeded violation: claim a live buffer was donated."""
    import jax.numpy as jnp

    alive = jnp.ones((8,), jnp.float32)
    telemetry.reset()
    telemetry.enable()
    try:
        with pytest.raises(SanitizerError, match="donation sanitizer"):
            DonationSanitizer.check("test dispatch", [alive])
        assert telemetry.peek("sanitizer.trips.donation") == 1
    finally:
        telemetry.reset()
        telemetry.disable()


# ---------------------------------------------------------------------------
# fit()-level integration: the armed guard + the fused step
# ---------------------------------------------------------------------------

def _fused_fit(monkeypatch, callback=None, nbatches=3, num_epoch=1):
    from mxnet_tpu import symbol as sym
    from mxnet_tpu.module import Module

    monkeypatch.setenv("MXNET_TPU_FUSED_STEP", "1")
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    X = rng.randn(8 * nbatches, 6).astype(np.float32)
    y = rng.randint(0, 8, size=8 * nbatches).astype(np.float32)
    data = mx.io.NDArrayIter(X, y, batch_size=8)
    mod = Module(net, context=mx.cpu())
    mod.fit(data, num_epoch=num_epoch, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01},
            batch_end_callback=callback)
    assert mod._fused_step_active
    return mod


def test_fused_fit_clean_under_transfer_guard(monkeypatch):
    """The whole fused path — marshalling, dispatch, metric fold,
    metric.get() — runs under the armed guard without a single
    unsanctioned transfer."""
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "transfer")
    _fused_fit(monkeypatch)


def test_fused_fit_guard_catches_seeded_violation(monkeypatch):
    """A step-loop callback smuggling a host array into a device op
    fails the batch it happens on, and the trip is counted."""
    import jax

    monkeypatch.setenv("MXNET_TPU_SANITIZE", "transfer")
    jit_mul = jax.jit(lambda a: a * 2)

    def bad_callback(param):
        jit_mul(np.ones((2,), np.float32))

    telemetry.reset()
    telemetry.enable()
    try:
        with pytest.raises(Exception) as ei:
            _fused_fit(monkeypatch, callback=bad_callback)
        assert sanitizers.is_transfer_guard_error(ei.value)
        assert telemetry.peek("sanitizer.trips.transfer") == 1
    finally:
        telemetry.reset()
        telemetry.disable()


def test_fused_fit_retrace_sanitizer_end_to_end(monkeypatch):
    """Same-shape batches never retrace after warmup: a fused fit with
    the retrace sanitizer armed (warmup 1) completes."""
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "retrace")
    monkeypatch.setenv("MXNET_TPU_SANITIZE_WARMUP", "1")
    _fused_fit(monkeypatch, nbatches=4)


def test_fused_fit_donation_sanitizer_end_to_end(monkeypatch):
    """The fused step's donated dispatch really consumes its buffers —
    across an epoch boundary: the epoch-end get_params() host sync used
    to rebind the host param dict onto zero-copy borrows of the device
    buffers, pinning them against donation (NDArray.__setitem__ now
    copies host sources). One epoch would not catch that."""
    monkeypatch.setenv("MXNET_TPU_SANITIZE", "donation")
    _fused_fit(monkeypatch, num_epoch=3)


def test_trace_report_has_sanitizer_column():
    import importlib
    import sys

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        trace_report = importlib.import_module("trace_report")
        importlib.reload(trace_report)
        assert "sanitizer_trips" in trace_report.DELTA_COLS
        out = trace_report.render([
            {"step": 1, "latency_ms": 5.0,
             "deltas": {"sanitizer_trips": 2}}])
        assert "san_trips" in out
    finally:
        sys.path.remove(os.path.join(ROOT, "tools"))
