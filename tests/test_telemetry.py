"""Unified telemetry subsystem: counters/gauges/histograms, spans,
exporters, and the framework instrumentation that reports through them
(engine, io, executor, kvstore, profiler.StepTimer)."""
import json
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.base import MXNetError


@pytest.fixture(autouse=True)
def _isolated_telemetry():
    """Each test starts with a clean, enabled registry and leaves the
    process-global state the way the suite expects (disabled, empty)."""
    telemetry.reset()
    telemetry.enable()
    yield
    telemetry.reset()
    telemetry.disable()


# -- primitive semantics -------------------------------------------------

def test_counter_semantics():
    telemetry.inc("t.c")
    telemetry.inc("t.c", 5)
    assert telemetry.counter("t.c").value == 6
    # registry returns the same object per name
    assert telemetry.counter("t.c") is telemetry.counter("t.c")


def test_gauge_last_write_wins():
    telemetry.set_gauge("t.g", 1.0)
    telemetry.set_gauge("t.g", 42.5)
    assert telemetry.gauge("t.g").value == 42.5


def test_histogram_summary_and_bound():
    h = telemetry.histogram("t.h", capacity=8)
    for v in range(100):
        telemetry.observe("t.h", float(v))
    ex = h.export()
    assert ex["count"] == 100
    assert ex["sum"] == sum(range(100))
    assert ex["min"] == 0.0 and ex["max"] == 99.0
    # ring is bounded: percentile sample holds only the last `capacity`
    assert len(h._ring) == 8
    assert ex["p50"] >= 92.0  # drawn from the most recent 8 samples


def test_metric_type_clash_raises():
    telemetry.inc("t.kind")
    with pytest.raises(MXNetError):
        telemetry.gauge("t.kind")


def test_snapshot_nesting_and_collision():
    telemetry.inc("a.b.c", 3)
    telemetry.set_gauge("a.b", 1.5)  # both leaf and prefix
    snap = telemetry.snapshot()
    assert snap["a"]["b"]["c"] == 3
    assert snap["a"]["b"]["_value"] == 1.5


# -- disabled mode -------------------------------------------------------

def test_disabled_mode_records_nothing():
    telemetry.disable()
    telemetry.inc("off.c")
    telemetry.set_gauge("off.g", 1.0)
    telemetry.observe("off.h", 1.0)
    with telemetry.span("off.span"):
        pass
    assert telemetry.snapshot() == {}
    assert telemetry.spans() == []


# -- spans ---------------------------------------------------------------

def test_span_records_interval_and_histogram():
    with telemetry.span("work"):
        pass
    (name, tid, _t0, dur) = telemetry.spans()[-1]
    assert name == "work"
    assert tid == threading.get_ident()
    assert dur >= 0.0
    snap = telemetry.snapshot()
    assert snap["span"]["work_ms"]["count"] == 1


def test_write_chrome_trace(tmp_path):
    with telemetry.span("step"):
        pass
    with telemetry.span("step"):
        pass
    path = str(tmp_path / "trace.json")
    n = telemetry.write_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert n == len(evs)
    xs = [ev for ev in evs if ev["ph"] == "X"]
    assert len(xs) == 2
    for ev in xs:
        assert ev["name"] == "step"
        assert ev["ts"] > 0 and ev["dur"] >= 0
    # ph="M" metadata names this process's lanes for merged traces
    metas = [ev for ev in evs if ev["ph"] == "M"]
    names = {ev["name"] for ev in metas}
    assert "process_name" in names and "thread_name" in names
    import os
    assert all(ev["pid"] == os.getpid() for ev in metas)


def test_write_chrome_trace_extra_events(tmp_path):
    with telemetry.span("host"):
        pass
    extra = [{"name": "remote", "ph": "X", "pid": 999, "tid": 1,
              "ts": 1.0, "dur": 2.0}]
    path = str(tmp_path / "trace.json")
    telemetry.write_chrome_trace(path, extra_events=extra)
    with open(path) as f:
        doc = json.load(f)
    assert any(ev.get("name") == "remote" and ev.get("pid") == 999
               for ev in doc["traceEvents"])


# -- concurrency ---------------------------------------------------------

def test_concurrent_increments_from_engine_workers():
    """Increments racing from ThreadedEngine worker threads must not
    lose updates."""
    from mxnet_tpu import engine as eng

    e = eng.ThreadedEngine(num_workers=4)
    n_ops = 200
    for _ in range(n_ops):
        e.push(lambda: telemetry.inc("race.c"),
               const_vars=(), mutable_vars=(e.new_variable(),))
    e.wait_for_all()
    assert telemetry.counter("race.c").value == n_ops
    # the engine's own instrumentation counted every push and dispatch
    assert telemetry.counter("engine.push").value >= n_ops
    assert telemetry.counter("engine.dispatch").value >= n_ops
    # queue-wait histogram saw the same ops
    qw = telemetry.histogram("engine.queue_wait_ms")
    assert qw.count >= n_ops


# -- exporters -----------------------------------------------------------

def test_dump_jsonl_round_trip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    telemetry.inc("j.c", 7)
    telemetry.dump_jsonl(path)
    telemetry.inc("j.c", 1)
    telemetry.dump_jsonl(path, extra={"note": "second"})
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[0]["telemetry"]["j"]["c"] == 7
    assert recs[1]["telemetry"]["j"]["c"] == 8
    assert recs[1]["note"] == "second"
    assert all("ts" in r for r in recs)


def test_step_timer_feeds_telemetry(tmp_path):
    path = str(tmp_path / "steps.jsonl")
    timer = mx.profiler.StepTimer(jsonl_path=path)
    n_steps = 3
    for _ in range(n_steps):
        with timer:
            pass
    assert telemetry.counter("profiler.steps").value == n_steps
    assert telemetry.histogram("profiler.step_ms").count == n_steps
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert len(recs) == n_steps
    assert all("step_ms" in r for r in recs)


def test_speedometer_emits_gauge():
    class _Param:
        epoch, nbatch, eval_metric = 0, 0, None

    sp = mx.callback.Speedometer(batch_size=4, frequent=2)
    p = _Param()
    sp(p)            # init tick
    p.nbatch = 2
    sp(p)            # frequent boundary -> emits
    assert telemetry.gauge("train.samples_per_sec").value > 0
    assert telemetry.counter("train.batches").value == 2


# -- end to end ----------------------------------------------------------

def test_module_fit_populates_counters(tmp_path):
    """A small Module.fit must leave nonzero engine/io/executor counters
    and dump_jsonl must produce one parseable record per step."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    x = np.random.RandomState(0).rand(20, 4).astype(np.float32)
    y = (np.arange(20) % 8).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=4)
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"])
    path = str(tmp_path / "fit.jsonl")

    class _PerStep:
        def __call__(self, param):
            telemetry.dump_jsonl(path)

    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=_PerStep())
    snap = telemetry.snapshot()
    assert snap["engine"]["dispatch"] > 0
    assert snap["engine"]["push"] > 0
    assert snap["io"]["batches"] >= 5
    assert snap["executor"]["forward"] >= 5
    assert snap["executor"]["backward"] >= 5
    assert snap["executor"]["jit_build"] >= 1
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert len(recs) == 5  # 20 samples / batch 4 = 5 steps
    assert recs[-1]["telemetry"]["executor"]["forward"] >= 5


def test_kvstore_counters():
    kv = mx.kv.create("local")
    a = mx.nd.ones((4, 4))
    kv.init(0, a)
    kv.push(0, mx.nd.ones((4, 4)))
    out = mx.nd.zeros((4, 4))
    kv.pull(0, out=out)
    snap = telemetry.snapshot()
    assert snap["kvstore"]["push"] >= 1
    assert snap["kvstore"]["pull"] >= 1
    assert snap["kvstore"]["push_bytes"] >= 4 * 4 * 4
    assert snap["kvstore"]["pull_bytes"] >= 4 * 4 * 4


# -- bucketed export + fleet merge (obswatch federation core) ------------

def test_histogram_bucket_export_cumulative():
    h = telemetry.Histogram("t.ms", bounds=(1.0, 5.0, 10.0))
    for v in (0.5, 0.7, 3.0, 20.0):
        h.observe(v)
    ex = h.export()
    assert ex["count"] == 4
    assert ex["buckets"]["bounds"] == [1.0, 5.0, 10.0]
    # cumulative le counts; the +Inf bucket is implicit (== count)
    assert ex["buckets"]["counts"] == [2, 3, 3]
    empty = telemetry.Histogram("t.empty", bounds=(1.0,)).export()
    assert empty == {"count": 0,
                     "buckets": {"bounds": [1.0], "counts": [0]}}


def test_bucket_quantile_interpolation():
    buckets = {"bounds": [10.0, 20.0], "counts": [10, 20]}
    # rank 10 of 20 sits at the top of the first bucket
    assert telemetry.bucket_quantile(buckets, 20, 0.5) == 10.0
    # rank 15 is halfway through the 10..20 bucket
    assert telemetry.bucket_quantile(buckets, 20, 0.75) == \
        pytest.approx(15.0)
    # ranks past the last finite bound clamp to the observed max
    assert telemetry.bucket_quantile(
        {"bounds": [10.0], "counts": [0]}, 5, 0.5, hi=42.0) == 42.0
    assert telemetry.bucket_quantile({}, 0, 0.5) is None


def test_merge_snapshots_sums_and_recurses():
    a = {"engine": {"push": 3, "dispatch": 1}, "io": {"wait_ms": 1.5}}
    b = {"engine": {"push": 4}, "io": {"wait_ms": 0.5}, "extra": 1}
    merged = telemetry.merge_snapshots([a, b])
    assert merged["engine"] == {"push": 7, "dispatch": 1}
    assert merged["io"]["wait_ms"] == pytest.approx(2.0)
    assert merged["extra"] == 1
    # inputs are never mutated
    assert a["engine"]["push"] == 3 and b["engine"]["push"] == 4


def test_merge_snapshots_histograms_bucket_wise():
    ha = telemetry.Histogram("a.ms", bounds=(1.0, 10.0))
    hb = telemetry.Histogram("b.ms", bounds=(1.0, 10.0))
    for v in (0.5, 2.0):
        ha.observe(v)
    for v in (3.0, 50.0):
        hb.observe(v)
    merged = telemetry.merge_snapshots(
        [{"lat": ha.export(include_sample=True)},
         {"lat": hb.export(include_sample=True)}])["lat"]
    assert merged["count"] == 4
    assert merged["buckets"]["counts"] == [1, 3]
    assert merged["min"] == 0.5 and merged["max"] == 50.0
    assert merged["sum"] == pytest.approx(55.5)
    # exact percentiles from the concatenated samples
    assert merged["sample"] == [0.5, 2.0, 3.0, 50.0]
    assert merged["p50"] == 3.0
    # without samples, percentiles interpolate from the merged buckets
    no_sample = telemetry.merge_snapshots(
        [{"lat": ha.export()}, {"lat": hb.export()}])["lat"]
    assert "sample" not in no_sample
    assert 1.0 <= no_sample["p50"] <= 10.0


def test_merge_snapshots_conflicting_bounds_raise():
    ha = telemetry.Histogram("a.ms", bounds=(1.0, 10.0))
    hb = telemetry.Histogram("b.ms", bounds=(1.0, 5.0))
    ha.observe(2.0)
    hb.observe(2.0)
    with pytest.raises(MXNetError, match="conflicting"):
        telemetry.merge_snapshots([{"lat": ha.export()},
                                   {"lat": hb.export()}])


def test_merge_snapshots_kind_mismatch_raises():
    h = telemetry.Histogram("a.ms")
    h.observe(1.0)
    with pytest.raises(MXNetError):
        telemetry.merge_snapshots([{"x": 1}, {"x": h.export()}])
    with pytest.raises(MXNetError):
        telemetry.merge_snapshots([{"x": 1}, {"x": "one"}])
