/* Execution gate for the R io-iterator bindings (round-4 verdict #3):
 * drives the exact .Call sequence mx.io.ImageRecordIter / mx.io.MNISTIter
 * / mx.io.CSVIter (R-package/R/io.R) and mx.model.FeedForward.create
 * (R/model.R, iterator form) perform — mxr_io_create with string kwargs,
 * before_first / next / value per batch, batches fed to a LeNet-style
 * executor trained with the optimizer.R SGD math. No R interpreter
 * exists in this image, so tests/r_shim.c supplies the R C API
 * (reference parity: R-package/R/mxnet_generated.R:480-610 creators,
 * exercised by the reference's R testthat CI).
 *
 * argv: 1=path.rec  2=data.csv  3=mnist-images  4=mnist-labels
 * Prints "final_acc=<v>"; the pytest wrapper gates >= 0.9.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "Rinternals.h"

SEXP mxr_io_create(SEXP name, SEXP keys, SEXP vals);
SEXP mxr_io_before_first(SEXP it);
SEXP mxr_io_next(SEXP it);
SEXP mxr_io_value(SEXP it);
SEXP mxr_sym_variable(SEXP name);
SEXP mxr_sym_create_atomic(SEXP opname, SEXP keys, SEXP vals);
SEXP mxr_sym_compose(SEXP ptr, SEXP name, SEXP keys, SEXP args);
SEXP mxr_sym_infer_shape(SEXP ptr, SEXP keys, SEXP ind, SEXP data);
SEXP mxr_sym_list_arguments(SEXP ptr);
SEXP mxr_exec_simple_bind(SEXP sym, SEXP dev_type, SEXP dev_id, SEXP keys,
                          SEXP ind, SEXP data, SEXP for_training);
SEXP mxr_exec_set_arg(SEXP ptr, SEXP name, SEXP values);
SEXP mxr_exec_forward(SEXP ptr, SEXP is_train);
SEXP mxr_exec_backward(SEXP ptr);
SEXP mxr_exec_get_output(SEXP ptr, SEXP index, SEXP size);
SEXP mxr_exec_get_grad(SEXP ptr, SEXP name, SEXP size);
SEXP mxr_random_seed(SEXP seed);

#define BATCH 8
#define IMG 12
#define NCLASS 2
#define ROUNDS 10

static SEXP ints(int n, const int *v) {
  SEXP s = Rf_allocVector(INTSXP, n);
  for (int i = 0; i < n; ++i) INTEGER(s)[i] = v[i];
  return s;
}
static SEXP int1(int v) { return ints(1, &v); }
static SEXP reals(R_xlen_t n, const double *v) {
  SEXP s = Rf_allocVector(REALSXP, n);
  for (R_xlen_t i = 0; i < n; ++i) REAL(s)[i] = v[i];
  return s;
}
static SEXP strs(int n, const char **v) {
  SEXP s = Rf_allocVector(STRSXP, n);
  for (int i = 0; i < n; ++i) SET_STRING_ELT(s, i, Rf_mkChar(v[i]));
  return s;
}
static SEXP atomic_op(const char *op, SEXP input, const char *name,
                      const char **pkeys, const char **pvals, int np) {
  SEXP h = mxr_sym_create_atomic(Rf_mkString(op), strs(np, pkeys),
                                 strs(np, pvals));
  const char *inkeys[] = {"data"};
  SEXP args = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(args, 0, input);
  mxr_sym_compose(h, Rf_mkString(name), strs(1, inkeys), args);
  return h;
}
static double frand(unsigned *seed) {
  *seed ^= *seed << 13;
  *seed ^= *seed >> 17;
  *seed ^= *seed << 5;
  return (double)(*seed % 1000003) / 1000003.0;
}
static long elems(SEXP arr) {
  SEXP dim = Rf_getAttrib(arr, Rf_install("mx.dim"));
  long n = 1;
  for (int i = 0; i < Rf_length(dim); ++i) n *= INTEGER(dim)[i];
  return n;
}

int main(int argc, char **argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s rec csv mnist-img mnist-lbl\n", argv[0]);
    return 2;
  }
  mxr_random_seed(int1(7));

  /* ---- mx.io.ImageRecordIter(path.imgrec=..., data.shape=c(3,12,12),
   * batch.size=8, shuffle=TRUE) — kwargs as the R wrapper stringifies
   * them ---- */
  const char *ik[] = {"path_imgrec", "data_shape", "batch_size",
                      "shuffle", "scale", "mean_r", "mean_g", "mean_b"};
  char shape_str[64];
  snprintf(shape_str, sizeof shape_str, "(3,%d,%d)", IMG, IMG);
  /* centered pixels ((x-127.5)/127.5), the R vignette recipe */
  const char *iv[] = {argv[1], shape_str, "8", "True", "0.00784313725",
                      "127.5", "127.5", "127.5"};
  SEXP rec_it = mxr_io_create(Rf_mkString("ImageRecordIter"),
                              strs(8, ik), strs(8, iv));

  /* ---- LeNet-style net: conv -> relu -> flatten -> FC(2) -> softmax */
  SEXP data = mxr_sym_variable(Rf_mkString("data"));
  const char *k_conv[] = {"num_filter", "kernel"};
  const char *v_conv[] = {"4", "(3, 3)"};
  SEXP conv = atomic_op("Convolution", data, "conv1", k_conv, v_conv, 2);
  const char *k_act[] = {"act_type"};
  const char *v_act[] = {"relu"};
  SEXP act = atomic_op("Activation", conv, "act1", k_act, v_act, 1);
  SEXP flat = atomic_op("Flatten", act, "flat", NULL, NULL, 0);
  const char *k_hid[] = {"num_hidden"};
  const char *v_hid[] = {"2"};
  SEXP fc = atomic_op("FullyConnected", flat, "fc", k_hid, v_hid, 1);
  SEXP net = atomic_op("SoftmaxOutput", fc, "softmax", NULL, NULL, 0);

  const char *shape_keys[] = {"data"};
  int ind[] = {0, 4};
  int sdata[] = {BATCH, 3, IMG, IMG};
  SEXP shapes = mxr_sym_infer_shape(net, strs(1, shape_keys),
                                    ints(2, ind), ints(4, sdata));
  SEXP arg_shapes = VECTOR_ELT(shapes, 0);
  SEXP arg_names = mxr_sym_list_arguments(net);
  int nargs = Rf_length(arg_names);
  SEXP exec = mxr_exec_simple_bind(net, int1(1), int1(0),
                                   strs(1, shape_keys), ints(2, ind),
                                   ints(4, sdata), int1(1));

  unsigned seed = 42;
  double *params[16], *moms[16];
  long psize[16];
  for (int i = 0; i < nargs; ++i) {
    const char *nm = CHAR(STRING_ELT(arg_names, i));
    SEXP shp = VECTOR_ELT(arg_shapes, i);
    long n = 1;
    for (int j = 0; j < Rf_length(shp); ++j) n *= INTEGER(shp)[j];
    psize[i] = n;
    params[i] = calloc(n, sizeof(double));
    moms[i] = calloc(n, sizeof(double));
    if (strstr(nm, "weight"))
      for (long j = 0; j < n; ++j)
        params[i][j] = (frand(&seed) - 0.5) * 0.2;
    if (strcmp(nm, "data") && strcmp(nm, "softmax_label"))
      mxr_exec_set_arg(exec, Rf_mkString(nm), reals(n, params[i]));
  }

  const double lr = 0.05, momentum = 0.9;
  double acc = 0.0;
  for (int round = 0; round < ROUNDS; ++round) {
    int correct = 0, seen = 0;
    mxr_io_before_first(rec_it);
    while (Rf_asInteger(mxr_io_next(rec_it))) {
      SEXP v = mxr_io_value(rec_it);
      SEXP bd = VECTOR_ELT(v, 0);           /* C-order (B,3,IMG,IMG) */
      SEXP bl = VECTOR_ELT(v, 1);
      if (elems(bd) != BATCH * 3 * IMG * IMG) {
        fprintf(stderr, "bad batch size %ld\n", elems(bd));
        return 1;
      }
      mxr_exec_set_arg(exec, Rf_mkString("data"), bd);
      mxr_exec_set_arg(exec, Rf_mkString("softmax_label"), bl);
      mxr_exec_forward(exec, int1(1));
      mxr_exec_backward(exec);
      for (int i = 0; i < nargs; ++i) {
        const char *nm = CHAR(STRING_ELT(arg_names, i));
        if (!strcmp(nm, "data") || !strcmp(nm, "softmax_label")) continue;
        SEXP g = mxr_exec_get_grad(exec, Rf_mkString(nm),
                                   int1((int)psize[i]));
        for (long j = 0; j < psize[i]; ++j) {
          moms[i][j] = momentum * moms[i][j] - lr * REAL(g)[j];
          params[i][j] += moms[i][j];
        }
        mxr_exec_set_arg(exec, Rf_mkString(nm),
                         reals(psize[i], params[i]));
      }
      SEXP out = mxr_exec_get_output(exec, int1(0),
                                     int1(BATCH * NCLASS));
      for (int b = 0; b < BATCH; ++b) {
        int guess = REAL(out)[b * NCLASS] > REAL(out)[b * NCLASS + 1]
                        ? 0 : 1;
        correct += (guess == (int)REAL(bl)[b]);
        seen += 1;
      }
    }
    acc = (double)correct / seen;
  }

  /* ---- mx.io.CSVIter: exact read-back of known rows ---- */
  const char *ck[] = {"data_csv", "data_shape", "batch_size"};
  const char *cv[] = {argv[2], "(3,)", "2"};
  SEXP csv_it = mxr_io_create(Rf_mkString("CSVIter"), strs(3, ck),
                              strs(3, cv));
  mxr_io_before_first(csv_it);
  if (!Rf_asInteger(mxr_io_next(csv_it))) return 1;
  SEXP cval = mxr_io_value(csv_it);
  SEXP cdat = VECTOR_ELT(cval, 0);
  /* wrapper wrote rows (r*3+c)*0.5 */
  for (int i = 0; i < 6; ++i) {
    double want = i * 0.5;
    if (REAL(cdat)[i] < want - 1e-5 || REAL(cdat)[i] > want + 1e-5) {
      fprintf(stderr, "csv[%d]=%f want %f\n", i, REAL(cdat)[i], want);
      return 1;
    }
  }

  /* ---- mx.io.MNISTIter: idx files parse, shapes and labels sane ---- */
  const char *mk[] = {"image", "label", "batch_size", "shuffle"};
  const char *mv[] = {argv[3], argv[4], "4", "False"};
  SEXP mn_it = mxr_io_create(Rf_mkString("MNISTIter"), strs(4, mk),
                             strs(4, mv));
  mxr_io_before_first(mn_it);
  if (!Rf_asInteger(mxr_io_next(mn_it))) return 1;
  SEXP mval = mxr_io_value(mn_it);
  if (elems(VECTOR_ELT(mval, 0)) != 4 * 1 * 28 * 28) {
    fprintf(stderr, "mnist batch elems %ld\n",
            elems(VECTOR_ELT(mval, 0)));
    return 1;
  }
  for (int i = 0; i < 4; ++i) {
    double l = REAL(VECTOR_ELT(mval, 1))[i];
    if (l < 0 || l >= 10) { fprintf(stderr, "mnist label %f\n", l);
                            return 1; }
  }

  printf("final_acc=%f\n", acc);
  return acc >= 0.9 ? 0 : 1;
}
