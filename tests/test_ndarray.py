"""NDArray tests (reference tests/python/unittest/test_ndarray.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import ndarray as nd


def test_ndarray_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert np.all(a.asnumpy() == 0)
    b = nd.ones((2,), dtype=np.int32)
    assert b.dtype == np.int32
    c = nd.full((2, 2), 7.5)
    assert np.all(c.asnumpy() == 7.5)
    d = nd.array([[1, 2], [3, 4]])
    assert d.dtype == np.float32
    assert d.asnumpy().tolist() == [[1, 2], [3, 4]]


def test_ndarray_elementwise():
    rng = np.random.RandomState(0)
    for _ in range(3):
        a_np = rng.randn(4, 5).astype(np.float32)
        b_np = rng.rand(4, 5).astype(np.float32) + 0.5
        a, b = nd.array(a_np), nd.array(b_np)
        np.testing.assert_allclose((a + b).asnumpy(), a_np + b_np, rtol=1e-5)
        np.testing.assert_allclose((a - b).asnumpy(), a_np - b_np, rtol=1e-5)
        np.testing.assert_allclose((a * b).asnumpy(), a_np * b_np, rtol=1e-5)
        np.testing.assert_allclose((a / b).asnumpy(), a_np / b_np, rtol=1e-5)
        np.testing.assert_allclose((a + 3).asnumpy(), a_np + 3, rtol=1e-5)
        np.testing.assert_allclose((2 - a).asnumpy(), 2 - a_np, rtol=1e-5)
        np.testing.assert_allclose((-a).asnumpy(), -a_np, rtol=1e-5)


def test_ndarray_inplace():
    a = nd.ones((2, 3))
    a += 2
    np.testing.assert_allclose(a.asnumpy(), np.full((2, 3), 3.0))
    a *= 2
    np.testing.assert_allclose(a.asnumpy(), np.full((2, 3), 6.0))
    b = nd.ones((2, 3))
    a -= b
    np.testing.assert_allclose(a.asnumpy(), np.full((2, 3), 5.0))


def test_ndarray_setitem_getitem():
    a = nd.zeros((4, 4))
    a[:] = 5
    assert np.all(a.asnumpy() == 5)
    a[1:3] = 1
    expected = np.full((4, 4), 5.0)
    expected[1:3] = 1
    np.testing.assert_allclose(a.asnumpy(), expected)
    sl = a[1:3]
    assert sl.shape == (2, 4)
    assert np.all(sl.asnumpy() == 1)
    np_b = np.arange(16).reshape(4, 4).astype(np.float32)
    b = nd.array(np_b)
    np.testing.assert_allclose(b[2].asnumpy(), np_b[2])


def test_ndarray_reshape_transpose():
    a_np = np.arange(24).reshape(2, 3, 4).astype(np.float32)
    a = nd.array(a_np)
    np.testing.assert_allclose(a.reshape((6, 4)).asnumpy(),
                               a_np.reshape(6, 4))
    np.testing.assert_allclose(a.reshape((-1, 4)).asnumpy(),
                               a_np.reshape(-1, 4))
    np.testing.assert_allclose(nd.transpose(a).asnumpy(), a_np.T)
    np.testing.assert_allclose(a.T.asnumpy(), a_np.T)


def test_ndarray_functions():
    a_np = np.random.rand(3, 4).astype(np.float32) + 0.1
    a = nd.array(a_np)
    np.testing.assert_allclose(nd.exp(a).asnumpy(), np.exp(a_np), rtol=1e-5)
    np.testing.assert_allclose(nd.log(a).asnumpy(), np.log(a_np), rtol=1e-5)
    np.testing.assert_allclose(nd.sqrt(a).asnumpy(), np.sqrt(a_np), rtol=1e-5)
    np.testing.assert_allclose(nd.square(a).asnumpy(), a_np ** 2, rtol=1e-5)
    np.testing.assert_allclose(nd.sum(a).asnumpy(), [a_np.sum()], rtol=1e-5)
    np.testing.assert_allclose(nd.max(a).asnumpy(), [a_np.max()], rtol=1e-5)
    np.testing.assert_allclose(
        nd.norm(a).asnumpy(), [np.sqrt((a_np ** 2).sum())], rtol=1e-5)
    b_np = np.random.rand(4, 5).astype(np.float32)
    b = nd.array(b_np)
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(), a_np.dot(b_np),
                               rtol=1e-4)
    np.testing.assert_allclose(nd.clip(a, 0.2, 0.8).asnumpy(),
                               np.clip(a_np, 0.2, 0.8), rtol=1e-6)
    np.testing.assert_allclose(nd.maximum(a, 0.5).asnumpy(),
                               np.maximum(a_np, 0.5), rtol=1e-6)


def test_ndarray_onehot():
    idx = nd.array([0, 2, 1])
    out = nd.zeros((3, 3))
    nd.onehot_encode(idx, out)
    np.testing.assert_allclose(out.asnumpy(), np.eye(3)[[0, 2, 1]])
    picked = nd.choose_element_0index(out, idx)
    np.testing.assert_allclose(picked.asnumpy(), [1, 1, 1])


def test_ndarray_copy():
    a = nd.array(np.random.rand(3, 3).astype(np.float32))
    b = a.copy()
    b += 1
    assert not np.allclose(a.asnumpy(), b.asnumpy())
    c = nd.zeros((3, 3))
    a.copyto(c)
    np.testing.assert_allclose(a.asnumpy(), c.asnumpy())
    d = a.as_in_context(mx.cpu(1))
    assert d.context == mx.cpu(1)
    np.testing.assert_allclose(a.asnumpy(), d.asnumpy())


def test_ndarray_saveload(tmp_path):
    fname = str(tmp_path / "arrays.bin")
    arrays = [nd.array(np.random.rand(3, 4).astype(np.float32)),
              nd.array(np.arange(5).astype(np.int32))]
    nd.save(fname, arrays)
    loaded = nd.load(fname)
    assert len(loaded) == 2
    for orig, back in zip(arrays, loaded):
        np.testing.assert_allclose(orig.asnumpy(), back.asnumpy())
        assert orig.dtype == back.dtype
    d = {"weight": arrays[0], "idx": arrays[1]}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded.keys()) == {"weight", "idx"}
    np.testing.assert_allclose(loaded["weight"].asnumpy(),
                               arrays[0].asnumpy())


def test_ndarray_concatenate():
    a = nd.array(np.ones((2, 3), dtype=np.float32))
    b = nd.array(np.zeros((3, 3), dtype=np.float32))
    c = nd.concatenate([a, b], axis=0)
    assert c.shape == (5, 3)
    np.testing.assert_allclose(c.asnumpy()[:2], 1)
    np.testing.assert_allclose(c.asnumpy()[2:], 0)


def test_ndarray_waitall():
    a = nd.ones((100, 100))
    for _ in range(10):
        a = a * 1.0001
    nd.waitall()
    assert a.asnumpy().shape == (100, 100)


def test_ndarray_64bit_dtype_honesty():
    """Requested 64-bit dtypes are honored (x64 on) or rejected loudly
    — never silently narrowed (the reference's mshadow dtype tables
    honor them; jax with x64 off would truncate)."""
    import subprocess
    import sys

    from mxnet_tpu.base import MXNetError

    for ctor in (lambda: nd.zeros((2,), dtype=np.int64),
                 lambda: nd.ones((2,), dtype=np.float64),
                 lambda: nd.full((2,), 3, dtype=np.uint64),
                 lambda: nd.arange(0, 4, dtype=np.int64),
                 lambda: nd.array([1, 2], dtype=np.float64),
                 lambda: nd.ones((2,)).astype(np.int64)):
        with pytest.raises(MXNetError, match="x64"):
            ctor()

    # implicit python-int/float sources still take the reference default
    # (float32, mx_real_t) without erroring
    assert nd.array([1, 2, 3]).dtype == np.float32

    # with x64 enabled the request is honored end-to-end
    code = (
        "import jax; jax.config.update('jax_enable_x64', True)\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from mxnet_tpu import ndarray as nd\n"
        "a = nd.zeros((2,), dtype=np.int64)\n"
        "assert a.dtype == np.int64, a.dtype\n"
        "b = nd.array([1.5, 2.5], dtype=np.float64)\n"
        "assert b.dtype == np.float64, b.dtype\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]


def test_load_64bit_checkpoint_narrows_with_warning():
    """nd.load of a 64-bit container (saved under x64, or written by the
    reference) must not hard-fail when x64 is off: it narrows loudly."""
    import io as _io
    import subprocess
    import sys
    import warnings

    # produce a float64+int64 container in an x64 subprocess
    path = "/tmp/x64_container.nd"
    code = (
        "import jax; jax.config.update('jax_enable_x64', True)\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from mxnet_tpu import ndarray as nd\n"
        "nd.save(%r, {'w': nd.array(np.array([1.5, 2.5]), "
        "dtype=np.float64), 'i': nd.array(np.array([3, 2**40]), "
        "dtype=np.int64)})\n" % path)
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        loaded = nd.load(path)
    assert loaded["w"].dtype == np.float32
    assert loaded["i"].dtype == np.int32
    assert any("narrowing" in str(x.message) for x in w)
    np.testing.assert_allclose(loaded["w"].asnumpy(), [1.5, 2.5])


def test_array_implicit_uint64_takes_default():
    """Implicit uint64 sources take the reference float32 default instead
    of reaching jax's silent uint32 truncation."""
    a = nd.array(np.array([2 ** 40, 1], dtype=np.uint64))
    assert a.dtype == np.float32
    np.testing.assert_allclose(a.asnumpy(), [float(2 ** 40), 1.0])


def test_shares_buffer_tristate():
    """_shares_buffer: True/False only when VERIFIED via buffer
    pointers; None when unverifiable (callers must copy defensively)."""
    import jax

    from mxnet_tpu.ndarray import _shares_buffer

    a = mx.nd.ones((2, 2))._data
    b = mx.nd.ones((2, 2))._data
    assert _shares_buffer(a, a) is True
    assert _shares_buffer(a, b) is False
    # device_put onto the same device may alias: whatever it returns,
    # the answer must be verified, never None, on a single local device
    c = jax.device_put(a, list(a.devices())[0])
    assert _shares_buffer(a, c) in (True, False)

    class _NoPointer:
        """Array-like with neither unsafe_buffer_pointer nor shards."""

    assert _shares_buffer(_NoPointer(), _NoPointer()) is None


def test_shares_buffer_sharded_via_addressable_shards():
    """Arrays whose only pointer access is per-shard (sharded arrays:
    unsafe_buffer_pointer raises) are verified by shard-pointer
    intersection instead of answering False blindly."""
    from mxnet_tpu.ndarray import _shares_buffer

    class _Shard:
        def __init__(self, ptr):
            self.data = self
            self._ptr = ptr

        def unsafe_buffer_pointer(self):
            return self._ptr

    class _Sharded:
        def __init__(self, ptrs):
            self.addressable_shards = [_Shard(p) for p in ptrs]

        def unsafe_buffer_pointer(self):
            raise RuntimeError("sharded array has no single buffer")

    assert _shares_buffer(_Sharded([1, 2]), _Sharded([2, 3])) is True
    assert _shares_buffer(_Sharded([1, 2]), _Sharded([3, 4])) is False
    assert _shares_buffer(_Sharded([]), _Sharded([1])) is None


def test_copyto_defensive_on_unverifiable_aliasing(monkeypatch):
    """When aliasing cannot be verified, copyto must still produce a
    buffer that survives donation of the source — i.e. it copies."""
    from mxnet_tpu import ndarray as ndmod

    monkeypatch.setattr(ndmod, "_shares_buffer", lambda a, b: None)
    src = mx.nd.array(np.arange(4, dtype=np.float32))
    dst = mx.nd.zeros((4,))
    src.copyto(dst)
    assert dst._data is not src._data
    np.testing.assert_array_equal(dst.asnumpy(),
                                  np.arange(4, dtype=np.float32))
