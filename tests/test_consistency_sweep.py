"""Per-op fp16-vs-fp32 consistency sweep.

The reference's GPU tier (tests/python/gpu/test_operator_gpu.py:16-50)
re-ran the operator suite through check_consistency over ctx x dtype
configs. Here the sweep axis is dtype: every symbol below binds once in
fp32 and once with fp16 inputs (type_dict), comparing outputs and
gradients under per-dtype tolerance."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_consistency

V = mx.sym.Variable


def _two(**shapes):
    return [
        {"ctx": mx.cpu(), **shapes},
        {"ctx": mx.cpu(), **shapes, "type_dict": {"data": np.float16}},
    ]


# (name, symbol builder, shapes dict, grad_req)
SWEEP = [
    ("fullyconnected",
     lambda: mx.sym.FullyConnected(data=V("data"), num_hidden=8, name="fc"),
     {"data": (4, 6)}, "write"),
    ("convolution",
     lambda: mx.sym.Convolution(data=V("data"), kernel=(3, 3), num_filter=4,
                                pad=(1, 1), name="conv"),
     {"data": (2, 3, 8, 8)}, "write"),
    ("convolution_grouped",
     lambda: mx.sym.Convolution(data=V("data"), kernel=(3, 3), num_filter=4,
                                num_group=2, name="conv"),
     {"data": (2, 4, 7, 7)}, "write"),
    ("convolution_1x1_stride2",
     lambda: mx.sym.Convolution(data=V("data"), kernel=(1, 1), num_filter=8,
                                stride=(2, 2), name="conv"),
     {"data": (2, 4, 8, 8)}, "write"),
    ("deconvolution",
     lambda: mx.sym.Deconvolution(data=V("data"), kernel=(3, 3),
                                  num_filter=4, name="dc"),
     {"data": (2, 3, 5, 5)}, "write"),
    ("pooling_max",
     lambda: mx.sym.Pooling(data=V("data"), kernel=(2, 2), stride=(2, 2),
                            pool_type="max"),
     {"data": (2, 3, 8, 8)}, "write"),
    ("pooling_avg",
     lambda: mx.sym.Pooling(data=V("data"), kernel=(3, 3), stride=(2, 2),
                            pool_type="avg"),
     {"data": (2, 3, 9, 9)}, "write"),
    ("pooling_global",
     lambda: mx.sym.Pooling(data=V("data"), kernel=(1, 1),
                            global_pool=True, pool_type="max"),
     {"data": (2, 3, 6, 6)}, "write"),
    ("batchnorm",
     lambda: mx.sym.BatchNorm(data=V("data"), fix_gamma=False, name="bn"),
     {"data": (4, 3, 6, 6)}, "write"),
    ("activation_relu",
     lambda: mx.sym.Activation(data=V("data"), act_type="relu"),
     {"data": (4, 8)}, "write"),
    ("activation_tanh",
     lambda: mx.sym.Activation(data=V("data"), act_type="tanh"),
     {"data": (4, 8)}, "write"),
    ("leakyrelu",
     lambda: mx.sym.LeakyReLU(data=V("data"), act_type="leaky", slope=0.1),
     {"data": (4, 8)}, "write"),
    ("softmax_activation",
     lambda: mx.sym.SoftmaxActivation(data=V("data")),
     {"data": (4, 10)}, "write"),
    ("lrn",
     lambda: mx.sym.LRN(data=V("data"), nsize=3),
     {"data": (2, 4, 5, 5)}, "write"),
    ("dropout_eval",
     lambda: mx.sym.Dropout(data=V("data"), p=0.5),
     {"data": (4, 8)}, "null"),
    ("flatten_reshape",
     lambda: mx.sym.Reshape(mx.sym.Flatten(data=V("data")), shape=(0, 4, -1)),
     {"data": (2, 4, 3, 2)}, "write"),
    ("transpose",
     lambda: mx.sym.transpose(V("data"), axes=(0, 2, 1)),
     {"data": (2, 3, 4)}, "write"),
    ("swapaxis",
     lambda: mx.sym.SwapAxis(data=V("data"), dim1=1, dim2=2),
     {"data": (2, 3, 4)}, "write"),
    ("slice_axis",
     lambda: mx.sym.slice_axis(V("data"), axis=1, begin=1, end=3),
     {"data": (2, 4, 3)}, "write"),
    ("flip",
     lambda: mx.sym.Flip(data=V("data"), axis=1),
     {"data": (2, 4, 3)}, "write"),
    ("sum_axis",
     lambda: mx.sym.sum(V("data"), axis=1),
     {"data": (3, 4, 5)}, "write"),
    ("max_axis",
     lambda: mx.sym.max(V("data"), axis=2),
     {"data": (3, 4, 5)}, "write"),
    ("broadcast_axis",
     lambda: mx.sym.broadcast_axis(V("data"), axis=1, size=4),
     {"data": (3, 1, 5)}, "write"),
    ("elemwise_chain",
     lambda: (V("data") * 2 + 1) / 3 - 0.5,
     {"data": (4, 5)}, "write"),
    ("unary_chain",
     lambda: mx.sym.exp(mx.sym.abs(V("data")) * 0.1),
     {"data": (4, 5)}, "write"),
    ("sqrt_square",
     lambda: mx.sym.sqrt(mx.sym.square(V("data")) + 1.0),
     {"data": (4, 5)}, "write"),
    ("embedding",
     lambda: mx.sym.Embedding(data=V("data"), input_dim=10, output_dim=4,
                              name="emb"),
     {"data": (6,)}, "null"),
    ("upsampling_nearest",
     lambda: mx.sym.UpSampling(V("data"), scale=2, sample_type="nearest",
                               num_args=1),
     {"data": (1, 2, 4, 4)}, "write"),
    ("crop_spatial",
     lambda: mx.sym.Crop(V("data"), num_args=1, h_w=(4, 4), offset=(1, 1)),
     {"data": (1, 2, 6, 6)}, "write"),
    ("smooth_l1",
     lambda: mx.sym.smooth_l1(V("data"), scalar=1.0),
     {"data": (4, 5)}, "write"),
    ("l2normalization",
     lambda: mx.sym.L2Normalization(data=V("data")),
     {"data": (4, 6)}, "write"),
    ("fc_relu_fc_stack",
     lambda: mx.sym.FullyConnected(
         data=mx.sym.Activation(
             data=mx.sym.FullyConnected(data=V("data"), num_hidden=8,
                                        name="fc1"),
             act_type="relu"),
         num_hidden=3, name="fc2"),
     {"data": (4, 6)}, "write"),
]


@pytest.mark.parametrize("name,build,shapes,grad_req",
                         SWEEP, ids=[c[0] for c in SWEEP])
def test_fp16_fp32_consistency(name, build, shapes, grad_req):
    check_consistency(build(), _two(**shapes), grad_req=grad_req)
