"""Cross-dtype operator consistency (the reference's GPU-vs-CPU
validation tier: tests/python/gpu/test_operator_gpu.py re-ran every op
through check_consistency across ctx x dtype configs with per-dtype
tolerances). Here the axes are dtype (fp16/fp32) and, when the session
has an accelerator, backend — exercised per core op family.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_consistency


def _cfgs(**shapes):
    return [
        {"ctx": mx.cpu(), **shapes},
        {"ctx": mx.cpu(), **shapes,
         "type_dict": {"data": np.float16}},
    ]


def test_consistency_fullyconnected():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc")
    check_consistency(net, _cfgs(data=(4, 6)))


def test_consistency_convolution_pooling():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=4,
                             pad=(1, 1), name="conv")
    net = mx.sym.Pooling(data=net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    check_consistency(net, _cfgs(data=(2, 3, 8, 8)))


def test_consistency_activation_family():
    for act in ("relu", "sigmoid", "tanh", "softrelu"):
        data = mx.sym.Variable("data")
        net = mx.sym.Activation(data=data, act_type=act)
        check_consistency(net, _cfgs(data=(4, 8)))


def test_consistency_batchnorm():
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(data=data, fix_gamma=False, name="bn")
    # BN in fp16 accumulates stats with fp16 inputs; loosen nothing —
    # stats are computed in >= f32 internally (ops/nn.py)
    check_consistency(net, _cfgs(data=(4, 3, 6, 6)))


def test_consistency_softmax_and_lrn():
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxActivation(data=data)
    check_consistency(net, _cfgs(data=(4, 10)))
    net = mx.sym.LRN(data=data, nsize=3)
    check_consistency(net, _cfgs(data=(2, 4, 5, 5)))


def test_consistency_elementwise_reduce():
    data = mx.sym.Variable("data")
    net = mx.sym.sum(data=data, axis=1)
    check_consistency(net, _cfgs(data=(3, 4, 5)), grad_req="null")


@pytest.mark.skipif(
    __import__("jax").default_backend() == "cpu",
    reason="needs an accelerator backend to compare against cpu")
def test_consistency_cross_backend():
    # the literal cuDNN-vs-CPU analogue: accelerator vs CPU backend
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, kernel=(3, 3), num_filter=4,
                             name="conv")
    net = mx.sym.Activation(data=net, act_type="relu")
    check_consistency(net, [
        {"ctx": mx.cpu(), "data": (2, 3, 8, 8)},
        {"ctx": mx.tpu(0), "data": (2, 3, 8, 8)},
    ])
