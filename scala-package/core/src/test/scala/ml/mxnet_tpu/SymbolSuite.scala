package ml.mxnet_tpu

import org.scalatest.FunSuite

/**
 * Symbol surface tests (reference scala-package core
 * SymbolSuite.scala + ExecutorSuite.scala). The same sequences run in
 * CI through the JNI shim (tests/jni_train.c builds, shape-infers,
 * binds and trains this composition natively).
 */
class SymbolSuite extends FunSuite {
  private def mlp(): Symbol = {
    val data = Symbol.Variable("data")
    val fc1 = SymbolOpsGen.FullyConnected(data, 16, name = "fc1")
    val act = SymbolOpsGen.Activation(fc1, "relu", name = "act")
    val fc2 = SymbolOpsGen.FullyConnected(act, 2, name = "fc2")
    SymbolOpsGen.SoftmaxOutput(fc2, name = "softmax")
  }

  test("typed creators compose the expected arguments") {
    val net = mlp()
    assert(net.listArguments.toSeq ==
      Seq("data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
          "softmax_label"))
  }

  test("shape inference resolves every argument") {
    val net = mlp()
    val (args, outs, _) = net.inferShapes(Map("data" -> Array(8, 5)))
    assert(outs(0).toSeq == Seq(8, 2))
    val byName = net.listArguments.zip(args).toMap
    assert(byName("fc1_weight").toSeq == Seq(16, 5))
  }

  test("json round-trip preserves structure") {
    val net = mlp()
    val back = Symbol.loadJson(net.toJson)
    assert(back.listArguments.toSeq == net.listArguments.toSeq)
  }

  test("executor binds and runs forward") {
    val net = mlp()
    val exe = net.simpleBind(Map("data" -> Array(4, 5)))
    exe.setArg("data", Array.fill(20)(1.0f))
    exe.forward()
    val out = exe.getOutput(0, 8)
    assert(math.abs(out.sum - 4.0f) < 1e-3)   // 4 softmax rows
    exe.close()
  }

  test("FeedForward estimator trains a separable task") {
    val rng = new scala.util.Random(3)
    val data = Array.tabulate(128) { i =>
      val cls = i % 2
      Array.fill(5)(rng.nextFloat() - 0.5f + (if (cls == 1) 1f else -1f))
    }
    val label = Array.tabulate(128)(i => (i % 2).toFloat)
    val iter = new NDArrayIter(data, label, 16, shuffle = true)
    val est = FeedForward.newBuilder(mlp())
      .setNumEpoch(8)
      .setBatchSize(16)
      .setOptimizer(new SGD(learningRate = 0.1f, momentum = 0.9f))
      .build()
    est.fit(iter, Array(5), verbose = false)
    val (_, acc) = est.score(iter, Array(5))
    assert(acc > 0.9)
    est.close()
  }
}
