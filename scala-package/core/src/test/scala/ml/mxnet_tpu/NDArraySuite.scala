package ml.mxnet_tpu

import org.scalatest.FunSuite

/**
 * NDArray surface tests (reference scala-package core
 * NDArraySuite.scala). No scalac/JVM exists in the build image's CI,
 * so these suites run wherever sbt does; the SAME assertions execute
 * in CI through the JNI shim drivers (tests/jni_train.c ndio +
 * funcInvoke modes drive ndCreate/ndSet/ndGet/ndSave/ndLoad and the
 * generated imperative functions natively).
 */
class NDArraySuite extends FunSuite {
  test("zeros and toArray") {
    val nd = NDArray.zeros(Array(2, 2))
    assert(nd.toArray.toSeq == Seq(0f, 0f, 0f, 0f))
    nd.close()
  }

  test("set and shape") {
    val nd = NDArray.array(Array(1f, 2f, 3f, 4f), Array(4))
    assert(nd.shape.toSeq == Seq(4))
    assert(nd.toArray.toSeq == Seq(1f, 2f, 3f, 4f))
    nd.close()
  }

  test("generated imperative ops write into out") {
    val a = NDArray.array(Array(1f, 2f), Array(2))
    val b = NDArray.array(Array(10f, 20f), Array(2))
    val out = NDArray.zeros(Array(2))
    NDArrayOpsGen.plus(a, b, out)
    assert(out.toArray.toSeq == Seq(11f, 22f))
    NDArrayOpsGen.mulScalar(out, 2f, out)
    assert(out.toArray.toSeq == Seq(22f, 44f))
    NDArrayOpsGen.rminusScalar(out, 50f, out)   // 50 - x
    assert(out.toArray.toSeq == Seq(28f, 6f))
    Seq(a, b, out).foreach(_.close())
  }

  test("save/load round-trip keeps caller-owned handles") {
    val path = java.io.File.createTempFile("nd", ".params").getPath
    val w = NDArray.array(Array(1f, 2f, 3f), Array(3))
    NDArrayIO.save(path, Map("arg:w" -> w))
    w.close()
    val loaded = NDArrayIO.load(path)
    assert(loaded.keySet == Set("arg:w"))
    assert(loaded("arg:w").toArray.toSeq == Seq(1f, 2f, 3f))
    loaded.values.foreach(_.close())   // dup'd handles: safe to free
  }

  test("listFunctions names the arithmetic surface") {
    val fns = LibInfo.lib.listFunctions().toSet
    assert(Set("_plus", "_minus", "_mul", "_div",
               "_rminus_scalar", "_rdiv_scalar").subsetOf(fns))
  }
}
