package ml.mxnet_tpu

/**
 * Native method table over libmxnet_tpu_jni.so (the JNI glue in
 * native/src/main/native/mxnet_tpu_jni.c, itself over the C ABI in
 * include/mxnet_tpu/c_api.h).
 *
 * Parity target: the reference scala-package's LibInfo
 * (scala-package/core/src/main/scala/ml/dmlc/mxnet/LibInfo.scala).
 * Handles are jlong; tensors cross as Array[Float] (row-major).
 */
private[mxnet_tpu] class LibInfo {
  // NDArray
  @native def ndCreate(shape: Array[Int], devType: Int, devId: Int): Long
  @native def ndFree(handle: Long): Unit
  @native def ndSet(handle: Long, data: Array[Float]): Unit
  @native def ndGet(handle: Long): Array[Float]
  @native def ndShape(handle: Long): Array[Int]

  // Symbol
  @native def symCreateFromJSON(json: String): Long
  @native def symToJSON(handle: Long): String
  @native def symFree(handle: Long): Unit
  @native def symListArguments(handle: Long): Array[String]
  @native def symListOutputs(handle: Long): Array[String]
  @native def symInferArgSizes(handle: Long, keys: Array[String],
                               indptr: Array[Int],
                               shapeData: Array[Int]): Array[Int]

  // Executor
  @native def execSimpleBind(symHandle: Long, devType: Int, devId: Int,
                             keys: Array[String], indptr: Array[Int],
                             shapeData: Array[Int],
                             forTraining: Int): Long
  @native def execSetArg(handle: Long, name: String,
                         data: Array[Float]): Unit
  @native def execSetAux(handle: Long, name: String,
                         data: Array[Float]): Unit
  @native def execForward(handle: Long, isTrain: Int): Unit
  @native def execBackward(handle: Long): Unit
  @native def execGetOutput(handle: Long, index: Int,
                            size: Int): Array[Float]
  @native def execGetGrad(handle: Long, name: String,
                          size: Int): Array[Float]
  @native def execFree(handle: Long): Unit

  // Round-2 surface: symbol file IO / grad / print, optimizer, misc
  @native def randomSeed(seed: Int): Unit
  @native def symCreateFromFile(path: String): Long
  @native def symSaveToFile(handle: Long, path: String): Unit
  @native def symGrad(handle: Long, wrt: Array[String]): Long
  @native def symPrint(handle: Long): String
  @native def optCreate(name: String, keys: Array[String],
                        vals: Array[String]): Long
  @native def optUpdate(handle: Long, index: Int, weight: Long,
                        grad: Long, lr: Float, wd: Float): Unit
  @native def optFree(handle: Long): Unit

  // Round-3 surface: registry symbol construction + shapes + aux +
  // named-params container IO (the typed Module API sits on these)
  @native def symCreateVariable(name: String): Long
  @native def symListAtomic(): Array[String]
  @native def symCreateAtomic(op: String, keys: Array[String],
                              vals: Array[String]): Long
  @native def symCompose(handle: Long, name: String, keys: Array[String],
                         args: Array[Long]): Unit
  @native def symListAuxiliary(handle: Long): Array[String]
  @native def symInferShapes(handle: Long, keys: Array[String],
                             indptr: Array[Int],
                             shapeData: Array[Int]): Array[Int]
  @native def execGetAux(handle: Long, name: String,
                         size: Int): Array[Float]
  @native def ndSave(path: String, names: Array[String],
                     handles: Array[Long]): Unit
  // element 0: Array[String] names; element 1: Array[Long] handles —
  // one parse of the container, load record freed native-side
  @native def ndLoad(path: String): Array[AnyRef]

  // Round-4 surface: imperative NDArray functions (NDArrayOpsGen sits
  // on these; reference LibInfo.mxFuncInvoke / mxListFunctions)
  @native def funcInvoke(name: String, use: Array[Long],
                         scalars: Array[Float], out: Long): Unit
  @native def listFunctions(): Array[String]

  // KVStore (distributed training; Spark workers call these)
  @native def kvCreate(kvType: String): Long
  @native def kvRank(handle: Long): Int
  @native def kvNumWorkers(handle: Long): Int
  @native def kvInit(handle: Long, key: Int, ndHandle: Long): Unit
  @native def kvPush(handle: Long, key: Int, ndHandle: Long,
                     priority: Int): Unit
  @native def kvPull(handle: Long, key: Int, ndHandle: Long,
                     priority: Int): Unit
  @native def kvBarrier(handle: Long): Unit
  @native def kvFree(handle: Long): Unit

  // Data iterators (reference ml.dmlc.mxnet.io MXDataIter surface)
  @native def iterCreate(name: String, keys: Array[String],
                         vals: Array[String]): Long
  @native def iterFree(handle: Long): Unit
  @native def iterBeforeFirst(handle: Long): Unit
  @native def iterNext(handle: Long): Int
  @native def iterGetData(handle: Long): Array[Float]
  @native def iterGetDataShape(handle: Long): Array[Int]
  @native def iterGetLabel(handle: Long): Array[Float]
  @native def iterGetPadNum(handle: Long): Int
}

object LibInfo {
  lazy val lib: LibInfo = {
    System.loadLibrary("mxnet_tpu_jni")
    new LibInfo
  }
}
