package ml.mxnet_tpu

/**
 * Estimator API (reference scala-package
 * ml.dmlc.mxnet.FeedForward, FeedForward.scala:1-666, plus its
 * Builder, FeedForward.scala:500-666): symbol + training
 * configuration in one object, `fit` to train, `predict` over a
 * DataIter, checkpoint save/load in the reference's
 * prefix-symbol.json / prefix-%04d.params layout (interoperable with
 * the Python and R frontends — same container format).
 *
 * The heavy lifting delegates to Module (one bound executor, fused
 * forward/backward under the hood); FeedForward owns the
 * configuration and lifecycle, exactly the reference's split.
 */
class FeedForward(val symbol: Symbol,
                  val devType: Int = Context.CPU,
                  val devId: Int = 0,
                  val numEpoch: Int = 10,
                  val optimizer: SGD = new SGD(0.01f),
                  val initializer: Initializer = new Uniform(0.07f),
                  val batchSize: Int = 128,
                  val dataName: String = "data",
                  val labelName: String = "softmax_label",
                  initArgParams: Map[String, Array[Float]] = null,
                  initAuxParams: Map[String, Array[Float]] = null)
    extends AutoCloseable {

  private var module: Module = _
  private var trained = false

  def argParams: Map[String, Array[Float]] =
    if (module != null) module.argParams
    else Option(initArgParams).getOrElse(Map.empty)

  def auxParams: Map[String, Array[Float]] =
    if (module != null) module.auxParams
    else Option(initAuxParams).getOrElse(Map.empty)

  private def ensureModule(dataShape: Array[Int]): Module = {
    if (module == null) {
      module = new Module(symbol, dataName, labelName, devType, devId)
        .bind(dataShape)
      if (initArgParams != null) {
        module.argParams = initArgParams
        module.setParams()
      } else {
        module.initParams(initializer)
      }
      if (initAuxParams != null) {
        module.auxParams = initAuxParams
        module.setParams()
      }
    }
    module
  }

  /** Train (reference FeedForward.fit, FeedForward.scala:200-320):
   *  infers the input shape from the first batch's length. */
  def fit(train: DataIter, featureShape: Array[Int],
          evalData: Option[DataIter] = None,
          metric: EvalMetric = new Accuracy,
          verbose: Boolean = true): this.type = {
    val dataShape = batchSize +: featureShape
    ensureModule(dataShape)
      .fit(train, numEpoch, optimizer, metric, evalData, verbose)
    trained = true
    this
  }

  /** Forward every batch of `data` and concatenate the outputs
   *  (reference FeedForward.predict, FeedForward.scala:120-180). */
  def predict(data: DataIter, featureShape: Array[Int])
      : Array[Array[Float]] = {
    val m = ensureModule(batchSize +: featureShape)
    data.reset()
    val out = scala.collection.mutable.ArrayBuffer[Array[Float]]()
    while (data.hasNext) out += m.predict(data.next().data)
    out.toArray
  }

  def score(data: DataIter, featureShape: Array[Int],
            metric: EvalMetric = new Accuracy): (String, Double) =
    ensureModule(batchSize +: featureShape).score(data, metric)

  /** Reference checkpoint layout (FeedForward.save ->
   *  Model.saveCheckpoint, FeedForward.scala:330-360). */
  def save(prefix: String, epoch: Int = numEpoch): Unit = {
    require(module != null, "save before bind/fit")
    module.saveCheckpoint(prefix, epoch)
  }

  override def close(): Unit = if (module != null) module.close()
}

object FeedForward {
  /** One-call train (the round-3 facade, kept for compatibility). */
  def fit(symbol: Symbol, train: DataIter, dataShape: Array[Int],
          numEpoch: Int = 10, learningRate: Float = 0.01f,
          momentum: Float = 0.0f): Module =
    new Module(symbol)
      .bind(dataShape)
      .initParams()
      .fit(train, numEpoch, new SGD(learningRate, momentum))

  /** Load a checkpoint as a ready-to-predict estimator (reference
   *  FeedForward.load, FeedForward.scala:380-420). */
  def load(prefix: String, epoch: Int, batchSize: Int = 128,
           dataName: String = "data"): FeedForward = {
    val sym = Symbol.load(s"$prefix-symbol.json")
    val loaded = NDArrayIO.load(f"$prefix-$epoch%04d.params")
    val args = loaded.collect {
      case (k, v) if k.startsWith("arg:") => k.drop(4) -> v.toArray
    }
    val auxs = loaded.collect {
      case (k, v) if k.startsWith("aux:") => k.drop(4) -> v.toArray
    }
    loaded.values.foreach(_.close())
    new FeedForward(sym, batchSize = batchSize, dataName = dataName,
                    initArgParams = args, initAuxParams = auxs)
  }

  def newBuilder(symbol: Symbol): Builder = new Builder(symbol)

  /** Reference FeedForward.Builder (FeedForward.scala:500-666). */
  class Builder(symbol: Symbol) {
    private var devType = Context.CPU
    private var devId = 0
    private var numEpoch = 10
    private var optimizer = new SGD(0.01f)
    private var initializer: Initializer = new Uniform(0.07f)
    private var batchSize = 128
    private var dataName = "data"
    private var labelName = "softmax_label"
    private var argParams: Map[String, Array[Float]] = null
    private var auxParams: Map[String, Array[Float]] = null

    def setContext(devType: Int, devId: Int = 0): Builder = {
      this.devType = devType; this.devId = devId; this
    }
    def setNumEpoch(n: Int): Builder = { numEpoch = n; this }
    def setOptimizer(opt: SGD): Builder = { optimizer = opt; this }
    def setInitializer(init: Initializer): Builder = {
      initializer = init; this
    }
    def setBatchSize(n: Int): Builder = { batchSize = n; this }
    def setDataName(n: String): Builder = { dataName = n; this }
    def setLabelName(n: String): Builder = { labelName = n; this }
    def setArgParams(p: Map[String, Array[Float]]): Builder = {
      argParams = p; this
    }
    def setAuxParams(p: Map[String, Array[Float]]): Builder = {
      auxParams = p; this
    }

    def build(): FeedForward =
      new FeedForward(symbol, devType, devId, numEpoch, optimizer,
                      initializer, batchSize, dataName, labelName,
                      argParams, auxParams)
  }
}
