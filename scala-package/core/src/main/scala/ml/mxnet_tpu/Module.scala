package ml.mxnet_tpu

import scala.collection.mutable

/**
 * Typed training API (reference scala-package
 * ml.dmlc.mxnet.module.Module + io/metric/initializer/optimizer
 * packages): DataIter -> Module.fit with initializer, optimizer and
 * metric, plus checkpoint save/load in the reference's
 * prefix-symbol.json / prefix-%04d.params layout (arg:/aux: key
 * prefixes), interoperable with the Python and R frontends.
 */
case class DataBatch(data: Array[Float], label: Array[Float],
                     pad: Int = 0)

trait DataIter {
  def batchSize: Int
  def reset(): Unit
  def hasNext: Boolean
  def next(): DataBatch
}

/** In-memory iterator (reference ml.dmlc.mxnet.io.NDArrayIter):
 *  row-major data (numSamples x featureSize), wrap-around padding. */
class NDArrayIter(data: Array[Array[Float]], label: Array[Float],
                  val batchSize: Int, shuffle: Boolean = false,
                  seed: Int = 0) extends DataIter {
  private val rng = new scala.util.Random(seed)
  private var order: Array[Int] = data.indices.toArray
  private var cursor = 0

  def reset(): Unit = {
    cursor = 0
    if (shuffle) order = rng.shuffle(data.indices.toList).toArray
  }

  def hasNext: Boolean = cursor < data.length

  def next(): DataBatch = {
    val idx = Array.tabulate(batchSize)(i => order((cursor + i) % data.length))
    cursor += batchSize
    DataBatch(idx.flatMap(data(_)), idx.map(label(_)))
  }
}

/** Runtime-backed iterator over the C ABI's registry (reference
 *  ml.dmlc.mxnet.io.MXDataIter): ImageRecordIter / MNISTIter / CSVIter
 *  / CachedImageRecordIter created by name with string kwargs. Batches
 *  arrive as flat row-major floats; `dataShape` gives the C-order batch
 *  shape for reshaping on the consumer side. */
class MXDataIter(name: String, params: Map[String, String])
    extends DataIter with AutoCloseable {
  private val lib = LibInfo.lib
  private val handle: Long = {
    val (ks, vs) = params.toSeq.unzip
    lib.iterCreate(name, ks.toArray, vs.toArray)
  }
  val batchSize: Int =
    params.get("batch_size").map(_.toInt).getOrElse(-1)
  private var advanced = false
  private var more = false
  private var shape: Array[Int] = null

  def reset(): Unit = {
    lib.iterBeforeFirst(handle)
    advanced = false
  }

  def hasNext: Boolean = {
    if (!advanced) {
      more = lib.iterNext(handle) != 0
      advanced = true
    }
    more
  }

  /** Batch-scoped reads happen HERE, while the runtime cursor is on
   *  this batch: hasNext pre-advances the cursor, so reading pad or
   *  shape through separate accessors after the fact would describe
   *  the WRONG batch. pad rides inside the DataBatch (the reference
   *  DataBatch carries pad the same way). */
  def next(): DataBatch = {
    if (!hasNext) throw new NoSuchElementException("iterator exhausted")
    advanced = false
    val d = lib.iterGetData(handle)
    val l = lib.iterGetLabel(handle)
    if (shape == null) shape = lib.iterGetDataShape(handle)
    DataBatch(d, l, lib.iterGetPadNum(handle))
  }

  /** C-order batch shape, e.g. (N, C, H, W) — constant per iterator;
   *  captured once alongside the first next() (a separate fetch per
   *  batch would pay a redundant device round-trip). Null before the
   *  first next(). */
  def dataShape: Array[Int] = shape

  def close(): Unit = lib.iterFree(handle)
}

object MXDataIter {
  def imageRecordIter(params: Map[String, String]): MXDataIter =
    new MXDataIter("ImageRecordIter", params)
  def mnistIter(params: Map[String, String]): MXDataIter =
    new MXDataIter("MNISTIter", params)
  def csvIter(params: Map[String, String]): MXDataIter =
    new MXDataIter("CSVIter", params)
}

trait EvalMetric {
  def name: String
  protected var sum = 0.0
  protected var count = 0
  def reset(): Unit = { sum = 0.0; count = 0 }
  def get: (String, Double) = (name, if (count == 0) 0.0 else sum / count)
  def update(label: Array[Float], pred: Array[Float], numClass: Int): Unit
}

class Accuracy extends EvalMetric {
  val name = "accuracy"
  def update(label: Array[Float], pred: Array[Float],
             numClass: Int): Unit = {
    for (i <- label.indices) {
      val row = pred.slice(i * numClass, (i + 1) * numClass)
      val guess = row.indices.maxBy(row(_))
      if (guess == label(i).toInt) sum += 1
      count += 1
    }
  }
}

class MSE extends EvalMetric {
  val name = "mse"
  def update(label: Array[Float], pred: Array[Float],
             numClass: Int): Unit = {
    for (i <- label.indices) {
      val d = pred(i) - label(i)
      sum += d * d
      count += 1
    }
  }
}

trait Initializer {
  def apply(name: String, size: Int, rng: scala.util.Random): Array[Float] =
    if (name.endsWith("bias") || name.endsWith("beta"))
      Array.fill(size)(0.0f)
    else if (name.endsWith("gamma")) Array.fill(size)(1.0f)
    else weights(size, rng)
  protected def weights(size: Int, rng: scala.util.Random): Array[Float]
}

class Uniform(scale: Float = 0.07f) extends Initializer {
  protected def weights(size: Int, rng: scala.util.Random): Array[Float] =
    Array.fill(size)((rng.nextFloat() * 2 - 1) * scale)
}

class Normal(sigma: Float = 0.01f) extends Initializer {
  protected def weights(size: Int, rng: scala.util.Random): Array[Float] =
    Array.fill(size)(rng.nextGaussian().toFloat * sigma)
}

/** SGD with momentum (reference ml.dmlc.mxnet.optimizer.SGD): the
 *  JVM-side mirror of python optimizer.py update rule. */
class SGD(val learningRate: Float = 0.01f, val momentum: Float = 0.0f,
          val wd: Float = 0.0f, val rescaleGrad: Float = 1.0f) {
  private val mom = mutable.Map.empty[String, Array[Float]]
  def update(name: String, weight: Array[Float],
             grad: Array[Float]): Array[Float] = {
    val m = mom.getOrElseUpdate(name, new Array[Float](weight.length))
    val out = new Array[Float](weight.length)
    var i = 0
    while (i < weight.length) {
      val g = grad(i) * rescaleGrad + wd * weight(i)
      m(i) = momentum * m(i) - learningRate * g
      out(i) = weight(i) + m(i)
      i += 1
    }
    out
  }
}

/**
 * Single-device typed Module. `fit` drives the same loop as the
 * reference Module.fit: per batch set data/label, fused
 * forward+backward, SGD update of every parameter, metric update;
 * per epoch metric reset + optional eval scoring.
 */
class Module(symbol: Symbol, dataName: String = "data",
             labelName: String = "softmax_label",
             devType: Int = Context.CPU, devId: Int = 0) {
  private var exec: Executor = _
  private var argShapes: Map[String, Array[Int]] = Map.empty
  private var outSize = 0
  private var numClass = 0
  var argParams: Map[String, Array[Float]] = Map.empty
  var auxParams: Map[String, Array[Float]] = Map.empty

  def bind(dataShape: Array[Int]): this.type = {
    val shapes = Map(dataName -> dataShape)
    val (args, outs, auxs) = symbol.inferShapes(shapes)
    argShapes = symbol.listArguments.zip(args).toMap
    outSize = outs(0).product
    numClass = outs(0).last
    exec = symbol.simpleBind(shapes, forTraining = true, devType, devId)
    this
  }

  def initParams(initializer: Initializer = new Uniform(0.07f),
                 seed: Int = 0): this.type = {
    val rng = new scala.util.Random(seed)
    argParams = argShapes.collect {
      case (name, shape)
          if name != dataName && !name.endsWith("label") =>
        name -> initializer(name, shape.product, rng)
    }
    argParams.foreach { case (n, v) => exec.setArg(n, v) }
    val (_, _, auxShapes) = symbol.inferShapes(
      Map(dataName -> argShapes(dataName)))
    auxParams = symbol.listAuxiliary.zip(auxShapes.map { s =>
      new Array[Float](s.product)
    }).toMap
    auxParams.foreach { case (n, v) =>
      // moving variances start at 1 (runtime rule)
      val init = if (n.endsWith("var")) v.map(_ => 1.0f) else v
      exec.setAux(n, init)
    }
    this
  }

  /** Push the current argParams/auxParams into the bound executor
   *  (reference Module.setParams — used by FeedForward.load to
   *  restore checkpointed weights into a fresh bind). */
  def setParams(): this.type = {
    argParams.foreach { case (n, v) => exec.setArg(n, v) }
    auxParams.foreach { case (n, v) => exec.setAux(n, v) }
    this
  }

  def fit(train: DataIter, numEpoch: Int, optimizer: SGD,
          metric: EvalMetric = new Accuracy,
          evalData: Option[DataIter] = None,
          verbose: Boolean = true): this.type = {
    for (epoch <- 1 to numEpoch) {
      train.reset()
      metric.reset()
      while (train.hasNext) {
        val batch = train.next()
        exec.setArg(dataName, batch.data)
        exec.setArg(labelName, batch.label)
        exec.forward(isTrain = true)
        exec.backward()
        argParams = argParams.map { case (name, value) =>
          val grad = exec.getGrad(name, value.length)
          val updated = optimizer.update(name, value, grad)
          exec.setArg(name, updated)
          name -> updated
        }
        metric.update(batch.label, exec.getOutput(0, outSize), numClass)
      }
      val (mname, mval) = metric.get
      if (verbose)
        println(f"Epoch [$epoch] Train-$mname=$mval%.4f")
      evalData.foreach { ev =>
        val (en, evv) = score(ev, new Accuracy)
        if (verbose) println(f"Epoch [$epoch] Validation-$en=$evv%.4f")
      }
    }
    auxParams = auxParams.map { case (n, v) =>
      n -> exec.getAux(n, v.length)
    }
    this
  }

  def score(it: DataIter, metric: EvalMetric): (String, Double) = {
    it.reset()
    metric.reset()
    while (it.hasNext) {
      val batch = it.next()
      exec.setArg(dataName, batch.data)
      exec.forward(isTrain = false)
      metric.update(batch.label, exec.getOutput(0, outSize), numClass)
    }
    metric.get
  }

  def predict(batch: Array[Float]): Array[Float] = {
    exec.setArg(dataName, batch)
    exec.forward(isTrain = false)
    exec.getOutput(0, outSize)
  }

  /** Reference checkpoint layout: prefix-symbol.json +
   *  prefix-%04d.params with arg:/aux: prefixes. */
  def saveCheckpoint(prefix: String, epoch: Int): Unit = {
    symbol.save(s"$prefix-symbol.json")
    val named = argParams.map { case (n, v) =>
      s"arg:$n" -> NDArray.array(v, Array(v.length))
    } ++ auxParams.map { case (n, v) =>
      s"aux:$n" -> NDArray.array(v, Array(v.length))
    }
    NDArrayIO.save(f"$prefix-$epoch%04d.params", named)
    named.values.foreach(_.close())
  }

  def close(): Unit = if (exec != null) exec.close()
}

object Module {
  def loadCheckpoint(prefix: String, epoch: Int,
                     dataName: String = "data"): Module = {
    val sym = Symbol.load(s"$prefix-symbol.json")
    val mod = new Module(sym, dataName)
    val loaded = NDArrayIO.load(f"$prefix-$epoch%04d.params")
    mod.argParams = loaded.collect {
      case (k, v) if k.startsWith("arg:") => k.drop(4) -> v.toArray
    }
    mod.auxParams = loaded.collect {
      case (k, v) if k.startsWith("aux:") => k.drop(4) -> v.toArray
    }
    loaded.values.foreach(_.close())
    mod
  }
}

// The estimator facade over Module lives in FeedForward.scala
// (reference ml.dmlc.mxnet.FeedForward, FeedForward.scala:1-666).
