package ml.mxnet_tpu.examples

import ml.mxnet_tpu._

/**
 * Typed-API training walkthrough (reference
 * scala-package/examples/.../TrainMnist.scala): builds the LeNet-ish
 * net through the GENERATED typed creators (SymbolOpsGen), trains with
 * the FeedForward estimator, checkpoints, reloads, and runs an
 * imperative NDArray op through NDArrayOpsGen — the round-4 surface in
 * one program.
 *
 * Run on a host with the JNI library built:
 *   scala -cp core.jar ml.mxnet_tpu.examples.TrainMnist <data>
 */
object TrainMnist {
  def buildNet(numClasses: Int): Symbol = {
    val data = Symbol.Variable("data")
    val c1 = SymbolOpsGen.Convolution(data, Array(3, 3), 8, name = "c1")
    val a1 = SymbolOpsGen.Activation(c1, "relu", name = "a1")
    val p1 = SymbolOpsGen.Pooling(a1, name = "p1", kernel = Array(2, 2),
                                  stride = Array(2, 2))
    val fl = SymbolOpsGen.Flatten(p1, name = "fl")
    val f1 = SymbolOpsGen.FullyConnected(fl, numClasses, name = "fc1")
    SymbolOpsGen.SoftmaxOutput(f1, name = "softmax")
  }

  def main(args: Array[String]): Unit = {
    val numClasses = 10
    val batch = 32
    val featureShape = Array(1, 28, 28)

    val (trainData, trainLabel) = Mnist.load(args.headOption.getOrElse("."))
    val iter = new NDArrayIter(trainData, trainLabel, batch,
                               shuffle = true)

    val estimator = FeedForward.newBuilder(buildNet(numClasses))
      .setNumEpoch(5)
      .setBatchSize(batch)
      .setOptimizer(new SGD(learningRate = 0.1f, momentum = 0.9f))
      .build()
    estimator.fit(iter, featureShape)
    estimator.save("mnist-lenet")
    estimator.close()

    // reload and score (checkpoint interop with Python/R: same layout)
    val restored = FeedForward.load("mnist-lenet", 5, batchSize = batch)
    val (name, value) = restored.score(iter, featureShape)
    println(s"reloaded $name=$value")
    restored.close()

    // the generated imperative surface: (a + b) * 2 elementwise
    val a = NDArray.array(Array(1f, 2f, 3f, 4f), Array(4))
    val b = NDArray.array(Array(9f, 8f, 7f, 6f), Array(4))
    val out = NDArray.zeros(Array(4))
    NDArrayOpsGen.mulScalar(NDArrayOpsGen.plus(a, b, out), 2f, out)
    println("funcInvoke: " + out.toArray.mkString(","))
    Seq(a, b, out).foreach(_.close())
  }
}

/** Minimal idx-format reader (the reference example read MNIST the
 *  same way; tools/make_mnist_synth.py writes compatible files). */
object Mnist {
  import java.io.{DataInputStream, FileInputStream}
  import java.util.zip.GZIPInputStream

  private def open(path: String): DataInputStream = {
    val raw = new FileInputStream(path)
    new DataInputStream(
      if (path.endsWith(".gz")) new GZIPInputStream(raw) else raw)
  }

  def load(dir: String): (Array[Array[Float]], Array[Float]) = {
    val imgs = open(s"$dir/train-images-idx3-ubyte")
    require(imgs.readInt() == 2051, "bad image magic")
    val n = imgs.readInt(); val h = imgs.readInt(); val w = imgs.readInt()
    val data = Array.fill(n) {
      Array.fill(h * w)((imgs.readUnsignedByte() / 255.0f))
    }
    imgs.close()
    val lbls = open(s"$dir/train-labels-idx1-ubyte")
    require(lbls.readInt() == 2049, "bad label magic")
    val m = lbls.readInt()
    val label = Array.fill(m)(lbls.readUnsignedByte().toFloat)
    lbls.close()
    (data, label)
  }
}
