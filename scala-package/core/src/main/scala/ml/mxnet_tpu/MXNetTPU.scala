package ml.mxnet_tpu

import scala.collection.mutable

/**
 * Scala frontend classes over the JNI table, mirroring the reference
 * scala-package's user API (ml.dmlc.mxnet.{NDArray, Symbol, Executor,
 * FeedForward}) on the TPU runtime ABI. Row-major shapes everywhere,
 * like the reference Scala binding (unlike the R/Matlab bindings there
 * is no layout flip: JVM arrays are row-major already).
 */
object Context {
  val CPU = 1
  val TPU = 2
}

class NDArray private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def shape: Array[Int] = LibInfo.lib.ndShape(handle)
  def set(data: Array[Float]): NDArray = {
    LibInfo.lib.ndSet(handle, data); this
  }
  def toArray: Array[Float] = LibInfo.lib.ndGet(handle)
  override def close(): Unit = LibInfo.lib.ndFree(handle)
}

object NDArray {
  def zeros(shape: Array[Int], devType: Int = Context.CPU,
            devId: Int = 0): NDArray =
    new NDArray(LibInfo.lib.ndCreate(shape, devType, devId))

  def array(data: Array[Float], shape: Array[Int]): NDArray =
    zeros(shape).set(data)
}

class Symbol private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def toJson: String = LibInfo.lib.symToJSON(handle)
  def listArguments: Array[String] = LibInfo.lib.symListArguments(handle)
  def listOutputs: Array[String] = LibInfo.lib.symListOutputs(handle)
  def save(path: String): Unit = LibInfo.lib.symSaveToFile(handle, path)
  /** Gradient symbol wrt the named arguments (MXSymbolGrad). */
  def grad(wrt: Array[String]): Symbol =
    new Symbol(LibInfo.lib.symGrad(handle, wrt))
  def debugStr: String = LibInfo.lib.symPrint(handle)

  /** CSR packing of named shapes for the C ABI. */
  private def packShapes(shapes: Map[String, Array[Int]])
      : (Array[String], Array[Int], Array[Int]) = {
    val keys = shapes.keys.toArray
    val indptr = mutable.ArrayBuffer(0)
    val data = mutable.ArrayBuffer[Int]()
    for (k <- keys) {
      data ++= shapes(k)
      indptr += data.length
    }
    (keys, indptr.toArray, data.toArray)
  }

  /** Per-argument element counts given named input shapes. */
  def inferArgSizes(shapes: Map[String, Array[Int]]): Map[String, Int] = {
    val (keys, indptr, data) = packShapes(shapes)
    val sizes = LibInfo.lib.symInferArgSizes(handle, keys, indptr, data)
    listArguments.zip(sizes).toMap
  }

  def listAuxiliary: Array[String] = LibInfo.lib.symListAuxiliary(handle)

  private def decodeShapes(flat: Array[Int]): Array[Array[Int]] = {
    val n = flat(0)
    val out = new Array[Array[Int]](n)
    var p = 1
    for (i <- 0 until n) {
      val ndim = flat(p); p += 1
      out(i) = flat.slice(p, p + ndim); p += ndim
    }
    out
  }

  /** Full shape inference (reference Symbol.inferShape): returns
   *  (argShapes, outShapes, auxShapes) given named input shapes.
   *  One native call carries all three sections back-to-back. */
  def inferShapes(shapes: Map[String, Array[Int]])
      : (Array[Array[Int]], Array[Array[Int]], Array[Array[Int]]) = {
    val (keys, indptr, data) = packShapes(shapes)
    val flat = LibInfo.lib.symInferShapes(handle, keys, indptr, data)
    var p = 0
    def section(): Array[Array[Int]] = {
      val n = flat(p); p += 1
      Array.fill(n) {
        val ndim = flat(p); p += 1
        val s = flat.slice(p, p + ndim); p += ndim
        s
      }
    }
    (section(), section(), section())
  }

  /** simple_bind with named input shapes (row-major). */
  def simpleBind(shapes: Map[String, Array[Int]],
                 forTraining: Boolean = false,
                 devType: Int = Context.CPU, devId: Int = 0): Executor = {
    val (keys, indptr, data) = packShapes(shapes)
    new Executor(LibInfo.lib.execSimpleBind(
      handle, devType, devId, keys, indptr, data,
      if (forTraining) 1 else 0), this)
  }

  override def close(): Unit = LibInfo.lib.symFree(handle)
}

object Symbol {
  def loadJson(json: String): Symbol =
    new Symbol(LibInfo.lib.symCreateFromJSON(json))

  def load(path: String): Symbol =
    new Symbol(LibInfo.lib.symCreateFromFile(path))

  def Variable(name: String): Symbol =
    new Symbol(LibInfo.lib.symCreateVariable(name))

  def listOperators: Array[String] = LibInfo.lib.symListAtomic()

  /** Registry-driven operator application (the reference generated
   *  typed creators from the same enumeration at build time;
   *  SymbolOps below provides the typed layer over this). */
  def create(op: String, params: Map[String, String], name: String,
             inputs: (String, Symbol)*): Symbol = {
    val h = LibInfo.lib.symCreateAtomic(
      op, params.keys.toArray, params.values.toArray)
    try {
      LibInfo.lib.symCompose(h, name, inputs.map(_._1).toArray,
                             inputs.map(_._2.handle).toArray)
    } catch {
      case e: Throwable =>
        LibInfo.lib.symFree(h)   // don't leak on bad compose
        throw e
    }
    new Symbol(h)
  }
}

/** Typed operator creators (reference scala-package generated these
 *  from the registry at build time; the most-used subset is typed here
 *  and `Symbol.create` reaches the rest of the registry). */
object SymbolOps {
  def FullyConnected(data: Symbol, numHidden: Int, name: String,
                     noBias: Boolean = false): Symbol =
    Symbol.create("FullyConnected",
                  Map("num_hidden" -> numHidden.toString,
                      "no_bias" -> noBias.toString),
                  name, "data" -> data)

  def Activation(data: Symbol, actType: String, name: String): Symbol =
    Symbol.create("Activation", Map("act_type" -> actType), name,
                  "data" -> data)

  def Convolution(data: Symbol, numFilter: Int, kernel: (Int, Int),
                  name: String, stride: (Int, Int) = (1, 1),
                  pad: (Int, Int) = (0, 0)): Symbol =
    Symbol.create(
      "Convolution",
      Map("num_filter" -> numFilter.toString,
          "kernel" -> s"(${kernel._1}, ${kernel._2})",
          "stride" -> s"(${stride._1}, ${stride._2})",
          "pad" -> s"(${pad._1}, ${pad._2})"),
      name, "data" -> data)

  def Pooling(data: Symbol, kernel: (Int, Int), poolType: String,
              name: String, stride: (Int, Int) = (1, 1)): Symbol =
    Symbol.create(
      "Pooling",
      Map("kernel" -> s"(${kernel._1}, ${kernel._2})",
          "pool_type" -> poolType,
          "stride" -> s"(${stride._1}, ${stride._2})"),
      name, "data" -> data)

  def Flatten(data: Symbol, name: String): Symbol =
    Symbol.create("Flatten", Map.empty, name, "data" -> data)

  def BatchNorm(data: Symbol, name: String): Symbol =
    Symbol.create("BatchNorm", Map.empty, name, "data" -> data)

  def Dropout(data: Symbol, p: Float, name: String): Symbol =
    Symbol.create("Dropout", Map("p" -> p.toString), name, "data" -> data)

  def Embedding(data: Symbol, inputDim: Int, outputDim: Int,
                name: String): Symbol =
    Symbol.create("Embedding",
                  Map("input_dim" -> inputDim.toString,
                      "output_dim" -> outputDim.toString),
                  name, "data" -> data)

  def SoftmaxOutput(data: Symbol, name: String): Symbol =
    Symbol.create("SoftmaxOutput", Map.empty, name, "data" -> data)

  def LinearRegressionOutput(data: Symbol, label: Symbol,
                             name: String): Symbol =
    Symbol.create("LinearRegressionOutput", Map.empty, name,
                  "data" -> data, "label" -> label)
}

object NDArrayIO {
  /** Named-params container save/load (reference NDArray.save/load —
   *  same binary layout as the Python side, so checkpoints cross). */
  def save(path: String, arrays: Map[String, NDArray]): Unit =
    LibInfo.lib.ndSave(path, arrays.keys.toArray,
                       arrays.values.map(_.handle).toArray)

  def load(path: String): Map[String, NDArray] = {
    val pair = LibInfo.lib.ndLoad(path)
    val names = pair(0).asInstanceOf[Array[String]]
    val handles = pair(1).asInstanceOf[Array[Long]]
    names.zip(handles.map(new NDArray(_))).toMap
  }
}

/** Registered optimizer over the C surface (reference
 *  ml.dmlc.mxnet.Optimizer): per-index state (momentum etc.) lives on
 *  the native handle; lr/wd are per-call like MXOptimizerUpdate. */
class Optimizer private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def update(index: Int, weight: NDArray, grad: NDArray, lr: Float,
             wd: Float = 0.0f): Unit =
    LibInfo.lib.optUpdate(handle, index, weight.handle, grad.handle, lr, wd)
  override def close(): Unit = LibInfo.lib.optFree(handle)
}

object Optimizer {
  def create(name: String, params: Map[String, String] = Map.empty)
      : Optimizer =
    new Optimizer(LibInfo.lib.optCreate(
      name, params.keys.toArray, params.values.toArray))
}

object Random {
  def seed(s: Int): Unit = LibInfo.lib.randomSeed(s)
}

class Executor private[mxnet_tpu] (private[mxnet_tpu] val handle: Long,
                                   val symbol: Symbol)
    extends AutoCloseable {
  def setArg(name: String, data: Array[Float]): Unit =
    LibInfo.lib.execSetArg(handle, name, data)
  def setAux(name: String, data: Array[Float]): Unit =
    LibInfo.lib.execSetAux(handle, name, data)
  def forward(isTrain: Boolean = false): Unit =
    LibInfo.lib.execForward(handle, if (isTrain) 1 else 0)
  def backward(): Unit = LibInfo.lib.execBackward(handle)
  def getOutput(index: Int, size: Int): Array[Float] =
    LibInfo.lib.execGetOutput(handle, index, size)
  def getGrad(name: String, size: Int): Array[Float] =
    LibInfo.lib.execGetGrad(handle, name, size)
  def getAux(name: String, size: Int): Array[Float] =
    LibInfo.lib.execGetAux(handle, name, size)
  override def close(): Unit = LibInfo.lib.execFree(handle)
}

/** KVStore for synchronous distributed training (reference
 *  ml.dmlc.mxnet.KVStore); "dist_sync" inside a Spark task joins the
 *  job's collective group. */
class KVStore private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def rank: Int = LibInfo.lib.kvRank(handle)
  def numWorkers: Int = LibInfo.lib.kvNumWorkers(handle)
  def init(key: Int, value: NDArray): Unit =
    LibInfo.lib.kvInit(handle, key, value.handle)
  def push(key: Int, value: NDArray, priority: Int = 0): Unit =
    LibInfo.lib.kvPush(handle, key, value.handle, priority)
  def pull(key: Int, out: NDArray, priority: Int = 0): Unit =
    LibInfo.lib.kvPull(handle, key, out.handle, priority)
  def barrier(): Unit = LibInfo.lib.kvBarrier(handle)
  override def close(): Unit = LibInfo.lib.kvFree(handle)
}

object KVStore {
  def create(kvType: String = "local"): KVStore =
    new KVStore(LibInfo.lib.kvCreate(kvType))
}

/**
 * Checkpoint-backed predictor + SGD stepper (the reference
 * FeedForward.load / predict workflow; same file layout:
 * prefix-symbol.json + prefix-%04d.params read through the native
 * NDArray container loader is left to the caller via Symbol.load +
 * Executor.setArg, as in the Perl/R bindings' train_step demos).
 */
object Model {
  /** One synchronous SGD step on a bound training executor. */
  def sgdStep(exec: Executor, params: Map[String, Array[Float]],
              lr: Float): Map[String, Array[Float]] = {
    exec.forward(isTrain = true)
    exec.backward()
    params.map { case (name, value) =>
      val grad = exec.getGrad(name, value.length)
      val updated = new Array[Float](value.length)
      var i = 0
      while (i < value.length) {
        updated(i) = value(i) - lr * grad(i)
        i += 1
      }
      exec.setArg(name, updated)
      name -> updated
    }
  }
}
