package ml.mxnet_tpu

import scala.collection.mutable

/**
 * Scala frontend classes over the JNI table, mirroring the reference
 * scala-package's user API (ml.dmlc.mxnet.{NDArray, Symbol, Executor,
 * FeedForward}) on the TPU runtime ABI. Row-major shapes everywhere,
 * like the reference Scala binding (unlike the R/Matlab bindings there
 * is no layout flip: JVM arrays are row-major already).
 */
object Context {
  val CPU = 1
  val TPU = 2
}

class NDArray private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def shape: Array[Int] = LibInfo.lib.ndShape(handle)
  def set(data: Array[Float]): NDArray = {
    LibInfo.lib.ndSet(handle, data); this
  }
  def toArray: Array[Float] = LibInfo.lib.ndGet(handle)
  override def close(): Unit = LibInfo.lib.ndFree(handle)
}

object NDArray {
  def zeros(shape: Array[Int], devType: Int = Context.CPU,
            devId: Int = 0): NDArray =
    new NDArray(LibInfo.lib.ndCreate(shape, devType, devId))

  def array(data: Array[Float], shape: Array[Int]): NDArray =
    zeros(shape).set(data)
}

class Symbol private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def toJson: String = LibInfo.lib.symToJSON(handle)
  def listArguments: Array[String] = LibInfo.lib.symListArguments(handle)
  def listOutputs: Array[String] = LibInfo.lib.symListOutputs(handle)
  def save(path: String): Unit = LibInfo.lib.symSaveToFile(handle, path)
  /** Gradient symbol wrt the named arguments (MXSymbolGrad). */
  def grad(wrt: Array[String]): Symbol =
    new Symbol(LibInfo.lib.symGrad(handle, wrt))
  def debugStr: String = LibInfo.lib.symPrint(handle)

  /** CSR packing of named shapes for the C ABI. */
  private def packShapes(shapes: Map[String, Array[Int]])
      : (Array[String], Array[Int], Array[Int]) = {
    val keys = shapes.keys.toArray
    val indptr = mutable.ArrayBuffer(0)
    val data = mutable.ArrayBuffer[Int]()
    for (k <- keys) {
      data ++= shapes(k)
      indptr += data.length
    }
    (keys, indptr.toArray, data.toArray)
  }

  /** Per-argument element counts given named input shapes. */
  def inferArgSizes(shapes: Map[String, Array[Int]]): Map[String, Int] = {
    val (keys, indptr, data) = packShapes(shapes)
    val sizes = LibInfo.lib.symInferArgSizes(handle, keys, indptr, data)
    listArguments.zip(sizes).toMap
  }

  /** simple_bind with named input shapes (row-major). */
  def simpleBind(shapes: Map[String, Array[Int]],
                 forTraining: Boolean = false,
                 devType: Int = Context.CPU, devId: Int = 0): Executor = {
    val (keys, indptr, data) = packShapes(shapes)
    new Executor(LibInfo.lib.execSimpleBind(
      handle, devType, devId, keys, indptr, data,
      if (forTraining) 1 else 0), this)
  }

  override def close(): Unit = LibInfo.lib.symFree(handle)
}

object Symbol {
  def loadJson(json: String): Symbol =
    new Symbol(LibInfo.lib.symCreateFromJSON(json))

  def load(path: String): Symbol =
    new Symbol(LibInfo.lib.symCreateFromFile(path))
}

/** Registered optimizer over the C surface (reference
 *  ml.dmlc.mxnet.Optimizer): per-index state (momentum etc.) lives on
 *  the native handle; lr/wd are per-call like MXOptimizerUpdate. */
class Optimizer private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def update(index: Int, weight: NDArray, grad: NDArray, lr: Float,
             wd: Float = 0.0f): Unit =
    LibInfo.lib.optUpdate(handle, index, weight.handle, grad.handle, lr, wd)
  override def close(): Unit = LibInfo.lib.optFree(handle)
}

object Optimizer {
  def create(name: String, params: Map[String, String] = Map.empty)
      : Optimizer =
    new Optimizer(LibInfo.lib.optCreate(
      name, params.keys.toArray, params.values.toArray))
}

object Random {
  def seed(s: Int): Unit = LibInfo.lib.randomSeed(s)
}

class Executor private[mxnet_tpu] (private[mxnet_tpu] val handle: Long,
                                   val symbol: Symbol)
    extends AutoCloseable {
  def setArg(name: String, data: Array[Float]): Unit =
    LibInfo.lib.execSetArg(handle, name, data)
  def setAux(name: String, data: Array[Float]): Unit =
    LibInfo.lib.execSetAux(handle, name, data)
  def forward(isTrain: Boolean = false): Unit =
    LibInfo.lib.execForward(handle, if (isTrain) 1 else 0)
  def backward(): Unit = LibInfo.lib.execBackward(handle)
  def getOutput(index: Int, size: Int): Array[Float] =
    LibInfo.lib.execGetOutput(handle, index, size)
  def getGrad(name: String, size: Int): Array[Float] =
    LibInfo.lib.execGetGrad(handle, name, size)
  override def close(): Unit = LibInfo.lib.execFree(handle)
}

/** KVStore for synchronous distributed training (reference
 *  ml.dmlc.mxnet.KVStore); "dist_sync" inside a Spark task joins the
 *  job's collective group. */
class KVStore private[mxnet_tpu] (private[mxnet_tpu] val handle: Long)
    extends AutoCloseable {
  def rank: Int = LibInfo.lib.kvRank(handle)
  def numWorkers: Int = LibInfo.lib.kvNumWorkers(handle)
  def init(key: Int, value: NDArray): Unit =
    LibInfo.lib.kvInit(handle, key, value.handle)
  def push(key: Int, value: NDArray, priority: Int = 0): Unit =
    LibInfo.lib.kvPush(handle, key, value.handle, priority)
  def pull(key: Int, out: NDArray, priority: Int = 0): Unit =
    LibInfo.lib.kvPull(handle, key, out.handle, priority)
  def barrier(): Unit = LibInfo.lib.kvBarrier(handle)
  override def close(): Unit = LibInfo.lib.kvFree(handle)
}

object KVStore {
  def create(kvType: String = "local"): KVStore =
    new KVStore(LibInfo.lib.kvCreate(kvType))
}

/**
 * Checkpoint-backed predictor + SGD stepper (the reference
 * FeedForward.load / predict workflow; same file layout:
 * prefix-symbol.json + prefix-%04d.params read through the native
 * NDArray container loader is left to the caller via Symbol.load +
 * Executor.setArg, as in the Perl/R bindings' train_step demos).
 */
object Model {
  /** One synchronous SGD step on a bound training executor. */
  def sgdStep(exec: Executor, params: Map[String, Array[Float]],
              lr: Float): Map[String, Array[Float]] = {
    exec.forward(isTrain = true)
    exec.backward()
    params.map { case (name, value) =>
      val grad = exec.getGrad(name, value.length)
      val updated = new Array[Float](value.length)
      var i = 0
      while (i < value.length) {
        updated(i) = value(i) - lr * grad(i)
        i += 1
      }
      exec.setArg(name, updated)
      name -> updated
    }
  }
}
