/*
 * JNI glue between the Scala frontend and the framework's C ABI.
 *
 * Parity target: the reference scala-package's native layer
 * (scala-package/native/src/main/native/ml_dmlc_mxnet_native_c_api.cc —
 * hand-written JNI over include/mxnet/c_api.h). Fresh implementation
 * over include/mxnet_tpu/c_api.h: handles cross as jlong, tensors as
 * jfloatArray, names as jobjectArray of String.
 *
 * Built with the JDK's jni.h by the sbt/maven native build (see
 * ../../../../README.md); the repository CI compiles it against a stub
 * jni.h for a syntax/ABI-usage gate (tests/test_scala_package.py).
 */
#include <jni.h>
#include <stdlib.h>
#include <string.h>

#include <mxnet_tpu/c_api.h>

#define JNIFN(ret, name) \
  JNIEXPORT ret JNICALL Java_ml_mxnet_1tpu_LibInfo_##name

static void throw_mx(JNIEnv *env) {
  jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
  (*env)->ThrowNew(env, cls, MXGetLastError());
}

/* ---- NDArray ---------------------------------------------------------- */

JNIFN(jlong, ndCreate)(JNIEnv *env, jobject obj, jintArray jshape,
                       jint devType, jint devId) {
  jsize ndim = (*env)->GetArrayLength(env, jshape);
  jint *dims = (*env)->GetIntArrayElements(env, jshape, NULL);
  mx_uint *cdims = (mx_uint *)malloc(ndim * sizeof(mx_uint));
  for (jsize i = 0; i < ndim; ++i) cdims[i] = (mx_uint)dims[i];
  (*env)->ReleaseIntArrayElements(env, jshape, dims, JNI_ABORT);
  NDArrayHandle h = NULL;
  int rc = MXNDArrayCreate(cdims, (mx_uint)ndim, devType, devId, &h);
  free(cdims);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(void, ndFree)(JNIEnv *env, jobject obj, jlong handle) {
  MXNDArrayFree((NDArrayHandle)(intptr_t)handle);
}

JNIFN(void, ndSet)(JNIEnv *env, jobject obj, jlong handle,
                   jfloatArray jdata) {
  jsize n = (*env)->GetArrayLength(env, jdata);
  jfloat *data = (*env)->GetFloatArrayElements(env, jdata, NULL);
  int rc = MXNDArraySyncCopyFromCPU((NDArrayHandle)(intptr_t)handle,
                                    (const mx_float *)data, (mx_uint)n);
  (*env)->ReleaseFloatArrayElements(env, jdata, data, JNI_ABORT);
  if (rc != 0) throw_mx(env);
}

JNIFN(jfloatArray, ndGet)(JNIEnv *env, jobject obj, jlong handle) {
  mx_uint ndim = 0;
  const mx_uint *dims = NULL;
  if (MXNDArrayGetShape((NDArrayHandle)(intptr_t)handle, &ndim,
                        &dims) != 0) {
    throw_mx(env);
    return NULL;
  }
  mx_uint n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= dims[i];
  float *buf = (float *)malloc(n * sizeof(float));
  if (MXNDArraySyncCopyToCPU((NDArrayHandle)(intptr_t)handle, buf,
                             n) != 0) {
    free(buf);
    throw_mx(env);
    return NULL;
  }
  jfloatArray out = (*env)->NewFloatArray(env, (jsize)n);
  (*env)->SetFloatArrayRegion(env, out, 0, (jsize)n, buf);
  free(buf);
  return out;
}

JNIFN(jintArray, ndShape)(JNIEnv *env, jobject obj, jlong handle) {
  mx_uint ndim = 0;
  const mx_uint *dims = NULL;
  if (MXNDArrayGetShape((NDArrayHandle)(intptr_t)handle, &ndim,
                        &dims) != 0) {
    throw_mx(env);
    return NULL;
  }
  jintArray out = (*env)->NewIntArray(env, (jsize)ndim);
  jint *tmp = (jint *)malloc(ndim * sizeof(jint));
  for (mx_uint i = 0; i < ndim; ++i) tmp[i] = (jint)dims[i];
  (*env)->SetIntArrayRegion(env, out, 0, (jsize)ndim, tmp);
  free(tmp);
  return out;
}

/* ---- Symbol ----------------------------------------------------------- */

JNIFN(jlong, symCreateFromJSON)(JNIEnv *env, jobject obj, jstring jjson) {
  const char *json = (*env)->GetStringUTFChars(env, jjson, NULL);
  SymbolHandle h = NULL;
  int rc = MXSymbolCreateFromJSON(json, &h);
  (*env)->ReleaseStringUTFChars(env, jjson, json);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(jstring, symToJSON)(JNIEnv *env, jobject obj, jlong handle) {
  const char *json = NULL;
  if (MXSymbolSaveToJSON((SymbolHandle)(intptr_t)handle, &json) != 0) {
    throw_mx(env);
    return NULL;
  }
  return (*env)->NewStringUTF(env, json);
}

JNIFN(void, symFree)(JNIEnv *env, jobject obj, jlong handle) {
  MXSymbolFree((SymbolHandle)(intptr_t)handle);
}

static jobjectArray strs_to_java(JNIEnv *env, mx_uint n,
                                 const char **strs) {
  jclass cls = (*env)->FindClass(env, "java/lang/String");
  jobjectArray out = (*env)->NewObjectArray(env, (jsize)n, cls, NULL);
  for (mx_uint i = 0; i < n; ++i)
    (*env)->SetObjectArrayElement(env, out, (jsize)i,
                                  (*env)->NewStringUTF(env, strs[i]));
  return out;
}

JNIFN(jobjectArray, symListArguments)(JNIEnv *env, jobject obj,
                                      jlong handle) {
  mx_uint n = 0;
  const char **names = NULL;
  if (MXSymbolListArguments((SymbolHandle)(intptr_t)handle, &n,
                            &names) != 0) {
    throw_mx(env);
    return NULL;
  }
  return strs_to_java(env, n, names);
}

JNIFN(jobjectArray, symListOutputs)(JNIEnv *env, jobject obj,
                                    jlong handle) {
  mx_uint n = 0;
  const char **names = NULL;
  if (MXSymbolListOutputs((SymbolHandle)(intptr_t)handle, &n,
                          &names) != 0) {
    throw_mx(env);
    return NULL;
  }
  return strs_to_java(env, n, names);
}

JNIFN(jintArray, symInferArgSizes)(JNIEnv *env, jobject obj,
                                   jlong handle, jobjectArray jkeys,
                                   jintArray jindptr,
                                   jintArray jshapeData) {
  jsize nk = (*env)->GetArrayLength(env, jkeys);
  const char **keys = (const char **)malloc(nk * sizeof(char *));
  jstring *jstrs = (jstring *)malloc(nk * sizeof(jstring));
  for (jsize i = 0; i < nk; ++i) {
    jstrs[i] = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    keys[i] = (*env)->GetStringUTFChars(env, jstrs[i], NULL);
  }
  jsize ni = (*env)->GetArrayLength(env, jindptr);
  jsize nd = (*env)->GetArrayLength(env, jshapeData);
  jint *indptr = (*env)->GetIntArrayElements(env, jindptr, NULL);
  jint *sdata = (*env)->GetIntArrayElements(env, jshapeData, NULL);
  mx_uint *cind = (mx_uint *)malloc(ni * sizeof(mx_uint));
  mx_uint *cdata = (mx_uint *)malloc(nd * sizeof(mx_uint));
  for (jsize i = 0; i < ni; ++i) cind[i] = (mx_uint)indptr[i];
  for (jsize i = 0; i < nd; ++i) cdata[i] = (mx_uint)sdata[i];
  mx_uint in_n = 0, out_n = 0;
  const mx_uint *in_ndim = NULL, *out_ndim = NULL;
  const mx_uint **in_data = NULL, **out_data = NULL;
  int rc = MXSymbolInferShape((SymbolHandle)(intptr_t)handle,
                              (mx_uint)nk, keys, cind, cdata,
                              &in_n, &in_ndim, &in_data,
                              &out_n, &out_ndim, &out_data);
  for (jsize i = 0; i < nk; ++i)
    (*env)->ReleaseStringUTFChars(env, jstrs[i], keys[i]);
  free(keys); free(jstrs); free(cind); free(cdata);
  (*env)->ReleaseIntArrayElements(env, jindptr, indptr, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, jshapeData, sdata, JNI_ABORT);
  if (rc != 0) { throw_mx(env); return NULL; }
  jint *sizes = (jint *)malloc(in_n * sizeof(jint));
  for (mx_uint i = 0; i < in_n; ++i) {
    jint prod = 1;
    for (mx_uint d = 0; d < in_ndim[i]; ++d)
      prod *= (jint)in_data[i][d];
    sizes[i] = prod;
  }
  jintArray out = (*env)->NewIntArray(env, (jsize)in_n);
  (*env)->SetIntArrayRegion(env, out, 0, (jsize)in_n, sizes);
  free(sizes);
  return out;
}

/* ---- Executor --------------------------------------------------------- */

/* keys: input names; indptr/shapeData: csr shapes (row-major dims) */
JNIFN(jlong, execSimpleBind)(JNIEnv *env, jobject obj, jlong symHandle,
                             jint devType, jint devId, jobjectArray jkeys,
                             jintArray jindptr, jintArray jshapeData,
                             jint forTraining) {
  jsize nk = (*env)->GetArrayLength(env, jkeys);
  const char **keys = (const char **)malloc(nk * sizeof(char *));
  jstring *jstrs = (jstring *)malloc(nk * sizeof(jstring));
  for (jsize i = 0; i < nk; ++i) {
    jstrs[i] = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    keys[i] = (*env)->GetStringUTFChars(env, jstrs[i], NULL);
  }
  jsize ni = (*env)->GetArrayLength(env, jindptr);
  jsize nd = (*env)->GetArrayLength(env, jshapeData);
  jint *indptr = (*env)->GetIntArrayElements(env, jindptr, NULL);
  jint *sdata = (*env)->GetIntArrayElements(env, jshapeData, NULL);
  mx_uint *cind = (mx_uint *)malloc(ni * sizeof(mx_uint));
  mx_uint *cdata = (mx_uint *)malloc(nd * sizeof(mx_uint));
  for (jsize i = 0; i < ni; ++i) cind[i] = (mx_uint)indptr[i];
  for (jsize i = 0; i < nd; ++i) cdata[i] = (mx_uint)sdata[i];
  ExecutorHandle h = NULL;
  int rc = MXExecutorSimpleBind((SymbolHandle)(intptr_t)symHandle, devType,
                                devId, (mx_uint)nk, keys, cind, cdata,
                                forTraining, &h);
  for (jsize i = 0; i < nk; ++i)
    (*env)->ReleaseStringUTFChars(env, jstrs[i], keys[i]);
  free(keys); free(jstrs); free(cind); free(cdata);
  (*env)->ReleaseIntArrayElements(env, jindptr, indptr, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, jshapeData, sdata, JNI_ABORT);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(void, execSetArg)(JNIEnv *env, jobject obj, jlong handle,
                        jstring jname, jfloatArray jdata) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  jsize n = (*env)->GetArrayLength(env, jdata);
  jfloat *data = (*env)->GetFloatArrayElements(env, jdata, NULL);
  int rc = MXExecutorSetArg((ExecutorHandle)(intptr_t)handle, name,
                            (const mx_float *)data, (mx_uint)n);
  (*env)->ReleaseFloatArrayElements(env, jdata, data, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (rc != 0) throw_mx(env);
}

JNIFN(void, execSetAux)(JNIEnv *env, jobject obj, jlong handle,
                        jstring jname, jfloatArray jdata) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  jsize n = (*env)->GetArrayLength(env, jdata);
  jfloat *data = (*env)->GetFloatArrayElements(env, jdata, NULL);
  int rc = MXExecutorSetAux((ExecutorHandle)(intptr_t)handle, name,
                            (const mx_float *)data, (mx_uint)n);
  (*env)->ReleaseFloatArrayElements(env, jdata, data, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (rc != 0) throw_mx(env);
}

JNIFN(void, execForward)(JNIEnv *env, jobject obj, jlong handle,
                         jint isTrain) {
  if (MXExecutorForward((ExecutorHandle)(intptr_t)handle, isTrain) != 0)
    throw_mx(env);
}

JNIFN(void, execBackward)(JNIEnv *env, jobject obj, jlong handle) {
  if (MXExecutorBackward((ExecutorHandle)(intptr_t)handle) != 0)
    throw_mx(env);
}

JNIFN(jfloatArray, execGetOutput)(JNIEnv *env, jobject obj, jlong handle,
                                  jint index, jint size) {
  float *buf = (float *)malloc((size_t)size * sizeof(float));
  if (MXExecutorGetOutput((ExecutorHandle)(intptr_t)handle,
                          (mx_uint)index, buf, (mx_uint)size) != 0) {
    free(buf);
    throw_mx(env);
    return NULL;
  }
  jfloatArray out = (*env)->NewFloatArray(env, size);
  (*env)->SetFloatArrayRegion(env, out, 0, size, buf);
  free(buf);
  return out;
}

JNIFN(jfloatArray, execGetGrad)(JNIEnv *env, jobject obj, jlong handle,
                                jstring jname, jint size) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  float *buf = (float *)malloc((size_t)size * sizeof(float));
  int rc = MXExecutorGetGrad((ExecutorHandle)(intptr_t)handle, name, buf,
                             (mx_uint)size);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (rc != 0) {
    free(buf);
    throw_mx(env);
    return NULL;
  }
  jfloatArray out = (*env)->NewFloatArray(env, size);
  (*env)->SetFloatArrayRegion(env, out, 0, size, buf);
  free(buf);
  return out;
}

JNIFN(void, execFree)(JNIEnv *env, jobject obj, jlong handle) {
  MXExecutorFree((ExecutorHandle)(intptr_t)handle);
}

/* ---- KVStore (dist training from Spark workers) ----------------------- */

JNIFN(jlong, kvCreate)(JNIEnv *env, jobject obj, jstring jtype) {
  const char *type = (*env)->GetStringUTFChars(env, jtype, NULL);
  KVStoreHandle h = NULL;
  int rc = MXKVStoreCreate(type, &h);
  (*env)->ReleaseStringUTFChars(env, jtype, type);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(jint, kvRank)(JNIEnv *env, jobject obj, jlong handle) {
  int rank = 0;
  if (MXKVStoreGetRank((KVStoreHandle)(intptr_t)handle, &rank) != 0)
    throw_mx(env);
  return rank;
}

JNIFN(jint, kvNumWorkers)(JNIEnv *env, jobject obj, jlong handle) {
  int size = 0;
  if (MXKVStoreGetGroupSize((KVStoreHandle)(intptr_t)handle, &size) != 0)
    throw_mx(env);
  return size;
}

JNIFN(void, kvInit)(JNIEnv *env, jobject obj, jlong handle, jint key,
                    jlong ndHandle) {
  int k = key;
  NDArrayHandle v = (NDArrayHandle)(intptr_t)ndHandle;
  if (MXKVStoreInit((KVStoreHandle)(intptr_t)handle, 1, &k, &v) != 0)
    throw_mx(env);
}

JNIFN(void, kvPush)(JNIEnv *env, jobject obj, jlong handle, jint key,
                    jlong ndHandle, jint priority) {
  int k = key;
  NDArrayHandle v = (NDArrayHandle)(intptr_t)ndHandle;
  if (MXKVStorePush((KVStoreHandle)(intptr_t)handle, 1, &k, &v,
                    priority) != 0)
    throw_mx(env);
}

JNIFN(void, kvPull)(JNIEnv *env, jobject obj, jlong handle, jint key,
                    jlong ndHandle, jint priority) {
  int k = key;
  NDArrayHandle v = (NDArrayHandle)(intptr_t)ndHandle;
  if (MXKVStorePull((KVStoreHandle)(intptr_t)handle, 1, &k, &v,
                    priority) != 0)
    throw_mx(env);
}

JNIFN(void, kvBarrier)(JNIEnv *env, jobject obj, jlong handle) {
  if (MXKVStoreBarrier((KVStoreHandle)(intptr_t)handle) != 0)
    throw_mx(env);
}

JNIFN(void, kvFree)(JNIEnv *env, jobject obj, jlong handle) {
  MXKVStoreFree((KVStoreHandle)(intptr_t)handle);
}

/* ---- Round-2 surface: symbol file IO / grad, optimizer, misc ---------- */

JNIFN(void, randomSeed)(JNIEnv *env, jobject obj, jint seed) {
  if (MXRandomSeed((int)seed) != 0) throw_mx(env);
}

JNIFN(jlong, symCreateFromFile)(JNIEnv *env, jobject obj, jstring jpath) {
  const char *path = (*env)->GetStringUTFChars(env, jpath, NULL);
  SymbolHandle h = NULL;
  int rc = MXSymbolCreateFromFile(path, &h);
  (*env)->ReleaseStringUTFChars(env, jpath, path);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(void, symSaveToFile)(JNIEnv *env, jobject obj, jlong handle,
                           jstring jpath) {
  const char *path = (*env)->GetStringUTFChars(env, jpath, NULL);
  int rc = MXSymbolSaveToFile((SymbolHandle)(intptr_t)handle, path);
  (*env)->ReleaseStringUTFChars(env, jpath, path);
  if (rc != 0) throw_mx(env);
}

JNIFN(jlong, symGrad)(JNIEnv *env, jobject obj, jlong handle,
                      jobjectArray jwrt) {
  jsize n = (*env)->GetArrayLength(env, jwrt);
  const char **wrt = (const char **)malloc(n * sizeof(char *));
  for (jsize i = 0; i < n; ++i) {
    jstring s = (jstring)(*env)->GetObjectArrayElement(env, jwrt, i);
    wrt[i] = (*env)->GetStringUTFChars(env, s, NULL);
  }
  SymbolHandle out = NULL;
  int rc = MXSymbolGrad((SymbolHandle)(intptr_t)handle, (mx_uint)n, wrt,
                        &out);
  for (jsize i = 0; i < n; ++i) {
    jstring s = (jstring)(*env)->GetObjectArrayElement(env, jwrt, i);
    (*env)->ReleaseStringUTFChars(env, s, wrt[i]);
  }
  free(wrt);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)out;
}

JNIFN(jstring, symPrint)(JNIEnv *env, jobject obj, jlong handle) {
  const char *s = NULL;
  if (MXSymbolPrint((SymbolHandle)(intptr_t)handle, &s) != 0) {
    throw_mx(env);
    return NULL;
  }
  return (*env)->NewStringUTF(env, s);
}

JNIFN(jlong, optCreate)(JNIEnv *env, jobject obj, jstring jname,
                        jobjectArray jkeys, jobjectArray jvals) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  OptimizerCreator creator = NULL;
  if (MXOptimizerFindCreator(name, &creator) != 0) {
    (*env)->ReleaseStringUTFChars(env, jname, name);
    throw_mx(env);
    return 0;
  }
  (*env)->ReleaseStringUTFChars(env, jname, name);
  jsize n = (*env)->GetArrayLength(env, jkeys);
  const char **keys = (const char **)malloc(n * sizeof(char *));
  const char **vals = (const char **)malloc(n * sizeof(char *));
  for (jsize i = 0; i < n; ++i) {
    jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    jstring v = (jstring)(*env)->GetObjectArrayElement(env, jvals, i);
    keys[i] = (*env)->GetStringUTFChars(env, k, NULL);
    vals[i] = (*env)->GetStringUTFChars(env, v, NULL);
  }
  OptimizerHandle h = NULL;
  int rc = MXOptimizerCreateOptimizer(creator, (mx_uint)n, keys, vals, &h);
  for (jsize i = 0; i < n; ++i) {
    jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    jstring v = (jstring)(*env)->GetObjectArrayElement(env, jvals, i);
    (*env)->ReleaseStringUTFChars(env, k, keys[i]);
    (*env)->ReleaseStringUTFChars(env, v, vals[i]);
  }
  free(keys);
  free(vals);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(void, optUpdate)(JNIEnv *env, jobject obj, jlong handle, jint index,
                       jlong weight, jlong grad, jfloat lr, jfloat wd) {
  if (MXOptimizerUpdate((OptimizerHandle)(intptr_t)handle, (int)index,
                        (NDArrayHandle)(intptr_t)weight,
                        (NDArrayHandle)(intptr_t)grad, (mx_float)lr,
                        (mx_float)wd) != 0)
    throw_mx(env);
}

JNIFN(void, optFree)(JNIEnv *env, jobject obj, jlong handle) {
  MXOptimizerFree((OptimizerHandle)(intptr_t)handle);
}

/* ---- Registry symbol construction (round 3: typed Module API) --------- */

JNIFN(jlong, symCreateVariable)(JNIEnv *env, jobject obj, jstring jname) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  SymbolHandle h = NULL;
  int rc = MXSymbolCreateVariable(name, &h);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(jobjectArray, symListAtomic)(JNIEnv *env, jobject obj) {
  mx_uint n = 0;
  AtomicSymbolCreator *creators = NULL;
  if (MXSymbolListAtomicSymbolCreators(&n, &creators) != 0) {
    throw_mx(env);
    return NULL;
  }
  const char **names = (const char **)malloc(n * sizeof(char *));
  for (mx_uint i = 0; i < n; ++i)
    if (MXSymbolGetAtomicSymbolName(creators[i], &names[i]) != 0) {
      free(names);
      throw_mx(env);
      return NULL;
    }
  jobjectArray out = strs_to_java(env, n, names);
  free(names);
  return out;
}

/* one-time creator-name cache: creator lookup must not pay an
 * O(registry) Python round-trip per operator creation */
static mx_uint g_creator_count = 0;
static AtomicSymbolCreator *g_creators = NULL;
static const char **g_creator_names = NULL;

static int ensure_creator_cache(void) {
  if (g_creators != NULL) return 0;
  mx_uint n = 0;
  AtomicSymbolCreator *creators = NULL;
  if (MXSymbolListAtomicSymbolCreators(&n, &creators) != 0) return -1;
  const char **names = (const char **)malloc(n * sizeof(char *));
  for (mx_uint i = 0; i < n; ++i)
    if (MXSymbolGetAtomicSymbolName(creators[i], &names[i]) != 0) {
      free(names);
      return -1;
    }
  g_creator_count = n;
  g_creators = creators;
  g_creator_names = names;
  return 0;
}

JNIFN(jlong, symCreateAtomic)(JNIEnv *env, jobject obj, jstring jop,
                              jobjectArray jkeys, jobjectArray jvals) {
  const char *op = (*env)->GetStringUTFChars(env, jop, NULL);
  AtomicSymbolCreator creator = NULL;
  if (ensure_creator_cache() != 0) {
    (*env)->ReleaseStringUTFChars(env, jop, op);
    throw_mx(env);
    return 0;
  }
  for (mx_uint i = 0; i < g_creator_count && creator == NULL; ++i)
    if (strcmp(g_creator_names[i], op) == 0)
      creator = g_creators[i];
  (*env)->ReleaseStringUTFChars(env, jop, op);
  if (creator == NULL) {
    jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
    (*env)->ThrowNew(env, cls, "unknown operator");
    return 0;
  }
  jsize np = (*env)->GetArrayLength(env, jkeys);
  const char **keys = (const char **)malloc((np ? np : 1) * sizeof(char *));
  const char **vals = (const char **)malloc((np ? np : 1) * sizeof(char *));
  for (jsize i = 0; i < np; ++i) {
    jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    jstring v = (jstring)(*env)->GetObjectArrayElement(env, jvals, i);
    keys[i] = (*env)->GetStringUTFChars(env, k, NULL);
    vals[i] = (*env)->GetStringUTFChars(env, v, NULL);
  }
  SymbolHandle h = NULL;
  int rc = MXSymbolCreateAtomicSymbol(creator, (mx_uint)np, keys, vals, &h);
  for (jsize i = 0; i < np; ++i) {
    jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    jstring v = (jstring)(*env)->GetObjectArrayElement(env, jvals, i);
    (*env)->ReleaseStringUTFChars(env, k, keys[i]);
    (*env)->ReleaseStringUTFChars(env, v, vals[i]);
  }
  free(keys);
  free(vals);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(void, symCompose)(JNIEnv *env, jobject obj, jlong handle,
                        jstring jname, jobjectArray jkeys,
                        jlongArray jargs) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  jsize n = (*env)->GetArrayLength(env, jargs);
  jsize nk = jkeys ? (*env)->GetArrayLength(env, jkeys) : 0;
  jlong *args = (*env)->GetLongArrayElements(env, jargs, NULL);
  SymbolHandle *handles =
      (SymbolHandle *)malloc((n ? n : 1) * sizeof(SymbolHandle));
  for (jsize i = 0; i < n; ++i)
    handles[i] = (SymbolHandle)(intptr_t)args[i];
  const char **keys = NULL;
  if (nk > 0) {
    keys = (const char **)malloc(nk * sizeof(char *));
    for (jsize i = 0; i < nk; ++i) {
      jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
      keys[i] = (*env)->GetStringUTFChars(env, k, NULL);
    }
  }
  int rc = MXSymbolCompose((SymbolHandle)(intptr_t)handle, name,
                           (mx_uint)n, keys, handles);
  if (keys) {
    for (jsize i = 0; i < nk; ++i) {
      jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
      (*env)->ReleaseStringUTFChars(env, k, keys[i]);
    }
    free((void *)keys);
  }
  (*env)->ReleaseLongArrayElements(env, jargs, args, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  free(handles);
  if (rc != 0) throw_mx(env);
}

JNIFN(jobjectArray, symListAuxiliary)(JNIEnv *env, jobject obj,
                                      jlong handle) {
  mx_uint n = 0;
  const char **names = NULL;
  if (MXSymbolListAuxiliaryStates((SymbolHandle)(intptr_t)handle, &n,
                                  &names) != 0) {
    throw_mx(env);
    return NULL;
  }
  return strs_to_java(env, n, names);
}

/* Flattened shape inference: ONE native call returns all three
 * sections back-to-back — [count, ndim_0, dims..., ...] for args,
 * then outputs, then aux — so a Module bind runs inference once.
 * Uses the Partial ABI entry because it also carries aux shapes
 * (BatchNorm moving stats). */
JNIFN(jintArray, symInferShapes)(JNIEnv *env, jobject obj, jlong handle,
                                 jobjectArray jkeys, jintArray jindptr,
                                 jintArray jshapeData) {
  jsize nk = (*env)->GetArrayLength(env, jkeys);
  const char **keys = (const char **)malloc((nk ? nk : 1) * sizeof(char *));
  jstring *jstrs = (jstring *)malloc((nk ? nk : 1) * sizeof(jstring));
  for (jsize i = 0; i < nk; ++i) {
    jstrs[i] = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    keys[i] = (*env)->GetStringUTFChars(env, jstrs[i], NULL);
  }
  jsize ni = (*env)->GetArrayLength(env, jindptr);
  jsize nd = (*env)->GetArrayLength(env, jshapeData);
  jint *indptr = (*env)->GetIntArrayElements(env, jindptr, NULL);
  jint *sdata = (*env)->GetIntArrayElements(env, jshapeData, NULL);
  mx_uint *cind = (mx_uint *)malloc((ni ? ni : 1) * sizeof(mx_uint));
  mx_uint *cdata = (mx_uint *)malloc((nd ? nd : 1) * sizeof(mx_uint));
  for (jsize i = 0; i < ni; ++i) cind[i] = (mx_uint)indptr[i];
  for (jsize i = 0; i < nd; ++i) cdata[i] = (mx_uint)sdata[i];
  mx_uint in_n = 0, out_n = 0, aux_n = 0;
  const mx_uint *in_ndim = NULL, *out_ndim = NULL, *aux_ndim = NULL;
  const mx_uint **in_data = NULL, **out_data = NULL, **aux_data = NULL;
  int complete = 0;
  int rc = MXSymbolInferShapePartial(
      (SymbolHandle)(intptr_t)handle, (mx_uint)nk, keys, cind, cdata,
      &in_n, &in_ndim, &in_data, &out_n, &out_ndim, &out_data,
      &aux_n, &aux_ndim, &aux_data, &complete);
  for (jsize i = 0; i < nk; ++i)
    (*env)->ReleaseStringUTFChars(env, jstrs[i], keys[i]);
  free(keys); free(jstrs); free(cind); free(cdata);
  (*env)->ReleaseIntArrayElements(env, jindptr, indptr, JNI_ABORT);
  (*env)->ReleaseIntArrayElements(env, jshapeData, sdata, JNI_ABORT);
  if (rc != 0 || !complete) {
    if (rc == 0) {
      jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
      (*env)->ThrowNew(env, cls, "infer_shape incomplete");
    } else {
      throw_mx(env);
    }
    return NULL;
  }
  const mx_uint counts[3] = {in_n, out_n, aux_n};
  const mx_uint *ndims[3] = {in_ndim, out_ndim, aux_ndim};
  const mx_uint **datas[3] = {in_data, out_data, aux_data};
  jsize total = 0;
  for (int s = 0; s < 3; ++s) {
    total += 1;
    for (mx_uint i = 0; i < counts[s]; ++i)
      total += 1 + (jsize)ndims[s][i];
  }
  jint *flat = (jint *)malloc(total * sizeof(jint));
  jsize p = 0;
  for (int s = 0; s < 3; ++s) {
    flat[p++] = (jint)counts[s];
    for (mx_uint i = 0; i < counts[s]; ++i) {
      flat[p++] = (jint)ndims[s][i];
      for (mx_uint d = 0; d < ndims[s][i]; ++d)
        flat[p++] = (jint)datas[s][i][d];
    }
  }
  jintArray out = (*env)->NewIntArray(env, total);
  (*env)->SetIntArrayRegion(env, out, 0, total, flat);
  free(flat);
  return out;
}

JNIFN(jfloatArray, execGetAux)(JNIEnv *env, jobject obj, jlong handle,
                               jstring jname, jint size) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  float *buf = (float *)malloc((size ? size : 1) * sizeof(float));
  int rc = MXExecutorGetAux((ExecutorHandle)(intptr_t)handle,
                            name, buf, (mx_uint)size);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (rc != 0) { free(buf); throw_mx(env); return NULL; }
  jfloatArray out = (*env)->NewFloatArray(env, (jsize)size);
  (*env)->SetFloatArrayRegion(env, out, 0, (jsize)size, buf);
  free(buf);
  return out;
}

JNIFN(void, ndSave)(JNIEnv *env, jobject obj, jstring jpath,
                    jobjectArray jnames, jlongArray jhandles) {
  const char *path = (*env)->GetStringUTFChars(env, jpath, NULL);
  jsize n = (*env)->GetArrayLength(env, jhandles);
  jlong *hs = (*env)->GetLongArrayElements(env, jhandles, NULL);
  NDArrayHandle *handles =
      (NDArrayHandle *)malloc((n ? n : 1) * sizeof(NDArrayHandle));
  const char **names = (const char **)malloc((n ? n : 1) * sizeof(char *));
  for (jsize i = 0; i < n; ++i) {
    handles[i] = (NDArrayHandle)(intptr_t)hs[i];
    jstring s = (jstring)(*env)->GetObjectArrayElement(env, jnames, i);
    names[i] = (*env)->GetStringUTFChars(env, s, NULL);
  }
  int rc = MXNDArraySave(path, (mx_uint)n, handles, names);
  for (jsize i = 0; i < n; ++i) {
    jstring s = (jstring)(*env)->GetObjectArrayElement(env, jnames, i);
    (*env)->ReleaseStringUTFChars(env, s, names[i]);
  }
  (*env)->ReleaseLongArrayElements(env, jhandles, hs, JNI_ABORT);
  (*env)->ReleaseStringUTFChars(env, jpath, path);
  free(handles);
  free((void *)names);
  if (rc != 0) throw_mx(env);
}

/* ---- Imperative NDArray functions (NDArrayOpsGen) --------------------- */

/* Invoke a registered fixed-arity function by name; result is written
 * into `out` (reference FunctionBase.invoke over MXFuncInvoke). */
JNIFN(void, funcInvoke)(JNIEnv *env, jobject obj, jstring jname,
                        jlongArray juse, jfloatArray jscalars,
                        jlong out) {
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  FunctionHandle fun = NULL;
  int rc = MXGetFunction(name, &fun);
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (rc != 0) { throw_mx(env); return; }
  /* validate arity BEFORE the invoke: MXFuncInvoke indexes the
   * declared n_use/n_scalar elements, so short caller arrays would be
   * an out-of-bounds read, not an error */
  mx_uint want_use = 0, want_scalar = 0, want_mutate = 0;
  int type_mask = 0;
  if (MXFuncDescribe(fun, &want_use, &want_scalar, &want_mutate,
                     &type_mask) != 0) {
    throw_mx(env);
    return;
  }
  if ((mx_uint)(*env)->GetArrayLength(env, juse) != want_use ||
      (mx_uint)(*env)->GetArrayLength(env, jscalars) != want_scalar) {
    jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
    (*env)->ThrowNew(env, cls, "funcInvoke: arity mismatch");
    return;
  }
  jsize nu = (*env)->GetArrayLength(env, juse);
  jlong *uh = (*env)->GetLongArrayElements(env, juse, NULL);
  NDArrayHandle *use =
      (NDArrayHandle *)malloc((nu ? nu : 1) * sizeof(NDArrayHandle));
  for (jsize i = 0; i < nu; ++i) use[i] = (NDArrayHandle)(intptr_t)uh[i];
  (*env)->ReleaseLongArrayElements(env, juse, uh, JNI_ABORT);
  jfloat *sc = (*env)->GetFloatArrayElements(env, jscalars, NULL);
  NDArrayHandle mutate[1] = {(NDArrayHandle)(intptr_t)out};
  rc = MXFuncInvoke(fun, use, (const mx_float *)sc, mutate);
  (*env)->ReleaseFloatArrayElements(env, jscalars, sc, JNI_ABORT);
  free(use);
  if (rc != 0) throw_mx(env);
}

/* Registered imperative function names (MXListFunctions). */
JNIFN(jobjectArray, listFunctions)(JNIEnv *env, jobject obj) {
  mx_uint n = 0;
  FunctionHandle *funs = NULL;
  if (MXListFunctions(&n, &funs) != 0) { throw_mx(env); return NULL; }
  jclass strcls = (*env)->FindClass(env, "java/lang/String");
  jobjectArray out = (*env)->NewObjectArray(env, (jsize)n, strcls, NULL);
  for (mx_uint i = 0; i < n; ++i) {
    const char *name = NULL, *desc = NULL;
    mx_uint na = 0;
    const char **an = NULL, **at = NULL, **ad = NULL;
    if (MXFuncGetInfo(funs[i], &name, &desc, &na, &an, &at, &ad) != 0) {
      throw_mx(env);
      return NULL;
    }
    (*env)->SetObjectArrayElement(env, out, (jsize)i,
                                  (*env)->NewStringUTF(env, name));
  }
  return out;
}

/* Loads ONCE; element 0 is the String[] of names, element 1 the
 * long[] of handles. MXNDArrayListFree releases the load record AND
 * its handles, so each handle is first detached via MXNDArrayDup into
 * a fresh caller-owned handle (closed with the wrapper's dispose). */
JNIFN(jobjectArray, ndLoad)(JNIEnv *env, jobject obj, jstring jpath) {
  const char *path = (*env)->GetStringUTFChars(env, jpath, NULL);
  mx_uint n = 0, nn = 0;
  NDArrayHandle *handles = NULL;
  const char **names = NULL;
  int rc = MXNDArrayLoad(path, &n, &handles, &nn, &names);
  (*env)->ReleaseStringUTFChars(env, jpath, path);
  if (rc != 0) { throw_mx(env); return NULL; }
  jobjectArray jnames = strs_to_java(env, nn, names);
  jlong *hs = (jlong *)malloc((n ? n : 1) * sizeof(jlong));
  for (mx_uint i = 0; i < n; ++i) {
    NDArrayHandle dup = NULL;
    MXNDArrayDup(handles[i], &dup);
    hs[i] = (jlong)(intptr_t)dup;
  }
  jlongArray jhandles = (*env)->NewLongArray(env, (jsize)n);
  (*env)->SetLongArrayRegion(env, jhandles, 0, (jsize)n, hs);
  free(hs);
  MXNDArrayListFree(handles, n, names);
  jclass objcls = (*env)->FindClass(env, "java/lang/Object");
  jobjectArray out = (*env)->NewObjectArray(env, 2, objcls, NULL);
  (*env)->SetObjectArrayElement(env, out, 0, (jobject)jnames);
  (*env)->SetObjectArrayElement(env, out, 1, (jobject)jhandles);
  return out;
}

/* ---- Data iterators ----------------------------------------------------
 * Parity target: the reference Scala io package (ml.dmlc.mxnet.io
 * MXDataIter over MXDataIterCreateIter). Data/label handles returned by
 * the C API are views owned by the iterator, so values are copied into
 * fresh Java arrays here and never freed through MXNDArrayFree. */

JNIFN(jlong, iterCreate)(JNIEnv *env, jobject obj, jstring jname,
                         jobjectArray jkeys, jobjectArray jvals) {
  mx_uint n = 0;
  DataIterCreator *creators = NULL;
  if (MXListDataIters(&n, &creators) != 0) { throw_mx(env); return 0; }
  const char *name = (*env)->GetStringUTFChars(env, jname, NULL);
  DataIterCreator creator = NULL;
  for (mx_uint i = 0; i < n && creator == NULL; ++i) {
    const char *inm = NULL, *desc = NULL;
    if (MXDataIterGetIterInfo(creators[i], &inm, &desc) != 0) {
      (*env)->ReleaseStringUTFChars(env, jname, name);
      throw_mx(env);
      return 0;
    }
    if (strcmp(inm, name) == 0) creator = creators[i];
  }
  (*env)->ReleaseStringUTFChars(env, jname, name);
  if (creator == NULL) {
    jclass cls = (*env)->FindClass(env, "java/lang/RuntimeException");
    (*env)->ThrowNew(env, cls, "unknown data iterator");
    return 0;
  }
  jsize np = (*env)->GetArrayLength(env, jkeys);
  const char **keys = (const char **)malloc((np ? np : 1) * sizeof(char *));
  const char **vals = (const char **)malloc((np ? np : 1) * sizeof(char *));
  for (jsize i = 0; i < np; ++i) {
    jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    jstring v = (jstring)(*env)->GetObjectArrayElement(env, jvals, i);
    keys[i] = (*env)->GetStringUTFChars(env, k, NULL);
    vals[i] = (*env)->GetStringUTFChars(env, v, NULL);
  }
  DataIterHandle h = NULL;
  int rc = MXDataIterCreateIter(creator, (mx_uint)np, keys, vals, &h);
  for (jsize i = 0; i < np; ++i) {
    jstring k = (jstring)(*env)->GetObjectArrayElement(env, jkeys, i);
    jstring v = (jstring)(*env)->GetObjectArrayElement(env, jvals, i);
    (*env)->ReleaseStringUTFChars(env, k, keys[i]);
    (*env)->ReleaseStringUTFChars(env, v, vals[i]);
  }
  free(keys);
  free(vals);
  if (rc != 0) { throw_mx(env); return 0; }
  return (jlong)(intptr_t)h;
}

JNIFN(void, iterFree)(JNIEnv *env, jobject obj, jlong handle) {
  MXDataIterFree((DataIterHandle)(intptr_t)handle);
}

JNIFN(void, iterBeforeFirst)(JNIEnv *env, jobject obj, jlong handle) {
  if (MXDataIterBeforeFirst((DataIterHandle)(intptr_t)handle) != 0)
    throw_mx(env);
}

JNIFN(jint, iterNext)(JNIEnv *env, jobject obj, jlong handle) {
  int more = 0;
  if (MXDataIterNext((DataIterHandle)(intptr_t)handle, &more) != 0) {
    throw_mx(env);
    return 0;
  }
  return (jint)more;
}

static jfloatArray iter_copy_array(JNIEnv *env, NDArrayHandle h) {
  mx_uint ndim = 0;
  const mx_uint *dims = NULL;
  if (MXNDArrayGetShape(h, &ndim, &dims) != 0) {
    throw_mx(env);
    return NULL;
  }
  mx_uint n = 1;
  for (mx_uint i = 0; i < ndim; ++i) n *= dims[i];
  float *buf = (float *)malloc(n * sizeof(float));
  if (MXNDArraySyncCopyToCPU(h, buf, n) != 0) {
    free(buf);
    throw_mx(env);
    return NULL;
  }
  jfloatArray out = (*env)->NewFloatArray(env, (jsize)n);
  (*env)->SetFloatArrayRegion(env, out, 0, (jsize)n, buf);
  free(buf);
  return out;
}

JNIFN(jfloatArray, iterGetData)(JNIEnv *env, jobject obj, jlong handle) {
  NDArrayHandle h = NULL;
  if (MXDataIterGetData((DataIterHandle)(intptr_t)handle, &h) != 0) {
    throw_mx(env);
    return NULL;
  }
  return iter_copy_array(env, h);
}

JNIFN(jintArray, iterGetDataShape)(JNIEnv *env, jobject obj,
                                   jlong handle) {
  NDArrayHandle h = NULL;
  if (MXDataIterGetData((DataIterHandle)(intptr_t)handle, &h) != 0) {
    throw_mx(env);
    return NULL;
  }
  mx_uint ndim = 0;
  const mx_uint *dims = NULL;
  if (MXNDArrayGetShape(h, &ndim, &dims) != 0) {
    throw_mx(env);
    return NULL;
  }
  jintArray out = (*env)->NewIntArray(env, (jsize)ndim);
  jint *tmp = (jint *)malloc(ndim * sizeof(jint));
  for (mx_uint i = 0; i < ndim; ++i) tmp[i] = (jint)dims[i];
  (*env)->SetIntArrayRegion(env, out, 0, (jsize)ndim, tmp);
  free(tmp);
  return out;
}

JNIFN(jfloatArray, iterGetLabel)(JNIEnv *env, jobject obj, jlong handle) {
  NDArrayHandle h = NULL;
  if (MXDataIterGetLabel((DataIterHandle)(intptr_t)handle, &h) != 0) {
    throw_mx(env);
    return NULL;
  }
  return iter_copy_array(env, h);
}

JNIFN(jint, iterGetPadNum)(JNIEnv *env, jobject obj, jlong handle) {
  int pad = 0;
  if (MXDataIterGetPadNum((DataIterHandle)(intptr_t)handle, &pad) != 0) {
    throw_mx(env);
    return 0;
  }
  return (jint)pad;
}
