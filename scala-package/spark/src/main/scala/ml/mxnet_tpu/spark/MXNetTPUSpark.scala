package ml.mxnet_tpu.spark

import ml.mxnet_tpu.{Executor, KVStore, NDArray, Symbol}

/**
 * Spark integration (reference scala-package/spark: MXNet.scala trains
 * on an RDD by launching a parameter-server job across executors).
 *
 * TPU-native re-design: there is no server tier — each Spark task joins
 * a jax.distributed collective group via the dist_sync kvstore (the
 * coordinator address comes from MXTPU_COORDINATOR, set per job), and
 * gradients ride XLA collectives exactly like tools/launch.py workers.
 *
 * Collective discipline: every rank must run the SAME number of
 * push/pull rounds, so an epoch is exactly `epochSize` steps on every
 * rank, each rank cycling its local partition (Spark gives no
 * equal-partition guarantee; deriving steps from partition length would
 * desynchronize the collectives and hang the job).
 *
 * Usage from a Spark driver (spark-core on the deployment classpath;
 * this module is validated structurally in CI, like the reference's
 * spark module which also only ran inside a real cluster):
 *
 * {{{
 * val mx = new MXNetTPUSpark()
 *   .setSymbolJson(symbolJson)
 *   .setDimension(784)          // feature width of each row
 *   .setBatchSize(128)
 *   .setNumEpoch(10)
 *   .setEpochSize(50)           // collective steps per epoch, all ranks
 *   .setLearningRate(0.05f)
 * val weights = data.repartition(numWorkers).mapPartitions { part =>
 *   Iterator(mx.trainPartition(part.map(r => (r.label, r.features))))
 * }.collect().head              // all ranks return identical weights
 * }}}
 */
class MXNetTPUSpark extends Serializable {
  private var symbolJson: String = _
  private var batchSize: Int = 128
  private var numEpoch: Int = 10
  private var epochSize: Int = 0
  private var learningRate: Float = 0.01f
  private var dimension: Int = 0

  def setSymbolJson(json: String): this.type = { symbolJson = json; this }
  def setBatchSize(b: Int): this.type = { batchSize = b; this }
  def setNumEpoch(n: Int): this.type = { numEpoch = n; this }
  def setEpochSize(n: Int): this.type = { epochSize = n; this }
  def setLearningRate(lr: Float): this.type = { learningRate = lr; this }
  def setDimension(d: Int): this.type = { dimension = d; this }

  /** The per-task body the reference ran inside mapPartitions:
   *  synchronous data parallelism — every step pushes local gradients
   *  into the dist_sync kvstore (summed across workers over XLA
   *  collectives) and pulls the reduced result back before the update,
   *  so all ranks hold identical weights throughout. */
  def trainPartition(rows: Iterator[(Float, Array[Float])])
      : Map[String, Array[Float]] = {
    require(dimension > 0, "call setDimension(d) with the feature width")
    require(epochSize > 0,
            "call setEpochSize(n): all ranks must agree on the number " +
            "of collective steps per epoch")
    val kv = KVStore.create("dist_sync")
    try {
      val sym = Symbol.loadJson(symbolJson)
      val data = rows.toArray
      require(data.length >= batchSize,
              s"partition has ${data.length} rows < batchSize $batchSize")
      val exec = sym.simpleBind(
        Map("data" -> Array(batchSize, dimension)), forTraining = true)
      try {
        var params = initParams(sym, exec, kv)
        val keyOf = params.keys.toArray.sorted.zipWithIndex.toMap
        // the push sums gradients over workers and the loss sums over
        // the local batch: normalize like module.py's
        // rescale_grad = 1 / (batch_size * num_workers)
        val rescale = 1.0f / (batchSize * kv.numWorkers)
        var cursor = 0
        def nextBatch(): Array[(Float, Array[Float])] = {
          val out = Array.tabulate(batchSize) { i =>
            data((cursor + i) % data.length)
          }
          cursor = (cursor + batchSize) % data.length
          out
        }
        for (_ <- 0 until numEpoch) {
          for (_ <- 0 until epochSize) {
            val batch = nextBatch()
            exec.setArg("data", batch.flatMap(_._2))
            exec.setArg("softmax_label", batch.map(_._1))
            exec.forward(isTrain = true)
            exec.backward()
            params = params.map { case (name, value) =>
              val gnd = NDArray.array(exec.getGrad(name, value.length),
                                      Array(value.length))
              try {
                kv.push(keyOf(name), gnd)   // summed across workers
                kv.pull(keyOf(name), gnd)
                val reduced = gnd.toArray
                val updated = new Array[Float](value.length)
                var i = 0
                while (i < value.length) {
                  updated(i) = value(i) -
                    learningRate * rescale * reduced(i)
                  i += 1
                }
                exec.setArg(name, updated)
                name -> updated
              } finally gnd.close()
            }
          }
          kv.barrier()
        }
        params
      } finally exec.close()
    } finally kv.close()
  }

  private def initParams(sym: Symbol, exec: Executor, kv: KVStore)
      : Map[String, Array[Float]] = {
    val rng = new scala.util.Random(0)
    val sizes = sym.inferArgSizes(
      Map("data" -> Array(batchSize, dimension)))
    val paramNames = sym.listArguments
      .filterNot(n => n == "data" || n.endsWith("label"))
    val keyOf = paramNames.sorted.zipWithIndex.toMap
    paramNames.map { name =>
      // same seed on every rank -> identical init; kv.init registers
      // the key so later push/pull rounds are well-defined
      val values =
        Array.fill(sizes(name))((rng.nextFloat() - 0.5f) * 0.1f)
      val nd = NDArray.array(values, Array(values.length))
      try kv.init(keyOf(name), nd) finally nd.close()
      exec.setArg(name, values)
      name -> values
    }.toMap
  }
}
