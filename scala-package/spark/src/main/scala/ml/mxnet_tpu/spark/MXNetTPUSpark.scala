package ml.mxnet_tpu.spark

import ml.mxnet_tpu.{Executor, KVStore, Model, NDArray, Symbol}

/**
 * Spark integration (reference scala-package/spark: MXNet.scala trains
 * on an RDD by launching a parameter-server job across executors).
 *
 * TPU-native re-design: there is no server tier — each Spark task joins
 * a jax.distributed collective group via the dist_sync kvstore (the
 * coordinator address comes from MXTPU_COORDINATOR, set per job), and
 * gradients ride XLA collectives exactly like tools/launch.py workers.
 * The trainer is deliberately the same few steps as the reference's
 * MXNet.fit: partition the data, run a synchronous SGD loop per task,
 * return the (identical) rank-0 weights.
 *
 * Structural sketch — compiles against spark-core but, like the
 * reference's spark module, is exercised only inside a real cluster:
 *
 * {{{
 * val mx = new MXNetTPUSpark()
 *   .setSymbolJson(symbolJson)
 *   .setDimension(784)          // feature width of each row
 *   .setBatchSize(128)
 *   .setNumEpoch(10)
 *   .setLearningRate(0.05f)
 * val model = mx.fit(sc, labeledPoints)
 * }}}
 */
class MXNetTPUSpark extends Serializable {
  private var symbolJson: String = _
  private var batchSize: Int = 128
  private var numEpoch: Int = 10
  private var learningRate: Float = 0.01f
  private var dimension: Int = 0

  def setSymbolJson(json: String): this.type = { symbolJson = json; this }
  def setBatchSize(b: Int): this.type = { batchSize = b; this }
  def setNumEpoch(n: Int): this.type = { numEpoch = n; this }
  def setLearningRate(lr: Float): this.type = { learningRate = lr; this }
  def setDimension(d: Int): this.type = { dimension = d; this }

  /**
   * Train on an RDD[(label, features)]. Uses the type as a structural
   * dependency only so the module compiles without spark on the
   * classpath at CI time; in a deployment this is
   * org.apache.spark.rdd.RDD[(Float, Array[Float])].
   */
  def fitPartitions(
      partitions: Iterator[Iterator[(Float, Array[Float])]])
      : Map[String, Array[Float]] = {
    var result: Map[String, Array[Float]] = Map.empty
    partitions.foreach { part =>
      result = trainPartition(part)
    }
    result
  }

  /** The per-task body the reference ran inside mapPartitions:
   *  synchronous data parallelism — every step pushes local gradients
   *  into the dist_sync kvstore (which sums them across workers over
   *  XLA collectives) and pulls the reduced result back before the
   *  update, so all ranks hold identical weights throughout. */
  def trainPartition(rows: Iterator[(Float, Array[Float])])
      : Map[String, Array[Float]] = {
    require(dimension > 0, "call setDimension(d) with the feature width")
    val kv = KVStore.create("dist_sync")
    try {
      val sym = Symbol.loadJson(symbolJson)
      val data = rows.toArray
      val exec = sym.simpleBind(
        Map("data" -> Array(batchSize, dimension)), forTraining = true)
      try {
        var params = initParams(sym, exec)
        val keyOf = params.keys.toArray.sorted.zipWithIndex.toMap
        for ((name, key) <- keyOf)   // rank-0 values broadcast on init
          kv.init(key, NDArray.array(params(name),
                                     Array(params(name).length)))
        for (_ <- 0 until numEpoch) {
          data.grouped(batchSize).foreach { batch =>
            if (batch.length == batchSize) {
              exec.setArg("data", batch.flatMap(_._2))
              exec.setArg("softmax_label", batch.map(_._1))
              exec.forward(isTrain = true)
              exec.backward()
              params = params.map { case (name, value) =>
                val gnd = NDArray.array(exec.getGrad(name, value.length),
                                        Array(value.length))
                try {
                  kv.push(keyOf(name), gnd)   // summed across workers
                  kv.pull(keyOf(name), gnd)
                  val reduced = gnd.toArray
                  val updated = new Array[Float](value.length)
                  var i = 0
                  while (i < value.length) {
                    updated(i) = value(i) - learningRate * reduced(i)
                    i += 1
                  }
                  exec.setArg(name, updated)
                  name -> updated
                } finally gnd.close()
              }
            }
          }
          kv.barrier()
        }
        params
      } finally exec.close()
    } finally kv.close()
  }

  private def initParams(sym: Symbol, exec: Executor)
      : Map[String, Array[Float]] = {
    val rng = new scala.util.Random(0)
    val sizes = sym.inferArgSizes(
      Map("data" -> Array(batchSize, dimension)))
    sym.listArguments
      .filterNot(n => n == "data" || n.endsWith("label"))
      .map { name =>
        // same seed on every rank -> identical init, as the reference's
        // kvstore init broadcast guarantees
        val values =
          Array.fill(sizes(name))((rng.nextFloat() - 0.5f) * 0.1f)
        exec.setArg(name, values)
        name -> values
      }.toMap
  }
}
