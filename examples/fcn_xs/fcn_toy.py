#!/usr/bin/env python
"""FCN semantic segmentation (reference example/fcn-xs): a conv
encoder, a 1x1 class head, and a Deconvolution (transposed conv)
upsampling path with Crop to the input geometry — per-pixel
SoftmaxOutput with multi_output, trained on a synthetic
blob-segmentation task.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

SIZE = 16
CLASSES = 2


def build_net():
    data = mx.sym.Variable("data")                        # (N,1,16,16)
    c1 = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                            num_filter=8, name="c1")
    c1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(c1, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")                  # (N,8,8,8)
    c2 = mx.sym.Convolution(p1, kernel=(3, 3), pad=(1, 1),
                            num_filter=16, name="c2")
    c2 = mx.sym.Activation(c2, act_type="relu")
    score = mx.sym.Convolution(c2, kernel=(1, 1), num_filter=CLASSES,
                               name="score")              # (N,C,8,8)
    up = mx.sym.Deconvolution(score, kernel=(4, 4), stride=(2, 2),
                              pad=(1, 1), num_filter=CLASSES,
                              name="up")                  # (N,C,16,16)
    up = mx.sym.Crop(up, data, name="crop")               # FCN crop-to-ref
    return mx.sym.SoftmaxOutput(up, multi_output=True, name="softmax")


def make_data(rng, n):
    """Images with a bright square blob; label = blob mask."""
    X = rng.rand(n, 1, SIZE, SIZE).astype(np.float32) * 0.3
    Y = np.zeros((n, SIZE, SIZE), np.float32)
    for i in range(n):
        r, c = rng.randint(1, SIZE - 9, 2)
        h, w = rng.randint(6, 9, 2)
        X[i, 0, r:r + h, c:c + w] += 0.7
        Y[i, r:r + h, c:c + w] = 1.0
    return X, Y


def main(seed=0):
    rng = np.random.RandomState(seed)
    X, Y = make_data(rng, 256)
    net = build_net()
    it = mx.io.NDArrayIter({"data": X}, {"softmax_label": Y},
                           batch_size=32, shuffle=True)
    model = mx.model.FeedForward.create(
        net, X=it, num_epoch=25, optimizer="adam", learning_rate=2e-2,
        ctx=mx.cpu())
    pred = model.predict(mx.io.NDArrayIter({"data": X}, batch_size=32))
    mask = pred.argmax(axis=1)                            # (N,16,16)
    iou_num = np.logical_and(mask == 1, Y == 1).sum()
    iou_den = np.logical_or(mask == 1, Y == 1).sum()
    iou = iou_num / max(iou_den, 1)
    print("blob IoU: %.3f" % iou)
    assert iou > 0.8, iou
    print("FCN OK")


if __name__ == "__main__":
    main()
