#!/usr/bin/env python
"""Torch plugin (reference plugin/torch + example/torch): a PyTorch
nn.Module embedded as a graph op via the torch bridge, trained
end-to-end next to native ops.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def main(seed=0):
    try:
        import torch
        import torch.nn as nn
    except ImportError:
        print("torch not available; skipping")
        return

    from mxnet_tpu.plugins.torch_bridge import torch_module

    rng = np.random.RandomState(seed)
    n, d = 384, 16
    y = rng.randint(0, 2, n).astype(np.float32)
    X = (rng.randn(n, d) + y[:, None] * 1.5).astype(np.float32)

    # a torch block in the middle of an mx graph
    data = mx.sym.Variable("data")
    h = torch_module(lambda: nn.Sequential(nn.Linear(16, 32), nn.Tanh()),
                     data=data, name="torchblock",
                     infer_shape_fn=lambda s: (s[0][0], 32))
    out = mx.sym.FullyConnected(h, num_hidden=2, name="cls")
    out = mx.sym.SoftmaxOutput(out, name="softmax")

    model = mx.model.FeedForward.create(
        out, X=mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True),
        num_epoch=6, learning_rate=0.2, ctx=mx.cpu())
    acc = (model.predict(mx.io.NDArrayIter(X, y, batch_size=64))
           .argmax(axis=1) == y).mean()
    print("accuracy with embedded torch block: %.3f" % acc)
    assert acc > 0.85, acc
    print("torch plugin OK")


if __name__ == "__main__":
    main()
