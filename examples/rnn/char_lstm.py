#!/usr/bin/env python
"""Character-level LSTM language model + sampling (reference
example/rnn/char_lstm.ipynb / lstm.py): train the fused-scan LSTM on a
text corpus, then generate text one character at a time.

With no corpus file given, trains on a built-in pattern text so the
script runs offline and the sampler's output is checkable.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import lstm_fused

DEFAULT_TEXT = ("the quick brown fox jumps over the lazy dog. " * 200)


def make_batches(text, vocab, seq_len, batch_size):
    ids = np.array([vocab[c] for c in text], dtype=np.float32)
    n_seq = (len(ids) - 1) // seq_len
    X = ids[:n_seq * seq_len].reshape(n_seq, seq_len)
    Y = ids[1:n_seq * seq_len + 1].reshape(n_seq, seq_len)
    n_batch = n_seq // batch_size * batch_size
    return X[:n_batch], Y[:n_batch]


def main():
    p = argparse.ArgumentParser(description="char-level LSTM LM")
    p.add_argument("--corpus", default=None, help="text file to train on")
    p.add_argument("--seq-len", type=int, default=32)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=16)
    p.add_argument("--num-layers", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-epochs", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--sample-len", type=int, default=120)
    args = p.parse_args()

    text = (open(args.corpus).read() if args.corpus else DEFAULT_TEXT)
    chars = sorted(set(text))
    vocab = {c: i for i, c in enumerate(chars)}
    inv_vocab = {i: c for c, i in vocab.items()}
    print("corpus: %d chars, vocab %d" % (len(text), len(vocab)))

    X, Y = make_batches(text, vocab, args.seq_len, args.batch_size)
    net = lstm_fused(args.num_layers, args.seq_len, len(vocab),
                     args.num_hidden, args.num_embed, len(vocab))
    it = mx.io.NDArrayIter(X, {"softmax_label": Y},
                           batch_size=args.batch_size, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})
    metric = mx.metric.create("ce")
    for epoch in range(args.num_epochs):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            # outputs are time-major flattened; align the label the same
            lab = batch.label[0].asnumpy().T.ravel()
            metric.update([mx.nd.array(lab)], mod.get_outputs())
        ce = metric.get()[1]
        print("epoch %d cross-entropy %.4f (ppl %.2f)"
              % (epoch, ce, np.exp(ce)))
    arg_params, aux_params = mod.get_params()

    # ---- sampling: re-bind at seq_len=1-ish by feeding a sliding window
    sample_net = lstm_fused(args.num_layers, args.seq_len, len(vocab),
                            args.num_hidden, args.num_embed, len(vocab))
    exe = sample_net.simple_bind(ctx=mx.cpu(), grad_req="null",
                                 data=(1, args.seq_len),
                                 softmax_label=(1, args.seq_len))
    # copy weights only — RNN begin-state args are batch-shaped and the
    # sampler binds batch 1 (fresh zero states are what we want anyway)
    weights = {n: v for n, v in arg_params.items()
               if tuple(v.shape) == tuple(exe.arg_dict[n].shape)}
    exe.copy_params_from(weights, aux_params)
    window = [vocab[text[i]] for i in range(args.seq_len)]
    out_chars = []
    rng = np.random.RandomState(0)
    for _ in range(args.sample_len):
        exe.forward(is_train=False,
                    data=np.array([window], dtype=np.float32))
        # outputs are time-major flattened (seq, batch, vocab): the last
        # timestep of the window predicts the next char
        probs = exe.outputs[0].asnumpy().reshape(
            args.seq_len, 1, len(vocab))[-1, 0]
        nxt = int(rng.choice(len(vocab), p=probs / probs.sum()))
        out_chars.append(inv_vocab[nxt])
        window = window[1:] + [nxt]
    sample = "".join(out_chars)
    print("sample:", repr(sample))
    if args.corpus is None:
        # trained on a periodic pattern: sampled text should reuse its
        # vocabulary heavily (crude but deterministic quality check)
        common = sum(sample.count(w) for w in ("the", "fox", "dog", "lazy"))
        print("pattern words in sample:", common)
        assert common >= 4


if __name__ == "__main__":
    main()
