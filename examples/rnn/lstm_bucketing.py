#!/usr/bin/env python
"""Bucketed LSTM language model (reference example/rnn/lstm_bucketing.py):
variable-length sequences grouped into buckets, one executor per bucket
sharing parameters via BucketingModule."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models


class BucketSentenceIter(mx.io.DataIter):
    """Group token sequences into buckets (reference BucketSentenceIter)."""

    def __init__(self, sentences, buckets, batch_size, vocab_size):
        super().__init__()
        self.buckets = sorted(buckets)
        self.batch_size = batch_size
        self.vocab_size = vocab_size
        self.data = {b: [] for b in self.buckets}
        for s in sentences:
            for b in self.buckets:
                if len(s) <= b:
                    padded = np.zeros(b, dtype=np.float32)
                    padded[:len(s)] = s
                    self.data[b].append(padded)
                    break
        self.plan = []
        for b in self.buckets:
            arr = np.array(self.data[b], dtype=np.float32)
            for i in range(len(arr) // batch_size):
                self.plan.append((b, arr[i * batch_size:(i + 1) * batch_size]))
        self.cur = 0
        self.default_bucket_key = self.buckets[-1]

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data",
                               (self.batch_size, self.default_bucket_key))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("softmax_label",
                               (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self.cur = 0

    def __next__(self):
        if self.cur >= len(self.plan):
            raise StopIteration
        bucket, batch = self.plan[self.cur]
        self.cur += 1
        # next-token labels (shifted by one)
        label = np.zeros_like(batch)
        label[:, :-1] = batch[:, 1:]
        return mx.io.DataBatch(
            [mx.nd.array(batch)], [mx.nd.array(label)],
            bucket_key=bucket,
            provide_data=[mx.io.DataDesc("data", (self.batch_size, bucket))],
            provide_label=[mx.io.DataDesc("softmax_label",
                                          (self.batch_size, bucket))])

    next = __next__


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-hidden", type=int, default=64)
    parser.add_argument("--num-embed", type=int, default=32)
    parser.add_argument("--num-layers", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--vocab", type=int, default=64)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    buckets = [8, 16, 24]
    rng = np.random.RandomState(0)
    sentences = [rng.randint(1, args.vocab, rng.randint(4, 24))
                 for _ in range(512)]
    data = BucketSentenceIter(sentences, buckets, args.batch_size, args.vocab)

    def sym_gen(seq_len):
        net = models.lstm_fused(args.num_layers, seq_len, args.vocab,
                                args.num_hidden, args.num_embed, args.vocab)
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=data.default_bucket_key,
                                 context=mx.cpu())
    mod.bind(data.provide_data, data.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    for epoch in range(args.num_epochs):
        data.reset()
        n = 0
        for batch in data:
            mod.forward_backward(batch)
            mod.update()
            n += 1
        logging.info("Epoch[%d] processed %d bucketed batches "
                     "(buckets bound: %s)", epoch, n,
                     sorted(mod._buckets.keys()))
    print("buckets bound:", sorted(mod._buckets.keys()))


if __name__ == "__main__":
    main()
