#!/usr/bin/env python
"""Deep Embedded Clustering (reference example/dec): pretrain an
autoencoder, k-means the embeddings, then jointly refine encoder +
centroids by minimizing KL(P || Q) of the student-t soft assignments —
the whole DEC objective built from symbols (pow/broadcast/MakeLoss),
with the centroids as a trainable Variable.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

K = 3       # clusters
EMB = 2     # embedding dim
D = 16      # input dim


def encoder(data):
    h = mx.sym.FullyConnected(data, num_hidden=32, name="enc1")
    h = mx.sym.Activation(h, act_type="relu")
    return mx.sym.FullyConnected(h, num_hidden=EMB, name="emb")


def soft_assignment(z, centroids, n):
    """Student-t q_ij over (n, K): 1/(1+||z_i - mu_j||^2) normalized."""
    zb = mx.sym.Reshape(z, shape=(n, 1, EMB))
    zb = mx.sym.broadcast_axis(zb, axis=1, size=K)          # (n,K,E)
    cb = mx.sym.Reshape(centroids, shape=(1, K, EMB))
    cb = mx.sym.broadcast_axis(cb, axis=0, size=n)          # (n,K,E)
    d2 = mx.sym.sum(mx.sym.square(zb - cb), axis=2)         # (n,K)
    inv = 1.0 / (1.0 + d2)
    return inv / mx.sym.Reshape(mx.sym.sum(inv, axis=1), shape=(n, 1))


def main(seed=0, n=300):
    rng = np.random.RandomState(seed)
    # 3 gaussian clusters living on a low-dim manifold in 16-d
    labels = rng.randint(0, K, n)
    centers2d = np.array([[3, 0], [-3, 0], [0, 3]], np.float32)
    latent = centers2d[labels] + rng.randn(n, 2) * 0.4
    lift = rng.randn(2, D).astype(np.float32)
    X = np.tanh(latent @ lift).astype(np.float32)

    # --- 1. pretrain the autoencoder -----------------------------------
    data = mx.sym.Variable("data")
    z = encoder(data)
    dec = mx.sym.FullyConnected(z, num_hidden=32, name="dec0")
    dec = mx.sym.Activation(dec, act_type="relu")
    dec = mx.sym.FullyConnected(dec, num_hidden=D, name="dec1")
    recon = mx.sym.LinearRegressionOutput(
        data=dec, label=mx.sym.Variable("recon_label"), name="recon")
    ae = recon.simple_bind(mx.cpu(), data=(n, D), recon_label=(n, D))
    init = mx.init.Xavier()
    for name, arr in ae.arg_dict.items():
        if name not in ("data", "recon_label"):
            init(name, arr)
    up = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=5e-3))
    ae.arg_dict["data"][:] = X
    ae.arg_dict["recon_label"][:] = X
    for step in range(1200):
        ae.forward(is_train=True)
        ae.backward()
        for i, nm in enumerate(recon.list_arguments()):
            if nm in ("data", "recon_label"):
                continue
            up(i, ae.grad_dict[nm], ae.arg_dict[nm])

    # --- 2. k-means init of centroids on the embeddings ----------------
    emb_exe = z.simple_bind(mx.cpu(), data=(n, D))
    emb_exe.arg_dict["data"][:] = X
    for nm in ("enc1_weight", "enc1_bias", "emb_weight", "emb_bias"):
        emb_exe.arg_dict[nm][:] = ae.arg_dict[nm].asnumpy()
    Z = emb_exe.forward()[0].asnumpy()

    def kmeans_once(init_idx):
        m = Z[init_idx].copy()
        for _ in range(25):
            a = ((Z[:, None, :] - m[None]) ** 2).sum(2).argmin(1)
            for j in range(K):
                if (a == j).any():
                    m[j] = Z[a == j].mean(axis=0)
        inertia = ((Z - m[a]) ** 2).sum()
        return m, inertia

    # multi-restart: a single draw can seed two centroids in one cluster
    mu, best = None, np.inf
    for _ in range(5):
        m, inertia = kmeans_once(rng.choice(n, K, replace=False))
        if inertia < best:
            mu, best = m, inertia

    # --- 3. DEC refinement: minimize KL(P||Q), centroids trainable -----
    q = soft_assignment(encoder(data), mx.sym.Variable("centroids"), n)
    p = mx.sym.Variable("target_p")
    kl = mx.sym.MakeLoss(mx.sym.sum(p * (mx.sym.log(p) - mx.sym.log(q))))
    dec_exe = kl.simple_bind(mx.cpu(), data=(n, D), centroids=(K, EMB),
                             target_p=(n, K),
                             grad_req={nm: "write" for nm
                                       in kl.list_arguments()
                                       if nm not in ("data", "target_p")})
    for nm in ("enc1_weight", "enc1_bias", "emb_weight", "emb_bias"):
        dec_exe.arg_dict[nm][:] = ae.arg_dict[nm].asnumpy()
    dec_exe.arg_dict["centroids"][:] = mu
    dec_exe.arg_dict["data"][:] = X
    up2 = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=2e-3))
    for it in range(30):
        # current Q -> sharpened target P (DEC eq. 3), updated per epoch
        # (computed host-side from the current embedding + centroids)
        Zc = dec_exe.arg_dict["centroids"].asnumpy()
        for nm in ("enc1_weight", "enc1_bias", "emb_weight", "emb_bias"):
            emb_exe.arg_dict[nm][:] = dec_exe.arg_dict[nm].asnumpy()
        Z = emb_exe.forward()[0].asnumpy()
        inv = 1.0 / (1.0 + ((Z[:, None] - Zc[None]) ** 2).sum(2))
        Q = inv / inv.sum(1, keepdims=True)
        W = Q ** 2 / Q.sum(0, keepdims=True)
        P = W / W.sum(1, keepdims=True)
        dec_exe.arg_dict["target_p"][:] = P.astype(np.float32)
        for _ in range(10):
            dec_exe.forward(is_train=True)
            dec_exe.backward()
            for i, nm in enumerate(kl.list_arguments()):
                if nm in ("data", "target_p"):
                    continue
                up2(100 + i, dec_exe.grad_dict[nm], dec_exe.arg_dict[nm])

    # --- evaluate: cluster purity under best label permutation ---------
    assign = Q.argmax(1)
    from itertools import permutations

    acc = max((assign == np.array([perm[l] for l in labels])).mean()
              for perm in permutations(range(K)))
    print("DEC cluster accuracy (best permutation): %.3f" % acc)
    assert acc > 0.9, acc
    print("DEC OK")


if __name__ == "__main__":
    main()
