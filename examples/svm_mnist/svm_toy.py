#!/usr/bin/env python
"""SVM output layer (reference example/svm_mnist): the same MLP trained
with SVMOutput (L2 hinge and L1 hinge) instead of softmax.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def build(use_linear):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SVMOutput(net, margin=1.0, regularization_coefficient=1.0,
                            use_linear=use_linear, name="svm")


def main(seed=0):
    rng = np.random.RandomState(seed)
    n, d = 512, 16
    y = rng.randint(0, 4, n).astype(np.float32)
    centers = rng.randn(4, d) * 2.5
    X = (centers[y.astype(int)] + rng.randn(n, d) * 0.6).astype(np.float32)
    for use_linear, name in ((False, "L2-SVM"), (True, "L1-SVM")):
        model = mx.model.FeedForward.create(
            build(use_linear),
            X=mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True,
                                label_name="svm_label"),
            num_epoch=10, learning_rate=0.05, ctx=mx.cpu())
        acc = (model.predict(mx.io.NDArrayIter(X, batch_size=64))
               .argmax(axis=1) == y).mean()
        print("%s train accuracy: %.3f" % (name, acc))
        assert acc > 0.9, (name, acc)
    print("SVM outputs OK")


if __name__ == "__main__":
    main()
