#!/usr/bin/env python
"""Finetuning (reference docs/how_to/finetune + pretrained-model zoo
workflow): load a trained checkpoint, graft a new classifier head onto
the trunk via get_internals, seed the trunk from the checkpoint's
arg_params, and train the new head — matching-name weight reuse, the
exact mechanics the reference used for ImageNet-pretrained finetuning.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def base_net(num_classes):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="trunk1")
    net = mx.sym.Activation(net, act_type="relu", name="trunk_relu")
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="head")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def make_task(rng, n, d, k, w):
    y = rng.randint(0, k, n).astype(np.float32)
    X = (rng.randn(n, d) + w[y.astype(int)]).astype(np.float32)
    return X, y


def main(seed=0):
    rng = np.random.RandomState(seed)
    d = 16
    # pretraining task: 4 classes on a shared feature basis
    basis = rng.randn(6, d) * 2.0
    Xa, ya = make_task(rng, 512, d, 4, basis[:4])
    model = mx.model.FeedForward.create(
        base_net(4), X=mx.io.NDArrayIter(Xa, ya, batch_size=64,
                                         shuffle=True),
        num_epoch=8, learning_rate=0.2, ctx=mx.cpu())
    prefix = os.path.join(tempfile.mkdtemp(), "pretrained")
    model.save(prefix, 8)

    # --- finetune: same trunk, NEW 2-way head, small target dataset ---
    Xb, yb = make_task(rng, 96, d, 2, basis[4:6])
    sym_loaded, arg_params, aux_params = mx.model.load_checkpoint(prefix, 8)
    trunk = sym_loaded.get_internals()["trunk_relu_output"]
    new_head = mx.sym.FullyConnected(trunk, num_hidden=2, name="newhead")
    new_net = mx.sym.SoftmaxOutput(new_head, name="softmax")

    # trunk weights come from the checkpoint (matching names); the new
    # head initializes fresh. allow_missing is the reference's finetune
    # switch for exactly this.
    ft = mx.mod.Module(new_net, context=mx.cpu())
    it = mx.io.NDArrayIter(Xb, yb, batch_size=32, shuffle=True)
    ft.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    ft.init_params(mx.init.Xavier(), arg_params=arg_params,
                   aux_params=aux_params, allow_missing=True)
    # verify the trunk really came from the checkpoint
    got = ft.get_params()[0]["trunk1_weight"].asnumpy()
    np.testing.assert_allclose(got, arg_params["trunk1_weight"].asnumpy())
    ft.fit(it, num_epoch=6, optimizer_params={"learning_rate": 0.1})
    acc = (ft.predict(mx.io.NDArrayIter(Xb, batch_size=32)).asnumpy()
           .argmax(axis=1) == yb).mean()

    # scratch baseline on the same small data
    scratch = mx.mod.Module(new_net, context=mx.cpu())
    it.reset()
    scratch.fit(it, num_epoch=6, optimizer_params={"learning_rate": 0.1})
    scratch_acc = (scratch.predict(mx.io.NDArrayIter(Xb, batch_size=32))
                   .asnumpy().argmax(axis=1) == yb).mean()
    print("finetuned acc: %.3f  from-scratch acc: %.3f" % (acc, scratch_acc))
    assert acc > 0.9, acc
    print("finetune OK")


if __name__ == "__main__":
    main()
