#!/usr/bin/env python
"""Long-context sequence parallelism (docs/long_context.md): a 4096-token
causal attention sharded over an 8-way ``sp`` mesh with ring attention —
each device holds T/8 of the sequence and K/V blocks rotate around the
ring via collective_permute, so no device ever materializes the full
T x T score matrix. Verified against single-device reference attention.

Runs on 8 virtual CPU devices (the script self-bootstraps XLA_FLAGS
before jax initializes) — the same code path the TPU mesh uses.
"""
import os
import sys

if "--child" not in sys.argv:
    # re-exec with the virtual 8-device CPU platform configured BEFORE
    # jax initializes (appending XLA_FLAGS later has no effect)
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    env["JAX_PLATFORMS"] = "cpu"
    os.execvpe(sys.executable,
               [sys.executable, os.path.abspath(__file__), "--child"], env)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.ring_attention import (make_ring_attention,
                                               reference_attention)


def main(seed=0, T=4096, H=8, D=32):
    mesh = make_mesh({"sp": 8})
    rng = np.random.RandomState(seed)
    q, k, v = (rng.randn(1, T, H, D).astype(np.float32) * 0.1
               for _ in range(3))

    attn = make_ring_attention(mesh, "sp", causal=True, impl="ring")
    out = np.asarray(attn(q, k, v))

    ref = np.asarray(reference_attention(q, k, v, causal=True))
    err = np.abs(out - ref).max()
    print("T=%d over 8-way sp mesh; max |ring - reference| = %.2e"
          % (T, err))
    assert err < 2e-5, err

    # Ulysses (all-to-all head parallelism) on the same mesh
    attn_u = make_ring_attention(mesh, "sp", causal=True, impl="ulysses")
    err_u = np.abs(np.asarray(attn_u(q, k, v)) - ref).max()
    print("ulysses max err = %.2e" % err_u)
    assert err_u < 2e-5, err_u

    # the point of sequence parallelism: per-device score-block memory
    full = T * T * H * 4 / 2**20
    block = (T // 8) * (T // 8) * H * 4 / 2**20
    print("score memory per device: full %.0f MiB -> ring block %.1f MiB"
          % (full, block))
    print("ring attention OK")


if __name__ == "__main__":
    main()
