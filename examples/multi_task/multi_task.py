#!/usr/bin/env python
"""Multi-task training (reference example/multi-task): one trunk, two
heads/losses joined with sym.Group, custom multi-metric.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build_net():
    data = mx.sym.Variable("data")
    trunk = mx.sym.FullyConnected(data=data, num_hidden=32, name="fc1")
    trunk = mx.sym.Activation(data=trunk, act_type="relu")
    head1 = mx.sym.FullyConnected(data=trunk, num_hidden=4, name="fc_cls")
    head1 = mx.sym.SoftmaxOutput(data=head1, name="softmax1",
                                 label=mx.sym.Variable("cls_label"))
    head2 = mx.sym.FullyConnected(data=trunk, num_hidden=1, name="fc_reg")
    head2 = mx.sym.LinearRegressionOutput(data=head2, name="reg",
                                          label=mx.sym.Variable("reg_label"))
    return mx.sym.Group([head1, head2])


class MultiMetric(mx.metric.EvalMetric):
    """Accuracy on the classification head + MSE on the regression head
    (reference example/multi-task's Multi_Accuracy idea)."""

    def __init__(self):
        super().__init__("multi")

    def update(self, labels, preds):
        cls_lbl = labels[0].asnumpy()
        probs = preds[0].asnumpy()
        reg_lbl = labels[1].asnumpy()
        reg = preds[1].asnumpy()
        acc = (probs.argmax(axis=1) == cls_lbl).mean()
        mse = ((reg - reg_lbl) ** 2).mean()
        # store acc - mse as a single "higher is better" scalar for fit
        # logging; score both properly below
        self.sum_metric += float(acc - mse)
        self.num_inst += 1


def main():
    rng = np.random.RandomState(0)
    n = 512
    y_cls = rng.randint(0, 4, n).astype(np.float32)
    X = rng.randn(n, 8).astype(np.float32) * 0.3
    X[np.arange(n), (y_cls * 2).astype(int)] += 1.5
    y_reg = (X.sum(axis=1) * 0.5).astype(np.float32).reshape(n, 1)

    net = build_net()
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["cls_label", "reg_label"])
    it = mx.io.NDArrayIter({"data": X},
                           {"cls_label": y_cls, "reg_label": y_reg},
                           batch_size=64)
    mod.fit(it, num_epoch=20, eval_metric=MultiMetric(),
            optimizer_params={"learning_rate": 0.2})

    # score both tasks
    it.reset()
    accs, mses = [], []
    for batch in it:
        mod.forward(batch, is_train=False)
        probs, reg = [o.asnumpy() for o in mod.get_outputs()]
        cls = batch.label[0].asnumpy()
        tgt = batch.label[1].asnumpy()
        accs.append((probs.argmax(axis=1) == cls).mean())
        mses.append(((reg - tgt) ** 2).mean())
    print("cls acc %.3f | reg mse %.4f"
          % (float(np.mean(accs)), float(np.mean(mses))))
    assert np.mean(accs) > 0.9
    assert np.mean(mses) < 0.3


if __name__ == "__main__":
    main()
