"""Python how-to walkthrough (reference example/python-howto/):
multiple_outputs.py (Group + bind exposes internal layers),
data_iter.py (custom DataIter protocol), monitor_weights.py
(Monitor with a norm stat installed through fit) — as one asserting
script instead of notebooks.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

# ---- multiple outputs: group an internal layer with the head --------
net = mx.sym.Variable("data")
fc1 = mx.sym.FullyConnected(data=net, name="fc1", num_hidden=16)
relu = mx.sym.Activation(data=fc1, name="relu1", act_type="relu")
fc2 = mx.sym.FullyConnected(data=relu, name="fc2", num_hidden=4)
out = mx.sym.SoftmaxOutput(data=fc2, name="softmax")
group = mx.sym.Group([fc1, out])
assert group.list_outputs() == ["fc1_output", "softmax_output"]
ex = group.simple_bind(mx.cpu(), data=(2, 8))
ex.arg_dict["data"][:] = np.random.RandomState(0).randn(2, 8)
outs = ex.forward()
assert outs[0].shape == (2, 16)          # the internal fc1 value
assert outs[1].shape == (2, 4)
np.testing.assert_allclose(outs[1].asnumpy().sum(axis=1), np.ones(2),
                           rtol=1e-5)

# ---- custom data iter (data_iter.py protocol) -----------------------
class SimpleIter(mx.io.DataIter):
    def __init__(self, n_batches=8, batch=16):
        super().__init__()
        self.batch_size = batch
        self.n = n_batches
        self.i = -1
        self.rng = np.random.RandomState(1)

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", (self.batch_size, 8))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self.i = -1

    def iter_next(self):
        self.i += 1
        return self.i < self.n

    def getdata(self):
        x = self.rng.randn(self.batch_size, 8).astype(np.float32)
        self._y = (x[:, 0] > 0).astype(np.float32)
        x[:, 1] += self._y * 2
        return [mx.nd.array(x)]

    def getlabel(self):
        return [mx.nd.array(self._y)]


# ---- monitor_weights.py: norm stat per batch through fit ------------
stats = []


def norm_stat(d):
    return mx.nd.norm(d) / np.sqrt(d.size)


mon = mx.monitor.Monitor(1, norm_stat)
mod = mx.mod.Module(out, context=mx.cpu())
mod.fit(SimpleIter(), num_epoch=2, monitor=mon,
        optimizer_params={"learning_rate": 0.1})
print("python howto OK")
