"""CIFAR training recipe (reference example/notebooks/cifar10-recipe.ipynb
+ cifar-100.ipynb): the full training workflow in one place —
ImageRecordIter data with augmentation, a conv factory net, an lr
FactorScheduler, per-epoch do_checkpoint callbacks, RESUME from a
saved epoch, and final scoring.

Zero-egress stand-in for CIFAR: synthetic 3x28x28 class-blob images
packed into recordio (the pipeline is identical).
"""
import glob
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu import recordio as rio

NCLASS = 3
IMG = 28


def make_rec(path, n, seed):
    rng = np.random.RandomState(seed)
    w = rio.MXRecordIO(path, "w")
    yy, xx = np.mgrid[0:IMG, 0:IMG]
    for i in range(n):
        c = i % NCLASS
        # class encoded in the blob's VERTICAL position: rand_mirror
        # flips x, so the label must not live on the x axis
        cx, cy = 14, 6 + 8 * c
        img = (((xx - cx) ** 2 + (yy - cy) ** 2) < 16) * 180.0
        img = (img[:, :, None] + rng.rand(IMG, IMG, 3) * 50).clip(0, 255)
        w.write(rio.pack_img(rio.IRHeader(0, float(c), i, 0),
                             img.astype(np.uint8), quality=95))
    w.close()


def conv_factory(data, num_filter, name):
    c = mx.sym.Convolution(data, num_filter=num_filter, kernel=(3, 3),
                           pad=(1, 1), name="conv_%s" % name)
    bn = mx.sym.BatchNorm(c, name="bn_%s" % name)
    return mx.sym.Activation(bn, act_type="relu", name="relu_%s" % name)


def build_net():
    net = mx.sym.Variable("data")
    net = conv_factory(net, 8, "a")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = conv_factory(net, 16, "b")
    net = mx.sym.Pooling(net, kernel=(2, 2), global_pool=True,
                         pool_type="avg")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=NCLASS,
                                name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    tmp = tempfile.mkdtemp(prefix="cifar_recipe_")
    make_rec(os.path.join(tmp, "train.rec"), 192, seed=0)
    make_rec(os.path.join(tmp, "val.rec"), 48, seed=1)

    def iters():
        train = mx.io.ImageRecordIter(
            path_imgrec=os.path.join(tmp, "train.rec"),
            data_shape=(3, IMG, IMG), batch_size=24, shuffle=True,
            rand_mirror=True, scale=1.0 / 255, preprocess_threads=2)
        val = mx.io.ImageRecordIter(
            path_imgrec=os.path.join(tmp, "val.rec"),
            data_shape=(3, IMG, IMG), batch_size=24, scale=1.0 / 255)
        return train, val

    prefix = os.path.join(tmp, "cifar")
    train, val = iters()
    model = mx.model.FeedForward(
        build_net(), ctx=mx.cpu(), num_epoch=6,
        optimizer="adam", learning_rate=0.01,
        initializer=mx.initializer.Xavier(),
        lr_scheduler=mx.lr_scheduler.FactorScheduler(step=16, factor=0.9))
    model.fit(X=train, eval_data=val,
              epoch_end_callback=mx.callback.do_checkpoint(prefix),
              batch_end_callback=mx.callback.Speedometer(24, 4))
    assert glob.glob(prefix + "-symbol.json"), "no symbol checkpoint"
    assert glob.glob(prefix + "-000*.params"), "no param checkpoints"

    # resume from epoch 3 and continue to 10 (the notebook's resume cell)
    resumed = mx.model.FeedForward.load(prefix, 3, ctx=mx.cpu(),
                                        num_epoch=10, optimizer="adam",
                                        learning_rate=0.005)
    train, val = iters()
    resumed.fit(X=train, eval_data=val)   # resumes at begin_epoch=3 from load()

    train, val = iters()
    acc = resumed.score(val)
    print("val accuracy after resume: %.3f" % acc)
    assert acc > 0.9, acc
    print("cifar recipe OK")


if __name__ == "__main__":
    main()
