"""Composite symbol walkthrough (reference
example/notebooks/composite_symbol.ipynb): build an Inception-style
factory block by composing symbols, inspect arguments/outputs, infer
shapes through the composite, and render the debug description.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def conv_factory(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
                 name=None):
    conv = mx.sym.Convolution(data=data, num_filter=num_filter,
                              kernel=kernel, stride=stride, pad=pad,
                              name="conv_%s" % name)
    bn = mx.sym.BatchNorm(data=conv, name="bn_%s" % name)
    return mx.sym.Activation(data=bn, act_type="relu",
                             name="relu_%s" % name)


def inception_block(data, f1, f3r, f3, f5r, f5, proj, name):
    b1 = conv_factory(data, f1, (1, 1), name="%s_1x1" % name)
    b3 = conv_factory(data, f3r, (1, 1), name="%s_3x3r" % name)
    b3 = conv_factory(b3, f3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    b5 = conv_factory(data, f5r, (1, 1), name="%s_5x5r" % name)
    b5 = conv_factory(b5, f5, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    bp = mx.sym.Pooling(data=data, kernel=(3, 3), stride=(1, 1),
                        pad=(1, 1), pool_type="max",
                        name="%s_pool" % name)
    bp = conv_factory(bp, proj, (1, 1), name="%s_proj" % name)
    return mx.sym.Concat(b1, b3, b5, bp, name="%s_concat" % name)


data = mx.sym.Variable("data")
blk = inception_block(data, 16, 8, 16, 4, 8, 8, "in3a")
blk = inception_block(blk, 16, 8, 16, 4, 8, 8, "in3b")
pool = mx.sym.Pooling(blk, kernel=(2, 2), global_pool=True,
                      pool_type="avg")
net = mx.sym.FullyConnected(mx.sym.Flatten(pool), num_hidden=10,
                            name="fc")
net = mx.sym.SoftmaxOutput(net, name="softmax")

args = net.list_arguments()
assert "conv_in3a_1x1_weight" in args and "fc_weight" in args
arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(2, 3, 28, 28))
assert out_shapes[0] == (2, 10)
# two stacked blocks -> concat output feeds the second block
concat_channels = 16 + 16 + 8 + 8
idx = args.index("conv_in3b_1x1_weight")
assert arg_shapes[idx][1] == concat_channels, arg_shapes[idx]
# aux states: one (mean, var) pair per BatchNorm
n_bn = sum(1 for a in net.list_auxiliary_states())
assert n_bn == 2 * 12, n_bn
txt = net.debug_str() if hasattr(net, "debug_str") else str(net)
print("composite symbol OK")
