"""simple_bind walkthrough (reference example/notebooks/simple_bind.ipynb):
the LOW-LEVEL training loop — simple_bind an MLP, initialize arg arrays
by hand, run forward/backward yourself, and apply SGD directly to the
executor's arrays; no Module/FeedForward anywhere.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

rng = np.random.RandomState(0)
n = 256
X = rng.randn(n, 16).astype(np.float32)
y = (X[:, :4].sum(axis=1) > 0).astype(np.float32)

net = mx.sym.Variable("data")
net = mx.sym.FullyConnected(net, num_hidden=32, name="fc1")
net = mx.sym.Activation(net, act_type="relu", name="act1")
net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
net = mx.sym.SoftmaxOutput(net, name="softmax")

batch = 32
ex = net.simple_bind(ctx=mx.cpu(), data=(batch, 16), grad_req="write")

# hand initialization, notebook-style
for name, arr in ex.arg_dict.items():
    if name.endswith("weight"):
        arr[:] = rng.uniform(-0.07, 0.07, arr.shape).astype(np.float32)
    elif name.endswith("bias"):
        arr[:] = 0

lr = 0.2
for epoch in range(12):
    correct = 0
    for start in range(0, n, batch):
        ex.arg_dict["data"][:] = X[start:start + batch]
        ex.arg_dict["softmax_label"][:] = y[start:start + batch]
        ex.forward(is_train=True)
        ex.backward()
        for name, grad in ex.grad_dict.items():
            if grad is None or name in ("data", "softmax_label"):
                continue
            ex.arg_dict[name][:] = ex.arg_dict[name] - (lr / batch) * grad
        pred = ex.outputs[0].asnumpy().argmax(axis=1)
        correct += int((pred == y[start:start + batch]).sum())
    acc = correct / n
final = acc
print("final accuracy %.3f" % final)
assert final > 0.95, final
print("simple bind OK")
