"""Predict-with-a-pretrained-model walkthrough (reference
example/notebooks/predict-with-pretrained-model.ipynb): load a
checkpointed model by (prefix, epoch), run batch prediction, read
top-k classes, and extract an INTERNAL feature layer by rebinding the
symbol's internals — the notebook's feature-extraction trick.

Zero-egress stand-in for the downloaded Inception checkpoint: a small
convnet trained briefly on synthetic blobs, saved, then reloaded.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

rng = np.random.RandomState(0)
n = 192
X = rng.rand(n, 1, 12, 12).astype(np.float32) * 0.3
y = rng.randint(0, 3, n).astype(np.float32)
for i in range(n):                      # class-dependent blob position
    c = int(y[i])
    X[i, 0, 2 + 3 * c:5 + 3 * c, 4:8] += 2.0

data = mx.sym.Variable("data")
net = mx.sym.Convolution(data, num_filter=8, kernel=(3, 3), name="c1")
net = mx.sym.Activation(net, act_type="relu", name="relu1")
net = mx.sym.Flatten(net, name="flat")
net = mx.sym.FullyConnected(net, num_hidden=16, name="feat")
net = mx.sym.Activation(net, act_type="relu", name="featact")
net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
net = mx.sym.SoftmaxOutput(net, name="softmax")

model = mx.model.FeedForward(net, ctx=mx.cpu(), num_epoch=20,
                             learning_rate=0.05, numpy_batch_size=32,
                             initializer=mx.initializer.Xavier())
model.fit(X=X, y=y)

prefix = os.path.join(tempfile.mkdtemp(prefix="nb_pretrained_"), "m")
model.save(prefix, 20)

# --- the notebook's flow starts here: load by prefix/epoch, predict ---
loaded = mx.model.FeedForward.load(prefix, 20)
probs = loaded.predict(X[:32])
assert probs.shape == (32, 3)
topk = probs.argsort(axis=1)[:, ::-1][:, :2]      # top-2 classes
acc = float((probs.argmax(axis=1) == y[:32]).mean())
print("top-1 accuracy on train slice: %.3f" % acc)
assert acc > 0.9, acc
assert all(topk[i, 0] == probs[i].argmax() for i in range(32))

# --- feature extraction: rebind an internal layer as the output ---
internals = loaded.symbol.get_internals()
feat_sym = internals["featact_output"]
feat = mx.model.FeedForward(feat_sym, ctx=mx.cpu(),
                            arg_params=loaded.arg_params,
                            aux_params=loaded.aux_params)
feats = feat.predict(X[:8])
assert feats.shape == (8, 16)
assert np.abs(feats).sum() > 0
print("predict pretrained OK")
