"""Neural style transfer (reference example/neural-style/run.py +
model_vgg19.py): optimize the INPUT image, not the weights.

This is the one example family that exercises gradient-w.r.t.-data
through the executor: bind with ``args_grad={"data": ...}`` only, call
``backward(head_grads)`` with per-output scaling (style weight / gram
normalizer, content weight), and feed the data gradient to an SGD
optimizer updating the image. A second forward-only executor computes
the total-variation gradient with a fixed Laplacian kernel shared
across channels via SliceChannel/Concat/Convolution — exactly the
reference's ``get_tv_grad_executor`` construction.

Zero-egress adaptation: no pretrained VGG19 download; a fixed-seed
random 3-block VGG-style feature net plays its role (style/gram math is
identical — Gatys-style losses only need a fixed nonlinear feature
extractor). Behavior gate: the style+content objective must drop to
under half its initial value, and image pixels must be what changed.
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)


def feature_net():
    """3-block conv net; group of (style1, style2, style3, content)."""
    data = mx.sym.Variable("data")
    x = data
    style_layers = []
    channels = [16, 32, 64]
    for b, ch in enumerate(channels, 1):
        x = mx.sym.Convolution(data=x, num_filter=ch, kernel=(3, 3),
                               pad=(1, 1), name="conv%d" % b)
        x = mx.sym.Activation(data=x, act_type="relu", name="relu%d" % b)
        style_layers.append(x)
        if b < len(channels):
            x = mx.sym.Pooling(data=x, kernel=(2, 2), stride=(2, 2),
                               pool_type="avg", name="pool%d" % b)
    content = style_layers[-1]
    return style_layers, content


def gram_symbols(style_layers, input_shape):
    """Gram matrix per style layer via the reference's FullyConnected
    trick: reshape to (C, H*W) then FC(x, weight=x) = x @ x.T."""
    grams, gscale = [], []
    for i, s in enumerate(style_layers):
        _, out_shapes, _ = mx.sym.Group([s]).infer_shape(data=input_shape)
        shape = out_shapes[0]                       # (1, C, H, W)
        c, hw = int(shape[1]), int(np.prod(shape[2:]))
        x = mx.sym.Reshape(s, target_shape=(c, hw))
        grams.append(mx.sym.FullyConnected(data=x, weight=x, no_bias=True,
                                           num_hidden=c))
        gscale.append(float(np.prod(shape[1:]) * shape[1]))
    return grams, gscale


def loss_symbols(grams, content):
    """Per-layer style losses sum((G - target)^2) + content loss."""
    style_losses = []
    for i, g in enumerate(grams):
        target = mx.sym.Variable("target_gram_%d" % i)
        style_losses.append(mx.sym.sum(mx.sym.square(target - g)))
    target_c = mx.sym.Variable("target_content")
    content_loss = mx.sym.sum(mx.sym.square(target_c - content))
    return style_losses, content_loss


def tv_grad_executor(img, tv_weight):
    """Total-variation gradient: depthwise Laplacian via the reference's
    SliceChannel + shared-kernel Convolution + Concat construction."""
    nchannel = img.shape[1]
    simg = mx.sym.Variable("img")
    skernel = mx.sym.Variable("kernel")
    channels = mx.sym.SliceChannel(simg, num_outputs=nchannel)
    out = mx.sym.Concat(*[
        mx.sym.Convolution(data=channels[i], weight=skernel, num_filter=1,
                           kernel=(3, 3), pad=(1, 1), no_bias=True)
        for i in range(nchannel)])
    kernel = mx.nd.array(np.array([[0, -1, 0], [-1, 4, -1], [0, -1, 0]],
                                  dtype=np.float32).reshape(1, 1, 3, 3) / 8.0)
    out = out * tv_weight
    return out.bind(mx.cpu(), args={"img": img, "kernel": kernel})


def main():
    rng = np.random.RandomState(7)
    size = (1, 3, 32, 32)
    content_np = (rng.rand(*size).astype(np.float32) - 0.5) * 2
    style_np = (rng.rand(*size).astype(np.float32) - 0.5) * 2

    style_layers, content_sym = feature_net()
    grams, gscale = gram_symbols(style_layers, size)

    # fixed random "pretrained" weights, shared by every executor
    feat = mx.sym.Group(grams + [content_sym])
    arg_shapes, _, _ = feat.infer_shape(data=size)
    args = {}
    for name, shape in zip(feat.list_arguments(), arg_shapes):
        args[name] = mx.nd.array(
            rng.randn(*shape).astype(np.float32) * (0.3 if "weight" in name
                                                    else 0.0))
    args["data"] = mx.nd.array(content_np)

    # pass 1/2: record style grams of the style image, content features
    # of the content image (forward-only executors)
    exe = feat.bind(mx.cpu(), args=args, grad_req="null")
    args["data"][:] = style_np
    target_grams = [o.asnumpy().copy() for o in exe.forward()[:-1]]
    args["data"][:] = content_np
    target_content = exe.forward()[-1].asnumpy().copy()

    # pass 3: loss graph, bind with gradient ONLY on data
    style_losses, content_loss = loss_symbols(grams, content_sym)
    loss_group = mx.sym.Group(style_losses + [content_loss])
    img = mx.nd.array(rng.uniform(-0.1, 0.1, size).astype(np.float32))
    largs = dict(args)
    largs["data"] = img
    for i, tg in enumerate(target_grams):
        largs["target_gram_%d" % i] = mx.nd.array(tg)
    largs["target_content"] = mx.nd.array(target_content)
    data_grad = mx.nd.zeros(size)
    lexe = loss_group.bind(mx.cpu(), args=largs,
                           args_grad={"data": data_grad}, grad_req="write")

    style_weight, content_weight, tv_weight, lr = 1.0, 10.0, 1e-2, 1e-3
    head_grads = [mx.nd.array(np.full((1,), style_weight / gscale[i],
                                      np.float32))
                  for i in range(len(style_losses))]
    head_grads.append(mx.nd.array(np.full((1,), content_weight, np.float32)))

    tv_exe = tv_grad_executor(img, tv_weight)
    opt = mx.optimizer.SGD(learning_rate=lr, momentum=0.9, wd=0.0,
                           lr_scheduler=mx.lr_scheduler.FactorScheduler(
                               step=40, factor=0.9))
    state = opt.create_state(0, img)

    def objective(outs):
        total = 0.0
        for i in range(len(style_losses)):
            total += float(outs[i].asnumpy().ravel()[0]) \
                * (style_weight / gscale[i])
        total += float(outs[-1].asnumpy().ravel()[0]) * content_weight
        return total

    first = None
    img0 = img.asnumpy().copy()
    clip_norm = float(np.prod(size))
    for epoch in range(80):
        # train forward is lazy here: the fused fwd+bwd materializes the
        # outputs with backward(), so read the loss afterwards
        lexe.forward(is_train=True)
        lexe.backward(head_grads)
        loss = objective(lexe.outputs)
        if first is None:
            first = loss
        g = data_grad.asnumpy()
        gnorm = float(np.linalg.norm(g))
        if gnorm > clip_norm:
            data_grad[:] = g * (clip_norm / gnorm)
        tv = tv_exe.forward()[0]
        opt.update(0, img, data_grad + tv, state)
        if epoch % 10 == 0:
            logging.info("epoch %d style+content loss %.4f", epoch, loss)

    final = objective(lexe.forward())
    moved = float(np.abs(img.asnumpy() - img0).max())
    logging.info("loss %.4f -> %.4f, max pixel change %.4f",
                 first, final, moved)
    assert final < 0.5 * first, (first, final)
    assert moved > 1e-3
    print("neural style OK")


if __name__ == "__main__":
    main()
