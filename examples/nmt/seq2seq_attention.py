#!/usr/bin/env python
"""Seq2seq with attention (reference example/nmt): encoder LSTM via
the fused RNN op, per-step decoder with Luong dot attention built from
batch_dot + SoftmaxActivation, trained to emit the reversed input
sequence — the translation-toy the reference's NMT example reduced to.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu.ops.seq import rnn_param_size

VOCAB = 10
SEQ = 6
EMBED = 16
HIDDEN = 32


def build(batch):
    src = mx.sym.Variable("src")                    # (T, N) ids
    emb = mx.sym.Embedding(src, input_dim=VOCAB, output_dim=EMBED,
                           name="src_embed")        # (T, N, E)
    enc = mx.sym.RNN(data=emb, parameters=mx.sym.Variable("enc_params"),
                     state=mx.sym.Variable("enc_state"),
                     state_cell=mx.sym.Variable("enc_cell"),
                     state_size=HIDDEN, num_layers=1, mode="lstm",
                     name="encoder")                # (T, N, H)
    # decoder: unrolled steps; input = previous target token (teacher
    # forcing), context = Luong dot attention over encoder states
    enc_nth = mx.sym.SwapAxis(enc, dim1=0, dim2=1)  # (N, T, H)
    tgt_in = mx.sym.Variable("tgt_in")              # (T, N) shifted ids
    tgt_emb = mx.sym.Embedding(tgt_in, input_dim=VOCAB, output_dim=EMBED,
                               name="tgt_embed")    # (T, N, E)
    steps = mx.sym.SliceChannel(tgt_emb, num_outputs=SEQ, axis=0,
                                squeeze_axis=True)  # SEQ x (N, E)

    # decoder cell weights shared across steps (one Variable set)
    w_ih = mx.sym.Variable("dec_ih_weight")
    b_ih = mx.sym.Variable("dec_ih_bias")
    w_hh = mx.sym.Variable("dec_hh_weight")
    b_hh = mx.sym.Variable("dec_hh_bias")
    w_out = mx.sym.Variable("out_weight")
    b_out = mx.sym.Variable("out_bias")

    h = mx.sym.Variable("dec_h0")                   # (N, H) zeros
    logits = []
    for t in range(SEQ):
        x_t = steps[t]                              # (N, E)
        gx = mx.sym.FullyConnected(data=x_t, weight=w_ih, bias=b_ih,
                                   num_hidden=HIDDEN,
                                   name="dec_ih%d" % t)
        gh = mx.sym.FullyConnected(data=h, weight=w_hh, bias=b_hh,
                                   num_hidden=HIDDEN,
                                   name="dec_hh%d" % t)
        h = mx.sym.Activation(gx + gh, act_type="tanh")
        # Luong dot attention: scores (N, T) = enc_nth @ h
        hq = mx.sym.Reshape(h, shape=(batch, HIDDEN, 1))
        scores = mx.sym.batch_dot(enc_nth, hq)       # (N, T, 1)
        scores = mx.sym.Reshape(scores, shape=(batch, SEQ))
        alpha = mx.sym.SoftmaxActivation(scores)     # (N, T)
        alpha3 = mx.sym.Reshape(alpha, shape=(batch, 1, SEQ))
        ctx_vec = mx.sym.batch_dot(alpha3, enc_nth)  # (N, 1, H)
        ctx_vec = mx.sym.Reshape(ctx_vec, shape=(batch, HIDDEN))
        feat = mx.sym.Concat(h, ctx_vec, dim=1)      # (N, 2H)
        logits.append(mx.sym.FullyConnected(
            data=feat, weight=w_out, bias=b_out, num_hidden=VOCAB,
            name="out%d" % t))
    out = mx.sym.Concat(*[mx.sym.Reshape(l, shape=(1, batch, VOCAB))
                          for l in logits], dim=0)  # (T, N, V)
    out = mx.sym.Reshape(out, shape=(SEQ * batch, VOCAB))
    return mx.sym.SoftmaxOutput(out, name="softmax")


def main(seed=0, batch=32, epochs=30):
    rng = np.random.RandomState(seed)
    net = build(batch)
    psize = rnn_param_size(1, EMBED, HIDDEN, False, "lstm")
    exe = net.simple_bind(
        mx.cpu(), src=(SEQ, batch), tgt_in=(SEQ, batch),
        enc_params=(psize,), enc_state=(1, batch, HIDDEN),
        enc_cell=(1, batch, HIDDEN), dec_h0=(batch, HIDDEN),
        softmax_label=(SEQ * batch,))
    init = mx.init.Xavier()
    skip = {"src", "tgt_in", "softmax_label", "enc_state", "enc_cell",
            "dec_h0"}
    for name, arr in exe.arg_dict.items():
        if name not in skip:
            if name.endswith("_bias"):
                arr[:] = np.zeros(arr.shape, np.float32)
            else:
                init(name if name.endswith("weight") else name + "_weight",
                     arr)
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=5e-3))

    def make_batch():
        s = rng.randint(1, VOCAB, (SEQ, batch))
        tgt = s[::-1]                                # reverse task
        tgt_in = np.vstack([np.zeros((1, batch), int), tgt[:-1]])
        return (s.astype(np.float32), tgt_in.astype(np.float32),
                tgt.reshape(-1).astype(np.float32))

    for epoch in range(epochs):
        correct = total = 0
        for _ in range(16):
            s, t_in, t_out = make_batch()
            exe.arg_dict["src"][:] = s
            exe.arg_dict["tgt_in"][:] = t_in
            exe.arg_dict["softmax_label"][:] = t_out
            exe.forward(is_train=True)
            exe.backward()
            for i, nm in enumerate(net.list_arguments()):
                if nm in skip:
                    continue
                updater(i, exe.grad_dict[nm], exe.arg_dict[nm])
            pred = exe.outputs[0].asnumpy().argmax(axis=1)
            correct += (pred == t_out).sum()
            total += t_out.size
    acc = correct / total
    print("teacher-forced token accuracy (reverse task): %.3f" % acc)
    assert acc > 0.9, acc
    print("NMT OK")


if __name__ == "__main__":
    main()
