#!/usr/bin/env python
"""Train MNIST through caffe layers (reference example/caffe/caffe_net.py):
the network is built entirely from sym.CaffeOp prototxt strings.

Uses idx-format MNIST from --data-dir when present, otherwise renders a
synthetic digit dataset to disk first (tools/make_mnist_synth.py)."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import sym


def get_mlp():
    data = sym.Variable("data")
    fc1 = sym.CaffeOp(data_0=data, num_weight=2, name="fc1",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 128}}')
    act1 = sym.CaffeOp(data_0=fc1, prototxt='layer{type:"TanH"}')
    fc2 = sym.CaffeOp(data_0=act1, num_weight=2, name="fc2",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 64}}')
    act2 = sym.CaffeOp(data_0=fc2, prototxt='layer{type:"TanH"}')
    fc3 = sym.CaffeOp(data_0=act2, num_weight=2, name="fc3",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 10}}')
    return sym.SoftmaxOutput(data=fc3, name="softmax")


def get_lenet():
    """LeNet with caffe conv/pool layers (reference caffe_net.py)."""
    data = sym.Variable("data")
    conv1 = sym.CaffeOp(data_0=data, num_weight=2, name="conv1",
                        prototxt='layer{type:"Convolution" '
                                 'convolution_param{num_output: 20 '
                                 'kernel_size: 5}}')
    pool1 = sym.CaffeOp(data_0=conv1,
                        prototxt='layer{type:"Pooling" pooling_param{'
                                 'pool: MAX kernel_size: 2 stride: 2}}')
    conv2 = sym.CaffeOp(data_0=pool1, num_weight=2, name="conv2",
                        prototxt='layer{type:"Convolution" '
                                 'convolution_param{num_output: 50 '
                                 'kernel_size: 5}}')
    pool2 = sym.CaffeOp(data_0=conv2,
                        prototxt='layer{type:"Pooling" pooling_param{'
                                 'pool: MAX kernel_size: 2 stride: 2}}')
    fc1 = sym.CaffeOp(data_0=sym.Flatten(data=pool2), num_weight=2,
                      name="fc1",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 500}}')
    act = sym.CaffeOp(data_0=fc1, prototxt='layer{type:"TanH"}')
    fc2 = sym.CaffeOp(data_0=act, num_weight=2, name="fc2",
                      prototxt='layer{type:"InnerProduct" '
                               'inner_product_param{num_output: 10}}')
    return sym.SoftmaxOutput(data=fc2, name="softmax")


def main():
    parser = argparse.ArgumentParser(description="caffe-layer mnist")
    parser.add_argument("--network", default="mlp",
                        choices=["mlp", "lenet"])
    parser.add_argument("--data-dir", default="mnist/")
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--num-epochs", type=int, default=10)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    train_img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    if not os.path.exists(train_img):
        logging.warning("no MNIST in %s; rendering a synthetic dataset",
                        args.data_dir)
        from tools.make_mnist_synth import generate
        generate(args.data_dir, 8000, 1000)

    flat = args.network == "mlp"
    train = mx.io.MNISTIter(
        image=train_img,
        label=os.path.join(args.data_dir, "train-labels-idx1-ubyte"),
        batch_size=args.batch_size, shuffle=True, flat=flat)
    val = mx.io.MNISTIter(
        image=os.path.join(args.data_dir, "t10k-images-idx3-ubyte"),
        label=os.path.join(args.data_dir, "t10k-labels-idx1-ubyte"),
        batch_size=args.batch_size, flat=flat)

    net = get_mlp() if args.network == "mlp" else get_lenet()
    mod = mx.mod.Module(net)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    acc = mod.score(val, "acc")[0][1]
    print("Final validation accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
