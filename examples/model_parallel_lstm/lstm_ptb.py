#!/usr/bin/env python
"""Model-parallel LSTM (reference example/model-parallel-lstm/lstm.py:48-199
+ docs/how_to/model_parallel_lstm.md): LSTM layers placed on different
devices via ctx_group/group2ctx; XLA compiles the whole step into one
multi-device program with cross-device transfers at layer boundaries."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def build_model_parallel_lstm(num_layers, vocab, num_embed, num_hidden):
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        embed = sym.Embedding(data=data, input_dim=vocab,
                              output_dim=num_embed, name="embed")
        body = sym.SwapAxis(data=embed, dim1=0, dim2=1)  # TNC
    for i in range(num_layers):
        with mx.AttrScope(ctx_group="layer%d" % i):
            body = sym.RNN(data=body, state_size=num_hidden, num_layers=1,
                           mode="lstm", name="lstm%d" % i)
    with mx.AttrScope(ctx_group="cls"):
        flat = sym.Reshape(data=body, target_shape=(-1, num_hidden))
        pred = sym.FullyConnected(data=flat, num_hidden=vocab, name="pred")
        label_t = sym.transpose(data=label)
        label_flat = sym.Reshape(data=label_t, target_shape=(-1,))
        out = sym.SoftmaxOutput(data=pred, label=label_flat, name="softmax")
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-hidden", type=int, default=32)
    parser.add_argument("--num-embed", type=int, default=16)
    parser.add_argument("--vocab", type=int, default=50)
    parser.add_argument("--seq-len", type=int, default=12)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--steps", type=int, default=20)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    import jax

    devs = jax.devices()
    net = build_model_parallel_lstm(args.num_layers, args.vocab,
                                    args.num_embed, args.num_hidden)
    # place each layer group on its own device (wrap around if fewer)
    group2ctx = {"embed": mx.cpu(0) if devs[0].platform == "cpu" else mx.tpu(0)}
    for i in range(args.num_layers):
        d = (i + 1) % len(devs)
        group2ctx["layer%d" % i] = (mx.cpu(d) if devs[d].platform == "cpu"
                                    else mx.tpu(d))
    group2ctx["cls"] = group2ctx["embed"]
    logging.info("placement: %s", group2ctx)

    shapes = {"data": (args.batch_size, args.seq_len),
              "softmax_label": (args.batch_size, args.seq_len)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    rng = np.random.RandomState(0)
    arg_names = net.list_arguments()
    args_nd, grads_nd = {}, {}
    for name, shape in zip(arg_names, arg_shapes):
        if name in shapes:
            args_nd[name] = mx.nd.zeros(shape)
        else:
            args_nd[name] = mx.nd.array(
                rng.randn(*shape).astype(np.float32) * 0.1)
            grads_nd[name] = mx.nd.zeros(shape)
    ex = net.bind(mx.cpu(), args_nd, args_grad=grads_nd,
                  group2ctx=group2ctx)

    lr = 0.05
    for step in range(args.steps):
        tokens = rng.randint(1, args.vocab,
                             (args.batch_size, args.seq_len)).astype(np.float32)
        args_nd["data"][:] = tokens
        args_nd["softmax_label"][:] = tokens  # identity LM
        ex.forward(is_train=True)
        ex.backward()
        for name, g in grads_nd.items():
            args_nd[name] -= g * lr
        if step % 5 == 0:
            out = ex.outputs[0].asnumpy()
            lab = tokens.T.ravel().astype(int)
            nll = -np.log(out[np.arange(len(lab)), lab] + 1e-8).mean()
            logging.info("step %d nll %.4f", step, nll)
    print("model-parallel LSTM ran %d steps across %d device groups"
          % (args.steps, len(set(group2ctx.values()))))


if __name__ == "__main__":
    main()
