/* Minimal C consumer of the predict ABI (reference example/cpp +
 * matlab/amalgamation wrappers consumed include/mxnet/c_predict_api.h
 * the same way).
 *
 * Build (after `make predict` at the repo root):
 *   gcc predict.c -o predict -I ../../include \
 *       -L ../../mxnet_tpu/_native -lmxtpu_predict \
 *       -Wl,-rpath,$PWD/../../mxnet_tpu/_native
 * Run:
 *   PYTHONPATH=../../ ./predict model-symbol.json model-0001.params \
 *       1,3,224,224
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "mxnet_tpu/c_predict_api.h"

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) return NULL;
  fseek(f, 0, SEEK_END);
  *size = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[*size] = 0;
  fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <symbol.json> <model.params> <N,C,H,W>\n", argv[0]);
    return 1;
  }
  long sym_size, param_size;
  char *sym_json = read_file(argv[1], &sym_size);
  char *params = read_file(argv[2], &param_size);
  if (!sym_json || !params) {
    fprintf(stderr, "cannot read model files\n");
    return 1;
  }

  mx_uint dims[8], ndim = 0;
  for (char *tok = strtok(argv[3], ","); tok && ndim < 8;
       tok = strtok(NULL, ","))
    dims[ndim++] = (mx_uint)atoi(tok);
  mx_uint indptr[2] = {0, ndim};
  const char *keys[] = {"data"};

  PredictorHandle h;
  if (MXPredCreate(sym_json, params, (int)param_size, 1, 0, 1, keys,
                   indptr, dims, &h) != 0) {
    fprintf(stderr, "MXPredCreate: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint in_size = 1;
  for (mx_uint i = 0; i < ndim; ++i) in_size *= dims[i];
  float *x = (float *)malloc(in_size * sizeof(float));
  for (mx_uint i = 0; i < in_size; ++i) x[i] = (float)(i % 255) / 255.0f;

  if (MXPredSetInput(h, "data", x, in_size) != 0 ||
      MXPredForward(h) != 0) {
    fprintf(stderr, "forward: %s\n", MXGetLastError());
    return 1;
  }

  mx_uint *shape, out_ndim;
  if (MXPredGetOutputShape(h, 0, &shape, &out_ndim) != 0) {
    fprintf(stderr, "shape: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint out_size = 1;
  printf("output shape: ");
  for (mx_uint i = 0; i < out_ndim; ++i) {
    printf("%u ", shape[i]);
    out_size *= shape[i];
  }
  printf("\n");

  float *out = (float *)malloc(out_size * sizeof(float));
  if (MXPredGetOutput(h, 0, out, out_size) != 0) {
    fprintf(stderr, "output: %s\n", MXGetLastError());
    return 1;
  }
  mx_uint best = 0;
  for (mx_uint i = 1; i < out_size && i < shape[out_ndim - 1]; ++i)
    if (out[i] > out[best]) best = i;
  printf("argmax: %u (%.6f)\n", best, out[best]);

  MXPredFree(h);
  free(x);
  free(out);
  free(sym_json);
  free(params);
  return 0;
}
