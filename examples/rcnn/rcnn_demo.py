#!/usr/bin/env python
"""Faster R-CNN building blocks demo (reference example/rcnn): ROIPooling
op + a Proposal layer implemented as a frontend CustomOp — the two pieces
BASELINE.md names as the rcnn target."""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

# honor JAX_PLATFORMS (the site hook overrides the env at import)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms",
                      os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu import operator as mop
from mxnet_tpu import symbol as sym


@mop.register("proposal")
class ProposalProp(mop.CustomOpProp):
    """Generate top-N box proposals from objectness scores + anchor deltas
    (simplified reference rcnn/symbol/proposal.py)."""

    def __init__(self, feat_stride="16", rpn_post_nms_top_n="8", **kwargs):
        super().__init__(need_top_grad=False)
        self.feat_stride = int(feat_stride)
        self.top_n = int(rpn_post_nms_top_n)

    def list_arguments(self):
        return ["cls_prob", "bbox_pred", "im_info"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [[self.top_n, 5]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        top_n = self.top_n
        stride = self.feat_stride

        class Proposal(mop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                scores = in_data[0].asnumpy()       # (N, A, H, W)
                deltas = in_data[1].asnumpy()       # (N, A*4, H, W)
                im_info = in_data[2].asnumpy()      # (N, 3)
                n, a, h, w = scores.shape
                ys, xs = np.meshgrid(np.arange(h), np.arange(w),
                                     indexing="ij")
                cx = (xs * stride + stride / 2).ravel()
                cy = (ys * stride + stride / 2).ravel()
                flat = scores[0].reshape(a, -1)
                order = np.argsort(flat.max(axis=0))[::-1][:top_n]
                size = stride * 1.5
                boxes = np.zeros((top_n, 5), dtype=np.float32)
                for i, idx in enumerate(order):
                    boxes[i] = [0, max(cx[idx] - size, 0),
                                max(cy[idx] - size, 0),
                                min(cx[idx] + size, im_info[0, 1]),
                                min(cy[idx] + size, im_info[0, 0])]
                self.assign(out_data[0], req[0], boxes)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                for g in in_grad:
                    g[:] = 0
        return Proposal()


def main():
    logging.basicConfig(level=logging.INFO)
    # toy backbone -> rpn -> proposal -> roi pooling -> head
    data = sym.Variable("data")
    conv = sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                           pad=(1, 1), name="backbone")
    relu = sym.Activation(conv, act_type="relu")
    rpn_cls = sym.Convolution(data=relu, kernel=(1, 1), num_filter=4,
                              name="rpn_cls")
    rpn_bbox = sym.Convolution(data=relu, kernel=(1, 1), num_filter=16,
                               name="rpn_bbox")
    im_info = sym.Variable("im_info")
    rois = sym.Custom(cls_prob=rpn_cls, bbox_pred=rpn_bbox, im_info=im_info,
                      op_type="proposal", feat_stride="4",
                      rpn_post_nms_top_n="8", name="proposal")
    pooled = sym.ROIPooling(data=relu, rois=rois, pooled_size=(3, 3),
                            spatial_scale=0.25, name="roi_pool")
    flat = sym.Flatten(data=pooled)
    cls = sym.FullyConnected(data=flat, num_hidden=4, name="cls_head")
    out = sym.SoftmaxActivation(cls, name="cls_prob")

    rng = np.random.RandomState(0)
    shapes = {"data": (1, 3, 32, 32), "im_info": (1, 3)}
    arg_shapes, out_shapes, _ = out.infer_shape(**shapes)
    args = {}
    for name, shape in zip(out.list_arguments(), arg_shapes):
        args[name] = mx.nd.array(rng.randn(*shape).astype(np.float32) * 0.1)
    args["im_info"][:] = np.array([[32, 32, 1.0]], dtype=np.float32)
    ex = out.bind(mx.cpu(), args, grad_req="null")
    result = ex.forward()[0].asnumpy()
    print("rcnn head output:", result.shape)  # (8 rois, 4 classes)
    assert result.shape == (8, 4)
    np.testing.assert_allclose(result.sum(axis=1), np.ones(8), rtol=1e-5)
    print("Faster R-CNN pipeline (Proposal CustomOp + ROIPooling) OK")


if __name__ == "__main__":
    main()
