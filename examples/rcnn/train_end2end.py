#!/usr/bin/env python
"""End-to-end Faster R-CNN training (reference example/rcnn/
train_end2end.py: joint RPN + RCNN-head training with the proposal
layer IN the loop).

Structure matches the reference pipeline on a toy detection task so it
runs anywhere (zero-egress: no VOC download):

  backbone conv -> RPN (objectness softmax w/ ignore labels + smooth-L1
  bbox regression against ANCHOR targets) -> Proposal CustomOp (no
  grad, in the training loop) -> ProposalTarget CustomOp (samples rois,
  assigns per-roi labels/targets like reference
  rcnn/symbol/proposal_target.py) -> ROIPooling -> head (per-roi class
  softmax + smooth-L1 box deltas).

All four losses train jointly through one bound executor; the gate
asserts the joint loss falls, RPN objectness becomes accurate, and the
trained detector localizes held-out objects (IoU vs ground truth).

Run: python train_end2end.py            (prints "rcnn end2end OK")
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

# honor JAX_PLATFORMS (the site hook overrides the env at import;
# forcing cpu needs an explicit config update after importing jax)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms",
                      os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
from mxnet_tpu import operator as mop
from mxnet_tpu import symbol as sym

IMG = 32
STRIDE = 4
FEAT = IMG // STRIDE          # 8x8 anchor grid
ANCHOR_SIZE = 10.0            # one square anchor per position
NUM_CLASSES = 3               # background + 2 object classes
TOP_N = 6                     # proposals kept per image
FG_COPIES = 3                 # gt replicas among the rois: the head's
                              # fg fraction (reference fg_fraction=0.25
                              # sampling — without it 6:1 background
                              # dominance teaches the head the prior)
ROIS = TOP_N + FG_COPIES      # + the gt copies (guaranteed positives)


def _anchors():
    ys, xs = np.meshgrid(np.arange(FEAT), np.arange(FEAT), indexing="ij")
    cx = xs.ravel() * STRIDE + STRIDE / 2.0
    cy = ys.ravel() * STRIDE + STRIDE / 2.0
    h = ANCHOR_SIZE / 2.0
    return np.stack([cx - h, cy - h, cx + h, cy + h], axis=1)  # (64,4)


def _iou(a, b):
    ix = np.maximum(0, np.minimum(a[:, 2], b[2]) - np.maximum(a[:, 0], b[0]))
    iy = np.maximum(0, np.minimum(a[:, 3], b[3]) - np.maximum(a[:, 1], b[1]))
    inter = ix * iy
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[2] - b[0]) * (b[3] - b[1])
    return inter / np.maximum(area_a + area_b - inter, 1e-6)


def _bbox_transform(boxes, gt):
    """(dx, dy, dw, dh) regression targets (reference
    rcnn/processing/bbox_regression.py math)."""
    w = boxes[:, 2] - boxes[:, 0]
    h = boxes[:, 3] - boxes[:, 1]
    cx = boxes[:, 0] + w / 2
    cy = boxes[:, 1] + h / 2
    gw = gt[2] - gt[0]
    gh = gt[3] - gt[1]
    gcx = gt[0] + gw / 2
    gcy = gt[1] + gh / 2
    return np.stack([(gcx - cx) / np.maximum(w, 1),
                     (gcy - cy) / np.maximum(h, 1),
                     np.log(np.maximum(gw, 1) / np.maximum(w, 1)),
                     np.log(np.maximum(gh, 1) / np.maximum(h, 1))],
                    axis=1).astype(np.float32)


def _bbox_apply(boxes, deltas):
    w = boxes[:, 2] - boxes[:, 0]
    h = boxes[:, 3] - boxes[:, 1]
    cx = boxes[:, 0] + w / 2 + deltas[:, 0] * w
    cy = boxes[:, 1] + h / 2 + deltas[:, 1] * h
    nw = w * np.exp(np.clip(deltas[:, 2], -2, 2))
    nh = h * np.exp(np.clip(deltas[:, 3], -2, 2))
    return np.stack([cx - nw / 2, cy - nh / 2, cx + nw / 2, cy + nh / 2],
                    axis=1)


@mop.register("anchor_target_e2e")
class AnchorTargetProp(mop.CustomOpProp):
    """Per-anchor objectness labels + bbox targets (reference
    rcnn/symbol/anchor_target.py scope: IoU>=0.5 positive, <0.2
    negative, else ignore=-1; smooth-L1 targets on positives)."""

    def __init__(self, **kwargs):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["gt_box"]

    def list_outputs(self):
        return ["label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        n = FEAT * FEAT
        return in_shape, [[n], [n, 4], [n, 4]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        anchors = _anchors()

        class AnchorTarget(mop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                gt = in_data[0].asnumpy()[0]          # (x1,y1,x2,y2)
                iou = _iou(anchors, gt)
                label = np.full(len(anchors), -1.0, np.float32)
                label[iou < 0.2] = 0.0
                label[iou >= 0.5] = 1.0
                label[np.argmax(iou)] = 1.0           # >=1 positive
                tgt = _bbox_transform(anchors, gt)
                wt = np.zeros_like(tgt)
                wt[label == 1.0] = 1.0
                self.assign(out_data[0], req[0], label)
                self.assign(out_data[1], req[1], tgt)
                self.assign(out_data[2], req[2], wt)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                for g in in_grad:
                    g[:] = 0
        return AnchorTarget()


@mop.register("proposal_e2e")
class ProposalProp(mop.CustomOpProp):
    """Top-N proposals from RPN outputs, anchors decoded with the
    predicted deltas (reference rcnn/symbol/proposal.py, no NMS on the
    toy grid)."""

    def __init__(self, **kwargs):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["cls_prob", "bbox_pred"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [[TOP_N, 4]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        anchors = _anchors()

        class Proposal(mop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                fg = in_data[0].asnumpy()[:, 1]       # (64,) fg score
                deltas = in_data[1].asnumpy()         # (64, 4)
                order = np.argsort(fg)[::-1][:TOP_N]
                boxes = _bbox_apply(anchors[order], deltas[order])
                self.assign(out_data[0], req[0],
                            np.clip(boxes, 0, IMG).astype(np.float32))

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                for g in in_grad:
                    g[:] = 0
        return Proposal()


@mop.register("proposal_target_e2e")
class ProposalTargetProp(mop.CustomOpProp):
    """Append the gt box to the proposals and emit per-roi head labels
    + bbox targets (reference rcnn/symbol/proposal_target.py: gt is
    always sampled so every image has foreground rois)."""

    def __init__(self, **kwargs):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["rois", "gt_box", "gt_class"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        return in_shape, [[ROIS, 5], [ROIS], [ROIS, 4], [ROIS, 4]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class ProposalTarget(mop.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                rois = in_data[0].asnumpy()           # (TOP_N, 4)
                gt = in_data[1].asnumpy()[0]
                gt_cls = float(in_data[2].asnumpy()[0])
                allb = np.vstack([rois] +
                                 [gt[None, :]] * FG_COPIES)  # (ROIS, 4)
                iou = _iou(allb, gt)
                label = np.where(iou >= 0.5, gt_cls, 0.0) \
                    .astype(np.float32)
                tgt = _bbox_transform(allb, gt)
                wt = np.zeros_like(tgt)
                wt[label > 0] = 1.0
                out = np.hstack([np.zeros((ROIS, 1), np.float32),
                                 allb.astype(np.float32)])
                self.assign(out_data[0], req[0], out)
                self.assign(out_data[1], req[1], label)
                self.assign(out_data[2], req[2], tgt)
                self.assign(out_data[3], req[3], wt)

            def backward(self, req, out_grad, in_data, out_data,
                         in_grad, aux):
                for g in in_grad:
                    g[:] = 0
        return ProposalTarget()


def build_net(train=True):
    data = sym.Variable("data")
    gt_box = sym.Variable("gt_box")
    gt_class = sym.Variable("gt_class")

    # LeakyReLU: plain ReLUs in a 2-conv backbone die wholesale when
    # the early RPN bias gradients are large (observed: all-zero feat
    # => zero weight grads network-wide), killing training
    body = sym.Convolution(data=data, kernel=(3, 3), num_filter=16,
                           pad=(1, 1), stride=(2, 2), name="c1")
    body = sym.LeakyReLU(body, act_type="leaky", slope=0.1)
    body = sym.Convolution(data=body, kernel=(3, 3), num_filter=16,
                           pad=(1, 1), stride=(2, 2), name="c2")
    feat = sym.LeakyReLU(body, act_type="leaky", slope=0.1)

    rpn_cls = sym.Convolution(data=feat, kernel=(1, 1), num_filter=2,
                              name="rpn_cls")      # (1, 2, 8, 8)
    rpn_bbox = sym.Convolution(data=feat, kernel=(1, 1), num_filter=4,
                               name="rpn_bbox")    # (1, 4, 8, 8)
    # (A, 2) / (A, 4) anchor-major rows
    cls_rows = sym.Reshape(
        sym.transpose(rpn_cls, axes=(0, 2, 3, 1)), shape=(-1, 2))
    bbox_rows = sym.Reshape(
        sym.transpose(rpn_bbox, axes=(0, 2, 3, 1)), shape=(-1, 4))

    tgt = sym.Custom(gt_box=gt_box, op_type="anchor_target_e2e",
                     name="anchor_target")
    rpn_label, rpn_tgt, rpn_wt = tgt[0], tgt[1], tgt[2]

    rpn_cls_loss = sym.SoftmaxOutput(
        data=cls_rows, label=rpn_label, use_ignore=True, ignore_label=-1,
        name="rpn_cls_prob")
    rpn_bbox_loss = sym.MakeLoss(
        sym.smooth_l1(bbox_rows * rpn_wt - rpn_tgt * rpn_wt, scalar=3.0),
        grad_scale=1.0 / (FEAT * FEAT), name="rpn_bbox_loss")

    rois4 = sym.Custom(cls_prob=sym.BlockGrad(rpn_cls_loss),
                       bbox_pred=sym.BlockGrad(bbox_rows),
                       op_type="proposal_e2e", name="proposal")
    ptgt = sym.Custom(rois=rois4, gt_box=gt_box, gt_class=gt_class,
                      op_type="proposal_target_e2e", name="ptarget")
    rois, head_label, head_tgt, head_wt = ptgt[0], ptgt[1], ptgt[2], ptgt[3]

    pooled = sym.ROIPooling(data=feat, rois=rois, pooled_size=(4, 4),
                            spatial_scale=1.0 / STRIDE, name="roi_pool")
    flat = sym.Flatten(data=pooled)
    fc = sym.Activation(sym.FullyConnected(data=flat, num_hidden=32,
                                           name="fc6"), act_type="relu")
    cls_score = sym.FullyConnected(data=fc, num_hidden=NUM_CLASSES,
                                   name="cls_score")
    bbox_pred = sym.FullyConnected(data=fc, num_hidden=4,
                                   name="bbox_pred")

    cls_loss = sym.SoftmaxOutput(data=cls_score, label=head_label,
                                 name="cls_prob")
    bbox_loss = sym.MakeLoss(
        sym.smooth_l1(bbox_pred * head_wt - head_tgt * head_wt,
                      scalar=1.0),
        grad_scale=1.0 / ROIS, name="bbox_loss")

    return sym.Group([rpn_cls_loss, rpn_bbox_loss, cls_loss, bbox_loss,
                      sym.BlockGrad(rois)])


def make_sample(rng):
    """One image: dark noise + one bright square of class 1 or 2."""
    img = rng.rand(1, 3, IMG, IMG).astype(np.float32) * 0.2
    size = rng.randint(8, 13)
    x = rng.randint(0, IMG - size)
    y = rng.randint(0, IMG - size)
    cls = rng.randint(1, NUM_CLASSES)
    img[0, cls - 1, y:y + size, x:x + size] = 1.0   # class = channel
    gt = np.array([[x, y, x + size, y + size]], np.float32)
    return img, gt, np.array([cls], np.float32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-images", type=int, default=60)
    parser.add_argument("--epochs", type=int, default=8)
    parser.add_argument("--lr", type=float, default=0.02)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    rng = np.random.RandomState(0)

    net = build_net()
    shapes = {"data": (1, 3, IMG, IMG), "gt_box": (1, 4),
              "gt_class": (1,)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    names = net.list_arguments()
    args_nd, grads = {}, {}
    for name, shape in zip(names, arg_shapes):
        if name in shapes:
            args_nd[name] = mx.nd.zeros(shape)
            continue
        args_nd[name] = mx.nd.array(
            rng.randn(*shape).astype(np.float32)
            * (0.0 if name.endswith("bias") else 0.1))
        grads[name] = mx.nd.zeros(shape)
    ex = net.bind(mx.cpu(), args_nd, args_grad=grads, grad_req="write")

    data = [make_sample(rng) for _ in range(args.num_images)]
    first_loss = last_loss = None
    mom = {k: np.zeros(v.shape, np.float32) for k, v in grads.items()}
    for epoch in range(args.epochs):
        total, rpn_correct, rpn_seen = 0.0, 0, 0
        for img, gt, cls in data:
            args_nd["data"][:] = img
            args_nd["gt_box"][:] = gt
            args_nd["gt_class"][:] = cls
            ex.forward(is_train=True)
            ex.backward()
            outs = ex.outputs
            for k, g in grads.items():
                # clip like the reference recipe (clip_gradient=5):
                # the RPN bias grad spikes ~30 on step 0 and an
                # unclipped momentum update saturates the objectness
                # softmax into a zero-gradient plateau
                gn = np.clip(g.asnumpy(), -2.0, 2.0)
                mom[k] = 0.5 * mom[k] - args.lr * gn
                args_nd[k][:] = args_nd[k].asnumpy() + mom[k]
            # joint loss proxy: rpn NLL + head NLL + both bbox losses
            rpn_prob = outs[0].asnumpy()
            anchors_lbl = _iou(_anchors(), gt[0])
            pos = anchors_lbl >= 0.5
            neg = anchors_lbl < 0.2
            nll = -np.log(np.maximum(rpn_prob[pos, 1], 1e-6)).sum() \
                - np.log(np.maximum(rpn_prob[neg, 0], 1e-6)).mean()
            head_prob = outs[2].asnumpy()
            nll += -np.log(np.maximum(head_prob[-1, int(cls[0])], 1e-6))
            nll += float(np.abs(outs[1].asnumpy()).sum())
            nll += float(np.abs(outs[3].asnumpy()).sum())
            total += nll
            guess = rpn_prob[:, 1] > 0.5
            rpn_correct += int((guess[pos]).sum() + (~guess[neg]).sum())
            rpn_seen += int(pos.sum() + neg.sum())
        if first_loss is None:
            first_loss = total
        last_loss = total
        logging.info("Epoch[%d] joint-loss=%.2f rpn-acc=%.3f", epoch,
                     total, rpn_correct / rpn_seen)

    rpn_acc = rpn_correct / rpn_seen
    assert last_loss < 0.6 * first_loss, (first_loss, last_loss)
    assert rpn_acc > 0.9, rpn_acc

    # held-out detection: top head-scored roi (deltas applied) must
    # localize the object
    ious = []
    for _ in range(10):
        img, gt, cls = make_sample(rng)
        args_nd["data"][:] = img
        args_nd["gt_box"][:] = gt          # targets unused at eval
        args_nd["gt_class"][:] = cls
        ex.forward(is_train=False)
        outs = ex.outputs
        rois = outs[4].asnumpy()[:, 1:]    # (ROIS, 4) incl. gt append
        head_prob = outs[2].asnumpy()
        # score ONLY the true proposals (drop the appended gt row)
        fg = head_prob[:TOP_N, 1:].sum(axis=1)
        best = rois[:TOP_N][np.argmax(fg)]
        ious.append(float(_iou(best[None, :], gt[0])[0]))
    mean_iou = float(np.mean(ious))
    logging.info("held-out mean IoU=%.3f", mean_iou)
    assert mean_iou > 0.3, ious
    print("rcnn end2end OK (loss %.1f->%.1f, rpn acc %.3f, IoU %.2f)"
          % (first_loss, last_loss, rpn_acc, mean_iou))


if __name__ == "__main__":
    main()
