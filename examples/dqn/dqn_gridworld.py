#!/usr/bin/env python
"""DQN (reference example/dqn, shrunk to a 5x5 gridworld): epsilon-greedy
Q-learning with an experience-replay buffer and a frozen target network —
the imperative NDArray + executor workflow of the reference's
base.py/qnet, with no RL-framework dependency.

The agent starts anywhere, the goal is the corner; reward -1 per step,
+10 at the goal. A converged Q-net's greedy policy reaches the goal from
every start within the Manhattan-optimal step budget.
"""
import collections
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

GRID = 5
ACTIONS = 4  # up/down/left/right
GAMMA = 0.9


def encode(pos):
    s = np.zeros((GRID * GRID,), np.float32)
    s[pos[0] * GRID + pos[1]] = 1.0
    return s


def step_env(pos, a):
    moves = [(-1, 0), (1, 0), (0, -1), (0, 1)]
    r, c = pos
    dr, dc = moves[a]
    r = min(max(r + dr, 0), GRID - 1)
    c = min(max(c + dc, 0), GRID - 1)
    new = (r, c)
    if new == (GRID - 1, GRID - 1):
        return new, 10.0, True
    return new, -1.0, False


def build_qnet():
    s = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(s, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    q = mx.sym.FullyConnected(h, num_hidden=ACTIONS, name="q")
    # LinearRegressionOutput against the TD target for the taken action
    return mx.sym.LinearRegressionOutput(
        data=q, label=mx.sym.Variable("target"), name="out")


def main(seed=0, episodes=250, batch=32):
    rng = np.random.RandomState(seed)
    net = build_qnet()
    exe = net.simple_bind(mx.cpu(), data=(batch, GRID * GRID),
                          target=(batch, ACTIONS))
    init = mx.init.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in ("data", "target"):
            init(name, arr)
    # frozen target network: a second executor, params copied periodically
    tgt = net.simple_bind(mx.cpu(), grad_req="null",
                          data=(batch, GRID * GRID),
                          target=(batch, ACTIONS))

    def sync_target():
        for name in exe.arg_dict:
            if name not in ("data", "target"):
                tgt.arg_dict[name][:] = exe.arg_dict[name].asnumpy()

    sync_target()
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=1e-2))
    replay = collections.deque(maxlen=4000)
    eps = 1.0

    def qvalues(states, executor):
        executor.arg_dict["data"][:] = states
        executor.arg_dict["target"][:] = np.zeros((batch, ACTIONS),
                                                  np.float32)
        return executor.forward()[0].asnumpy()

    for ep in range(episodes):
        pos = (rng.randint(GRID), rng.randint(GRID))
        for t in range(30):
            if rng.rand() < eps:
                a = rng.randint(ACTIONS)
            else:
                st = np.tile(encode(pos), (batch, 1))
                a = int(qvalues(st, exe)[0].argmax())
            new, r, done = step_env(pos, a)
            replay.append((encode(pos), a, r, encode(new), done))
            pos = new
            if done:
                break
        eps = max(0.05, eps * 0.99)

        # one batched TD update per episode
        if len(replay) >= batch:
            idx = rng.randint(0, len(replay), batch)
            s = np.stack([replay[i][0] for i in idx])
            a = np.array([replay[i][1] for i in idx])
            r = np.array([replay[i][2] for i in idx], np.float32)
            s2 = np.stack([replay[i][3] for i in idx])
            done = np.array([replay[i][4] for i in idx])
            q_now = qvalues(s, exe)
            q_next = qvalues(s2, tgt).max(axis=1)
            target = q_now.copy()
            target[np.arange(batch), a] = r + GAMMA * q_next * (~done)
            exe.arg_dict["data"][:] = s
            exe.arg_dict["target"][:] = target
            exe.forward(is_train=True)
            exe.backward()
            for i, name in enumerate(net.list_arguments()):
                if name in ("data", "target"):
                    continue
                updater(i, exe.grad_dict[name], exe.arg_dict[name])
        if ep % 20 == 0:
            sync_target()

    # greedy rollout from every start must reach the goal near-optimally
    failures = 0
    for r0 in range(GRID):
        for c0 in range(GRID):
            pos = (r0, c0)
            budget = 2 * (GRID - 1 - r0 + GRID - 1 - c0) + 2
            for t in range(max(budget, 1)):
                if pos == (GRID - 1, GRID - 1):
                    break
                st = np.tile(encode(pos), (batch, 1))
                pos, _, done = step_env(pos,
                                        int(qvalues(st, exe)[0].argmax()))
            if pos != (GRID - 1, GRID - 1):
                failures += 1
    print("greedy policy failures: %d / %d starts" % (failures, GRID * GRID))
    assert failures <= 2, failures
    print("DQN OK")


if __name__ == "__main__":
    main()
