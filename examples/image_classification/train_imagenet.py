#!/usr/bin/env python
"""Train an image classifier on ImageNet recordio files (reference
example/image-classification/train_imagenet.py:1-87 — the reference's
north-star training recipe).

Data: train.rec / val.rec built by tools/im2rec.py. Each worker reads
its own shard (num_parts=kv.num_workers, part_index=kv.rank), exactly
the reference's DP input sharding; kvstore tpu_sync runs the in-step
GSPMD all-reduce on one host, dist_sync spans hosts via
tools/launch.py.

Single chip:
    python train_imagenet.py --data-dir /data/imagenet --gpus 0
Multi-host DP:
    python tools/launch.py -n 4 --launcher ssh -H hosts.txt \
        python train_imagenet.py --data-dir /data/imagenet \
        --kv-store dist_sync
"""
import argparse
import importlib
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


# honor JAX_PLATFORMS (the site hook overrides the env at import;
# forcing cpu needs an explicit config update after importing jax)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms",
                      os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx
import train_model

# -n / -s stay reserved for the distributed launcher (reference note)
parser = argparse.ArgumentParser(
    description="train an image classifier on imagenet")
parser.add_argument("--network", default="inception-bn",
                    choices=["alexnet", "vgg", "googlenet",
                             "inception-bn", "inception-v3", "resnet"],
                    help="the cnn to use")
parser.add_argument("--data-dir", required=True,
                    help="directory holding train.rec / val.rec")
parser.add_argument("--model-prefix", default=None,
                    help="prefix of the checkpoint to load")
parser.add_argument("--save-model-prefix", default=None,
                    help="prefix of the checkpoint to save")
parser.add_argument("--lr", type=float, default=0.01)
parser.add_argument("--lr-factor", type=float, default=1,
                    help="multiply lr by this every lr-factor-epoch")
parser.add_argument("--lr-factor-epoch", type=float, default=1)
parser.add_argument("--clip-gradient", type=float, default=5.0)
parser.add_argument("--num-epochs", type=int, default=20)
parser.add_argument("--load-epoch", type=int, default=None)
parser.add_argument("--batch-size", type=int, default=32)
parser.add_argument("--gpus", default=None,
                    help="accelerator ids, e.g. '0' (TPU chips here)")
parser.add_argument("--kv-store", default="local",
                    help="local | tpu_sync | dist_sync | dist_async")
parser.add_argument("--num-examples", type=int, default=1281167)
parser.add_argument("--num-classes", type=int, default=1000)
parser.add_argument("--log-file", default=None)
parser.add_argument("--log-dir", default="/tmp/")
parser.add_argument("--train-dataset", default="train.rec")
parser.add_argument("--val-dataset", default="val.rec")
parser.add_argument("--data-shape", type=int, default=224,
                    help="input image edge length")
parser.add_argument("--preprocess-threads", type=int, default=4,
                    help="decode pool size (feed-the-chip knob)")
parser.add_argument("--use-cache", action="store_true",
                    help="decode each .rec ONCE into a uint8 memmap "
                         "cache next to it, then feed training from the "
                         "cache with crop/mirror/normalize fused on "
                         "device — sustains TPU-rate input from one "
                         "host core (docs/performance.md); per-epoch "
                         "JPEG decode needs ~28 cores at 224px")
parser.add_argument("--cache-margin", type=int, default=32,
                    help="stored-image margin above the crop size "
                         "(store 256 for 224 crops)")
args = parser.parse_args()


def get_net(name, num_classes):
    from mxnet_tpu import models

    if name == "resnet":
        return models.get_resnet50(num_classes=num_classes)
    if name == "inception-bn":
        return models.get_inception_bn(num_classes=num_classes)
    builders = {"alexnet": models.get_alexnet, "vgg": models.get_vgg,
                "googlenet": models.get_googlenet,
                "inception-v3": models.get_inception_v3}
    return builders[name](num_classes)


def get_iterator(args, kv):
    data_shape = (3, args.data_shape, args.data_shape)
    if args.use_cache:
        return get_cached_iterator(args, kv, data_shape)
    train = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(args.data_dir, args.train_dataset),
        mean_r=123.68, mean_g=116.779, mean_b=103.939,
        data_shape=data_shape,
        batch_size=args.batch_size,
        rand_crop=True,
        rand_mirror=True,
        shuffle=True,
        preprocess_threads=args.preprocess_threads,
        num_parts=kv.num_workers,
        part_index=kv.rank)
    val = mx.io.ImageRecordIter(
        path_imgrec=os.path.join(args.data_dir, args.val_dataset),
        mean_r=123.68, mean_g=116.779, mean_b=103.939,
        rand_crop=False,
        rand_mirror=False,
        data_shape=data_shape,
        batch_size=args.batch_size,
        preprocess_threads=args.preprocess_threads,
        num_parts=kv.num_workers,
        part_index=kv.rank)
    return train, val


def get_cached_iterator(args, kv, data_shape):
    """The cache-fed input path (mxnet_tpu.io_cache): decode each .rec
    once into a memmapped uint8 store, then feed every epoch from the
    cache with the augmentation arithmetic fused on device. Exactly ONE
    rank builds (O_EXCL lockfile in the shared data dir); the others
    wait for the finished cache, and a regenerated .rec invalidates it
    (size/mtime fingerprint in the meta)."""
    from mxnet_tpu import io_cache

    store = args.data_shape + args.cache_margin
    iters = []
    for dataset, train_aug in ((args.train_dataset, True),
                               (args.val_dataset, False)):
        rec = os.path.join(args.data_dir, dataset)
        prefix = rec + ".cache"
        io_cache.build_decoded_cache(
            rec, prefix, (3, store, store),
            preprocess_threads=args.preprocess_threads)
        iters.append(io_cache.CachedImageRecordIter(
            prefix, data_shape, args.batch_size,
            shuffle=train_aug, rand_crop=train_aug,
            rand_mirror=train_aug, device_augment=True,
            mean_r=123.68, mean_g=116.779, mean_b=103.939,
            num_parts=kv.num_workers, part_index=kv.rank))
    return iters[0], iters[1]


net = get_net(args.network, args.num_classes)
train_model.fit(args, net, get_iterator)
print("train imagenet OK")
