# U-Net symbol in R (reference
# example/image-classification/symbol_unet.R): encoder-decoder with
# skip connections via Concat; Deconvolution up-pooling.
library(mxnet.tpu)

convolution_module <- function(net, kernel_size, pad_size, filter_count,
                               stride = c(1, 1), batch_norm = TRUE,
                               down_pool = FALSE, up_pool = FALSE,
                               act_type = "relu", convolution = TRUE) {
  if (up_pool) {
    net <- mx.symbol.create("Deconvolution", net, kernel = c(2, 2),
                            pad = c(0, 0), stride = c(2, 2),
                            num_filter = filter_count)
    net <- mx.symbol.create("BatchNorm", net)
    if (act_type != "")
      net <- mx.symbol.create("Activation", net, act_type = act_type)
  }
  if (convolution)
    net <- mx.symbol.create("Convolution", net, kernel = kernel_size,
                            stride = stride, pad = pad_size,
                            num_filter = filter_count)
  if (batch_norm)
    net <- mx.symbol.create("BatchNorm", net)
  if (act_type != "")
    net <- mx.symbol.create("Activation", net, act_type = act_type)
  if (down_pool)
    net <- mx.symbol.create("Pooling", net, pool_type = "max",
                            kernel = c(2, 2), stride = c(2, 2))
  net
}

get_symbol <- function(num_classes = 10) {
  data <- mx.symbol.Variable("data")
  kernel_size <- c(3, 3)
  pad_size <- c(1, 1)
  filter_count <- 32

  # encoder
  pool1 <- convolution_module(data, kernel_size, pad_size, filter_count,
                              down_pool = TRUE)
  net <- pool1
  pool2 <- convolution_module(net, kernel_size, pad_size,
                              filter_count * 2, down_pool = TRUE)
  net <- pool2
  pool3 <- convolution_module(net, kernel_size, pad_size,
                              filter_count * 4, down_pool = TRUE)
  net <- pool3
  pool4 <- convolution_module(net, kernel_size, pad_size,
                              filter_count * 4, down_pool = TRUE)
  net <- pool4
  net <- mx.symbol.create("Dropout", net, p = 0.5)
  pool5 <- convolution_module(net, kernel_size, pad_size,
                              filter_count * 8, down_pool = TRUE)
  net <- pool5

  # decoder with skip connections
  net <- convolution_module(net, kernel_size, pad_size,
                            filter_count * 4, up_pool = TRUE)
  net <- convolution_module(net, kernel_size, pad_size,
                            filter_count * 4, up_pool = TRUE)
  net <- mx.symbol.create("Concat", pool3, net, num_args = 2)
  net <- mx.symbol.create("Dropout", net, p = 0.5)
  net <- convolution_module(net, kernel_size, pad_size,
                            filter_count * 4)
  net <- convolution_module(net, kernel_size, pad_size,
                            filter_count * 4, up_pool = TRUE)
  net <- mx.symbol.create("Concat", pool2, net, num_args = 2)
  net <- mx.symbol.create("Dropout", net, p = 0.5)
  net <- convolution_module(net, kernel_size, pad_size,
                            filter_count * 4)
  net <- convolution_module(net, kernel_size, pad_size,
                            filter_count * 4, up_pool = TRUE)
  convolution_module(net, kernel_size, pad_size, filter_count * 4,
                     up_pool = TRUE)
}
