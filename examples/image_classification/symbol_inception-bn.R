# Inception-BatchNorm symbol in R (reference
# example/image-classification/symbol_inception-bn.R).
library(mxnet.tpu)

conv.bn.act <- function(data, num_filter, kernel, stride = c(1, 1),
                        pad = c(0, 0), name = "") {
  conv <- mx.symbol.create("Convolution", data, kernel = kernel,
                           stride = stride, pad = pad,
                           num_filter = num_filter,
                           name = paste0(name, "_conv"))
  bn <- mx.symbol.create("BatchNorm", conv, name = paste0(name, "_bn"))
  mx.symbol.create("Activation", bn, act_type = "relu",
                   name = paste0(name, "_relu"))
}

inception.bn <- function(data, n1x1, n3x3red, n3x3, nd3x3red, nd3x3,
                         pool, proj, name) {
  c1 <- conv.bn.act(data, n1x1, c(1, 1), name = paste0(name, "_1x1"))
  c3 <- conv.bn.act(data, n3x3red, c(1, 1),
                    name = paste0(name, "_3x3r"))
  c3 <- conv.bn.act(c3, n3x3, c(3, 3), pad = c(1, 1),
                    name = paste0(name, "_3x3"))
  cd <- conv.bn.act(data, nd3x3red, c(1, 1),
                    name = paste0(name, "_d3x3r"))
  cd <- conv.bn.act(cd, nd3x3, c(3, 3), pad = c(1, 1),
                    name = paste0(name, "_d3x3a"))
  cd <- conv.bn.act(cd, nd3x3, c(3, 3), pad = c(1, 1),
                    name = paste0(name, "_d3x3b"))
  p <- mx.symbol.create("Pooling", data, kernel = c(3, 3),
                        stride = c(1, 1), pad = c(1, 1),
                        pool_type = pool, name = paste0(name, "_pool"))
  pp <- conv.bn.act(p, proj, c(1, 1), name = paste0(name, "_proj"))
  mx.symbol.create("Concat", c1, c3, cd, pp, num_args = 4,
                   name = paste0(name, "_concat"))
}

inception.bn.stride <- function(data, n3x3red, n3x3, nd3x3red, nd3x3,
                                name) {
  c3 <- conv.bn.act(data, n3x3red, c(1, 1),
                    name = paste0(name, "_3x3r"))
  c3 <- conv.bn.act(c3, n3x3, c(3, 3), stride = c(2, 2), pad = c(1, 1),
                    name = paste0(name, "_3x3"))
  cd <- conv.bn.act(data, nd3x3red, c(1, 1),
                    name = paste0(name, "_d3x3r"))
  cd <- conv.bn.act(cd, nd3x3, c(3, 3), pad = c(1, 1),
                    name = paste0(name, "_d3x3a"))
  cd <- conv.bn.act(cd, nd3x3, c(3, 3), stride = c(2, 2), pad = c(1, 1),
                    name = paste0(name, "_d3x3b"))
  p <- mx.symbol.create("Pooling", data, kernel = c(3, 3),
                        stride = c(2, 2), pad = c(1, 1),
                        pool_type = "max", name = paste0(name, "_pool"))
  mx.symbol.create("Concat", c3, cd, p, num_args = 3,
                   name = paste0(name, "_concat"))
}

get_symbol <- function(num_classes = 1000) {
  data <- mx.symbol.Variable("data")
  net <- conv.bn.act(data, 64, c(7, 7), c(2, 2), c(3, 3), "stem1")
  net <- mx.symbol.create("Pooling", net, kernel = c(3, 3),
                          stride = c(2, 2), pad = c(1, 1),
                          pool_type = "max")
  net <- conv.bn.act(net, 64, c(1, 1), name = "stem2r")
  net <- conv.bn.act(net, 192, c(3, 3), pad = c(1, 1), name = "stem2")
  net <- mx.symbol.create("Pooling", net, kernel = c(3, 3),
                          stride = c(2, 2), pad = c(1, 1),
                          pool_type = "max")
  net <- inception.bn(net, 64, 64, 64, 64, 96, "avg", 32, "in3a")
  net <- inception.bn(net, 64, 64, 96, 64, 96, "avg", 64, "in3b")
  net <- inception.bn.stride(net, 128, 160, 64, 96, "in3c")
  net <- inception.bn(net, 224, 64, 96, 96, 128, "avg", 128, "in4a")
  net <- inception.bn(net, 192, 96, 128, 96, 128, "avg", 128, "in4b")
  net <- inception.bn(net, 160, 128, 160, 128, 160, "avg", 128, "in4c")
  net <- inception.bn(net, 96, 128, 192, 160, 192, "avg", 128, "in4d")
  net <- inception.bn.stride(net, 128, 192, 192, 256, "in4e")
  net <- inception.bn(net, 352, 192, 320, 160, 224, "avg", 128, "in5a")
  net <- inception.bn(net, 352, 192, 320, 192, 224, "max", 128, "in5b")
  net <- mx.symbol.create("Pooling", net, kernel = c(7, 7),
                          stride = c(1, 1), pool_type = "avg",
                          name = "gpool")
  net <- mx.symbol.create("Flatten", net)
  net <- mx.symbol.create("FullyConnected", net,
                          num_hidden = num_classes, name = "fc1")
  mx.symbol.create("SoftmaxOutput", net, name = "softmax")
}
