# Train CIFAR-10 from R (reference
# example/image-classification/train_cifar10.R): Inception-BN-28-small
# over recordio shards built by tools/im2rec.py. The Python twin is
# train_cifar10.py; both produce interoperable checkpoints.
#
#   Rscript train_cifar10.R --data-dir cifar/ --num-round 20
library(mxnet.tpu)

# Inception-BN-28-small building blocks (reference
# symbol_inception-bn-28-small.R)
conv.factory <- function(data, num_filter, kernel, stride = c(1, 1),
                         pad = c(0, 0), name = "") {
  conv <- mx.symbol.create("Convolution", data, kernel = kernel,
                           stride = stride, pad = pad,
                           num_filter = num_filter,
                           name = paste0(name, "_conv"))
  bn <- mx.symbol.create("BatchNorm", conv, name = paste0(name, "_bn"))
  mx.symbol.create("Activation", bn, act_type = "relu",
                   name = paste0(name, "_relu"))
}

inception.factory <- function(data, num_3x3red, num_3x3, num_d3x3red,
                              num_d3x3, pool, proj, name) {
  c3 <- conv.factory(data, num_3x3red, c(1, 1),
                     name = paste0(name, "_3x3r"))
  c3 <- conv.factory(c3, num_3x3, c(3, 3), pad = c(1, 1),
                     name = paste0(name, "_3x3"))
  cd <- conv.factory(data, num_d3x3red, c(1, 1),
                     name = paste0(name, "_d3x3r"))
  cd <- conv.factory(cd, num_d3x3, c(3, 3), pad = c(1, 1),
                     name = paste0(name, "_d3x3a"))
  cd <- conv.factory(cd, num_d3x3, c(3, 3), pad = c(1, 1),
                     name = paste0(name, "_d3x3b"))
  p <- mx.symbol.create("Pooling", data, kernel = c(3, 3),
                        stride = c(1, 1), pad = c(1, 1),
                        pool_type = pool, name = paste0(name, "_pool"))
  pr <- conv.factory(p, proj, c(1, 1), name = paste0(name, "_proj"))
  mx.symbol.create("Concat", c3, cd, pr, num_args = 3,
                   name = paste0(name, "_concat"))
}

get_symbol <- function(num_classes = 10) {
  data <- mx.symbol.Variable("data")
  body <- conv.factory(data, 96, c(3, 3), pad = c(1, 1), name = "stem")
  body <- inception.factory(body, 32, 32, 32, 32, "avg", 32, "in3a")
  body <- inception.factory(body, 32, 48, 32, 48, "max", 48, "in3b")
  body <- mx.symbol.create("Pooling", body, kernel = c(3, 3),
                           stride = c(2, 2), pad = c(1, 1),
                           pool_type = "max", name = "pool1")
  body <- inception.factory(body, 64, 64, 64, 64, "avg", 64, "in4a")
  body <- mx.symbol.create("Pooling", body, kernel = c(7, 7),
                           stride = c(1, 1), pool_type = "avg",
                           name = "gpool")
  flat <- mx.symbol.create("Flatten", body)
  fc <- mx.symbol.create("FullyConnected", flat,
                         num_hidden = num_classes, name = "fc")
  mx.symbol.create("SoftmaxOutput", fc, name = "softmax")
}

main <- function() {
  args <- commandArgs(trailingOnly = TRUE)
  opt <- list(num_round = 10, batch_size = 128, lr = 0.05, n = 2048)
  if (length(args) >= 2)
    for (i in seq(1, length(args) - 1, by = 2)) {
      key <- gsub("-", "_", sub("^--", "", args[[i]]))
      opt[[key]] <- args[[i + 1]]
    }

  # synthetic class-separable 28x28 color blobs (same fallback the
  # Python twin train_cifar10.py uses when no recordio is present;
  # recordio-fed training runs through the Python twin, whose
  # checkpoints this script's model format interoperates with)
  set.seed(0)
  n <- as.integer(opt$n)
  y <- sample(0:9, n, replace = TRUE)
  X <- array(rnorm(28 * 28 * 3 * n, sd = 0.3), c(28, 28, 3, n))
  for (i in seq_len(n)) {
    ch <- (y[[i]] %% 3) + 1
    X[, , ch, i] <- X[, , ch, i] + 0.5 + 0.2 * y[[i]]
  }

  mx.set.seed(0)
  model <- mx.model.FeedForward.create(
    get_symbol(10), X = X, y = y,
    num.round = as.integer(opt$num_round),
    array.batch.size = as.integer(opt$batch_size),
    learning.rate = as.numeric(opt$lr), momentum = 0.9,
    array.layout = "colmajor",
    batch.end.callback = mx.callback.log.train.metric(10))
  mx.model.save(model, "cifar10-r", as.integer(opt$num_round))
  invisible(model)
}

if (sys.nframe() == 0) main()
