#!/usr/bin/env python
"""Train CIFAR-10 (reference example/image-classification/train_cifar10.py).

The reference's CIFAR benchmark net is Inception-BN-28-small at batch 128
(BASELINE.md: 842 img/s on 1 GTX 980). Data comes from recordio files
(cifar/train.rec, cifar/test.rec — build with tools/im2rec.py), with a
synthetic fallback so the script runs offline.

Examples:
    python train_cifar10.py --data-dir cifar/ --num-epochs 20
    python train_cifar10.py --network resnet --kv-store tpu_sync
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models


def get_net(name, num_classes=10):
    if name == "inception-bn-28-small":
        return models.get_inception_bn_28_small(num_classes)
    if name == "resnet":
        return models.get_resnet50(num_classes, small_input=True)
    if name == "lenet":
        return models.get_lenet(num_classes)
    raise ValueError("unknown network %s" % name)


def get_iters(args):
    train_rec = os.path.join(args.data_dir, "train.rec")
    val_rec = os.path.join(args.data_dir, "test.rec")
    if os.path.exists(train_rec):
        mean_img = os.path.join(args.data_dir, "mean.nd")
        train = mx.io.ImageRecordIter(
            path_imgrec=train_rec, data_shape=(3, 28, 28), mean_img=mean_img,
            batch_size=args.batch_size, rand_crop=True, rand_mirror=True,
            shuffle=True, num_parts=args.num_parts,
            part_index=args.part_index)
        val = mx.io.ImageRecordIter(
            path_imgrec=val_rec, data_shape=(3, 28, 28), mean_img=mean_img,
            batch_size=args.batch_size)
        return train, val
    logging.warning("CIFAR recordio not found in %s; using synthetic data",
                    args.data_dir)
    rng = np.random.RandomState(0)
    n = 2048
    y = rng.randint(0, 10, n).astype(np.float32)
    X = rng.randn(n, 3, 28, 28).astype(np.float32) * 0.3
    for i in range(n):  # class-dependent channel shift: separable
        X[i, int(y[i]) % 3] += 0.5 + 0.2 * int(y[i])
    cut = n * 7 // 8
    train = mx.io.NDArrayIter(X[:cut], y[:cut], batch_size=args.batch_size,
                              shuffle=True, last_batch_handle="discard")
    val = mx.io.NDArrayIter(X[cut:], y[cut:], batch_size=args.batch_size)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train cifar10")
    parser.add_argument("--network", default="inception-bn-28-small",
                        choices=["inception-bn-28-small", "resnet", "lenet"])
    parser.add_argument("--data-dir", default="cifar/")
    parser.add_argument("--gpus", default=None,
                        help="accelerator ids, e.g. '0' or '0,1'")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--lr-factor", type=float, default=0.94)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--num-parts", type=int, default=1)
    parser.add_argument("--part-index", type=int, default=0)
    parser.add_argument("--model-prefix", default=None)
    parser.add_argument("--load-epoch", type=int, default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = get_net(args.network)
    train, val = get_iters(args)
    if args.gpus:
        ctx = [mx.tpu(int(i)) for i in args.gpus.split(",")]
    else:
        ctx = [mx.cpu()]
    kv = mx.kv.create(args.kv_store)

    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        net, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch

    checkpoint = (mx.callback.do_checkpoint(args.model_prefix)
                  if args.model_prefix else None)
    model = mx.model.FeedForward(
        symbol=net, ctx=ctx, num_epoch=args.num_epochs,
        learning_rate=args.lr, momentum=0.9, wd=1e-4,
        lr_scheduler=mx.lr_scheduler.FactorScheduler(
            step=max(1, 50000 // args.batch_size), factor=args.lr_factor),
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        arg_params=arg_params, aux_params=aux_params,
        begin_epoch=begin_epoch)
    model.fit(X=train, eval_data=val, kvstore=kv,
              batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                         50),
              epoch_end_callback=checkpoint)


if __name__ == "__main__":
    main()
