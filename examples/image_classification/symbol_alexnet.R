# AlexNet symbol in R (reference
# example/image-classification/symbol_alexnet.R). Build with
# get_symbol(num_classes) and train via mx.model.FeedForward.create.
library(mxnet.tpu)

get_symbol <- function(num_classes = 1000) {
  input_data <- mx.symbol.Variable("data")
  # stage 1
  conv1 <- mx.symbol.create("Convolution", input_data, kernel = c(11, 11),
                            stride = c(4, 4), num_filter = 96)
  relu1 <- mx.symbol.create("Activation", conv1, act_type = "relu")
  pool1 <- mx.symbol.create("Pooling", relu1, pool_type = "max",
                            kernel = c(3, 3), stride = c(2, 2))
  lrn1 <- mx.symbol.create("LRN", pool1, nsize = 5)
  # stage 2
  conv2 <- mx.symbol.create("Convolution", lrn1, kernel = c(5, 5),
                            pad = c(2, 2), num_filter = 256)
  relu2 <- mx.symbol.create("Activation", conv2, act_type = "relu")
  pool2 <- mx.symbol.create("Pooling", relu2, kernel = c(3, 3),
                            stride = c(2, 2), pool_type = "max")
  lrn2 <- mx.symbol.create("LRN", pool2, nsize = 5)
  # stage 3
  conv3 <- mx.symbol.create("Convolution", lrn2, kernel = c(3, 3),
                            pad = c(1, 1), num_filter = 384)
  relu3 <- mx.symbol.create("Activation", conv3, act_type = "relu")
  conv4 <- mx.symbol.create("Convolution", relu3, kernel = c(3, 3),
                            pad = c(1, 1), num_filter = 384)
  relu4 <- mx.symbol.create("Activation", conv4, act_type = "relu")
  conv5 <- mx.symbol.create("Convolution", relu4, kernel = c(3, 3),
                            pad = c(1, 1), num_filter = 256)
  relu5 <- mx.symbol.create("Activation", conv5, act_type = "relu")
  pool3 <- mx.symbol.create("Pooling", relu5, kernel = c(3, 3),
                            stride = c(2, 2), pool_type = "max")
  # stage 4
  flatten <- mx.symbol.create("Flatten", pool3)
  fc1 <- mx.symbol.create("FullyConnected", flatten, num_hidden = 4096)
  relu6 <- mx.symbol.create("Activation", fc1, act_type = "relu")
  dropout1 <- mx.symbol.create("Dropout", relu6, p = 0.5)
  # stage 5
  fc2 <- mx.symbol.create("FullyConnected", dropout1, num_hidden = 4096)
  relu7 <- mx.symbol.create("Activation", fc2, act_type = "relu")
  dropout2 <- mx.symbol.create("Dropout", relu7, p = 0.5)
  # stage 6
  fc3 <- mx.symbol.create("FullyConnected", dropout2,
                          num_hidden = num_classes)
  mx.symbol.create("SoftmaxOutput", fc3, name = "softmax")
}
