"""Shared fit() wiring for the image-classification recipes (reference
example/image-classification/train_model.py:1-120): kvstore creation,
per-node logging, checkpoint load/save, dist epoch-size scaling, lr
schedule, clip-gradient, top-k metrics, Speedometer.

train_imagenet.py / train_cifar10.py hand this module their parsed args
plus a data-loader callback, exactly like the reference split.
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


# honor JAX_PLATFORMS (the site hook overrides the env at import;
# forcing cpu needs an explicit config update after importing jax)
if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms",
                      os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def fit(args, network, data_loader, batch_end_callback=None):
    # kvstore first: dist tiers must form the collective group before
    # anything touches the accelerator (reference train_model.py:8)
    kv = mx.kv.create(args.kv_store)

    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    if getattr(args, "log_file", None):
        os.makedirs(args.log_dir, exist_ok=True)
        handler = logging.FileHandler(
            os.path.join(args.log_dir, args.log_file))
        handler.setFormatter(logging.Formatter(head))
        logging.getLogger().addHandler(handler)
        logging.getLogger().setLevel(logging.DEBUG)
    else:
        logging.basicConfig(level=logging.INFO, format=head)
    logging.info("start with arguments %s", args)

    # resume (reference: per-rank prefix so ranks don't clobber)
    model_prefix = args.model_prefix
    if model_prefix is not None and kv.num_workers > 1:
        model_prefix += "-%d" % kv.rank
    model_args = {}
    if getattr(args, "load_epoch", None) is not None:
        assert model_prefix is not None
        net, arg_params, aux_params = mx.model.load_checkpoint(
            model_prefix, args.load_epoch)
        model_args = {"arg_params": arg_params,
                      "aux_params": aux_params,
                      "begin_epoch": args.load_epoch}
        network = net

    save_model_prefix = getattr(args, "save_model_prefix", None)
    if save_model_prefix is not None and kv.num_workers > 1:
        save_model_prefix += "-%d" % kv.rank   # ranks must not clobber
    if save_model_prefix is None:
        save_model_prefix = model_prefix       # already rank-suffixed
    checkpoint = None if save_model_prefix is None \
        else mx.callback.do_checkpoint(save_model_prefix)

    train, val = data_loader(args, kv)

    if getattr(args, "gpus", None):
        devs = [mx.tpu(int(i)) for i in args.gpus.split(",")]
    else:
        devs = mx.cpu()

    epoch_size = args.num_examples // args.batch_size
    if "dist" in args.kv_store:
        epoch_size //= kv.num_workers

    if getattr(args, "lr_factor", 1) < 1:
        model_args["lr_scheduler"] = mx.lr_scheduler.FactorScheduler(
            step=max(int(epoch_size * args.lr_factor_epoch), 1),
            factor=args.lr_factor)
    if getattr(args, "clip_gradient", None) is not None:
        model_args["clip_gradient"] = args.clip_gradient

    model = mx.model.FeedForward(
        ctx=devs,
        symbol=network,
        num_epoch=args.num_epochs,
        learning_rate=args.lr,
        momentum=0.9,
        wd=0.00001,
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34),
        **model_args)

    eval_metrics = ["accuracy"]
    for top_k in [5]:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=top_k))

    callbacks = list(batch_end_callback or [])
    callbacks.append(mx.callback.Speedometer(args.batch_size, 50))

    model.fit(X=train, eval_data=val, eval_metric=eval_metrics,
              kvstore=kv, batch_end_callback=callbacks,
              epoch_end_callback=checkpoint)
    return model
