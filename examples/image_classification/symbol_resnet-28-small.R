# Small ResNet for 28x28 inputs in R (reference
# example/image-classification/symbol_resnet-28-small.R).
library(mxnet.tpu)

conv.factory <- function(data, num_filter, kernel, stride = c(1, 1),
                         pad = c(0, 0), act = TRUE, name = "") {
  conv <- mx.symbol.create("Convolution", data, kernel = kernel,
                           stride = stride, pad = pad,
                           num_filter = num_filter,
                           name = paste0("conv_", name))
  bn <- mx.symbol.create("BatchNorm", conv, name = paste0("bn_", name))
  if (act) {
    return(mx.symbol.create("Activation", bn, act_type = "relu",
                            name = paste0("relu_", name)))
  }
  bn
}

residual.factory <- function(data, num_filter, dim.match, name) {
  if (dim.match) {
    identity.data <- data
    conv1 <- conv.factory(data, num_filter, c(3, 3), c(1, 1), c(1, 1),
                          name = paste0(name, "_c1"))
    conv2 <- conv.factory(conv1, num_filter, c(3, 3), c(1, 1), c(1, 1),
                          act = FALSE, name = paste0(name, "_c2"))
    new.data <- identity.data + conv2
  } else {
    conv1 <- conv.factory(data, num_filter, c(3, 3), c(2, 2), c(1, 1),
                          name = paste0(name, "_c1"))
    conv2 <- conv.factory(conv1, num_filter, c(3, 3), c(1, 1), c(1, 1),
                          act = FALSE, name = paste0(name, "_c2"))
    project.data <- conv.factory(data, num_filter, c(2, 2), c(2, 2),
                                 act = FALSE,
                                 name = paste0(name, "_proj"))
    new.data <- project.data + conv2
  }
  mx.symbol.create("Activation", new.data, act_type = "relu",
                   name = paste0(name, "_out"))
}

residual.net <- function(data, n) {
  net <- data
  for (i in seq_len(n)) net <- residual.factory(net, 16, TRUE,
                                                paste0("a", i))
  net <- residual.factory(net, 32, FALSE, "b0")
  for (i in seq_len(n - 1)) net <- residual.factory(net, 32, TRUE,
                                                    paste0("b", i))
  net <- residual.factory(net, 64, FALSE, "c0")
  for (i in seq_len(n - 1)) net <- residual.factory(net, 64, TRUE,
                                                    paste0("c", i))
  net
}

get_symbol <- function(num_classes = 10, n = 3) {
  data <- mx.symbol.Variable("data")
  net <- conv.factory(data, 16, c(3, 3), c(1, 1), c(1, 1),
                      name = "stem")
  net <- residual.net(net, n)
  net <- mx.symbol.create("Pooling", net, kernel = c(7, 7),
                          pool_type = "avg", name = "gpool")
  net <- mx.symbol.create("Flatten", net)
  net <- mx.symbol.create("FullyConnected", net,
                          num_hidden = num_classes, name = "fc")
  mx.symbol.create("SoftmaxOutput", net, name = "softmax")
}
