# GoogLeNet (Inception v1) symbol in R (reference
# example/image-classification/symbol_googlenet.R).
library(mxnet.tpu)

conv.factory2 <- function(data, num_filter, kernel, stride = c(1, 1),
                          pad = c(0, 0), name = "") {
  conv <- mx.symbol.create("Convolution", data, kernel = kernel,
                           stride = stride, pad = pad,
                           num_filter = num_filter,
                           name = paste0("conv_", name))
  mx.symbol.create("Activation", conv, act_type = "relu",
                   name = paste0("relu_", name))
}

inception7 <- function(data, n1x1, n3x3red, n3x3, n5x5red, n5x5, proj,
                       name) {
  c1 <- conv.factory2(data, n1x1, c(1, 1), name = paste0(name, "_1x1"))
  c3r <- conv.factory2(data, n3x3red, c(1, 1),
                       name = paste0(name, "_3x3r"))
  c3 <- conv.factory2(c3r, n3x3, c(3, 3), pad = c(1, 1),
                      name = paste0(name, "_3x3"))
  c5r <- conv.factory2(data, n5x5red, c(1, 1),
                       name = paste0(name, "_5x5r"))
  c5 <- conv.factory2(c5r, n5x5, c(5, 5), pad = c(2, 2),
                      name = paste0(name, "_5x5"))
  p <- mx.symbol.create("Pooling", data, kernel = c(3, 3),
                        stride = c(1, 1), pad = c(1, 1),
                        pool_type = "max", name = paste0(name, "_pool"))
  pp <- conv.factory2(p, proj, c(1, 1), name = paste0(name, "_proj"))
  mx.symbol.create("Concat", c1, c3, c5, pp, num_args = 4,
                   name = paste0(name, "_concat"))
}

get_symbol <- function(num_classes = 1000) {
  data <- mx.symbol.Variable("data")
  net <- conv.factory2(data, 64, c(7, 7), c(2, 2), c(3, 3), "stem1")
  net <- mx.symbol.create("Pooling", net, kernel = c(3, 3),
                          stride = c(2, 2), pad = c(1, 1),
                          pool_type = "max")
  net <- conv.factory2(net, 64, c(1, 1), name = "stem2r")
  net <- conv.factory2(net, 192, c(3, 3), pad = c(1, 1), name = "stem2")
  net <- mx.symbol.create("Pooling", net, kernel = c(3, 3),
                          stride = c(2, 2), pad = c(1, 1),
                          pool_type = "max")
  net <- inception7(net, 64, 96, 128, 16, 32, 32, "in3a")
  net <- inception7(net, 128, 128, 192, 32, 96, 64, "in3b")
  net <- mx.symbol.create("Pooling", net, kernel = c(3, 3),
                          stride = c(2, 2), pad = c(1, 1),
                          pool_type = "max")
  net <- inception7(net, 192, 96, 208, 16, 48, 64, "in4a")
  net <- inception7(net, 160, 112, 224, 24, 64, 64, "in4b")
  net <- inception7(net, 128, 128, 256, 24, 64, 64, "in4c")
  net <- inception7(net, 112, 144, 288, 32, 64, 64, "in4d")
  net <- inception7(net, 256, 160, 320, 32, 128, 128, "in4e")
  net <- mx.symbol.create("Pooling", net, kernel = c(3, 3),
                          stride = c(2, 2), pad = c(1, 1),
                          pool_type = "max")
  net <- inception7(net, 256, 160, 320, 32, 128, 128, "in5a")
  net <- inception7(net, 384, 192, 384, 48, 128, 128, "in5b")
  net <- mx.symbol.create("Pooling", net, kernel = c(7, 7),
                          stride = c(1, 1), pool_type = "avg")
  net <- mx.symbol.create("Flatten", net)
  net <- mx.symbol.create("FullyConnected", net,
                          num_hidden = num_classes, name = "fc")
  mx.symbol.create("SoftmaxOutput", net, name = "softmax")
}
