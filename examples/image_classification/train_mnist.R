# Train MNIST from R (reference
# example/image-classification/train_mnist.R). Works against idx files
# in --data-dir (tools/make_mnist_synth.py generates compatible files
# offline; the reference downloaded the real set). The Python twin is
# train_mnist.py; both write the same checkpoint layout.
#
#   Rscript train_mnist.R --network mlp --data-dir mnist/
library(mxnet.tpu)

get_mlp <- function() {
  data <- mx.symbol.Variable("data")
  fc1 <- mx.symbol.FullyConnected(data = data, name = "fc1",
                                  num_hidden = 128)
  act1 <- mx.symbol.create("Activation", fc1, act_type = "relu")
  fc2 <- mx.symbol.FullyConnected(data = act1, name = "fc2",
                                  num_hidden = 64)
  act2 <- mx.symbol.create("Activation", fc2, act_type = "relu")
  fc3 <- mx.symbol.FullyConnected(data = act2, name = "fc3",
                                  num_hidden = 10)
  mx.symbol.create("SoftmaxOutput", fc3, name = "softmax")
}

get_lenet <- function() {
  data <- mx.symbol.Variable("data")
  conv1 <- mx.symbol.create("Convolution", data, kernel = c(5, 5),
                            num_filter = 20)
  tanh1 <- mx.symbol.create("Activation", conv1, act_type = "tanh")
  pool1 <- mx.symbol.create("Pooling", tanh1, pool_type = "max",
                            kernel = c(2, 2), stride = c(2, 2))
  conv2 <- mx.symbol.create("Convolution", pool1, kernel = c(5, 5),
                            num_filter = 50)
  tanh2 <- mx.symbol.create("Activation", conv2, act_type = "tanh")
  pool2 <- mx.symbol.create("Pooling", tanh2, pool_type = "max",
                            kernel = c(2, 2), stride = c(2, 2))
  flatten <- mx.symbol.create("Flatten", pool2)
  fc1 <- mx.symbol.create("FullyConnected", flatten, num_hidden = 500)
  tanh3 <- mx.symbol.create("Activation", fc1, act_type = "tanh")
  fc2 <- mx.symbol.create("FullyConnected", tanh3, num_hidden = 10)
  mx.symbol.create("SoftmaxOutput", fc2, name = "softmax")
}

read.idx <- function(image_file, label_file, flat) {
  img <- file(image_file, "rb")
  stopifnot(readBin(img, "integer", 1, endian = "big") == 2051L)
  n <- readBin(img, "integer", 1, endian = "big")
  h <- readBin(img, "integer", 1, endian = "big")
  w <- readBin(img, "integer", 1, endian = "big")
  raw <- as.numeric(readBin(img, "integer", n * h * w, size = 1,
                            signed = FALSE)) / 255
  close(img)
  lbl <- file(label_file, "rb")
  stopifnot(readBin(lbl, "integer", 1, endian = "big") == 2049L)
  m <- readBin(lbl, "integer", 1, endian = "big")
  y <- as.numeric(readBin(lbl, "integer", m, size = 1, signed = FALSE))
  close(lbl)
  # idx is row-major (n, h, w); colmajor R wants feature-major columns
  X <- array(raw, dim = c(w * h, n))
  if (!flat) dim(X) <- c(w, h, 1, n)
  list(x = X, y = y)
}

main <- function() {
  args <- commandArgs(trailingOnly = TRUE)
  opt <- list(network = "mlp", data_dir = "mnist/", num_round = 10,
              batch_size = 128, lr = 0.1)
  if (length(args) >= 2)
    for (i in seq(1, length(args) - 1, by = 2)) {
      key <- gsub("-", "_", sub("^--", "", args[[i]]))
      opt[[key]] <- args[[i + 1]]
    }

  flat <- identical(opt$network, "mlp")
  net <- if (flat) get_mlp() else get_lenet()
  train <- read.idx(file.path(opt$data_dir, "train-images-idx3-ubyte"),
                    file.path(opt$data_dir, "train-labels-idx1-ubyte"),
                    flat)
  mx.set.seed(0)
  model <- mx.model.FeedForward.create(
    net, X = train$x, y = train$y,
    num.round = as.integer(opt$num_round),
    array.batch.size = as.integer(opt$batch_size),
    learning.rate = as.numeric(opt$lr), momentum = 0.9,
    array.layout = "colmajor",
    batch.end.callback = mx.callback.log.train.metric(100))
  mx.model.save(model, "mnist-r", as.integer(opt$num_round))
  invisible(model)
}

if (sys.nframe() == 0) main()
