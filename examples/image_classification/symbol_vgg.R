# VGG-16 symbol in R (reference
# example/image-classification/symbol_vgg.R).
library(mxnet.tpu)

conv.block <- function(data, num_filter, name) {
  conv <- mx.symbol.create("Convolution", data, kernel = c(3, 3),
                           pad = c(1, 1), num_filter = num_filter,
                           name = paste0("conv", name))
  mx.symbol.create("Activation", conv, act_type = "relu",
                   name = paste0("relu", name))
}

get_symbol <- function(num_classes = 1000) {
  data <- mx.symbol.Variable("data")
  # group 1
  net <- conv.block(data, 64, "1_1")
  net <- conv.block(net, 64, "1_2")
  net <- mx.symbol.create("Pooling", net, pool_type = "max",
                          kernel = c(2, 2), stride = c(2, 2))
  # group 2
  net <- conv.block(net, 128, "2_1")
  net <- conv.block(net, 128, "2_2")
  net <- mx.symbol.create("Pooling", net, pool_type = "max",
                          kernel = c(2, 2), stride = c(2, 2))
  # group 3
  net <- conv.block(net, 256, "3_1")
  net <- conv.block(net, 256, "3_2")
  net <- conv.block(net, 256, "3_3")
  net <- mx.symbol.create("Pooling", net, pool_type = "max",
                          kernel = c(2, 2), stride = c(2, 2))
  # group 4
  net <- conv.block(net, 512, "4_1")
  net <- conv.block(net, 512, "4_2")
  net <- conv.block(net, 512, "4_3")
  net <- mx.symbol.create("Pooling", net, pool_type = "max",
                          kernel = c(2, 2), stride = c(2, 2))
  # group 5
  net <- conv.block(net, 512, "5_1")
  net <- conv.block(net, 512, "5_2")
  net <- conv.block(net, 512, "5_3")
  net <- mx.symbol.create("Pooling", net, pool_type = "max",
                          kernel = c(2, 2), stride = c(2, 2))
  # classifier
  net <- mx.symbol.create("Flatten", net)
  net <- mx.symbol.create("FullyConnected", net, num_hidden = 4096,
                          name = "fc6")
  net <- mx.symbol.create("Activation", net, act_type = "relu")
  net <- mx.symbol.create("Dropout", net, p = 0.5)
  net <- mx.symbol.create("FullyConnected", net, num_hidden = 4096,
                          name = "fc7")
  net <- mx.symbol.create("Activation", net, act_type = "relu")
  net <- mx.symbol.create("Dropout", net, p = 0.5)
  net <- mx.symbol.create("FullyConnected", net,
                          num_hidden = num_classes, name = "fc8")
  mx.symbol.create("SoftmaxOutput", net, name = "softmax")
}
