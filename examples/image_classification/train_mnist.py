#!/usr/bin/env python
"""Train MNIST (reference example/image-classification/train_mnist.py).

Uses idx-format MNIST files if --data-dir has them (the reference's layout:
train-images-idx3-ubyte etc.), otherwise generates a synthetic separable
digit task so the script runs in offline environments.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models


def get_iters(args):
    train_img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    train_lbl = os.path.join(args.data_dir, "train-labels-idx1-ubyte")
    val_img = os.path.join(args.data_dir, "t10k-images-idx3-ubyte")
    val_lbl = os.path.join(args.data_dir, "t10k-labels-idx1-ubyte")
    flat = args.network == "mlp"
    if os.path.exists(train_img):
        train = mx.io.MNISTIter(image=train_img, label=train_lbl,
                                batch_size=args.batch_size, shuffle=True,
                                flat=flat, num_parts=args.num_parts,
                                part_index=args.part_index)
        val = mx.io.MNISTIter(image=val_img, label=val_lbl,
                              batch_size=args.batch_size, flat=flat,
                              shuffle=False)
        return train, val
    logging.warning("MNIST not found in %s; using synthetic digits",
                    args.data_dir)
    rng = np.random.RandomState(0)
    n = 4096
    y = rng.randint(0, 10, n).astype(np.float32)
    X = np.zeros((n, 1, 28, 28), dtype=np.float32)
    for i in range(n):
        c = int(y[i])
        X[i, 0, 2 * c:2 * c + 4, :] = 1.0
    X += rng.randn(*X.shape).astype(np.float32) * 0.1
    if flat:
        X = X.reshape(n, 784)
    cut = n * 7 // 8
    train = mx.io.NDArrayIter(X[:cut], y[:cut], batch_size=args.batch_size,
                              shuffle=True, last_batch_handle="discard")
    val = mx.io.NDArrayIter(X[cut:], y[cut:], batch_size=args.batch_size)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    parser.add_argument("--data-dir", default="mnist/")
    parser.add_argument("--gpus", default=None,
                        help="accelerator ids, e.g. '0' or '0,1'")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--model-prefix", default=None)
    parser.add_argument("--load-epoch", type=int, default=None)
    parser.add_argument("--num-parts", type=int, default=1)
    parser.add_argument("--part-index", type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = models.get_mlp() if args.network == "mlp" else models.get_lenet()
    train, val = get_iters(args)
    if args.gpus:
        ctx = [mx.gpu(int(i)) for i in args.gpus.split(",")]
    else:
        ctx = mx.cpu()

    mod = mx.mod.Module(net, context=ctx)
    arg_params = aux_params = None
    begin_epoch = 0
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)
        begin_epoch = args.load_epoch
    epoch_cb = (mx.callback.do_checkpoint(args.model_prefix)
                if args.model_prefix else None)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-5},
            initializer=mx.init.Xavier(),
            arg_params=arg_params, aux_params=aux_params,
            allow_missing=True, begin_epoch=begin_epoch,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50),
            epoch_end_callback=epoch_cb)
    acc = mod.score(val, "acc")[0][1]
    print("Final validation accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
