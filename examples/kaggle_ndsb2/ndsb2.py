"""Kaggle NDSB-2 cardiac-volume pipeline (reference
example/kaggle-ndsb2/Train.py): predict a cumulative distribution
P(volume <= v) per case and score with CRPS.

What this family uniquely exercises:
  * frame-DIFFERENCE input built symbolically: SliceChannel over the
    frame axis, pairwise subtraction, Concat (reference
    ``Train.py:16-24`` — in-graph preprocessing, not host-side);
  * LogisticRegressionOutput with a VECTOR label per sample (the
    600-bin CDF target; here 40 bins), the sigmoid-regression path;
  * CDF label encoding ``(x < arange(bins))`` (reference
    ``encode_label``) and the CRPS metric with monotonic rectification
    of the predicted CDF (reference ``Train.py:40-50``).

Synthetic stand-in: "volume" is the number of active pixels in a
moving blob across frames; the CDF target thresholds it. Gates: CRPS
well under the 0.25 chance level and a monotone submission.
"""
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx

logging.basicConfig(level=logging.INFO)

FRAMES = 6
IMG = 12
BINS = 40


def get_net():
    source = mx.sym.Variable("data")
    source = (source - 128.0) * (1.0 / 128.0)
    frames = mx.sym.SliceChannel(source, num_outputs=FRAMES)
    diffs = [frames[i + 1] - frames[i] for i in range(FRAMES - 1)]
    source = mx.sym.Concat(*diffs)
    net = mx.sym.Convolution(source, kernel=(3, 3), num_filter=8)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(data=net, num_hidden=BINS)
    return mx.sym.LogisticRegressionOutput(data=net, name="softmax")


def CRPS(label, pred):
    """Continuous ranked probability score with the reference's
    monotonic rectification of the predicted CDF."""
    pred = pred.copy()
    for j in range(pred.shape[1] - 1):
        pred[:, j + 1] = np.maximum(pred[:, j + 1], pred[:, j])
    return float(np.sum(np.square(label - pred)) / label.size)


def encode_label(volumes):
    """CDF target: bin b is 1 iff volume < b (reference encode_label)."""
    return np.array([(v < np.arange(BINS)) for v in volumes],
                    dtype=np.float32)


def make_data(rng, n):
    X = np.zeros((n, FRAMES, IMG, IMG), dtype=np.float32)
    vol = np.zeros(n)
    yy, xx = np.mgrid[0:IMG, 0:IMG]
    for i in range(n):
        r = rng.uniform(1.5, 4.5)
        for t in range(FRAMES):
            cx = 4 + 2 * np.sin(t / 2.0)
            cy = 4 + 2 * np.cos(t / 2.0)
            mask = ((xx - cx) ** 2 + (yy - cy) ** 2) < r ** 2
            X[i, t] = mask * 200.0 + rng.rand(IMG, IMG) * 20.0
        vol[i] = (np.pi * r * r) * BINS / 80.0   # scaled to bin range
    return X, encode_label(vol)


def main():
    rng = np.random.RandomState(0)
    X, y = make_data(rng, 320)
    Xv, yv = make_data(rng, 64)

    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    vit = mx.io.NDArrayIter(Xv, yv, batch_size=32,
                            label_name="softmax_label")

    mod = mx.mod.Module(get_net(), context=mx.cpu())
    mod.fit(it, num_epoch=12, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(magnitude=2.0),
            eval_metric=mx.metric.np_metric(CRPS, name="CRPS"))

    vit.reset()
    preds = []
    for batch in vit:
        mod.forward(batch, is_train=False)
        preds.append(mod.get_outputs()[0].asnumpy())
    pred = np.concatenate(preds)[:len(Xv)]
    score = CRPS(yv, pred)
    logging.info("validation CRPS %.4f (chance ~0.25)", score)
    assert score < 0.05, score

    # submission_helper: rectified monotone CDF rows in [0, 1]
    mono = pred.copy()
    for j in range(BINS - 1):
        mono[:, j + 1] = np.maximum(mono[:, j + 1], mono[:, j])
    assert (np.diff(mono, axis=1) >= 0).all()
    assert mono.min() >= 0.0 and mono.max() <= 1.0
    print("kaggle ndsb2 OK")


if __name__ == "__main__":
    main()
