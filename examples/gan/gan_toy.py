#!/usr/bin/env python
"""Toy GAN (reference example/gan, shrunk to a 2-D mixture): generator
and discriminator as two executors trained adversarially with
LogisticRegressionOutput, the two-executor update dance of the
reference's dcgan.py.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import jax

if os.environ.get("JAX_PLATFORMS"):
    # the TPU site hook can override the env at import; re-apply it so
    # JAX_PLATFORMS=cpu runs of the examples stay off-device
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import mxnet_tpu as mx


def generator(z_dim):
    z = mx.sym.Variable("z")
    g = mx.sym.FullyConnected(z, num_hidden=32, name="g1")
    g = mx.sym.Activation(g, act_type="relu")
    g = mx.sym.FullyConnected(g, num_hidden=2, name="g2")
    return g


def discriminator():
    x = mx.sym.Variable("x")
    d = mx.sym.FullyConnected(x, num_hidden=32, name="d1")
    d = mx.sym.Activation(d, act_type="tanh")
    d = mx.sym.FullyConnected(d, num_hidden=1, name="d2")
    return mx.sym.LogisticRegressionOutput(
        data=d, label=mx.sym.Variable("label"), name="dout")


def _init(exe, skip, seed):
    init = mx.init.Xavier()
    for name, arr in exe.arg_dict.items():
        if name not in skip:
            init(name, arr)


def _sgd_step(sym, exe, skip, updater, base_index=0):
    for i, name in enumerate(sym.list_arguments()):
        if name in skip:
            continue
        updater(base_index + i, exe.grad_dict[name], exe.arg_dict[name])


def real_batch(rng, n):
    # ring of 4 gaussians
    centers = np.array([[2, 0], [-2, 0], [0, 2], [0, -2]], np.float32)
    idx = rng.randint(0, 4, n)
    return centers[idx] + rng.randn(n, 2).astype(np.float32) * 0.2


def main(seed=0, steps=1000, batch=64, z_dim=8):
    rng = np.random.RandomState(seed)
    g_sym = generator(z_dim)
    d_sym = discriminator()

    g_exe = g_sym.simple_bind(mx.cpu(), z=(batch, z_dim))
    d_reqs = {n: "write" for n in d_sym.list_arguments()}
    d_reqs["label"] = "null"          # no gradient for the target
    d_exe = d_sym.simple_bind(mx.cpu(), grad_req=d_reqs,
                              x=(batch, 2), label=(batch, 1))
    _init(g_exe, {"z"}, seed)
    _init(d_exe, {"x", "label"}, seed + 1)
    g_up = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=1e-2))
    d_up = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=2e-3))

    ones = np.ones((batch, 1), np.float32)
    zeros = np.zeros((batch, 1), np.float32)
    for step in range(steps):
        # --- discriminator on real
        d_exe.arg_dict["x"][:] = real_batch(rng, batch)
        d_exe.arg_dict["label"][:] = ones
        d_exe.forward(is_train=True)
        d_exe.backward()
        _sgd_step(d_sym, d_exe, {"x", "label"}, d_up)
        # --- discriminator on fake
        g_exe.arg_dict["z"][:] = rng.randn(batch, z_dim).astype(np.float32)
        g_exe.forward(is_train=True)
        fake = g_exe.outputs[0].asnumpy()
        d_exe.arg_dict["x"][:] = fake
        d_exe.arg_dict["label"][:] = zeros
        d_exe.forward(is_train=True)
        d_exe.backward()
        _sgd_step(d_sym, d_exe, {"x", "label"}, d_up)
        # --- generator: push D(fake) toward "real", gradient flows
        #     through D's input gradient into G
        d_exe.arg_dict["label"][:] = ones
        d_exe.forward(is_train=True)
        d_exe.backward()
        g_exe.backward([mx.nd.array(d_exe.grad_dict["x"].asnumpy())])
        _sgd_step(g_sym, g_exe, {"z"}, g_up, base_index=100)

    # fakes should land near the 4 modes: mean distance to the nearest
    # center well under the prior's
    g_exe.arg_dict["z"][:] = rng.randn(batch, z_dim).astype(np.float32)
    fake = g_exe.forward()[0].asnumpy()
    centers = np.array([[2, 0], [-2, 0], [0, 2], [0, -2]], np.float32)
    dists = np.linalg.norm(fake[:, None, :] - centers[None], axis=2).min(1)
    print("mean distance of fakes to nearest mode: %.3f" % dists.mean())
    assert dists.mean() < 1.2, dists.mean()
    print("GAN OK")


if __name__ == "__main__":
    main()
